"""The solver service: deadline-bounded, batched, fault-isolated solves.

The ISSUE-9 tentpole front-end gluing the serve layers together::

    submit ->  admission (bucket, deadline, load shed, breaker gate)
    drain  ->  executor  (padded vmap batch, AOT-compiled, one dispatch)
           ->  certify   (trusted host residual per request)
           ->  isolate   (bisect-split a failing batch: one poisoned
                          problem fails ALONE, batch-mates still certify;
                          re-execution absorbs one-shot faults)
           ->  escalate  (retry/backoff around ``certified_solve`` with
                          the deadline threaded and the load-aware
                          degradation ladder)

Every request ends in exactly one structured outcome -- ``serve_result/
v1`` with status ``ok`` / ``failed`` / ``timed_out``, or a
``serve_reject/v1`` at submit -- and every ``ok`` carries a residual
measured on the TRUSTED host path: zero silent garbage by construction
(the chaos matrix in ``tests/serve`` pins it under fault injection).

The service is synchronous and deterministic: ``submit`` enqueues (or
fast-rejects), ``drain`` processes the queue to completion.  An async
front-end is one thread + this object; determinism (injectable clock +
sleep, seeded jitter) is what makes the breaker/chaos tests replayable.

Observability: per-request latency histograms, queue-depth / pressure /
breaker gauges, and -- when an ``obs.Tracer`` is active -- one span per
batch and per escalated request, riding the same ``phase_hook`` seam as
the drivers.
"""
from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs.lifecycle import RequestTrace
from ..obs.tracer import active_tracer, phase_hook
from ..resilience.certify import certified_solve, default_tol
from .admission import AdmissionController, Bucket, Deadline, reject_doc
from .executor import Executor, ls_residual, residual, route_for
from .policy import (DEGRADE_PRESSURE, OPEN, CircuitBreaker, RetryPolicy,
                     select_ladder)

RESULT_SCHEMA = "serve_result/v1"


class SolverService:
    """See module docstring.  ``grid`` is the escalation grid (default:
    the process default grid); ``fastpath=False`` routes every request
    straight to the certified distributed path (the big-problem /
    chaos-redist serving mode).  ``clock``/``sleep`` are injectable for
    deterministic tests."""

    def __init__(self, grid=None, *, max_batch: int = 8, capacity: int = 16,
                 shed: bool = True, fastpath: bool = True,
                 health: bool = True, seed: int = 0,
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 1.0,
                 retries: int = 1, backoff_base_s: float = 0.05,
                 degrade_pressure: float = DEGRADE_PRESSURE,
                 escalate_nb: int | None = None, tol_factor: float = 1.0,
                 flops_per_s: float | None = None,
                 hbm_bytes: float | None = None,
                 pipeline_depth: int = 2,
                 name: str | None = None, tune_ns: str = "",
                 device=None,
                 clock=time.monotonic, sleep=None, flight=None):
        self.grid = grid
        self.max_batch = max(int(max_batch), 1)
        self.capacity = max(int(capacity), 1)
        self.fastpath = bool(fastpath)
        self.health = bool(health)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.degrade_pressure = float(degrade_pressure)
        self.escalate_nb = escalate_nb
        self.tol_factor = float(tol_factor)
        #: fleet identity (ISSUE 19): ``name`` labels this member's
        #: metric series and stamps its result/reject docs; ``tune_ns``
        #: namespaces its tuner constants; ``device`` pins its batch
        #: executables.  All default off -- a direct SolverService keeps
        #: PR-9 semantics (unlabeled gauges, ``grid: None`` in docs).
        self.name = name
        self.tune_ns = str(tune_ns)
        #: flight recorder (ISSUE 20): shared ring the breakers,
        #: lifecycle traces and reject paths all feed; a fleet passes
        #: ONE recorder to every member, None = not recording
        self.flight = flight
        self.clock = clock
        self._sleep = sleep if sleep is not None else time.sleep
        kw = {} if flops_per_s is None else {"flops_per_s": flops_per_s}
        if hbm_bytes is not None:
            kw["hbm_bytes"] = hbm_bytes
        self.admission = AdmissionController(
            shed=shed, max_batch=self.max_batch, clock=clock,
            pipeline_depth=pipeline_depth, grid=name, **kw)
        self.executor = Executor(clock=clock, device=device,
                                 tune_ns=self.tune_ns)
        self.retry = RetryPolicy(retries=retries, base_s=backoff_base_s,
                                 seed=seed)
        self.breakers: dict = {}         # bucket.key() -> CircuitBreaker
        self._queues: dict = {}          # Bucket -> [SolveRequest]
        self.results: dict = {}          # id -> serve_result/v1 | reject
        self.solutions: dict = {}        # id -> np.ndarray
        self._shutdown = False           # set by shutdown(); rejects submits
        self._dispatch: dict = {}        # id -> tuner-fed routing provenance
        #: streaming completion hook (ISSUE 14): called as
        #: ``on_result(id, doc, x)`` the moment a request finalizes --
        #: BEFORE drain returns -- so an async front can resolve futures
        #: per batch.  A raising hook never poisons batch-mates.
        self.on_result = None

    # ---- bookkeeping -------------------------------------------------
    def _grid(self):
        if self.grid is None:
            from ..core.grid import default_grid
            self.grid = default_grid()
        return self.grid

    def breaker(self, bucket: Bucket) -> CircuitBreaker:
        br = self.breakers.get(bucket.key())
        if br is None:
            br = self.breakers[bucket.key()] = CircuitBreaker(
                bucket.key(), threshold=self.breaker_threshold,
                cooldown_s=self.breaker_cooldown_s, clock=self.clock,
                grid=self.name, flight=self.flight)
        return br

    def queue_depth(self, bucket: Bucket | None = None) -> int:
        if bucket is not None:
            return len(self._queues.get(bucket, ()))
        return sum(len(q) for q in self._queues.values())

    def pressure(self) -> float:
        """Queue depth / capacity: the degradation + shedding signal."""
        return self.queue_depth() / self.capacity

    def _gauges(self) -> None:
        if self.name is None:
            _metrics.set_gauge("serve_queue_depth", self.queue_depth())
            _metrics.set_gauge("serve_pressure", self.pressure())
        else:
            # fleet members label their series per grid (ISSUE 19) so
            # the pool's gauges do not clobber each other
            _metrics.set_gauge("serve_queue_depth", self.queue_depth(),
                               grid=self.name)
            _metrics.set_gauge("serve_pressure", self.pressure(),
                               grid=self.name)

    def _tol(self, req) -> float:
        return self.tol_factor * default_tol(req.n, req.A.dtype)

    def _route(self, bucket: Bucket):
        """Tuner-fed dispatch decision for this batch's bucket (ISSUE
        14): per-request vmap estimate from the admission EWMA vs the
        tuning cache's measured grid winner."""
        import jax
        est = self.admission.estimate_batch_s(bucket) / self.max_batch
        g = self._grid()
        return route_for(bucket, (g.height, g.width),
                         jax.default_backend(), est, ns=self.tune_ns)

    # ---- submit ------------------------------------------------------
    def submit(self, op: str, A, B, *, budget_s: float | None = None,
               deadline: Deadline | None = None,
               tenant: str | None = None, trace=None):
        """Admit one request.  Returns the request id (int) on accept or
        a structured ``serve_reject/v1`` dict on fast reject (load shed,
        expired deadline, open breaker, malformed request).  ``tenant``
        rides into the result/reject documents (the fleet path, ISSUE
        19; quota enforcement itself lives in the fleet scheduler).
        ``trace`` (ISSUE 20) is the request's lifecycle trace -- the
        fleet passes the one it opened at fleet submit; a direct caller
        gets a fresh one so every outcome doc carries a ``timeline``."""
        if deadline is None and budget_s is not None:
            deadline = Deadline(budget_s, clock=self.clock)
        if trace is None:
            trace = RequestTrace(clock=self.clock, tenant=tenant, op=op,
                                 flight=self.flight)
            trace.mark("submitted", op=op)
        if self._shutdown:
            rej = reject_doc("shutdown", queue_depth=self.queue_depth(),
                             deadline=deadline, grid=self.name,
                             tenant=tenant, trace=trace,
                             detail="service has shut down")
            self._flight_reject("shutdown", tenant)
            _metrics.inc("serve_rejects", reason="shutdown")
            return rej
        req = self.admission.admit(op, A, B, deadline=deadline,
                                   queue_depth=self.queue_depth,
                                   tenant=tenant, trace=trace)
        if isinstance(req, dict):        # bad_request / expired / shed
            self._flight_reject(req["reason"], tenant)
            _metrics.inc("serve_rejects", reason=req["reason"])
            return req
        bucket = req.bucket
        br = self.breaker(bucket)
        if br.state == OPEN:
            # peek-only: the half-open probe slot belongs to QUEUED work,
            # so an open breaker sheds new submissions without consuming it
            elapsed_ok = br.opened_at is not None \
                and self.clock() - br.opened_at >= br.cooldown_s
            if not elapsed_ok:
                rej = reject_doc("breaker_open", bucket=bucket,
                                 queue_depth=self.queue_depth(bucket),
                                 deadline=deadline, grid=self.name,
                                 tenant=tenant, trace=trace,
                                 detail=f"breaker open for {bucket.key()}")
                self._flight_reject("breaker_open", tenant)
                _metrics.inc("serve_rejects", reason="breaker_open")
                return rej
        self._queues.setdefault(bucket, []).append(req)
        self._gauges()
        return req.id

    def _flight_reject(self, reason: str, tenant) -> None:
        if self.flight is not None:
            self.flight.record("reject", reason=reason, grid=self.name,
                               tenant=tenant)

    def _pop_batch(self):
        """FIFO batch pop: the bucket whose HEAD request is oldest
        yields up to ``max_batch`` requests; None when nothing queued."""
        if not self._queues:
            return None
        bucket = min(self._queues,
                     key=lambda b: self._queues[b][0].submitted)
        q = self._queues[bucket]
        batch, rest = q[:self.max_batch], q[self.max_batch:]
        if rest:
            self._queues[bucket] = rest
        else:
            del self._queues[bucket]
        self._gauges()
        return bucket, batch

    # ---- drain -------------------------------------------------------
    def drain(self) -> dict:
        """Process the queue to completion; returns {id: result doc} for
        everything finalized by this call."""
        tm = phase_hook("serve")
        tm.start()
        done: dict = {}
        before = set(self.results)
        bi = 0
        while True:
            popped = self._pop_batch()
            if popped is None:
                break
            bucket, batch = popped
            self._run_batch(bucket, batch, tm, bi)
            bi += 1
        for rid, doc in self.results.items():
            if rid not in before:
                done[rid] = doc
        self._gauges()
        return done

    def shutdown(self, drain: bool = True) -> dict:
        """Graceful stop (ISSUE 11): nothing queued is dropped silently.

        With ``drain=True`` (default) the queue is processed to
        completion first -- every queued request finishes through the
        normal path.  With ``drain=False`` (emergency stop) queued
        requests are flushed UNEXECUTED: each gets a structured
        ``serve_reject/v1`` with ``reason='shutdown'`` (plus its request
        ``id``) recorded in :attr:`results`.  Either way the service
        then rejects new ``submit`` calls with ``reason='shutdown'``
        and ``shutdown`` is idempotent.  Returns ``{id: doc}`` for every
        request settled by this call."""
        done: dict = {}
        if drain:
            done.update(self.drain())
        for bucket in sorted(self._queues, key=lambda b: b.key()):
            for req in self._queues[bucket]:
                rej = reject_doc("shutdown", bucket=bucket,
                                 queue_depth=0, deadline=req.deadline,
                                 grid=self.name, tenant=req.tenant,
                                 trace=req.trace,
                                 detail="flushed by shutdown(drain=False)")
                rej["id"] = req.id
                self.results[req.id] = rej
                done[req.id] = rej
                self._flight_reject("shutdown", req.tenant)
                _metrics.inc("serve_rejects", reason="shutdown")
                if self.on_result is not None:
                    # flushed requests are completions too: a front
                    # holding futures for them must see them resolve
                    try:
                        self.on_result(req.id, rej, None)
                    except Exception:
                        _metrics.inc("serve_callback_errors", op=req.op)
        self._queues.clear()
        self._shutdown = True
        self._gauges()
        return done

    def solve(self, op: str, A, B, *, budget_s: float | None = None):
        """Convenience synchronous one-shot: submit + drain.  Returns
        ``(X, doc)`` where doc is a result or reject document."""
        rid = self.submit(op, A, B, budget_s=budget_s)
        if isinstance(rid, dict):
            return None, rid
        self.drain()
        return self.solutions.get(rid), self.results[rid]

    # ---- the batch pipeline ------------------------------------------
    def _run_batch(self, bucket: Bucket, reqs, tm, bi: int) -> None:
        live = self._prepare_batch(bucket, reqs)
        if not live:
            return
        tr = active_tracer()
        span = tr.span(f"serve:batch:{bucket.key()}", n=len(live)) \
            if tr is not None else _null_cm()
        with span:
            xs, seconds = self.executor.run(bucket, live)
        tm.tick("batch", bi)
        self._complete_batch(bucket, live, xs, seconds)

    def _prepare_batch(self, bucket: Bucket, reqs) -> list:
        """Pre-execution leg of the batch pipeline: drop expired
        requests, honor the breaker gate, and make the tuner-fed
        dispatch decision.  Returns the live requests to batch-execute
        on the vmap path, or ``[]`` when everything already settled
        (dropped / escalated / grid-routed).  The async front calls this
        and :meth:`_complete_batch` directly so batch k+1's host staging
        can overlap batch k's device execution (ISSUE 14)."""
        live = []
        for req in reqs:
            if req.deadline is not None and req.deadline.expired():
                self._finalize(req, bucket, status="timed_out",
                               path="dropped", timed_out=True)
            else:
                live.append(req)
        if not live:
            return []
        br = self.breaker(bucket)
        if not (self.fastpath and br.allow()):
            _metrics.inc("serve_fastpath_bypass", op=bucket.op)
            for req in live:
                self._escalate(bucket, req)
            return []
        route, prov = self._route(bucket)
        for req in live:
            self._dispatch[req.id] = prov
        if route == "grid":
            # the tuner's measured grid winner beats the per-request
            # vmap estimate: serve each request on the distributed path
            _metrics.inc("serve_grid_dispatch", op=bucket.op)
            for req in live:
                self._escalate(bucket, req, path="grid")
            return []
        return live

    def _complete_batch(self, bucket: Bucket, live, xs,
                        seconds: float) -> None:
        """Post-execution leg: EWMA feedback, trusted certification,
        breaker bookkeeping, bisect isolation of failures."""
        self.admission.observe_batch(bucket, seconds)
        br = self.breaker(bucket)
        passed, failed = self._certify(bucket, live, xs)
        if failed:
            br.record_failure()
        else:
            br.record_success()
        if failed:
            self._isolate(bucket, failed)

    def _certify(self, bucket: Bucket, reqs, xs, path="fastpath"):
        """Trusted per-request residuals; finalize passes, return fails."""
        meas = ls_residual if bucket.op == "lstsq" else residual
        passed, failed = [], []
        for req, X in zip(reqs, xs):
            res = meas(req.A, req.B, X)
            ok = res <= self._tol(req)
            if req.trace is not None:
                req.trace.mark("certified", ok=bool(ok),
                               residual=float(res))
            if ok:
                self._finalize(req, bucket, status="ok", path=path,
                               rung="fastpath", residual=res, x=X)
                passed.append(req)
            else:
                failed.append(req)
        return passed, failed

    def _isolate(self, bucket: Bucket, reqs, depth: int = 0) -> None:
        """Bisect-split a failing group: fresh re-executions certify the
        clean batch-mates (and absorb one-shot faults); a singleton gets
        ONE fresh solo re-execution (the cheap transient-fault retry)
        and only then escapes to the escalation ladder ALONE."""
        if len(reqs) == 1:
            if depth == 0:
                # the batch itself was the singleton: no re-execution
                # evidence yet, give it the solo retry too
                xs, _ = self.executor.run(bucket, reqs)
                _, failed = self._certify(bucket, reqs, xs)
                if not failed:
                    return
            self._escalate(bucket, reqs[0], bisected=True)
            return
        _metrics.inc("serve_bisect_splits", op=bucket.op)
        mid = (len(reqs) + 1) // 2
        for half in (reqs[:mid], reqs[mid:]):
            if not half:
                continue
            xs, _ = self.executor.run(bucket, half)
            _, failed = self._certify(bucket, half, xs)
            if failed:
                if len(half) == 1:
                    self._escalate(bucket, half[0], bisected=True)
                else:
                    self._isolate(bucket, failed, depth + 1)

    # ---- escalation --------------------------------------------------
    def _escalate(self, bucket: Bucket, req, bisected: bool = False,
                  path: str = "escalated") -> None:
        if req.trace is not None:
            req.trace.mark("escalated", path=path, bisected=bool(bisected))
        tr = active_tracer()
        span = tr.span(f"serve:req:{req.id}", op=req.op, grid=self.name,
                       tenant=req.tenant) \
            if tr is not None else _null_cm()
        with span:
            self._escalate_inner(bucket, req, bisected, path)

    def _escalate_inner(self, bucket, req, bisected: bool,
                        path: str = "escalated") -> None:
        from ..core.dist import MC, MR
        from ..core.distmatrix import from_global
        if req.deadline is not None and req.deadline.expired():
            self._finalize(req, bucket, status="timed_out", path=path,
                           timed_out=True, bisected=bisected)
            return
        if req.op == "lstsq":
            self._escalate_lstsq(bucket, req, bisected, path)
            return
        ladder = select_ladder(req.op, self.pressure(),
                               self.degrade_pressure)
        tol = self._tol(req)
        g = self._grid()
        retries = 0
        cert = None
        X = None
        for attempt in range(self.retry.retries + 1):
            Ad = from_global(req.A, MC, MR, grid=g)
            Bd = from_global(req.B, MC, MR, grid=g)
            Xd, cert = certified_solve(req.op, Ad, Bd, tol=tol,
                                       nb=self.escalate_nb, ladder=ladder,
                                       health=self.health,
                                       deadline=req.deadline)
            # owned copy: ``np.asarray`` of a float64 jax CPU array is a
            # zero-copy view of the device buffer, which the allocator
            # reuses once the array drops -- a stored solution would
            # silently mutate under a later solve
            X = None if Xd is None else np.array(
                _to_host(Xd), dtype=np.float64)
            if req.trace is not None:
                req.trace.mark("certified", ok=bool(cert["certified"]),
                               residual=cert["residual"],
                               rung=str(cert["rung"]))
            _metrics.inc("serve_escalations", op=req.op,
                         rung=str(cert["rung"]))
            if cert["certified"]:
                self._finalize(req, bucket, status="ok", path=path,
                               rung=cert["rung"], residual=cert["residual"],
                               x=X, certificate=cert, retries=retries,
                               bisected=bisected)
                return
            if cert["timed_out"]:
                break
            if attempt < self.retry.retries:
                delay = self.retry.delay_s(req.id, attempt + 1,
                                           req.deadline)
                if delay < 0.0:
                    break                # no budget left for a retry
                if delay > 0.0:
                    self._sleep(delay)
                retries += 1
                _metrics.inc("serve_retries", op=req.op)
        timed_out = bool(cert is not None and cert["timed_out"])
        self._finalize(req, bucket,
                       status="timed_out" if timed_out else "failed",
                       path=path, rung=None,
                       residual=None if cert is None else cert["residual"],
                       x=X, certificate=cert, retries=retries,
                       timed_out=timed_out, bisected=bisected)

    def _escalate_lstsq(self, bucket, req, bisected: bool,
                        path: str = "escalated") -> None:
        """Least-squares escalation: the DISTRIBUTED QR path
        (``lapack.qr.least_squares``) with the same retry/backoff and
        trusted normal-equations certification as the square ladder
        (``certified_solve`` has no lstsq rung -- the grid solve IS the
        stronger rung here).  The factorization runs ABFT-guarded
        (ISSUE 15): a transient fault inside the escalation QR is
        detected at the corrupted panel and repaired by one panel
        re-execution instead of burning a whole serve retry -- every
        escalation rung is now corruption-attested."""
        from ..core.dist import MC, MR
        from ..core.distmatrix import from_global, to_global
        from ..lapack.qr import least_squares
        tol = self._tol(req)
        g = self._grid()
        retries = 0
        res = None
        X = None
        for attempt in range(self.retry.retries + 1):
            if req.deadline is not None and req.deadline.expired():
                self._finalize(req, bucket, status="timed_out", path=path,
                               timed_out=True, bisected=bisected,
                               retries=retries)
                return
            Ad = from_global(req.A, MC, MR, grid=g)
            Bd = from_global(req.B, MC, MR, grid=g)
            Xd = least_squares(Ad, Bd, nb=self.escalate_nb, abft=True)
            X = np.array(to_global(Xd), dtype=np.float64)  # owned copy
            res = ls_residual(req.A, req.B, X)
            if req.trace is not None:
                req.trace.mark("certified", ok=bool(res <= tol),
                               residual=float(res), rung="grid_qr")
            _metrics.inc("serve_escalations", op=req.op, rung="grid_qr")
            if res <= tol:
                self._finalize(req, bucket, status="ok", path=path,
                               rung="grid_qr", residual=res, x=X,
                               retries=retries, bisected=bisected)
                return
            if attempt < self.retry.retries:
                delay = self.retry.delay_s(req.id, attempt + 1, req.deadline)
                if delay < 0.0:
                    break
                if delay > 0.0:
                    self._sleep(delay)
                retries += 1
                _metrics.inc("serve_retries", op=req.op)
        self._finalize(req, bucket, status="failed", path=path, rung=None,
                       residual=res, x=X, retries=retries,
                       bisected=bisected)

    # ---- finalize ----------------------------------------------------
    def _finalize(self, req, bucket: Bucket, *, status: str, path: str,
                  rung: str | None = None, residual: float | None = None,
                  x=None, certificate: dict | None = None, retries: int = 0,
                  timed_out: bool = False, bisected: bool = False) -> None:
        latency = self.clock() - req.submitted
        if req.trace is not None:
            req.trace.annotate(grid=self.name, bucket=bucket, op=req.op)
            req.trace.mark("done", status=status, path=path)
        doc = {"schema": RESULT_SCHEMA, "id": req.id, "op": req.op,
               "n": req.n, "nrhs": req.nrhs, "bucket": bucket.key(),
               "status": status, "path": path, "rung": rung,
               "residual": residual, "tol": self._tol(req),
               "retries": int(retries), "bisected": bool(bisected),
               "timed_out": bool(timed_out), "latency_s": float(latency),
               "deadline": req.deadline.to_doc()
               if req.deadline is not None else None,
               "certificate": certificate,
               "breaker": self.breaker(bucket).state,
               "dispatch": self._dispatch.pop(req.id, None),
               "grid": self.name, "tenant": req.tenant,
               "timeline": req.trace.to_doc()
               if req.trace is not None else None}
        self.results[req.id] = doc
        if self.flight is not None and status == "failed":
            # an unrecovered request -- escalation + bisection exhausted
            # -- is a flight-recorder dump trigger (ISSUE 20)
            self.flight.trigger("unrecovered", id=req.id, op=req.op,
                                bucket=bucket.key(), grid=self.name,
                                tenant=req.tenant)
        x_out = x if status == "ok" else None
        if x_out is not None:
            self.solutions[req.id] = x_out
        _metrics.inc("serve_requests", op=req.op, status=status)
        if self.name is None:
            _metrics.observe("serve_latency_seconds", float(latency),
                             op=req.op)
        else:
            _metrics.observe("serve_latency_seconds", float(latency),
                             op=req.op, grid=self.name)
        if req.tenant is not None:
            _metrics.observe("serve_tenant_latency_seconds", float(latency),
                             tenant=req.tenant)
        if self.on_result is not None:
            try:
                self.on_result(req.id, doc, x_out)
            except Exception:
                # a raising completion callback must never poison the
                # batch-mates still being finalized
                _metrics.inc("serve_callback_errors", op=req.op)


def _to_host(Xd):
    from ..core.distmatrix import to_global
    return to_global(Xd)


class _null_cm:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
