"""Admission control: shape bucketing, deadlines, and load shedding.

The front door of the solver service (ISSUE 9).  Production traffic at
the scale the source paper targets (arXiv 2112.09017) is overwhelmingly
many small-to-medium solves; this module turns an arbitrary stream of
``A x = b`` requests into a SMALL set of canonical geometries the
executor can batch and AOT-compile once:

  * **shape bucketing** -- request dims round up to the tuner's
    power-of-two buckets (:func:`~elemental_tpu.tune.cache.shape_bucket`,
    the SAME bucketing the tuning cache keys on, so serve buckets and
    tuned knob entries line up 1:1);
  * **deadlines** -- every request carries a :class:`Deadline` (budget /
    elapsed / remaining), the object the whole dispatch chain threads:
    the batcher drops expired requests before launch, the executor
    checks it before dispatch, and ``certified_solve(deadline=)`` stops
    the escalation ladder on it (the ISSUE-9 certify satellite);
  * **load shedding** -- when the estimated queue wait for a request's
    bucket (queued batches ahead x the bucket's cost estimate) exceeds
    its remaining budget, the request is rejected FAST with a structured
    ``serve_reject/v1`` document instead of being queued to die: the
    client learns in microseconds, not after the deadline.

Cost estimates are per-bucket EWMAs of measured batch seconds (the
executor reports every batch it runs), seeded cold by a flops/throughput
model -- so shedding is conservative on a cold service and converges to
the observed rate.

All clocks are injectable (``clock=`` on :class:`Deadline` and
:class:`AdmissionController`), which is what makes the chaos/breaker
tests deterministic under replay.
"""
from __future__ import annotations

import dataclasses
import itertools
import time

import numpy as np

from ..tune.cache import shape_bucket

REJECT_SCHEMA = "serve_reject/v1"

#: reject reasons (pinned by tests/serve).  'shutdown' (ISSUE 11) marks
#: requests flushed by ``SolverService.shutdown`` -- queued work that was
#: NOT executed gets this structured reject instead of being dropped.
#: 'memory_pressure' (ISSUE 18): the bucket's statically derived peak
#: bytes at max_batch do not fit the configured per-device HBM.
#: 'quota' (ISSUE 19): the submitting tenant is at its configured
#: max-outstanding limit in the fleet's fair scheduler.
REJECT_REASONS = ("queue_pressure", "deadline_expired", "breaker_open",
                  "bad_request", "shutdown", "memory_pressure", "quota")

#: cold-start throughput assumption for the flops-based cost seed,
#: flop/s.  Deliberately modest (CPU-class): a cold service sheds
#: conservatively and the EWMA takes over after the first batch.
COLD_FLOPS_PER_S = 2.0e9

#: EWMA smoothing for measured batch seconds
EWMA_ALPHA = 0.4


class Deadline:
    """A wall-clock budget: ``budget`` seconds from construction.

    The request-scoped object the service propagates through dispatch
    (admission -> batcher -> executor -> escalation); duck-typed by
    ``certified_solve(deadline=)`` which only needs :meth:`remaining`.
    ``clock`` is injectable for deterministic tests (default
    ``time.monotonic``)."""

    __slots__ = ("budget", "clock", "start")

    def __init__(self, budget: float, clock=time.monotonic):
        self.budget = float(budget)
        self.clock = clock
        self.start = clock()

    def elapsed(self) -> float:
        return self.clock() - self.start

    def remaining(self) -> float:
        return self.budget - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def to_doc(self) -> dict:
        return {"budget_s": self.budget, "elapsed_s": self.elapsed(),
                "remaining_s": self.remaining()}

    def __repr__(self):
        return (f"Deadline(budget={self.budget:.3g}s, "
                f"remaining={self.remaining():.3g}s)")


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One canonical serve geometry: (op, padded dims, dtype).

    Square solves (lu/hpd) carry ``n x nrhs``; tall-skinny least-squares
    requests (``op='lstsq'``, ISSUE 14) additionally carry ``m`` -- the
    padded ROW count -- so the key/geometry vocabulary stays backward
    compatible for the square ops (``m is None``)."""
    op: str                      # "lu" | "hpd" | "lstsq"
    n: int                       # pow2-bucketed system size (columns)
    nrhs: int                    # pow2-bucketed right-hand-side count
    dtype: str
    m: int | None = None         # lstsq only: padded row count

    def key(self) -> str:
        """Cache-key string, same style as ``tuning_cache/v1`` filenames."""
        if self.m is not None:
            return f"{self.op}__b{self.m}x{self.n}x{self.nrhs}__{self.dtype}"
        return f"{self.op}__b{self.n}x{self.nrhs}__{self.dtype}"

    def solve_flops(self) -> float:
        """Factor + solve flops of ONE padded problem (the cost seed)."""
        n, k = float(self.n), float(self.nrhs)
        if self.op == "lstsq":
            m = float(self.m)
            return 2.0 * m * n * n + 2.0 * m * n * k   # QR + apply/solve
        factor = (n ** 3) / 3.0 if self.op == "hpd" else 2.0 * (n ** 3) / 3.0
        return factor + 2.0 * n * n * k


def make_bucket(op: str, n: int, nrhs: int, dtype,
                m: int | None = None) -> Bucket:
    """Bucket a concrete request geometry (pow2 per dim, tuner-aligned).

    For ``op='lstsq'`` pass the raw row count ``m``: columns bucket to
    ``N = pow2(n)`` first, rows to ``M = pow2(m + (N - n))`` -- the extra
    ``N - n`` rows are where the executor's identity pad lives (see
    ``executor.pad_problem_ls``), so every request of the bucket embeds
    losslessly whatever its raw shape."""
    bn, brhs = shape_bucket((int(n), max(int(nrhs), 1)))
    if op == "lstsq":
        if m is None:
            raise ValueError("lstsq buckets need the row count m")
        (bm,) = shape_bucket((int(m) + int(bn) - int(n),))
        return Bucket(op=op, n=int(bn), nrhs=int(brhs),
                      dtype=np.dtype(dtype).name, m=int(bm))
    return Bucket(op=op, n=int(bn), nrhs=int(brhs), dtype=np.dtype(dtype).name)


@dataclasses.dataclass
class SolveRequest:
    """One admitted request (host-side problem data + its deadline)."""
    id: int
    op: str                      # "lu" | "hpd"
    A: np.ndarray                # (n, n) host array
    B: np.ndarray                # (n, nrhs) host array
    bucket: Bucket
    deadline: Deadline | None
    submitted: float             # admission clock timestamp
    tenant: str | None = None    # fleet tenant (ISSUE 19), None = direct
    #: lifecycle timeline (ISSUE 20): the per-request
    #: ``obs.lifecycle.RequestTrace`` that rides the request through
    #: stage/dispatch/collect/certify; None = untraced (old callers)
    trace: object = None

    @property
    def n(self) -> int:
        return int(self.A.shape[0])

    @property
    def nrhs(self) -> int:
        return int(self.B.shape[1])


def reject_doc(reason: str, *, bucket: Bucket | None = None,
               queue_depth: int = 0, estimate_s: float | None = None,
               deadline: Deadline | None = None, detail: str = "",
               grid: str | None = None, tenant: str | None = None,
               trace=None) -> dict:
    """A structured fast-reject (``serve_reject/v1``).

    ``grid`` / ``tenant`` (ISSUE 19) attribute the decision to the fleet
    member that made it and the quota bucket it was charged against;
    both default to None for the single-service path, so old documents
    and old readers stay valid (absent == None).

    ``trace`` (ISSUE 20): the request's lifecycle
    :class:`~elemental_tpu.obs.lifecycle.RequestTrace`, when one exists.
    The reject closes it -- ``shed`` (with the reason) then the terminal
    ``rejected`` edge -- and the doc gains the ``timeline`` sub-doc, so
    rejected requests carry the same end-to-end record results do."""
    if reason not in REJECT_REASONS:
        raise ValueError(f"unknown reject reason {reason!r}; "
                         f"expected one of {REJECT_REASONS}")
    doc = {"schema": REJECT_SCHEMA, "reason": reason,
           "bucket": bucket.key() if bucket is not None else None,
           "queue_depth": int(queue_depth),
           "estimate_s": None if estimate_s is None else float(estimate_s),
           "deadline": deadline.to_doc() if deadline is not None else None,
           "detail": detail, "grid": grid, "tenant": tenant,
           "timeline": None}
    if trace is not None:
        trace.annotate(grid=grid, tenant=tenant, bucket=bucket)
        trace.mark("shed", reason=reason)
        trace.mark("rejected")
        doc["timeline"] = trace.to_doc()
    return doc


def validate_problem(op: str, A, B):
    """Canonicalize ONE request: op aliasing, shape/dtype checks, and
    the tuner-aligned bucket.  Returns ``(op, A, B, bucket)`` on success
    or a ``serve_reject/v1`` dict (``reason='bad_request'``) -- the
    validation half of :meth:`AdmissionController.admit`, split out so
    the fleet router (ISSUE 19) can bucket a request BEFORE choosing
    which grid's admission controller will see it."""
    op = "hpd" if op == "cholesky" else op
    op = "lstsq" if op == "qr" else op
    if op not in ("lu", "hpd", "lstsq"):
        return reject_doc(
            "bad_request",
            detail=f"op must be 'lu', 'hpd' or 'lstsq', got {op!r}")
    A = np.asarray(A)
    B = np.asarray(B)
    if B.ndim == 1:
        B = B[:, None]
    square_ok = A.ndim == 2 and A.shape[0] == A.shape[1]
    tall_ok = A.ndim == 2 and A.shape[0] >= A.shape[1]
    shape_ok = (tall_ok if op == "lstsq" else square_ok) \
        and B.ndim == 2 and B.shape[0] == A.shape[0]
    if not shape_ok:
        return reject_doc("bad_request",
                          detail=f"bad shapes A{A.shape} B{B.shape}")
    if not np.issubdtype(A.dtype, np.inexact):
        A = A.astype(np.float64)
        B = B.astype(np.float64)
    bucket = make_bucket(op, A.shape[1], B.shape[1], A.dtype,
                         m=A.shape[0] if op == "lstsq" else None)
    return op, A, B, bucket


class AdmissionController:
    """Buckets requests, estimates queue cost, sheds load.

    ``admit(op, A, B, deadline, queue_depth)`` validates the request,
    assigns its bucket, and EITHER returns a :class:`SolveRequest` or a
    ``serve_reject/v1`` dict when the estimated wait cannot fit the
    deadline (``shed=False`` disables shedding -- bench mode).  The
    caller owns the queue; ``queue_depth`` is the number of requests
    already waiting in the same bucket."""

    def __init__(self, *, shed: bool = True, max_batch: int = 8,
                 flops_per_s: float = COLD_FLOPS_PER_S,
                 clock=time.monotonic, hbm_bytes: float | None = None,
                 pipeline_depth: int = 2, grid: str | None = None):
        self.shed = bool(shed)
        self.max_batch = max(int(max_batch), 1)
        self.flops_per_s = float(flops_per_s)
        self.clock = clock
        #: per-device HBM budget for the memory-pressure check (ISSUE 18).
        #: None = the backend default from the tuner's machine table,
        #: resolved lazily (jax must not initialize at import time)
        self.hbm_bytes = None if hbm_bytes is None else float(hbm_bytes)
        #: resident batches the worker keeps in flight (ISSUE 19): the
        #: memory-pressure threshold is ``depth x`` the single-batch
        #: peak -- 2 for the classic double buffer, k for a depth-k
        #: pipelined fleet member
        self.pipeline_depth = max(int(pipeline_depth), 1)
        #: fleet member name stamped into every reject this controller
        #: issues (None for a direct single-service deployment)
        self.grid = grid
        self._ids = itertools.count()
        self._ewma: dict = {}            # bucket.key() -> seconds per batch
        self._peak_memo: dict = {}       # bucket.key() -> peak bytes | None

    # ---- memory pressure (ISSUE 18) ---------------------------------
    def _hbm_budget(self) -> float:
        if self.hbm_bytes is None:
            import jax
            from ..tune.cost_model import machine_for
            self.hbm_bytes = float(
                machine_for(jax.default_backend()).hbm_bytes)
        return self.hbm_bytes

    def bucket_peak_bytes(self, bucket: Bucket) -> float | None:
        """Statically derived peak live bytes of ONE max_batch batch of
        this bucket (the executor's vmapped kernel, liveness-walked --
        no device execution).  Memoized per bucket; None when the
        abstract trace is unavailable (never a reason to shed)."""
        key = bucket.key()
        if key in self._peak_memo:
            return self._peak_memo[key]
        try:                    # lazy: executor imports Bucket from here
            from .executor import batch_peak_bytes
            peak = batch_peak_bytes(bucket, self.max_batch)
        except Exception:
            peak = None
        self._peak_memo[key] = peak
        return peak

    def memory_pressure(self, bucket: Bucket):
        """(peak bytes, budget) when the bucket CANNOT fit, else None.

        The pipelined worker keeps ``pipeline_depth`` batches resident
        (in flight on device + staging), so the shed threshold is
        ``depth x`` the single batch peak against the per-device HBM
        budget -- 2x for the classic double buffer.  A fleet member with
        a small per-grid budget therefore sheds a bucket its big-grid
        pool-mate still admits (ISSUE 19)."""
        if not self.shed:
            return None
        peak = self.bucket_peak_bytes(bucket)
        if peak is None:
            return None
        budget = self._hbm_budget()
        if self.pipeline_depth * peak > budget:
            return peak, budget
        return None

    # ---- cost estimation --------------------------------------------
    def estimate_batch_s(self, bucket: Bucket) -> float:
        """Estimated seconds for ONE max_batch batch of this bucket:
        measured EWMA when warm, flops/throughput when cold."""
        est = self._ewma.get(bucket.key())
        if est is not None:
            return est
        return bucket.solve_flops() * self.max_batch / self.flops_per_s

    def observe_batch(self, bucket: Bucket, seconds: float) -> None:
        """Executor feedback: one batch of ``bucket`` took ``seconds``."""
        key = bucket.key()
        prev = self._ewma.get(key)
        s = float(seconds)
        self._ewma[key] = s if prev is None \
            else EWMA_ALPHA * s + (1.0 - EWMA_ALPHA) * prev

    def estimated_wait_s(self, bucket: Bucket, queue_depth: int) -> float:
        """Queue wait estimate: batches ahead x per-batch estimate (the
        request itself rides the LAST of those batches)."""
        batches = -(-max(int(queue_depth) + 1, 1) // self.max_batch)
        return batches * self.estimate_batch_s(bucket)

    # ---- admission ---------------------------------------------------
    def admit(self, op: str, A, B, deadline: Deadline | None = None,
              queue_depth=0, tenant: str | None = None, trace=None):
        """One admission decision: :class:`SolveRequest` or reject dict.

        ``queue_depth`` is the number of same-bucket requests already
        waiting -- an int, or a callable ``bucket -> int`` (the bucket is
        only known after validation, so a queue-owning caller passes its
        depth lookup).  ``tenant`` rides into the request and every
        reject this call issues (the fleet path, ISSUE 19).  ``trace``
        (ISSUE 20) is the request's lifecycle trace: admission marks the
        ``admitted`` edge (or closes it with ``shed``/``rejected``) and
        attaches it to the :class:`SolveRequest` so the executor can
        mark the batch stages."""
        v = validate_problem(op, A, B)
        if isinstance(v, dict):
            v["grid"] = self.grid
            v["tenant"] = tenant
            if trace is not None:
                trace.annotate(grid=self.grid, tenant=tenant)
                trace.mark("shed", reason=v["reason"])
                trace.mark("rejected")
                v["timeline"] = trace.to_doc()
            return v
        op, A, B, bucket = v
        if callable(queue_depth):
            queue_depth = int(queue_depth(bucket))
        pressure = self.memory_pressure(bucket)
        if pressure is not None:
            peak, budget = pressure
            return reject_doc(
                "memory_pressure", bucket=bucket, queue_depth=queue_depth,
                deadline=deadline, grid=self.grid, tenant=tenant,
                trace=trace,
                detail=f"static peak {int(peak)} B/batch x"
                       f"{self.pipeline_depth} ("
                       + ("double buffer"
                          if self.pipeline_depth == 2
                          else f"pipeline depth {self.pipeline_depth}")
                       + f") exceeds the {int(budget)} B HBM budget")
        if deadline is not None:
            if deadline.expired():
                return reject_doc("deadline_expired", bucket=bucket,
                                  queue_depth=queue_depth, deadline=deadline,
                                  grid=self.grid, tenant=tenant, trace=trace)
            if self.shed:
                wait = self.estimated_wait_s(bucket, queue_depth)
                if wait > deadline.remaining():
                    return reject_doc(
                        "queue_pressure", bucket=bucket,
                        queue_depth=queue_depth, estimate_s=wait,
                        deadline=deadline, grid=self.grid, tenant=tenant,
                        trace=trace,
                        detail=f"estimated wait {wait:.3g}s exceeds "
                               f"remaining {deadline.remaining():.3g}s")
        req = SolveRequest(id=next(self._ids), op=op, A=A, B=B,
                           bucket=bucket, deadline=deadline,
                           submitted=self.clock(), tenant=tenant,
                           trace=trace)
        if trace is not None:
            trace.annotate(id=trace.id if trace.id is not None else req.id,
                           grid=self.grid, tenant=tenant, bucket=bucket,
                           op=op)
            trace.mark("admitted", grid=self.grid, bucket=bucket.key(),
                       queue_depth=queue_depth)
        return req
