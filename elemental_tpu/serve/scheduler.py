"""Tenant-aware fair scheduling: quotas + deficit round robin (ISSUE 19).

The fleet's front queue.  A single FIFO lets one tenant's burst starve
everyone behind it; the :class:`FairScheduler` instead keeps ONE queue
per tenant and serves them by **deficit round robin** (DRR): each visit
tops a tenant's deficit counter up by ``quantum x share`` and dequeues
work while the deficit covers the head item's cost, so over any window
every backlogged tenant drains in proportion to its configured share --
a 40-request burst from one tenant cannot push another tenant's single
request more than one round back.  Costs default to the bucket's
padded solve flops (a 512-system counts more than a 32-system), so
fairness is in COMPUTE, not request count.

Quotas are the other half (:class:`TenantQuota`): ``max_outstanding``
caps how many of a tenant's requests may be unresolved at once --
enforcement lives in the fleet's submit path, which issues the
schema-pinned ``serve_reject/v1`` ``reason='quota'`` BEFORE anything is
queued (the reject-fast contract admission established for shedding).

Determinism: tenants are visited in first-arrival order, the round
cursor is plain state, and nothing reads a wall clock -- a replayed
submission sequence pops in an identical order, which is what lets the
fairness tests pin latency bounds under injected clocks.

Observability: ``serve_tenant_queue_depth`` and ``serve_tenant_deficit``
gauges per tenant, ``serve_tenant_enqueued`` counters.
"""
from __future__ import annotations

import collections
import dataclasses

from ..obs import metrics as _metrics

#: tenant used when a caller never names one
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's scheduling contract.

    ``share`` is the DRR weight (relative drain rate among backlogged
    tenants); ``max_outstanding`` caps unresolved requests (None =
    unlimited) -- exceeding it draws a ``'quota'`` reject at submit."""
    share: float = 1.0
    max_outstanding: int | None = None

    def __post_init__(self):
        if not (self.share > 0.0):
            raise ValueError(f"tenant share must be > 0, got {self.share}")
        if self.max_outstanding is not None and self.max_outstanding < 1:
            raise ValueError("max_outstanding must be >= 1 or None")


class FairScheduler:
    """Deficit-round-robin fair queue over per-tenant FIFOs.

    ``push(tenant, item, cost)`` enqueues; ``pop()`` returns the next
    item under DRR or None when empty.  A tenant keeps the turn while
    its deficit covers its queue head (classic DRR serves a full
    quantum per visit), then the cursor advances.  ``quantum=None``
    (default) auto-sizes each top-up to the largest head cost among
    backlogged tenants, the standard choice that guarantees every visit
    can afford at least one item regardless of cost scale."""

    def __init__(self, *, quotas: dict | None = None,
                 default_share: float = 1.0,
                 quantum: float | None = None):
        self.quotas = {str(t): q if isinstance(q, TenantQuota)
                       else TenantQuota(**dict(q))
                       for t, q in (quotas or {}).items()}
        self.default_share = float(default_share)
        self.quantum = None if quantum is None else float(quantum)
        self._queues: dict = {}          # tenant -> deque[(item, cost)]
        self._deficit: dict = {}         # tenant -> float
        self._order: list = []           # first-arrival tenant order
        self._cursor = 0                 # index into _order
        self._topped = False             # cursor position got its top-up

    # ---- quota lookup ------------------------------------------------
    def quota(self, tenant: str) -> TenantQuota:
        q = self.quotas.get(tenant)
        if q is None:
            q = TenantQuota(share=self.default_share)
        return q

    def share(self, tenant: str) -> float:
        return self.quota(tenant).share

    # ---- queue ops ---------------------------------------------------
    def push(self, tenant: str, item, cost: float = 1.0) -> None:
        tenant = str(tenant)
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = collections.deque()
            self._deficit[tenant] = 0.0
            self._order.append(tenant)
        q.append((item, max(float(cost), 1e-30)))
        tr = getattr(item, "trace", None)
        if tr is not None:                       # lifecycle (ISSUE 20)
            tr.mark("tenant_queued", tenant=tenant, depth=len(q))
        _metrics.inc("serve_tenant_enqueued", tenant=tenant)
        _metrics.set_gauge("serve_tenant_queue_depth", len(q),
                           tenant=tenant)

    def push_front(self, tenant: str, item, cost: float = 1.0) -> None:
        """Router un-pop: re-queue ``item`` at the HEAD of its tenant's
        queue and refund the deficit :meth:`pop` spent on it.  The fleet
        uses this when every member capable of the item's bucket is at
        capacity -- the item must wait without losing its turn."""
        tenant = str(tenant)
        c = max(float(cost), 1e-30)
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = collections.deque()
            self._deficit[tenant] = 0.0
            self._order.append(tenant)
        q.appendleft((item, c))
        self._deficit[tenant] += c
        _metrics.set_gauge("serve_tenant_queue_depth", len(q),
                           tenant=tenant)

    def pending(self, tenant: str | None = None) -> int:
        if tenant is not None:
            return len(self._queues.get(str(tenant), ()))
        return sum(len(q) for q in self._queues.values())

    def _quantum(self) -> float:
        if self.quantum is not None:
            return self.quantum
        heads = [q[0][1] for q in self._queues.values() if q]
        return max(heads) if heads else 1.0

    def _advance(self) -> None:
        self._cursor += 1
        self._topped = False

    def pop(self):
        """The next item under DRR, or None when nothing is queued.

        A tenant's deficit tops up once per VISIT -- the first time the
        cursor lands on it, not once per pop -- so a tenant keeps the
        turn only while already-granted credit covers its heads, then
        yields.  (Topping up per pop would refill the same tenant
        forever under uniform costs: the exact starvation DRR exists to
        prevent.)

        Termination: every full sweep over backlogged tenants tops each
        deficit up by ``quantum x share > 0`` and the affordable head
        cost is finite, so some tenant becomes servable after finitely
        many sweeps (one, with the auto quantum and shares >= 1)."""
        if self.pending() == 0:
            return None
        n = len(self._order)
        visited_since_serve = 0
        while True:
            tenant = self._order[self._cursor % n]
            q = self._queues[tenant]
            if not q:
                self._deficit[tenant] = 0.0      # classic DRR reset
                self._advance()
                continue
            item, cost = q[0]
            if not self._topped and self._deficit[tenant] < cost:
                self._deficit[tenant] += self._quantum() \
                    * self.share(tenant)
                self._topped = True
            if self._deficit[tenant] < cost:
                visited_since_serve += 1
                if visited_since_serve > 4 * n + 4:
                    # cost scale outran the quantum (small shares or
                    # fixed-quantum configs): serve the head anyway
                    # rather than spin -- progress beats exactness
                    self._deficit[tenant] = cost
                else:
                    self._advance()
                    continue
            q.popleft()
            self._deficit[tenant] -= cost
            if not q:
                self._deficit[tenant] = 0.0      # empty queue: no credit
                self._advance()                  # give up the turn
            tr = getattr(item, "trace", None)
            if tr is not None:                   # queue-wait (ISSUE 20)
                t_q = tr.edge_t("tenant_queued")
                if t_q is not None:
                    _metrics.observe("serve_queue_wait_seconds",
                                     tr.clock() - t_q, tenant=tenant)
            _metrics.set_gauge("serve_tenant_queue_depth", len(q),
                               tenant=tenant)
            _metrics.set_gauge("serve_tenant_deficit",
                               self._deficit[tenant], tenant=tenant)
            return item

    def flush(self) -> list:
        """Drain EVERYTHING (shutdown path): all queued items in tenant
        arrival order, FIFO within each tenant.  Resets all deficits."""
        out = []
        self._topped = False
        for tenant in self._order:
            q = self._queues[tenant]
            while q:
                out.append(q.popleft()[0])
            self._deficit[tenant] = 0.0
            _metrics.set_gauge("serve_tenant_queue_depth", 0,
                               tenant=tenant)
        return out

    def to_doc(self) -> dict:
        """Introspection snapshot (what the fleet's stats report)."""
        return {"tenants": list(self._order),
                "depths": {t: len(self._queues[t]) for t in self._order},
                "deficits": {t: self._deficit[t] for t in self._order},
                "shares": {t: self.share(t) for t in self._order}}
