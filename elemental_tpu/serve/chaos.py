"""Chaos harness: the serve acceptance matrix under fault injection.

Drives a :class:`~.service.SolverService` against the ISSUE-7
:class:`~elemental_tpu.resilience.FaultPlan` machinery (seeded,
bit-identically replayable) and CLASSIFIES every request's outcome, so
the acceptance matrix

    {bitflip, scale, nan} x {redistribute, compute} x {oneshot, persistent}

is pinned as data: every fault is either

  * **absorbed**  -- the request still ended ``ok`` within its deadline
    (bisect re-execution ate a one-shot fault, or escalation repaired
    it), with the independently recomputed residual under tol;
  * **isolated**  -- the faulted request failed/timed out ALONE while
    its batch-mates ended ``ok`` (zero collateral damage);
  * **surfaced**  -- a structured failure (certificate with failing
    phase / timed_out flag), never a silent garbage solution.

Violations -- silent garbage (``ok`` whose trusted recomputed residual
exceeds tol), collateral damage (a non-faulted batch-mate not ``ok`` in
a one-shot cell), or an unstructured failure -- are collected per cell;
a clean matrix has none.  ``python -m perf.serve chaos`` is the CLI /
``tools/check.sh serve`` gate; ``tests/serve/test_chaos.py`` pins the
matrix plus replay determinism in tier-1.

Fault-target routing: ``compute`` cells run the BATCHED fast path (the
executor's solve output crosses the compute seam -- call 0 is the first
batch); ``redistribute`` / ``panel_spread`` cells run ``fastpath=False``
so every request exercises the distributed certified path where the
engine seams live (the big-problem serving mode).

ISSUE 11 grows the matrix a ``qr`` op column (:func:`run_qr_cell`):
serve admission only solves lu/hpd, so the qr cells drive the driver
directly under the same fault axes.  ISSUE 15 upgrades the column to
``qr(..., abft=True, health=True)``: detection now rides the
Huang-Abraham checksum checks, ALL THREE kinds gate (bitflip included
-- see :data:`QR_DETECTED_KINDS`), and each cell additionally pins the
recovery contract (one recomputed panel, clean trusted residual).

ISSUE 14 grows an **async** column: :func:`run_async_cell` drives the
pipelined :class:`~.async_front.AsyncSolverService` with TWO batches in
flight so the fault lands mid-pipeline -- batch 0's device output is
corrupted while batch 1 is already staged/dispatched behind it -- and
pins that the damage never leaks into the neighbor batch.  The same
grading applies (the worker thread is deterministic: single consumer,
FIFO batch pop, so seeded plans replay).  :func:`run_async_shutdown_cell`
pins ``shutdown(drain=False)`` under load: every future resolves, every
unexecuted request gets a STRUCTURED ``serve_reject/v1`` shutdown
reject, zero silent drops.

ISSUE 19 grows a **fleet** column: :func:`run_fleet_saturation_cell`
drives a 2-grid sync fleet into overload under a virtual clock (batch
execution advances injected time, so deadline/shed dynamics are exact
and replayable) and pins that every shed is STRUCTURED and attributed
to its grid while admitted latency stays deadline-bounded and FLAT as
overload grows; :func:`run_fleet_grid_loss_cell` poisons one member's
executor until its breaker opens and pins that traffic RE-ROUTES to the
healthy member (every post-loss request served there, fastpath) with
zero silent drops and bit-identical replay.
"""
from __future__ import annotations

import json

import numpy as np

from ..resilience.faults import (FAULT_KINDS, FaultPlan, FaultSpec,
                                 logs_identical)
from ..redist.engine import fault_injection
from .admission import REJECT_REASONS, REJECT_SCHEMA
from .async_front import AsyncSolverService
from .executor import residual
from .service import SolverService

CHAOS_SCHEMA = "chaos_report/v1"

#: the matrix's default target axis (panel_spread is covered by the
#: resilience suite; serve adds the compute axis it introduced)
CHAOS_TARGETS = ("redistribute", "compute")
CHAOS_MODES = ("oneshot", "persistent")

#: ops whose serve path exercises each target (overridable per cell)
_OP_FOR_TARGET = {"redistribute": "lu", "panel_spread": "hpd",
                  "compute": "hpd"}


def build_workload(op: str, n: int, nrhs: int, count: int, seed: int,
                   dtype=None):
    """``count`` well-conditioned problems (same bucket), seeded.

    ``dtype=None`` adapts to the runtime: float64 when jax x64 is
    enabled (the test harness), float32 otherwise (plain CLI processes,
    where float64 payloads would silently downcast and no residual could
    meet a float64-class tolerance)."""
    if dtype is None:
        import jax
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    dtype = np.dtype(dtype)
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(count):
        F = rng.normal(size=(n, n))
        A = F @ F.T / n + n * np.eye(n) if op == "hpd" \
            else F + n * np.eye(n)
        B = rng.normal(size=(n, nrhs))
        out.append((A.astype(dtype), B.astype(dtype)))
    return out


def make_service(grid, *, fastpath: bool, requests: int,
                 clock=None, sleep=None, **kw) -> SolverService:
    """A chaos-shaped service: one batch holds the whole workload, no
    shedding, a breaker too patient to interfere with the cell (breaker
    dynamics have their own tests), near-zero backoff."""
    skw = dict(max_batch=max(requests, 1), capacity=4 * max(requests, 1),
               shed=False, fastpath=fastpath, breaker_threshold=99,
               retries=1, backoff_base_s=0.0)
    skw.update(kw)
    if clock is not None:
        skw["clock"] = clock
    if sleep is not None:
        skw["sleep"] = sleep
    return SolverService(grid, **skw)


def compute_slots(plan: FaultPlan, bucket_n: int, bucket_nrhs: int) -> set:
    """Batch slots whose payload a ``compute`` fault event on the FIRST
    batched dispatch touched (flat index -> leading batch axis)."""
    hit = set()
    per = bucket_n * bucket_nrhs
    for ev in plan.log:
        if ev.target == "compute" and len(ev.shape) == 3:
            hit.update(int(i) // per for i in np.asarray(ev.indices))
    return hit


def run_cell(grid, *, kind: str, target: str, mode: str,
             op: str | None = None, n: int = 16, nrhs: int = 2,
             requests: int = 4, call: int = 0, nelem: int = 2,
             seed: int = 13, budget_s: float | None = None,
             service_kw: dict | None = None):
    """One acceptance-matrix cell.  Returns ``(cell_doc, plan, service)``
    -- the plan so callers can replay-compare logs, the service so tests
    can poke solutions/metrics."""
    op = op or _OP_FOR_TARGET[target]
    fastpath = target == "compute"
    svc = make_service(grid, fastpath=fastpath, requests=requests,
                       **(service_kw or {}))
    workload = build_workload(op, n, nrhs, requests, seed)
    plan = FaultPlan(seed=seed, faults=[
        FaultSpec(target, kind, call=call, every=(mode == "persistent"),
                  nelem=nelem)])
    ids = []
    for A, B in workload:
        rid = svc.submit(op, A, B, budget_s=budget_s)
        assert not isinstance(rid, dict), f"chaos submit rejected: {rid}"
        ids.append(rid)
    with fault_injection(plan):
        svc.drain()
    return _classify(svc, plan, workload, ids, kind=kind, target=target,
                     mode=mode, op=op, budget_s=budget_s), plan, svc


def _classify(svc, plan, workload, ids, *, kind, target, mode, op,
              budget_s):
    outcomes = {}
    violations = []
    hit_slots = None
    if target == "compute" and mode == "oneshot" and plan.log:
        b = svc.results[ids[0]]["bucket"]        # all same bucket
        bn, brhs = (int(x) for x in
                    b.split("__b")[1].split("__")[0].split("x"))
        hit_slots = compute_slots(plan, bn, brhs)
    n_ok = 0
    for slot, (rid, (A, B)) in enumerate(zip(ids, workload)):
        doc = svc.results[rid]
        st = doc["status"]
        outcomes[rid] = st
        if st == "ok":
            n_ok += 1
            X = svc.solutions.get(rid)
            if X is None or residual(A, B, X) > doc["tol"]:
                violations.append({"kind": "silent_garbage", "id": rid,
                                   "detail": "ok result fails the trusted "
                                             "recomputed residual"})
        elif st == "failed":
            if doc["certificate"] is None:
                violations.append({"kind": "unstructured", "id": rid,
                                   "detail": "failed without certificate"})
        elif st == "timed_out":
            if not doc["timed_out"]:
                violations.append({"kind": "unstructured", "id": rid,
                                   "detail": "timed_out without flag"})
            cert = doc["certificate"]
            if cert is not None and not cert["timed_out"] \
                    and len(cert["attempts"]) >= len(cert["ladder"]):
                violations.append({
                    "kind": "overrun", "id": rid,
                    "detail": "full ladder ran past an expired deadline"})
        else:
            violations.append({"kind": "unstructured", "id": rid,
                               "detail": f"unexpected status {st!r}"})
        # zero collateral damage: in a one-shot compute cell, a request
        # whose batch slot the fault never touched must end ok
        if hit_slots is not None and slot not in hit_slots and st != "ok":
            violations.append({"kind": "collateral", "id": rid,
                               "detail": f"untouched slot {slot} not ok"})
    if mode == "oneshot" and len(ids) - n_ok > 1:
        violations.append({"kind": "collateral",
                           "detail": f"one-shot fault took down "
                                     f"{len(ids) - n_ok} requests"})
    verdict = "absorbed" if n_ok == len(ids) else \
        ("isolated" if n_ok >= len(ids) - 1 and mode == "oneshot"
         else "surfaced")
    return {"kind": kind, "target": target, "mode": mode, "op": op,
            "requests": len(ids), "ok": n_ok, "fired": plan.fired(),
            "budget_s": budget_s, "outcomes": outcomes,
            "verdict": verdict, "violations": violations}


def run_async_cell(grid, *, kind: str, mode: str, target: str = "compute",
                   op: str | None = None, n: int = 16, nrhs: int = 2,
                   requests: int = 8, call: int = 0, nelem: int = 2,
                   seed: int = 13, budget_s: float | None = None,
                   service_kw: dict | None = None):
    """One async-column cell: the fault lands MID-PIPELINE.

    ``requests`` split into two batches (``max_batch = requests // 2``)
    so that when batch 0's solve output crosses the compute seam
    (call 0), batch 1 is already staged and dispatched behind it on the
    device queue.  The front is built with ``autostart=False`` and the
    whole workload pre-loaded before the worker starts, which fixes
    batch membership -- the cell is deterministic and seeded plans
    replay.  Grading is the sync :func:`_classify` verbatim: a one-shot
    fault in batch 0 must leave every batch-1 (neighbor) request ``ok``
    -- anything else is ``collateral``.  Returns
    ``(cell_doc, plan, front)``."""
    op = op or _OP_FOR_TARGET[target]
    fastpath = target == "compute"
    batch = max(requests // 2, 1)
    svc = make_service(grid, fastpath=fastpath, requests=batch,
                       **(service_kw or {}))
    front = AsyncSolverService(svc, donate=True, autostart=False)
    workload = build_workload(op, n, nrhs, requests, seed)
    plan = FaultPlan(seed=seed, faults=[
        FaultSpec(target, kind, call=call, every=(mode == "persistent"),
                  nelem=nelem)])
    futs = [front.submit(op, A, B, budget_s=budget_s)
            for A, B in workload]
    with fault_injection(plan):
        front.start()
        front.shutdown(drain=True)
    ids = [f.id for f in futs]   # assigned at worker ingest; join'd now
    cell = _classify(svc, plan, workload, ids, kind=kind, target=target,
                     mode=mode, op=op, budget_s=budget_s)
    cell["column"] = "async"
    cell["batches"] = -(-requests // batch)
    for f in futs:
        if not f.done():                        # zero silent drops
            cell["violations"].append(
                {"kind": "silent_drop", "id": f.id,
                 "detail": "future never resolved through drain"})
    return cell, plan, front


def run_async_shutdown_cell(grid, *, n: int = 16, nrhs: int = 2,
                            requests: int = 12, seed: int = 13,
                            service_kw: dict | None = None):
    """``shutdown(drain=False)`` under load: structured flush, no drops.

    Three batches of work; a gate callback PARKS the worker inside
    batch 0's completion -- at which point batch 1 is already dispatched
    (double buffering stages k+1 before collecting k) and batch 2 still
    queued -- then hard-stops.  Deterministic pins: batch 0 and the
    in-flight batch 1 complete ``ok``; batch 2 flushes with structured
    ``serve_reject/v1`` ``reason="shutdown"`` rejects; every future
    resolves (zero silent drops); a post-shutdown submit rejects
    immediately.  Returns ``(cell_doc, front)``."""
    import threading
    batch = max(requests // 3, 1)
    svc = make_service(grid, fastpath=True, requests=batch,
                       **(service_kw or {}))
    front = AsyncSolverService(svc, donate=True, autostart=False)
    workload = build_workload("hpd", n, nrhs, requests, seed)
    futs = [front.submit("hpd", A, B) for A, B in workload]
    parked, go = threading.Event(), threading.Event()

    def _gate(_fut):                    # fires on the worker thread
        parked.set()
        go.wait(timeout=120.0)

    futs[0].add_done_callback(_gate)
    front.start()
    assert parked.wait(timeout=120.0), "worker never reached batch 0"
    # worker is parked mid-completion of batch 0; batch 1 is on device.
    # Flip the stop flags BEFORE releasing it so the very next loop
    # iteration takes the emergency-stop path (GIL makes the writes
    # visible); shutdown() is idempotent and just joins.
    front._stop, front._drain = True, False
    go.set()
    front.shutdown(drain=False)
    violations = []
    outcomes = {}
    n_ok = n_flush = 0
    for f, (A, B) in zip(futs, workload):
        if not f.done():
            violations.append({"kind": "silent_drop", "id": f.id,
                               "detail": "future unresolved after "
                                         "shutdown(drain=False)"})
            outcomes[f.id] = "dropped"
            continue
        X, doc = f.result(timeout=0)
        if doc.get("schema") == REJECT_SCHEMA:
            outcomes[f.id] = f"reject:{doc.get('reason')}"
            if doc.get("reason") != "shutdown":
                violations.append({"kind": "unstructured", "id": f.id,
                                   "detail": f"flushed with reason "
                                             f"{doc.get('reason')!r}"})
            else:
                n_flush += 1
        elif doc.get("status") == "ok":
            outcomes[f.id] = "ok"
            n_ok += 1
            if X is None or residual(A, B, X) > doc["tol"]:
                violations.append({"kind": "silent_garbage", "id": f.id,
                                   "detail": "ok result fails the "
                                             "trusted residual"})
        else:
            outcomes[f.id] = doc.get("status", "?")
            violations.append({"kind": "unstructured", "id": f.id,
                               "detail": "neither ok nor a shutdown "
                                         "reject under hard stop"})
    if not violations and n_ok + n_flush != requests:
        violations.append({"kind": "unstructured",
                           "detail": "outcome ledger does not cover "
                                     "the workload"})
    if n_flush == 0:
        violations.append({"kind": "vacuous",
                           "detail": "hard stop flushed nothing -- the "
                                     "under-load pin is unexercised"})
    late = front.submit("hpd", *workload[0])
    _, late_doc = late.result(timeout=5.0)
    if late_doc.get("schema") != REJECT_SCHEMA \
            or late_doc.get("reason") != "shutdown":
        violations.append({"kind": "unstructured",
                           "detail": "post-shutdown submit not rejected "
                                     "with reason='shutdown'"})
    return {"kind": "shutdown", "target": "pipeline",
            "mode": "drain_false", "op": "hpd", "column": "async",
            "requests": requests, "ok": n_ok, "flushed": n_flush,
            "fired": 0, "budget_s": None, "outcomes": outcomes,
            "verdict": "isolated" if not violations else "surfaced",
            "violations": violations}, front


#: the qr column's detection contract (ISSUE 11 -> ISSUE 15): the cells
#: run ``qr(..., abft=True)``, so ALL THREE kinds gate -- 'nan' and
#: 'scale' were already caught by the health parity, and 'bitflip' (the
#: former documented gap: a shrinking exponent-bit flip sits below the
#: growth threshold) is now caught by the Huang-Abraham column-sum
#: checks, exactly as for lu / cholesky.  A silent undetected corruption
#: for ANY kind is a matrix violation.
QR_DETECTED_KINDS = ("bitflip", "scale", "nan")


def run_qr_cell(grid, *, kind: str, target: str, n: int = 16,
                nb: int = 8, call: int = 0, nelem: int = 2,
                seed: int = 13):
    """One qr-column cell: ``qr(..., abft=True, health=True)`` under a
    one-shot fault, classified against a clean reference run.

    qr has no serve admission path (the service solves 'lu'/'hpd'), so
    the column runs the driver directly.  With ABFT guarding (ISSUE 15)
    the EXPECTED verdict for every one-shot cell is ``absorbed``: the
    checksum checks detect the corrupted panel, the transaction layer
    re-executes it (``recompute_count == 1``), and the committed factor
    is bit-identical to the clean run -- graded against a clean
    ``64*n*eps``-class factorization residual besides the bitwise
    comparison.  ``surfaced`` (corrupted but flagged through abft /
    health) stays structured; ``undetected`` is a violation for every
    kind in :data:`QR_DETECTED_KINDS` (all three, since ISSUE 15); a
    landed fault that is neither recovered nor surfaced -- or a recovery
    that costs more than the one corrupted panel -- is an
    ``unrecovered`` violation.  Returns ``(cell, plan)``."""
    import jax
    import elemental_tpu as el
    from ..core.distmatrix import to_global
    from ..resilience.abft import last_abft_report
    from ..resilience.health import HealthMonitor

    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    eps = float(np.finfo(dtype).eps)
    rng = np.random.default_rng(seed)
    An = rng.normal(size=(n, n)).astype(dtype)
    clean = np.asarray(to_global(
        el.qr(el.from_global(An, el.MC, el.MR, grid=grid), nb=nb)[0]))
    plan = FaultPlan(seed=seed, faults=[
        FaultSpec(target, kind, call=call, nelem=nelem)])
    mon = HealthMonitor()
    with fault_injection(plan):
        out = el.qr(el.from_global(An, el.MC, el.MR, grid=grid), nb=nb,
                    abft=True, health=mon)
    rep = mon.report()
    arep = last_abft_report("qr")
    got = np.asarray(to_global(out[0]))
    with np.errstate(over="ignore", invalid="ignore"):
        same = bool(np.allclose(got, clean, rtol=1e-6, atol=1e-9,
                                equal_nan=False))
    # trusted recomputed factorization residual ||A - Q R|| / ||A||
    Qg = np.asarray(to_global(el.explicit_q(out[0], out[1])))
    with np.errstate(over="ignore", invalid="ignore"):
        residual = float(np.linalg.norm(An - Qg @ np.triu(got))
                         / np.linalg.norm(An))
    res_ok = bool(np.isfinite(residual) and residual <= 64.0 * n * eps)
    detected = (rep["ok"] is False) or (arep["ok"] is False) \
        or bool(arep["violations"])
    verdict = "absorbed" if same and res_ok else \
        ("surfaced" if detected else "undetected")
    violations = []
    if plan.fired() == 0:
        violations.append({"kind": "vacuous",
                           "detail": "fault never landed"})
    if verdict == "undetected" and kind in QR_DETECTED_KINDS:
        violations.append({"kind": "silent_garbage",
                           "detail": f"qr {kind} corruption unflagged by "
                                     f"abft/health"})
    if plan.fired() and (verdict != "absorbed"
                         or arep["recompute_count"] != 1):
        violations.append(
            {"kind": "unrecovered",
             "detail": f"qr {kind}/{target} one-shot: verdict={verdict}, "
                       f"recompute_count={arep['recompute_count']} "
                       "(want absorbed at exactly one panel)"})
    return {"kind": kind, "target": target, "mode": "oneshot",
            "op": "qr", "requests": 1, "ok": int(same),
            "fired": plan.fired(), "budget_s": None,
            "outcomes": {"qr": verdict}, "verdict": verdict,
            "health_flags": [f["kind"] for f in rep["flags"]],
            "abft": {"ok": arep["ok"],
                     "violations": len(arep["violations"]),
                     "recompute_count": arep["recompute_count"],
                     "recovered_panels": arep["recovered_panels"]},
            "residual": residual,
            "violations": violations}, plan


def chaos_matrix(grid, *, kinds=FAULT_KINDS, targets=CHAOS_TARGETS,
                 modes=CHAOS_MODES, seed: int = 13, n: int = 16,
                 requests: int = 4, qr_column: bool = True,
                 async_column: bool = True, **kw):
    """The full acceptance matrix -> ``chaos_report/v1``.

    ``qr_column=True`` (default) appends the qr op column (ISSUE 11,
    abft-guarded since ISSUE 15): one :func:`run_qr_cell` per
    (kind, target), all kinds gated (:data:`QR_DETECTED_KINDS`).

    ``async_column=True`` (default) appends the ISSUE-14 async column:
    one mid-pipeline :func:`run_async_cell` per (kind, mode) on the
    compute seam, plus one :func:`run_async_shutdown_cell`."""
    cells = []
    nviol = 0
    vacuous = 0
    for target in targets:
        for kind in kinds:
            for mode in modes:
                cell, plan, _ = run_cell(
                    grid, kind=kind, target=target, mode=mode, seed=seed,
                    n=n, requests=requests,
                    call=2 if target == "redistribute" else 0, **kw)
                if cell["fired"] == 0:
                    vacuous += 1
                    cell["violations"].append(
                        {"kind": "vacuous",
                         "detail": "fault never landed"})
                nviol += len(cell["violations"])
                cells.append(cell)
    if qr_column:
        for target in targets:
            for kind in kinds:
                cell, _ = run_qr_cell(grid, kind=kind, target=target,
                                      seed=seed)
                if cell["fired"] == 0:
                    vacuous += 1
                nviol += len(cell["violations"])
                cells.append(cell)
    if async_column:
        for kind in kinds:
            for mode in modes:
                cell, _, _ = run_async_cell(
                    grid, kind=kind, mode=mode, seed=seed, n=n,
                    requests=2 * requests, **kw)
                if cell["fired"] == 0:
                    vacuous += 1
                    cell["violations"].append(
                        {"kind": "vacuous",
                         "detail": "fault never landed"})
                nviol += len(cell["violations"])
                cells.append(cell)
        cell, _ = run_async_shutdown_cell(grid, n=n, seed=seed,
                                          requests=3 * requests)
        nviol += len(cell["violations"])
        cells.append(cell)
    return {"schema": CHAOS_SCHEMA, "grid": [grid.height, grid.width],
            "seed": seed, "cells": cells, "violations_total": nviol,
            "vacuous_cells": vacuous, "ok": nviol == 0}


# ---- fleet column (ISSUE 19) ----------------------------------------

class _ChaosClock:
    """Manually advanced virtual clock for deterministic fleet cells."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t

    def sleep(self, dt: float) -> None:
        self.advance(dt)


class _TimedExecutor:
    """Saturation-cell executor shim: the member's REAL executor runs
    the batch, then the shared virtual clock jumps ``batch_s`` and the
    batch reports that duration -- so EWMA estimates, deadlines, and
    latency ledgers all see one consistent simulated timeline regardless
    of host speed.  Only ``run`` is shimmed: the sync service touches
    nothing else on the executor."""

    def __init__(self, inner, clock: _ChaosClock, batch_s: float):
        self._inner = inner
        self._clock = clock
        self.batch_s = float(batch_s)

    def run(self, bucket, reqs):
        xs, _ = self._inner.run(bucket, reqs)
        self._clock.advance(self.batch_s)
        return xs, self.batch_s

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _PoisonedExecutor:
    """Grid-loss-cell executor shim: every batch (and every bisect
    re-execution) on the poisoned member returns corrupted solutions, so
    certification fails persistently and the member's breaker trips --
    the 'grid died' stand-in.  Escalation bypasses the executor (the
    distributed certified path), so poisoned requests still end ok."""

    def __init__(self, inner):
        self._inner = inner

    def run(self, bucket, reqs):
        xs, seconds = self._inner.run(bucket, reqs)
        return [np.asarray(x) + 1.0e3 for x in xs], seconds

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _fleet_grade(futs, workload, violations, outcomes):
    """Shared fleet-cell grading: every future resolved, every outcome
    structured (ok with a passing trusted residual, a known reject
    reason, or a flagged failure/timeout).  Returns (n_ok, sheds)."""
    n_ok = 0
    sheds = []
    for i, (f, (A, B)) in enumerate(zip(futs, workload)):
        if not f.done():
            violations.append({"kind": "silent_drop", "id": i,
                               "detail": "future unresolved after drain"})
            outcomes[i] = "dropped"
            continue
        X, doc = f.result(timeout=0)
        if doc.get("schema") == REJECT_SCHEMA:
            reason = doc.get("reason")
            outcomes[i] = f"reject:{reason}:{doc.get('grid')}"
            if reason not in REJECT_REASONS:
                violations.append({"kind": "unstructured", "id": i,
                                   "detail": f"unknown reject {reason!r}"})
            sheds.append(doc)
        elif doc.get("status") == "ok":
            outcomes[i] = f"ok:{doc.get('grid')}:{doc.get('path')}"
            n_ok += 1
            if X is None or residual(A, B, X) > doc["tol"]:
                violations.append({"kind": "silent_garbage", "id": i,
                                   "detail": "ok result fails the trusted "
                                             "recomputed residual"})
        elif doc.get("status") in ("failed", "timed_out"):
            outcomes[i] = f"{doc['status']}:{doc.get('grid')}"
        else:
            outcomes[i] = str(doc.get("status"))
            violations.append({"kind": "unstructured", "id": i,
                               "detail": f"unexpected outcome {doc!r}"})
    return n_ok, sheds


def run_fleet_saturation_cell(*, grids: int = 2, n: int = 16,
                              nrhs: int = 2, max_batch: int = 4,
                              batch_s: float = 1.0,
                              budget_s: float | None = None,
                              light: int = 4, overload=(16, 32),
                              seed: int = 13):
    """Fleet saturation: overload sheds STRUCTURED, latency stays flat.

    A ``grids``-member sync fleet under a virtual clock: every executed
    batch advances injected time by ``batch_s`` and each member's EWMA
    is pre-seeded to it, so admission's wait estimates are exact.  One
    light wave (no shedding expected) then rising overload waves, each
    request carrying ``budget_s`` (default ``2.5 x batch_s``).  Pins:

      * every future resolves (zero silent drops);
      * at overload, sheds happen, every one a known structured reason
        CARRYING THE GRID ID that made the call;
      * the light wave sheds nothing (no vacuous over-shedding);
      * p99 virtual latency of ``ok`` requests stays within ``budget_s
        + 2 x batch_s`` at EVERY wave -- overload sheds instead of
        stretching admitted tails.

    Returns ``(cell_doc, fleet)``."""
    from .fleet import SolverFleet
    if budget_s is None:
        budget_s = 2.5 * batch_s
    clock = _ChaosClock()
    fleet = SolverFleet(grids=grids, pipelined=False, max_batch=max_batch,
                        shed=True, breaker_threshold=99, retries=0,
                        backoff_base_s=0.0, clock=clock,
                        sleep=clock.sleep)
    bucket = None
    for svc in fleet.services:
        svc.executor = _TimedExecutor(svc.executor, clock, batch_s)
    violations: list = []
    outcomes: dict = {}
    waves = []
    total_sheds = 0
    for wi, count in enumerate([light, *overload]):
        workload = build_workload("hpd", n, nrhs, count, seed + wi)
        futs = []
        for i, (A, B) in enumerate(workload):
            futs.append(fleet.submit("hpd", A, B, budget_s=budget_s,
                                     tenant=f"t{i % 2}"))
            if bucket is None and futs[-1].done() is False:
                # seed every member's EWMA at the simulated batch rate
                # (first wave, first admitted request fixes the bucket)
                from .admission import make_bucket
                bucket = make_bucket("hpd", n, nrhs, A.dtype)
                for svc in fleet.services:
                    svc.admission.observe_batch(bucket, batch_s)
        fleet.drain()
        wave_viol: list = []
        wave_out: dict = {}
        n_ok, sheds = _fleet_grade(futs, workload, wave_viol, wave_out)
        for doc in sheds:
            if doc.get("grid") is None:
                wave_viol.append({
                    "kind": "unstructured",
                    "detail": f"shed {doc.get('reason')!r} without a "
                              f"grid attribution"})
        lat = sorted(f.result(0)[1]["latency_s"] for f in futs
                     if f.done() and f.result(0)[1].get("status") == "ok")
        p99 = lat[max(int(0.99 * len(lat)) - 1, 0)] if lat else 0.0
        bound = budget_s + 2.0 * batch_s
        if p99 > bound:
            wave_viol.append({
                "kind": "unstructured",
                "detail": f"wave {count}: admitted p99 {p99:.3g}s exceeds "
                          f"{bound:.3g}s -- overload stretched the tail "
                          f"instead of shedding"})
        if wi == 0 and sheds:
            wave_viol.append({"kind": "vacuous",
                              "detail": "light wave shed load"})
        waves.append({"requests": count, "ok": n_ok,
                      "sheds": len(sheds), "p99_s": p99})
        total_sheds += len(sheds) if wi > 0 else 0
        violations.extend(wave_viol)
        outcomes.update({f"w{wi}:{k}": v for k, v in wave_out.items()})
    if total_sheds == 0:
        violations.append({"kind": "vacuous",
                           "detail": "overload waves never shed -- the "
                                     "saturation pin is unexercised"})
    fleet.shutdown(drain=True)
    return {"kind": "saturation", "target": "fleet", "mode": "overload",
            "op": "hpd", "column": "fleet", "grids": grids,
            "requests": light + sum(overload), "waves": waves,
            "ok": sum(w["ok"] for w in waves), "fired": total_sheds,
            "budget_s": budget_s, "outcomes": outcomes,
            "verdict": "isolated" if not violations else "surfaced",
            "violations": violations}, fleet


def run_fleet_grid_loss_cell(*, n: int = 16, nrhs: int = 2,
                             requests: int = 8, seed: int = 13):
    """Grid loss: one member's breaker opens, traffic re-routes.

    A 2-member sync fleet (``max_batch=2``, ``breaker_threshold=2``)
    whose member ``g0`` gets a poisoned executor: every fast-path batch
    it runs certifies FALSE, so two consecutive batches trip its breaker
    while the poisoned requests recover through the distributed
    escalation path.  The clock is virtual and never advanced, so the
    cooldown never elapses -- ``g0`` stays lost.  Phase A routes
    ``requests`` across both members (backlog-tie alternation) and trips
    ``g0``; phase B submits ``requests`` more and pins that EVERY one is
    served by ``g1`` on the fast path.  Zero silent drops, zero sheds,
    zero silent garbage; deterministic outcome/grid/path ledger (the
    replay oracle runs the cell twice and compares).  Returns
    ``(cell_doc, fleet)``."""
    from .fleet import SolverFleet
    from .policy import OPEN
    clock = _ChaosClock()
    fleet = SolverFleet(grids=2, pipelined=False, max_batch=2,
                        shed=False, breaker_threshold=2, retries=0,
                        backoff_base_s=0.0, breaker_cooldown_s=1.0e9,
                        clock=clock, sleep=clock.sleep)
    fleet.services[0].executor = _PoisonedExecutor(
        fleet.services[0].executor)
    violations: list = []
    outcomes: dict = {}
    workload_a = build_workload("hpd", n, nrhs, requests, seed)
    futs_a = [fleet.submit("hpd", A, B, tenant="a") for A, B in workload_a]
    fleet.drain()
    out_a: dict = {}
    ok_a, sheds_a = _fleet_grade(futs_a, workload_a, violations, out_a)
    outcomes.update({f"a:{k}": v for k, v in out_a.items()})
    if ok_a != requests:
        violations.append({
            "kind": "collateral",
            "detail": f"phase A: {requests - ok_a} poisoned-member "
                      f"requests did not recover via escalation"})
    g0_served = sum(1 for f in futs_a
                    if f.done() and f.result(0)[1].get("grid") == "g0")
    if g0_served == 0:
        violations.append({"kind": "vacuous",
                           "detail": "phase A never touched the poisoned "
                                     "member"})
    br0 = fleet.services[0].breakers
    if not any(b.state == OPEN for b in br0.values()):
        violations.append({"kind": "vacuous",
                           "detail": "poisoned member's breaker never "
                                     "opened -- no grid loss happened"})
    elif fleet.flight.last_dump() is None:
        violations.append({"kind": "unstructured",
                           "detail": "breaker opened but the flight "
                                     "recorder never dumped"})
    workload_b = build_workload("hpd", n, nrhs, requests, seed + 1)
    futs_b = [fleet.submit("hpd", A, B, tenant="b") for A, B in workload_b]
    fleet.drain()
    out_b: dict = {}
    ok_b, sheds_b = _fleet_grade(futs_b, workload_b, violations, out_b)
    outcomes.update({f"b:{k}": v for k, v in out_b.items()})
    for i, f in enumerate(futs_b):
        if not f.done():
            continue
        _, doc = f.result(timeout=0)
        if doc.get("grid") != "g1" or doc.get("path") != "fastpath" \
                or doc.get("status") != "ok":
            violations.append({
                "kind": "collateral", "id": i,
                "detail": f"phase B request not re-routed to the healthy "
                          f"member fast path: grid={doc.get('grid')!r} "
                          f"path={doc.get('path')!r} "
                          f"status={doc.get('status')!r}"})
    if sheds_a or sheds_b:
        violations.append({"kind": "collateral",
                           "detail": "grid loss shed load instead of "
                                     "re-routing it"})
    fleet.shutdown(drain=True)
    return {"kind": "grid_loss", "target": "fleet", "mode": "persistent",
            "op": "hpd", "column": "fleet", "grids": 2,
            "requests": 2 * requests, "ok": ok_a + ok_b, "fired": g0_served,
            "budget_s": None, "outcomes": outcomes,
            # the breaker-open dump (ISSUE 20): the flight recorder's
            # retrospective of everything the fleet did before the trip;
            # deterministic under the virtual clock, so replays compare
            # it bit-for-bit
            "flight": fleet.flight.last_dump(),
            "verdict": "isolated" if not violations else "surfaced",
            "violations": violations}, fleet


def fleet_replay_identical(*, n: int = 16, requests: int = 8,
                           seed: int = 29) -> bool:
    """Run the grid-loss cell twice with the same seed: identical
    outcome/grid/path ledgers and verdicts (the fleet's determinism
    oracle -- sync mode is single-threaded under a virtual clock, so
    routing, breaker trips, and escalations replay bit-identically)."""
    c1, _ = run_fleet_grid_loss_cell(n=n, requests=requests, seed=seed)
    c2, _ = run_fleet_grid_loss_cell(n=n, requests=requests, seed=seed)
    same = [c1["outcomes"][k] for k in sorted(c1["outcomes"])] \
        == [c2["outcomes"][k] for k in sorted(c2["outcomes"])]
    # the breaker-open flight dump must replay BIT-IDENTICALLY (ISSUE
    # 20): the recorder touches only the injected clock and lock-ordered
    # sequence numbers, so the serialized dumps compare byte-for-byte
    same_flight = json.dumps(c1.get("flight"), sort_keys=True) \
        == json.dumps(c2.get("flight"), sort_keys=True)
    return same and same_flight and c1["verdict"] == c2["verdict"] \
        and c1["ok"] == c2["ok"]


def replay_identical(grid, *, kind: str = "bitflip",
                     target: str = "compute", mode: str = "persistent",
                     seed: int = 29, **kw) -> bool:
    """Replay one cell twice with the same seed: bit-identical fault
    logs AND identical per-request outcomes (the determinism oracle the
    breaker/chaos tests build on)."""
    c1, p1, _ = run_cell(grid, kind=kind, target=target, mode=mode,
                         seed=seed, **kw)
    c2, p2, _ = run_cell(grid, kind=kind, target=target, mode=mode,
                         seed=seed, **kw)
    same_outcomes = [c1["outcomes"][k] for k in sorted(c1["outcomes"])] \
        == [c2["outcomes"][k] for k in sorted(c2["outcomes"])]
    return logs_identical(p1, p2) and same_outcomes \
        and c1["verdict"] == c2["verdict"]
