"""Async pipelined front-end: overlapped admission/execution around the
synchronous :class:`~.service.SolverService` core (ISSUE 14 tentpole).

The core stays deliberately synchronous and deterministic; this module
adds exactly one worker thread and a thread-safe submission queue, and
gets its throughput from TWO overlaps the synchronous path cannot have:

  * **depth-k pipelining** -- jax dispatch is asynchronous, so the
    worker STAGES (host pad/stack + executable lookup) and DISPATCHES
    up to ``depth`` batches before collecting the oldest.  The default
    ``depth=2`` is the classic double buffer (bit-identical to the
    ISSUE-14 worker); a fleet member facing a deep submit queue (ISSUE
    19) runs ``depth=3+`` so the device queue never drains even when
    one host-side collect runs long::

        host   : stage k | stage k+1 | collect k | stage k+2 | collect k+1
        device :         |-- solve k --|-- solve k+1 --|-- solve k+2 --|

  * **buffer donation** -- the batch executables are compiled with
    ``donate_argnums=(0, 1)`` (``donate=True``, default), so
    steady-state serving reuses the batch buffers instead of
    allocating.  (On backends where an operand cannot alias the output
    -- the A operand never can -- jax silently keeps a copy; only the
    B operand actually aliases.  Donated operands are DEAD after
    dispatch; the executor drops its references.)  Donation is gated
    to accelerator backends by :func:`donation_safe` -- XLA's CPU
    client corrupts in-flight donated outputs under overlapped
    dispatch (see its docstring), and host memory gains nothing from
    donation anyway.

Completions STREAM: every ``submit`` returns a :class:`ServeFuture`
that resolves (with its unchanged ``serve_result/v1`` doc) the moment
its batch certifies -- not at drain -- via the core's ``on_result``
hook; per-future callbacks fire on the worker thread.

All core state (queues, breakers, results) is touched ONLY by the
worker thread -- ``submit`` just enqueues -- so the core needs no
locks and stays bit-identical to the synchronous path for the same
request set (the bench asserts exactly that).  The price of pipelining
is that admission/breaker decisions for batch k+1 may be made before
batch k's outcome lands; the chaos matrix's async column pins that a
mid-pipeline fault is still isolated to its own batch.

Observability: ``serve_async_submit_queue`` / ``serve_async_inflight``
gauges, per-stage latency histograms (from the executor), and a
``serve_pipeline_occupancy`` gauge (device-busy seconds / worker
wall-clock -- 1.0 means the device never waited on the host).  See
ADVICE.md for how to read them.

Shutdown semantics (both idempotent, both join the worker -- no thread
leaks):

  * ``shutdown(drain=True)`` -- stop accepting, finish EVERYTHING
    queued through the normal pipeline, resolve every future.
  * ``shutdown(drain=False)`` -- emergency stop: the in-flight batch
    (already on device) completes, everything still queued -- ingested
    or not -- resolves with a structured ``serve_reject/v1``
    (``reason='shutdown'``).  Zero silent drops: every future issued
    ever resolves.
"""
from __future__ import annotations

import queue
import threading

from ..obs import metrics as _metrics
from ..obs.lifecycle import RequestTrace
from .admission import Deadline, reject_doc
from .service import SolverService

#: worker idle poll (seconds): how quickly the worker notices new
#: submissions / stop flags when nothing is queued.  Wake-ups are
#: event-driven (a sentinel rides the queue), so this is a backstop.
POLL_S = 0.05


def donation_safe() -> bool:
    """May the PIPELINED front donate batch buffers on this backend?

    XLA's CPU client mis-accounts donated buffers under OVERLAPPED
    async dispatch: with batch k still in flight, its output (aliased
    into a donated operand) can be recycled by a concurrent allocation
    and read back as freed-heap garbage -- observed as rare (~1e-2)
    corrupt solutions in the double-buffered worker, never on the
    serial sync path.  Donation also buys nothing on host memory, so
    the front donates only on accelerator backends; the executor's
    ``donate=`` stays honest for the overlap-free synchronous ``run``."""
    import jax
    return jax.default_backend() != "cpu"


class ServeFuture:
    """One streamed completion: resolves with ``(x, doc)``.

    ``doc`` is the unchanged ``serve_result/v1`` (or ``serve_reject/v1``)
    document; ``x`` is the host float64 solution for ``status='ok'``,
    else None.  Thread-safe; callbacks added after resolution fire
    immediately (on the caller's thread), callbacks added before fire on
    the worker thread as the batch certifies."""

    __slots__ = ("id", "_event", "_doc", "_x", "_callbacks", "_lock")

    def __init__(self):
        self.id: int | None = None       # core request id once admitted
        self._event = threading.Event()
        self._doc = None
        self._x = None
        self._callbacks: list = []
        self._lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Block until resolved; returns ``(x, doc)``.  Raises
        ``TimeoutError`` if ``timeout`` elapses first."""
        if not self._event.wait(timeout):
            raise TimeoutError("ServeFuture not resolved within timeout")
        return self._x, self._doc

    def add_done_callback(self, fn) -> None:
        """``fn(future)`` when resolved (immediately if already done)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # worker-side -----------------------------------------------------
    def _resolve(self, doc, x) -> None:
        with self._lock:
            if self._event.is_set():
                return                   # first resolution wins
            self._doc, self._x = doc, x
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for fn in callbacks:
            try:
                fn(self)
            except Exception:
                _metrics.inc("serve_callback_errors", op="future")


class _Submission:
    """One enqueued submit (plain struct; also the wake-up sentinel when
    ``future is None``)."""

    __slots__ = ("op", "A", "B", "deadline", "future", "tenant", "trace")

    def __init__(self, op=None, A=None, B=None, deadline=None, future=None,
                 tenant=None, trace=None):
        self.op, self.A, self.B = op, A, B
        self.deadline, self.future = deadline, future
        self.tenant = tenant
        self.trace = trace


class AsyncSolverService:
    """See module docstring.  Wraps a fresh :class:`SolverService` built
    from ``**core_kw`` (or the caller's ``service=``); ``donate=``
    controls buffer donation on the batch path.  The worker thread
    starts immediately and is joined by :meth:`shutdown`."""

    def __init__(self, service: SolverService | None = None, *,
                 donate: bool = True, poll_s: float = POLL_S,
                 autostart: bool = True, depth: int = 2, **core_kw):
        if service is None:
            core_kw.setdefault("pipeline_depth", max(int(depth), 1))
            service = SolverService(**core_kw)
        self.service = service
        self.donate = bool(donate) and donation_safe()
        self.poll_s = float(poll_s)
        #: batches kept dispatched before collecting the oldest (ISSUE
        #: 19): 2 = the classic double buffer, k > 2 = deep pipelining
        self.depth = max(int(depth), 1)
        self._qin: queue.Queue = queue.Queue()
        self._futures: dict = {}         # core request id -> ServeFuture
        self._settled: list = []         # worker-appended (id, doc) ledger
        self._stop = False               # accept no new submissions
        self._drain = True               # drain queues on stop?
        self._busy_s = 0.0               # device-busy seconds (collected)
        self._t_start = None             # first-batch worker timestamp
        self._t_last = None
        self._t_ready = None             # previous batch's ready time
        self.service.on_result = self._on_result
        # thread name carries the grid for the per-worker export tracks
        # (ISSUE 20); leak checks match by the shared prefix
        wname = "elemental-serve-worker"
        if self.service.name:
            wname += f":{self.service.name}"
        self._worker = threading.Thread(
            target=self._run, name=wname, daemon=True)
        if autostart:
            self._worker.start()

    def start(self) -> None:
        """Start the worker (no-op if already running).  ``autostart=
        False`` + explicit start lets deterministic harnesses (chaos)
        pre-load the submission queue so batch membership is fixed."""
        if self._worker.ident is None:
            self._worker.start()

    # ---- client side -------------------------------------------------
    def submit(self, op: str, A, B, *, budget_s: float | None = None,
               deadline: Deadline | None = None,
               callback=None, tenant: str | None = None,
               trace: RequestTrace | None = None) -> ServeFuture:
        """Enqueue one request; returns its :class:`ServeFuture`.

        Rejections (load shed, expired deadline, open breaker, bad
        request, shutdown) resolve the future with the structured
        ``serve_reject/v1`` -- nothing raises.  The deadline clock
        starts HERE (submit time), not at worker ingest -- and so does
        the lifecycle timeline: ``submitted`` is stamped on the CALLER's
        thread (a fleet passes its own ``trace``, already stamped)."""
        fut = ServeFuture()
        if callback is not None:
            fut.add_done_callback(callback)
        if deadline is None and budget_s is not None:
            deadline = Deadline(budget_s, clock=self.service.clock)
        if trace is None:
            trace = RequestTrace(clock=self.service.clock, tenant=tenant,
                                 op=op, flight=self.service.flight)
            trace.mark("submitted", op=op)
        if self._stop:
            _metrics.inc("serve_rejects", reason="shutdown")
            fut._resolve(reject_doc("shutdown", deadline=deadline,
                                    grid=self.service.name, tenant=tenant,
                                    detail="async service has shut down",
                                    trace=trace),
                         None)
            return fut
        self._qin.put(_Submission(op, A, B, deadline, fut, tenant, trace))
        _metrics.set_gauge("serve_async_submit_queue", self._qin.qsize())
        return fut

    def shutdown(self, drain: bool = True) -> dict:
        """Stop the service and JOIN the worker (no thread leak).

        ``drain=True`` finishes everything queued through the normal
        pipeline first; ``drain=False`` flushes queued work with
        structured shutdown rejects (the batch already on device still
        completes).  Returns ``{id: doc}`` for every ADMITTED request
        settled by this call; never-admitted submissions still resolve
        their futures with shutdown rejects.  Idempotent."""
        n0 = len(self._settled)
        self._drain = bool(drain)
        self._stop = True
        self._qin.put(_Submission())     # wake the worker
        self.start()                     # autostart=False: drain now
        if self._worker.is_alive():
            self._worker.join()
        done = dict(self._settled[n0:])
        self._gauges(inflight=0)
        return done

    def results(self) -> dict:
        """The core's ``{id: doc}`` ledger (resolved requests only)."""
        return self.service.results

    def pipeline_stats(self) -> dict:
        """Occupancy counters: device-busy seconds over worker
        wall-clock since the first batch (1.0 = device never idle)."""
        wall = 0.0
        if self._t_start is not None and self._t_last is not None:
            wall = max(self._t_last - self._t_start, 0.0)
        occ = self._busy_s / wall if wall > 0 else 0.0
        return {"device_busy_s": self._busy_s, "wall_s": wall,
                "occupancy": occ}

    # ---- worker side -------------------------------------------------
    def _on_result(self, rid: int, doc: dict, x) -> None:
        self._settled.append((rid, doc))
        fut = self._futures.pop(rid, None)
        if fut is not None:
            fut._resolve(doc, x)

    def _gauges(self, inflight: int) -> None:
        _metrics.set_gauge("serve_async_submit_queue", self._qin.qsize())
        _metrics.set_gauge("serve_async_inflight", inflight)
        stats = self.pipeline_stats()
        _metrics.set_gauge("serve_pipeline_occupancy", stats["occupancy"])

    def _ingest(self, block: bool) -> None:
        """Move submissions from the thread-safe queue into the core
        (admission runs HERE, on the worker thread -- the core is
        single-threaded by construction)."""
        svc = self.service
        first = True
        while True:
            try:
                sub = self._qin.get(
                    timeout=self.poll_s if block and first else None) \
                    if block and first else self._qin.get_nowait()
            except queue.Empty:
                return
            first = False
            if sub.future is None:
                continue                 # wake-up sentinel
            if self._stop and not self._drain:
                self._flush_submission(sub)
                continue
            out = svc.submit(sub.op, sub.A, sub.B, deadline=sub.deadline,
                             tenant=sub.tenant, trace=sub.trace)
            if isinstance(out, dict):    # structured fast reject
                sub.future._resolve(out, None)
            else:
                sub.future.id = out
                self._futures[out] = sub.future

    def _flush_submission(self, sub) -> None:
        """Resolve a never-admitted submission with a shutdown reject
        (the drain=False path: zero silent drops)."""
        _metrics.inc("serve_rejects", reason="shutdown")
        sub.future._resolve(
            reject_doc("shutdown", deadline=sub.deadline,
                       grid=self.service.name, tenant=sub.tenant,
                       detail="flushed by shutdown(drain=False)",
                       trace=sub.trace), None)

    def _stage_next(self):
        """Pop + prepare + stage + DISPATCH the next batch (returns the
        in-flight (bucket, staged) pair, or None).  Preparation may
        settle requests inline (drops / escalations / grid routing) --
        those stream immediately and the next queued batch is tried."""
        svc = self.service
        while True:
            popped = svc._pop_batch()
            if popped is None:
                return None
            bucket, batch = popped
            live = svc._prepare_batch(bucket, batch)
            if live:
                break
        staged = svc.executor.stage(bucket, live, donate=self.donate)
        svc.executor.dispatch(staged)
        if self._t_start is None:
            self._t_start = svc.clock()
        return bucket, staged

    def _collect(self, inflight) -> None:
        """Block for the in-flight batch and run the completion leg
        (certify -> breaker -> isolate); futures resolve via
        ``on_result`` inside ``_finalize``."""
        svc = self.service
        bucket, staged = inflight
        t0 = staged.t0
        xs, seconds = svc.executor.collect(staged)
        # dispatch->ready includes time queued BEHIND the previous batch
        # (double buffering enqueues early); device-busy time for the
        # occupancy gauge starts when the device actually picked it up
        ready = t0 + seconds
        start = t0 if self._t_ready is None else max(t0, self._t_ready)
        self._busy_s += max(ready - start, 0.0)
        self._t_ready = ready
        self._t_last = svc.clock()
        svc._complete_batch(bucket, staged.requests, xs, seconds)

    def _run(self) -> None:
        import collections
        svc = self.service
        pipeline: collections.deque = collections.deque()
        while True:
            stopping = self._stop
            self._ingest(block=(not pipeline and not stopping
                                and not svc._queues))
            if self._stop and not self._drain:
                # emergency stop: let the device finish what it holds,
                # flush everything else with structured rejects
                while pipeline:
                    self._collect(pipeline.popleft())
                self._ingest(block=False)
                svc_done = svc.shutdown(drain=False)
                for rid, doc in svc_done.items():
                    self._on_result(rid, doc, None)
                self._gauges(inflight=0)
                return
            # depth-k pipeline: stage + dispatch until ``depth`` batches
            # are in flight BEFORE collecting the oldest -- the device
            # queue serializes them, so the device goes batch to batch
            # while the host stages and collects in its shadow.  depth=2
            # reproduces the ISSUE-14 double buffer event order exactly
            # (stage k+1, collect k, stage k+2, collect k+1, ...).
            while len(pipeline) < self.depth:
                nxt = self._stage_next()
                if nxt is None:
                    break
                pipeline.append(nxt)
            if pipeline:
                self._collect(pipeline.popleft())
            self._gauges(inflight=len(pipeline))
            if not pipeline and not svc._queues \
                    and self._qin.empty() and stopping:
                svc.shutdown(drain=True)     # idempotent: marks core
                self._gauges(inflight=0)
                return


def serve_async(requests, *, donate: bool = True,
                **core_kw) -> tuple:
    """One-shot convenience: pump ``(op, A, B)`` triples through a fresh
    async service, wait for every completion, shut down cleanly.
    Returns ``(docs, xs)`` lists in submission order."""
    front = AsyncSolverService(donate=donate, **core_kw)
    futures = [front.submit(op, A, B) for (op, A, B) in requests]
    out = [f.result() for f in futures]
    front.shutdown(drain=True)
    return [doc for _, doc in out], [x for x, _ in out]
