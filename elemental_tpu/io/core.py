"""IO: print, write, read, checkpoint.

Reference: Elemental ``src/io/`` -- ``Print.cpp`` (``El::Print``),
``Write.cpp``/``Read.cpp`` (ASCII / BINARY / BINARY_FLAT / MATRIX_MARKET
formats), with distributed IO funneled through a ``[CIRC,CIRC]`` gather.

TPU-native shape: ``to_global`` is the ``[CIRC,CIRC]`` analog (the storage
array's index-permutation inverse); the ``"shards"`` format instead dumps
the stacked-storage array as-is plus its layout metadata -- the
BINARY_FLAT / per-rank-files analog, reloadable into the SAME grid shape
without ever forming the global matrix on one host (at multi-host scale an
orbax-style async checkpointer slots in here).
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax.numpy as jnp

from ..core.dist import Dist, MC, MR
from ..core.distmatrix import DistMatrix, from_global, to_global
from ..core.grid import Grid, default_grid


def print_matrix(A: DistMatrix, title: str = "", stream=None,
                 precision: int = 6):
    """Formatted print of the global matrix (``El::Print``; gathers through
    the [CIRC,CIRC]-analog bridge)."""
    import sys
    stream = stream or sys.stdout
    arr = np.asarray(to_global(A))
    if title:
        stream.write(f"{title}\n")
    with np.printoptions(precision=precision, suppress=False,
                         linewidth=120, threshold=10000):
        stream.write(f"{arr}\n")


def write_matrix(A: DistMatrix, path: str, format: str = "npy") -> None:
    """Write a DistMatrix (``El::Write``).

    ``format``:
      * 'npy'    -- the GLOBAL matrix as a standard .npy (BINARY analog;
        gathers to host -- interoperable, not for at-scale operands).
      * 'shards' -- the stacked-storage array + layout metadata as
        ``<path>.npz`` (BINARY_FLAT analog; no global gather, reload
        requires an identical grid shape).
    """
    if format == "npy":
        np.save(path, np.asarray(to_global(A)))
        return
    if format == "shards":
        meta = dict(gshape=list(A.gshape), cdist=A.cdist.value,
                    rdist=A.rdist.value, calign=A.calign, ralign=A.ralign,
                    grid=[A.grid.height, A.grid.width])
        np.savez(path, storage=np.asarray(A.local),
                 meta=json.dumps(meta))
        return
    raise ValueError(f"unknown format {format!r}")


def read_matrix(path: str, cdist: Dist = MC, rdist: Dist = MR,
                grid: Grid | None = None) -> DistMatrix:
    """Read a matrix written by :func:`write_matrix` (``El::Read``)."""
    grid = grid or default_grid()
    if path.endswith(".npz") or os.path.exists(path + ".npz"):
        p = path if path.endswith(".npz") else path + ".npz"
        data = np.load(p, allow_pickle=False)
        meta = json.loads(str(data["meta"]))
        if meta["grid"] != [grid.height, grid.width]:
            raise ValueError(
                f"shard checkpoint was written on a {meta['grid']} grid; "
                f"reload on {[grid.height, grid.width]} requires the global "
                "'npy' format (cross-grid TranslateBetweenGrids analog)")
        d = Dist(meta["cdist"]), Dist(meta["rdist"])
        return DistMatrix(jnp.asarray(data["storage"]),
                          tuple(meta["gshape"]), d[0], d[1],
                          meta["calign"], meta["ralign"], grid)
    p = path if path.endswith(".npy") else path + ".npy"
    return from_global(np.load(p), cdist, rdist, grid=grid)


def checkpoint(path: str, **named: DistMatrix) -> None:
    """Write a named set of DistMatrices as shard files under ``path``
    (SURVEY.md §6.4 checkpoint/resume building block)."""
    os.makedirs(path, exist_ok=True)
    for name, A in named.items():
        write_matrix(A, os.path.join(path, name), format="shards")


def restore(path: str, names, grid: Grid | None = None) -> dict:
    """Reload a :func:`checkpoint` directory; returns {name: DistMatrix}."""
    return {name: read_matrix(os.path.join(path, name), grid=grid)
            for name in names}


# ---------------------------------------------------------------------
# Matrix Market + Display/Spy (SURVEY.md §3.5 IO row completion)
# ---------------------------------------------------------------------

def _mm_body(*columns) -> str:
    """Bulk-format numeric columns into MatrixMarket body lines.

    numpy C-level string ops (``np.char.mod`` per column + joins) instead
    of a per-entry Python format loop -- the body of an m x n dense write
    is O(mn) work either way, but this keeps it out of the interpreter
    (~30x on the 1e6-entry matrices this library considers small)."""
    parts = [np.char.mod("%d", col) if np.issubdtype(col.dtype, np.integer)
             else np.char.mod("%.17g", col) for col in columns]
    out = parts[0]
    for p in parts[1:]:
        out = np.char.add(np.char.add(out, " "), p)
    return "\n".join(out)


def write_matrix_market(A, path: str, comment: str = "") -> None:
    """Write to MatrixMarket format (``El::Write`` MATRIX_MARKET): dense
    DistMatrix -> 'array' format; DistSparseMatrix -> 'coordinate'.
    Bodies are numpy-bulk-formatted (:func:`_mm_body`), no per-entry
    Python loop."""
    from ..sparse.core import DistSparseMatrix
    import numpy as np
    if isinstance(A, DistSparseMatrix):
        from ..sparse.core import sparse_to_coo
        rows, cols, vals = sparse_to_coo(A)
        rows = np.asarray(rows, np.int64) + 1
        cols = np.asarray(cols, np.int64) + 1
        vals = np.asarray(vals)
        m, n = A.gshape
        cplx = np.iscomplexobj(vals)
        field = "complex" if cplx else "real"
        with open(path, "w") as f:
            f.write(f"%%MatrixMarket matrix coordinate {field} general\n")
            if comment:
                f.write(f"% {comment}\n")
            f.write(f"{m} {n} {len(vals)}\n")
            if len(vals):
                body = _mm_body(rows, cols, vals.real, vals.imag) if cplx \
                    else _mm_body(rows, cols, vals)
                f.write(body + "\n")
        return
    arr = np.asarray(to_global(A))
    m, n = arr.shape
    cplx = np.iscomplexobj(arr)
    field = "complex" if cplx else "real"
    flat = arr.flatten(order="F")        # column-major per the MM spec
    with open(path, "w") as f:
        f.write(f"%%MatrixMarket matrix array {field} general\n")
        if comment:
            f.write(f"% {comment}\n")
        f.write(f"{m} {n}\n")
        if flat.size:
            body = _mm_body(flat.real, flat.imag) if cplx else _mm_body(flat)
            f.write(body + "\n")


def read_matrix_market(path: str, grid: Grid | None = None, sparse=None):
    """Read MatrixMarket (``El::Read`` MATRIX_MARKET): 'array' ->
    DistMatrix [MC,MR]; 'coordinate' -> DistSparseMatrix (or a dense
    DistMatrix when ``sparse=False``).  Symmetric/hermitian/skew files
    are expanded to general storage."""
    import numpy as np
    with open(path) as f:
        header = f.readline().strip().lower().split()
        if len(header) < 5 or header[0] != "%%matrixmarket":
            raise ValueError(f"not a MatrixMarket file: {path}")
        _, obj, fmt, field, symm = header[:5]
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        dims = line.split()
        if fmt == "coordinate":
            m, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
            # bulk parse: one read + one numpy conversion for all triplets
            # (loadtxt-style; no per-line Python loop)
            ncol = {"pattern": 2, "complex": 4}.get(field, 3)
            toks = np.array(f.read().split(), dtype=np.str_)
            if toks.size < nnz * ncol:
                raise ValueError(
                    f"truncated MatrixMarket body: {toks.size} tokens for "
                    f"{nnz} x {ncol} entries in {path}")
            data = toks[: nnz * ncol].reshape(nnz, ncol)
            rows = data[:, 0].astype(np.int64) - 1
            cols = data[:, 1].astype(np.int64) - 1
            if field == "pattern":
                vals = np.ones(nnz, np.float64)
            elif field == "complex":
                vals = data[:, 2].astype(np.float64) \
                    + 1j * data[:, 3].astype(np.float64)
            else:
                vals = data[:, 2].astype(np.float64)
            if symm in ("symmetric", "hermitian", "skew-symmetric"):
                off = rows != cols
                r2, c2, v2 = cols[off], rows[off], vals[off]
                if symm == "hermitian":
                    v2 = np.conj(v2)
                elif symm == "skew-symmetric":
                    v2 = -v2
                rows = np.concatenate([rows, r2])
                cols = np.concatenate([cols, c2])
                vals = np.concatenate([vals, v2])
            from ..sparse.core import dist_sparse_from_coo
            if sparse is False:
                dense = np.zeros((m, n), vals.dtype)
                np.add.at(dense, (rows, cols), vals)
                return from_global(dense, MC, MR, grid=grid)
            return dist_sparse_from_coo(rows, cols, vals, m, n, grid=grid)
        m, n = int(dims[0]), int(dims[1])
        data = np.array(f.read().split(), np.float64)
        if field == "complex":
            data = data[0::2] + 1j * data[1::2]
        if symm in ("symmetric", "hermitian", "skew-symmetric"):
            # packed lower triangle, column-major; skew files omit the
            # (zero) diagonal, storing only the strictly-lower part
            skew = symm == "skew-symmetric"
            arr = np.zeros((m, n), data.dtype)
            at = 0
            for j in range(n):
                lo = j + 1 if skew else j
                cnt = m - lo
                arr[lo:, j] = data[at:at + cnt]
                at += cnt
            up = arr.T.copy()
            if symm == "hermitian":
                up = up.conj()
            elif skew:
                up = -up
            arr = arr + up - np.diag(np.diag(arr))
        else:
            arr = data[: m * n].reshape((n, m)).T    # column-major
        return from_global(arr, MC, MR, grid=grid)


def display(A, title: str = "", path: str | None = None, cmap="viridis"):
    """Heat-map dump of |A| (``El::Display``; matplotlib instead of Qt5 --
    SURVEY.md §3.7 item 6).  Saves to ``path`` (default: <title>.png)."""
    import numpy as np
    from matplotlib.figure import Figure
    from ..sparse.core import DistSparseMatrix
    if isinstance(A, DistSparseMatrix):
        A = A.to_dense()                # a heat map is dense by nature
    arr = np.asarray(to_global(A))
    fig = Figure(figsize=(6, 5))        # Agg canvas; no global-backend switch
    ax = fig.add_subplot()
    im = ax.imshow(np.abs(arr), aspect="auto", cmap=cmap,
                   interpolation="nearest")
    fig.colorbar(im, ax=ax)
    ax.set_title(title or "DistMatrix")
    out = path or f"{(title or 'matrix').replace(' ', '_')}.png"
    fig.savefig(out, dpi=120, bbox_inches="tight")
    return out


def spy(A, tol: float = 0.0, title: str = "", path: str | None = None):
    """Sparsity portrait (``El::Spy``): marks |A_ij| > tol."""
    import numpy as np
    from matplotlib.figure import Figure
    from ..sparse.core import DistSparseMatrix, sparse_to_coo
    fig = Figure(figsize=(6, 6))        # Agg canvas; no global-backend switch
    ax = fig.add_subplot()
    if isinstance(A, DistSparseMatrix):
        # plot the triplets directly: O(nnz), never a dense m x n mask
        rows, cols, vals = sparse_to_coo(A)
        keep = np.abs(vals) > tol
        m, n = A.gshape
        ax.plot(cols[keep], rows[keep], ".", markersize=2)
        ax.set_xlim(-0.5, n - 0.5)
        ax.set_ylim(m - 0.5, -0.5)
        ax.set_aspect("equal")
        nnz = int(keep.sum())
    else:
        arr = np.asarray(to_global(A))
        mask = np.abs(arr) > tol
        ax.spy(mask, markersize=2)
        nnz = int(mask.sum())
    ax.set_title(title or f"nnz = {nnz}")
    out = path or f"{(title or 'spy').replace(' ', '_')}.png"
    fig.savefig(out, dpi=120, bbox_inches="tight")
    return out
