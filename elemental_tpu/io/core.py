"""IO: print, write, read, checkpoint.

Reference: Elemental ``src/io/`` -- ``Print.cpp`` (``El::Print``),
``Write.cpp``/``Read.cpp`` (ASCII / BINARY / BINARY_FLAT / MATRIX_MARKET
formats), with distributed IO funneled through a ``[CIRC,CIRC]`` gather.

TPU-native shape: ``to_global`` is the ``[CIRC,CIRC]`` analog (the storage
array's index-permutation inverse); the ``"shards"`` format instead dumps
the stacked-storage array as-is plus its layout metadata -- the
BINARY_FLAT / per-rank-files analog, reloadable into the SAME grid shape
without ever forming the global matrix on one host (at multi-host scale an
orbax-style async checkpointer slots in here).
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax.numpy as jnp

from ..core.dist import Dist, MC, MR
from ..core.distmatrix import DistMatrix, from_global, to_global
from ..core.grid import Grid, default_grid


def print_matrix(A: DistMatrix, title: str = "", stream=None,
                 precision: int = 6):
    """Formatted print of the global matrix (``El::Print``; gathers through
    the [CIRC,CIRC]-analog bridge)."""
    import sys
    stream = stream or sys.stdout
    arr = np.asarray(to_global(A))
    if title:
        stream.write(f"{title}\n")
    with np.printoptions(precision=precision, suppress=False,
                         linewidth=120, threshold=10000):
        stream.write(f"{arr}\n")


def write_matrix(A: DistMatrix, path: str, format: str = "npy") -> None:
    """Write a DistMatrix (``El::Write``).

    ``format``:
      * 'npy'    -- the GLOBAL matrix as a standard .npy (BINARY analog;
        gathers to host -- interoperable, not for at-scale operands).
      * 'shards' -- the stacked-storage array + layout metadata as
        ``<path>.npz`` (BINARY_FLAT analog; no global gather, reload
        requires an identical grid shape).
    """
    if format == "npy":
        np.save(path, np.asarray(to_global(A)))
        return
    if format == "shards":
        meta = dict(gshape=list(A.gshape), cdist=A.cdist.value,
                    rdist=A.rdist.value, calign=A.calign, ralign=A.ralign,
                    grid=[A.grid.height, A.grid.width])
        np.savez(path, storage=np.asarray(A.local),
                 meta=json.dumps(meta))
        return
    raise ValueError(f"unknown format {format!r}")


def read_matrix(path: str, cdist: Dist = MC, rdist: Dist = MR,
                grid: Grid | None = None) -> DistMatrix:
    """Read a matrix written by :func:`write_matrix` (``El::Read``)."""
    grid = grid or default_grid()
    if path.endswith(".npz") or os.path.exists(path + ".npz"):
        p = path if path.endswith(".npz") else path + ".npz"
        data = np.load(p, allow_pickle=False)
        meta = json.loads(str(data["meta"]))
        if meta["grid"] != [grid.height, grid.width]:
            raise ValueError(
                f"shard checkpoint was written on a {meta['grid']} grid; "
                f"reload on {[grid.height, grid.width]} requires the global "
                "'npy' format (cross-grid TranslateBetweenGrids analog)")
        d = Dist(meta["cdist"]), Dist(meta["rdist"])
        return DistMatrix(jnp.asarray(data["storage"]),
                          tuple(meta["gshape"]), d[0], d[1],
                          meta["calign"], meta["ralign"], grid)
    p = path if path.endswith(".npy") else path + ".npy"
    return from_global(np.load(p), cdist, rdist, grid=grid)


def checkpoint(path: str, **named: DistMatrix) -> None:
    """Write a named set of DistMatrices as shard files under ``path``
    (SURVEY.md §6.4 checkpoint/resume building block)."""
    os.makedirs(path, exist_ok=True)
    for name, A in named.items():
        write_matrix(A, os.path.join(path, name), format="shards")


def restore(path: str, names, grid: Grid | None = None) -> dict:
    """Reload a :func:`checkpoint` directory; returns {name: DistMatrix}."""
    return {name: read_matrix(os.path.join(path, name), grid=grid)
            for name in names}
