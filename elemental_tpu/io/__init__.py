"""IO layer (SURVEY.md §3.5): print/write/read/checkpoint.

Reference: Elemental ``src/io/``.
"""
from .core import (print_matrix, write_matrix, read_matrix, checkpoint,
                   restore, write_matrix_market, read_matrix_market,
                   display, spy)
