"""Interior (arbitrary-offset) submatrix extraction and embedding.

The reference reads/writes arbitrary interior submatrices through FLAME
views plus alignment-shifted redistributions (Elemental
``include/El/core/View.hpp`` views carry nonzero alignments;
``copy::ColAlign``-style shifts re-land them).  Our storage views
(:mod:`..core.view`) are pure-local but require stride-grain offsets; this
module supplies the general case as a standalone op:

  * :func:`interior_view`   -- ``B = A[s:e, s2:e2]`` as a NEW zero-aligned
    DistMatrix with the same distribution pair.
  * :func:`interior_update` -- functionally write ``B`` into ``A`` at an
    arbitrary ``(i0, j0)`` offset.

TPU-native cost model: a global range whose start ``s`` is NOT a stride
multiple shifts every row's owner by the fixed rotation ``s mod S`` -- so
the whole move is ONE ``lax.ppermute`` rotation per distributed dim plus a
per-device static local slice (no all-to-all, no replication).  This is the
communication-optimal analog of the reference's aligned-copy kernels and
the tool that lets divide-and-conquer algorithms (QDWH-eig, Schur-SDC)
split at data-dependent spectral boundaries.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core import indexing as ix
from ..core.compat import shard_map
from ..core.dist import Dist, MC, MR, VC, VR, stride as dist_stride, rank_of
from ..core.distmatrix import DistMatrix


def _pad_dim(x, dim: int, target: int):
    cur = x.shape[dim]
    if cur >= target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[dim] = (0, target - cur)
    return jnp.pad(x, pads)


def _rot_perm(d: Dist, delta: int, r: int, c: int):
    """(axes, perm) rotating rank space by ``delta``: rank q receives from
    rank (q + delta) % S.  ppermute's multi-axis linear id follows MESH order
    (mc major; verified empirically -- the tuple order given is ignored), so
    VC's column-major rank is translated to device ids explicitly."""
    if d is MC:
        S = r
        return "mc", [((q + delta) % S, q) for q in range(S)]
    if d is MR:
        S = c
        return "mr", [((q + delta) % S, q) for q in range(S)]
    p = r * c
    if d is VC:
        lin = [(v % r) * c + v // r for v in range(p)]   # device id of VC rank v
    elif d is VR:
        lin = list(range(p))                             # VR rank == device id
    else:
        raise ValueError(f"no permute axes for {d}")
    return ("mc", "mr"), [(lin[(q + delta) % p], lin[q]) for q in range(p)]


def _extract_dim(x, dim: int, d: Dist, s: int, e: int, r: int, c: int):
    """One dim of the extract: rows [s, e) -> new zero-aligned dim."""
    S = dist_stride(d, r, c)
    if S == 1:
        return lax.slice_in_dim(x, s, e, axis=dim)
    l_new = ix.max_local_length(e - s, S)
    if s % S:
        axes, perm = _rot_perm(d, s % S, r, c)
        x = lax.ppermute(x, axes, perm)
    x = _pad_dim(x, dim, s // S + 1 + l_new)
    q = rank_of(d, r, c)
    o = (q + s) // S
    y = lax.dynamic_slice_in_dim(x, o, l_new, axis=dim)
    gi = jnp.arange(l_new) * S + q            # new global index of each slot
    shape = [1] * y.ndim
    shape[dim] = l_new
    return jnp.where((gi < (e - s)).reshape(shape), y, 0)


def _embed_dim(big, small, dim: int, d: Dist, s: int, h: int, r: int, c: int):
    """One dim of the embed: write ``small`` (extent h) at offset ``s``."""
    S = dist_stride(d, r, c)
    if S == 1:
        return lax.dynamic_update_slice_in_dim(big, small, s, axis=dim)
    l_small = small.shape[dim]
    if s % S:
        axes, perm = _rot_perm(d, -(s % S) % S, r, c)
        small = lax.ppermute(small, axes, perm)
    q = rank_of(d, r, c)
    qB = (q - s) % S                          # source rank of the held block
    o = (qB + s) // S
    gj = jnp.arange(l_small) * S + qB         # source global index per slot
    shape = [1] * small.ndim
    shape[dim] = l_small
    valid = (gj < h).reshape(shape)
    orig = big.shape[dim]
    big = _pad_dim(big, dim, s // S + 1 + l_small)
    seg = lax.dynamic_slice_in_dim(big, o, l_small, axis=dim)
    seg = jnp.where(valid, small, seg)
    out = lax.dynamic_update_slice_in_dim(big, seg, o, axis=dim)
    if out.shape[dim] != orig:
        out = lax.slice_in_dim(out, 0, orig, axis=dim)
    return out


def _check_zero_aligned(*Ms: DistMatrix):
    for A in Ms:
        if (A.calign, A.ralign) != (0, 0):
            raise ValueError(f"interior ops require zero alignment, got {A}")


@partial(jax.jit, static_argnums=(1, 2))
def interior_view(A: DistMatrix, rows=None, cols=None) -> DistMatrix:
    """``A[rows[0]:rows[1], cols[0]:cols[1]]`` as a new zero-aligned
    DistMatrix (same distribution pair), for ARBITRARY offsets."""
    _check_zero_aligned(A)
    m, n = A.gshape
    rows = (0, m) if rows is None else rows
    cols = (0, n) if cols is None else cols
    (rs, re), (cs, ce) = rows, cols
    if not (0 <= rs <= re <= m and 0 <= cs <= ce <= n):
        raise ValueError(f"range ({rows},{cols}) out of bounds for {A.gshape}")
    g = A.grid
    r, c = g.height, g.width
    out_meta = DistMatrix(None, (re - rs, ce - cs), A.cdist, A.rdist, 0, 0, g)

    def f(a):
        x = _extract_dim(a.local, 0, a.cdist, rs, re, r, c)
        x = _extract_dim(x, 1, a.rdist, cs, ce, r, c)
        return out_meta.with_local(x)

    return shard_map(f, mesh=g.mesh, in_specs=(A.spec,),
                         out_specs=out_meta.spec, check_vma=False)(A)


@partial(jax.jit, static_argnums=(2,))
def interior_update(A: DistMatrix, B: DistMatrix, at=(0, 0)) -> DistMatrix:
    """Functionally write ``B`` into ``A`` starting at global ``at=(i0,j0)``
    (arbitrary offsets; B must share A's distribution pair and grid)."""
    _check_zero_aligned(A, B)
    if B.dist != A.dist or B.grid != A.grid:
        raise ValueError(f"interior_update needs matching layout: {A} vs {B}")
    i0, j0 = at
    m, n = A.gshape
    h, w = B.gshape
    if i0 + h > m or j0 + w > n:
        raise ValueError(f"block {B.gshape} at {at} exceeds {A.gshape}")
    g = A.grid
    r, c = g.height, g.width

    def f(a, b):
        loc = a.local
        # 1. pull out the column strip [j0, j0+w) of A (full rows, B's cols)
        strip = _extract_dim(loc, 1, a.rdist, j0, j0 + w, r, c)
        # 2. embed B's rows into the strip at row offset i0
        strip = _embed_dim(strip, b.local, 0, a.cdist, i0, h, r, c)
        # 3. write the strip back into A's columns at offset j0
        loc = _embed_dim(loc, strip, 1, a.rdist, j0, w, r, c)
        return a.with_local(loc)

    return shard_map(f, mesh=g.mesh, in_specs=(A.spec, B.spec),
                         out_specs=A.spec, check_vma=False)(A, B)


# ---------------------------------------------------------------------
# stacking helpers (QDWH's [sqrt(c) X; I] and friends)
# ---------------------------------------------------------------------

def _blank(m: int, n: int, like: DistMatrix) -> DistMatrix:
    meta = DistMatrix(None, (m, n), like.cdist, like.rdist, 0, 0, like.grid)
    stor = jnp.zeros((meta.col_stride * meta.local_rows,
                      meta.row_stride * meta.local_cols), like.dtype)
    return meta.with_local(stor)


def vstack(A: DistMatrix, B: DistMatrix) -> DistMatrix:
    """[A; B] (concatenate rows) with A's distribution pair."""
    if A.gshape[1] != B.gshape[1]:
        raise ValueError(f"vstack width mismatch {A.gshape} vs {B.gshape}")
    out = _blank(A.gshape[0] + B.gshape[0], A.gshape[1], A)
    out = interior_update(out, A, (0, 0))
    return interior_update(out, B, (A.gshape[0], 0))


def hstack(A: DistMatrix, B: DistMatrix) -> DistMatrix:
    """[A, B] (concatenate columns) with A's distribution pair."""
    if A.gshape[0] != B.gshape[0]:
        raise ValueError(f"hstack height mismatch {A.gshape} vs {B.gshape}")
    out = _blank(A.gshape[0], A.gshape[1] + B.gshape[1], A)
    out = interior_update(out, A, (0, 0))
    return interior_update(out, B, (0, A.gshape[1]))
