"""One-shot redistribution plan compiler (ISSUE 12 -- the COSTA direction).

COSTA (arXiv 2106.06601) and "Memory-efficient array redistribution
through portable collective communication" (arXiv 2112.01075) observe
that an arbitrary src->dst distribution change factors into exactly one
collective exchange once the shard intersections are computed statically.
This module is that computation, engine-independent and numpy-only:

  ``compile_plan(src, dst, gshape, grid_shape) -> RedistPlan | None``

The compiler works per mesh axis.  Each distribution pins some device
coordinates as a residue function of the global index (MC pins ``mc`` to
``i % r``; MR pins ``mr``; VC/VR pin both through the 1-D rank; STAR pins
nothing).  For every entry a receiver needs under the destination pair
there is a unique *canonical sender*: the device taking the source's
pinned coordinates and copying the receiver's coordinates on the source's
free axes.  An axis carries traffic iff the source pins it AND the
destination's pin is not the identical residue function -- which yields
three plan kinds:

  * ``'local'``    -- no axis carries traffic: pure gather/scatter on-chip
                      (e.g. ``[STAR,STAR] -> [MC,MR]``, ``[MC,*] -> [VC,*]``).
  * ``'ppermute'`` -- every device exchanges its whole slot with exactly
                      one peer: a wholesale relabeling (e.g. ``VC <-> VR``).
  * ``'a2a'``      -- one ``lax.all_to_all`` over exactly the
                      traffic-carrying axes.

Per (sender, receiver) pair the owned-by-src / needed-by-dst index sets
along each dim are congruence intersections ``i = a (mod S_src)`` and
``i = b (mod S_dst)`` -- an arithmetic progression of period
``lcm(S_src, S_dst)`` solved by CRT (or empty, in which case the slot
ships sentinel padding; the byte estimate is honest about that and the
chain-vs-direct arbitration lives with the caller/tuner).  The emitted
index maps are dense ``(p, K, R)``/``(p, K, C)`` int32 tables selected by
device id inside ``shard_map`` -- see ``engine._direct_exec``.

Phase 2 (ISSUE 13) closed the PR-12 restrictions: nonzero alignments
shift the congruence residues (the local index ``i // S`` is
alignment-independent, so only the CRT anchors move), ``[MD,⋆]``
endpoints ride the same per-axis machinery (MD pins BOTH mesh coords --
entry k on device ``(k%r, k%c)`` -- with stride ``lcm(r, c)``; devices
outside the diagonal comm own the empty residue set), and ``[CIRC,CIRC]``
endpoints compile to a costed ``'bridge'`` plan the engine executes on
its eager root path.  ``compile_plan`` returns None only for
``src == dst`` at identical alignments (a true no-op).  Slots are RAGGED:
trailing all-sentinel positions are trimmed per dim, and an a2a whose
traffic graph decomposes into smaller components runs over
``axis_index_groups`` subgroups -- both cut the padded wire bytes the
PR-12 plans shipped for incompatible-residue pairs.
"""
from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np

from ..core import indexing as ix
from ..core.dist import (MC, MR, VC, VR, STAR, MD, CIRC, md_params,
                         stride as dist_stride)

#: mesh axis names in mesh order; linear device id = mc * c + mr
MESH_AXES = ("mc", "mr")

#: mesh axes whose device coordinate each dist pins
_PINS = {MC: ("mc",), MR: ("mr",), VC: ("mc", "mr"), VR: ("mc", "mr"),
         MD: ("mc", "mr"), STAR: ()}


def _pin(d, g: int, r: int, c: int) -> dict:
    """Device coordinates dist ``d`` forces for global index ``g``."""
    if d is MC:
        return {"mc": g % r}
    if d is MR:
        return {"mr": g % c}
    if d is VC:
        q = g % (r * c)
        return {"mc": q % r, "mr": q // r}
    if d is VR:
        q = g % (r * c)
        return {"mc": q // c, "mr": q % c}
    if d is MD:
        return {"mc": g % r, "mr": g % c}
    return {}


def _rank_under(d, mc: int, mr: int, r: int, c: int):
    """The residue a device (mc, mr) owns under dist ``d`` (0 for STAR).

    For MD the residue is k0, the first diagonal entry the device owns
    (mod lcm(r, c)); devices outside the diagonal comm ((mc - mr) not a
    multiple of gcd(r, c)) own the EMPTY residue set -- returned as None,
    which the map-filling loop reads as "skip this (device, slot)"."""
    if d is MC:
        return mc
    if d is MR:
        return mr
    if d is VC:
        return mc + r * mr
    if d is VR:
        return mr + c * mc
    if d is MD:
        g, L, inv = md_params(r, c)
        if (mc - mr) % g:
            return None
        return (mc + r * ((((mr - mc) // g) * inv) % (c // g))) % L
    return 0


def _axis_pinner(pair, axis: str):
    """(dim, dist) of the pair member pinning ``axis``, or None (free)."""
    for dim, d in enumerate(pair):
        if axis in _PINS.get(d, ()):
            return dim, d
    return None


def _lcm(a: int, b: int) -> int:
    return a // math.gcd(a, b) * b


def comm_axes_for(src, dst, r: int, c: int,
                  src_align: tuple = (0, 0), dst_align: tuple = (0, 0)) -> tuple:
    """Mesh axes that carry traffic for ``src -> dst`` on an r x c grid.

    An axis moves data iff the source pins it and the destination does
    not pin it with the identical residue function (same dim, same value
    for every global index over one lcm period).  A dim alignment ``a``
    shifts its residue function by ``a`` (the device owning global ``g``
    is the zero-aligned owner of ``g + a``), so pins are compared at
    ``g + align``.  Size-1 axes never carry traffic.
    """
    sizes = {"mc": r, "mr": c}
    axes = []
    for axis in MESH_AXES:
        if sizes[axis] == 1:
            continue
        sp = _axis_pinner(src, axis)
        if sp is None:
            continue                      # free in src: sender copies q's coord
        dp = _axis_pinner(dst, axis)
        if dp is None or dp[0] != sp[0]:
            axes.append(axis)
            continue
        period = _lcm(dist_stride(sp[1], r, c), dist_stride(dp[1], r, c))
        s_al, d_al = src_align[sp[0]], dst_align[dp[0]]
        if any(_pin(sp[1], g + s_al, r, c)[axis]
               != _pin(dp[1], g + d_al, r, c)[axis]
               for g in range(period)):
            axes.append(axis)
    return tuple(axes)


def _crt(a1: int, n1: int, a2: int, n2: int):
    """Solve x = a1 (mod n1), x = a2 (mod n2): (x0, lcm) or None (empty)."""
    g = math.gcd(n1, n2)
    if (a2 - a1) % g:
        return None
    lcm = n1 // g * n2
    m = n2 // g
    if m == 1:
        return a1 % lcm, lcm
    t = ((a2 - a1) // g * pow(n1 // g, -1, m)) % m
    return (a1 + n1 * t) % lcm, lcm


@dataclasses.dataclass(frozen=True, eq=False)
class RedistPlan:
    """A compiled one-shot redistribution: one collective (or none) plus
    static pre-gather / post-scatter index maps.

    The maps are dense per-device tables (row 0 = device ``mc*c+mr == 0``)
    with an out-of-range *sentinel* (== the local extent) marking padding:
    the gather masks sentinels to zero, the scatter drops them
    (``mode='drop'``), which preserves the engine's padding-is-zero
    storage invariant with no data-dependent shapes.
    """
    src: tuple                #: (cdist, rdist) source pair
    dst: tuple                #: (cdist, rdist) destination pair
    gshape: tuple             #: global (m, n)
    grid_shape: tuple         #: (r, c)
    kind: str                 #: 'local' | 'ppermute' | 'a2a' | 'bridge'
    comm_axes: tuple          #: mesh axes the collective runs over
    perm: tuple               #: ((src_id, dst_id), ...) for 'ppermute'
    slot_shape: tuple         #: (R, C) of one exchange slot
    send_rows: np.ndarray     #: (p, K, R) src-local row of slot element
    send_cols: np.ndarray     #: (p, K, C) src-local col of slot element
    recv_rows: np.ndarray     #: (p, K, R) dst-local row of slot element
    recv_cols: np.ndarray     #: (p, K, C) dst-local col of slot element
    src_local: tuple          #: (lr, lc) of the source block inside shard_map
    dst_local: tuple          #: (lr, lc) of the destination block
    groups: tuple = ()        #: equal-size a2a subgroups of participant
                              #: indices (``lax.all_to_all`` axis_index_groups
                              #: order), or () for the full comm product

    @property
    def nslots(self) -> int:
        return self.send_rows.shape[1]

    @property
    def rounds(self) -> int:
        """Collective rounds this plan issues (the chain's comparison unit)."""
        return 0 if self.kind == "local" else 1

    def wire_bytes(self, itemsize: int) -> int:
        """Ring-model bytes RECEIVED per device for one execution.

        Honest about residual slot padding: incompatible (sender,
        receiver) residue pairs inside one subgroup still ship their
        (zero) slots, so an inflated exchange prices higher than the
        fused chain hop -- the chain-vs-direct arbitration keys off
        exactly this number.  Ragged-slot trimming and subgroup packing
        shrink ``slot_shape``/``nslots`` first, so this prices the wire
        actually used, not the PR-12 padded rectangle.
        """
        R, C = self.slot_shape
        slot = R * C * itemsize
        if self.kind == "a2a":
            return slot * (self.nslots - 1)       # K slots, keep 1/K
        if self.kind == "ppermute":
            return slot
        if self.kind == "bridge":
            return R * C * itemsize               # full matrix through root
        return 0

    def describe(self) -> str:
        s = f"[{self.src[0].value},{self.src[1].value}]"
        d = f"[{self.dst[0].value},{self.dst[1].value}]"
        R, C = self.slot_shape
        axes = ",".join(self.comm_axes) or "-"
        grp = f", {len(self.groups)} group(s)" if self.groups else ""
        return (f"{s}->{d}: {self.kind} over ({axes}), {self.rounds} "
                f"round(s), {self.nslots} slot(s) of {R}x{C}{grp}")


@functools.lru_cache(maxsize=None)
def compile_plan(src: tuple, dst: tuple, gshape: tuple,
                 grid_shape: tuple,
                 src_align: tuple = (0, 0), dst_align: tuple = (0, 0)):
    """Compile ``src -> dst`` on ``grid_shape`` into a one-shot plan.

    Covers the full ``LEGAL_PAIRS x LEGAL_PAIRS`` matrix at arbitrary
    legal alignments.  Returns None only for ``src == dst`` at identical
    alignments (a true no-op -- whitelisted by the coverage gate) and
    for MD endpoints at nonzero alignments (which the engine rejects
    before planning).  ``[CIRC,CIRC]`` endpoints compile to a ``'bridge'``
    plan: costed metadata (1 round, full-matrix bytes) executed by the
    engine's eager root path.
    """
    src, dst = tuple(src), tuple(dst)
    src_align, dst_align = tuple(src_align), tuple(dst_align)
    r, c = grid_shape
    p = r * c
    if src == dst and src_align == dst_align:
        return None
    m, n = gshape
    if CIRC in (*src, *dst):
        empty = np.zeros((p, 1, 0), np.int32)
        empty.setflags(write=False)
        return RedistPlan(
            src=src, dst=dst, gshape=(m, n), grid_shape=(r, c),
            kind="bridge", comm_axes=(), perm=(), slot_shape=(m, n),
            send_rows=empty, send_cols=empty, recv_rows=empty,
            recv_cols=empty, src_local=(0, 0), dst_local=(0, 0))
    if MD in (*src, *dst) and (src_align != (0, 0) or dst_align != (0, 0)):
        return None                       # engine raises before planning
    sizes = {"mc": r, "mr": c}
    comm = comm_axes_for(src, dst, r, c, src_align, dst_align)
    K = 1
    for a in comm:
        K *= sizes[a]

    Ss_row, Sd_row = dist_stride(src[0], r, c), dist_stride(dst[0], r, c)
    Ss_col, Sd_col = dist_stride(src[1], r, c), dist_stride(dst[1], r, c)
    Lrow, Lcol = _lcm(Ss_row, Sd_row), _lcm(Ss_col, Sd_col)
    R = max(1, -(-m // Lrow))
    C = max(1, -(-n // Lcol))
    src_lr, src_lc = ix.max_local_length(m, Ss_row), ix.max_local_length(n, Ss_col)
    dst_lr, dst_lc = ix.max_local_length(m, Sd_row), ix.max_local_length(n, Sd_col)

    send_rows = np.full((p, K, R), src_lr, np.int32)
    send_cols = np.full((p, K, C), src_lc, np.int32)
    recv_rows = np.full((p, K, R), dst_lr, np.int32)
    recv_cols = np.full((p, K, C), dst_lc, np.int32)

    def coords(d):
        return d // c, d % c

    def peer(d, k):
        """Device at participant index k of d's comm group (the all_to_all
        slot order: first comm axis major, matching jax's flattening)."""
        mc_, mr_ = coords(d)
        cs = {"mc": mc_, "mr": mr_}
        for a in reversed(comm):
            cs[a] = k % sizes[a]
            k //= sizes[a]
        return cs["mc"], cs["mr"]

    def pidx(d):
        """Participant index of device d within its own comm group."""
        mc_, mr_ = coords(d)
        cs = {"mc": mc_, "mr": mr_}
        k = 0
        for a in comm:
            k = k * sizes[a] + cs[a]
        return k

    dims = ((m, Lrow, Ss_row, Sd_row, src_lr, dst_lr, send_rows, recv_rows, R),
            (n, Lcol, Ss_col, Sd_col, src_lc, dst_lc, send_cols, recv_cols, C))

    for d in range(p):
        own = coords(d)
        for k in range(K):
            other = peer(d, k)
            for dim, (ext, L, Ssrc, Sdst, s_len, d_len, smap, rmap, cnt) \
                    in enumerate(dims):
                ds_, dd_ = src[dim], dst[dim]
                s_al, d_al = src_align[dim], dst_align[dim]
                rs_own = _rank_under(ds_, *own, r, c)
                rs_oth = _rank_under(ds_, *other, r, c)
                rd_own = _rank_under(dd_, *own, r, c)
                rd_oth = _rank_under(dd_, *other, r, c)
                # d as SENDER to receiver `other`.  A dim alignment `a`
                # shifts the owned residue set: device with residue rho
                # owns i = (rho - a) (mod S).  None = owns nothing (MD
                # off-diagonal): skip, the slot stays sentinel padding.
                if rs_own is not None and rd_oth is not None:
                    hit = _crt((rs_own - s_al) % Ssrc, Ssrc,
                               (rd_oth - d_al) % Sdst, Sdst)
                    if hit is not None:
                        gi = hit[0] + np.arange(cnt, dtype=np.int64) * L
                        smap[d, k, :] = np.where(gi < ext, gi // Ssrc, s_len)
                # d as RECEIVER of slot k (sent by `other`)
                if rs_oth is not None and rd_own is not None:
                    hit = _crt((rs_oth - s_al) % Ssrc, Ssrc,
                               (rd_own - d_al) % Sdst, Sdst)
                    if hit is not None:
                        gi = hit[0] + np.arange(cnt, dtype=np.int64) * L
                        rmap[d, k, :] = np.where(gi < ext, gi // Sdst, d_len)

    # Ragged slots, part 1: per-row valid entries are a front prefix
    # (gi = hit0 + t*L is increasing), so the union of used positions is
    # a prefix too -- trim the trailing all-sentinel tail of each dim.
    # Sender slot position t and receiver slot position t address the
    # same global element by construction (same CRT enumeration), so a
    # joint trim preserves the correspondence.
    def _prefix(mask_s: np.ndarray, mask_r: np.ndarray) -> int:
        used = mask_s.any(axis=(0, 1)) | mask_r.any(axis=(0, 1))
        nz = np.nonzero(used)[0]
        return int(nz[-1]) + 1 if len(nz) else 1

    R_used = _prefix(send_rows < src_lr, recv_rows < dst_lr)
    C_used = _prefix(send_cols < src_lc, recv_cols < dst_lc)
    if (R_used, C_used) != (R, C):
        R, C = R_used, C_used
        send_rows = np.ascontiguousarray(send_rows[:, :, :R])
        recv_rows = np.ascontiguousarray(recv_rows[:, :, :R])
        send_cols = np.ascontiguousarray(send_cols[:, :, :C])
        recv_cols = np.ascontiguousarray(recv_cols[:, :, :C])

    kind, perm, a2a_groups = ("local", (), ()) if not comm else ("a2a", (), ())
    if comm:
        ne_send = ((send_rows < src_lr).any(-1) & (send_cols < src_lc).any(-1))
        ne_recv = ((recv_rows < dst_lr).any(-1) & (recv_cols < dst_lc).any(-1))
        if (ne_send.sum(1) <= 1).all() and (ne_recv.sum(1) <= 1).all():
            # wholesale relabeling candidate: one peer per device.  ppermute
            # applies ONE perm to every group of the named axes, so demand
            # the within-group perm be identical across groups.
            groups: dict = {}
            for d in range(p):
                ks = np.nonzero(ne_send[d])[0]
                if len(ks) == 0:
                    continue
                qc = peer(d, int(ks[0]))
                q = qc[0] * c + qc[1]
                gkey = tuple(v for a, v in zip(MESH_AXES, coords(d))
                             if a not in comm)
                groups.setdefault(gkey, set()).add((pidx(d), pidx(q)))
            sets = list(groups.values())
            if sets and all(s == sets[0] for s in sets):
                kind = "ppermute"
                perm = tuple(sorted(sets[0]))
                sel_s = np.array([int(np.nonzero(ne_send[d])[0][0])
                                  if ne_send[d].any() else 0
                                  for d in range(p)])
                sel_r = np.array([int(np.nonzero(ne_recv[d])[0][0])
                                  if ne_recv[d].any() else 0
                                  for d in range(p)])
                ar = np.arange(p)
                send_rows = send_rows[ar, sel_s][:, None, :]
                send_cols = send_cols[ar, sel_s][:, None, :]
                recv_rows = np.where(ne_recv[ar, sel_r][:, None],
                                     recv_rows[ar, sel_r], dst_lr)[:, None, :]
                recv_cols = np.where(ne_recv[ar, sel_r][:, None],
                                     recv_cols[ar, sel_r], dst_lc)[:, None, :]
        if kind == "a2a" and K > 1:
            # Ragged slots, part 2: incompatible residue pairs (e.g. the
            # MD diagonal talking only to itself) leave whole slots empty.
            # Build the UNION traffic graph over participant indices
            # (shared across outer mesh groups -- axis_index_groups applies
            # one partition to every outer coordinate), take its connected
            # components, and when they pack exactly into equal bins of
            # K* = max component size, run the a2a over those subgroups
            # with K* slots instead of K.
            ne = ne_send | ne_recv
            adj = [set() for _ in range(K)]
            for d in range(p):
                q = pidx(d)
                for k in np.nonzero(ne[d])[0]:
                    adj[q].add(int(k))
                    adj[int(k)].add(q)
            seen = [False] * K
            comps = []
            for s0 in range(K):
                if seen[s0]:
                    continue
                stack, comp = [s0], []
                seen[s0] = True
                while stack:
                    v = stack.pop()
                    comp.append(v)
                    for w in adj[v]:
                        if not seen[w]:
                            seen[w] = True
                            stack.append(w)
                comps.append(sorted(comp))
            kstar = max(len(cm) for cm in comps)
            if kstar < K:
                bins, ok = [], True
                for comp in sorted(comps, key=len, reverse=True):
                    for b in bins:
                        if len(b) + len(comp) <= kstar:
                            b.extend(comp)
                            break
                    else:
                        bins.append(list(comp))
                ok = all(len(b) == kstar for b in bins) \
                    and len(bins) * kstar == K
                if ok:
                    a2a_groups = tuple(tuple(sorted(b)) for b in bins)
                    group_of = {}
                    for b in a2a_groups:
                        for q in b:
                            group_of[q] = b
                    sel = np.array([group_of[pidx(d)] for d in range(p)],
                                   dtype=np.int64)       # (p, K*)
                    ar = np.arange(p)[:, None]
                    send_rows = np.ascontiguousarray(send_rows[ar, sel])
                    send_cols = np.ascontiguousarray(send_cols[ar, sel])
                    recv_rows = np.ascontiguousarray(recv_rows[ar, sel])
                    recv_cols = np.ascontiguousarray(recv_cols[ar, sel])

    for t in (send_rows, send_cols, recv_rows, recv_cols):
        t.setflags(write=False)
    return RedistPlan(
        src=src, dst=dst, gshape=(m, n), grid_shape=(r, c), kind=kind,
        comm_axes=comm, perm=perm, slot_shape=(R, C),
        send_rows=send_rows, send_cols=send_cols,
        recv_rows=recv_rows, recv_cols=recv_cols,
        src_local=(src_lr, src_lc), dst_local=(dst_lr, dst_lc),
        groups=a2a_groups)


# ---------------------------------------------------------------------
# Slice-set compilation (ISSUE 16 -- the slicing-gemm schedule)
# ---------------------------------------------------------------------

def slice_row_mode(m: int, n: int, grid_shape: tuple) -> bool:
    """Which output dimension the slicing gemm slices 1-D cyclic.

    Row slices ([VC,STAR] output) when the output is tall (``m >= n``)
    or the grid is Nx1 (where [MC,MR] <-> [VC,STAR] is a pure local
    relabeling, leaving the B broadcast as the ONLY collective); column
    slices ([STAR,VR]) otherwise -- symmetrically free on 1xN grids.
    One rule shared by the executor (``blas.level3._summa_slice``), the
    cost model and the analysis drivers, so the tuner prices exactly the
    plans the executor runs."""
    r, c = grid_shape
    return c == 1 or (r != 1 and m >= n)


def compile_slice_plan(src: tuple, dst: tuple, gshape: tuple,
                       grid_shape: tuple, rows: tuple | None = None,
                       cols: tuple | None = None,
                       src_align: tuple = (0, 0),
                       dst_align: tuple = (0, 0)):
    """Compile ``src -> dst`` for a contiguous SUB-RANGE of the operand.

    ``rows=(r0, r1)`` / ``cols=(c0, c1)`` select the half-open global
    slice ``A[r0:r1, c0:c1]`` (defaults: the full extent).  The view
    identity makes this exact, not approximate: the device owning global
    index ``g`` of a matrix aligned at ``a`` is the zero-aligned owner of
    ``g + a``, so a sub-range starting at ``r0`` is itself a distributed
    matrix of shape ``(r1-r0, c1-c0)`` aligned at
    ``(align + offset) mod stride`` -- and the full ``compile_plan``
    machinery (ragged trimming, FFD a2a packing, CRT intersections)
    applies unchanged.  This is how per-block operand slices of the
    slicing gemm (and any future blocked one-shot consumer) compile
    without a full-matrix-endpoint detour.  lru-cached via
    ``compile_plan``; returns None for a no-op exactly as it does."""
    m, n = gshape
    r0, r1 = (0, m) if rows is None else rows
    c0, c1 = (0, n) if cols is None else cols
    if not (0 <= r0 <= r1 <= m and 0 <= c0 <= c1 <= n):
        raise ValueError(f"slice rows={rows} cols={cols} outside {gshape}")
    r, c = grid_shape
    sa = ((src_align[0] + r0) % dist_stride(src[0], r, c),
          (src_align[1] + c0) % dist_stride(src[1], r, c))
    da = ((dst_align[0] + r0) % dist_stride(dst[0], r, c),
          (dst_align[1] + c0) % dist_stride(dst[1], r, c))
    return compile_plan(tuple(src), tuple(dst), (r1 - r0, c1 - c0),
                        (r, c), sa, da)


def gemm_slice_plans(m: int, k: int, n: int, grid_shape: tuple):
    """The compiled plan set of the slicing gemm at one geometry.

    Returns ``(mode, plans)`` where mode is ``'local'`` (1x1: zero
    collectives), ``'rows'`` or ``'cols'``, and plans is a tuple of
    ``(tag, RedistPlan)`` -- the pure-relabeling degenerate legs (Nx1 /
    1xN grids) come back as zero-round ``kind='local'`` plans.  Single
    source of truth for the cost model's closed-form slot-byte pricing
    and the analysis pins."""
    r, c = grid_shape
    if r * c == 1:
        return "local", ()
    if slice_row_mode(m, n, grid_shape):
        return "rows", (
            ("A->[VC,*]", compile_plan((MC, MR), (VC, STAR), (m, k),
                                       grid_shape)),
            ("B->[*,*]", compile_plan((MC, MR), (STAR, STAR), (k, n),
                                      grid_shape)),
            ("D->[MC,MR]", compile_plan((VC, STAR), (MC, MR), (m, n),
                                        grid_shape)),
        )
    return "cols", (
        ("A->[*,*]", compile_plan((MC, MR), (STAR, STAR), (m, k),
                                  grid_shape)),
        ("B->[*,VR]", compile_plan((MC, MR), (STAR, VR), (k, n),
                                   grid_shape)),
        ("D->[MC,MR]", compile_plan((STAR, VR), (MC, MR), (m, n),
                                    grid_shape)),
    )
