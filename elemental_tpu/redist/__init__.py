"""Redistribution subsystem: the chained engine (:mod:`.engine`), the
one-shot plan compiler (:mod:`.plan`, ISSUE 12), and the wire codecs
(:mod:`.quantize`).  Only the numpy-level compiler is re-exported here;
import :mod:`.engine` explicitly for the executing entry points."""
from .plan import RedistPlan, compile_plan, comm_axes_for

__all__ = ["RedistPlan", "compile_plan", "comm_axes_for"]
