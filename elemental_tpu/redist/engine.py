"""The redistribution engine.

TPU-native rebuild of the reference's ``El::copy`` namespace
(Elemental ``src/blas_like/level1/Copy/*.hpp`` -- ``AllGather``,
``ColAllGather``, ``PartialColAllGather``, ``Filter``, ``PartialColFilter``,
``Gather``, ``Scatter``, ...): ``B = A`` between any two of the legal
distribution pairs, implemented as named-axis collectives + pure-local
index shuffles inside ``shard_map``.

Structure:
  * ``_gather_dim``  -- dist dim -> replicated dim  (lax.all_gather + interleave)
  * ``_filter_dim``  -- replicated dim -> dist dim  (pure local selection)
  * partial gathers/filters for the V* <-> M* ladder
  * ``to_dist``      -- the dispatch table (fast paths, generic fallback
                        through [STAR,STAR] for the cold pairs)
  * ``contract``     -- the reference's ``Contract``/``AxpyContract``
                        (SumScatter of partial products; lowers to
                        ``lax.psum_scatter``)

Everything here assumes it is called INSIDE ``shard_map`` over the grid's
mesh; the public jit-able entry point is :func:`redistribute`.

Alignment support: the generic path handles arbitrary alignments; fast paths
currently require zero alignments (the blocked algorithms only use zero) and
fall back otherwise.
"""
from __future__ import annotations

import contextlib
import dataclasses
from collections import Counter
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core import indexing as ix
from ..core.compat import shard_map
from ..core.dist import (
    Dist, MC, MR, VC, VR, STAR, MD, CIRC,
    stride as dist_stride, gather_axes, rank_of, md_slot_of_global,
)
from ..core.distmatrix import DistMatrix, _check_pair
from .plan import compile_plan
from .quantize import (QUANT_TILE, check_comm_precision, q8_pack, q8_unpack,
                       quantizable)

#: legal values of :func:`redistribute`'s ``path`` argument.  ``None`` and
#: ``'chain'`` are the factored multi-hop route (bit-identical to the
#: pre-ISSUE-12 engine); ``'direct'`` executes the one-shot compiled plan
#: (:mod:`.plan`) where one exists, falling back to the chain otherwise;
#: ``'auto'`` arbitrates per call with the ring-model cost below.
REDIST_PATHS = (None, "chain", "direct", "auto")


#: Trace-time instrumentation: public-entry call counts, keyed by
#: ``(src_dist_pair, dst_dist_pair)`` for :func:`redistribute` and by the
#: string ``"panel_spread"`` for :func:`panel_spread`.  Tests assert routing
#: through it (e.g. that the cholesky/herk trailing chain takes the fused
#: panel-spread path instead of three redistribute calls); clear between
#: measurements with ``REDIST_COUNTS.clear()``.  Counts python-level entry
#: calls, not executed collectives -- jit caching does not hide them.
REDIST_COUNTS: Counter = Counter()


@contextlib.contextmanager
def redist_counts():
    """Scoped redistribute/panel_spread call counting.

    Swaps a fresh Counter in for the module-global :data:`REDIST_COUNTS`
    for the duration of the block and yields it: counts observed inside
    the block accumulate on the yielded Counter (readable both during and
    after the block), and the previous global counter is restored
    untouched on exit -- so counter state cannot leak between tests or
    measurements.  The module-level ``REDIST_COUNTS`` name remains as the
    backward-compatible process-global default for code that does not use
    the context manager (note: ``from ... import REDIST_COUNTS`` binds the
    *current* counter object; prefer this context manager, the
    ``redist_counter`` pytest fixture, or attribute access via the
    module)."""
    global REDIST_COUNTS
    prev = REDIST_COUNTS
    cur: Counter = Counter()
    REDIST_COUNTS = cur
    try:
        yield cur
    finally:
        REDIST_COUNTS = prev


# ---------------------------------------------------------------------
# dist-metadata trace hook (the static comm-plan analyzer's view of the
# engine: elemental_tpu/analysis/ correlates these Python-level records
# with the collectives it finds in the traced jaxpr)
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RedistRecord:
    """One public-entry redistribution call observed under redist_trace."""
    kind: str            # "redistribute" | "panel_spread"
    src: tuple           # (cdist, rdist) Dist pair of the source
    dst: tuple           # target pair ("panel_spread": the [MC,*]/[*,MR] pair)
    gshape: tuple        # source global shape
    dtype: str
    in_id: int           # id() of the source local array/tracer
    out_ids: tuple       # id() of the produced local array(s)/tracer(s)
    grid_shape: tuple = ()   # (r, c) of the grid (obs ring-byte estimates)
    #: dtype actually moved on the wire (== ``dtype`` unless the entry ran
    #: under a ``comm_precision`` mode -- "bfloat16" / "int8" then)
    wire_dtype: str = ""
    #: route the engine resolved for this entry: "chain" (factored hops,
    #: the default), "direct" (one-shot compiled plan), or "storage" (the
    #: row-permute fast path, whose cross-device motion GSPMD plans)
    path: str = "chain"
    #: collective rounds the resolved route issues (-1 = not computed)
    rounds: int = -1
    #: ring-model bytes received per device by the resolved route
    #: (-1 = not computed); with ``rounds`` this is the "per-round wire
    #: bytes" record of the chosen path
    wire_bytes: int = -1
    #: why a ``path='direct'|'auto'`` request resolved to the chain
    #: ("" = it did not fall back): "noop" (src == dst at equal aligns),
    #: "no_plan" (compile_plan returned None), or "arbitration" (the
    #: measured/ring cost model preferred the chain under 'auto').
    #: Mirrored into the ``redist_fallbacks`` obs counter.
    fallback_reason: str = ""
    # live references keep the ids above unambiguous (no id reuse after GC)
    refs: tuple = dataclasses.field(default=(), repr=False, compare=False)

    @property
    def label(self) -> str:
        # non-redistribute kinds ("panel_spread", "row_permute") label as
        # themselves; dist pairs keep the PATH-INDEPENDENT [src]->[dst]
        # form so comm-plan goldens aggregate identically on either route
        if self.kind != "redistribute":
            return self.kind
        s = f"[{self.src[0].value},{self.src[1].value}]"
        d = f"[{self.dst[0].value},{self.dst[1].value}]"
        return f"{s}->{d}"


_REDIST_TRACE: list | None = None


@contextlib.contextmanager
def redist_trace():
    """Record dist-level metadata for every :func:`redistribute` /
    :func:`panel_spread` entry inside the block.

    Yields the live list of :class:`RedistRecord`; the analyzer uses the
    ``in_id``/``out_ids`` object identities to prove data-flow adjacency
    (a record whose input IS a previous record's untouched output had no
    intervening compute -- the round-trip lint)."""
    global _REDIST_TRACE
    prev = _REDIST_TRACE
    log: list = []
    _REDIST_TRACE = log
    try:
        yield log
    finally:
        _REDIST_TRACE = prev


#: runtime observers (``elemental_tpu.obs.Tracer`` activation registers
#: one): callbacks invoked with every RedistRecord as it happens, whether
#: or not a ``redist_trace`` block is also collecting.
_REDIST_OBSERVERS: list = []


def add_redist_observer(cb) -> callable:
    """Register ``cb(record)`` on every public redistribute/panel_spread
    entry; returns a zero-argument remover (idempotent)."""
    _REDIST_OBSERVERS.append(cb)

    def remove():
        try:
            _REDIST_OBSERVERS.remove(cb)
        except ValueError:
            pass
    return remove


# ---------------------------------------------------------------------
# fault-injection seam (elemental_tpu.resilience, ISSUE 7): a seeded
# FaultPlan installed here corrupts chosen public redistribute /
# panel_spread payloads, so the certified-solve tests can prove each
# corruption class is repaired by escalation or surfaced as a health
# report.  None (the default) is the zero-overhead path.
# ---------------------------------------------------------------------

_FAULT_INJECTOR = None


@contextlib.contextmanager
def fault_injection(plan):
    """Install ``plan`` (a ``resilience.faults.FaultPlan``, or anything
    with ``apply(target, outputs) -> outputs``) as the engine's fault
    injector for the block; the previous injector is restored on exit.
    Every public :func:`redistribute` / :func:`panel_spread` entry routes
    its output local array(s) through ``plan.apply`` before returning."""
    global _FAULT_INJECTOR
    prev = _FAULT_INJECTOR
    _FAULT_INJECTOR = plan
    try:
        yield plan
    finally:
        _FAULT_INJECTOR = prev


def set_fault_step(step) -> None:
    """Announce the current driver panel step to the installed fault
    injector (``None`` = leaving the step scope).  Gates
    ``FaultSpec(window=...)`` rules (ISSUE 11): the ABFT-guarded
    factorizations call this at every panel-transaction boundary so
    chaos tests can corrupt a chosen step deterministically.  A no-op --
    zero traced operations -- when no injector is installed or the
    injector has no ``set_step``."""
    inj = _FAULT_INJECTOR
    if inj is not None:
        f = getattr(inj, "set_step", None)
        if f is not None:
            f(step)


def apply_fault(target: str, outputs: tuple) -> tuple:
    """Route eager kernel outputs through the installed fault injector;
    identity (and zero-overhead) when none is installed.

    The engine corrupts its own ``redistribute``/``panel_spread`` payloads
    internally; this is the seam OTHER layers use for the ``'compute'``
    fault target (ISSUE 9) -- the lu/cholesky/qr panel kernels and the
    serve executor's batched solve route their local outputs through it,
    so chaos tests cover soft errors in local math with the same seeded
    bit-identical replay guarantee as the collective targets."""
    if _FAULT_INJECTOR is None:
        return tuple(outputs)
    return tuple(_FAULT_INJECTOR.apply(target, tuple(outputs)))


def _trace_record(kind, src, dst, gshape, dtype, objs_in, objs_out,
                  grid_shape=(), wire_dtype=None, path="chain", rounds=-1,
                  wire_bytes=-1, fallback_reason="", observers_only=False):
    """Build + publish one RedistRecord.  ``observers_only`` skips the
    ``redist_trace`` list (used by the row-permute fast path: the obs
    tracer must see its wire traffic, but the comm-plan goldens aggregate
    ``redist_trace`` records and GSPMD-planned motion has no explicit
    collective rounds to pin)."""
    if _REDIST_TRACE is None and not _REDIST_OBSERVERS:
        return
    rec = RedistRecord(
        kind=kind, src=tuple(src), dst=tuple(dst), gshape=tuple(gshape),
        dtype=str(dtype), in_id=id(objs_in),
        out_ids=tuple(id(o) for o in objs_out), grid_shape=tuple(grid_shape),
        wire_dtype=str(wire_dtype or dtype), path=path, rounds=rounds,
        wire_bytes=wire_bytes, fallback_reason=fallback_reason,
        refs=(objs_in,) + tuple(objs_out))
    if _REDIST_TRACE is not None and not observers_only:
        _REDIST_TRACE.append(rec)
    for cb in tuple(_REDIST_OBSERVERS):
        cb(rec)


# ---------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------

def _pad_dim(x, dim: int, target: int):
    cur = x.shape[dim]
    if cur == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[dim] = (0, target - cur)
    return jnp.pad(x, pads)


def _gather_dim(x, dim: int, d: Dist, align: int, extent: int, r: int, c: int):
    """Rebuild the full (true-extent) dimension on every device."""
    if d is MD:
        if r * c == 1:
            return lax.slice_in_dim(x, 0, extent, axis=dim)
        # p slot-ranges of length l gathered mc-major, then the static
        # slot permutation rebuilds global order (copy:: for [MD,*])
        g = lax.all_gather(x, ("mc", "mr"), axis=0)       # (p, l, ...)
        shape = list(x.shape)
        shape[dim] = x.shape[dim] * r * c
        g = jnp.moveaxis(g, 0, dim)
        gflat = g.reshape(shape)                          # slot-major flat
        idx = jnp.asarray(md_slot_of_global(r, c, extent))
        return jnp.take(gflat, idx, axis=dim)
    S = dist_stride(d, r, c)
    if S == 1:
        return lax.slice_in_dim(x, 0, extent, axis=dim)
    g = lax.all_gather(x, gather_axes(d), axis=0)        # (S, ...) rank-ordered
    if align:
        g = jnp.roll(g, -align, axis=0)                   # block s <- shift s
    g = jnp.moveaxis(g, 0, dim + 1)                       # interleave position
    shape = list(x.shape)
    shape[dim] = x.shape[dim] * S
    g = g.reshape(shape)                                  # index i = iLoc*S + s
    return lax.slice_in_dim(g, 0, extent, axis=dim)


def _filter_md(x, dim: int, extent: int, r: int, c: int):
    """Replicated dim -> this device's MD slot range: k = k0 + t*lcm for
    owners (k0 = rank_of(MD) < lcm), all-zero slots for devices outside
    the diagonal comm (sentinel k0 == lcm maps every index out of range)."""
    L = dist_stride(MD, r, c)
    l = ix.max_local_length(extent, L)
    k0 = rank_of(MD, r, c)
    gi = jnp.arange(l) * L + k0
    gi = jnp.where((k0 < L) & (gi < extent), gi, extent)
    return jnp.take(x, gi, axis=dim, mode="fill", fill_value=0)


def _filter_dim(x, dim: int, S: int, shift, l_out: int):
    """Select this device's cyclic slice of a replicated dimension."""
    if S == 1:
        return _pad_dim(x, dim, l_out)
    x = _pad_dim(x, dim, S * l_out)
    shape = list(x.shape)
    shape[dim : dim + 1] = [l_out, S]
    x = x.reshape(shape)                                  # (..., l_out, S, ...)
    return lax.dynamic_index_in_dim(x, shift, axis=dim + 1, keepdims=False)


def _partial_gather_dim(x, dim: int, axes, nblocks: int, l_out: int):
    """V* -> M* ladder: gather ``nblocks`` interleaved sub-blocks.

    cf. ``copy::PartialColAllGather``: the devices sharing this dimension's
    coarse rank gather their fine-grained cyclic blocks; interleaving them
    yields the coarse-cyclic local block.
    """
    if nblocks == 1:                    # degenerate: nothing to exchange
        return lax.slice_in_dim(x, 0, l_out, axis=dim)
    g = lax.all_gather(x, axes, axis=0)                   # (nblocks, l_in, ...)
    g = jnp.moveaxis(g, 0, dim + 1)
    shape = list(x.shape)
    shape[dim] = x.shape[dim] * nblocks
    g = g.reshape(shape)                                  # jLoc = iLoc*nb + b
    return lax.slice_in_dim(g, 0, l_out, axis=dim)


def _partial_filter_dim(x, dim: int, nblocks: int, sub_rank, l_out: int):
    """M* -> V* ladder: pure-local selection of the finer cyclic slice
    (cf. ``copy::PartialColFilter``)."""
    x = _pad_dim(x, dim, nblocks * l_out)
    shape = list(x.shape)
    shape[dim : dim + 1] = [l_out, nblocks]
    x = x.reshape(shape)
    return lax.dynamic_index_in_dim(x, sub_rank, axis=dim + 1, keepdims=False)


# ---------------------------------------------------------------------
# fused M <-> V conversions (one all_to_all; the reference's
# copy::Exchange-class kernels, mn/p volume instead of the mn/r gather)
# ---------------------------------------------------------------------

def _fused_to_v(A: DistMatrix) -> DistMatrix:
    """[MC,MR] -> [VC,STAR] or [MR,MC] -> [VR,STAR]: the V dist refines the
    row dist, so ONE all_to_all over the column axis both refines the rows
    and rebuilds the full column extent (each peer contributes its cyclic
    column slice; the interleave positions land exactly at the natural
    global order)."""
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    m, n = A.gshape
    if A.dist == (MC, MR):
        ax, n_other, dst = "mr", c, VC
    else:                                   # (MR, MC)
        ax, n_other, dst = "mc", r, VR
    lt = ix.max_local_length(m, p)
    x = _pad_dim(A.local, 0, n_other * lt)
    lc = x.shape[1]
    x3 = x.reshape(lt, n_other, lc)         # row t = w*n_other + g
    y = x3 if n_other == 1 \
        else lax.all_to_all(x3, ax, split_axis=1, concat_axis=1)
    z = jnp.moveaxis(y, 1, 2).reshape(lt, lc * n_other)
    z = lax.slice_in_dim(z, 0, n, axis=1)
    v = rank_of(dst, r, c)
    gi = jnp.arange(lt) * p + v
    z = jnp.where((gi < m)[:, None], z, 0)
    return DistMatrix(z, A.gshape, dst, STAR, 0, 0, g)


def _fused_from_v(A: DistMatrix) -> DistMatrix:
    """[VC,STAR] -> [MC,MR] or [VR,STAR] -> [MR,MC] (inverse of
    :func:`_fused_to_v`; one all_to_all over the target column axis)."""
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    m, n = A.gshape
    if A.cdist is VC:
        ax, n_other, dst = "mr", c, (MC, MR)
        S_row = r
    else:                                   # VR
        ax, n_other, dst = "mc", r, (MR, MC)
        S_row = c
    lp = A.local.shape[0]                   # ceil(m/p)
    lcd = ix.max_local_length(n, n_other)
    x = _pad_dim(A.local, 1, n_other * lcd)
    x3 = x.reshape(lp, lcd, n_other)        # col j = u*n_other + s
    y = x3 if n_other == 1 \
        else lax.all_to_all(x3, ax, split_axis=2, concat_axis=2)
    z = jnp.moveaxis(y, 2, 1).reshape(lp * n_other, lcd)
    lr = ix.max_local_length(m, S_row)
    z = lax.slice_in_dim(z, 0, lr, axis=0)
    q_row = rank_of(dst[0], r, c)
    gi = jnp.arange(lr) * S_row + q_row
    q_col = rank_of(dst[1], r, c)
    gj = jnp.arange(lcd) * n_other + q_col
    z = jnp.where((gi < m)[:, None] & (gj < n)[None, :], z, 0)
    return DistMatrix(z, A.gshape, dst[0], dst[1], 0, 0, g)


def _t_meta(A: DistMatrix) -> DistMatrix:
    """Local transpose + swapped metadata (free; used to reuse the fused
    row-kernels for the [STAR,V] column forms)."""
    m, n = A.gshape
    return DistMatrix(A.local.T, (n, m), A.rdist, A.cdist,
                      A.ralign, A.calign, A.grid)


def _fused_to_star_star(A: DistMatrix) -> DistMatrix | None:
    """[MC,MR] / [MR,MC] -> [STAR,STAR] in ONE all_gather over the flattened
    ('mc','mr') axis + a static interleave, instead of the generic route's
    two sequential per-dim gathers with an mn/r intermediate (the panel
    gather of the blocked factorizations -- e.g. the LU look-ahead strip --
    is the hot caller).  Falls back (None) on 1-D grids, where the generic
    path is already a single collective."""
    g = A.grid
    r, c = g.height, g.width
    if r == 1 or c == 1:
        return None
    m, n = A.gshape
    x = A.local
    lr, lc = x.shape
    gx = lax.all_gather(x, ("mc", "mr"), axis=0)      # (r*c, lr, lc), mc-major
    G = gx.reshape(r, c, lr, lc)
    if A.dist == (MC, MR):
        # global (i, j) = (il*r + mc, jl*c + mr)
        full = G.transpose(2, 0, 3, 1).reshape(lr * r, lc * c)
    else:                                             # (MR, MC)
        # global (i, j) = (il*c + mr, jl*r + mc)
        full = G.transpose(2, 1, 3, 0).reshape(lr * c, lc * r)
    full = lax.slice(full, (0, 0), (m, n))
    return DistMatrix(full, A.gshape, STAR, STAR, 0, 0, g)


def _fused_dispatch(A: DistMatrix, dst) -> DistMatrix | None:
    src = A.dist
    if src in ((MC, MR), (MR, MC)) and dst == (STAR, STAR):
        return _fused_to_star_star(A)
    if src == (MC, MR) and dst == (VC, STAR):
        return _fused_to_v(A)
    if src == (MR, MC) and dst == (VR, STAR):
        return _fused_to_v(A)
    if src == (VC, STAR) and dst == (MC, MR):
        return _fused_from_v(A)
    if src == (VR, STAR) and dst == (MR, MC):
        return _fused_from_v(A)
    # transposed (column) forms ride the row kernels on the local transpose
    if src == (MC, MR) and dst == (STAR, VR):
        return _t_meta(_fused_to_v(_t_meta(A)))
    if src == (MR, MC) and dst == (STAR, VC):
        return _t_meta(_fused_to_v(_t_meta(A)))
    if src == (STAR, VR) and dst == (MC, MR):
        return _t_meta(_fused_from_v(_t_meta(A)))
    if src == (STAR, VC) and dst == (MR, MC):
        return _t_meta(_fused_from_v(_t_meta(A)))
    return None


# ---------------------------------------------------------------------
# re-alignment (pure ppermute rotation per dim)
# ---------------------------------------------------------------------

def _realign(A: DistMatrix, calign: int, ralign: int) -> DistMatrix:
    """Change alignments in place: owner of index i moves from (i+a)%S to
    (i+a')%S -- a wholesale device ROTATION per dim, no local rearrangement
    (the reference's aligned-copy SendRecv)."""
    from .interior import _rot_perm
    g = A.grid
    r, c = g.height, g.width
    x = A.local
    for dim, d, a_old, a_new in ((0, A.cdist, A.calign, calign),
                                 (1, A.rdist, A.ralign, ralign)):
        S = dist_stride(d, r, c)
        if S == 1 or a_old == a_new:
            continue
        axes, perm = _rot_perm(d, (a_old - a_new) % S, r, c)
        x = lax.ppermute(x, axes, perm)
    return DistMatrix(x, A.gshape, A.cdist, A.rdist, calign, ralign, A.grid)


# ---------------------------------------------------------------------
# whole-matrix operations (inside shard_map)
# ---------------------------------------------------------------------

def to_star_star(A: DistMatrix) -> DistMatrix:
    g = A.grid
    r, c = g.height, g.width
    xg = _gather_dim(A.local, 0, A.cdist, A.calign, A.gshape[0], r, c)
    xg = _gather_dim(xg, 1, A.rdist, A.ralign, A.gshape[1], r, c)
    return DistMatrix(xg, A.gshape, STAR, STAR, 0, 0, g)


def _from_star_star(xg, gshape, cdist, rdist, calign, ralign, grid) -> DistMatrix:
    r, c = grid.height, grid.width
    Sc, Sr = dist_stride(cdist, r, c), dist_stride(rdist, r, c)
    lr = ix.max_local_length(gshape[0], Sc)
    lc = ix.max_local_length(gshape[1], Sr)
    if cdist is MD:
        loc = _filter_md(xg, 0, gshape[0], r, c)
    else:
        loc = _filter_dim(xg, 0, Sc,
                          ix.shift(rank_of(cdist, r, c), calign, Sc), lr)
    if rdist is MD:
        loc = _filter_md(loc, 1, gshape[1], r, c)
    else:
        loc = _filter_dim(loc, 1, Sr,
                          ix.shift(rank_of(rdist, r, c), ralign, Sr), lc)
    # zero the padding tail (rows whose global index >= extent)
    loc = _zero_padding(loc, gshape, cdist, rdist, calign, ralign, grid)
    return DistMatrix(loc, gshape, cdist, rdist, calign, ralign, grid)


def _zero_padding(loc, gshape, cdist, rdist, calign, ralign, grid) -> jnp.ndarray:
    """Enforce the padding-is-zero invariant on a freshly filtered block."""
    r, c = grid.height, grid.width
    Sc, Sr = dist_stride(cdist, r, c), dist_stride(rdist, r, c)
    out = loc
    if cdist is MD or rdist is MD:
        return out        # _filter_md zero-fills everything out of range
    if loc.shape[0] * Sc != gshape[0]:
        shift = ix.shift(rank_of(cdist, r, c), calign, Sc)
        gi = jnp.arange(loc.shape[0]) * Sc + shift
        out = jnp.where((gi < gshape[0])[:, None], out, 0)
    if loc.shape[1] * Sr != gshape[1]:
        shift = ix.shift(rank_of(rdist, r, c), ralign, Sr)
        gj = jnp.arange(loc.shape[1]) * Sr + shift
        out = jnp.where((gj < gshape[1])[None, :], out, 0)
    return out


def _zero_aligned(A: DistMatrix) -> bool:
    return A.calign == 0 and A.ralign == 0


def to_dist(A: DistMatrix, cdist: Dist, rdist: Dist,
            calign: int = 0, ralign: int = 0) -> DistMatrix:
    """``B[cdist,rdist] = A`` -- the redistribution dispatch (inside shard_map)."""
    _check_pair(cdist, rdist)
    g = A.grid
    src = (A.cdist, A.rdist)
    dst = (cdist, rdist)

    if src == dst and (A.calign, A.ralign) == (calign, ralign):
        return A

    # MD's owner map is not a nested axis order: every conversion rides
    # the MD-aware gather/filter through [STAR,STAR] (copy::Gather/
    # Scatter class; the hot MD op -- diagonal extraction -- is the
    # pure-local path in level1.get_diagonal, not a redistribution)
    if MD in (A.cdist, A.rdist, cdist, rdist):
        if (calign, ralign) != (0, 0):
            raise ValueError("MD redistributions require zero alignments")
        ss = to_star_star(A)
        return _from_star_star(ss.local, A.gshape, cdist, rdist, 0, 0, g)

    # alignment-only change: a pure per-dim device rotation
    if src == dst:
        return _realign(A, calign, ralign)
    # misaligned source / aligned target: rotate to/from zero alignment so
    # every dist change runs on the zero-aligned fast paths (this removes
    # the [STAR,STAR] fallback from all aligned redistributions)
    if not _zero_aligned(A):
        return to_dist(_realign(A, 0, 0), cdist, rdist, calign, ralign)
    if (calign, ralign) != (0, 0):
        out = to_dist(A, cdist, rdist, 0, 0)
        return _realign(out, calign, ralign)

    # ---- fast paths (zero alignments) --------------------------------
    out = _fused_dispatch(A, dst)
    if out is not None:
        return out
    # pure row-dim change, column dist untouched
    if A.cdist is cdist:
        out = _rowdim_change(A, rdist)
        if out is not None:
            return out
    # pure col-dim change, row dist untouched
    if A.rdist is rdist:
        out = _coldim_change(A, cdist)
        if out is not None:
            return out
    # composite chains of fast single-dim hops
    chain = _CHAINS.get((src, dst))
    if chain is not None:
        out = A
        for hop in chain:
            out = to_dist(out, *hop)
        return out

    # ---- generic fallback: through [STAR,STAR] ------------------------
    ss = to_star_star(A)
    return _from_star_star(ss.local, A.gshape, cdist, rdist, calign, ralign, g)


#: Multi-hop routes for the pairs without a dedicated kernel.  Every route
#: now rides the FUSED all_to_all M<->V conversions (:func:`_fused_to_v` /
#: :func:`_fused_from_v`, mn/p volume per hop) plus the [VC]<->[VR]
#: ppermute -- the reference's ``copy::Exchange`` family
#: (``src/blas_like/level1/Copy/Exchange.hpp``); the old gather+filter
#: first hops (mn/r volume) are gone.
_CHAINS = {
    # transpose-pair exchange: fused demote, ppermute, fused promote
    ((MC, MR), (MR, MC)): ((VC, STAR), (VR, STAR), (MR, MC)),
    ((MR, MC), (MC, MR)): ((VR, STAR), (VC, STAR), (MC, MR)),
    # remaining 1-D cyclic forms (the directly-fused ones dispatch earlier)
    ((MC, MR), (VR, STAR)): ((VC, STAR), (VR, STAR)),
    ((MC, MR), (STAR, VC)): ((STAR, VR), (STAR, VC)),
    ((VR, STAR), (MC, MR)): ((VC, STAR), (MC, MR)),
    ((STAR, VC), (MC, MR)): ((STAR, VR), (MC, MR)),
    ((MR, MC), (VC, STAR)): ((VR, STAR), (VC, STAR)),
    ((MR, MC), (STAR, VR)): ((STAR, VC), (STAR, VR)),
    ((VC, STAR), (MR, MC)): ((VR, STAR), (MR, MC)),
    ((STAR, VR), (MR, MC)): ((STAR, VC), (MR, MC)),
    # cross-dim single-replicated targets (SUMMA panel moves)
    ((MC, MR), (MR, STAR)): ((VC, STAR), (VR, STAR), (MR, STAR)),
    ((MC, MR), (STAR, MC)): ((STAR, VR), (STAR, VC), (STAR, MC)),
    ((MR, MC), (MC, STAR)): ((VR, STAR), (VC, STAR), (MC, STAR)),
    ((MR, MC), (STAR, MR)): ((STAR, VC), (STAR, VR), (STAR, MR)),
    ((MR, STAR), (MC, MR)): ((VR, STAR), (VC, STAR), (MC, MR)),
    ((STAR, MC), (MC, MR)): ((STAR, VC), (STAR, VR), (MC, MR)),
    ((MC, STAR), (MR, MC)): ((VC, STAR), (VR, STAR), (MR, MC)),
    ((STAR, MR), (MR, MC)): ((STAR, VR), (STAR, VC), (MR, MC)),
    # V-form to the opposite M-form (Cholesky/Herk panel adjoint chains)
    ((VC, STAR), (MR, STAR)): ((VR, STAR), (MR, STAR)),
    ((VR, STAR), (MC, STAR)): ((VC, STAR), (MC, STAR)),
    ((STAR, VC), (STAR, MR)): ((STAR, VR), (STAR, MR)),
    ((STAR, VR), (STAR, MC)): ((STAR, VC), (STAR, MC)),
}


def _rowdim_change(A: DistMatrix, rdist: Dist) -> DistMatrix | None:
    """Change only the row (second-dim) distribution; col dist fixed.

    Legality of the source/target pairs guarantees the axes involved are
    disjoint from the column distribution's axes.
    """
    g = A.grid
    r, c = g.height, g.width
    m, n = A.gshape
    src = A.rdist
    if src is rdist:
        return A
    # replicated -> distributed: local filter
    if src is STAR:
        Sr = dist_stride(rdist, r, c)
        lc = ix.max_local_length(n, Sr)
        loc = _filter_dim(A.local, 1, Sr, ix.shift(rank_of(rdist, r, c), 0, Sr), lc)
        return DistMatrix(loc, A.gshape, A.cdist, rdist, A.calign, 0, g)
    # distributed -> replicated: gather
    if rdist is STAR:
        loc = _gather_dim(A.local, 1, src, A.ralign, n, r, c)
        return DistMatrix(loc, A.gshape, A.cdist, STAR, A.calign, 0, g)
    # V* <-> M* partial ladder on dim 1
    out = _partial_ladder(A, dim=1, src=src, dst=rdist)
    if out is not None:
        return out
    return None


def _coldim_change(A: DistMatrix, cdist: Dist) -> DistMatrix | None:
    g = A.grid
    r, c = g.height, g.width
    m, n = A.gshape
    src = A.cdist
    if src is cdist:
        return A
    if src is STAR:
        Sc = dist_stride(cdist, r, c)
        lr = ix.max_local_length(m, Sc)
        loc = _filter_dim(A.local, 0, Sc, ix.shift(rank_of(cdist, r, c), 0, Sc), lr)
        return DistMatrix(loc, A.gshape, cdist, A.rdist, 0, A.ralign, g)
    if cdist is STAR:
        loc = _gather_dim(A.local, 0, src, A.calign, m, r, c)
        return DistMatrix(loc, A.gshape, STAR, A.rdist, 0, A.ralign, g)
    out = _partial_ladder(A, dim=0, src=src, dst=cdist)
    if out is not None:
        return out
    return None


def _partial_ladder(A: DistMatrix, dim: int, src: Dist, dst: Dist) -> DistMatrix | None:
    """[VC,*]<->[MC,*] / [VR,*]<->[MR,*] partial gathers/filters (zero align).

    VC refines MC (q_vc = mc + r*mr), VR refines MR (q_vr = mr + c*mc):
      * V -> M: all_gather the co-axis, interleave      (PartialColAllGather)
      * M -> V: pure-local cyclic sub-selection         (PartialColFilter)
    """
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    extent = A.gshape[dim]
    if (src, dst) == (VC, MC) or (src, dst) == (VR, MR):
        axes = ("mr",) if src is VC else ("mc",)
        nblocks = c if src is VC else r
        coarse = r if src is VC else c
        l_out = ix.max_local_length(extent, coarse)
        loc = _partial_gather_dim(A.local, dim, axes, nblocks, l_out)
        return _retag(A, dim, dst, loc)
    if (src, dst) == (MC, VC) or (src, dst) == (MR, VR):
        nblocks = c if dst is VC else r
        sub = lax.axis_index("mr") if dst is VC else lax.axis_index("mc")
        l_out = ix.max_local_length(extent, p)
        loc = _partial_filter_dim(A.local, dim, nblocks, sub, l_out)
        return _retag(A, dim, dst, loc)
    if {src, dst} == {VC, VR}:
        loc = _vc_vr_permute(A.local, src, r, c)
        return _retag(A, dim, dst, loc)
    return None


def _vc_vr_permute(x, src: Dist, r: int, c: int):
    """[VC,*] <-> [VR,*]: a pure block permutation between the two 1-D rank
    orderings (the reference does this with a single pairwise SendRecv --
    ``copy::Exchange`` inside ``src/blas_like/level1/Copy/``); here one
    ``lax.ppermute`` over the flattened ('mc','mr') axis (linear index
    mc*c + mr, first name major).

    VC rank v lives on device (mc=v%r, mr=v//r); VR rank v on
    (mc=v//c, mr=v%c).  The residue class {i : i%p == v} moves wholesale
    from its VC owner to its VR owner (or back).
    """
    p = r * c
    if p == 1 or r == 1 or c == 1:
        return x
    # linear device index under ('mc','mr') = mc*c + mr; note VR rank v lives
    # on (mc=v//c, mr=v%c), i.e. the linear device index IS the VR rank.
    vc_dev = [(v % r) * c + v // r for v in range(p)]   # device holding VC rank v
    if src is VC:
        perm = [(vc_dev[v], v) for v in range(p)]
    else:
        perm = [(v, vc_dev[v]) for v in range(p)]
    return lax.ppermute(x, ("mc", "mr"), perm)


def _retag(A: DistMatrix, dim: int, d: Dist, loc) -> DistMatrix:
    if dim == 0:
        return DistMatrix(loc, A.gshape, d, A.rdist, 0, A.ralign, A.grid)
    return DistMatrix(loc, A.gshape, A.cdist, d, A.calign, 0, A.grid)


# ---------------------------------------------------------------------
# one-shot direct path (ISSUE 12 -- the COSTA plan compiler in .plan):
# static chain-cost mirror, the shard_map executor for a compiled
# RedistPlan, and the per-call chain-vs-direct arbitration
# ---------------------------------------------------------------------

def _fused_steps(src, dst, r, c):
    """Steps of the fused fast paths of :func:`_fused_dispatch`, as
    (kind, participants, moving-block dist pair) tuples -- None when no
    fused kernel dispatches (mirrors its conditions exactly)."""
    if src in ((MC, MR), (MR, MC)) and dst == (STAR, STAR):
        if r > 1 and c > 1:
            return [("ag", r * c, src)]
        return None                         # 1-D grid: generic route
    fused_v = {((MC, MR), (VC, STAR)), ((VC, STAR), (MC, MR)),
               ((MR, MC), (VR, STAR)), ((VR, STAR), (MR, MC)),
               ((MC, MR), (STAR, VR)), ((STAR, VR), (MC, MR)),
               ((MR, MC), (STAR, VC)), ((STAR, VC), (MR, MC))}
    if (src, dst) in fused_v:
        # the fused M<->V kernels a2a over the axis the V dist refines
        # ALONG: c participants when VC is the V endpoint, r when VR
        vs = [d for pair in (src, dst) for d in pair if d in (VC, VR)]
        return [("a2a", c if vs[0] is VC else r, src)]
    return None


def _dim_steps(pair, dim, new, r, c):
    """Steps of a single-dim change (:func:`_rowdim_change` /
    :func:`_coldim_change` + the partial ladder), or None (no fast path)."""
    src_d = pair[dim]
    p = r * c
    if src_d is new:
        return []
    if src_d is STAR:
        return [("local", 1, pair)]
    if new is STAR:
        S = dist_stride(src_d, r, c)
        return [("ag", S, pair)] if S > 1 else [("local", 1, pair)]
    if (src_d, new) in ((VC, MC), (VR, MR)):
        nb = c if src_d is VC else r
        return [("ag", nb, pair)] if nb > 1 else [("local", 1, pair)]
    if (src_d, new) in ((MC, VC), (MR, VR)):
        return [("local", 1, pair)]
    if {src_d, new} == {VC, VR}:
        if p == 1 or r == 1 or c == 1:
            return [("local", 1, pair)]
        return [("ppermute", p, pair)]
    return None


def _chain_steps(src, dst, r, c):
    """Static mirror of :func:`to_dist`'s zero-aligned dispatch: the
    ordered (kind, participants, block pair) collective steps the chained
    route runs for ``src -> dst``.  Purely metadata -- nothing traces."""
    if src == dst:
        return []
    steps = _fused_steps(src, dst, r, c)
    if steps is not None:
        return steps
    if src[0] is dst[0]:
        steps = _dim_steps(src, 1, dst[1], r, c)
        if steps is not None:
            return steps
    if src[1] is dst[1]:
        steps = _dim_steps(src, 0, dst[0], r, c)
        if steps is not None:
            return steps
    route = _CHAINS.get((src, dst))
    if route is not None:
        steps, cur = [], src
        for hop in route:
            steps += _chain_steps(cur, hop, r, c)
            cur = hop
        return steps
    # generic fallback: per-dim gathers through [STAR,STAR], local filter
    steps = []
    for dim, pair in ((0, src), (1, (STAR, src[1]))):
        if pair[dim] is MD:
            steps.append(("ag", r * c, pair))
        elif dist_stride(pair[dim], r, c) > 1:
            steps.append(("ag", dist_stride(pair[dim], r, c), pair))
    return steps


@lru_cache(maxsize=None)
def chain_cost(src, dst, gshape, grid_shape, itemsize):
    """(collective_rounds, ring-model bytes received per device) of the
    CHAINED route for a zero-aligned ``src -> dst`` -- the comparison
    the direct plan is arbitrated against (and the payload of the EL002
    rewrite hint)."""
    src, dst = tuple(src), tuple(dst)
    r, c = grid_shape
    m, n = gshape
    if src == dst or r * c == 1:
        return 0, 0
    rounds, total = 0, 0
    for kind, S, pair in _chain_steps(src, dst, r, c):
        if kind == "local" or S <= 1:
            continue
        b = (itemsize * ix.max_local_length(m, dist_stride(pair[0], r, c))
             * ix.max_local_length(n, dist_stride(pair[1], r, c)))
        rounds += 1
        if kind == "ag":
            total += b * (S - 1)
        elif kind == "a2a":
            total += b * (S - 1) // S
        else:                                  # ppermute
            total += b
    return rounds, total


def direct_plan_for(A: DistMatrix, cdist: Dist, rdist: Dist,
                    calign: int = 0, ralign: int = 0):
    """The compiled one-shot plan for this redistribution (alignments
    included since phase 2), or None when no plan applies (a no-op, or
    an MD endpoint at nonzero alignments -- which ``to_dist`` rejects)."""
    return compile_plan(A.dist, (cdist, rdist), A.gshape,
                        (A.grid.height, A.grid.width),
                        (A.calign, A.ralign), (calign, ralign))


def _machine_terms(grid_shape=None):
    """(latency_s, bw_bytes_per_s) for the running backend.

    Measured ``redist_constants/v1`` recorded by ``perf.redist_bench
    --record`` for this (grid, backend) take precedence over the static
    :mod:`..tune.cost_model` ring model; safe TPU-ish defaults when the
    tune subsystem is unavailable."""
    backend = jax.default_backend()
    if grid_shape is not None:
        try:
            from ..tune.cache import load_redist_constants
            doc = load_redist_constants(tuple(grid_shape), backend)
        except Exception:
            doc = None
        if doc is not None:
            return float(doc["alpha_s"]), float(doc["bw_bytes_per_s"])
    try:
        from ..tune.cost_model import machine_for
        mm = machine_for(backend)
        return mm.latency_s, mm.bw_bytes_per_s
    except Exception:
        return 2e-6, 4.5e10


def _direct_wins(plan, gshape, itemsize) -> bool:
    """``path='auto'`` arbitration: alpha-beta (latency x rounds +
    bytes / bandwidth) comparison of the one-shot plan against the
    chained route, using the measured per-(grid, backend) constants when
    ``redist_bench --record`` has written them; ties go to the chain
    (the bit-identical default)."""
    rounds_c, bytes_c = chain_cost(plan.src, plan.dst, gshape,
                                   plan.grid_shape, itemsize)
    if rounds_c == 0:
        return False
    lat, bw = _machine_terms(plan.grid_shape)
    t_direct = lat * plan.rounds + plan.wire_bytes(itemsize) / bw
    t_chain = lat * rounds_c + bytes_c / bw
    return t_direct < t_chain


def _direct_exec(x, plan, wire, dt):
    """Execute a compiled RedistPlan inside shard_map: static-map gather
    -> one collective (or none) -> static-map scatter onto zeros.

    The (p, K, R)/(p, K, C) tables become jaxpr constants; each device
    selects its row by ``axis_index``.  Sentinel indices (== the local
    extent) mask to zero on the gather and drop on the scatter, which
    keeps the padding-is-zero storage invariant without data-dependent
    shapes.  ``wire='int8'`` block-scale-packs each slot (vmap of the
    :mod:`.quantize` codec) so the ONE collective moves int8; bf16 is
    cast by the caller around this function."""
    r, c = plan.grid_shape
    dev = lax.axis_index("mc") * c + lax.axis_index("mr")
    sr = jnp.take(jnp.asarray(plan.send_rows), dev, axis=0)     # (K, R)
    sc = jnp.take(jnp.asarray(plan.send_cols), dev, axis=0)     # (K, C)
    lr_s, lc_s = plan.src_local
    ok = (sr < lr_s)[:, :, None] & (sc < lc_s)[:, None, :]
    vals = x[jnp.clip(sr, 0, lr_s - 1)[:, :, None],
             jnp.clip(sc, 0, lc_s - 1)[:, None, :]]
    vals = jnp.where(ok, vals, 0)                               # (K, R, C)
    R, C = plan.slot_shape
    q8 = wire == "int8" and plan.kind != "local"
    if q8:
        vals = jax.vmap(lambda s: q8_pack(s, QUANT_TILE))(vals)
    if plan.kind == "a2a":
        # ragged subgroup a2a: the plan's equal-size participant groups
        # (or None for the full comm product); the K* slots are addressed
        # by GROUP position, which the remapped index tables encode
        gg = [list(g) for g in plan.groups] if plan.groups else None
        recv = lax.all_to_all(vals, plan.comm_axes, split_axis=0,
                              concat_axis=0, axis_index_groups=gg)
    elif plan.kind == "ppermute":
        recv = lax.ppermute(vals, plan.comm_axes, list(plan.perm))
    else:
        recv = vals
    if q8:
        recv = jax.vmap(lambda s: q8_unpack(s, (R, C), dt, QUANT_TILE))(recv)
    rr = jnp.take(jnp.asarray(plan.recv_rows), dev, axis=0)
    rc = jnp.take(jnp.asarray(plan.recv_cols), dev, axis=0)
    out = jnp.zeros(plan.dst_local, recv.dtype)
    return out.at[rr[:, :, None], rc[:, None, :]].set(recv, mode="drop")


@partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _redistribute_direct_jit(A: DistMatrix, cdist: Dist, rdist: Dist,
                             calign: int = 0, ralign: int = 0,
                             wire=None) -> DistMatrix:
    plan = compile_plan(A.dist, (cdist, rdist), A.gshape,
                        (A.grid.height, A.grid.width),
                        (A.calign, A.ralign), (calign, ralign))
    out_meta = DistMatrix(None, A.gshape, cdist, rdist, calign, ralign,
                          A.grid)
    dt = A.dtype

    def f(a):
        x = a.local
        if wire == "bf16":
            x = x.astype(jnp.bfloat16)
        loc = _direct_exec(x, plan, wire, dt)
        loc = loc.astype(dt)
        return DistMatrix(loc, A.gshape, cdist, rdist, calign, ralign,
                          A.grid)

    return shard_map(
        f, mesh=A.grid.mesh, in_specs=(A.spec,), out_specs=out_meta.spec,
        check_vma=False,
    )(A)


# ---------------------------------------------------------------------
# quantized wire precision (the ``comm_precision`` knob, ISSUE 8 --
# EQuARX direction, PAPERS.md 2506.17615): encode the payload narrow,
# run the SAME collective schedule on it, decode on the far side.  The
# codec lives in :mod:`.quantize`; this section is the engine routing.
# ---------------------------------------------------------------------

#: wire dtype names recorded on RedistRecord per resolved mode
_WIRE_DTYPES = {"bf16": "bfloat16", "int8": "int8"}

#: dists the fused int8 gather kernels understand (MD's slot permutation
#: and CIRC's eager bridge stay full precision)
_Q8_DISTS = frozenset({MC, MR, VC, VR, STAR})


def _wire_mode(A: DistMatrix, mode, q8_ok: bool):
    """Resolve a requested ``comm_precision`` to the wire mode actually
    run: ``None`` (bit-identical full precision), ``'bf16'``, or
    ``'int8'``.

    ``None`` is returned -- regardless of the request -- whenever
    quantization could not save a byte or would corrupt a non-codec
    payload: 1x1 grids (collectives elide), non-real-float dtypes, and
    replicated sources (pure-local filters).  ``'int8'`` requires a
    dedicated fused kernel (``q8_ok``: the gather-to-replicated family
    and ``panel_spread``); elsewhere the request degrades to the
    accuracy-SAFER ``'bf16'`` cast, which every pair supports."""
    check_comm_precision(mode)
    if mode is None:
        return None
    if A.grid.size == 1 or not quantizable(A.dtype):
        return None
    if A.dist == (STAR, STAR):
        return None                  # replicated source: pure local filter
    if mode == "int8":
        return "int8" if q8_ok else "bf16"
    return "bf16"


def _q8_gather_blocks(x, axes, tile: int):
    """all_gather whole per-device blocks at int8 wire precision: pack
    (payload + bitcast scales, one array), ONE collective, per-source
    decode.  Returns the ``(S, *x.shape)`` stack the interleave math of
    the full-precision kernels consumes unchanged."""
    packed = q8_pack(x, tile)
    gx = lax.all_gather(packed, axes, axis=0)
    return jax.vmap(lambda b: q8_unpack(b, x.shape, x.dtype, tile))(gx)


def _gather_dim_q8(x, dim: int, d: Dist, extent: int, r: int, c: int,
                   tile: int):
    """Zero-aligned :func:`_gather_dim` with an int8 block-scaled wire."""
    S = dist_stride(d, r, c)
    if S == 1:
        return lax.slice_in_dim(x, 0, extent, axis=dim)
    g = _q8_gather_blocks(x, gather_axes(d), tile)
    g = jnp.moveaxis(g, 0, dim + 1)
    shape = list(x.shape)
    shape[dim] = x.shape[dim] * S
    g = g.reshape(shape)
    return lax.slice_in_dim(g, 0, extent, axis=dim)


def _to_star_star_q8(A: DistMatrix, tile: int) -> DistMatrix:
    """:func:`to_star_star` at int8 wire precision -- same collective
    rounds (the fused 2-D gather when available, per-dim otherwise),
    ~4x fewer bytes on the wire."""
    g = A.grid
    r, c = g.height, g.width
    m, n = A.gshape
    x = A.local
    if A.dist in ((MC, MR), (MR, MC)) and r > 1 and c > 1:
        lr, lc = x.shape
        G = _q8_gather_blocks(x, ("mc", "mr"), tile).reshape(r, c, lr, lc)
        if A.dist == (MC, MR):
            full = G.transpose(2, 0, 3, 1).reshape(lr * r, lc * c)
        else:
            full = G.transpose(2, 1, 3, 0).reshape(lr * c, lc * r)
        full = lax.slice(full, (0, 0), (m, n))
        return DistMatrix(full, A.gshape, STAR, STAR, 0, 0, g)
    xg = _gather_dim_q8(x, 0, A.cdist, m, r, c, tile)
    xg = _gather_dim_q8(xg, 1, A.rdist, n, r, c, tile)
    return DistMatrix(xg, A.gshape, STAR, STAR, 0, 0, g)


@partial(jax.jit, static_argnums=(1,))
def _redistribute_q8_jit(A: DistMatrix, tile: int) -> DistMatrix:
    out_meta = DistMatrix(None, A.gshape, STAR, STAR, 0, 0, A.grid)

    def f(a):
        return _to_star_star_q8(a, tile)

    return shard_map(
        f, mesh=A.grid.mesh, in_specs=(A.spec,), out_specs=out_meta.spec,
        check_vma=False,
    )(A)


# ---------------------------------------------------------------------
# fused panel spread ([VC,STAR] -> the [MC,STAR]/[STAR,MR] operand pair)
# ---------------------------------------------------------------------

def _panel_spread_to_pair(A: DistMatrix, conj: bool):
    """Inside shard_map: one (m, k) [VC,STAR] panel -> its [MC,STAR] spread
    AND its [STAR,MR] adjoint, in ONE collective round.

    A single all_gather over the flattened ('mr','mc') axis rebuilds the
    full panel on every device; both outputs are then pure-local filters
    (plus the free local transpose for the adjoint).  The separate-call
    route costs three collective rounds: the [MC,STAR] partial gather, the
    VC->VR ppermute and the VR->MR partial gather of the adjoint chain.
    The panels here are thin (k = nb << m), so they are latency-bound and
    one full-panel round beats three partial rounds despite moving
    ~m*k instead of ~m*k*(1/r + 1/c) per device -- the collective-fusion
    trade of the array-redistribution literature (PAPERS.md 2112.01075).
    """
    g = A.grid
    r, c = g.height, g.width
    m, k = A.gshape
    full = _gather_dim(A.local, 0, VC, 0, m, r, c)        # replicated (m, k)
    mc = _from_star_star(full, (m, k), MC, STAR, 0, 0, g)
    adj = full.T
    if conj:
        adj = jnp.conj(adj)
    mr = _from_star_star(adj, (k, m), STAR, MR, 0, 0, g)
    return mc, mr


def _panel_spread_to_pair_q8(A: DistMatrix, conj: bool, tile: int):
    """:func:`_panel_spread_to_pair` at int8 wire precision: the one
    all_gather moves the packed block-scaled panel, both outputs decode
    locally -- same single collective round."""
    g = A.grid
    r, c = g.height, g.width
    m, k = A.gshape
    full = _gather_dim_q8(A.local, 0, VC, m, r, c, tile)
    mc = _from_star_star(full, (m, k), MC, STAR, 0, 0, g)
    adj = full.T
    if conj:
        adj = jnp.conj(adj)
    mr = _from_star_star(adj, (k, m), STAR, MR, 0, 0, g)
    return mc, mr


@partial(jax.jit, static_argnums=(1, 2))
def _panel_spread_jit(A: DistMatrix, conj: bool, wire=None):
    g = A.grid
    m, k = A.gshape
    dt = A.dtype
    mc_meta = DistMatrix(None, (m, k), MC, STAR, 0, 0, g)
    mr_meta = DistMatrix(None, (k, m), STAR, MR, 0, 0, g)

    def f(a):
        if wire == "int8":
            return _panel_spread_to_pair_q8(a, conj, QUANT_TILE)
        if wire == "bf16":
            a = a.with_local(a.local.astype(jnp.bfloat16))
        mc, mr = _panel_spread_to_pair(a, conj)
        if wire == "bf16":
            mc = mc.with_local(mc.local.astype(dt))
            mr = mr.with_local(mr.local.astype(dt))
        return mc, mr

    return shard_map(
        f, mesh=g.mesh, in_specs=(A.spec,),
        out_specs=(mc_meta.spec, mr_meta.spec), check_vma=False,
    )(A)


def panel_spread(A: DistMatrix, conj: bool = True, comm_precision=None):
    """``(A -> [MC,STAR],  op(A)^T -> [STAR,MR])`` for a zero-aligned
    [VC,STAR] panel, fused into a single collective round.

    The hot move of the Hermitian rank-k family: ``cholesky``'s trailing
    update and ``herk``/``her2k``'s per-panel chain all need exactly this
    operand pair for the ``LocalTrrk`` storage matmul.  ``conj=True``
    (default) produces the conjugate-transposed adjoint (``A^H``);
    ``conj=False`` the plain transpose (the ``syrk`` form).

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'``) selects the
    wire precision of the one collective (see :mod:`.quantize` and
    :func:`redistribute`): the panel is encoded narrow, gathered, and
    decoded back to its compute dtype on every device -- 2x/4x fewer
    bytes at the same round count.  ``None`` (default) is the
    bit-identical full-precision path."""
    if A.dist != (VC, STAR) or (A.calign, A.ralign) != (0, 0):
        raise ValueError(f"panel_spread needs a zero-aligned [VC,STAR] "
                         f"panel, got {A}")
    REDIST_COUNTS["panel_spread"] += 1
    wire = _wire_mode(A, comm_precision, q8_ok=True)
    mc, mr = _panel_spread_jit(A, conj, wire)
    if _FAULT_INJECTOR is not None:
        lmc, lmr = _FAULT_INJECTOR.apply("panel_spread",
                                         (mc.local, mr.local))
        mc, mr = mc.with_local(lmc), mr.with_local(lmr)
    _trace_record("panel_spread", A.dist, ((MC, STAR), (STAR, MR)),
                  A.gshape, A.dtype, A.local, (mc.local, mr.local),
                  grid_shape=(A.grid.height, A.grid.width),
                  wire_dtype=_WIRE_DTYPES.get(wire))
    return mc, mr


# ---------------------------------------------------------------------
# batched storage-level row permutations (the COSTA-style one-shot plan)
# ---------------------------------------------------------------------

def _storage_row_of(i, S: int, lr: int):
    """Storage row of global row i for a stride-S zero-aligned column dim
    (stacked-storage layout: slot-major, then local offset)."""
    if S == 1:
        return i
    return (i % S) * lr + i // S


def move_rows(A: DistMatrix, targets, sources, valid) -> DistMatrix:
    """Move global rows ``sources`` to positions ``targets`` in ONE
    storage-level gather/scatter pass, dropping entries where ``valid`` is
    False (sentinel padding).

    The batched-permutation fast path of the engine (COSTA direction,
    PAPERS.md 2106.06601): a panel's composed pivot permutation -- nb
    tournament winners plus the <= nb rows they displace, or partial
    pivoting's <= 2 nb moved rows -- is applied as a single collective
    plan on the stacked storage instead of a per-row swap chain.  The
    storage row map is a bijection between slots and virtual indices, so
    invalid slots are forced out of range rather than trusting the
    sentinel's arithmetic image.  No named collective is issued: the
    cross-device row motion lowers through GSPMD's partitioner, so the
    comm-plan analyzer sees the swap phase as zero explicit rounds
    (``REDIST_COUNTS['row_permute']`` still counts the entry calls)."""
    REDIST_COUNTS["row_permute"] += 1
    S, lr = A.col_stride, A.local_rows
    m = A.gshape[0]
    sidx = _storage_row_of(jnp.clip(targets, 0, m - 1), S, lr)
    sidx = jnp.where(valid, sidx, S * lr)          # OOB => scatter drops
    gsrc = _storage_row_of(jnp.clip(sources, 0, m - 1), S, lr)
    stor = A.local
    rows = jnp.take(stor, gsrc, axis=0)
    out = A.with_local(stor.at[sidx].set(rows, mode="drop"))
    # observer seam (ISSUE 12): the obs tracer must see this entry's wire
    # traffic (<= moved rows x local row width, worst case all cross-chip)
    # even though GSPMD plans the motion -- observers_only keeps it OUT of
    # the comm-plan golden aggregation, which pins explicit rounds
    k = int(targets.shape[0])
    _trace_record("row_permute", A.dist, A.dist, (k, A.gshape[1]),
                  A.dtype, A.local, (out.local,),
                  grid_shape=(A.grid.height, A.grid.width),
                  path="storage", rounds=0,
                  wire_bytes=k * stor.shape[1] * jnp.dtype(A.dtype).itemsize,
                  observers_only=True)
    return out


def permute_rows_storage(A: DistMatrix, perm, inverse: bool = False
                         ) -> DistMatrix:
    """``B[i] = A[perm[i]]`` as ONE storage-level gather for a zero-aligned
    row-cyclic matrix (full-permutation sibling of :func:`move_rows`).

    Replaces the historical [STAR,VR] round trip (two collective rounds:
    demote + promote) with a single storage gather whose cross-device
    motion GSPMD plans directly -- the engine-level fast path behind
    ``lapack.lu.permute_rows``."""
    if (A.calign, A.ralign) != (0, 0):
        raise ValueError(f"permute_rows_storage needs zero alignments, got {A}")
    REDIST_COUNTS["row_permute"] += 1
    p = jnp.argsort(perm) if inverse else perm
    m = A.gshape[0]
    S, lr = A.col_stride, A.local_rows
    if S == 1:
        res = A.with_local(jnp.take(A.local, p, axis=0))
    else:
        sr = jnp.arange(S * lr)
        gi = (sr % lr) * S + sr // lr              # global row of storage slot
        src = _storage_row_of(p[jnp.clip(gi, 0, m - 1)], S, lr)
        out = jnp.take(A.local, src, axis=0)
        out = jnp.where((gi < m)[:, None], out, 0)  # keep padding zeroed
        res = A.with_local(out)
    # observer seam (ISSUE 12): surface the GSPMD-planned full-permutation
    # motion to the obs tracer (worst case the whole local block crosses
    # chips); observers_only keeps it out of the round-pinning goldens
    _trace_record("row_permute", A.dist, A.dist, A.gshape, A.dtype,
                  A.local, (res.local,),
                  grid_shape=(A.grid.height, A.grid.width),
                  path="storage", rounds=0,
                  wire_bytes=int(A.local.size) * jnp.dtype(A.dtype).itemsize,
                  observers_only=True)
    return res


# ---------------------------------------------------------------------
# transpose-dist ([U,V] -> [V,U] with local transpose; free)
# ---------------------------------------------------------------------

def transpose_dist(A: DistMatrix, conj: bool = False) -> DistMatrix:
    """A^T tagged [rdist, cdist] -- Elemental's ``copy::TransposeDist``."""
    loc = A.local.T
    if conj:
        loc = jnp.conj(loc)
    m, n = A.gshape
    return DistMatrix(loc, (n, m), A.rdist, A.cdist, A.ralign, A.calign, A.grid)


# ---------------------------------------------------------------------
# Contract / SumScatter (partial products -> distributed sum)
# ---------------------------------------------------------------------

def contract(A: DistMatrix, cdist: Dist, rdist: Dist) -> DistMatrix:
    """Sum partial contributions held per-device and land on [cdist,rdist].

    The reference's ``Contract``/``AxpyContract`` (``src/blas_like/level1/
    Contract.cpp``): e.g. partial [MC,STAR] -> [MC,MR] is a ReduceScatter
    over the MR comm; here ``lax.psum_scatter`` after a local residue-block
    rearrangement (cyclic target layout).  Zero alignments.
    """
    g = A.grid
    r, c = g.height, g.width
    m, n = A.gshape
    src = (A.cdist, A.rdist)
    dst = (cdist, rdist)
    if src == (MC, STAR) and dst == (MC, MR):
        loc = _scatter_sum_dim(A.local, 1, "mr", c, ix.max_local_length(n, c))
        return DistMatrix(loc, A.gshape, MC, MR, A.calign, 0, g)
    if src == (STAR, MR) and dst == (MC, MR):
        loc = _scatter_sum_dim(A.local, 0, "mc", r, ix.max_local_length(m, r))
        return DistMatrix(loc, A.gshape, MC, MR, 0, A.ralign, g)
    if src == (MR, STAR) and dst == (MR, MC):
        loc = _scatter_sum_dim(A.local, 1, "mc", r, ix.max_local_length(n, r))
        return DistMatrix(loc, A.gshape, MR, MC, A.calign, 0, g)
    if src == (STAR, MC) and dst == (MR, MC):
        loc = _scatter_sum_dim(A.local, 0, "mr", c, ix.max_local_length(m, c))
        return DistMatrix(loc, A.gshape, MR, MC, 0, A.ralign, g)
    if src == (STAR, STAR) and dst == (MC, MR):
        loc = _scatter_sum_dim(A.local, 0, "mc", r, ix.max_local_length(m, r))
        loc = _scatter_sum_dim(loc, 1, "mr", c, ix.max_local_length(n, c))
        return DistMatrix(loc, A.gshape, MC, MR, 0, 0, g)
    if src == (STAR, STAR) and dst == (STAR, STAR):
        # partial replicated -> full sum everywhere
        loc = lax.psum(lax.psum(A.local, "mc"), "mr")
        return DistMatrix(loc, A.gshape, STAR, STAR, 0, 0, g)
    if src == (STAR, STAR) and dst == (VC, STAR):
        ss = contract(A, STAR, STAR)
        return to_dist(ss, VC, STAR)
    raise NotImplementedError(f"contract {src} -> {dst}")


def _scatter_sum_dim(x, dim: int, axis_name: str, S: int, l_out: int):
    """psum_scatter a replicated-partial dimension onto its cyclic owners."""
    if S == 1:
        return _pad_dim(x, dim, l_out)
    x = _pad_dim(x, dim, S * l_out)
    shape = list(x.shape)
    shape[dim : dim + 1] = [l_out, S]
    x = x.reshape(shape)                       # (..., l_out, S, ...)
    x = jnp.moveaxis(x, dim + 1, dim)          # (..., S, l_out, ...) residue-major
    shape2 = list(x.shape)
    shape2[dim : dim + 2] = [S * l_out]
    x = x.reshape(shape2)
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


# ---------------------------------------------------------------------
# public jit-able wrapper
# ---------------------------------------------------------------------

def redistribute(A: DistMatrix, cdist: Dist, rdist: Dist,
                 calign: int = 0, ralign: int = 0,
                 comm_precision=None, path=None) -> DistMatrix:
    """B[cdist,rdist] = A, as a standalone (jit-able) op on storage-form
    DistMatrix.  ``Copy(A, B)`` / ``operator=`` of the reference.

    jit-cached on (static metadata, dst dists, aligns): eager callers (tests,
    blocked loops run outside an enclosing jit) hit the compile cache instead
    of re-tracing a fresh ``shard_map`` closure per call.

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'``) opts this
    entry into a narrow wire encoding (:mod:`.quantize`): the payload is
    encoded inside the jitted shard_map, the collectives move the narrow
    dtype (the comm-plan analyzer sees the true wire bytes), and the
    result decodes back to the source dtype.  ``'bf16'`` applies to every
    pair; ``'int8'`` (block-scaled, packed scales, round-identical) has a
    fused kernel for the zero-aligned gather-to-[STAR,STAR] family and
    degrades to ``'bf16'`` elsewhere.  ``None`` (default) is the
    bit-identical full-precision path; the knob is a no-op on 1x1 grids,
    non-real-float payloads, and replicated sources (pure-local filters).

    ``path`` (see :data:`REDIST_PATHS`, ISSUE 12/13) selects the route:
    ``None``/``'chain'`` run the factored multi-hop dispatch (bit-identical
    to the historical engine); ``'direct'`` executes the ONE-SHOT compiled
    plan (:mod:`.plan` -- a single all_to_all/ppermute with static ragged
    gather/scatter index maps), which since phase 2 covers every legal
    pair at every legal alignment (MD included; CIRC endpoints compile to
    a costed bridge executed on the eager root path), falling back to the
    chain only for no-ops; ``'auto'`` compiles the plan and takes it only
    where the alpha-beta cost -- measured ``redist_constants/v1`` when
    ``perf.redist_bench --record`` has written them for this (grid,
    backend), the static ring model otherwise -- says it beats the chain
    (ties go to the chain).  Fallbacks increment the ``redist_fallbacks``
    obs counter and stamp ``RedistRecord.fallback_reason``.  On the
    direct route an ``'int8'`` ``comm_precision`` block-scale-packs every
    plan slot, so the narrow payload rides ANY pair's single collective
    -- not just the gather-to-[STAR,STAR] family.

    CIRC conversions (root-only storage) route their collective leg
    through the SAME compiled ``_redistribute_jit`` as every other pair
    (copy::Gather fuses to one gather chain to ``[STAR,STAR]``;
    copy::Scatter is a zero-collective local filter); only the root-edge
    ``device_put`` itself stays outside the shard_map."""
    _check_pair(cdist, rdist)
    if path not in REDIST_PATHS:
        raise ValueError(f"path must be one of {REDIST_PATHS}, got {path!r}")
    REDIST_COUNTS[(A.dist, (cdist, rdist))] += 1
    grid_shape = (A.grid.height, A.grid.width)
    circ = cdist is CIRC or A.cdist is CIRC
    noop = A.dist == (cdist, rdist) \
        and (A.calign, A.ralign) == (calign, ralign)
    plan = None
    fallback_reason = ""
    if path in ("direct", "auto"):
        if noop:
            fallback_reason = "noop"
        else:
            plan = direct_plan_for(A, cdist, rdist, calign, ralign)
            if plan is None:
                fallback_reason = "no_plan"
            elif path == "auto" and plan.kind != "bridge" and \
                    not _direct_wins(plan, A.gshape,
                                     jnp.dtype(A.dtype).itemsize):
                plan, fallback_reason = None, "arbitration"
    if fallback_reason:
        from ..obs import metrics as _metrics
        _metrics.inc("redist_fallbacks", reason=fallback_reason)
    if plan is not None and not circ:
        wire = None if plan.kind == "local" \
            else _wire_mode(A, comm_precision, q8_ok=True)
        out = _redistribute_direct_jit(A, cdist, rdist, calign, ralign, wire)
        if _FAULT_INJECTOR is not None:
            out = out.with_local(
                _FAULT_INJECTOR.apply("redistribute", (out.local,))[0])
        wire_sz = {"bf16": 2, "int8": 1}.get(wire, jnp.dtype(A.dtype).itemsize)
        _trace_record("redistribute", A.dist, (cdist, rdist), A.gshape,
                      A.dtype, A.local, (out.local,), grid_shape=grid_shape,
                      wire_dtype=_WIRE_DTYPES.get(wire), path="direct",
                      rounds=plan.rounds, wire_bytes=plan.wire_bytes(wire_sz))
        return out
    if circ:
        check_comm_precision(comm_precision)
        wire = None
        out = _redistribute_circ(A, cdist, rdist, calign, ralign)
    else:
        q8_ok = ((cdist, rdist) == (STAR, STAR)
                 and (calign, ralign) == (0, 0) and _zero_aligned(A)
                 and set(A.dist) <= _Q8_DISTS)
        wire = None if noop else _wire_mode(A, comm_precision, q8_ok)
        if wire == "int8":
            out = _redistribute_q8_jit(A, QUANT_TILE)
        else:
            out = _redistribute_jit(A, cdist, rdist, calign, ralign, wire)
    if _FAULT_INJECTOR is not None:
        out = out.with_local(
            _FAULT_INJECTOR.apply("redistribute", (out.local,))[0])
    if plan is not None:
        # CIRC bridge under 'direct'/'auto': executed by the eager root
        # path above, recorded as the direct route with the plan's
        # honest full-matrix cost (arbitration does not apply -- the
        # chain route IS the same eager bridge)
        _trace_record("redistribute", A.dist, (cdist, rdist), A.gshape,
                      A.dtype, A.local, (out.local,), grid_shape=grid_shape,
                      wire_dtype=_WIRE_DTYPES.get(wire), path="direct",
                      rounds=plan.rounds,
                      wire_bytes=plan.wire_bytes(jnp.dtype(A.dtype).itemsize))
        return out
    rounds = wire_bytes = -1
    if not circ and not noop and _zero_aligned(A) and (calign, ralign) == (0, 0):
        wire_sz = {"bf16": 2, "int8": 1}.get(wire, jnp.dtype(A.dtype).itemsize)
        rounds, wire_bytes = chain_cost(A.dist, (cdist, rdist), A.gshape,
                                        grid_shape, wire_sz)
    _trace_record("redistribute", A.dist, (cdist, rdist), A.gshape,
                  A.dtype, A.local, (out.local,),
                  grid_shape=grid_shape,
                  wire_dtype=_WIRE_DTYPES.get(wire), path="chain",
                  rounds=rounds, wire_bytes=wire_bytes,
                  fallback_reason=fallback_reason)
    return out


def _redistribute_circ(A: DistMatrix, cdist: Dist, rdist: Dist,
                       calign: int, ralign: int) -> DistMatrix:
    """CIRC endpoints via the JITTED shard_map path (ISSUE 14 satellite).

    PR 9-13 ran these through the eager global bridges (``to_global`` /
    ``from_global``: per-dimension index-map gathers executed op-by-op,
    whose implicit cross-device resharding paid a host sync at this
    edge -- the ROADMAP's ``'bridge'`` leftover).  Both directions now
    route every collective through the SAME compiled ``_redistribute_jit``
    as the non-CIRC pairs -- ``[STAR,STAR]`` storage IS the global array
    (identity index maps), so only a root ``device_put`` remains at the
    edge:

      * dst CIRC: ONE fused gather chain to ``[STAR,STAR]``, then a
        comm-free root-local ``device_put`` (``copy::Gather``);
      * src CIRC: root-broadcast ``device_put`` (``copy::Scatter``),
        then a ZERO-collective jitted local filter to the target pair.
    """
    import jax.sharding as jsh
    g = A.grid
    if A.cdist is CIRC and cdist is CIRC:
        return A
    if cdist is CIRC:
        star = _redistribute_jit(A, STAR, STAR, 0, 0, None)
        arr = jax.device_put(
            star.local, jsh.SingleDeviceSharding(g.mesh.devices.flat[0]))
        return DistMatrix(arr, A.gshape, CIRC, CIRC, 0, 0, g)
    # CIRC source: broadcast the root array, wrap it as [STAR,STAR]
    # (identity storage form), then filter locally inside the jitted path
    arr = jax.device_put(A.local, g.sharding(jax.sharding.PartitionSpec()))
    star = DistMatrix(arr, A.gshape, STAR, STAR, 0, 0, g)
    return _redistribute_jit(star, cdist, rdist, calign, ralign, None)


@partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _redistribute_jit(A: DistMatrix, cdist: Dist, rdist: Dist,
                      calign: int, ralign: int, wire=None) -> DistMatrix:
    out_meta = DistMatrix(None, A.gshape, cdist, rdist, calign, ralign, A.grid)
    dt = A.dtype

    def f(a):
        # bf16 wire: the cast sits INSIDE the traced program, so every
        # collective of the chain moves bfloat16 (half the bytes) and the
        # jaxpr-level analyzer reads the true payload dtype off the
        # collective operand
        if wire == "bf16":
            a = a.with_local(a.local.astype(jnp.bfloat16))
        out = to_dist(a, cdist, rdist, calign, ralign)
        if wire == "bf16":
            out = out.with_local(out.local.astype(dt))
        return out

    return shard_map(
        f, mesh=A.grid.mesh, in_specs=(A.spec,), out_specs=out_meta.spec,
        check_vma=False,
    )(A)
