"""Block-scaled wire codecs for the quantized-collective path (ISSUE 8).

The EQuARX direction (PAPERS.md, arXiv 2506.17615): collectives inside a
distributed factorization are bandwidth-bound, and a block-quantized
payload moves 2-4x fewer bytes at negligible quality loss -- provided the
compute on either side stays full precision and an outer residual
certificate (``resilience.certified_solve``) guards the result.  This
module holds the pure per-device codec; the engine
(:mod:`.engine`) decides WHERE it runs (encode before the collective,
decode on the far side).

Two wire modes (the ``comm_precision`` knob vocabulary):

``'bf16'``
    a plain cast: 2x fewer bytes, ~3 decimal digits of mantissa.  Applied
    around any redistribution pair (the cast happens inside the engine's
    jitted shard_map, so the collective operand in the traced program IS
    bfloat16 -- the comm-plan analyzer and cost model see the true wire
    bytes).

``'int8'``
    block-scaled integer quantization: per :data:`QUANT_TILE`-sized local
    tile, ``scale = amax / 127`` and ``q = round(x / scale)`` -- ~4x fewer
    bytes at ~``amax_tile / 127`` absolute error per element (the
    documented bound, pinned by ``tests/core/test_comm_precision.py``).
    The f32 scales are BITCAST-PACKED into extra int8 rows of the payload
    (:func:`q8_pack`), so the whole encoded shard still travels in ONE
    collective -- round counts stay identical to the unquantized schedule.

Non-finite contract: NaN/Inf inputs are NEVER masked to finite values.
The per-tile ``amax`` of a tile containing a non-finite entry is itself
non-finite, so the tile's scale -- and therefore every decoded element of
that tile -- is non-finite: the resilience health guards still see the
corruption (tile-granular, not element-exact).

Scope: the codec applies to real float32/float64 payloads.  Complex,
integer, and already-narrow dtypes pass through at full precision (the
engine's ``_wire_mode`` gate).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

#: legal values of the ``comm_precision`` knob (``None`` = full precision,
#: the bit-identical zero-overhead path)
COMM_PRECISIONS = (None, "bf16", "int8")

#: side of the square local tiles the int8 scales are computed over.  64
#: divides every grain-aligned nb the blocked drivers use (the NB_LADDER
#: floor), so panels tile evenly; scales add ~4/64^2 relative bytes.
QUANT_TILE = 64


def check_comm_precision(mode) -> None:
    """Raise ValueError on an illegal ``comm_precision`` value."""
    if mode not in COMM_PRECISIONS:
        raise ValueError(
            f"comm_precision must be one of {COMM_PRECISIONS}, got {mode!r}")


def quantizable(dtype) -> bool:
    """True when the codec applies: real float32/float64 payloads."""
    return jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.float64))


def _tile_counts(shape, tile: int):
    lr, lc = shape
    return -(-lr // tile), -(-lc // tile)


def q8_encode(x, tile: int = QUANT_TILE):
    """Block-scaled int8 quantization of a 2-D block.

    Returns ``(q, scales)``: ``q`` int8 with ``x``'s shape, ``scales``
    float32 of shape ``(ceil(lr/tile), ceil(lc/tile))``.  Zero tiles get
    scale 1 (exact zeros round-trip); non-finite tiles get a non-finite
    scale (see module docstring)."""
    lr, lc = x.shape
    tr, tc = _tile_counts(x.shape, tile)
    xp = jnp.pad(x, ((0, tr * tile - lr), (0, tc * tile - lc)))
    xb = xp.reshape(tr, tile, tc, tile)
    amax = jnp.max(jnp.abs(xb), axis=(1, 3)).astype(jnp.float32)
    # keep NaN/Inf amax (NaN == 0 is False): the scale must stay
    # non-finite so decode cannot mask a corrupted tile
    scale = jnp.where(amax == 0, jnp.float32(1), amax) / jnp.float32(127)
    q = jnp.round(xb / scale[:, None, :, None].astype(x.dtype))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q.reshape(tr * tile, tc * tile)[:lr, :lc], scale


def q8_decode(q, scales, dtype, tile: int = QUANT_TILE):
    """Inverse of :func:`q8_encode` (up to the documented error bound)."""
    lr, lc = q.shape
    tr, tc = _tile_counts(q.shape, tile)
    qp = jnp.pad(q, ((0, tr * tile - lr), (0, tc * tile - lc)))
    qb = qp.reshape(tr, tile, tc, tile).astype(jnp.float32)
    xb = qb * scales[:, None, :, None]
    return xb.reshape(tr * tile, tc * tile)[:lr, :lc].astype(dtype)


def q8_packed_rows(shape, tile: int = QUANT_TILE) -> int:
    """Rows of a :func:`q8_pack` payload for a ``shape`` block (static)."""
    lr, lc = shape
    tr, tc = _tile_counts(shape, tile)
    return lr + -(-tr * tc * 4 // lc)


def q8_pack(x, tile: int = QUANT_TILE):
    """Encode + pack one block into a single int8 wire array.

    The f32 scales are bitcast to int8 and appended as whole extra rows
    below the payload, so the encoded shard travels through the SAME
    collective as the data (whole local blocks move intact in every
    engine gather kernel) -- one round, ~4x fewer bytes."""
    lr, lc = x.shape
    q, scales = q8_encode(x, tile)
    sraw = lax.bitcast_convert_type(scales.reshape(-1), jnp.int8).reshape(-1)
    srows = -(-sraw.shape[0] // lc)
    sraw = jnp.pad(sraw, (0, srows * lc - sraw.shape[0]))
    return jnp.concatenate([q, sraw.reshape(srows, lc)], axis=0)


def q8_unpack(packed, shape, dtype, tile: int = QUANT_TILE):
    """Inverse of :func:`q8_pack`: split payload/scales, decode."""
    lr, lc = shape
    tr, tc = _tile_counts(shape, tile)
    q = packed[:lr]
    sraw = packed[lr:].reshape(-1)[: tr * tc * 4].reshape(tr * tc, 4)
    scales = lax.bitcast_convert_type(sraw, jnp.float32).reshape(tr, tc)
    return q8_decode(q, scales, dtype, tile)
