"""Runtime span tracer: nested spans, driver phase hooks, collective events.

The structural half of the observability subsystem (ISSUE 5).  A
:class:`Tracer` records three kinds of evidence from ONE eager run:

  * explicit spans -- ``with tracer.span(name, sync=outputs, **attrs):``
    context-manager blocks that nest via a stack; ``sync`` takes the
    phase's output arrays and the span closes only after
    ``jax.block_until_ready`` on them, so the recorded wall-clock is
    honest under jax's async dispatch;
  * phase records -- the driver hooks.  Every tuned driver (``cholesky``,
    ``lu``, ``qr``, ``gemm``, ``trsm``, ``herk``) calls the PhaseTimer
    tick protocol (``start()`` + ``tick(phase, step, *arrays)``) at its
    phase boundaries; a tracer-backed :class:`_TickChannel` turns those
    ticks into (driver, phase, step, t0, t1) records, from which the
    exporter synthesizes the driver -> step -> phase span nesting.
    ``tick`` blocks on the phase's outputs exactly like the original
    ``perf.phase_timer.PhaseTimer`` (which is now a shim over this);
  * collective events -- while a tracer is ACTIVE (``with tracer:``), it
    registers an observer on the redistribution engine's trace hook, so
    every public ``redistribute``/``panel_spread`` entry lands as an
    instant event carrying src/dst distributions, global shape, dtype,
    and a ring-model byte estimate, attributed to the innermost open
    span / most recent driver.

Activation (``with tracer:``) also makes the tracer the process-current
one, so :func:`phase_hook` -- the single line each driver runs at entry
-- routes the driver's ticks here without any driver-level plumbing.
Like the PhaseTimer it generalizes, the tracer is an EAGER-mode tool:
under ``jax.jit`` the ticks see tracers and degrade to no-ops (the
driver fuses into one program and there are no phase boundaries to
time).

Metrics: unless constructed with ``metrics=False``, every phase record
feeds a ``phase_seconds{driver,phase}`` histogram and every collective
event bumps ``redist_calls{label}`` / ``redist_bytes{label}`` counters
on the CURRENT :mod:`.metrics` registry; :func:`phase_hook` additionally
counts ``op_calls{op}`` per driver entry (Python-entry counts, the same
caveat as ``engine.REDIST_COUNTS``).
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

import jax
import numpy as np

from . import metrics as _metrics

TRACE_SCHEMA = "obs_trace/v1"


@dataclasses.dataclass
class Span:
    """One explicit (context-manager) span."""
    name: str
    t0: float
    t1: float | None
    depth: int
    attrs: dict
    #: originating thread (0 = unattributed/legacy); the exporter keys
    #: Chrome-trace tracks by this so fleet worker spans don't collide
    thread: int = 0
    thread_name: str = ""


@dataclasses.dataclass
class PhaseRecord:
    """One driver phase interval reconstructed from a tick."""
    driver: str
    phase: str
    step: int
    t0: float
    t1: float
    call: int                    # driver-invocation ordinal (channel id)
    thread: int = 0
    thread_name: str = ""

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0


@dataclasses.dataclass
class InstantEvent:
    """One generic instant event (e.g. a resilience health flag)."""
    t: float
    name: str
    attrs: dict
    thread: int = 0
    thread_name: str = ""


@dataclasses.dataclass
class CommEvent:
    """One public redistribute/panel_spread entry observed at runtime."""
    t: float
    kind: str                    # "redistribute" | "panel_spread" | "row_permute"
    label: str                   # "[MC,MR]->[STAR,STAR]" | "panel_spread"
    gshape: tuple
    dtype: str
    bytes: int                   # ring-model estimate at the LOGICAL dtype
    span: str | None             # innermost open explicit span
    driver: str | None           # most recent driver channel
    #: dtype/bytes actually on the wire: == dtype/bytes unless the entry
    #: ran under a ``comm_precision`` mode (ISSUE 8), where the payload
    #: is bfloat16/int8 and wire_bytes shows the 2-4x drop
    wire_dtype: str = ""
    wire_bytes: int = 0
    #: route the engine resolved (ISSUE 12): "chain" | "direct" |
    #: "storage" (row-permute fast path); "" for pre-path entries
    path: str = ""
    #: collective rounds of the resolved route (-1 = engine didn't price)
    rounds: int = -1
    #: the engine's exact ring-model pricing of the resolved route at the
    #: wire dtype (-1 = not computed) -- finer than the coarse ``bytes``/
    #: ``wire_bytes`` estimate, and the per-round byte record of the path
    engine_wire_bytes: int = -1
    thread: int = 0
    thread_name: str = ""


def ring_bytes(gshape, dtype, grid_shape) -> int:
    """Ring-model per-device byte estimate for moving a ``gshape`` matrix
    across a ``grid_shape`` mesh: each device receives the payload minus
    its own shard, ``payload * (p - 1) / p`` (0 on a 1x1 grid -- no
    collective executes).  The jaxpr-level analyzer
    (``analysis.jaxpr_walk.estimate_bytes``) refines this per collective;
    at the public-entry granularity recorded here the single formula is
    the honest common denominator."""
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 4
    payload = itemsize
    for d in gshape:
        payload *= int(d)
    p = 1
    for d in grid_shape:
        p *= int(d)
    if p <= 1:
        return 0
    return payload * (p - 1) // p


class NullHook:
    """Zero-overhead stand-in so drivers can call tick() unconditionally."""
    __slots__ = ()

    def start(self):
        pass

    def tick(self, phase, step, *arrays):
        pass


NULL_HOOK = NullHook()


class _TickChannel:
    """One driver invocation's tick stream (PhaseTimer protocol)."""
    __slots__ = ("tracer", "driver", "attrs", "call", "_t")

    def __init__(self, tracer: "Tracer", driver: str, call: int, attrs: dict):
        self.tracer = tracer
        self.driver = driver
        self.attrs = attrs
        self.call = call
        self._t = None

    def start(self):
        """(Re)arm the clock at a driver's entry."""
        self._t = self.tracer.clock()

    def tick(self, phase, step, *arrays):
        """Block on ``arrays`` and close the [previous-tick, now] phase."""
        if arrays:
            jax.block_until_ready(arrays)
        now = self.tracer.clock()
        t0 = self._t if self._t is not None else now
        self.tracer._add_phase(self.driver, str(phase), int(step), t0, now,
                               self.call)
        self._t = now


class _Fanout:
    """Tick fan-out: an explicit PhaseTimer AND the active tracer both see
    every tick (the first hook's block_until_ready makes the second ~free)."""
    __slots__ = ("hooks",)

    def __init__(self, hooks):
        self.hooks = tuple(hooks)

    def start(self):
        for h in self.hooks:
            h.start()

    def tick(self, phase, step, *arrays):
        for h in self.hooks:
            h.tick(phase, step, *arrays)


_ACTIVE: "Tracer | None" = None


def active_tracer() -> "Tracer | None":
    """The tracer currently activated via ``with tracer:``, if any."""
    return _ACTIVE


class Tracer:
    """Collects spans, driver phase records, and collective events.

    Thread-safe (ISSUE 20 satellite): fleet GridWorker threads record
    spans/phases/instants concurrently with the submitting thread.  The
    shared record lists append under one lock; span NESTING state (the
    open-span stack and the most-recent-driver attribution) is
    thread-local, so each thread nests independently and the exporter
    can key tracks by the recorded originating thread.
    """

    def __init__(self, metrics: bool = True, clock=time.perf_counter):
        self.clock = clock
        self.spans: list[Span] = []
        self.phases: list[PhaseRecord] = []
        self.comms: list[CommEvent] = []
        self.instants: list[InstantEvent] = []
        self.home_thread = threading.get_ident()
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._metrics = metrics
        self._ncalls = 0
        self._prev_active: Tracer | None = None
        self._unobserve = None

    def _thread_stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @property
    def _cur_driver(self):
        return getattr(self._tls, "driver", None)

    @_cur_driver.setter
    def _cur_driver(self, driver):
        self._tls.driver = driver

    @staticmethod
    def _whoami() -> tuple:
        return threading.get_ident(), threading.current_thread().name

    # ---- explicit spans ---------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, sync=None, **attrs):
        """Open a nested span; if ``sync`` is given (arrays / pytree), the
        span blocks on it before closing so the duration is honest."""
        stack = self._thread_stack()
        ident, tname = self._whoami()
        s = Span(name=str(name), t0=self.clock(), t1=None,
                 depth=len(stack), attrs=dict(attrs), thread=ident,
                 thread_name=tname)
        with self._lock:
            self.spans.append(s)
        stack.append(s)
        try:
            yield s
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            s.t1 = self.clock()
            stack.pop()

    # ---- driver tick channels ---------------------------------------
    def channel(self, driver: str, **attrs) -> _TickChannel:
        """A fresh tick channel; one per driver invocation."""
        with self._lock:
            self._ncalls += 1
            call = self._ncalls
        self._cur_driver = driver
        return _TickChannel(self, driver, call, attrs)

    def _add_phase(self, driver, phase, step, t0, t1, call):
        ident, tname = self._whoami()
        rec = PhaseRecord(driver, phase, step, t0, t1, call,
                          thread=ident, thread_name=tname)
        with self._lock:
            self.phases.append(rec)
        self._cur_driver = driver
        if self._metrics:
            _metrics.observe("phase_seconds", t1 - t0, driver=driver,
                             phase=phase)

    # ---- generic instant events -------------------------------------
    def instant(self, name: str, **attrs) -> None:
        """Record a zero-duration event (rendered on an ``events`` track
        by the Chrome-trace exporter).  The resilience health guards use
        this to surface ``health:<kind>`` flags inline with the phase
        spans of the run that produced them; request lifecycle marks use
        it with a ``flow=`` attr, which the exporter links into
        Chrome-trace flow events (``ph: s/t/f``)."""
        ident, tname = self._whoami()
        ev = InstantEvent(t=self.clock(), name=str(name),
                          attrs=dict(attrs), thread=ident,
                          thread_name=tname)
        with self._lock:
            self.instants.append(ev)

    # ---- engine observer --------------------------------------------
    def _on_redist(self, rec) -> None:
        grid_shape = getattr(rec, "grid_shape", ())
        nbytes = ring_bytes(rec.gshape, rec.dtype, grid_shape)
        wire = getattr(rec, "wire_dtype", "") or rec.dtype
        wbytes = nbytes if wire == rec.dtype \
            else ring_bytes(rec.gshape, wire, grid_shape)
        stack = self._thread_stack()
        ident, tname = self._whoami()
        ev = CommEvent(
            t=self.clock(), kind=rec.kind, label=rec.label,
            gshape=tuple(rec.gshape), dtype=rec.dtype, bytes=nbytes,
            span=stack[-1].name if stack else None,
            driver=self._cur_driver, wire_dtype=wire, wire_bytes=wbytes,
            path=str(getattr(rec, "path", "") or ""),
            rounds=int(getattr(rec, "rounds", -1)),
            engine_wire_bytes=int(getattr(rec, "wire_bytes", -1)),
            thread=ident, thread_name=tname)
        with self._lock:
            self.comms.append(ev)
        if self._metrics:
            _metrics.inc("redist_calls", label=rec.label)
            _metrics.inc("redist_bytes", nbytes, label=rec.label)
            _metrics.inc("redist_wire_bytes", wbytes, label=rec.label)
            # byte-family histogram (per-family ladder, ISSUE 20): the
            # wire-byte distribution per entry, not just the total
            _metrics.observe("redist_event_bytes", wbytes,
                             label=rec.label)

    # ---- activation --------------------------------------------------
    def __enter__(self) -> "Tracer":
        global _ACTIVE
        from ..redist.engine import add_redist_observer
        self._prev_active = _ACTIVE
        _ACTIVE = self
        self._unobserve = add_redist_observer(self._on_redist)
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev_active
        self._prev_active = None
        if self._unobserve is not None:
            self._unobserve()
            self._unobserve = None

    # ---- aggregation -------------------------------------------------
    def redist_counts(self) -> dict:
        """{label: count} over the recorded collective events -- the
        runtime twin of a ``comm_plan/v1`` document's ``redistributes``
        table (tests cross-check the two against the goldens).  Storage
        -level ``row_permute`` entries are excluded to match: GSPMD plans
        their motion, so the goldens pin no explicit rounds for them (the
        byte totals below still count their wire traffic)."""
        out: dict = {}
        for ev in self.comms:
            if ev.kind == "row_permute":
                continue
            out[ev.label] = out.get(ev.label, 0) + 1
        return dict(sorted(out.items()))

    def redist_bytes_total(self) -> int:
        return sum(ev.bytes for ev in self.comms)

    def redist_wire_bytes_total(self) -> int:
        """Total estimated bytes actually moved on the wire -- equals
        :meth:`redist_bytes_total` unless some entries ran under a
        ``comm_precision`` mode (the quantized-collective win, measurable
        end-to-end here)."""
        return sum(ev.wire_bytes for ev in self.comms)

    def phase_totals(self) -> dict:
        """{driver: {phase: seconds}} aggregated over all records."""
        out: dict = {}
        for r in self.phases:
            d = out.setdefault(r.driver, {})
            d[r.phase] = d.get(r.phase, 0.0) + r.seconds
        return out

    def driver_calls(self) -> list:
        """[(call id, driver, t0, t1, steps)] synthesized from phase
        records -- one entry per driver invocation (tick channel)."""
        agg: dict = {}
        for r in self.phases:
            cur = agg.get(r.call)
            if cur is None:
                agg[r.call] = [r.call, r.driver, r.t0, r.t1, {r.step}]
            else:
                cur[2] = min(cur[2], r.t0)
                cur[3] = max(cur[3], r.t1)
                cur[4].add(r.step)
        return [tuple(v[:4]) + (sorted(v[4]),)
                for _, v in sorted(agg.items())]


def phase_hook(driver: str, timer=None, **attrs):
    """The one-line driver integration: resolve this invocation's tick
    hook.  Counts the invocation (``op_calls{op=driver}`` on the current
    metrics registry), then returns

      * the explicit ``timer`` when no tracer is active (classic
        PhaseTimer usage, unchanged),
      * the active tracer's fresh channel when one is activated,
      * a fan-out over both when both are present,
      * the shared :data:`NULL_HOOK` when neither -- drivers stay
        zero-overhead dead code under jit, exactly like the old
        ``_NULL_TIMER``.
    """
    _metrics.inc("op_calls", op=driver)
    tr = _ACTIVE
    if tr is None:
        return timer if timer is not None else NULL_HOOK
    chan = tr.channel(driver, **attrs)
    chan.start()
    if timer is None:
        return chan
    return _Fanout((timer, chan))
