"""``PhaseTimer``: per-phase wall-clock attribution (``phase_timings/v1``).

The original ``perf/phase_timer.py`` implementation, now a THIN SHIM over
the span tracer (:mod:`.tracer`): ticks land as the tracer's
:class:`~elemental_tpu.obs.tracer.PhaseRecord` intervals and the report
aggregates them into the byte-identical ``phase_timings/v1`` document the
old standalone class produced (``tests/perf/test_phase_smoke.py`` pins
the schema; ``perf.phase_timer`` re-exports everything here for its
historical importers).

Any driver that accepts a ``timer`` argument calls
``timer.tick(phase, step, *arrays)`` at its phase boundaries.  The timer
synchronizes on the phase's outputs (``jax.block_until_ready``) and
charges the elapsed wall-clock since the previous tick to
``(phase, step)``, so a run yields a machine-readable breakdown per
blocked step.

Usage (EAGER -- wrapping the driver in jit would fuse the phases away and
make the ticks no-ops on tracers)::

    from perf.phase_timer import PhaseTimer
    t = PhaseTimer()
    LU, perm = el.lu(A, nb=2048, timer=t)
    print(t.json(driver="lu", n=n, nb=2048))

``python perf/ab_harness.py phases [lu|cholesky]`` is the CLI wrapper;
``python -m perf.trace`` is the full-subsystem CLI (nested spans +
collective events + Perfetto export).  Schema (``phase_timings/v1``; LU
emits panel/swap/solve/update, Cholesky diag/panel/spread/update and
``tail`` on the crossover step)::

    {"schema": "phase_timings/v1",
     "steps":  [{"step": 0, "panel": s, "swap": s, "solve": s, "update": s},
                ...],                      # seconds; phases may be absent
     "totals": {"panel": s, "swap": s, "solve": s, "update": s},
     "total_seconds": s,
     ...caller metadata (driver, n, nb, device, ...)}

Timing note: eager dispatch is asynchronous, so the sync INSIDE tick is
what makes the attribution honest; each phase's time includes its share of
dispatch overhead (the same caveat as any op-by-op profile).  Use the A/B
modes of ``perf/ab_harness.py`` for end-to-end fused-program numbers.
"""
from __future__ import annotations

import json

from .tracer import Tracer

SCHEMA = "phase_timings/v1"

#: canonical phase order for reports (drivers emit a subset: LU ticks
#: panel/swap/solve/update, Cholesky diag/panel/spread/update + tail,
#: QR panel/update, gemm panel, trsm solve/update, herk spread/update)
PHASES = ("diag", "panel", "swap", "solve", "spread", "update", "tail")


class PhaseTimer:
    """Accumulates (phase, step, seconds) records from a driver's ticks.

    Backed by a private (metrics-silent) :class:`Tracer` whose tick
    channel does the sync + interval bookkeeping; an externally supplied
    ``tracer`` lets callers merge PhaseTimer ticks into a larger trace.
    """

    def __init__(self, tracer: Tracer | None = None):
        self.tracer = tracer if tracer is not None else Tracer(metrics=False)
        self._chan = self.tracer.channel("phase_timer")
        self._chan._t = None            # unarmed until start()/first tick

    def start(self):
        """(Re)arm the clock at a driver's entry."""
        self._chan.start()

    def tick(self, phase, step, *arrays):
        """Block on ``arrays`` and charge the elapsed time to (phase, step)."""
        self._chan.tick(phase, step, *arrays)

    @property
    def records(self) -> list[dict]:
        """The historical record shape: [{"phase", "step", "seconds"}]."""
        return [{"phase": r.phase, "step": r.step, "seconds": r.seconds}
                for r in self.tracer.phases if r.call == self._chan.call]

    def report(self, **meta) -> dict:
        """The schema dict above; ``meta`` keys merge at top level."""
        steps: dict[int, dict] = {}
        totals: dict[str, float] = {}
        for r in self.records:
            d = steps.setdefault(r["step"], {})
            d[r["phase"]] = d.get(r["phase"], 0.0) + r["seconds"]
            totals[r["phase"]] = totals.get(r["phase"], 0.0) + r["seconds"]
        out = {
            "schema": SCHEMA,
            "steps": [{"step": k, **v} for k, v in sorted(steps.items())],
            "totals": {p: totals[p] for p in PHASES if p in totals}
            | {p: t for p, t in totals.items() if p not in PHASES},
            "total_seconds": sum(totals.values()),
        }
        out.update(meta)
        return out

    def json(self, **meta) -> str:
        return json.dumps(self.report(**meta))
