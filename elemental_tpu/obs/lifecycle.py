"""Per-request lifecycle tracing -> ``serve_timeline/v1`` (ISSUE 20).

The serving tier's request-level twin of the driver span tracer: one
:class:`RequestTrace` rides each request through the whole fleet stack
and timestamps every lifecycle EDGE it crosses --

    ================  ====================================================
    edge              marked by
    ========================  ============================================
    ``submitted``     fleet/async/service ``submit()`` entry
    ``tenant_queued``  ``FairScheduler.push`` (fleet tenant lane entry)
    ``admitted``      ``AdmissionController.admit`` success (via service)
    ``shed``          any reject path, with ``reason=`` attribution
    ``staged``        ``Executor.stage`` (operands packed + compiled)
    ``dispatched``    ``Executor.dispatch`` (async launch)
    ``collected``     ``Executor.collect`` (results on host)
    ``certified``     ``SolverService._certify`` (residual measured)
    ``escalated``     ``SolverService._escalate`` (dense-path rerun)
    ``done``          ``SolverService._finalize`` (terminal result)
    ``rejected``      terminal edge of every reject
    ========================  ============================================

Edges may repeat (a bisected batch stages/collects/certifies twice); the
contract is MONOTONE timestamps under the injected clock, first edge
``submitted``, terminal edge ``done``/``rejected``.  Attribution
(``tenant``/``grid``/``bucket``/``op``) is learned as the request moves
-- the fleet stamps the tenant at submit, the routed member stamps its
grid name at admission -- and every mark is mirrored to

  * the member-shared :class:`~elemental_tpu.obs.flight.FlightRecorder`
    (when attached), so the seconds before a fault are reconstructable;
  * the ACTIVE :class:`~elemental_tpu.obs.tracer.Tracer` as a
    ``lifecycle:<edge>`` instant carrying ``flow=<request id>``, which
    the Chrome-trace exporter links into ``ph: s/t/f`` flow events --
    the Perfetto arrows hopping a request across grid-worker tracks.

``to_doc()`` renders the STABLE ``serve_timeline/v1`` sub-document that
``serve_result/v1``/``serve_reject/v1`` carry under ``"timeline"``;
:func:`check_timeline` is the completeness/monotonicity oracle the tests
and ``perf.trace serve --smoke`` both run.

Thread-safety: marks arrive from the submitting thread, the fleet pump,
and grid-worker threads; the per-trace lock serializes them.
"""
from __future__ import annotations

import threading
import time

from . import tracer as _tracer

SCHEMA = "serve_timeline/v1"

#: canonical edge vocabulary (extra edges are allowed, these are known)
EDGES = ("submitted", "tenant_queued", "admitted", "shed", "staged",
         "dispatched", "collected", "certified", "escalated", "done",
         "rejected")

#: edges every successful solve must have crossed
REQUIRED_OK = ("submitted", "admitted", "done")

#: additional edges for a batch-path (fastpath) solve
BATCH_EDGES = ("staged", "dispatched", "collected", "certified")


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_json_safe(x) for x in v]
    return str(v)


class RequestTrace:
    """Thread-safe lifecycle timeline for ONE serve request."""

    __slots__ = ("id", "clock", "tenant", "grid", "bucket", "op", "flight",
                 "_events", "_lock")

    def __init__(self, id=None, *, clock=time.monotonic, tenant=None,
                 op=None, flight=None):
        self.id = id
        self.clock = clock
        self.tenant = tenant
        self.op = op
        self.grid = None
        self.bucket = None
        self.flight = flight
        self._events: list = []
        self._lock = threading.Lock()

    # ---- attribution -------------------------------------------------
    def annotate(self, **attrs) -> None:
        """Set identity/attribution fields as they become known
        (``id``/``tenant``/``grid``/``bucket``/``op``); None is a no-op
        so call sites can pass what they have unconditionally."""
        for k in ("id", "tenant", "grid", "bucket", "op"):
            v = attrs.get(k)
            if v is not None:
                setattr(self, k, v)

    # ---- marking -----------------------------------------------------
    def mark(self, edge: str, **attrs) -> float:
        """Timestamp ``edge`` now (injected clock); mirrors the event to
        the attached flight recorder and the active tracer's flow."""
        edge = str(edge)
        t = float(self.clock())
        rec = {k: v for k, v in attrs.items() if v is not None}
        with self._lock:
            self._events.append((edge, t, rec))
        # attribution fields first, the mark's own attrs win on collision
        mirror = {"id": self.id, "tenant": self.tenant, "grid": self.grid}
        mirror.update(rec)
        fl = self.flight
        if fl is not None:
            fl.record("edge:" + edge, **mirror)
        tr = _tracer.active_tracer()
        if tr is not None:
            mirror.pop("id", None)
            tr.instant("lifecycle:" + edge, flow=self.id, **mirror)
        return t

    # ---- reads -------------------------------------------------------
    def edges(self) -> list:
        """Snapshot [(edge, t, attrs), ...] in mark order."""
        with self._lock:
            return list(self._events)

    def edge_t(self, edge: str):
        """Timestamp of the LAST crossing of ``edge`` (None if never)."""
        with self._lock:
            for e, t, _ in reversed(self._events):
                if e == edge:
                    return t
        return None

    def to_doc(self) -> dict:
        """The stable ``serve_timeline/v1`` sub-document."""
        with self._lock:
            evs = list(self._events)
        t0 = evs[0][1] if evs else 0.0
        bucket = self.bucket
        if hasattr(bucket, "key"):
            bucket = list(bucket.key())
        rows = []
        for edge, t, attrs in evs:
            row = {"edge": edge, "t": t, "dt": t - t0}
            for k, v in attrs.items():
                row[str(k)] = _json_safe(v)
            rows.append(row)
        return {"schema": SCHEMA, "id": self.id,
                "tenant": self.tenant, "grid": self.grid,
                "bucket": _json_safe(bucket), "op": self.op,
                "t0": t0, "edges": rows}


def check_timeline(timeline, *, path=None, fleet: bool = False) -> list:
    """Validate a ``serve_timeline/v1`` sub-doc; returns a list of
    problem strings (empty = complete and monotone).

    ``path`` is the result doc's ``"path"`` ("fastpath" requires the
    stage/dispatch/collect/certify edges, "escalated"/"grid" the
    escalation edge); ``fleet=True`` additionally requires the
    tenant-queue edge.
    """
    if not isinstance(timeline, dict) or timeline.get("schema") != SCHEMA:
        return [f"missing or mis-schemaed timeline: {timeline!r:.80}"]
    rows = timeline.get("edges") or []
    edges = [r.get("edge") for r in rows]
    ts = [r.get("t") for r in rows]
    if not edges:
        return ["timeline has no edges"]
    problems = []
    if edges[0] != "submitted":
        problems.append(f"first edge is {edges[0]!r}, not 'submitted'")
    if edges[-1] not in ("done", "rejected"):
        problems.append(f"terminal edge is {edges[-1]!r}")
    if any(b < a for a, b in zip(ts, ts[1:])):
        problems.append("timestamps not monotone")
    if edges[-1] == "rejected":
        if "shed" not in edges:
            problems.append("rejected without a 'shed' edge")
        return problems
    for e in REQUIRED_OK:
        if e not in edges:
            problems.append(f"missing required edge {e!r}")
    if fleet and "tenant_queued" not in edges:
        problems.append("fleet timeline missing 'tenant_queued'")
    if path == "fastpath":
        for e in BATCH_EDGES:
            if e not in edges:
                problems.append(f"fastpath missing edge {e!r}")
    elif path in ("escalated", "grid") and "escalated" not in edges:
        problems.append(f"{path} path missing 'escalated' edge")
    return problems
