"""Metrics registry: counters, gauges, histograms -> ``obs_metrics/v1``.

The numeric half of the observability subsystem (the span half is
:mod:`.tracer`).  One :class:`MetricsRegistry` holds three families:

  * counters   -- monotonically increasing totals (driver invocation
                  counts, redistribute calls/bytes, tuning-cache
                  hit/miss/stale events, and the ``abft_checks`` /
                  ``abft_violations`` / ``abft_recovered_panels``
                  family labelled by ``driver`` in {lu, cholesky, qr});
  * gauges     -- last-written values;
  * histograms -- summary stats + a fixed log-ladder bucket table
                  (phase wall-clock observations).

Every series is keyed by (name, labels); labels are plain JSON-able
scalars.  The process-global default registry (:data:`REGISTRY`) is what
module-level :func:`inc` / :func:`observe` / :func:`set_gauge` write to;
:func:`scoped` swaps a fresh registry in for a ``with`` block (the same
isolation pattern as ``engine.redist_counts``), so tests and CLI runs
read a clean slate without clearing global state.

The JSON document (``obs_metrics/v1``) is STABLE -- pinned by
``tests/obs`` -- and is what ``python -m perf.trace run`` emits and
``bench.py`` embeds under its ``"obs"`` key::

    {"schema": "obs_metrics/v1",
     "counters":   [{"name": ..., "labels": {...}, "value": N}, ...],
     "gauges":     [{"name": ..., "labels": {...}, "value": X}, ...],
     "histograms": [{"name": ..., "labels": {...}, "count": N,
                     "sum": S, "min": m, "max": M, "mean": S/N,
                     "buckets": [{"le": sec|"+Inf", "count": cum}, ...]},
                    ...],
     ...caller metadata}

Entries are sorted by (name, labels) so documents diff cleanly.
"""
from __future__ import annotations

import contextlib
import json

SCHEMA = "obs_metrics/v1"

#: histogram bucket upper bounds, seconds (log ladder; +Inf is implicit)
BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), v) for k, v in labels.items()))


def _coerce(v):
    """Labels must survive JSON round-trips losslessly."""
    return v if isinstance(v, (str, int, float, bool)) or v is None else str(v)


class MetricsRegistry:
    """One in-process sink for counters/gauges/histograms."""

    def __init__(self):
        self._counters: dict = {}
        self._gauges: dict = {}
        self._hists: dict = {}      # key -> [count, sum, min, max, [bucket counts]]

    # ---- writes ------------------------------------------------------
    def inc(self, name: str, value: float = 1, **labels) -> None:
        key = (name, _label_key(labels))
        self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        self._gauges[(name, _label_key(labels))] = value

    def observe(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = [0, 0.0, None, None, [0] * (len(BUCKETS) + 1)]
        h[0] += 1
        h[1] += value
        h[2] = value if h[2] is None else min(h[2], value)
        h[3] = value if h[3] is None else max(h[3], value)
        for i, le in enumerate(BUCKETS):
            if value <= le:
                h[4][i] += 1
                break
        else:
            h[4][-1] += 1

    # ---- reads -------------------------------------------------------
    def counter_value(self, name: str, **labels) -> float:
        return self._counters.get((name, _label_key(labels)), 0)

    def counters(self, name: str | None = None) -> dict:
        """{(name, labels-tuple): value}, optionally filtered by name."""
        return {k: v for k, v in self._counters.items()
                if name is None or k[0] == name}

    def to_doc(self, **meta) -> dict:
        """The stable ``obs_metrics/v1`` document (meta merges at top level)."""
        def rows(table):
            out = []
            for (name, lk), v in sorted(table.items(), key=lambda kv: repr(kv[0])):
                out.append({"name": name,
                            "labels": {k: _coerce(v2) for k, v2 in lk},
                            "value": v})
            return out

        hists = []
        for (name, lk), h in sorted(self._hists.items(), key=lambda kv: repr(kv[0])):
            cum, buckets = 0, []
            for le, cnt in zip(BUCKETS, h[4]):
                cum += cnt
                buckets.append({"le": le, "count": cum})
            buckets.append({"le": "+Inf", "count": cum + h[4][-1]})
            hists.append({"name": name,
                          "labels": {k: _coerce(v) for k, v in lk},
                          "count": h[0], "sum": h[1],
                          "min": h[2], "max": h[3],
                          "mean": (h[1] / h[0]) if h[0] else None,
                          "buckets": buckets})
        doc = {"schema": SCHEMA, "counters": rows(self._counters),
               "gauges": rows(self._gauges), "histograms": hists}
        doc.update(meta)
        return doc

    def to_json(self, indent: int | None = None, **meta) -> str:
        return json.dumps(self.to_doc(**meta), indent=indent)


#: the process-global default registry
REGISTRY = MetricsRegistry()

_CURRENT: MetricsRegistry = REGISTRY


def current() -> MetricsRegistry:
    """The registry module-level writes currently target."""
    return _CURRENT


@contextlib.contextmanager
def scoped(registry: MetricsRegistry | None = None):
    """Swap a fresh (or given) registry in for the block and yield it."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = registry if registry is not None else MetricsRegistry()
    try:
        yield _CURRENT
    finally:
        _CURRENT = prev


def inc(name: str, value: float = 1, **labels) -> None:
    _CURRENT.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    _CURRENT.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    _CURRENT.observe(name, value, **labels)
