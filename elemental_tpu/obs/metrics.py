"""Metrics registry: counters, gauges, histograms -> ``obs_metrics/v1``.

The numeric half of the observability subsystem (the span half is
:mod:`.tracer`).  One :class:`MetricsRegistry` holds three families:

  * counters   -- monotonically increasing totals (driver invocation
                  counts, redistribute calls/bytes, tuning-cache
                  hit/miss/stale events, and the ``abft_checks`` /
                  ``abft_violations`` / ``abft_recovered_panels``
                  family labelled by ``driver`` in {lu, cholesky, qr});
  * gauges     -- last-written values;
  * histograms -- summary stats + a fixed log-ladder bucket table
                  (phase wall-clock observations).

Every series is keyed by (name, labels); labels are plain JSON-able
scalars.  The process-global default registry (:data:`REGISTRY`) is what
module-level :func:`inc` / :func:`observe` / :func:`set_gauge` write to;
:func:`scoped` swaps a fresh registry in for a ``with`` block (the same
isolation pattern as ``engine.redist_counts``), so tests and CLI runs
read a clean slate without clearing global state.

The JSON document (``obs_metrics/v1``) is STABLE -- pinned by
``tests/obs`` -- and is what ``python -m perf.trace run`` emits and
``bench.py`` embeds under its ``"obs"`` key::

    {"schema": "obs_metrics/v1",
     "counters":   [{"name": ..., "labels": {...}, "value": N}, ...],
     "gauges":     [{"name": ..., "labels": {...}, "value": X}, ...],
     "histograms": [{"name": ..., "labels": {...}, "count": N,
                     "sum": S, "min": m, "max": M, "mean": S/N,
                     "buckets": [{"le": sec|"+Inf", "count": cum}, ...]},
                    ...],
     ...caller metadata}

Entries are sorted by (name, labels) so documents diff cleanly.
"""
from __future__ import annotations

import contextlib
import json
import threading

SCHEMA = "obs_metrics/v1"

#: histogram bucket upper bounds, seconds (log ladder; +Inf is implicit)
BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0)

#: per-family ladders (ISSUE 20 satellite): byte-valued observations
#: (``*_bytes``) and count-valued ones (``*_count``) get ladders in
#: their own units instead of landing in the seconds ladder's top bucket
BYTE_BUCKETS = (256, 4096, 65536, 1 << 20, 16 << 20, 256 << 20,
                4 << 30, 64 << 30)
COUNT_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 1000, 10000)

FAMILIES = {"seconds": BUCKETS, "bytes": BYTE_BUCKETS,
            "count": COUNT_BUCKETS}

#: explicit metric-name -> family registrations (suffix rules otherwise)
_FAMILY_OVERRIDES: dict = {}


def set_hist_family(name: str, family: str) -> None:
    """Pin metric ``name``'s histogram ladder to ``family`` (one of
    :data:`FAMILIES`); overrides the suffix-based default."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; "
                         f"expected one of {sorted(FAMILIES)}")
    _FAMILY_OVERRIDES[name] = family


def hist_family(name: str) -> str:
    """Resolve a metric name's bucket family: explicit registration
    first, then suffix convention (``*_bytes`` -> bytes, ``*_count`` /
    ``*_calls`` -> count), else seconds."""
    fam = _FAMILY_OVERRIDES.get(name)
    if fam is not None:
        return fam
    if name.endswith("_bytes"):
        return "bytes"
    if name.endswith(("_count", "_calls")):
        return "count"
    return "seconds"


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), v) for k, v in labels.items()))


def _coerce(v):
    """Labels must survive JSON round-trips losslessly."""
    return v if isinstance(v, (str, int, float, bool)) or v is None else str(v)


class MetricsRegistry:
    """One in-process sink for counters/gauges/histograms.

    Thread-safe (ISSUE 20 satellite): fleet GridWorker threads write
    concurrently with the submitting thread, so every read-modify-write
    -- the counter add, the lazy histogram init, the bucket bump --
    happens under one registry lock.  Reads snapshot under the same
    lock, so ``to_doc`` never sees a half-updated histogram.
    """

    def __init__(self):
        self._counters: dict = {}
        self._gauges: dict = {}
        # key -> [count, sum, min, max, [bucket counts], ladder, family]
        self._hists: dict = {}
        self._lock = threading.Lock()

    # ---- writes ------------------------------------------------------
    def inc(self, name: str, value: float = 1, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = value

    def observe(self, name: str, value: float, family: str | None = None,
                **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                fam = family if family is not None else hist_family(name)
                ladder = FAMILIES.get(fam, BUCKETS)
                h = self._hists[key] = [0, 0.0, None, None,
                                        [0] * (len(ladder) + 1), ladder,
                                        fam]
            h[0] += 1
            h[1] += value
            h[2] = value if h[2] is None else min(h[2], value)
            h[3] = value if h[3] is None else max(h[3], value)
            for i, le in enumerate(h[5]):
                if value <= le:
                    h[4][i] += 1
                    break
            else:
                h[4][-1] += 1

    # ---- reads -------------------------------------------------------
    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0)

    def counters(self, name: str | None = None) -> dict:
        """{(name, labels-tuple): value}, optionally filtered by name."""
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if name is None or k[0] == name}

    def to_doc(self, **meta) -> dict:
        """The stable ``obs_metrics/v1`` document (meta merges at top level)."""
        def rows(table):
            out = []
            for (name, lk), v in sorted(table.items(), key=lambda kv: repr(kv[0])):
                out.append({"name": name,
                            "labels": {k: _coerce(v2) for k, v2 in lk},
                            "value": v})
            return out

        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hist_snap = [(k, [h[0], h[1], h[2], h[3], list(h[4]), h[5],
                              h[6]])
                         for k, h in self._hists.items()]
        hists = []
        for (name, lk), h in sorted(hist_snap, key=lambda kv: repr(kv[0])):
            cum, buckets = 0, []
            for le, cnt in zip(h[5], h[4]):
                cum += cnt
                buckets.append({"le": le, "count": cum})
            buckets.append({"le": "+Inf", "count": cum + h[4][-1]})
            hists.append({"name": name,
                          "labels": {k: _coerce(v) for k, v in lk},
                          "count": h[0], "sum": h[1],
                          "min": h[2], "max": h[3],
                          "mean": (h[1] / h[0]) if h[0] else None,
                          "family": h[6],
                          "buckets": buckets})
        doc = {"schema": SCHEMA, "counters": rows(counters),
               "gauges": rows(gauges), "histograms": hists}
        doc.update(meta)
        return doc

    def to_json(self, indent: int | None = None, **meta) -> str:
        return json.dumps(self.to_doc(**meta), indent=indent)


#: the process-global default registry
REGISTRY = MetricsRegistry()

_CURRENT: MetricsRegistry = REGISTRY


def current() -> MetricsRegistry:
    """The registry module-level writes currently target."""
    return _CURRENT


@contextlib.contextmanager
def scoped(registry: MetricsRegistry | None = None):
    """Swap a fresh (or given) registry in for the block and yield it."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = registry if registry is not None else MetricsRegistry()
    try:
        yield _CURRENT
    finally:
        _CURRENT = prev


def inc(name: str, value: float = 1, **labels) -> None:
    _CURRENT.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    _CURRENT.set_gauge(name, value, **labels)


def observe(name: str, value: float, family: str | None = None,
            **labels) -> None:
    _CURRENT.observe(name, value, family=family, **labels)
