"""Exporters: Chrome-trace/Perfetto ``trace.json`` from a Tracer run.

``chrome_trace_doc`` renders one :class:`~elemental_tpu.obs.tracer.Tracer`
into the Chrome Trace Event JSON-object format, which Perfetto
(https://ui.perfetto.dev) and chrome://tracing both load directly:

  * spans are duration events (``ph: "X"``, micros since the run origin);
  * the driver -> step -> phase nesting is laid out as ONE TRACK PER
    PHASE LANE: track 0 carries the synthesized driver spans (one per
    tick channel) plus any explicit ``tracer.span`` blocks, track 1 the
    synthesized per-step spans, and each phase name gets its own track
    (``diag``/``panel``/``swap``/... in the canonical PHASES order,
    unseen names appended) so overlap between lanes is visible at a
    glance -- the look-ahead schedule's whole point;
  * collectives are instant events (``ph: "i"``) on a dedicated
    ``collectives`` track, with src->dst label, global shape, dtype and
    ring-model bytes in ``args``.

The document carries a top-level ``"schema": "obs_chrome_trace/v1"`` key
(Chrome/Perfetto ignore unknown keys in the object format) pinned by
``tests/obs``; run metadata rides ``otherData``.

``phase_timings_to_chrome`` converts a historical ``phase_timings/v1``
document (``bench.py --phases`` / ``ab_harness.py phases`` output, which
records durations but no timestamps) into the same trace format by laying
the steps out sequentially -- ``python -m perf.trace export`` is the CLI.
"""
from __future__ import annotations

import json

from .phase_timer import PHASES, SCHEMA as PHASE_SCHEMA
from .tracer import Tracer

CHROME_SCHEMA = "obs_chrome_trace/v1"

_PID = 0
_TID_DRIVER = 0
_TID_STEP = 1
_FIRST_PHASE_TID = 2


def _lanes(phase_names) -> dict:
    """Stable phase-name -> tid map: canonical order first, extras after."""
    lanes: dict = {}
    tid = _FIRST_PHASE_TID
    for p in PHASES:
        if p in phase_names:
            lanes[p] = tid
            tid += 1
    for p in sorted(phase_names):
        if p not in lanes:
            lanes[p] = tid
            tid += 1
    return lanes


def _meta_events(lanes: dict, have_comms: bool,
                 have_instants: bool = False) -> list:
    evs = [{"ph": "M", "pid": _PID, "tid": _TID_DRIVER, "name": "thread_name",
            "args": {"name": "drivers"}},
           {"ph": "M", "pid": _PID, "tid": _TID_STEP, "name": "thread_name",
            "args": {"name": "steps"}}]
    for p, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        evs.append({"ph": "M", "pid": _PID, "tid": tid, "name": "thread_name",
                    "args": {"name": f"phase:{p}"}})
    if have_comms:
        evs.append({"ph": "M", "pid": _PID, "tid": _comm_tid(lanes),
                    "name": "thread_name", "args": {"name": "collectives"}})
    if have_instants:
        evs.append({"ph": "M", "pid": _PID,
                    "tid": _instant_tid(lanes, have_comms),
                    "name": "thread_name", "args": {"name": "events"}})
    return evs


def _comm_tid(lanes: dict) -> int:
    return (max(lanes.values()) + 1) if lanes else _FIRST_PHASE_TID


def _instant_tid(lanes: dict, have_comms: bool) -> int:
    return _comm_tid(lanes) + (1 if have_comms else 0)


def chrome_trace_doc(tracer: Tracer, **meta) -> dict:
    """Render a tracer's spans/phases/collectives as a Chrome trace."""
    instants = getattr(tracer, "instants", ())
    times = ([r.t0 for r in tracer.phases]
             + [s.t0 for s in tracer.spans]
             + [ev.t for ev in tracer.comms]
             + [ev.t for ev in instants])
    origin = min(times) if times else 0.0

    def us(t: float) -> float:
        return round((t - origin) * 1e6, 3)

    lanes = _lanes({r.phase for r in tracer.phases})
    events = _meta_events(lanes, bool(tracer.comms), bool(instants))

    # synthesized driver spans (one per tick channel) on the driver track
    for call, driver, t0, t1, steps in tracer.driver_calls():
        events.append({"ph": "X", "pid": _PID, "tid": _TID_DRIVER,
                       "name": driver, "ts": us(t0),
                       "dur": round((t1 - t0) * 1e6, 3),
                       "args": {"call": call, "steps": len(steps)}})
    # explicit context-manager spans share the driver track (depth in args)
    for s in tracer.spans:
        t1 = s.t1 if s.t1 is not None else s.t0
        events.append({"ph": "X", "pid": _PID, "tid": _TID_DRIVER,
                       "name": s.name, "ts": us(s.t0),
                       "dur": round((t1 - s.t0) * 1e6, 3),
                       "args": {"depth": s.depth, **s.attrs}})
    # synthesized step spans
    steps_agg: dict = {}
    for r in tracer.phases:
        key = (r.call, r.step)
        cur = steps_agg.get(key)
        if cur is None:
            steps_agg[key] = [r.driver, r.t0, r.t1]
        else:
            cur[1] = min(cur[1], r.t0)
            cur[2] = max(cur[2], r.t1)
    for (call, step), (driver, t0, t1) in sorted(steps_agg.items()):
        events.append({"ph": "X", "pid": _PID, "tid": _TID_STEP,
                       "name": f"{driver}[{step}]", "ts": us(t0),
                       "dur": round((t1 - t0) * 1e6, 3),
                       "args": {"call": call, "step": step}})
    # phase spans, one lane per phase name
    for r in tracer.phases:
        events.append({"ph": "X", "pid": _PID, "tid": lanes[r.phase],
                       "name": r.phase, "ts": us(r.t0),
                       "dur": round(r.seconds * 1e6, 3),
                       "args": {"driver": r.driver, "step": r.step,
                                "call": r.call}})
    # collective instants
    ctid = _comm_tid(lanes)
    for ev in tracer.comms:
        events.append({"ph": "i", "s": "t", "pid": _PID, "tid": ctid,
                       "name": ev.label, "ts": us(ev.t),
                       "args": {"kind": ev.kind, "gshape": list(ev.gshape),
                                "dtype": ev.dtype, "bytes": ev.bytes,
                                "wire_dtype": getattr(ev, "wire_dtype", "")
                                or ev.dtype,
                                "wire_bytes": getattr(ev, "wire_bytes", 0)
                                or ev.bytes,
                                "driver": ev.driver, "span": ev.span}})
    # generic instants (health flags, ...) on a dedicated events track
    etid = _instant_tid(lanes, bool(tracer.comms))
    for ev in instants:
        events.append({"ph": "i", "s": "t", "pid": _PID, "tid": etid,
                       "name": ev.name, "ts": us(ev.t),
                       "args": dict(ev.attrs)})
    return {"schema": CHROME_SCHEMA, "traceEvents": events,
            "displayTimeUnit": "ms", "otherData": dict(meta)}


def phase_timings_to_chrome(doc: dict, **meta) -> dict:
    """Synthesize a Chrome trace from a ``phase_timings/v1`` document.

    The phase-timings schema records per-(step, phase) DURATIONS but no
    timestamps, so the steps are laid out back-to-back in listed order
    (phases within a step in canonical order) -- lane structure and
    relative widths are faithful, absolute placement is synthetic
    (flagged in ``otherData.synthesized``)."""
    if doc.get("schema") != PHASE_SCHEMA:
        raise ValueError(f"expected a {PHASE_SCHEMA} document, got "
                         f"schema={doc.get('schema')!r}")
    driver = str(doc.get("driver", "driver"))
    phase_names = set()
    for srec in doc.get("steps", []):
        phase_names |= set(srec) - {"step"}
    lanes = _lanes(phase_names)
    events = _meta_events(lanes, have_comms=False)
    order = [p for p in PHASES if p in phase_names] \
        + sorted(phase_names - set(PHASES))
    t = 0.0
    for srec in doc.get("steps", []):
        step_t0 = t
        for p in order:
            if p not in srec:
                continue
            dur = float(srec[p])
            events.append({"ph": "X", "pid": _PID, "tid": lanes[p],
                           "name": p, "ts": round(t * 1e6, 3),
                           "dur": round(dur * 1e6, 3),
                           "args": {"driver": driver, "step": srec["step"]}})
            t += dur
        events.append({"ph": "X", "pid": _PID, "tid": _TID_STEP,
                       "name": f"{driver}[{srec['step']}]",
                       "ts": round(step_t0 * 1e6, 3),
                       "dur": round((t - step_t0) * 1e6, 3),
                       "args": {"step": srec["step"]}})
    events.append({"ph": "X", "pid": _PID, "tid": _TID_DRIVER, "name": driver,
                   "ts": 0.0, "dur": round(t * 1e6, 3),
                   "args": {"total_seconds": doc.get("total_seconds")}})
    other = {"synthesized": True,
             "source_schema": PHASE_SCHEMA}
    for k in ("driver", "n", "nb", "device", "lookahead"):
        if k in doc:
            other[k] = doc[k]
    other.update(meta)
    return {"schema": CHROME_SCHEMA, "traceEvents": events,
            "displayTimeUnit": "ms", "otherData": other}


def write_json(path: str, doc: dict) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
