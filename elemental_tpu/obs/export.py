"""Exporters: Chrome-trace/Perfetto ``trace.json`` from a Tracer run.

``chrome_trace_doc`` renders one :class:`~elemental_tpu.obs.tracer.Tracer`
into the Chrome Trace Event JSON-object format, which Perfetto
(https://ui.perfetto.dev) and chrome://tracing both load directly:

  * spans are duration events (``ph: "X"``, micros since the run origin);
  * the driver -> step -> phase nesting is laid out as ONE TRACK PER
    PHASE LANE: track 0 carries the synthesized driver spans (one per
    tick channel) plus any explicit ``tracer.span`` blocks, track 1 the
    synthesized per-step spans, and each phase name gets its own track
    (``diag``/``panel``/``swap``/... in the canonical PHASES order,
    unseen names appended) so overlap between lanes is visible at a
    glance -- the look-ahead schedule's whole point;
  * collectives are instant events (``ph: "i"``) on a dedicated
    ``collectives`` track, with src->dst label, global shape, dtype and
    ring-model bytes in ``args``.

The document carries a top-level ``"schema": "obs_chrome_trace/v1"`` key
(Chrome/Perfetto ignore unknown keys in the object format) pinned by
``tests/obs``; run metadata rides ``otherData``.

``phase_timings_to_chrome`` converts a historical ``phase_timings/v1``
document (``bench.py --phases`` / ``ab_harness.py phases`` output, which
records durations but no timestamps) into the same trace format by laying
the steps out sequentially -- ``python -m perf.trace export`` is the CLI.
"""
from __future__ import annotations

import json

from .phase_timer import PHASES, SCHEMA as PHASE_SCHEMA
from .tracer import Tracer

CHROME_SCHEMA = "obs_chrome_trace/v1"

_PID = 0
_TID_DRIVER = 0
_TID_STEP = 1
_FIRST_PHASE_TID = 2


def _lanes(phase_names) -> dict:
    """Stable phase-name -> tid map: canonical order first, extras after."""
    lanes: dict = {}
    tid = _FIRST_PHASE_TID
    for p in PHASES:
        if p in phase_names:
            lanes[p] = tid
            tid += 1
    for p in sorted(phase_names):
        if p not in lanes:
            lanes[p] = tid
            tid += 1
    return lanes


def _meta_events(lanes: dict, have_comms: bool,
                 have_instants: bool = False) -> list:
    evs = [{"ph": "M", "pid": _PID, "tid": _TID_DRIVER, "name": "thread_name",
            "args": {"name": "drivers"}},
           {"ph": "M", "pid": _PID, "tid": _TID_STEP, "name": "thread_name",
            "args": {"name": "steps"}}]
    for p, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        evs.append({"ph": "M", "pid": _PID, "tid": tid, "name": "thread_name",
                    "args": {"name": f"phase:{p}"}})
    if have_comms:
        evs.append({"ph": "M", "pid": _PID, "tid": _comm_tid(lanes),
                    "name": "thread_name", "args": {"name": "collectives"}})
    if have_instants:
        evs.append({"ph": "M", "pid": _PID,
                    "tid": _instant_tid(lanes, have_comms),
                    "name": "thread_name", "args": {"name": "events"}})
    return evs


def _comm_tid(lanes: dict) -> int:
    return (max(lanes.values()) + 1) if lanes else _FIRST_PHASE_TID


def _instant_tid(lanes: dict, have_comms: bool) -> int:
    return _comm_tid(lanes) + (1 if have_comms else 0)


def _driver_calls(phases) -> list:
    """[(call, driver, t0, t1, steps)] synthesized from ``phases``."""
    agg: dict = {}
    for r in phases:
        cur = agg.get(r.call)
        if cur is None:
            agg[r.call] = [r.call, r.driver, r.t0, r.t1, {r.step}]
        else:
            cur[2] = min(cur[2], r.t0)
            cur[3] = max(cur[3], r.t1)
            cur[4].add(r.step)
    return [tuple(v[:4]) + (sorted(v[4]),) for _, v in sorted(agg.items())]


def _group_by_thread(tracer: Tracer) -> tuple:
    """Partition a tracer's records by originating thread.

    Records with no thread attribution (legacy ``thread=0``) fold into
    the tracer's HOME thread, which keeps the pre-ISSUE-20 single-thread
    layout (driver track 0, steps 1, phase lanes...) byte-stable.
    Returns ``(home_ident, {ident: group})`` where each group holds
    ``spans``/``phases``/``comms``/``instants`` lists plus a display
    ``name`` and first-event time for deterministic track ordering.
    """
    home = getattr(tracer, "home_thread", 0)
    groups: dict = {}

    def add(kind, ev, t):
        th = getattr(ev, "thread", 0) or home
        g = groups.get(th)
        if g is None:
            g = groups[th] = {"spans": [], "phases": [], "comms": [],
                              "instants": [], "name": "", "first": t}
        g[kind].append(ev)
        g["first"] = min(g["first"], t)
        if not g["name"]:
            g["name"] = getattr(ev, "thread_name", "") or ""

    for s in tracer.spans:
        add("spans", s, s.t0)
    for r in tracer.phases:
        add("phases", r, r.t0)
    for ev in tracer.comms:
        add("comms", ev, ev.t)
    for ev in getattr(tracer, "instants", ()):
        add("instants", ev, ev.t)
    return home, groups


def chrome_trace_doc(tracer: Tracer, **meta) -> dict:
    """Render a tracer's spans/phases/collectives as a Chrome trace.

    Tracks are keyed by ORIGINATING THREAD (ISSUE 20): the tracer's home
    thread keeps the historical layout (driver track, step track, one
    lane per phase, collectives, events); every other recording thread
    -- e.g. each fleet grid worker -- gets its own contiguous track
    block labelled by its thread name, so a 2-grid fleet trace renders
    as one track group per worker instead of interleaved garbage.

    Instants carrying a ``flow`` attr (request lifecycle marks) are
    additionally linked into Chrome-trace FLOW events (``ph: "s"`` at
    the first mark, ``"t"`` steps, ``"f"`` at the last) sharing
    ``id=<flow>``, which Perfetto draws as arrows hopping a request
    across grid-worker tracks.
    """
    times = ([r.t0 for r in tracer.phases]
             + [s.t0 for s in tracer.spans]
             + [ev.t for ev in tracer.comms]
             + [ev.t for ev in getattr(tracer, "instants", ())])
    origin = min(times) if times else 0.0

    def us(t: float) -> float:
        return round((t - origin) * 1e6, 3)

    home, groups = _group_by_thread(tracer)
    home_g = groups.get(home, {"spans": [], "phases": [], "comms": [],
                               "instants": [], "name": "", "first": 0.0})
    lanes = _lanes({r.phase for r in home_g["phases"]})
    events = _meta_events(lanes, bool(home_g["comms"]),
                          bool(home_g["instants"]))
    placed_instants: list = []   # (instant, tid) for flow-event linking

    def emit_group(g, tid_span, tid_step, phase_lanes, tid_comm, tid_inst):
        for call, driver, t0, t1, steps in _driver_calls(g["phases"]):
            events.append({"ph": "X", "pid": _PID, "tid": tid_span,
                           "name": driver, "ts": us(t0),
                           "dur": round((t1 - t0) * 1e6, 3),
                           "args": {"call": call, "steps": len(steps)}})
        for s in g["spans"]:
            t1 = s.t1 if s.t1 is not None else s.t0
            events.append({"ph": "X", "pid": _PID, "tid": tid_span,
                           "name": s.name, "ts": us(s.t0),
                           "dur": round((t1 - s.t0) * 1e6, 3),
                           "args": {"depth": s.depth, **s.attrs}})
        steps_agg: dict = {}
        for r in g["phases"]:
            key = (r.call, r.step)
            cur = steps_agg.get(key)
            if cur is None:
                steps_agg[key] = [r.driver, r.t0, r.t1]
            else:
                cur[1] = min(cur[1], r.t0)
                cur[2] = max(cur[2], r.t1)
        for (call, step), (driver, t0, t1) in sorted(steps_agg.items()):
            events.append({"ph": "X", "pid": _PID, "tid": tid_step,
                           "name": f"{driver}[{step}]", "ts": us(t0),
                           "dur": round((t1 - t0) * 1e6, 3),
                           "args": {"call": call, "step": step}})
        for r in g["phases"]:
            events.append({"ph": "X", "pid": _PID,
                           "tid": phase_lanes[r.phase],
                           "name": r.phase, "ts": us(r.t0),
                           "dur": round(r.seconds * 1e6, 3),
                           "args": {"driver": r.driver, "step": r.step,
                                    "call": r.call}})
        for ev in g["comms"]:
            events.append({"ph": "i", "s": "t", "pid": _PID,
                           "tid": tid_comm,
                           "name": ev.label, "ts": us(ev.t),
                           "args": {"kind": ev.kind,
                                    "gshape": list(ev.gshape),
                                    "dtype": ev.dtype, "bytes": ev.bytes,
                                    "wire_dtype":
                                    getattr(ev, "wire_dtype", "")
                                    or ev.dtype,
                                    "wire_bytes":
                                    getattr(ev, "wire_bytes", 0)
                                    or ev.bytes,
                                    "driver": ev.driver, "span": ev.span}})
        for ev in g["instants"]:
            events.append({"ph": "i", "s": "t", "pid": _PID,
                           "tid": tid_inst,
                           "name": ev.name, "ts": us(ev.t),
                           "args": dict(ev.attrs)})
            placed_instants.append((ev, tid_inst))

    # home thread: the historical fixed layout
    emit_group(home_g, _TID_DRIVER, _TID_STEP, lanes,
               _comm_tid(lanes), _instant_tid(lanes, bool(home_g["comms"])))
    next_tid = _instant_tid(lanes, bool(home_g["comms"])) \
        + (1 if home_g["instants"] else 0)

    # one track block per foreign recording thread (grid workers, ...)
    foreign = sorted((th for th in groups if th != home),
                     key=lambda th: (groups[th]["first"], th))
    for th in foreign:
        g = groups[th]
        label = g["name"] or f"thread-{th}"
        tid_span = next_tid
        next_tid += 1
        events.append({"ph": "M", "pid": _PID, "tid": tid_span,
                       "name": "thread_name", "args": {"name": label}})
        if g["phases"]:
            tid_step = next_tid
            next_tid += 1
            events.append({"ph": "M", "pid": _PID, "tid": tid_step,
                           "name": "thread_name",
                           "args": {"name": f"{label} steps"}})
            phase_lanes = {}
            for p in sorted({r.phase for r in g["phases"]}):
                phase_lanes[p] = next_tid
                events.append({"ph": "M", "pid": _PID, "tid": next_tid,
                               "name": "thread_name",
                               "args": {"name": f"{label} phase:{p}"}})
                next_tid += 1
        else:
            tid_step, phase_lanes = tid_span, {}
        if g["comms"]:
            tid_comm = next_tid
            next_tid += 1
            events.append({"ph": "M", "pid": _PID, "tid": tid_comm,
                           "name": "thread_name",
                           "args": {"name": f"{label} collectives"}})
        else:
            tid_comm = tid_span
        if g["instants"]:
            tid_inst = next_tid
            next_tid += 1
            events.append({"ph": "M", "pid": _PID, "tid": tid_inst,
                           "name": "thread_name",
                           "args": {"name": f"{label} events"}})
        else:
            tid_inst = tid_span
        emit_group(g, tid_span, tid_step, phase_lanes, tid_comm, tid_inst)

    # flow events: link same-``flow`` lifecycle instants across tracks
    flows: dict = {}
    for i, (ev, tid) in enumerate(placed_instants):
        fid = ev.attrs.get("flow") if isinstance(ev.attrs, dict) else None
        if fid is None:
            continue
        flows.setdefault(fid, []).append((ev.t, i, ev, tid))
    for fid in sorted(flows, key=str):
        chain = sorted(flows[fid])
        if len(chain) < 2:
            continue
        for j, (t, _, ev, tid) in enumerate(chain):
            ph = "s" if j == 0 else ("f" if j == len(chain) - 1 else "t")
            events.append({"ph": ph, "pid": _PID, "tid": tid,
                           "name": "serve:req", "cat": "lifecycle",
                           "id": str(fid), "ts": us(t)})
    return {"schema": CHROME_SCHEMA, "traceEvents": events,
            "displayTimeUnit": "ms", "otherData": dict(meta)}


def phase_timings_to_chrome(doc: dict, **meta) -> dict:
    """Synthesize a Chrome trace from a ``phase_timings/v1`` document.

    The phase-timings schema records per-(step, phase) DURATIONS but no
    timestamps, so the steps are laid out back-to-back in listed order
    (phases within a step in canonical order) -- lane structure and
    relative widths are faithful, absolute placement is synthetic
    (flagged in ``otherData.synthesized``)."""
    if doc.get("schema") != PHASE_SCHEMA:
        raise ValueError(f"expected a {PHASE_SCHEMA} document, got "
                         f"schema={doc.get('schema')!r}")
    driver = str(doc.get("driver", "driver"))
    phase_names = set()
    for srec in doc.get("steps", []):
        phase_names |= set(srec) - {"step"}
    lanes = _lanes(phase_names)
    events = _meta_events(lanes, have_comms=False)
    order = [p for p in PHASES if p in phase_names] \
        + sorted(phase_names - set(PHASES))
    t = 0.0
    for srec in doc.get("steps", []):
        step_t0 = t
        for p in order:
            if p not in srec:
                continue
            dur = float(srec[p])
            events.append({"ph": "X", "pid": _PID, "tid": lanes[p],
                           "name": p, "ts": round(t * 1e6, 3),
                           "dur": round(dur * 1e6, 3),
                           "args": {"driver": driver, "step": srec["step"]}})
            t += dur
        events.append({"ph": "X", "pid": _PID, "tid": _TID_STEP,
                       "name": f"{driver}[{srec['step']}]",
                       "ts": round(step_t0 * 1e6, 3),
                       "dur": round((t - step_t0) * 1e6, 3),
                       "args": {"step": srec["step"]}})
    events.append({"ph": "X", "pid": _PID, "tid": _TID_DRIVER, "name": driver,
                   "ts": 0.0, "dur": round(t * 1e6, 3),
                   "args": {"total_seconds": doc.get("total_seconds")}})
    other = {"synthesized": True,
             "source_schema": PHASE_SCHEMA}
    for k in ("driver", "n", "nb", "device", "lookahead"):
        if k in doc:
            other[k] = doc[k]
    other.update(meta)
    return {"schema": CHROME_SCHEMA, "traceEvents": events,
            "displayTimeUnit": "ms", "otherData": other}


def write_json(path: str, doc: dict) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
