"""Unified runtime observability (ISSUE 5): spans, metrics, exporters.

Four pieces, one subsystem -- the layer every perf PR reports through:

  :mod:`.tracer`       span tracer: ``Tracer`` (explicit nested spans via
                       ``span()``, driver tick channels, engine collective
                       observer) + :func:`phase_hook`, the one-line driver
                       integration all six tuned drivers call
  :mod:`.metrics`      counters / gauges / histograms ->
                       ``obs_metrics/v1`` (op invocation counts,
                       redistribute calls/bytes, tuning-cache events,
                       phase-time histograms)
  :mod:`.phase_timer`  ``PhaseTimer`` -- the historical per-phase
                       attribution tool, now a shim over the tracer
                       (``phase_timings/v1`` unchanged;
                       ``perf.phase_timer`` re-exports from here)
  :mod:`.export`       Chrome-trace/Perfetto ``trace.json`` rendering

CLI: ``python -m perf.trace {run,summary,export}``.  Regression gate over
the bench trajectory: ``tools/bench_diff.py`` (wired into
``tools/check.sh``).
"""
from .metrics import (SCHEMA as METRICS_SCHEMA, MetricsRegistry, REGISTRY,
                      current as current_metrics, scoped as metrics_scope,
                      inc, observe, set_gauge)
from .tracer import (TRACE_SCHEMA, CommEvent, InstantEvent, NullHook,
                     NULL_HOOK, PhaseRecord, Span, Tracer, active_tracer,
                     phase_hook, ring_bytes)
from .phase_timer import PHASES, SCHEMA as PHASE_TIMINGS_SCHEMA, PhaseTimer
from .export import (CHROME_SCHEMA, chrome_trace_doc,
                     phase_timings_to_chrome, write_json)

__all__ = [
    "METRICS_SCHEMA", "MetricsRegistry", "REGISTRY", "current_metrics",
    "metrics_scope", "inc", "observe", "set_gauge",
    "TRACE_SCHEMA", "CommEvent", "InstantEvent", "NullHook", "NULL_HOOK",
    "PhaseRecord", "Span", "Tracer", "active_tracer", "phase_hook",
    "ring_bytes",
    "PHASES", "PHASE_TIMINGS_SCHEMA", "PhaseTimer",
    "CHROME_SCHEMA", "chrome_trace_doc", "phase_timings_to_chrome",
    "write_json",
]
