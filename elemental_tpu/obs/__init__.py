"""Unified runtime observability (ISSUE 5): spans, metrics, exporters.

Four pieces, one subsystem -- the layer every perf PR reports through:

  :mod:`.tracer`       span tracer: ``Tracer`` (explicit nested spans via
                       ``span()``, driver tick channels, engine collective
                       observer) + :func:`phase_hook`, the one-line driver
                       integration all six tuned drivers call
  :mod:`.metrics`      counters / gauges / histograms ->
                       ``obs_metrics/v1`` (op invocation counts,
                       redistribute calls/bytes, tuning-cache events,
                       phase-time histograms)
  :mod:`.phase_timer`  ``PhaseTimer`` -- the historical per-phase
                       attribution tool, now a shim over the tracer
                       (``phase_timings/v1`` unchanged;
                       ``perf.phase_timer`` re-exports from here)
  :mod:`.export`       Chrome-trace/Perfetto ``trace.json`` rendering
                       (thread-keyed tracks + request flow events)

Fleet request telemetry (ISSUE 20) adds three serving-tier modules:

  :mod:`.lifecycle`    per-request ``RequestTrace`` -> the
                       ``serve_timeline/v1`` sub-doc every
                       ``serve_result``/``serve_reject`` carries
  :mod:`.slo`          windowed per-(tenant, grid, bucket) SLO burn
                       rates -> ``serve_slo/v1``
  :mod:`.flight`       fault-triggered flight recorder ->
                       ``flight_record/v1``

CLI: ``python -m perf.trace {run,summary,export,serve}``.  Regression
gate over the bench trajectory: ``tools/bench_diff.py`` (wired into
``tools/check.sh``).
"""
from .metrics import (SCHEMA as METRICS_SCHEMA, FAMILIES as HIST_FAMILIES,
                      MetricsRegistry, REGISTRY,
                      current as current_metrics, scoped as metrics_scope,
                      hist_family, inc, observe, set_gauge,
                      set_hist_family)
from .tracer import (TRACE_SCHEMA, CommEvent, InstantEvent, NullHook,
                     NULL_HOOK, PhaseRecord, Span, Tracer, active_tracer,
                     phase_hook, ring_bytes)
from .phase_timer import PHASES, SCHEMA as PHASE_TIMINGS_SCHEMA, PhaseTimer
from .export import (CHROME_SCHEMA, chrome_trace_doc,
                     phase_timings_to_chrome, write_json)
from .lifecycle import (SCHEMA as TIMELINE_SCHEMA, EDGES as LIFECYCLE_EDGES,
                        RequestTrace, check_timeline)
from .slo import (SCHEMA as SLO_SCHEMA, SLOMonitor, SLOTarget)
from .flight import (SCHEMA as FLIGHT_SCHEMA, FlightRecorder)

__all__ = [
    "METRICS_SCHEMA", "HIST_FAMILIES", "MetricsRegistry", "REGISTRY",
    "current_metrics", "metrics_scope", "hist_family", "inc", "observe",
    "set_gauge", "set_hist_family",
    "TRACE_SCHEMA", "CommEvent", "InstantEvent", "NullHook", "NULL_HOOK",
    "PhaseRecord", "Span", "Tracer", "active_tracer", "phase_hook",
    "ring_bytes",
    "PHASES", "PHASE_TIMINGS_SCHEMA", "PhaseTimer",
    "CHROME_SCHEMA", "chrome_trace_doc", "phase_timings_to_chrome",
    "write_json",
    "TIMELINE_SCHEMA", "LIFECYCLE_EDGES", "RequestTrace", "check_timeline",
    "SLO_SCHEMA", "SLOMonitor", "SLOTarget",
    "FLIGHT_SCHEMA", "FlightRecorder",
]
