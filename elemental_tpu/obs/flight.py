"""Fault-triggered flight recorder -> ``flight_record/v1`` (ISSUE 20).

A bounded ring buffer of structured serve events -- lifecycle edges
(``edge:<name>``, fed by :class:`~elemental_tpu.obs.lifecycle
.RequestTrace`), rejects, circuit-breaker transitions, health/ABFT flags
-- that auto-dumps the last ``capacity`` events the moment a TRIGGER
fires, so the seconds BEFORE a fault are reconstructable after the fact
(the serving-tier equivalent of an aircraft FDR).

Triggers (each produces one ``flight_record/v1`` dump in :attr:`dumps`
and invokes ``on_dump``):

  * ``breaker_open``  -- a :class:`~elemental_tpu.serve.policy
    .CircuitBreaker` transitions to OPEN (wired in ``_transition``);
  * ``unrecovered``   -- a request finalizes ``status="failed"`` after
    escalation/bisection exhausted recovery;
  * ``quota_storm``   -- ``quota_storm_threshold`` consecutive quota
    rejects (a tenant hammering past its outstanding cap);
  * ``chaos_fault``   -- chaos harness cells announce injected faults;
  * ``manual``        -- anything else (CLI smoke uses this).

DETERMINISM CONTRACT: the recorder touches nothing but its injected
``clock`` -- no wall time, no randomness -- and orders events by a
monotone sequence number taken under the lock, so a chaos cell driven by
a virtual clock and a seeded fault plan produces a BYTE-IDENTICAL dump
on replay (pinned by ``tests/serve``; the same run-twice-compare oracle
as ``chaos.fleet_replay_identical``).

Thread-safety: ``record``/``trigger`` are called from the fleet pump,
grid-worker threads, and breaker paths concurrently; one lock serializes
the ring, the sequence counter, and the storm detector.
"""
from __future__ import annotations

import collections
import threading
import time

SCHEMA = "flight_record/v1"

#: trigger vocabulary (informational -- unknown reasons still dump)
TRIGGERS = ("breaker_open", "unrecovered", "quota_storm", "chaos_fault",
            "manual")


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_json_safe(x) for x in v]
    return str(v)


class FlightRecorder:
    """Bounded ring of structured events + trigger-fired dumps."""

    def __init__(self, *, capacity: int = 256, clock=time.monotonic,
                 quota_storm_threshold: int = 8, on_dump=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.clock = clock
        self.quota_storm_threshold = int(quota_storm_threshold)
        self.on_dump = on_dump
        self.dumps: list = []
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._total = 0
        self._quota_run = 0

    # ---- recording ---------------------------------------------------
    def record(self, kind: str, **attrs) -> None:
        """Append one structured event; fires ``quota_storm`` when the
        consecutive-quota-reject run reaches the threshold."""
        storm = False
        with self._lock:
            self._seq += 1
            self._total += 1
            ev = {"seq": self._seq, "t": float(self.clock()),
                  "kind": str(kind)}
            for k, v in attrs.items():
                if v is not None:
                    ev[str(k)] = _json_safe(v)
            self._ring.append(ev)
            if kind == "reject":
                if attrs.get("reason") == "quota":
                    self._quota_run += 1
                    if self._quota_run == self.quota_storm_threshold:
                        storm, self._quota_run = True, 0
                else:
                    self._quota_run = 0
        if storm:
            self.trigger("quota_storm",
                         rejects=self.quota_storm_threshold)

    # ---- triggering --------------------------------------------------
    def trigger(self, reason: str, **attrs) -> dict:
        """Dump the ring NOW as a ``flight_record/v1`` doc."""
        with self._lock:
            events = [dict(ev) for ev in self._ring]
            total = self._total
            trig = {"reason": str(reason), "t": float(self.clock()),
                    "seq": self._seq}
            for k, v in attrs.items():
                if v is not None:
                    trig[str(k)] = _json_safe(v)
            doc = {"schema": SCHEMA, "trigger": trig,
                   "capacity": self.capacity, "recorded": total,
                   "dropped": total - len(events), "events": events}
            self.dumps.append(doc)
        if self.on_dump is not None:
            self.on_dump(doc)
        return doc

    # ---- reads -------------------------------------------------------
    def last_dump(self) -> dict | None:
        with self._lock:
            return self.dumps[-1] if self.dumps else None

    def events(self) -> list:
        """Snapshot of the current ring contents (oldest first)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
