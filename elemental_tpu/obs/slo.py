"""Windowed per-(tenant, grid, bucket) SLO monitoring -> ``serve_slo/v1``.

(ISSUE 20.)  The fleet's online tail-latency/error/shed view: a
count-based sliding window (last ``window`` outcomes per series key) of
every ``serve_result/v1``/``serve_reject/v1`` the fleet settles, from
which :meth:`SLOMonitor.snapshot` computes

  * nearest-rank latency percentiles (p50/p95/p99, milliseconds, over
    completed solves -- sheds carry no latency);
  * ``error_rate`` (non-``ok`` completions / completions) and
    ``shed_rate`` (rejects / all outcomes);
  * BURN RATES against the configured :class:`SLOTarget`: how fast each
    series is consuming its error budget, normalized so 1.0 = exactly
    on target and >1.0 = burning faster than the SLO allows::

        burn_latency = frac(latency > p99_ms) / (1 - latency_objective)
        burn_error   = error_rate / error_budget
        burn_shed    = shed_rate  / shed_budget

A count-based window (rather than wall-clock) keeps snapshots
deterministic under the chaos harness's virtual clocks.  ``snapshot``
emits the STABLE ``serve_slo/v1`` document (series sorted by key) and
mirrors the headline numbers as gauges (``serve_slo_p99_ms``,
``serve_slo_burn_latency``, ...) on the current metrics registry;
``bench_serve.py``'s fleet section records the doc plus the worst
per-tenant p99 as ``serve_slo_p99_ms``, which ``tools/bench_diff.py``
gates lower-is-better.
"""
from __future__ import annotations

import collections
import dataclasses
import threading

from . import metrics as _metrics

SCHEMA = "serve_slo/v1"


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One series' objectives: latency target + budgets."""
    p99_ms: float = 1000.0           # latency objective threshold
    latency_objective: float = 0.99  # fraction that must beat p99_ms
    error_budget: float = 0.01       # allowed non-ok completion fraction
    shed_budget: float = 0.05        # allowed reject fraction

    def to_doc(self) -> dict:
        return {"p99_ms": self.p99_ms,
                "latency_objective": self.latency_objective,
                "error_budget": self.error_budget,
                "shed_budget": self.shed_budget}


DEFAULT_TARGET = SLOTarget()


def _pctl(sorted_vals: list, q: float):
    """Nearest-rank percentile over an ascending list (None if empty)."""
    if not sorted_vals:
        return None
    i = max(0, min(len(sorted_vals) - 1,
                   int(-(-q * len(sorted_vals) // 1)) - 1))
    return sorted_vals[i]


def _bucket_label(bucket) -> str:
    if hasattr(bucket, "key"):
        bucket = bucket.key()
    if isinstance(bucket, (tuple, list)):
        return "x".join(str(b) for b in bucket)
    return str(bucket)


class SLOMonitor:
    """Sliding-window outcome tracker keyed by (tenant, grid, bucket)."""

    def __init__(self, *, window: int = 256, target: SLOTarget | None = None,
                 targets: dict | None = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.target = target if target is not None else DEFAULT_TARGET
        #: per-tenant target overrides {tenant: SLOTarget}
        self.targets = dict(targets or {})
        self._series: dict = {}   # key -> deque[(latency_ms|None, status)]
        self._lock = threading.Lock()

    def target_for(self, tenant: str) -> SLOTarget:
        return self.targets.get(tenant, self.target)

    # ---- feeding -----------------------------------------------------
    def record(self, doc: dict) -> None:
        """Ingest one serve_result/serve_reject document."""
        rejected = "reason" in doc and "status" not in doc
        status = "shed" if rejected else str(doc.get("status", "ok"))
        lat = None if rejected else float(doc.get("latency_s") or 0.0) * 1e3
        key = (str(doc.get("tenant") or "default"),
               str(doc.get("grid") or "-"),
               _bucket_label(doc.get("bucket")))
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                dq = self._series[key] = collections.deque(
                    maxlen=self.window)
            dq.append((lat, status))

    # ---- snapshotting ------------------------------------------------
    def snapshot(self, *, gauges: bool = True, **meta) -> dict:
        """The stable ``serve_slo/v1`` doc; mirrors headline numbers as
        gauges on the current metrics registry unless ``gauges=False``."""
        with self._lock:
            series = {k: list(dq) for k, dq in self._series.items()}
        rows = []
        for key in sorted(series):
            tenant, grid, bucket = key
            outcomes = series[key]
            lats = sorted(l for l, s in outcomes if l is not None)
            n = len(outcomes)
            sheds = sum(1 for _, s in outcomes if s == "shed")
            done = n - sheds
            errors = sum(1 for _, s in outcomes
                         if s not in ("ok", "shed"))
            tgt = self.target_for(tenant)
            p99 = _pctl(lats, 0.99)
            over = sum(1 for l in lats if l > tgt.p99_ms)
            frac_over = (over / len(lats)) if lats else 0.0
            err_rate = (errors / done) if done else 0.0
            shed_rate = (sheds / n) if n else 0.0
            burn = {
                "latency": frac_over / max(1e-12,
                                           1.0 - tgt.latency_objective),
                "error": err_rate / max(1e-12, tgt.error_budget),
                "shed": shed_rate / max(1e-12, tgt.shed_budget),
            }
            row = {"tenant": tenant, "grid": grid, "bucket": bucket,
                   "count": n, "ok": done - errors, "errors": errors,
                   "sheds": sheds,
                   "p50_ms": _pctl(lats, 0.50), "p95_ms": _pctl(lats, 0.95),
                   "p99_ms": p99, "error_rate": err_rate,
                   "shed_rate": shed_rate, "target": tgt.to_doc(),
                   "burn": burn}
            rows.append(row)
            if gauges:
                labels = {"tenant": tenant, "grid": grid, "bucket": bucket}
                if p99 is not None:
                    _metrics.set_gauge("serve_slo_p99_ms", p99, **labels)
                _metrics.set_gauge("serve_slo_burn_latency",
                                   burn["latency"], **labels)
                _metrics.set_gauge("serve_slo_burn_error", burn["error"],
                                   **labels)
                _metrics.set_gauge("serve_slo_burn_shed", burn["shed"],
                                   **labels)
        doc = {"schema": SCHEMA, "window": self.window, "series": rows}
        doc.update(meta)
        return doc

    # ---- headline reads ----------------------------------------------
    def per_tenant_p99_ms(self) -> dict:
        """{tenant: p99 ms over that tenant's pooled window outcomes}."""
        with self._lock:
            series = {k: list(dq) for k, dq in self._series.items()}
        pools: dict = {}
        for (tenant, _, _), outcomes in series.items():
            pools.setdefault(tenant, []).extend(
                l for l, s in outcomes if l is not None)
        return {t: _pctl(sorted(ls), 0.99)
                for t, ls in sorted(pools.items()) if ls}

    def worst_p99_ms(self):
        """Max per-tenant p99 (the single gateable scalar), or None."""
        per = self.per_tenant_p99_ms()
        return max(per.values()) if per else None
