"""elemental_tpu: TPU-native distributed dense linear algebra.

A from-scratch JAX/XLA/shard_map re-design of the capabilities of the
reference framework (Elemental: distributed-memory dense linear algebra over
a 2-D process grid).  See SURVEY.md for the blueprint.
"""
from .core.dist import Dist, MC, MD, MR, VC, VR, STAR, CIRC, LEGAL_PAIRS
from .core.grid import Grid, default_grid, set_default_grid
from .core.environment import (blocksize, set_blocksize, push_blocksize,
                               pop_blocksize, blocksize_scope, Timer, Args,
                               ProgressLog)
from .core.ctrl import (SignCtrl, PolarCtrl, HermitianEigCtrl, SVDCtrl,
                        SchurCtrl, PseudospecCtrl, LDLPivotCtrl, QRCtrl,
                        LeastSquaresCtrl)
from .core.distmatrix import (DistMatrix, from_global, to_global,
                              zeros, remote_updates)
from .core.block import (BlockMatrix, block_from_global, block_from_array,
                         block_to_global, block_to_cyclic, block_from_cyclic,
                         as_elemental)
from .core.multivec import (DistMultiVec, mv_from_global, mv_to_global,
                            mv_zeros, mv_axpy, mv_scale, mv_dot, mv_nrm2,
                            mv_remote_updates, mv_to_distmatrix,
                            mv_from_distmatrix)
from .redist.engine import redistribute, transpose_dist, panel_spread

__version__ = "0.2.0"

from . import (blas, lapack, matrices, optimization, control, lattice, tune,
               obs, resilience, serve)
from .resilience import (certified_solve, HealthMonitor, last_health_report,
                         FaultPlan, FaultSpec, fault_injection)
from .serve import SolverService, Deadline
from .blas import (gemm, herk, syrk, trrk, trsm, trr2k, her2k, syr2k,
                   hemm, symm, trmm, two_sided_trsm, two_sided_trmm,
                   multishift_trsm, quasi_trsm)
from .blas import gemv, ger, hemv, symv, her2, trmv, trsv
from .blas import (axpy, scale, fill, entrywise_map, hadamard,
                   index_dependent_fill, make_trapezoidal, shift_diagonal,
                   make_symmetric, get_diagonal, set_diagonal,
                   diagonal_scale, diagonal_solve, frobenius_norm, max_norm,
                   one_norm, infinity_norm, dot, dotu, trace, transpose,
                   adjoint, real_part, imag_part, max_abs_loc, max_loc,
                   scale_trapezoid, axpy_trapezoid, safe_scale,
                   get_submatrix, set_submatrix)
from .lapack import (cholesky, hpd_solve, cholesky_solve_after,
                     cholesky_pivoted, cholesky_mod)
from .lapack import (lu, lu_solve, lu_solve_after, permute_rows,
                     permute_cols, lu_full_pivot)
from .lapack import (qr, apply_q, explicit_q, least_squares, tsqr, lq,
                     apply_q_lq, explicit_l, qr_col_piv, rq)
from .lapack import ridge, tikhonov, lse, glm
from .lapack import (hermitian_tridiag, apply_q_herm_tridiag, hessenberg,
                     apply_q_hessenberg, bidiag, apply_p_bidiag)
from .lapack import ldl, ldl_solve_after, symmetric_solve, hermitian_solve, inertia
from .lapack import (polar, sign, inverse, triangular_inverse, hpd_inverse,
                     pseudoinverse, square_root, hpd_square_root)
from .lapack import (herm_eig, skew_herm_eig, herm_gen_def_eig, hermitian_svd,
                     svd, tridiag_eig)
from .redist.interior import interior_view, interior_update, vstack, hstack
from .optimization import (MehrotraCtrl, lp, qp, socp, soft_threshold, svt,
                           bp, lav, nnls, lasso, svm, rpca,
                           lp_affine, qp_affine, socp_affine,
                           ruiz_equil, geom_equil, symmetric_ruiz_equil,
                           lp_sparse, lav_sparse, bp_sparse,
                           cp, ds, en, nmf, sparse_inv_cov,
                           long_only_portfolio, tv)
from .control import sylvester, lyapunov, riccati
from .lattice import lll, is_lll_reduced, shortest_vector
from .lapack.schur import schur, triang_eig, eig, pseudospectra
from .lapack.props import (determinant, safe_determinant, hpd_determinant,
                           two_norm_estimate, condition, nuclear_norm,
                           schatten_norm, two_norm)
from .io import (print_matrix, write_matrix, read_matrix, checkpoint,
                 restore, write_matrix_market, read_matrix_market, display,
                 spy)
from . import sparse
from .sparse import (Graph, DistGraph, SparseMatrix, DistSparseMatrix,
                     DistMap, sparse_from_coo, dist_sparse_from_coo,
                     cg, cgls, gmres, sparse_direct_solve)
