"""Deterministic fault injection for the redistribution engine.

The adversarial half of the resilience subsystem (ISSUE 7): a seeded
:class:`FaultPlan` installs into ``redist.engine`` (the one choke point
every driver's data motion routes through -- the same seam the ISSUE-5
observers ride) and corrupts CHOSEN public ``redistribute`` /
``panel_spread`` payloads on CHOSEN calls, so tests can *prove* each
corruption class is either repaired by the certified-solve escalation
ladder or surfaced as a health report -- never silently propagated into
results.

Determinism is the contract: every corruption site derives its own
``numpy`` Generator from ``(seed, target, call index, output index,
kind)``, so an identical plan replayed over an identical run produces
BIT-IDENTICAL corrupted payloads (pinned by
``tests/resilience/test_faults.py``); the :attr:`FaultPlan.log` records
(flat indices, before, after) per event for exactly that comparison.

Corruption classes (``FaultSpec.kind``):

  * ``'bitflip'``  -- XOR one high (exponent-region) bit of each chosen
    element: the single-event-upset model;
  * ``'scale'``    -- multiply chosen elements by ``FaultSpec.factor``
    (default 1e12): the growth-blowup model, finite but catastrophic;
  * ``'nan'``      -- splat NaN: the poisoned-collective model.

Targets (``FaultSpec.target``): ``'redistribute'`` and ``'panel_spread'``
-- the engine's two public data-motion entries -- plus ``'compute'``
(ISSUE 9): LOCAL math outputs routed through ``engine.apply_fault`` --
the lu/cholesky/qr panel kernels and the serve executor's batched solve
-- so chaos tests cover soft errors in local compute, not just corrupted
collectives.  Call indices count Python-level entries per target (the
same counting semantics as ``engine.REDIST_COUNTS``), starting at 0 when
the plan is installed; ``every=True`` corrupts every call from ``call``
onward (the persistent-corruption mode certified solves must SURFACE, vs
the one-shot mode they must REPAIR).

Like the tracer and the health monitor this is an EAGER-mode tool: a
payload that is still a jax tracer (an enclosing jit) is counted but
passed through uncorrupted.
"""
from __future__ import annotations

import dataclasses

import numpy as np

FAULT_KINDS = ("bitflip", "scale", "nan")
#: 'compute' was APPENDED in ISSUE 9 -- the enumerate-derived seed words
#: below keep the original targets' corruption streams bit-identical
FAULT_TARGETS = ("redistribute", "panel_spread", "compute")

#: stable per-target / per-kind seed words (never reorder: part of the
#: determinism contract -- a plan's corruption stream is pinned by tests)
_TARGET_WORD = {t: i + 1 for i, t in enumerate(FAULT_TARGETS)}
_KIND_WORD = {k: i + 1 for i, k in enumerate(FAULT_KINDS)}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One corruption rule of a plan.

    ``window=(start, stop)`` (ISSUE 11) scopes the rule to driver panel
    STEPS ``start <= k < stop`` -- drivers that announce their current
    step via ``engine.set_fault_step`` (the ABFT-guarded factorizations)
    gate the rule on it, so chaos can deterministically corrupt a chosen
    panel.  Windowed one-shot rules (``every=False``) fire exactly ONCE:
    on the first matching call inside the window (``call`` then acts as
    a minimum call index, default 0) -- so a recovery retry of the
    corrupted panel re-executes CLEAN.  ``every=True`` windows corrupt
    every in-window call from ``call`` onward.  Outside any
    ``set_fault_step`` scope a windowed rule never fires; the corruption
    stream of non-windowed rules is unchanged (replay bit-identity)."""
    target: str                  # "redistribute" | "panel_spread"
    kind: str                    # "bitflip" | "scale" | "nan"
    call: int = 0                # nth public entry of ``target`` (0-based)
    every: bool = False          # corrupt every call index >= ``call``
    nelem: int = 1               # elements corrupted per payload array
    factor: float = 1e12         # 'scale' multiplier
    window: tuple | None = None  # (start, stop) panel-step scope

    def __post_init__(self):
        if self.target not in FAULT_TARGETS:
            raise ValueError(f"unknown fault target {self.target!r}; "
                             f"expected one of {FAULT_TARGETS}")
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {FAULT_KINDS}")
        if self.call < 0 or self.nelem < 1:
            raise ValueError("FaultSpec needs call >= 0 and nelem >= 1")
        if self.window is not None:
            w = tuple(self.window)
            if len(w) != 2 or int(w[0]) < 0 or int(w[1]) <= int(w[0]):
                raise ValueError("FaultSpec window needs (start, stop) "
                                 "with 0 <= start < stop")
            object.__setattr__(self, "window", (int(w[0]), int(w[1])))

    def matches(self, target: str, call: int,
                step: int | None = None) -> bool:
        if self.target != target:
            return False
        if self.window is not None:
            if step is None or not (self.window[0] <= step
                                    < self.window[1]):
                return False
            return call >= self.call  # one-shot gating lives in the plan
        return call >= self.call if self.every else call == self.call


@dataclasses.dataclass
class FaultEvent:
    """One applied corruption (host copies -- the determinism evidence)."""
    target: str
    call: int
    output: int                  # index within the entry's output tuple
    kind: str
    shape: tuple
    dtype: str
    indices: np.ndarray          # flat element indices corrupted
    before: np.ndarray
    after: np.ndarray
    step: int | None = None      # announced panel step, if any (ISSUE 11)


class FaultPlan:
    """A seeded, replayable corruption schedule (see module docstring).

    Install with ``redist.engine.fault_injection(plan)`` (re-exported as
    ``elemental_tpu.resilience.fault_injection``); :meth:`reset` rewinds
    the call counters and the log so the SAME plan object can replay a
    second identical run for bit-identity comparison."""

    def __init__(self, seed: int, faults):
        self.seed = int(seed)
        self.faults = tuple(faults)
        for f in self.faults:
            if not isinstance(f, FaultSpec):
                raise TypeError(f"FaultPlan needs FaultSpec entries, got "
                                f"{type(f).__name__}")
        self.calls: dict = {t: 0 for t in FAULT_TARGETS}
        self.log: list[FaultEvent] = []
        self.step: int | None = None      # current driver panel step
        self._window_fired: set = set()   # one-shot windowed rules spent

    def reset(self) -> "FaultPlan":
        self.calls = {t: 0 for t in FAULT_TARGETS}
        self.log = []
        self.step = None
        self._window_fired = set()
        return self

    def set_step(self, step: int | None) -> None:
        """Announce the driver's current panel step (``None`` = outside
        any step scope).  Drivers call this through
        ``engine.set_fault_step``; it gates ``window=`` rules only."""
        self.step = None if step is None else int(step)

    # ---- the engine-facing entry ------------------------------------
    def apply(self, target: str, outputs: tuple) -> tuple:
        """Count one public ``target`` entry and return the (possibly
        corrupted) output arrays.  Tracer payloads pass through."""
        call = self.calls[target]
        self.calls[target] = call + 1
        matched = [(si, f) for si, f in enumerate(self.faults)
                   if f.matches(target, call, self.step)
                   and not (f.window is not None and not f.every
                            and si in self._window_fired)]
        if not matched:
            return tuple(outputs)
        import jax
        if any(isinstance(o, jax.core.Tracer) for o in outputs):
            return tuple(outputs)         # inside jit: eager-only tool
        specs = []
        for si, f in matched:
            if f.window is not None and not f.every:
                self._window_fired.add(si)  # windowed one-shot: now spent
            specs.append(f)
        out = list(outputs)
        for spec in specs:
            for oi, arr in enumerate(out):
                out[oi] = self._corrupt(arr, spec, target, call, oi)
        return tuple(out)

    # ---- corruption kernels -----------------------------------------
    def _corrupt(self, arr, spec: FaultSpec, target: str, call: int,
                 oi: int):
        import jax.numpy as jnp
        dt = np.dtype(arr.dtype)
        if not np.issubdtype(dt, np.inexact) or arr.size == 0:
            return arr
        rng = np.random.default_rng(
            [self.seed, _TARGET_WORD[target], call, oi,
             _KIND_WORD[spec.kind]])
        n = int(arr.size)
        k = min(int(spec.nelem), n)
        idx = np.sort(rng.choice(n, size=k, replace=False))
        host = np.asarray(arr)
        before = host.reshape(-1)[idx].copy()
        after = self._values(before, spec, rng, dt)
        coords = np.unravel_index(idx, host.shape)
        new = arr.at[tuple(jnp.asarray(c) for c in coords)].set(
            jnp.asarray(after))
        self.log.append(FaultEvent(
            target=target, call=call, output=oi, kind=spec.kind,
            shape=tuple(host.shape), dtype=dt.name,
            indices=idx, before=before, after=after.copy(),
            step=self.step))
        return new

    @staticmethod
    def _values(before: np.ndarray, spec: FaultSpec, rng, dt) -> np.ndarray:
        if spec.kind == "nan":
            return np.full_like(before, np.nan)
        if spec.kind == "scale":
            return (before * before.dtype.type(spec.factor)).astype(dt)
        # bitflip: XOR one exponent-region bit per element (complex flips
        # the real component's representation)
        vals = before.copy()
        comp = np.iscomplexobj(vals)
        re = np.ascontiguousarray(vals.real) if comp else vals
        fdt = re.dtype
        udt = np.dtype(f"uint{fdt.itemsize * 8}")
        bits = fdt.itemsize * 8
        # mantissa-top .. exponent bits: always a macroscopic change, never
        # the sign bit alone
        b = rng.integers(bits - 12, bits - 1, size=vals.shape)
        mask = np.left_shift(np.ones_like(b, dtype=udt), b.astype(udt))
        flipped = (re.view(udt) ^ mask).view(fdt)
        if comp:
            return (flipped + 1j * vals.imag).astype(dt)
        return flipped.astype(dt)

    # ---- summaries ---------------------------------------------------
    def fired(self) -> int:
        """Number of corruption events applied so far."""
        return len(self.log)

    def summary(self) -> list:
        return [{"target": ev.target, "call": ev.call, "output": ev.output,
                 "kind": ev.kind, "nelem": int(ev.indices.size)}
                for ev in self.log]


def logs_identical(a: FaultPlan, b: FaultPlan) -> bool:
    """Bit-exact comparison of two plans' corruption logs (the
    determinism oracle: same seed + same run => identical)."""
    if len(a.log) != len(b.log):
        return False
    for ea, eb in zip(a.log, b.log):
        if (ea.target, ea.call, ea.output, ea.kind, ea.shape, ea.dtype,
                ea.step) \
                != (eb.target, eb.call, eb.output, eb.kind, eb.shape,
                    eb.dtype, eb.step):
            return False
        if not np.array_equal(ea.indices, eb.indices):
            return False
        if ea.before.tobytes() != eb.before.tobytes() \
                or ea.after.tobytes() != eb.after.tobytes():
            return False
    return True
