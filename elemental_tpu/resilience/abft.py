"""ABFT checksum-guarded factorizations (Huang-Abraham, ISSUE 11 + 15).

Algorithm-based fault tolerance for the distributed LU / Cholesky / QR
drivers: every panel step maintains PER-COLUMN checksum vectors through
the same redistribute / ``panel_spread`` / trailing-matmul path the
unguarded schedule uses, and verifies the checksum invariants with one
cheap reduction per region per panel.  A violated invariant marks the
panel CORRUPTED; the :mod:`.recovery` panel-transaction layer then rolls
the step back and re-executes only that panel (bounded retries), so a
one-shot transient fault costs ONE recomputed panel instead of a whole
O(n^3) re-solve.

The invariants (all per-column sums, evaluated in global column order so
any two distributions compare elementwise):

  * **transport** -- ``colsum(X)`` is preserved by every redistribute /
    ``panel_spread`` (data motion moves elements, it never changes
    them); the ``[STAR,MR]`` adjoint of a spread satisfies
    ``colsum(L21^H) == conj(rowsum(L21))``.
  * **factor (LU)** -- ``colsum(P . panel) == colsum(L) @ U``: column
    sums are invariant under row permutation, so the packed panel's
    unit-lower/upper split must reproduce the gathered panel's sums.
  * **factor (Cholesky)** -- ``colsum(L11 L11^H) == colsum(L11) @
    L11^H`` against the symmetrized diagonal block.
  * **factor (QR)** -- ``c(A) = c(Q R)``: the packed panel is the
    compact-WY image ``(I - V T V^H) [R; 0]`` of the gathered columns,
    so ``colsum(panel) == colsum(R) - cV @ (T @ (V1^H R))`` with
    ``cV = 1^T V`` -- valid for BOTH the classic larfg recurrence and
    the TSQR tree (the tree preserves column sums leaf-to-root, so one
    check at reconstruction covers it; the packed ``(V, tau, R)`` is
    self-consistent whichever panel produced it).
  * **solve** -- ``colsum(L11 @ U12) == colsum(A12)`` (LU row-block
    solve) / ``colsum(L21 L11^H) == colsum(A21)`` (Cholesky panel).
  * **trailing update (Huang-Abraham)** -- ``colsum(A22') ==
    colsum(A22) - colsum(L21) @ U12``, with ``colsum(L21)`` taken from
    the REPLICATED packed panel so the prediction is independent of the
    transported operands the update itself consumed.  QR's compact-WY
    form obeys the same separable identity: ``1^T (V_mc W) == cV @ W``
    with ``W = T^H (V^H A2)``, so the trailing colsums are pinned by
    ``c(A2) - cV @ W`` with ``cV`` again from the replicated panel,
    independent of the transported ``V_mc``.  (Cholesky's
    masked-lower update has no separable column identity; its trailing
    check is consistency-grade -- the predicted delta is reduced from
    the update product itself -- while its fault surface is covered by
    the transport/factor/solve checks above.)

Per-column sums (not one scalar sum) are the detection contract: a
single bit flip in an (m x n) region moves one COLUMN's sum by the
element-scale change, a ~1/eps factor above the reduction-order noise
floor of that column, where a whole-matrix scalar sum would bury the
same signal under sqrt(m*n) accumulated rounding.

Thresholds are relative to per-column mass (``sum |x|``): ``transport``
checks use ``tol_factor * eps * sqrt(rows)`` (reduction-order noise
only), ``compute`` checks ``tol_factor * eps * (nb + sqrt(rows))``
(one blocked matmul of rounding).  With ``comm_precision`` set the wire
is int8/bf16 block-scaled and every check widens by ``quant_slack``
(default 0.25 relative) so quantization never false-positives --
documented trade: quantized wire keeps nan/scale-class detection but
may miss single-bitflip-class faults below the slack.

Eager-mode semantics match the health monitor: check REDUCTIONS are
always traced (so the ``lu_abft`` / ``cholesky_abft`` comm-plan goldens
pin the guarded schedule), but comparison/rollback happen host-side and
degrade to pass-through under jit -- one attempt per panel, static
control flow.

``lu(..., abft=True)`` / ``cholesky(..., abft=True)`` /
``qr(..., abft=True)`` dispatch here (``abft=`` also accepts a
caller-owned :class:`AbftGuard`); ``abft=None`` never imports this
module -- the unguarded drivers are bit-identical to before and their
comm goldens unchanged.  The guarded schedule is the CLASSIC
right-looking one on every grid (lookahead / crossover / calu do not
compose with per-panel transactions and are ignored; qr keeps its
``panel=`` choice -- both 'classic' and 'tsqr' are guarded), including
1x1 -- so fault seams and comm plans are grid-uniform.
"""
from __future__ import annotations

import math

import numpy as np

ABFT_SCHEMA = "abft_report/v1"

#: base threshold multiple on eps (see module docstring)
TOL_FACTOR = 64.0

#: flat relative slack added to every check under quantized wire
QUANT_SLACK = 0.25

#: bounded retries per panel transaction (attempts = 1 + max_retries)
MAX_RETRIES = 2


def _is_tracer(x) -> bool:
    import jax
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------
# distribution-agnostic checksum reductions.  All return vectors in
# GLOBAL column (or row) order, so sums of the same logical region under
# different distributions compare elementwise; padding rows/cols are
# masked out (the engine only guarantees padding stays zero on the paths
# it owns).
# ---------------------------------------------------------------------

def _indices(dm):
    from ..blas.level1 import _global_indices
    return _global_indices(dm)


def _colsum(dm, absval: bool = False):
    """Global-order per-column sums of a DistMatrix (any distribution)."""
    import jax.numpy as jnp
    I, J = _indices(dm)
    gm, gn = dm.gshape
    loc = jnp.abs(dm.local) if absval else dm.local
    vals = jnp.where((I < gm)[:, None], loc, 0)
    return _scatter_cols(jnp.sum(vals, axis=0), J, gn)


def _rowsum(dm):
    """Global-order per-row sums of a DistMatrix."""
    import jax.numpy as jnp
    I, J = _indices(dm)
    gm, gn = dm.gshape
    vals = jnp.where((J < gn)[None, :], dm.local, 0)
    partial = jnp.sum(vals, axis=1)
    ok = I < gm
    return jnp.zeros((gm,), partial.dtype).at[
        jnp.where(ok, I, 0)].add(jnp.where(ok, partial, 0))


def _wcolsum(dm, w, absval: bool = False):
    """``w @ dm`` in global column order: the checksum-row image of a
    row-replicated operand (``[STAR,VR]`` / ``[STAR,MR]`` row blocks,
    where local rows == global rows)."""
    import jax.numpy as jnp
    _, J = _indices(dm)
    gn = dm.gshape[1]
    loc = dm.local[:w.shape[0], :]
    if absval:
        partial = jnp.matmul(jnp.abs(w), jnp.abs(loc))
    else:
        partial = jnp.matmul(w, loc)
    return _scatter_cols(partial, J, gn)


def _scatter_cols(partial, J, gn: int):
    import jax.numpy as jnp
    ok = J < gn
    return jnp.zeros((gn,), partial.dtype).at[
        jnp.where(ok, J, 0)].add(jnp.where(ok, partial, 0))


def _arr_colsum(arr, rows: int, absval: bool = False):
    """Per-column sums of a replicated storage array's first ``rows``
    rows (replicated blocks carry their logical region contiguously)."""
    import jax.numpy as jnp
    a = arr[:rows, :]
    return jnp.sum(jnp.abs(a) if absval else a, axis=0)


# ---------------------------------------------------------------------
# the guard
# ---------------------------------------------------------------------

class _DeferredCheck:
    """One recorded invariant: jnp vectors until host evaluation."""
    __slots__ = ("name", "pred", "actual", "mass", "kind", "rows", "nb")

    def __init__(self, name, pred, actual, mass, kind, rows, nb):
        self.name = name
        self.pred = pred
        self.actual = actual
        self.mass = mass
        self.kind = kind
        self.rows = rows
        self.nb = nb


class AbftGuard:
    """Checksum bookkeeping + thresholds + the ``abft_report/v1`` doc.

    Reusable as the ``abft=`` argument of ``lu`` / ``cholesky`` (pass
    ``True`` for a driver-internal guard; the report then lands in
    :func:`last_abft_report`).  One guard covers one driver invocation
    (:meth:`begin` resets it)."""

    def __init__(self, *, tol_factor: float = TOL_FACTOR,
                 quant_slack: float = QUANT_SLACK,
                 max_retries: int = MAX_RETRIES):
        self.tol_factor = float(tol_factor)
        self.quant_slack = float(quant_slack)
        self.max_retries = max(int(max_retries), 0)
        self.driver: str | None = None
        self._eps = 1e-7
        self._quant = False
        self._report = None
        self._reset_counters()

    def _reset_counters(self):
        self._pending: list[_DeferredCheck] = []
        self._checks = 0
        self._panels = 0
        self._violations: list[dict] = []
        self._recovered: list[int] = []
        self._unrecovered: list[int] = []
        self._recomputes = 0

    # ---- driver binding ---------------------------------------------
    def begin(self, driver: str, A, comm_precision=None) -> "AbftGuard":
        import jax.numpy as jnp
        self.driver = str(driver)
        self._report = None
        self._reset_counters()
        dt = A.dtype
        self._eps = float(jnp.finfo(dt).eps) \
            if jnp.issubdtype(dt, jnp.inexact) else 1e-7
        self._quant = comm_precision is not None
        return self

    # ---- per-attempt recording --------------------------------------
    def start_attempt(self) -> None:
        self._pending = []

    def check(self, name: str, pred, actual, mass=None,
              kind: str = "transport", rows: int = 1, nb: int = 1) -> None:
        """Record one deferred invariant: ``pred`` vs ``actual`` (global-
        order checksum vectors), denominated by per-column ``mass``."""
        self._checks += 1
        self._pending.append(_DeferredCheck(name, pred, actual, mass,
                                            kind, int(rows), int(nb)))

    def end_attempt(self, step: int, attempt: int) -> list[dict]:
        """Host-evaluate the attempt's checks -> violation dicts (empty
        under jit: tracer-valued checks are counted, never compared)."""
        pending, self._pending = self._pending, []
        viols = []
        for ck in pending:
            if _is_tracer(ck.pred) or _is_tracer(ck.actual):
                continue                  # traced: counting only
            v = self._evaluate(ck, step, attempt)
            if v is not None:
                viols.append(v)
        return viols

    def _rtol(self, ck: _DeferredCheck) -> float:
        base = self.tol_factor * self._eps
        if ck.kind == "compute":
            rtol = base * (ck.nb + math.sqrt(max(ck.rows, 1)))
        else:
            rtol = base * math.sqrt(max(ck.rows, 1))
        if self._quant:
            rtol += self.quant_slack
        return rtol

    def _evaluate(self, ck: _DeferredCheck, step: int,
                  attempt: int) -> dict | None:
        pred = np.asarray(ck.pred, dtype=np.complex128) \
            if np.iscomplexobj(np.asarray(ck.pred)) \
            else np.asarray(ck.pred, dtype=np.float64)
        actual = np.asarray(ck.actual).astype(pred.dtype)
        mass = np.abs(np.asarray(ck.mass, dtype=np.float64)) \
            if ck.mass is not None else np.zeros_like(np.abs(pred))
        with np.errstate(over="ignore", invalid="ignore"):
            err = np.abs(pred - actual)
            floor = mass + np.abs(actual) + np.abs(pred)
            den = floor + 1e-3 * (float(np.mean(floor))
                                  if floor.size else 0.0) + 1e-30
            rel = err / den
        bad = ~np.isfinite(rel) | (rel > self._rtol(ck))
        if not bool(bad.any()):
            return None
        finite = bool(np.isfinite(err).all())
        worst = None if not finite else float(np.nanmax(rel))
        return {"step": int(step), "attempt": int(attempt),
                "phase": ck.name, "kind": ck.kind,
                "value": worst, "nonfinite": not finite,
                "columns": int(np.count_nonzero(bad))}

    # ---- transaction outcomes (recovery.py drives these) -------------
    def note_violation(self, viols: list[dict]) -> None:
        self._violations.extend(viols)

    def note_recompute(self) -> None:
        self._recomputes += 1

    def note_recovered(self, step: int) -> None:
        self._recovered.append(int(step))

    def note_unrecovered(self, step: int) -> None:
        self._unrecovered.append(int(step))

    def note_panel(self) -> None:
        self._panels += 1

    # ---- report ------------------------------------------------------
    @property
    def checks(self) -> int:
        return self._checks

    @property
    def recompute_count(self) -> int:
        """Panel re-executions (the recovery-cost counter the ISSUE-11
        acceptance test pins to 1 for a single one-shot fault)."""
        return self._recomputes

    def report(self, emit: bool = True) -> dict:
        """The ``abft_report/v1`` document.  First emitting call bumps
        ``abft_checks`` / ``abft_violations`` / ``abft_recovered_panels``
        on the obs metrics registry; later calls return the cache."""
        if self._report is not None:
            return self._report
        doc = {"schema": ABFT_SCHEMA, "driver": self.driver,
               "ok": not self._unrecovered,
               "panels": self._panels, "checks": self._checks,
               "violations": list(self._violations),
               "recovered_panels": sorted(set(self._recovered)),
               "unrecovered_panels": sorted(set(self._unrecovered)),
               "recompute_count": self._recomputes,
               "max_retries": self.max_retries,
               "quantized_wire": self._quant}
        self._report = doc
        if emit:
            self._emit(doc)
        return doc

    def _emit(self, doc: dict) -> None:
        from ..obs import metrics as _metrics
        drv = doc["driver"] or "?"
        _metrics.inc("abft_checks", doc["checks"], driver=drv)
        if doc["violations"]:
            _metrics.inc("abft_violations", len(doc["violations"]),
                         driver=drv)
        if doc["recovered_panels"]:
            _metrics.inc("abft_recovered_panels",
                         len(doc["recovered_panels"]), driver=drv)
        _LAST[drv] = doc
        _LAST["_latest"] = doc

    def flag_health(self, monitor) -> None:
        """Push unrecovered violations into a bound HealthMonitor so they
        surface through the existing ``health_report/v1`` path (and from
        there through ``certified_solve`` / serve certificates)."""
        if monitor is None or not self._unrecovered:
            return
        for v in self._violations:
            if v["step"] in self._unrecovered:
                monitor.flag("abft", v["phase"], v["step"], v["value"])


#: most recent emitted abft report per driver (+ "_latest")
_LAST: dict = {}


def last_abft_report(driver: str | None = None) -> dict | None:
    """The most recently emitted ``abft_report/v1`` (per driver, or the
    latest overall with ``driver=None``)."""
    return _LAST.get(driver if driver is not None else "_latest")


def resolve_abft(abft) -> AbftGuard:
    """The driver-facing ``abft=`` resolver: a caller-owned
    :class:`AbftGuard` passes through, any other truthy value makes a
    fresh driver-internal guard."""
    return abft if isinstance(abft, AbftGuard) else AbftGuard()


# ---------------------------------------------------------------------
# guarded LU (classic right-looking schedule + per-panel transactions)
# ---------------------------------------------------------------------

def abft_lu(A, nb=None, precision=None, update_precision=None,
            comm_precision=None, timer=None, health=None, abft=True,
            plan=None):
    """Checksum-guarded LU with partial pivoting (see module docstring).

    Same ``(packed LU, perm)`` contract as ``lapack.lu``; the schedule
    is the classic right-looking one on every grid.  Reached via
    ``lu(..., abft=)``."""
    import jax.numpy as jnp
    from ..core.dist import MC, MR, STAR, VR
    from ..core.distmatrix import DistMatrix
    from ..core.view import view
    from ..redist.engine import apply_fault, redistribute
    from ..blas.level3 import _blocksize, local_rank_update
    from ..lapack.lu import (_apply_swaps_moved, _hi, _moved_rows,
                             _panel_dispatch, _phase_hook,
                             _unit_lower_inv, _update_cols_ge,
                             _update_cols_lt)
    from .recovery import run_step
    from .health import attach_health

    guard = resolve_abft(abft)
    m, n = A.gshape
    g = A.grid
    guard.begin("lu", A, comm_precision=comm_precision)
    tm = _phase_hook("lu", timer)
    hm = None
    if health:
        tm, hm = attach_health("lu", health, tm, scale_from=A)
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    upd = precision if update_precision is None else update_precision
    cp = comm_precision
    perm0 = jnp.arange(m)
    tm.start()

    def col_up(e):
        return min(-(-e // c) * c, n)

    def step_fn(state, k, s):
        # ticks are BUFFERED per attempt and replayed only after the
        # step commits, so health never sees a rolled-back attempt
        A, perm = state
        ticks = []
        e = min(s + ib, kend)
        nbw = e - s
        e_up = col_up(e)
        pan_v = view(A, rows=(s, m), cols=(s, e_up))
        pan_sum = _colsum(pan_v)
        pan_mass = _colsum(pan_v, absval=True)
        panel = redistribute(pan_v, STAR, STAR, comm_precision=cp)
        ploc = panel.local[:m - s, :e_up - s]
        guard.check("panel_gather", pan_sum, jnp.sum(ploc, axis=0),
                    mass=pan_mass, kind="transport", rows=m - s)
        Pf, pperm = _panel_dispatch(ploc[:, :nbw], nbw, precision, plan)
        Pf, = apply_fault("compute", (Pf,))
        # factor invariant: colsums survive the panel's row permutation
        cL = (jnp.sum(jnp.tril(Pf[:nbw], -1), axis=0)
              + jnp.sum(Pf[nbw:], axis=0) + 1.0)
        U11 = jnp.triu(Pf[:nbw])
        guard.check("panel", jnp.matmul(cL, U11),
                    jnp.sum(ploc[:, :nbw], axis=0),
                    mass=jnp.sum(jnp.abs(ploc[:, :nbw]), axis=0),
                    kind="compute", rows=m - s, nb=nbw)
        perm = perm.at[s:].set(jnp.take(perm[s:], pperm, axis=0))
        idx, src = _moved_rows(pperm, nbw)
        valid = idx < (m - s)
        A = _apply_swaps_moved(A, idx + s,
                               jnp.clip(src, 0, m - s - 1) + s, valid)
        ticks.append(("swap", (A,)))
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        pf_w = redistribute(Pf_ss, MC, MR)
        guard.check("panel_write", jnp.sum(Pf_w, axis=0), _colsum(pf_w),
                    mass=jnp.sum(jnp.abs(Pf_w), axis=0),
                    kind="transport", rows=m - s)
        A = _update_cols_lt(A, pf_w, (s, m), (s, e_up), e)
        if e >= n:
            return (A, perm), Pf, pperm, ticks
        Li11 = _unit_lower_inv(jnp.tril(Pf[:nbw, :], -1)
                               + jnp.eye(nbw, dtype=Pf.dtype),
                               nbw, precision)
        a1n_v = view(A, rows=(s, e), cols=(s, n))
        a1n_sum = _colsum(a1n_v)
        a1n_mass = _colsum(a1n_v, absval=True)
        A1n = redistribute(a1n_v, STAR, VR, comm_precision=cp)
        guard.check("solve_gather", a1n_sum, _colsum(A1n),
                    mass=a1n_mass, kind="transport", rows=nbw)
        u1n = jnp.matmul(Li11, A1n.local, precision=_hi(precision)
                         ).astype(Pf.dtype)
        U1n = DistMatrix(u1n, (nbw, n - s), STAR, VR, 0, 0, g)
        cL11 = jnp.sum(jnp.tril(Pf[:nbw], -1), axis=0) + 1.0
        guard.check("solve", _wcolsum(U1n, cL11), _colsum(A1n),
                    mass=_wcolsum(U1n, cL11, absval=True) + a1n_mass,
                    kind="compute", rows=nbw, nb=nbw)
        U1n_mr = redistribute(U1n, STAR, MR, comm_precision=cp)
        guard.check("solve_move", _colsum(U1n), _colsum(U1n_mr),
                    mass=_colsum(U1n, absval=True), kind="transport",
                    rows=nbw)
        u_w = redistribute(U1n_mr, MC, MR)
        guard.check("u_write", _colsum(U1n_mr), _colsum(u_w),
                    mass=_colsum(U1n_mr, absval=True), kind="transport",
                    rows=nbw)
        A = _update_cols_ge(A, u_w, (s, e), (s, n), e)
        ticks.append(("solve", (U1n_mr,)))
        if e < m:
            t_view = view(A, rows=(e, m), cols=(e, n))
            t_pre = _colsum(t_view)
            t_mass = _colsum(t_view, absval=True)
            U12_mr = view(U1n_mr, cols=(e - s, n - s))
            L21_ss = DistMatrix(Pf[nbw:, :], (m - e, nbw), STAR, STAR,
                                0, 0, g)
            L21_mc = redistribute(L21_ss, MC, STAR)
            cL21 = jnp.sum(Pf[nbw:, :], axis=0)
            guard.check("l21_move", cL21, _colsum(L21_mc),
                        mass=jnp.sum(jnp.abs(Pf[nbw:, :]), axis=0),
                        kind="transport", rows=m - e)
            A = local_rank_update(A, L21_mc.local, U12_mr.local,
                                  rows=(e, m), cols=(e, n), precision=upd)
            # Huang-Abraham: predicted trailing colsums from the
            # REPLICATED panel, measured against the updated block
            delta = _wcolsum(U12_mr, cL21)
            dmass = _wcolsum(U12_mr, cL21, absval=True)
            guard.check("update", t_pre - delta,
                        _colsum(view(A, rows=(e, m), cols=(e, n))),
                        mass=t_mass + dmass, kind="compute",
                        rows=m - e, nb=nbw)
            ticks.append(("update", (A,)))
        return (A, perm), Pf, pperm, ticks

    state = (A, perm0)
    for k, s in enumerate(range(0, kend, ib)):
        state, Pf, pperm, ticks = run_step(
            guard, k, lambda st: step_fn(st, k, s), state)
        tm.tick("panel", k, Pf, pperm)
        for phase, arrs in ticks:
            tm.tick(phase, k, *arrs)
    guard.flag_health(hm)
    guard.report()
    if hm is not None:
        hm.report()
    return state


# ---------------------------------------------------------------------
# guarded Cholesky (classic LVar3 schedule + per-panel transactions)
# ---------------------------------------------------------------------

def abft_cholesky(A, nb=None, precision=None, comm_precision=None,
                  timer=None, health=None, abft=True, plan=None):
    """Checksum-guarded lower Cholesky (see module docstring).  Same
    contract as ``lapack.cholesky(..., uplo='L')``; reached via
    ``cholesky(..., abft=)``."""
    import jax.numpy as jnp
    from ..core.dist import MC, MR, STAR, VC
    from ..core.distmatrix import DistMatrix
    from ..core.view import view, update_view
    from ..redist.engine import panel_spread, redistribute
    from ..blas.level1 import make_trapezoidal
    from ..blas.level3 import _blocksize, _mask_triangle
    from ..lapack.lu import _hi, _phase_hook
    from ..lapack.cholesky import _potrf_inv
    from .recovery import run_step
    from .health import attach_health

    guard = resolve_abft(abft)
    m = A.gshape[0]
    g = A.grid
    guard.begin("cholesky", A, comm_precision=comm_precision)
    tm = _phase_hook("cholesky", timer)
    hm = None
    if health:
        tm, hm = attach_health("cholesky", health, tm, scale_from=A)
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), m)
    cp = comm_precision
    tm.start()

    def step_fn(L, k, s):
        # ticks buffered per attempt, replayed on commit (see abft_lu)
        ticks = []
        e = min(s + ib, m)
        w = e - s
        a11_v = view(L, rows=(s, e), cols=(s, e))
        a11_sum = _colsum(a11_v)
        a11_mass = _colsum(a11_v, absval=True)
        A11 = redistribute(a11_v, STAR, STAR, comm_precision=cp)
        aloc = A11.local[:w, :w]
        guard.check("diag_gather", a11_sum, jnp.sum(aloc, axis=0),
                    mass=a11_mass, kind="transport", rows=w)
        L11, Li11 = _potrf_inv(A11.local, precision, plan=plan)
        d = jnp.tril(aloc)
        d = d + jnp.conj(jnp.tril(d, -1)).T
        cL = jnp.sum(L11, axis=0)
        guard.check("diag", jnp.matmul(cL, jnp.conj(L11).T),
                    jnp.sum(d, axis=0),
                    mass=jnp.sum(jnp.abs(d), axis=0),
                    kind="compute", rows=w, nb=w)
        L11_ss = DistMatrix(L11, (w, w), STAR, STAR, 0, 0, g)
        l11_w = redistribute(L11_ss, MC, MR)
        guard.check("diag_write", jnp.sum(L11, axis=0), _colsum(l11_w),
                    mass=jnp.sum(jnp.abs(L11), axis=0),
                    kind="transport", rows=w)
        L = update_view(L, l11_w, rows=(s, e), cols=(s, e))
        if e == m:
            return L, L11, ticks
        a21_v = view(L, rows=(e, m), cols=(s, e))
        a21_sum = _colsum(a21_v)
        a21_mass = _colsum(a21_v, absval=True)
        A21_vc = redistribute(a21_v, VC, STAR, comm_precision=cp)
        guard.check("panel_gather", a21_sum, _colsum(A21_vc),
                    mass=a21_mass, kind="transport", rows=m - e)
        x21 = jnp.matmul(A21_vc.local, jnp.conj(Li11).T,
                         precision=_hi(precision)).astype(L.dtype)
        L21_vc = DistMatrix(x21, (m - e, w), VC, STAR, 0, 0, g)
        cx = _colsum(L21_vc)
        cx_mass = _colsum(L21_vc, absval=True)
        # panel solve invariant: colsum(L21 L11^H) == colsum(A21) --
        # the check that catches a corrupted Li11 (the second output of
        # the 'compute' fault seam)
        guard.check("panel", jnp.matmul(cx, jnp.conj(L11).T),
                    _colsum(A21_vc), mass=a21_mass + cx_mass,
                    kind="compute", rows=m - e, nb=w)
        ticks.append(("panel", (L21_vc,)))
        L21_mc, L21H_mr = panel_spread(L21_vc, conj=True,
                                       comm_precision=cp)
        guard.check("spread_mc", cx, _colsum(L21_mc), mass=cx_mass,
                    kind="transport", rows=m - e)
        guard.check("spread_mr", jnp.conj(_rowsum(L21_vc)),
                    _colsum(L21H_mr), mass=_colsum(L21H_mr, absval=True),
                    kind="transport", rows=w)
        ticks.append(("spread", (L21_mc, L21H_mr)))
        A22 = view(L, rows=(e, m), cols=(e, m))
        t_pre = _colsum(A22)
        t_mass = _colsum(A22, absval=True)
        upd = jnp.matmul(L21_mc.local, L21H_mr.local, precision=precision)
        mask = _mask_triangle(A22, "L")
        mupd = jnp.where(mask, upd.astype(L.dtype), 0)
        # masked-lower update: no separable column identity, so the
        # predicted delta reduces the update product itself
        # (consistency-grade; operands are transport/solve-checked above)
        delta = _colsum(A22.with_local(mupd))
        dmass = _colsum(A22.with_local(jnp.abs(mupd)))
        A22new = jnp.where(mask, A22.local - upd.astype(L.dtype),
                           A22.local)
        L = update_view(L, A22.with_local(A22new), rows=(e, m),
                        cols=(e, m))
        guard.check("update", t_pre - delta,
                    _colsum(view(L, rows=(e, m), cols=(e, m))),
                    mass=t_mass + dmass, kind="compute",
                    rows=m - e, nb=w)
        l21_w = redistribute(L21_mc, MC, MR)
        guard.check("panel_write", _colsum(L21_mc), _colsum(l21_w),
                    mass=cx_mass, kind="transport", rows=m - e)
        L = update_view(L, l21_w, rows=(e, m), cols=(s, e))
        ticks.append(("update", (L,)))
        return L, L11, ticks

    L = A
    for k, s in enumerate(range(0, m, ib)):
        L, L11, ticks = run_step(guard, k, lambda st: step_fn(st, k, s), L)
        tm.tick("diag", k, L11)
        for phase, arrs in ticks:
            tm.tick(phase, k, *arrs)
    guard.flag_health(hm)
    guard.report()
    if hm is not None:
        hm.report()
    return make_trapezoidal(L, "L")


# ---------------------------------------------------------------------
# guarded QR (blocked Householder schedule + per-panel transactions)
# ---------------------------------------------------------------------

def abft_qr(A, nb=None, precision=None, panel="classic",
            comm_precision=None, timer=None, health=None, abft=True,
            plan=None):
    """Checksum-guarded blocked Householder QR (see module docstring).

    Same ``(packed, tau)`` geqrf contract as ``lapack.qr``; reached via
    ``qr(..., abft=)``.  ``panel`` keeps its 'classic'/'tsqr' meaning
    (the factor invariant only consumes the self-consistent packed
    ``(V, tau, R)``, so the TSQR tree is guarded by the same single
    reconstruction check); the panel gathers ride the default hop-chain
    path (``redist_path`` does not compose with per-panel transactions
    and is ignored)."""
    import jax.numpy as jnp
    from ..core.dist import MC, MR, STAR
    from ..core.distmatrix import DistMatrix
    from ..core.view import view
    from ..redist.engine import apply_fault, redistribute
    from ..blas.level3 import _blocksize
    from ..lapack.lu import (_hi, _phase_hook, _update_cols_ge,
                             _update_cols_lt)
    from ..lapack.qr import (_larft, _panel_qr_dispatch, _panel_qr_tsqr,
                             _panel_v, _record_qr_nb)
    from .recovery import run_step
    from .health import attach_health

    guard = resolve_abft(abft)
    m, n = A.gshape
    g = A.grid
    guard.begin("qr", A, comm_precision=comm_precision)
    tm = _phase_hook("qr", timer)
    hm = None
    if health:
        tm, hm = attach_health("qr", health, tm, scale_from=A)
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    cp = comm_precision
    tm.start()

    def step_fn(A, k, s):
        # ticks buffered per attempt, replayed on commit (see abft_lu)
        ticks = []
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        pan_v = view(A, rows=(s, m), cols=(s, e_up))
        pan_sum = _colsum(pan_v)
        pan_mass = _colsum(pan_v, absval=True)
        panel_ss = redistribute(pan_v, STAR, STAR, comm_precision=cp)
        ploc = panel_ss.local[:m - s, :e_up - s]
        guard.check("panel_gather", pan_sum, jnp.sum(ploc, axis=0),
                    mass=pan_mass, kind="transport", rows=m - s)
        Tk = None
        if panel == "tsqr":
            Pf, tau = _panel_qr_tsqr(ploc[:, :nbw], r, precision)
        else:
            Pf, tau, Tk = _panel_qr_dispatch(ploc[:, :nbw], plan)
        Pf, = apply_fault("compute", (Pf,))
        # factor invariant: panel = (I - V T V^H) [R; 0], so
        # colsum(panel) == colsum(R) - cV @ (T @ (V1^H R))
        V = _panel_v(Pf)
        T = Tk if Tk is not None else _larft(V, tau)
        R11 = jnp.triu(Pf[:nbw])
        cV = jnp.sum(V, axis=0)
        rpred = (jnp.sum(R11, axis=0)
                 - jnp.matmul(cV, jnp.matmul(
                     T, jnp.matmul(jnp.conj(V[:nbw]).T, R11))))
        guard.check("panel", rpred, jnp.sum(ploc[:, :nbw], axis=0),
                    mass=jnp.sum(jnp.abs(ploc[:, :nbw]), axis=0),
                    kind="compute", rows=m - s, nb=nbw)
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        pf_w = redistribute(Pf_ss, MC, MR)
        guard.check("panel_write", jnp.sum(Pf_w, axis=0), _colsum(pf_w),
                    mass=jnp.sum(jnp.abs(Pf_w), axis=0),
                    kind="transport", rows=m - s)
        A = _update_cols_lt(A, pf_w, (s, m), (s, e_up), e)
        if e < n:
            V_ss = DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0, g)
            V_mc = redistribute(V_ss, MC, STAR)
            guard.check("v_move", cV, _colsum(V_mc),
                        mass=jnp.sum(jnp.abs(V), axis=0),
                        kind="transport", rows=m - s)
            A2 = view(A, rows=(s, m), cols=(s, n))
            t_pre = _colsum(A2)
            t_mass = _colsum(A2, absval=True)
            W = jnp.matmul(jnp.conj(V_mc.local).T, A2.local,
                           precision=_hi(precision))
            W = jnp.matmul(jnp.conj(T).T, W, precision=_hi(precision))
            upd = jnp.matmul(V_mc.local, W, precision=_hi(precision))
            # Huang-Abraham: 1^T (V_mc W) == cV @ W, cV from the
            # REPLICATED panel -- independent of the transported V_mc.
            # The strip's first nbw global columns hold the already-
            # written packed panel; _update_cols_ge leaves them
            # untouched, so their predicted delta is exactly zero.
            _, J = _indices(A2)
            delta = _scatter_cols(jnp.matmul(cV, W), J, n - s)
            dmass = _scatter_cols(
                jnp.matmul(jnp.abs(cV), jnp.abs(W)), J, n - s)
            keep = jnp.arange(n - s) >= nbw
            delta = jnp.where(keep, delta, 0)
            dmass = jnp.where(keep, dmass, 0)
            A = _update_cols_ge(
                A, A2.with_local(A2.local - upd.astype(A.dtype)),
                (s, m), (s, n), e)
            guard.check("update", t_pre - delta,
                        _colsum(view(A, rows=(s, m), cols=(s, n))),
                        mass=t_mass + dmass, kind="compute",
                        rows=m - s, nb=nbw)
            ticks.append(("update", (A,)))
        return A, Pf, tau, ticks

    taus = []
    for k, s in enumerate(range(0, kend, ib)):
        # taus accumulate in the COMMIT loop, never inside the
        # transaction body: a retried attempt must not double-append
        A, Pf, tau, ticks = run_step(
            guard, k, lambda st: step_fn(st, k, s), A)
        taus.append(tau)
        tm.tick("panel", k, Pf, tau)
        for phase, arrs in ticks:
            tm.tick(phase, k, *arrs)
    _record_qr_nb(A, ib)
    guard.flag_health(hm)
    guard.report()
    if hm is not None:
        hm.report()
    return A, jnp.concatenate(taus) if taus else jnp.zeros((0,), A.dtype)
