"""Numerical-health guards: cheap per-phase checks -> ``health_report/v1``.

The detection half of the resilience subsystem (ISSUE 7).  A
:class:`HealthMonitor` speaks the PhaseTimer tick protocol (``start()`` +
``tick(phase, step, *arrays)``), so it rides the SAME driver hook seam the
observability subsystem built (ISSUE 5): ``lu(..., health=...)`` /
``cholesky(..., health=...)`` fan the monitor into the phase hook next to
any explicit timer / active tracer, and every phase boundary the driver
already ticks becomes a checkpoint.  With ``health=None`` (the default)
NOTHING is attached -- the drivers keep the zero-overhead NULL_HOOK path,
pinned by the redist-count and comm-plan goldens.

Checks (all engine-free: pure reductions on the ticked arrays, no
redistribute/panel_spread entries, so the comm plan of a monitored run is
identical to an unmonitored one):

  * **NaN/Inf scan** -- every inexact-dtype leaf of every tick is
    ``isfinite``-reduced; the first non-finite phase is what a corrupted
    collective payload (see :mod:`.faults`) surfaces as.
  * **Growth estimate** -- running ``max |ticked panel/update| / max |A|``,
    the practical stand-in for the factorization growth factor.  CALU's
    tournament trades partial pivoting's ``2^k`` bound for a
    ``2^{nb log2 r}``-class one (ISSUE 6's documented caveat); this is
    the guard that notices when that trade goes wrong at runtime.
  * **Diagonal checks** -- driver-aware: LU's packed ``panel`` ticks carry
    the pivots on the diagonal (near-zero pivot == (near-)singular);
    Cholesky's ``diag`` ticks carry L11 (non-positive / near-zero
    diagonal == not positive definite; an outright non-PD block already
    NaNs out of ``jnp.linalg.cholesky`` and is caught by the scan).

Evaluation is DEFERRED: ticks record jnp scalars (one reduction per leaf,
no host sync per phase); :meth:`HealthMonitor.report` converts them once,
builds the structured ``health_report/v1`` document, bumps
``health_checks``/``health_flags`` on the current obs metrics registry,
and -- when a :class:`~elemental_tpu.obs.tracer.Tracer` is active --
attaches one ``health:<kind>`` instant event per flag to the trace.
Like the tracer, the monitor is an EAGER-mode tool: under jit the ticked
leaves are tracers and the checks degrade to no-ops.

``health_report/v1``::

    {"schema": "health_report/v1", "driver": "lu", "ok": false,
     "checks": 12,                       # ticks inspected
     "flags": [{"kind": "nonfinite", "phase": "update", "step": 3,
                "value": null}, ...],    # kinds: nonfinite | growth |
                                         #   small_pivot | nonpositive_diag
     "growth_estimate": 1.8,             # max |intermediate| / max |A|
     "scale": 3.2,                       # max |A| (the growth anchor)
     "min_diag": 0.41,                   # worst diagonal seen (driver units)
     "failing_phase": "update" | null}   # first flagged phase
"""
from __future__ import annotations

import dataclasses

import numpy as np

HEALTH_SCHEMA = "health_report/v1"

#: growth-estimate flag threshold: |intermediate| exceeding ``max|A|`` by
#: this factor marks the factorization as suspect (partial pivoting keeps
#: the ratio near O(n); a corrupted payload or a lost CALU tournament
#: lands orders of magnitude beyond it)
GROWTH_LIMIT = 1e8

#: phases whose FIRST inexact leaf carries a meaningful diagonal, per
#: driver: LU packs the pivots on the panel diagonal, Cholesky factors
#: L11 in the diag phase, and QR's packed panel carries R's diagonal
#: (the larfg betas -- near-zero == rank-deficient, the ``small_pivot``
#: flag; ISSUE 9 parity).  Other drivers get scan + growth only.
DIAG_PHASES = {"lu": ("panel",), "cholesky": ("diag",), "qr": ("panel",)}


def _is_tracer(x) -> bool:
    import jax
    return isinstance(x, jax.core.Tracer)


def _float_leaves(arrays):
    """Inexact-dtype array leaves of a tick payload (DistMatrix flattens
    to its storage array; int perm vectors are skipped)."""
    import jax
    import jax.numpy as jnp
    out = []
    for leaf in jax.tree_util.tree_leaves(arrays):
        try:
            dt = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
                else leaf.dtype
        except (TypeError, ValueError):
            continue
        if jnp.issubdtype(dt, jnp.inexact):
            out.append(leaf)
    return out


@dataclasses.dataclass
class _Check:
    """One deferred per-tick observation (jnp scalars until report())."""
    phase: str
    step: int
    finite: object          # jnp bool: all leaves finite
    maxabs: object | None   # jnp scalar: max |leaf| over inexact leaves
    diag_min: object | None  # jnp scalar: min pivot/diag magnitude
    diag_signed: object | None  # jnp scalar: min REAL diag (cholesky sign)


class HealthMonitor:
    """Tick-protocol numerical-health guard (see module docstring).

    Reusable as the ``health=`` argument of ``lu``/``cholesky`` (the
    driver binds the name and input scale at entry) and directly by
    :func:`~elemental_tpu.resilience.certify.certified_solve`, which
    runs one monitor per escalation-ladder attempt.
    """

    def __init__(self, growth_limit: float = GROWTH_LIMIT,
                 diag_rtol: float | None = None):
        self.growth_limit = float(growth_limit)
        self.diag_rtol = diag_rtol        # None: 8*eps(dtype) at report time
        self.driver: str | None = None
        self._scale = None                # deferred jnp max |A|
        self._eps = None
        self._checks: list[_Check] = []
        self._extra_flags: list[dict] = []
        self._emitted = False
        self._report = None

    # ---- driver binding ---------------------------------------------
    def begin(self, driver: str, scale_from=None) -> "HealthMonitor":
        """Bind the driver name and the growth anchor ``max |A|`` (one
        deferred reduction on the input storage).  Called by the driver's
        ``health=`` plumbing; rebinding RESETS the monitor -- one monitor
        covers one driver invocation (read ``report()`` between runs)."""
        import jax.numpy as jnp
        self.driver = str(driver)
        self._checks = []
        self._extra_flags = []
        self._report = None
        self._emitted = False
        if scale_from is not None and not _is_tracer(scale_from):
            arr = getattr(scale_from, "local", scale_from)
            if not _is_tracer(arr) and getattr(arr, "size", 0):
                self._scale = jnp.max(jnp.abs(arr))
                self._eps = float(jnp.finfo(arr.dtype).eps) \
                    if jnp.issubdtype(arr.dtype, jnp.inexact) else None
        return self

    # ---- PhaseTimer protocol ----------------------------------------
    def start(self):
        pass

    def tick(self, phase, step, *arrays):
        import jax.numpy as jnp
        leaves = _float_leaves(arrays)
        if not leaves or any(_is_tracer(x) for x in leaves):
            return                        # under jit / nothing to check
        fin = None
        mx = None
        for leaf in leaves:
            if leaf.size == 0:
                continue
            f = jnp.all(jnp.isfinite(leaf))
            fin = f if fin is None else jnp.logical_and(fin, f)
            a = jnp.max(jnp.abs(leaf))
            mx = a if mx is None else jnp.maximum(mx, a)
        if fin is None:
            return
        dmin = dsigned = None
        if str(phase) in DIAG_PHASES.get(self.driver or "", ()):
            d = jnp.diagonal(leaves[0])
            if d.size:
                dmin = jnp.min(jnp.abs(d))
                dsigned = jnp.min(jnp.real(d))
        self._checks.append(_Check(str(phase), int(step), fin, mx,
                                   dmin, dsigned))

    def flag(self, kind: str, phase: str, step: int, value=None) -> None:
        """Append an externally-detected flag (ISSUE 11: the ABFT guard
        pushes UNRECOVERED checksum violations here, kind ``"abft"``, so
        they surface through the same ``health_report/v1`` document and
        ``failing_phase`` plumbing as the monitor's own checks).  Must be
        called before :meth:`report` caches."""
        self._extra_flags.append({"kind": str(kind), "phase": str(phase),
                                  "step": int(step), "value": value})

    # ---- report ------------------------------------------------------
    @property
    def checks(self) -> int:
        return len(self._checks)

    def report(self, emit: bool = True) -> dict:
        """Evaluate the deferred checks into a ``health_report/v1`` doc.

        The first call (with ``emit=True``) also bumps the obs metrics
        registry and attaches ``health:<kind>`` instant events to the
        active tracer; later calls return the cached document."""
        if self._report is not None:
            return self._report
        flags = list(self._extra_flags)
        scale = float(np.asarray(self._scale)) if self._scale is not None \
            else None
        gmax = None
        min_diag = None
        for ck in self._checks:
            if not bool(np.asarray(ck.finite)):
                flags.append({"kind": "nonfinite", "phase": ck.phase,
                              "step": ck.step, "value": None})
                continue                  # maxabs of a NaN tick is noise
            if ck.maxabs is not None:
                v = float(np.asarray(ck.maxabs))
                gmax = v if gmax is None else max(gmax, v)
            if ck.diag_min is not None:
                dv = float(np.asarray(ck.diag_min))
                ds = float(np.asarray(ck.diag_signed))
                min_diag = dv if min_diag is None else min(min_diag, dv)
                tiny = self._diag_threshold(scale)
                if self.driver == "cholesky" and ds <= 0.0:
                    flags.append({"kind": "nonpositive_diag",
                                  "phase": ck.phase, "step": ck.step,
                                  "value": ds})
                elif dv <= tiny:
                    flags.append({"kind": "small_pivot", "phase": ck.phase,
                                  "step": ck.step, "value": dv})
        growth = None
        if gmax is not None and scale:
            growth = gmax / scale
            if growth > self.growth_limit:
                worst = max((ck for ck in self._checks
                             if ck.maxabs is not None),
                            key=lambda ck: float(np.asarray(ck.maxabs)))
                flags.append({"kind": "growth", "phase": worst.phase,
                              "step": worst.step, "value": growth})
        doc = {"schema": HEALTH_SCHEMA, "driver": self.driver,
               "ok": not flags, "checks": len(self._checks), "flags": flags,
               "growth_estimate": growth, "scale": scale,
               "min_diag": min_diag,
               "failing_phase": flags[0]["phase"] if flags else None}
        self._report = doc
        if emit and not self._emitted:
            self._emitted = True
            self._emit(doc)
        return doc

    def _diag_threshold(self, scale) -> float:
        if self.diag_rtol is not None:
            rtol = self.diag_rtol
        else:
            rtol = 8.0 * (self._eps if self._eps is not None else 1e-7)
        return rtol * (scale if scale else 1.0)

    def _emit(self, doc: dict) -> None:
        from ..obs import metrics as _metrics
        from ..obs.tracer import active_tracer
        drv = doc["driver"] or "?"
        _metrics.inc("health_checks", doc["checks"], driver=drv)
        tr = active_tracer()
        for fl in doc["flags"]:
            _metrics.inc("health_flags", driver=drv, kind=fl["kind"],
                         phase=fl["phase"])
            if tr is not None:
                tr.instant(f"health:{fl['kind']}", driver=drv,
                           phase=fl["phase"], step=fl["step"],
                           value=fl["value"])
        _LAST[drv] = doc
        _LAST["_latest"] = doc


#: the most recent emitted report per driver (+ "_latest"); the
#: ``health=True`` convenience form lands here so callers who did not
#: keep the monitor can still read the outcome.
_LAST: dict = {}


def last_health_report(driver: str | None = None) -> dict | None:
    """The most recently emitted ``health_report/v1`` (per driver, or the
    latest overall with ``driver=None``)."""
    return _LAST.get(driver if driver is not None else "_latest")


class _HookPair:
    """Tick fan-out of (existing hook, monitor) -- the resilience twin of
    ``obs.tracer._Fanout``, kept local so health stays importable without
    touching the tracer's private surface."""
    __slots__ = ("hooks",)

    def __init__(self, hooks):
        self.hooks = tuple(hooks)

    def start(self):
        for h in self.hooks:
            h.start()

    def tick(self, phase, step, *arrays):
        for h in self.hooks:
            h.tick(phase, step, *arrays)


def attach_health(driver: str, health, hook, scale_from=None):
    """Resolve a driver's ``health=`` argument into (hook', monitor).

    ``health`` may be a :class:`HealthMonitor` (caller-owned: read
    ``monitor.report()`` afterwards) or any truthy value (driver-internal
    monitor; the emitted report is retrievable via
    :func:`last_health_report`).  The returned hook fans ticks out to both
    the existing hook (timer / tracer channel / NULL_HOOK) and the
    monitor; with a falsy ``health`` the hook passes through untouched."""
    if not health:
        return hook, None
    mon = health if isinstance(health, HealthMonitor) else HealthMonitor()
    mon.begin(driver, scale_from=scale_from)
    from ..obs.tracer import NULL_HOOK
    if hook is NULL_HOOK or hook is None:
        return mon, mon
    return _HookPair((hook, mon)), mon


def factor_diag_info(op: str, factor) -> dict:
    """Structured singularity signal from a packed factor's diagonal.

    ``op``: ``'lu'`` (packed L\\U: the diagonal holds U's pivots;
    non-finite or numerically-zero -- ``|u_kk| <= k * eps * max|u|``, the
    floating-point image of an exactly-singular input, whose cancellation
    rarely survives pivoting bit-exactly -- == singular) or ``'hpd'``
    (Cholesky L/U factor: non-finite -- ``jnp.linalg.cholesky`` NaNs past
    the breakdown point -- or non-positive / numerically-zero real
    diagonal == not positive definite).  Returns::

        {"singular": bool, "diag_index": first offending index | None,
         "finite": bool}

    Engine-free (``get_diagonal`` is a pure storage reduction), so the
    signal is trustworthy even under fault injection."""
    from ..blas.level1 import get_diagonal
    d = np.asarray(get_diagonal(factor).local).ravel()
    finite = bool(np.isfinite(d).all())
    mag = np.abs(d[np.isfinite(d)])
    dmax = float(mag.max()) if mag.size else 0.0
    eps = float(np.finfo(d.dtype).eps) if np.issubdtype(d.dtype, np.inexact) \
        else 0.0
    tiny = max(d.size, 1) * eps * dmax
    if op == "lu":
        bad = ~np.isfinite(d) | (np.abs(d) <= tiny)
    else:
        bad = ~np.isfinite(d) | (np.real(d) <= tiny)
    idx = int(np.argmax(bad)) if bad.any() else None
    return {"singular": bool(bad.any()), "diag_index": idx, "finite": finite}
