"""Certified solves: residual certificate + deterministic escalation.

The recovery half of the resilience subsystem (ISSUE 7).
:func:`certified_solve` wraps the ``lu_solve`` / ``hpd_solve`` drivers
into the retry/backoff shape for NUMERICAL failure: run the fast
configuration first, measure the TRUE residual through a trusted path,
iteratively refine, and on certification failure climb a deterministic
escalation ladder -- re-using the tuner's knob vocabulary for each rung's
configuration (``panel`` / ``update_precision`` / ``precision`` /
``lookahead``; see ``tune.knobs``) -- until a rung certifies or the
ladder is exhausted.

The ladder (order pinned by ``tests/resilience``)::

    quant    wire-quantized fast configuration (ISSUE 8): the same
             speed-first knobs as 'fast' PLUS ``comm_precision='int8'``
             -- block-scaled int8/bf16 payloads on every bulk collective,
             2-4x fewer bytes on the wire -- with a refinement budget
             (8 iterations) sized so the ~1e-2 quantized-factor error
             refines down to fp64-class tolerances on well-conditioned
             systems.  On 1x1 grids the knob is a no-op (bit-identical
             to 'fast').
    fast     speed-first factorization: CALU tournament panel (lu) /
             default-precision trailing updates, full-precision wire
    refine   SAME factor, larger iterative-refinement budget (cheapest
             escalation: no refactorization)
    fp32     refactor with full-precision trailing updates
    classic  refactor with the classic (partial-pivot / classic-schedule)
             panel -- the maximum-stability baseline

This closes the loop the ROADMAP's quantized-collectives item (EQuARX,
arXiv 2506.17615) planned: aggressive ``comm_precision`` runs FIRST, the
trusted residual certificate decides whether its answer stands, and any
failure escalates to full-precision wire -- zero silent accuracy loss by
construction.

Trust boundary: the certificate's residual is computed HOST-SIDE in
float64 from ``to_global`` snapshots (pure storage gathers -- no engine
collectives), so a fault-injected or otherwise corrupted redistribution
layer (see :mod:`.faults`) can corrupt the SOLVE but never the
MEASUREMENT: a garbage solution cannot be certified, and a clean
escalation rung certifies even while lower rungs are being corrupted.
Each factorization attempt runs under its own
:class:`~elemental_tpu.resilience.health.HealthMonitor`, so a failed
certificate carries the health report naming the failing phase.

``solve_certificate/v1`` (the ``info`` return)::

    {"schema": "solve_certificate/v1", "op": "lu", "certified": true,
     "rung": "fast",                  # certifying rung (None on failure)
     "residual": 3.1e-15, "tol": 6.8e-13,
     "refine_iters": 0,              # iterations at the certifying rung
     "ladder": ["fast", "refine", "fp32", "classic"],
     "attempts": [{"rung", "residual", "refine_iters", "singular",
                   "diag_index", "health"}, ...],
     "singular": false,              # every FULL-WIRE attempt was singular
                                     #   (a wire-quantized factorization
                                     #   perturbs exact zeros off the
                                     #   diagonal, so quant rungs cannot
                                     #   attest singularity either way)
     "timed_out": false,             # a ``deadline=`` expired before the
                                     #   ladder finished (ISSUE 9): the
                                     #   certificate is best-so-far, not
                                     #   the full ladder's verdict
     "failing_phase": null,          # first health-flagged phase /
                                     #   "diag" (singular) / "deadline"
                                     #   (timed out, no other evidence) /
                                     #   "residual"
     "health": {...}}                # last attempt's health_report/v1

The residual certified is ``||B - A X||_F / (||A||_F ||X||_F + ||B||_F)``
(normwise relative backward error); the documented default tolerance is
``64 * n * eps(A.dtype)``.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .health import HealthMonitor

CERT_SCHEMA = "solve_certificate/v1"

#: documented default certification tolerance: ``TOL_FACTOR * n * eps``
TOL_FACTOR = 64.0

#: canonical ladder rung names, in escalation order (pinned by tests).
#: 'abft' (ISSUE 11) sits between the cheap re-refine rung and the full
#: fp32 refactorization: a TRANSIENT fault is repaired by re-executing
#: one panel (checksum-guarded classic schedule) before the ladder pays
#: for whole-solve escalation.  Since ISSUE 15 the serve layer's
#: grid_qr escalation applies the same guarding to its least-squares QR
#: (``least_squares(..., abft=True)``), so every factorization a serve
#: escalation can reach is panel-recoverable.
LADDER_NAMES = ("quant", "fast", "refine", "abft", "fp32", "classic")


@dataclasses.dataclass(frozen=True)
class Rung:
    """One escalation rung: a driver knob configuration + budgets."""
    name: str
    config: dict                 # driver kwargs (tuner knob vocabulary)
    refine: int                  # iterative-refinement budget
    refactor: bool = True        # fresh factorization at this rung?


def default_ladder(op: str):
    """The documented ladder for ``op`` ('lu' | 'hpd').  Rung configs are
    knob dicts in the tuner's vocabulary (``tune.knobs``): 'quant' is the
    ISSUE-8 wire-quantized rung ('fast' + ``comm_precision='int8'``,
    ``COMM_PRECISIONS[2]``), 'fast' rides the ISSUE-6 CALU panel
    (``LU_PANELS[1]``; degenerates to classic on single-row grids inside
    the driver) with default-precision trailing updates, 'abft' (ISSUE
    11) re-factors under the checksum-guarded classic schedule
    (``abft=True``: a transient fault is detected and repaired at PANEL
    granularity inside the driver, so this rung succeeds where 'refine'
    could not without paying fp32), 'classic' is ``LU_PANELS[0]`` / the
    classic schedule."""
    from jax import lax
    from ..tune.knobs import COMM_PRECISIONS
    q8 = COMM_PRECISIONS[2]                      # 'int8'
    if op == "lu":
        from ..tune.knobs import LU_PANELS
        classic, calu = LU_PANELS
        fast = {"panel": calu, "update_precision": lax.Precision.DEFAULT}
        return (
            Rung("quant", {**fast, "comm_precision": q8}, refine=8),
            Rung("fast", fast, refine=2),
            Rung("refine", fast, refine=8, refactor=False),
            Rung("abft", {"abft": True, "update_precision": None},
                 refine=4),
            Rung("fp32", {"panel": calu, "update_precision": None},
                 refine=4),
            Rung("classic", {"panel": classic, "update_precision": None},
                 refine=4),
        )
    if op == "hpd":
        fast = {"precision": None}
        return (
            Rung("quant", {**fast, "comm_precision": q8}, refine=8),
            Rung("fast", fast, refine=2),
            Rung("refine", fast, refine=8, refactor=False),
            Rung("abft", {"abft": True, "precision": None}, refine=4),
            Rung("fp32", {"precision": lax.Precision.HIGHEST}, refine=4),
            Rung("classic", {"precision": lax.Precision.HIGHEST,
                             "lookahead": False}, refine=4),
        )
    raise ValueError(f"certified_solve op must be 'lu' or 'hpd', got {op!r}")


def default_tol(n: int, dtype) -> float:
    import jax.numpy as jnp
    return TOL_FACTOR * max(int(n), 1) * float(jnp.finfo(dtype).eps)


# ---------------------------------------------------------------------
# trusted host-side measurement (engine-free: to_global is a storage take)
# ---------------------------------------------------------------------

def _host(A) -> np.ndarray:
    from ..core.distmatrix import to_global
    arr = np.asarray(to_global(A))
    return arr.astype(np.complex128 if np.iscomplexobj(arr) else np.float64)


def _residual(An, Bn, Xn, normA, normB) -> float:
    # corrupted solves legitimately overflow here; inf is the verdict
    with np.errstate(over="ignore", invalid="ignore"):
        r = Bn - An @ Xn
        normX = np.linalg.norm(Xn)
        den = normA * normX + normB
        if not np.isfinite(den) or den == 0.0:
            return float("inf")
        res = np.linalg.norm(r) / den
    return float(res) if np.isfinite(res) else float("inf")


# ---------------------------------------------------------------------
# per-op factor / solve-after adapters
# ---------------------------------------------------------------------

def _factor(op: str, A, nb, config: dict, monitor):
    if op == "lu":
        from ..lapack.lu import lu
        return lu(A, nb=nb, health=monitor, **config)
    from ..lapack.cholesky import cholesky
    return cholesky(A, "L", nb=nb, health=monitor, **config)


def _solve_after(op: str, factor, B, nb):
    if op == "lu":
        from ..lapack.lu import lu_solve_after
        LU_, perm = factor
        return lu_solve_after(LU_, perm, B, nb=nb)
    from ..lapack.cholesky import cholesky_solve_after
    return cholesky_solve_after(factor, B, "L", nb=nb)


def _factor_matrix(op: str, factor):
    return factor[0] if op == "lu" else factor


# ---------------------------------------------------------------------
# the certified solve
# ---------------------------------------------------------------------

def certified_solve(op: str, A, B, *, tol: float | None = None,
                    nb: int | None = None, ladder=None, health: bool = True,
                    deadline=None):
    """Solve ``A X = B`` with a residual certificate and escalation.

    ``op``: ``'lu'`` (general square A) or ``'hpd'`` (Hermitian positive
    definite A; ``'cholesky'`` is accepted as an alias).  Returns
    ``(X, info)`` with ``info`` a ``solve_certificate/v1`` document (see
    module docstring); ``X`` is the best solution produced (``None`` when
    no attempt produced one: every attempted factorization was singular,
    or the deadline expired before the first rung).  ``tol`` defaults
    to the documented ``64 * n * eps(A.dtype)``; ``ladder`` overrides the
    rung sequence (a tuple of :class:`Rung`); ``health=False`` skips the
    per-attempt health monitors (the certificate alone still guards the
    result).  EAGER-mode: the escalation control flow is host-side.

    ``deadline`` (ISSUE 9) bounds wall-clock: any object with a
    ``remaining() -> seconds`` method (canonically
    :class:`elemental_tpu.serve.Deadline`).  Every rung attempt -- and
    every refinement iteration -- checks the remaining budget BEFORE
    launching; an exhausted budget stops the ladder and returns the
    best-so-far solution with ``timed_out=True`` in the certificate
    instead of silently running the remaining rungs, so the worst-case
    overrun is one rung, never the whole ladder.
    """
    if op == "cholesky":
        op = "hpd"
    rungs = tuple(ladder) if ladder is not None else default_ladder(op)
    n = int(A.gshape[0])
    if tol is None:
        tol = default_tol(n, A.dtype)
    tol = float(tol)
    An = _host(A)
    Bn = _host(B)
    normA = np.linalg.norm(An)
    normB = np.linalg.norm(Bn)
    dtype = np.dtype(B.dtype)

    from .health import factor_diag_info
    attempts: list = []
    factor = None
    diag = None
    monitor = None
    X = None
    timed_out = False
    best = None                           # (residual, X, refine_iters)
    for rung in rungs:
        if deadline is not None and deadline.remaining() <= 0.0:
            timed_out = True              # check BEFORE launch: the only
            break                         # overrun is the rung in flight
        att = {"rung": rung.name, "residual": None, "refine_iters": 0,
               "singular": False, "diag_index": None, "health": None}
        if rung.refactor or factor is None:
            monitor = HealthMonitor() if health else None
            factor = _factor(op, A, nb, rung.config, monitor)
            diag = factor_diag_info(op, _factor_matrix(op, factor))
        if monitor is not None:
            att["health"] = monitor.report()
        att["singular"] = diag["singular"]
        att["diag_index"] = diag["diag_index"]
        if diag["singular"]:
            attempts.append(att)
            continue                      # solve-after would be garbage
        X = _solve_after(op, factor, B, nb)
        res = _residual(An, Bn, _host(X), normA, normB)
        it = 0
        while res > tol and it < rung.refine and np.isfinite(res):
            if deadline is not None and deadline.remaining() <= 0.0:
                timed_out = True
                break
            with np.errstate(over="ignore", invalid="ignore"):
                Rn = Bn - An @ _host(X)
            if not np.isfinite(Rn).all():
                break
            from ..core.distmatrix import from_global
            from ..core.dist import MC, MR
            Rd = from_global(Rn.astype(dtype), MC, MR, grid=B.grid)
            D = _solve_after(op, factor, Rd, nb)
            X = X.with_local(X.local + D.local)
            it += 1
            new = _residual(An, Bn, _host(X), normA, normB)
            if not (new < 0.9 * res):
                res = min(res, new)
                break                     # refinement stalled: escalate
            res = new
        att["residual"] = res if np.isfinite(res) else None
        att["refine_iters"] = it
        attempts.append(att)
        if np.isfinite(res) and (best is None or res < best[0]):
            best = (res, X, it)
        if np.isfinite(res) and res <= tol:
            return X, _certificate(op, True, rung.name, res, tol, it,
                                   rungs, attempts)
        if timed_out:
            break
    # ladder exhausted or deadline expired: best-so-far, never certified
    if best is not None:
        res_out, X, it_out = best
    else:
        last = attempts[-1] if attempts else None
        res_out = last["residual"] if last and last["residual"] is not None \
            else float("nan")
        it_out = last["refine_iters"] if last else 0
    cert = _certificate(op, False, None, res_out, tol, it_out,
                        rungs, attempts, timed_out=timed_out)
    if cert["singular"]:
        # the only solves produced (if any) came from wire-quantized
        # factors of an attested-singular system: suppress the garbage
        X = None
    return X, cert


def _failing_phase(attempts, timed_out=False) -> str | None:
    for att in attempts:
        rep = att.get("health")
        if rep and rep.get("flags"):
            return rep["flags"][0]["phase"]
    for att in attempts:
        if att.get("singular"):
            return "diag"
    if timed_out:
        return "deadline"                 # budget, not numerics, stopped us
    return "residual"


def _certificate(op, certified, rung, residual, tol, iters, rungs,
                 attempts, timed_out=False) -> dict:
    last_health = None
    for att in reversed(attempts):
        if att.get("health") is not None:
            last_health = att["health"]
            break
    # singularity is attested by the rungs that factored at FULL wire
    # precision: a comm_precision rung's quantization perturbs an exactly
    # zero pivot into a small nonzero one, so its diag verdict is
    # inconclusive in both directions
    attested = [a for a, r in zip(attempts, rungs)
                if not r.config.get("comm_precision")]
    return {"schema": CERT_SCHEMA, "op": op, "certified": bool(certified),
            "rung": rung,
            "residual": None if residual is None or not np.isfinite(residual)
            else float(residual),
            "tol": float(tol), "refine_iters": int(iters),
            "ladder": [r.name for r in rungs],
            "attempts": attempts,
            "singular": bool(attested) and all(a["singular"]
                                               for a in attested),
            "timed_out": bool(timed_out),
            "failing_phase": None if certified
            else _failing_phase(attempts, timed_out),
            "health": last_health}
