"""Resilience: numerical-health guards, certified solves, fault injection.

The robustness subsystem (ISSUE 7).  At the scale the source paper
targets (multi-thousand-chip factorizations, arXiv 2112.09017), silent
NaN / growth blowups are the failure mode, not crashes -- this package
makes numerical corruption DETECTED, REPORTED, and RECOVERED:

  :mod:`.health`   per-phase health guards riding the driver tick-hook
                   seam (``lu(..., health=...)``) -> ``health_report/v1``
  :mod:`.certify`  ``certified_solve``: true-residual certificate +
                   iterative refinement + the deterministic escalation
                   ladder (quant -> fast -> refine -> abft -> fp32 ->
                   classic),
                   deadline-boundable via ``deadline=`` (ISSUE 9: an
                   exhausted budget returns best-so-far + ``timed_out``)
  :mod:`.faults`   seeded ``FaultPlan`` corruption of engine payloads
                   (install via :func:`fault_injection`, the
                   ``redist.engine`` seam) and -- via the ``compute``
                   target (ISSUE 9) -- of local panel/batch kernel
                   outputs -- the test harness proving every corruption
                   class is repaired or surfaced
  :mod:`.abft`     checksum-guarded factorizations (ISSUE 11 + 15):
                   ``lu(..., abft=)`` / ``cholesky(..., abft=)`` /
                   ``qr(..., abft=)`` verify Huang-Abraham column-sum
                   invariants per panel -> ``abft_report/v1``
  :mod:`.recovery` the panel-transaction layer: a violated panel step is
                   rolled back and re-executed (bounded retries), so a
                   transient fault costs ONE recomputed panel instead of
                   a full re-solve

CLI: ``python -m perf.certify {run,smoke}``, ``python -m perf.abft
smoke``; gates: ``tools/check.sh resilience``, ``tools/check.sh abft``.
"""
from ..redist.engine import fault_injection
from .health import (HEALTH_SCHEMA, HealthMonitor, attach_health,
                     factor_diag_info, last_health_report)
from .certify import (CERT_SCHEMA, LADDER_NAMES, Rung, certified_solve,
                      default_ladder, default_tol)
from .faults import (FAULT_KINDS, FAULT_TARGETS, FaultEvent, FaultPlan,
                     FaultSpec, logs_identical)
from .abft import (ABFT_SCHEMA, AbftGuard, abft_cholesky, abft_lu,
                   abft_qr, last_abft_report)
from .recovery import run_step

__all__ = [
    "HEALTH_SCHEMA", "HealthMonitor", "attach_health", "factor_diag_info",
    "last_health_report",
    "CERT_SCHEMA", "LADDER_NAMES", "Rung", "certified_solve",
    "default_ladder", "default_tol",
    "FAULT_KINDS", "FAULT_TARGETS", "FaultEvent", "FaultPlan", "FaultSpec",
    "logs_identical", "fault_injection",
    "ABFT_SCHEMA", "AbftGuard", "abft_cholesky", "abft_lu", "abft_qr",
    "last_abft_report", "run_step",
]
