"""Panel-transaction recovery for the ABFT-guarded factorizations.

The rollback half of ISSUE 11: :func:`run_step` wraps ONE panel step of
an :mod:`.abft`-guarded driver (lu and cholesky since ISSUE 11, qr
since ISSUE 15 -- every blocked factorization rides this runner) as a
transaction.  The step body is a
pure function ``state -> (state', *extras)`` over immutable jax arrays,
so "snapshot" is free -- the pre-step state simply stays referenced --
and rollback is "discard the attempt's outputs and call the body again".

Per attempt the runner

  1. announces the panel step to the fault-injection seam
     (``engine.set_fault_step``) so ``FaultSpec(window=...)`` rules can
     target exactly this panel,
  2. runs the body, which records its checksum invariants on the guard,
  3. host-evaluates the attempt's checks (:meth:`AbftGuard.end_attempt`).

A clean attempt commits.  A violated one is discarded and the body
re-executed -- the ONLY recomputation is this panel step, counted on
``AbftGuard.recompute_count`` (the recovery-cost number the acceptance
tests pin) -- up to ``guard.max_retries`` retries; a step still violated
after the last retry commits anyway (the arrays are the best available)
and is marked UNRECOVERED, which the guard surfaces through the bound
``health_report/v1`` monitor and the ``abft_report/v1`` ``ok=False``
verdict so ``certified_solve`` escalates past the abft rung.

Retries emit an ``abft:recover`` span on the active tracer (with the
step / attempt / violated phases as attributes) so recovery cost is
visible on the same timeline as the phases it re-executes.

Under jit the guard's checks are tracer-valued and never compared, so
every step takes exactly one attempt: traced/eager control flow stays
identical and the guarded drivers remain traceable for the ``*_abft``
comm-plan goldens.
"""
from __future__ import annotations


def run_step(guard, step: int, body, state):
    """Run one guarded panel step as a transaction (see module doc).

    ``body(state)`` must be pure in ``state`` (immutable jax arrays) and
    may return any tuple whose first element is the new state; whatever
    it returns is returned unchanged for the committing attempt.
    """
    import contextlib

    from ..redist.engine import set_fault_step
    from ..obs.tracer import active_tracer

    def attempt_once(attempt):
        set_fault_step(step)
        guard.start_attempt()
        try:
            res = body(state)
        finally:
            set_fault_step(None)
        return res, guard.end_attempt(step, attempt)

    attempts = guard.max_retries + 1
    result, viols = attempt_once(0)
    for attempt in range(1, attempts):
        if not viols:
            break
        guard.note_violation(viols)
        # roll back: drop the attempt's outputs, re-execute this panel
        guard.note_recompute()
        tr = active_tracer()
        phases = ",".join(sorted({v["phase"] for v in viols}))
        span = tr.span("abft:recover", step=step, attempt=attempt,
                       violated=phases) if tr is not None \
            else contextlib.nullcontext()
        with span:
            result, viols = attempt_once(attempt)
        if not viols:
            guard.note_recovered(step)
    else:
        if viols:
            guard.note_violation(viols)
            guard.note_unrecovered(step)
    guard.note_panel()
    return result
