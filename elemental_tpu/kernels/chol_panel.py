"""Fused Pallas ``_potrf_inv``: blocked lower Cholesky of a diagonal
block AND its triangular inverse in one kernel launch.

The XLA path (``lapack.cholesky._potrf_inv_impl``) already restructures
the work into ``bs``-sized diagonal potrfs plus matmul assembly, but it
still pays one ``cholesky`` + one ``triangular_solve`` launch per block
-- latency-bound inner loops on the factorization spine.  Here the
whole (w, w) block lives in VMEM: the per-block potrf is an in-kernel
column recurrence, the per-block inverse is an in-kernel forward
substitution, and the inverse assembly / trailing updates are the same
MXU dots the reference issues -- all inside one ``pallas_call``.

The block recurrences are written with masked row/column extraction
(``where``-sums over exact zeros) instead of gathers: everything stays
(b, b)-shaped and Mosaic-friendly.  The math matches the reference
block-for-block but the scalar recurrences round differently from
XLA's native potrf/trsm, so the twin contract is residual-bounded
(``L L^H ~ A``, ``Li L ~ I``), not bit-pinned -- see
``tests/kernels/test_chol_panel.py`` for the documented bounds.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .common import interpret_default, pad_square

_HI = lax.Precision.HIGHEST


def _chol_unb(B):
    """Unblocked lower Cholesky of a (b, b) symmetrized block: column
    recurrence with masked extraction, valid in the lower triangle."""
    b = B.shape[0]
    dt = B.dtype
    ri = lax.broadcasted_iota(jnp.int32, (b, b), 0)
    ci = lax.broadcasted_iota(jnp.int32, (b, b), 1)
    rcol = ri[:, :1]

    def body(j, A):
        # columns < j hold finished L columns; the lower triangle of
        # columns >= j holds the running Schur complement
        piv = jnp.sum(jnp.where((ri == j) & (ci == j), A, 0))
        dj = jnp.sqrt(piv)
        colj = jnp.sum(jnp.where(ci == j, A, 0), axis=1, keepdims=True)
        lcol = jnp.where(rcol > j, colj / dj, jnp.zeros_like(colj))
        lcol = jnp.where(rcol == j, dj.astype(dt), lcol)
        outer = lcol * jnp.swapaxes(jnp.conj(lcol), 0, 1)
        A = A - jnp.where((ci > j) & (ri >= ci), outer, 0)
        return jnp.where(ci == j, lcol, A)

    return jnp.tril(lax.fori_loop(0, b, body, B))


def _trinv_unb(L):
    """Forward-substitution inverse of a (b, b) lower-triangular block:
    row i of L^{-1} from rows < i, one masked (1, b) x (b, b) dot per
    step."""
    b = L.shape[0]
    dt = L.dtype
    ri = lax.broadcasted_iota(jnp.int32, (b, b), 0)
    ci = lax.broadcasted_iota(jnp.int32, (b, b), 1)
    crow = ci[:1, :]
    one = jnp.ones((), dt)

    def body(i, X):
        lrow = jnp.sum(jnp.where(ri == i, L, 0), axis=0, keepdims=True)
        dii = jnp.sum(jnp.where(crow == i, lrow, 0))
        lstrict = jnp.where(crow < i, lrow, jnp.zeros_like(lrow))
        corr = jnp.dot(lstrict, X, precision=_HI)
        erow = jnp.where(crow == i, one, jnp.zeros_like(lrow))
        newrow = (erow - corr) / dii
        return jnp.where(ri == i, newrow, X)

    return lax.fori_loop(0, b, body, jnp.zeros((b, b), dt))


def _potrf_inv_kernel(d_ref, l_ref, li_ref, *, w, bs, precision):
    D = d_ref[...]
    dt = D.dtype
    # symmetrize from the lower triangle, as the reference does (the
    # padded border is zero and stays zero)
    d = jnp.tril(D)
    d = d + jnp.conj(jnp.tril(d, -1)).T
    L = jnp.zeros_like(d)
    Li = jnp.zeros_like(d)
    T = d
    # block writes go through dynamic_update_slice (static starts): the
    # .at[].set scatter path constant-folds its index arrays, and when a
    # slice covers the whole (unpadded) block those fold to EMPTY int32
    # constants the kernel would illegally capture
    for s in range(0, w, bs):
        e = min(s + bs, w)
        dkk = T[s:e, s:e]
        dkk = jnp.tril(dkk) + jnp.conj(jnp.tril(dkk, -1)).T
        Lkk = _chol_unb(dkk)
        Likk = _trinv_unb(Lkk)
        L = lax.dynamic_update_slice(L, Lkk, (s, s))
        # inverse assembly: Li[s:e, :s] = -Likk @ L[s:e, :s] @ Li[:s, :s]
        if s > 0:
            corr = jnp.dot(
                Likk, jnp.dot(L[s:e, :s], Li[:s, :s], precision=precision),
                precision=precision)
            Li = lax.dynamic_update_slice(Li, -corr.astype(dt), (s, 0))
        Li = lax.dynamic_update_slice(Li, Likk, (s, s))
        if e < w:
            B21 = jnp.dot(T[e:w, s:e], jnp.conj(Likk).T,
                          precision=precision).astype(dt)
            L = lax.dynamic_update_slice(L, B21, (e, s))
            T = lax.dynamic_update_slice(
                T, T[e:w, e:w] - jnp.dot(B21, jnp.conj(B21).T,
                                         precision=precision).astype(dt),
                (e, e))
    l_ref[...] = L
    li_ref[...] = Li


def potrf_inv(D, precision=None, *, bs: int = 512, interpret=None):
    """Fused twin of ``lapack.cholesky._potrf_inv_impl``: one launch,
    same contract ``(L, L^{-1})`` from a (w, w) Hermitian block (lower
    triangle valid).  Real dtypes only -- complex panels are gated back
    to the XLA path by the ``panel_impl`` dispatch."""
    w = D.shape[0]
    if jnp.issubdtype(D.dtype, jnp.complexfloating):
        raise ValueError("pallas potrf_inv is real-only; the panel_impl "
                         "dispatch falls back to xla for complex dtypes")
    # factor-forming dots run at full accumulation, matching lu._hi
    precision = _HI if precision is None else precision
    Dp = pad_square(D)
    kern = functools.partial(_potrf_inv_kernel, w=w, bs=int(bs),
                             precision=precision)
    shp = jax.ShapeDtypeStruct(Dp.shape, D.dtype)
    L, Li = pl.pallas_call(
        kern,
        out_shape=(shp, shp),
        interpret=interpret_default(interpret),
    )(Dp)
    return L[:w, :w], Li[:w, :w]
