"""Shared plumbing for the fused Pallas panel kernels (ISSUE 17).

Panel factorization is replicated-local compute: every rank holds the
whole [STAR,STAR] panel and runs the same serial column recurrence, so
the fusion problem is purely single-chip -- keep the panel resident in
VMEM, run the recurrence as one kernel body, and emit the packed factor
in a single store.  This module holds what all three kernels share:

* tile-aligned padding: float32 VMEM tiles are (sublane, lane) =
  (8, 128), so inputs are padded up to tile multiples and the column
  recurrences run only over the real extent -- the padding is zeros
  that never reach a pivot decision or a stored factor entry (padded
  rows are masked out of argmax candidates; padded columns only ever
  receive exact-zero updates);
* the VMEM residency budget that gates whole-panel fusion: a panel
  whose working set cannot fit stays on the XLA ladder.  Honesty about
  applicability is what keeps the ``panel_impl='auto'`` cost term
  truthful -- the kernels never silently spill;
* the interpret-mode decision: off-TPU the kernels run under
  ``pl.pallas_call(interpret=True)`` so CPU CI executes the very same
  kernel bodies -- bit-for-bit for the LU pivot sequence, residual-
  bounded for Cholesky/QR -- against their XLA twins.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: float32 VMEM tile extents (sublane x lane); narrower dtypes pack more
#: sublanes but (8, 128) alignment is valid for every dtype we ship.
SUBLANE = 8
LANE = 128

#: Per-core VMEM the fused kernels may claim for one panel's working set
#: (input + functional carries + packed output).  ~16 MiB/core is the
#: architectural budget; claiming all of it would starve the compiler's
#: own double-buffering, so the gate in :meth:`PanelPlan.use_pallas`
#: divides this by the kernel's resident-copy count.
PANEL_VMEM_BUDGET = 16 * 2 ** 20


def round_up(n: int, m: int) -> int:
    return -(-max(int(n), 1) // m) * m


def interpret_default(interpret=None) -> bool:
    """Resolve the ``interpret=`` tristate: explicit wins, else interpret
    everywhere but real TPU (CPU CI runs the same kernel bodies)."""
    if interpret is not None:
        return bool(interpret)
    return jax.default_backend() != "tpu"


def pad_tiles(x):
    """Zero-pad a 2-D operand up to (SUBLANE, LANE) tile multiples."""
    m, n = x.shape
    mp, np_ = round_up(m, SUBLANE), round_up(n, LANE)
    if (mp, np_) == (m, n):
        return x
    return jnp.pad(x, ((0, mp - m), (0, np_ - n)))


def pad_square(x):
    """Zero-pad a square operand to a LANE multiple on both axes (the
    Cholesky/larft kernels transpose in-kernel, so both axes must be
    lane-aligned)."""
    w = x.shape[0]
    wp = round_up(w, LANE)
    if wp == w:
        return x
    return jnp.pad(x, ((0, wp - w), (0, wp - w)))


def panel_fits(shape, dtype, copies: int = 3,
               budget: int = PANEL_VMEM_BUDGET) -> bool:
    """Static gate: does ``copies`` tile-padded residents of this panel
    fit the VMEM budget?  Evaluated per call site at trace time (shapes
    are static), so the xla/pallas choice is baked into the jaxpr."""
    mp = round_up(shape[0], SUBLANE)
    np_ = round_up(shape[1], LANE)
    return copies * mp * np_ * jnp.dtype(dtype).itemsize <= budget
