"""Fused Pallas QR panel: the larfg reflector chain AND the larft
T-triangle build in one kernel launch.

The XLA path runs ``lapack.qr._panel_qr`` (per column: a norm, a
divide, one (1, n) row dot, one rank-1 update) and then ``_larft`` (a
Gram matmul plus k small matvecs) as separate fori_loops -- dozens of
latency-bound launches per panel on the factorization spine.  Here the
panel is VMEM-resident: the reflector chain, the Gram product
``V^H V``, and the forward-columnwise T recurrence all run inside one
``pallas_call``, returning ``(packed V\\R, tau, T)`` so the driver
skips the separate ``_larft`` call entirely.

The kernel body mirrors the reference op-for-op (same degenerate
guards, same HIGHEST-precision dots), but the padded-operand reductions
group differently than the XLA (M,)-vector sums, so the twin contract
is residual-bounded (``Q R ~ A``, orthonormal Q), not bit-pinned --
see ``tests/kernels/test_qr_panel.py`` for the documented bounds.
Real dtypes only; complex panels are gated back to XLA by the
``panel_impl`` dispatch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .common import LANE, interpret_default, pad_tiles, round_up

_HI = lax.Precision.HIGHEST


def _qr_panel_kernel(p_ref, out_ref, tau_ref, t_ref, *, m, k):
    P = p_ref[...]
    mp, wp = P.shape
    dt = P.dtype
    ridx = lax.broadcasted_iota(jnp.int32, (mp, 1), 0)
    cidx = lax.broadcasted_iota(jnp.int32, (1, wp), 1)

    def body(j, state):
        # the larfg recurrence of _panel_qr, column-masked: padded rows
        # are zero and contribute exact zeros to sigma / the row dot
        P, tau = state
        col = lax.dynamic_slice_in_dim(P, j, 1, 1)
        alpha = lax.dynamic_slice(P, (j, j), (1, 1))[0, 0]
        tail = jnp.where(ridx > j, col, 0)
        sigma = jnp.sum(jnp.abs(tail) ** 2)
        anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
        re_a = jnp.real(alpha)
        beta = -jnp.sign(jnp.where(re_a == 0, 1.0, re_a)) * anorm
        degenerate = anorm == 0
        safe_beta = jnp.where(degenerate, 1.0, beta)
        tau_j = jnp.where(degenerate, 0.0, (safe_beta - alpha) / safe_beta)
        denom = alpha - safe_beta
        safe_denom = jnp.where(denom == 0, 1.0, denom)
        v = jnp.where(ridx > j, col / safe_denom, jnp.zeros_like(col))
        v = jnp.where(ridx == j,
                      jnp.where(degenerate, 0.0, 1.0).astype(dt), v)
        w = jnp.dot(jnp.swapaxes(jnp.conj(v), 0, 1), P, precision=_HI)
        upd = (jnp.conj(tau_j) * v) * w
        P = P - jnp.where(cidx > j, upd, 0)
        newcol = jnp.where(ridx > j, v, col)
        newcol = jnp.where(ridx == j, jnp.asarray(beta, dt), newcol)
        P = lax.dynamic_update_slice_in_dim(P, newcol, j, 1)
        tau = lax.dynamic_update_slice(
            tau, jnp.asarray(tau_j, dt).reshape(1, 1), (j, 0))
        return P, tau

    P, tau = lax.fori_loop(0, k, body, (P, jnp.zeros((wp, 1), dt)))

    # larft, fused: V from the packed panel, one Gram dot, then the
    # forward-columnwise T recurrence of _larft.  Padded V columns are
    # unit vectors e_j but every T write is masked to kidx < i < k, so
    # the padded border of T stays exactly zero.
    V = jnp.tril(P, -1) + jnp.eye(mp, wp, dtype=dt)
    B = jnp.dot(jnp.swapaxes(jnp.conj(V), 0, 1), V, precision=_HI)
    kidx = lax.broadcasted_iota(jnp.int32, (wp, 1), 0)

    def tbody(i, T):
        coli = lax.dynamic_slice_in_dim(B, i, 1, 1)
        coli = jnp.where(kidx < i, coli, jnp.zeros_like(coli))
        taui = lax.dynamic_slice(tau, (i, 0), (1, 1))[0, 0]
        newcol = -taui * jnp.dot(T, coli, precision=_HI)
        newcol = jnp.where(kidx == i, taui.astype(dt), newcol)
        return lax.dynamic_update_slice_in_dim(T, newcol, i, 1)

    T = lax.fori_loop(0, k, tbody, jnp.zeros((wp, wp), dt))
    out_ref[...] = P
    tau_ref[...] = tau
    t_ref[...] = T


def qr_panel(P, *, interpret=None):
    """Fused twin of ``lapack.qr._panel_qr`` + ``_larft``: one launch
    returning ``(packed V\\R, tau, T)`` with the same LAPACK larfg
    conventions (real beta, H_j = I - tau_j v_j v_j^H applied as H^H)."""
    M, k = P.shape
    if jnp.issubdtype(P.dtype, jnp.complexfloating):
        raise ValueError("pallas QR panel is real-only; the panel_impl "
                         "dispatch falls back to xla for complex dtypes")
    Pp = pad_tiles(P)
    mp, wp = Pp.shape
    tp = round_up(wp, LANE)
    kern = functools.partial(_qr_panel_kernel, m=M, k=k)
    packed, tau, T = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((mp, wp), P.dtype),
                   jax.ShapeDtypeStruct((wp, 1), P.dtype),
                   jax.ShapeDtypeStruct((tp, tp), P.dtype)),
        interpret=interpret_default(interpret),
    )(Pp)
    return packed[:M, :k], tau[:k, 0], T[:k, :k]
