"""Fused Pallas LU panel: pivot search, row swap, column scale, and the
rank-1 / chunk-blocked trailing updates in ONE kernel launch.

The XLA path (``lapack.lu._panel_lu``) lowers each column step to a
handful of small ops -- argmax, two row gathers, two scatters, a divide,
an outer product -- and the ``_INNERS`` chunk ladder adds a
triangular-solve + matmul pair per chunk.  At nb = 256 that is O(10^3)
tiny kernels on the factorization's serial spine.  Here the whole panel
sits in VMEM and the column recurrence is a single ``lax.fori_loop``
inside one ``pallas_call``; the packed L\\U factor and the pivot
sequence come back in one store each.

Two modes, selected by the static ``inner`` width:

* ``inner=0`` -- the unblocked twin of ``_panel_lu_unb``.  Every op is
  elementwise or an argmax (no reductions over changed extents), so the
  pivot sequence and the packed factor are BIT-IDENTICAL to the XLA
  reference, including first-max argmax tie-breaking.  This is the mode
  the CPU CI pins.
* ``inner=k`` -- the in-kernel analog of the ``_INNERS`` chunk ladder:
  per-column rank-1 updates restricted to the current chunk, then a
  forward-substitution U12 solve and one MXU-shaped trailing ``dot``
  per chunk.  Same math as the ladder's ``triangular_solve`` + matmul
  pair, different summation order -- residual-bounded, not bit-pinned.

Pivot indices are returned as the per-step swap sequence (LAPACK ipiv
convention, absolute panel rows); the composed permutation is replayed
OUTSIDE the kernel by the exact bookkeeping ``_panel_lu_unb`` does on
``perm`` -- integer swaps are not worth VMEM residency and keeping the
kernel outputs matrix-shaped keeps the Mosaic lowering trivial.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .common import interpret_default, pad_tiles


def _swap_rows(X, j, p):
    rowj = lax.dynamic_slice_in_dim(X, j, 1, 0)
    rowp = lax.dynamic_slice_in_dim(X, p, 1, 0)
    X = lax.dynamic_update_slice_in_dim(X, rowp, j, 0)
    return lax.dynamic_update_slice_in_dim(X, rowj, p, 0)


def _lu_panel_kernel(p_ref, out_ref, piv_ref, *, m, nbw, inner, precision):
    P = p_ref[...]
    mp, wp = P.shape
    dt = P.dtype
    ridx = lax.broadcasted_iota(jnp.int32, (mp, 1), 0)
    cidx = lax.broadcasted_iota(jnp.int32, (1, wp), 1)
    neg = jnp.asarray(-jnp.inf, dt)

    def col_step(hi):
        # factor column j in place, restricting the rank-1 update to
        # columns (j, hi): hi == wp is the unblocked _panel_lu_unb twin
        # (padded columns are zero, so updating them is a no-op), hi ==
        # chunk end is the blocked-MXU mode.  Ops mirror the reference
        # body exactly -- same candidate mask, same first-max argmax,
        # same divide -- so the unblocked pivot sequence is bit-equal.
        def body(j, carry):
            P, piv = carry
            col = lax.dynamic_slice_in_dim(P, j, 1, 1)
            cand = jnp.where((ridx >= j) & (ridx < m), jnp.abs(col), neg)
            p = jnp.argmax(cand).astype(jnp.int32)
            P = _swap_rows(P, j, p)
            piv = lax.dynamic_update_slice(piv, p[None, None], (j, 0))
            pivval = lax.dynamic_slice(P, (j, j), (1, 1))
            col = lax.dynamic_slice_in_dim(P, j, 1, 1)
            colnew = jnp.where(ridx > j, col / pivval, col)
            P = lax.dynamic_update_slice_in_dim(P, colnew, j, 1)
            l = jnp.where(ridx > j, colnew, jnp.zeros_like(colnew))
            urow = lax.dynamic_slice_in_dim(P, j, 1, 0)
            urow = jnp.where((cidx > j) & (cidx < hi), urow,
                             jnp.zeros_like(urow))
            return P - l * urow, piv

        return body

    piv = jnp.zeros((wp, 1), jnp.int32)
    if inner <= 0 or inner >= nbw:
        P, piv = lax.fori_loop(0, nbw, col_step(wp), (P, piv))
    else:
        for s in range(0, nbw, inner):
            e = min(s + inner, nbw)
            P, piv = lax.fori_loop(s, e, col_step(e), (P, piv))
            if e >= nbw:
                break
            # chunk tail, fused: U12 = L11^{-1} A12 by unit-diagonal
            # forward substitution (the ladder's triangular_solve), then
            # one MXU trailing dot A22 -= L21 @ U12 (the ladder's
            # matmul) -- both on the VMEM-resident carry.
            w = e - s
            L11 = P[s:e, s:e]
            tloc = lax.broadcasted_iota(jnp.int32, (1, w), 1)
            trail = cidx >= e

            def sub_body(i, U):
                li = lax.dynamic_slice_in_dim(L11, i, 1, 0)
                li = jnp.where(tloc < i, li, jnp.zeros_like(li))
                corr = jnp.dot(li, U, precision=precision)
                ui = lax.dynamic_slice_in_dim(U, i, 1, 0)
                return lax.dynamic_update_slice_in_dim(U, ui - corr, i, 0)

            A12 = jnp.where(trail, P[s:e, :], jnp.zeros((w, wp), dt))
            U12 = lax.fori_loop(0, w, sub_body, A12)
            P = P.at[s:e, :].set(jnp.where(trail, U12, P[s:e, :]))
            L21 = jnp.where(ridx >= e, P[:, s:e], jnp.zeros((mp, w), dt))
            P = P - jnp.dot(L21, U12, precision=precision)
    out_ref[...] = P
    piv_ref[...] = piv


def lu_panel(P, nbw: int, precision=None, *, inner: int = 0,
             interpret=None):
    """Fused twin of ``lapack.lu._panel_lu``: one launch, same contract
    ``(packed L\\U, composed row permutation)``.

    Real dtypes only -- callers gate complex panels back to the XLA
    ladder (the dispatch in ``PanelPlan.use_pallas``); reaching here
    with a complex panel is a caller bug and raises loudly.
    """
    M, w = P.shape
    nbw = int(nbw)
    if jnp.issubdtype(P.dtype, jnp.complexfloating):
        raise ValueError("pallas LU panel is real-only; the panel_impl "
                         "dispatch falls back to xla for complex dtypes")
    Pp = pad_tiles(P)
    mp, wp = Pp.shape
    kern = functools.partial(_lu_panel_kernel, m=M, nbw=nbw,
                             inner=int(inner), precision=precision)
    packed, piv = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((mp, wp), P.dtype),
                   jax.ShapeDtypeStruct((wp, 1), jnp.int32)),
        interpret=interpret_default(interpret),
    )(Pp)
    packed = packed[:M, :w]
    piv = piv[:nbw, 0]

    # replay the per-step swap sequence into the composed permutation --
    # exactly the bookkeeping _panel_lu_unb does on `perm`, hoisted out
    # of the kernel (integer swaps don't earn VMEM residency).
    def body(j, perm):
        p = piv[j]
        pj, pp_ = perm[j], perm[p]
        return perm.at[j].set(pp_).at[p].set(pj)

    perm = lax.fori_loop(0, nbw, body, jnp.arange(M))
    return packed, perm
