"""Fused Pallas panel kernels for the factorization critical path
(ISSUE 17).

Panel factorization is the serial spine of every blocked schedule: the
LU chunk ladder, the Cholesky diagonal-block factor/inverse pair, and
the QR larfg chain each lower to dozens of small XLA ops whose launch
and layout overhead dominates at small nb.  This package fuses each
primitive into one ``pallas_call`` that keeps the replicated panel
resident in VMEM:

* :func:`lu_panel` -- pivot search + column scale + rank-1/chunked
  trailing updates, bit-twin of ``lapack.lu._panel_lu`` (pivot sequence
  identical in unblocked mode);
* :func:`potrf_inv` -- blocked potrf + triangular inverse, twin of
  ``lapack.cholesky._potrf_inv_impl`` (residual-bounded);
* :func:`qr_panel` -- larfg reflector chain + larft T build, twin of
  ``lapack.qr._panel_qr`` + ``_larft`` (residual-bounded).

Selection is driven by the ``panel_impl='xla'|'pallas'|'auto'`` knob on
``lu`` / ``cholesky`` / ``qr``: :func:`resolve_panel` turns the
resolved knob into a :class:`PanelPlan`, and each call site asks
``plan.use_pallas(shape, dtype)`` -- a STATIC trace-time gate that
falls back to the XLA twin for complex dtypes and for panels whose
working set exceeds the VMEM budget, so the fused kernels never
silently spill.  Off-TPU the kernels run under
``pl.pallas_call(interpret=True)``, which is how CPU CI pins the twins
(see ``tests/kernels/``).

Panels are replicated-local compute: a ``pallas_call`` is a local
primitive with no collectives, so every comm-plan golden is byte-
identical under either implementation (gated by ``tools/check.sh
kernels``).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .common import (LANE, PANEL_VMEM_BUDGET, SUBLANE, interpret_default,
                     pad_square, pad_tiles, panel_fits, round_up)
from .lu_panel import lu_panel
from .chol_panel import potrf_inv
from .qr_panel import qr_panel

#: implementations the ``panel_impl`` knob enumerates ('auto' resolves
#: to one of these); 'xla' first, so ties in the tuner's cost ranking
#: keep the status-quo path (same convention as tune.knobs.LU_PANELS).
PANEL_IMPLS = ("xla", "pallas")

#: LU chunk ladder, pinned from a v5e A/B sweep (perf/ab_harness.py lu,
#: BENCH_r05: 512/64 beat 256/64 and 512/128 by 4-7%% at N=16384).
#: Single source of truth -- lapack.lu, the A/B harness, and bench
#: provenance all read it through default_inners() / resolve_panel()
#: rather than importing a bare module constant that monkeypatching
#: would silently go stale on (the ISSUE 17 staleness footgun).
DEFAULT_INNERS = (512, 64)


def default_inners() -> tuple:
    """The pinned LU panel chunk ladder (see :data:`DEFAULT_INNERS`)."""
    return DEFAULT_INNERS


@dataclass(frozen=True)
class PanelPlan:
    """Resolved panel-implementation choice plus its provenance.

    ``impl`` is the post-'auto' knob value; ``inners`` is the LU chunk
    ladder the XLA path recurses on AND the width the fused kernel's
    blocked mode uses (``pallas_inner``); ``source`` records where the
    choice came from ('default', 'explicit', 'tuned', 'complex-xla')
    so bench provenance can attribute a headline move to the knob.
    """

    impl: str = "xla"
    inners: tuple = DEFAULT_INNERS
    source: str = "default"

    def use_pallas(self, shape, dtype, copies: int = 3) -> bool:
        """Static per-call-site gate: fused kernel only for real dtypes
        whose padded working set (``copies`` VMEM residents) fits the
        budget; everything else stays on the XLA twin."""
        if self.impl != "pallas":
            return False
        if jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
            return False
        return panel_fits(shape, dtype, copies=copies)

    @property
    def pallas_inner(self) -> int:
        """Chunk width for the fused LU kernel's blocked mode: the
        finest rung of the ladder (coarser rungs exist to amortize XLA
        launches, which the fused kernel has already paid once)."""
        return int(self.inners[-1]) if self.inners else 0

    def to_doc(self) -> dict:
        return {"impl": self.impl, "inners": list(self.inners),
                "source": self.source}


def resolve_panel(panel_impl=None, *, dtype=None, inners=None,
                  source: str | None = None) -> PanelPlan:
    """Turn a resolved ``panel_impl`` knob value into a
    :class:`PanelPlan`.

    ``None`` means the status-quo XLA path ('auto' is resolved by
    ``tune.resolve_knobs`` BEFORE this point -- drivers never pass it
    here).  Complex dtypes fall back to 'xla' silently by design: the
    knob is a performance hint and the XLA twin is the same math, so a
    complex matrix through ``panel_impl='pallas'`` must factor, not
    raise (pinned by tests/kernels/test_dispatch.py).
    """
    impl = "xla" if panel_impl is None else str(panel_impl)
    if impl == "auto":
        # defensive: an unresolved 'auto' (e.g. tuner disabled) keeps
        # the status-quo path rather than guessing at the backend here
        impl = "xla"
    if impl not in PANEL_IMPLS:
        raise ValueError(
            f"panel_impl must be one of {PANEL_IMPLS + ('auto',)}, "
            f"got {panel_impl!r}")
    src = source if source is not None else (
        "default" if panel_impl is None else "explicit")
    if (impl == "pallas" and dtype is not None
            and jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)):
        impl, src = "xla", "complex-xla"
    lad = default_inners() if inners is None else tuple(
        int(i) for i in inners)
    return PanelPlan(impl=impl, inners=lad, source=src)
