"""Positive-orthant cone utilities + IPM control structs.

Reference: Elemental ``src/optimization/util/PosOrth/**`` (``El::pos_orth``:
``MaxStep``, ``NumOutside``, complementarity helpers) and the ``MehrotraCtrl``
tuning struct (``include/El/optimization/solvers.hpp``), mapped to a plain
dataclass per SURVEY.md §6.6.

Vectors are (k, 1) [MC,MR] DistMatrices; elementwise cone ops run directly
on storage arrays (each entry exactly once, padding zero -- guarded where a
zero denominator could poison the result).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core.distmatrix import DistMatrix


@dataclasses.dataclass(frozen=True)
class MehrotraCtrl:
    """Tolerances/switches for the Mehrotra predictor-corrector IPMs
    (``El::MehrotraCtrl``)."""
    tol: float = 1e-8
    max_iters: int = 100
    eta: float = 0.995          # fraction-to-the-boundary damping
    init_shift: float = 10.0    # Mehrotra initialization delta scaling
    print_progress: bool = False
    equilibrate: bool = True    # Ruiz-equilibrate the data first
                                # (El::RuizEquil, upstream's mandatory step)


def safe_div(a, b):
    """a / b with 0/0 -> 0 (padding-safe elementwise divide)."""
    return jnp.where(b != 0, a / jnp.where(b == 0, 1, b), 0)


def max_step(x: DistMatrix, dx: DistMatrix, cap: float = 1.0):
    """sup {alpha <= cap : x + alpha dx >= 0} for interior x > 0
    (``El::pos_orth::MaxStep``).  Returns a traced scalar."""
    ratio = jnp.where(dx.local < 0, -safe_div(x.local, dx.local), jnp.inf)
    return jnp.minimum(jnp.min(ratio), cap)


def num_outside(x: DistMatrix):
    """Entries strictly outside the cone (``pos_orth::NumOutside``);
    padding zeros count as on the boundary, not outside."""
    return jnp.sum(x.local < 0)

