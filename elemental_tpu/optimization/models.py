"""Optimization models reduced to the LP/QP solvers + prox ADMM.

Reference: Elemental ``src/optimization/models/**`` -- ``BP.cpp``
(``El::BP``: basis pursuit -> LP), ``LAV.cpp`` (least absolute value
regression -> LP), ``NNLS.cpp`` (-> QP), ``Lasso``/BPDN (-> QP),
``SVM.cpp`` (soft-margin -> QP), ``RPCA.cpp`` (ADMM with SVT).

Each model assembles its standard form with the distributed stacking
primitives (vstack/hstack/interior_update) and hands off to
:func:`..optimization.lp.lp` / :func:`..optimization.qp.qp`.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.distmatrix import DistMatrix
from ..redist.interior import interior_view, interior_update, vstack, hstack, _blank
from ..core.dist import MC, MR
from ..blas.level1 import shift_diagonal, frobenius_norm
from ..blas.level3 import gemm
from .lp import lp, _tp
from .qp import qp
from .prox import soft_threshold, svt
from .util import MehrotraCtrl


def _identity_like(A: DistMatrix, m: int) -> DistMatrix:
    return shift_diagonal(_blank(m, m, A), 1)


def _neg(A: DistMatrix) -> DistMatrix:
    return A.with_local(-A.local)


def _ones(A: DistMatrix, m: int) -> DistMatrix:
    from ..blas.level1 import fill
    return fill(_blank(m, 1, A), 1)


def bp(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
       nb: int | None = None, precision=None):
    """Basis pursuit min ||x||_1 s.t. Ax = b (``El::BP``): split x = u - v,
    LP over [u; v] >= 0."""
    m, n = A.gshape
    Ae = hstack(A, _neg(A))
    ce = _ones(A, 2 * n)
    x2, _, _, info = lp(Ae, b, ce, ctrl, nb=nb, precision=precision)
    u = interior_view(x2, (0, n), (0, 1))
    v = interior_view(x2, (n, 2 * n), (0, 1))
    return u.with_local(u.local - v.local), info


def lav(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
        nb: int | None = None, precision=None):
    """Least-absolute-value regression min ||Ax - b||_1 (``El::LAV``):
    x = xp - xm, residual r = u - v, LP over [xp; xm; u; v] >= 0."""
    m, n = A.gshape
    I = _identity_like(A, m)
    Ae = hstack(hstack(A, _neg(A)), hstack(I, _neg(I)))
    cz = _blank(2 * n, 1, A)
    co = _ones(A, 2 * m)
    ce = vstack(cz, co)
    x4, _, _, info = lp(Ae, b, ce, ctrl, nb=nb, precision=precision)
    xp = interior_view(x4, (0, n), (0, 1))
    xm = interior_view(x4, (n, 2 * n), (0, 1))
    return xp.with_local(xp.local - xm.local), info


def nnls(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
         nb: int | None = None, precision=None):
    """Nonnegative least squares min ||Ax - b||_2, x >= 0 (``El::NNLS``):
    QP with Q = A^T A, c = -A^T b."""
    At = _tp(A)
    Q = gemm(At, A, nb=nb, precision=precision)
    c = _neg(gemm(At, b, nb=nb, precision=precision))
    x, _, _, info = qp(Q, c, ctrl=ctrl, nb=nb, precision=precision)
    return x, info


def lasso(A: DistMatrix, b: DistMatrix, lam: float,
          ctrl: MehrotraCtrl | None = None, nb: int | None = None,
          precision=None):
    """min 1/2 ||Ax - b||^2 + lam ||x||_1 (``El::Lasso``/BPDN): split
    x = u - v, QP over [u; v] >= 0 with Q = [[G, -G], [-G, G]]."""
    m, n = A.gshape
    At = _tp(A)
    G = gemm(At, A, nb=nb, precision=precision)
    Atb = gemm(At, b, nb=nb, precision=precision)
    Q = _blank(2 * n, 2 * n, A)
    Q = interior_update(Q, G, (0, 0))
    Q = interior_update(Q, _neg(G), (0, n))
    Q = interior_update(Q, _neg(G), (n, 0))
    Q = interior_update(Q, G, (n, n))
    lam1 = _ones(A, 2 * n)
    c = vstack(_neg(Atb), Atb)
    c = c.with_local(lam * lam1.local + c.local)
    x2, _, _, info = qp(Q, c, ctrl=ctrl, nb=nb, precision=precision)
    u = interior_view(x2, (0, n), (0, 1))
    v = interior_view(x2, (n, 2 * n), (0, 1))
    return u.with_local(u.local - v.local), info


def svm(X: DistMatrix, labels, C: float = 1.0,
        ctrl: MehrotraCtrl | None = None, nb: int | None = None,
        precision=None):
    """Soft-margin linear SVM (``El::SVM``) via the box-constrained dual

        min 1/2 a^T (Y X X^T Y) a - 1^T a,  0 <= a <= C,  y^T a = 0

    solved as a standard-form QP over [a; s] with a + s = C.  Returns
    (w, bias, info)."""
    m, n = X.gshape
    y = jnp.asarray(labels).reshape(-1)
    if y.shape[0] != m:
        raise ValueError(f"labels must have length {m}")
    Xt = _tp(X)
    K = gemm(X, Xt, nb=nb, precision=precision)          # m x m Gram
    from ..core.distmatrix import to_global, from_global
    # Y K Y scaling is a rank-structured elementwise op: do it via the
    # replicated label vector on storage index maps
    from ..blas.level1 import _global_indices
    I, J = _global_indices(K)
    yI = y[jnp.clip(I, 0, m - 1)][:, None]
    yJ = y[jnp.clip(J, 0, m - 1)][None, :]
    Kyy = K.with_local(K.local * yI * yJ)
    Q = _blank(2 * m, 2 * m, X)
    Q = interior_update(Q, Kyy, (0, 0))
    c = vstack(_neg(_ones(X, m)), _blank(m, 1, X))
    # equality constraints: y^T a = 0;  a + s = C
    yrow = from_global(np.asarray(y, np.float64).reshape(1, -1)
                       .astype(np.dtype(X.dtype)), MC, MR, grid=X.grid)
    Arow = hstack(yrow, _blank(1, m, X))
    I_m = _identity_like(X, m)
    Abox = hstack(I_m, I_m)
    Ae = vstack(Arow, Abox)
    be = vstack(_blank(1, 1, X), _ones(X, m).with_local(
        C * _ones(X, m).local))
    x2, _, _, info = qp(Q, c, Ae, be, ctrl=ctrl, nb=nb, precision=precision)
    a = interior_view(x2, (0, m), (0, 1))
    # w = X^T (a . y);  bias from margin support vectors (0 < a < C)
    ay = a.with_local(a.local * y[jnp.clip(_global_indices(a)[0], 0, m - 1)][:, None])
    w = gemm(Xt, ay, nb=nb, precision=precision)
    ag = np.asarray(to_global(a)).ravel()
    Xg = np.asarray(to_global(X))
    wg = np.asarray(to_global(w)).ravel()
    sv = (ag > 1e-6 * C) & (ag < (1 - 1e-6) * C)
    yn = np.asarray(y)
    bias = float(np.mean(yn[sv] - Xg[sv] @ wg)) if np.any(sv) else 0.0
    return w, bias, info


def rpca(M: DistMatrix, lam: float | None = None, tol: float = 1e-6,
         max_iters: int = 100, nb: int | None = None, precision=None):
    """Robust PCA min ||L||_* + lam ||S||_1 s.t. L + S = M (``El::RPCA``,
    ALM/ADMM with singular-value thresholding).  Returns (L, S, info)."""
    m, n = M.gshape
    lam = lam if lam is not None else 1.0 / math.sqrt(max(m, n))
    normM = float(frobenius_norm(M))
    # canonical IALM parameters (Lin-Chen-Ma): Y0 = M / J(M), mu0 = 1.25/||M||_2
    from ..lapack.spectral import svd as _svd
    s2 = float(_svd(M, vectors=False, nb=nb, precision=precision)[0])
    ninf = float(jnp.max(jnp.abs(M.local)))
    J = max(s2, ninf / lam, 1e-300)
    S = M.with_local(jnp.zeros_like(M.local))
    Y = M.with_local(M.local / J)
    mu = 1.25 / max(s2, 1e-300)
    mu_max = mu * 1e7
    info = {"iters": 0, "converged": False}
    for it in range(max_iters):
        L = svt(M.with_local(M.local - S.local + Y.local / mu), 1.0 / mu,
                nb=nb, precision=precision)
        S = soft_threshold(M.with_local(M.local - L.local + Y.local / mu),
                           lam / mu)
        R = M.with_local(M.local - L.local - S.local)
        Y = Y.with_local(Y.local + mu * R.local)
        mu = min(1.5 * mu, mu_max)          # inexact-ALM penalty growth
        err = float(frobenius_norm(R)) / max(normM, 1e-300)
        info.update(iters=it, err=err)
        if err < tol:
            info["converged"] = True
            break
    return L, S, info
