"""Optimization models reduced to the LP/QP solvers + prox ADMM.

Reference: Elemental ``src/optimization/models/**`` -- ``BP.cpp``
(``El::BP``: basis pursuit -> LP), ``LAV.cpp`` (least absolute value
regression -> LP), ``NNLS.cpp`` (-> QP), ``Lasso``/BPDN (-> QP),
``SVM.cpp`` (soft-margin -> QP), ``RPCA.cpp`` (ADMM with SVT).

Each model assembles its standard form with the distributed stacking
primitives (vstack/hstack/interior_update) and hands off to
:func:`..optimization.lp.lp` / :func:`..optimization.qp.qp`.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.distmatrix import DistMatrix, from_global, to_global
from ..redist.interior import interior_view, interior_update, vstack, hstack, _blank
from ..core.dist import MC, MR
from ..blas.level1 import shift_diagonal, frobenius_norm
from ..blas.level3 import gemm
from .lp import lp, _tp
from .qp import qp
from .prox import soft_threshold, svt
from .util import MehrotraCtrl


def _identity_like(A: DistMatrix, m: int) -> DistMatrix:
    return shift_diagonal(_blank(m, m, A), 1)


def _neg(A: DistMatrix) -> DistMatrix:
    return A.with_local(-A.local)


def _ones(A: DistMatrix, m: int) -> DistMatrix:
    from ..blas.level1 import fill
    return fill(_blank(m, 1, A), 1)


def bp(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
       nb: int | None = None, precision=None):
    """Basis pursuit min ||x||_1 s.t. Ax = b (``El::BP``): split x = u - v,
    LP over [u; v] >= 0."""
    m, n = A.gshape
    Ae = hstack(A, _neg(A))
    ce = _ones(A, 2 * n)
    x2, _, _, info = lp(Ae, b, ce, ctrl, nb=nb, precision=precision)
    u = interior_view(x2, (0, n), (0, 1))
    v = interior_view(x2, (n, 2 * n), (0, 1))
    return u.with_local(u.local - v.local), info


def lav(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
        nb: int | None = None, precision=None):
    """Least-absolute-value regression min ||Ax - b||_1 (``El::LAV``):
    x = xp - xm, residual r = u - v, LP over [xp; xm; u; v] >= 0."""
    m, n = A.gshape
    I = _identity_like(A, m)
    Ae = hstack(hstack(A, _neg(A)), hstack(I, _neg(I)))
    cz = _blank(2 * n, 1, A)
    co = _ones(A, 2 * m)
    ce = vstack(cz, co)
    x4, _, _, info = lp(Ae, b, ce, ctrl, nb=nb, precision=precision)
    xp = interior_view(x4, (0, n), (0, 1))
    xm = interior_view(x4, (n, 2 * n), (0, 1))
    return xp.with_local(xp.local - xm.local), info


def nnls(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
         nb: int | None = None, precision=None):
    """Nonnegative least squares min ||Ax - b||_2, x >= 0 (``El::NNLS``):
    QP with Q = A^T A, c = -A^T b."""
    At = _tp(A)
    Q = gemm(At, A, nb=nb, precision=precision)
    c = _neg(gemm(At, b, nb=nb, precision=precision))
    x, _, _, info = qp(Q, c, ctrl=ctrl, nb=nb, precision=precision)
    return x, info


def lasso(A: DistMatrix, b: DistMatrix, lam: float,
          ctrl: MehrotraCtrl | None = None, nb: int | None = None,
          precision=None):
    """min 1/2 ||Ax - b||^2 + lam ||x||_1 (``El::Lasso``/BPDN): split
    x = u - v, QP over [u; v] >= 0 with Q = [[G, -G], [-G, G]]."""
    m, n = A.gshape
    At = _tp(A)
    G = gemm(At, A, nb=nb, precision=precision)
    Atb = gemm(At, b, nb=nb, precision=precision)
    Q = _blank(2 * n, 2 * n, A)
    Q = interior_update(Q, G, (0, 0))
    Q = interior_update(Q, _neg(G), (0, n))
    Q = interior_update(Q, _neg(G), (n, 0))
    Q = interior_update(Q, G, (n, n))
    lam1 = _ones(A, 2 * n)
    c = vstack(_neg(Atb), Atb)
    c = c.with_local(lam * lam1.local + c.local)
    x2, _, _, info = qp(Q, c, ctrl=ctrl, nb=nb, precision=precision)
    u = interior_view(x2, (0, n), (0, 1))
    v = interior_view(x2, (n, 2 * n), (0, 1))
    return u.with_local(u.local - v.local), info


def svm(X: DistMatrix, labels, C: float = 1.0,
        ctrl: MehrotraCtrl | None = None, nb: int | None = None,
        precision=None):
    """Soft-margin linear SVM (``El::SVM``) via the box-constrained dual

        min 1/2 a^T (Y X X^T Y) a - 1^T a,  0 <= a <= C,  y^T a = 0

    solved as a standard-form QP over [a; s] with a + s = C.  Returns
    (w, bias, info)."""
    m, n = X.gshape
    y = jnp.asarray(labels).reshape(-1)
    if y.shape[0] != m:
        raise ValueError(f"labels must have length {m}")
    Xt = _tp(X)
    K = gemm(X, Xt, nb=nb, precision=precision)          # m x m Gram
    from ..core.distmatrix import to_global, from_global
    # Y K Y scaling is a rank-structured elementwise op: do it via the
    # replicated label vector on storage index maps
    from ..blas.level1 import _global_indices
    I, J = _global_indices(K)
    yI = y[jnp.clip(I, 0, m - 1)][:, None]
    yJ = y[jnp.clip(J, 0, m - 1)][None, :]
    Kyy = K.with_local(K.local * yI * yJ)
    Q = _blank(2 * m, 2 * m, X)
    Q = interior_update(Q, Kyy, (0, 0))
    c = vstack(_neg(_ones(X, m)), _blank(m, 1, X))
    # equality constraints: y^T a = 0;  a + s = C
    yrow = from_global(np.asarray(y, np.float64).reshape(1, -1)
                       .astype(np.dtype(X.dtype)), MC, MR, grid=X.grid)
    Arow = hstack(yrow, _blank(1, m, X))
    I_m = _identity_like(X, m)
    Abox = hstack(I_m, I_m)
    Ae = vstack(Arow, Abox)
    be = vstack(_blank(1, 1, X), _ones(X, m).with_local(
        C * _ones(X, m).local))
    x2, _, _, info = qp(Q, c, Ae, be, ctrl=ctrl, nb=nb, precision=precision)
    a = interior_view(x2, (0, m), (0, 1))
    # w = X^T (a . y);  bias from margin support vectors (0 < a < C)
    ay = a.with_local(a.local * y[jnp.clip(_global_indices(a)[0], 0, m - 1)][:, None])
    w = gemm(Xt, ay, nb=nb, precision=precision)
    ag = np.asarray(to_global(a)).ravel()
    Xg = np.asarray(to_global(X))
    wg = np.asarray(to_global(w)).ravel()
    sv = (ag > 1e-6 * C) & (ag < (1 - 1e-6) * C)
    yn = np.asarray(y)
    bias = float(np.mean(yn[sv] - Xg[sv] @ wg)) if np.any(sv) else 0.0
    return w, bias, info


def rpca(M: DistMatrix, lam: float | None = None, tol: float = 1e-6,
         max_iters: int = 100, nb: int | None = None, precision=None):
    """Robust PCA min ||L||_* + lam ||S||_1 s.t. L + S = M (``El::RPCA``,
    ALM/ADMM with singular-value thresholding).  Returns (L, S, info)."""
    m, n = M.gshape
    lam = lam if lam is not None else 1.0 / math.sqrt(max(m, n))
    normM = float(frobenius_norm(M))
    # canonical IALM parameters (Lin-Chen-Ma): Y0 = M / J(M), mu0 = 1.25/||M||_2
    from ..lapack.spectral import svd as _svd
    s2 = float(_svd(M, vectors=False, nb=nb, precision=precision)[0])
    ninf = float(jnp.max(jnp.abs(M.local)))
    J = max(s2, ninf / lam, 1e-300)
    S = M.with_local(jnp.zeros_like(M.local))
    Y = M.with_local(M.local / J)
    mu = 1.25 / max(s2, 1e-300)
    mu_max = mu * 1e7
    info = {"iters": 0, "converged": False}
    for it in range(max_iters):
        L = svt(M.with_local(M.local - S.local + Y.local / mu), 1.0 / mu,
                nb=nb, precision=precision)
        S = soft_threshold(M.with_local(M.local - L.local + Y.local / mu),
                           lam / mu)
        R = M.with_local(M.local - L.local - S.local)
        Y = Y.with_local(Y.local + mu * R.local)
        mu = min(1.5 * mu, mu_max)          # inexact-ALM penalty growth
        err = float(frobenius_norm(R)) / max(normM, 1e-300)
        info.update(iters=it, err=err)
        if err < tol:
            info["converged"] = True
            break
    return L, S, info


# ---------------------------------------------------------------------
# round-5 model breadth (remaining src/optimization/models/** entries)
# ---------------------------------------------------------------------

def _from_np(M, grid, dtype=np.float64):
    M = np.atleast_2d(np.asarray(M, dtype))
    return from_global(M, MC, MR, grid=grid)


def _tg(A: DistMatrix):
    return to_global(A)


def cp(A: DistMatrix, b: DistMatrix, ctrl: MehrotraCtrl | None = None,
       nb: int | None = None, precision=None):
    """Chebyshev point: min ||Ax - b||_inf (``El::CP``): affine LP on
    (x, t) with -t <= (Ax - b)_i <= t.  Returns (x, info)."""
    from .affine import lp_affine
    m, n = A.gshape
    g = A.grid
    An = np.asarray(_tg(A))
    bn = np.asarray(_tg(b)).ravel()
    G = np.block([[An, -np.ones((m, 1))], [-An, -np.ones((m, 1))]])
    h = np.concatenate([bn, -bn])
    c = np.concatenate([np.zeros(n), [1.0]])
    x, y, z, s, info = lp_affine(None, _from_np(G, g), None,
                                 _from_np(c.reshape(-1, 1), g),
                                 _from_np(h.reshape(-1, 1), g),
                                 ctrl, nb, precision)
    return x[:n], info


def ds(A: DistMatrix, b: DistMatrix, lam: float,
       ctrl: MehrotraCtrl | None = None, nb: int | None = None,
       precision=None):
    """Dantzig selector: min ||x||_1 s.t. ||A^T(b - Ax)||_inf <= lam
    (``El::DS``): affine LP on split x = u - v >= 0.  Returns (x, info)."""
    from .affine import lp_affine
    m, n = A.gshape
    g = A.grid
    An = np.asarray(_tg(A))
    bn = np.asarray(_tg(b)).ravel()
    AtA = An.T @ An
    Atb = An.T @ bn
    # variables (u, v) >= 0; constraints -lam <= A'b - A'A(u - v) <= lam
    G = np.block([
        [-AtA, AtA],                      # A'A(u-v) >= A'b - lam
        [AtA, -AtA],                      # A'A(u-v) <= A'b + lam
        [-np.eye(n), np.zeros((n, n))],   # u >= 0
        [np.zeros((n, n)), -np.eye(n)],   # v >= 0
    ])
    h = np.concatenate([lam - Atb, lam + Atb, np.zeros(2 * n)])
    c = np.ones(2 * n)
    x, y, z, s, info = lp_affine(None, _from_np(G, g), None,
                                 _from_np(c.reshape(-1, 1), g),
                                 _from_np(h.reshape(-1, 1), g),
                                 ctrl, nb, precision)
    return x[:n] - x[n:], info


def en(A: DistMatrix, b: DistMatrix, lam1: float, lam2: float,
       ctrl: MehrotraCtrl | None = None, nb: int | None = None,
       precision=None):
    """Elastic net: min (1/2)||Ax-b||^2 + lam1 ||x||_1 + (lam2/2)||x||^2
    (``El::EN``): QP on the split x = u - v >= 0.  Returns (x, info)."""
    from .affine import qp_affine
    m, n = A.gshape
    g = A.grid
    An = np.asarray(_tg(A))
    bn = np.asarray(_tg(b)).ravel()
    AtA = An.T @ An
    Q = np.block([[AtA + lam2 * np.eye(n), -AtA],
                  [-AtA, AtA + lam2 * np.eye(n)]])
    c = lam1 * np.ones(2 * n) - np.concatenate([An.T @ bn, -(An.T @ bn)])
    G = -np.eye(2 * n)
    h = np.zeros(2 * n)
    x, y, z, s, info = qp_affine(_from_np(Q, g), None, _from_np(G, g),
                                 None, _from_np(c.reshape(-1, 1), g),
                                 _from_np(h.reshape(-1, 1), g),
                                 ctrl, nb, precision)
    return x[:n] - x[n:], info


def nmf(X: DistMatrix, rank: int, max_iters: int = 200, tol: float = 1e-5,
        seed: int = 0, nb: int | None = None, precision=None):
    """Nonnegative matrix factorization X ~= W H, W, H >= 0 (``El::NMF``).

    TPU-native redesign: upstream alternates NNLS solves; here the
    Lee-Seung multiplicative updates run instead -- the SAME monotone
    objective descent, but each step is two distributed matmuls per
    factor (MXU-shaped) rather than per-column QP solves.
    Returns (W, H, info)."""
    m, n = X.gshape
    g = X.grid
    rng = np.random.default_rng(seed)
    W = _from_np(np.abs(rng.normal(size=(m, rank))) + 0.1, g)
    H = _from_np(np.abs(rng.normal(size=(rank, n))) + 0.1, g)
    eps = 1e-12
    last = np.inf
    nrmX = max(float(frobenius_norm(X)), 1e-30)
    info = {"iters": 0}
    for it in range(max_iters):
        # H <- H * (W'X) / (W'W H)
        WtX = gemm(W, X, orient_a="T", nb=nb, precision=precision)
        WtWH = gemm(gemm(W, W, orient_a="T", nb=nb, precision=precision),
                    H, nb=nb, precision=precision)
        H = H.with_local(H.local * WtX.local / (WtWH.local + eps))
        # W <- W * (X H') / (W H H')
        XHt = gemm(X, H, orient_b="T", nb=nb, precision=precision)
        WHHt = gemm(W, gemm(H, H, orient_b="T", nb=nb, precision=precision),
                    nb=nb, precision=precision)
        W = W.with_local(W.local * XHt.local / (WHHt.local + eps))
        R = gemm(W, H, nb=nb, precision=precision)
        err = float(frobenius_norm(X.with_local(X.local - R.local))) \
            / nrmX
        info.update(iters=it, rel_err=err)
        if abs(last - err) < tol * max(err, 1e-30):
            break
        last = err
    return W, H, info


def sparse_inv_cov(S: DistMatrix, lam: float, rho: float = 1.0,
                   max_iters: int = 300, tol: float = 1e-6,
                   nb: int | None = None, precision=None):
    """Graphical lasso: min tr(S X) - logdet X + lam ||X||_1
    (``El::SparseInvCov``, ADMM): the X-update is one Hermitian
    eigensolve (matmul-rich on TPU), the Z-update a soft-threshold.
    Returns (Z, info) -- Z is the SPARSE consensus iterate (the
    soft-thresholded copy); it is symmetric but not guaranteed positive
    definite, so take logdet/Cholesky of the problem's X-side quantity,
    not of this return."""
    from ..lapack.spectral import herm_eig
    from ..core.dist import STAR
    from ..core.distmatrix import DistMatrix as _DM
    n = S.gshape[0]
    g = S.grid
    Z = S.with_local(jnp.zeros_like(S.local))
    U = S.with_local(jnp.zeros_like(S.local))
    info = {"iters": 0, "converged": False}
    X = Z
    for it in range(max_iters):
        # X-update: minimize tr(SX) - logdet X + rho/2 ||X - Z + U||^2
        # => eig-decompose rho (Z - U) - S and shift eigenvalues
        M = S.with_local(rho * (Z.local - U.local) - S.local)
        w, V = herm_eig(M, nb=nb, precision=precision)
        w = jnp.asarray(w)
        xi = (w + jnp.sqrt(w * w + 4.0 * rho)) / (2.0 * rho)
        d = _DM(xi.reshape(-1, 1).astype(S.dtype), (n, 1), STAR, STAR,
                0, 0, g)
        from ..blas.level1 import diagonal_scale
        X = gemm(diagonal_scale("R", d, V), V, orient_b="T", nb=nb,
                 precision=precision)
        Zold = Z
        Z = soft_threshold(X.with_local(X.local + U.local), lam / rho)
        U = U.with_local(U.local + X.local - Z.local)
        prim = float(frobenius_norm(X.with_local(X.local - Z.local)))
        dual = rho * float(frobenius_norm(
            Z.with_local(Z.local - Zold.local)))
        info.update(iters=it, prim=prim, dual=dual)
        if prim < tol * n and dual < tol * n:
            info["converged"] = True
            break
    return Z, info


def long_only_portfolio(Sigma: DistMatrix, mu_vec, gamma: float = 1.0,
                        ctrl: MehrotraCtrl | None = None,
                        nb: int | None = None, precision=None):
    """Long-only risk-adjusted portfolio (``El::LongOnlyPortfolio``):
    max mu'x - gamma * sqrt(x' Sigma x)  s.t.  1'x = 1, x >= 0,
    as the SOCP min -mu'x + gamma t with ||Sigma^{1/2} x|| <= t.

    NOTE on the objective: the risk term is the STANDARD DEVIATION
    (the SOCP-natural form per SURVEY.md §3.5's "(SOCP)" row); a
    variance-penalized gamma from a QP formulation does not transfer
    at the same value.  Returns (x, info)."""
    from .affine import socp_affine
    n = Sigma.gshape[0]
    g = Sigma.grid
    Sn = np.asarray(_tg(Sigma))
    mu_ = np.asarray(mu_vec).ravel()
    w, V = np.linalg.eigh((Sn + Sn.T) / 2)
    Shalf = V @ np.diag(np.sqrt(np.maximum(w, 0))) @ V.T
    # variables (x, t); cones: n order-1 (x >= 0) + one order-(n+1) SOC
    G = np.zeros((n + 1 + n, n + 1))
    h = np.zeros(n + 1 + n)
    for i in range(n):                       # s_i = x_i  (order-1 cones)
        G[i, i] = -1.0
    G[n, n] = -1.0                           # SOC head: s = t
    G[n + 1:, :n] = -Shalf                   # SOC barb: Sigma^{1/2} x
    A = np.concatenate([np.ones(n), [0.0]]).reshape(1, -1)
    b = np.array([1.0])
    c = np.concatenate([-mu_, [gamma]])
    orders = [1] * n + [n + 1]
    x, y, z, s, info = socp_affine(_from_np(A, g), _from_np(G, g),
                                   _from_np(b.reshape(-1, 1), g),
                                   _from_np(c.reshape(-1, 1), g),
                                   _from_np(h.reshape(-1, 1), g),
                                   orders, ctrl, nb, precision)
    return x[:n], info


def tv(b, lam: float, grid=None, ctrl: MehrotraCtrl | None = None,
       nb: int | None = None, precision=None):
    """1-D total-variation denoising: min (1/2)||x-b||^2 + lam ||Dx||_1
    (``El::TV``): QP on (x, t) with -t <= Dx <= t.  Returns (x, info)."""
    from .affine import qp_affine
    from ..core.grid import default_grid
    g = grid or default_grid()
    bn = np.asarray(b).ravel()
    n = bn.shape[0]
    D = (np.eye(n - 1, n, 1) - np.eye(n - 1, n))
    N = n + (n - 1)
    Q = np.zeros((N, N))
    Q[:n, :n] = np.eye(n)
    c = np.concatenate([-bn, lam * np.ones(n - 1)])
    G = np.block([[D, -np.eye(n - 1)], [-D, -np.eye(n - 1)]])
    h = np.zeros(2 * (n - 1))
    x, y, z, s, info = qp_affine(_from_np(Q, g), None, _from_np(G, g),
                                 None, _from_np(c.reshape(-1, 1), g),
                                 _from_np(h.reshape(-1, 1), g),
                                 ctrl, nb, precision)
    return x[:n], info
