"""Proximal operators.

Reference: Elemental ``src/optimization/prox/**`` -- ``SoftThreshold.cpp``
(``El::SoftThreshold``), ``SVT.cpp`` (``El::SVT``, singular-value
thresholding; ``svt::Normal`` dense variant), ``Clip.cpp``,
``FrobeniusProx.cpp``, ``HingeLossProx.cpp``, ``LogisticProx.cpp``.

All elementwise operators run directly on [MC,MR] storage (each entry once,
padding zero preserved since every operator maps 0 -> 0 or is masked); SVT
rides the distributed SVD.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dist import STAR
from ..core.distmatrix import DistMatrix
from ..blas.level1 import _valid_mask, diagonal_scale
from ..blas.level3 import gemm


def soft_threshold(A: DistMatrix, tau) -> DistMatrix:
    """prox_{tau ||.||_1}: sign(a) max(|a| - tau, 0) (``El::SoftThreshold``)."""
    a = A.local
    mag = jnp.maximum(jnp.abs(a) - tau, 0)
    phase = jnp.where(jnp.abs(a) == 0, 0, a / jnp.where(a == 0, 1, jnp.abs(a)))
    return A.with_local(phase * mag)


def clip(A: DistMatrix, lo, hi) -> DistMatrix:
    """Entrywise clamp to [lo, hi] on the valid region (``El::Clip``)."""
    out = jnp.clip(A.local, lo, hi)
    return A.with_local(jnp.where(_valid_mask(A), out, 0))


def frobenius_prox(A: DistMatrix, rho) -> DistMatrix:
    """prox_{rho ||.||_F}: scale toward zero (``El::FrobeniusProx``)."""
    nrm = jnp.linalg.norm(A.local)
    scale = jnp.maximum(1 - rho / jnp.maximum(nrm, 1e-300), 0)
    return A.with_local(scale * A.local)


def hinge_loss_prox(A: DistMatrix, rho) -> DistMatrix:
    """prox of the hinge loss sum max(1 - a, 0) (``El::HingeLossProx``)."""
    a = A.local
    out = jnp.where(a < 1 - 1 / rho, a + 1 / rho, jnp.where(a > 1, a, 1.0))
    return A.with_local(jnp.where(_valid_mask(A), out, 0))


def logistic_prox(A: DistMatrix, rho, newton_iters: int = 8) -> DistMatrix:
    """prox of sum log(1 + e^{-a}) via elementwise Newton
    (``El::LogisticProx``)."""
    a = A.local
    x = jnp.maximum(a, 0.0)
    for _ in range(newton_iters):
        sig = 1.0 / (1.0 + jnp.exp(-x))
        f = rho * (x - a) + sig - 1.0          # d/dx [rho/2 (x-a)^2 + log1pexp(-x)]
        fp = rho + sig * (1 - sig)
        x = x - f / fp
    return A.with_local(jnp.where(_valid_mask(A), x, 0))


def svt(A: DistMatrix, tau, nb: int | None = None, precision=None,
        eig_approach: str = "tridiag") -> DistMatrix:
    """Singular-value thresholding prox_{tau ||.||_*} (``El::SVT``,
    ``svt::Normal``): U max(s - tau, 0) V^H via the distributed SVD."""
    from ..lapack.spectral import svd
    U, s, V = svd(A, nb=nb, precision=precision, eig_approach=eig_approach)
    st = jnp.maximum(s - tau, 0).astype(A.dtype)
    d = DistMatrix(st[:, None], (st.shape[0], 1), STAR, STAR, 0, 0, A.grid)
    Us = diagonal_scale("R", d, U)
    return gemm(Us, V, orient_b="C", nb=nb, precision=precision)
