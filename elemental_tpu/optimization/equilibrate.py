"""Ruiz + geometric matrix equilibration for the IPMs.

Reference: Elemental ``src/optimization/util/`` equilibration helpers
(``El::RuizEquil``, ``El::GeomEquil``, ``El::SymmetricRuizEquil``), the
mandatory first step of every upstream IPM solve (SURVEY.md §4.6): badly
scaled (A, b, c) -- rows/columns spanning orders of magnitude, the NORMAL
case in practice -- stall Mehrotra or lose digits in the normal-equations
Cholesky, so A is rescaled to D_r A D_c with near-unit row/column norms
first and the solution mapped back afterwards.

Scale vectors are replicated (they are O(m + n) against the O(mn)
distributed operand, the same subordinate role as the SOC member vectors);
the row/column max reductions run on the storage array (each global entry
exactly once, padding zeros ignored by the max since |entries| >= 0).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dist import STAR
from ..core.distmatrix import DistMatrix
from ..blas.level1 import _global_indices, diagonal_scale


def _wrap(v, grid):
    """Replicated (k,) vector -> (k,1) [STAR,STAR] for diagonal_scale."""
    return DistMatrix(v.reshape(-1, 1), (v.shape[0], 1), STAR, STAR, 0, 0,
                      grid)


def row_col_maxabs(A: DistMatrix):
    """Per-row and per-column max |A_ij| as replicated (m,), (n,) vectors."""
    m, n = A.gshape
    I, J = _global_indices(A)
    a = jnp.abs(A.local)
    valid = (I[:, None] < m) & (J[None, :] < n)
    a = jnp.where(valid, a, 0)
    rloc = jnp.max(a, axis=1)                       # per storage row
    cloc = jnp.max(a, axis=0)
    rowm = jnp.zeros((m,), a.dtype).at[jnp.clip(I, 0, m - 1)].max(rloc)
    colm = jnp.zeros((n,), a.dtype).at[jnp.clip(J, 0, n - 1)].max(cloc)
    return rowm, colm


def row_col_minabs(A: DistMatrix):
    """Per-row/column min of the NONZERO |A_ij| (zeros treated as absent;
    all-zero rows/cols report inf)."""
    m, n = A.gshape
    I, J = _global_indices(A)
    a = jnp.abs(A.local)
    valid = (I[:, None] < m) & (J[None, :] < n) & (a > 0)
    a = jnp.where(valid, a, jnp.inf)
    rloc = jnp.min(a, axis=1)
    cloc = jnp.min(a, axis=0)
    rowm = jnp.full((m,), jnp.inf, a.dtype).at[jnp.clip(I, 0, m - 1)].min(rloc)
    colm = jnp.full((n,), jnp.inf, a.dtype).at[jnp.clip(J, 0, n - 1)].min(cloc)
    return rowm, colm


def ruiz_equil(A: DistMatrix, iters: int = 6):
    """Ruiz iteration (``El::RuizEquil``): repeatedly scale rows and columns
    by 1/sqrt(max-abs), converging to unit row/column inf-norms.

    Returns (A_scaled = D_r A D_c, d_r, d_c) with the scale vectors
    replicated; recover original-variable quantities via x = D_c x~,
    y = D_r y~ (LP convention: A~x~=b~ with b~ = D_r b, c~ = D_c c)."""
    m, n = A.gshape
    dt = jnp.real(jnp.zeros((), A.dtype)).dtype
    d_r = jnp.ones((m,), dt)
    d_c = jnp.ones((n,), dt)
    As = A
    for _ in range(iters):
        rowm, colm = row_col_maxabs(As)
        sr = 1.0 / jnp.sqrt(jnp.maximum(rowm, 1e-30))
        sc = 1.0 / jnp.sqrt(jnp.maximum(colm, 1e-30))
        # all-zero rows/cols keep scale 1 (nothing to normalize)
        sr = jnp.where(rowm > 0, sr, 1.0)
        sc = jnp.where(colm > 0, sc, 1.0)
        As = diagonal_scale("L", _wrap(sr, A.grid), As)
        As = diagonal_scale("R", _wrap(sc, A.grid), As)
        d_r = d_r * sr
        d_c = d_c * sc
    return As, d_r, d_c


def geom_equil(A: DistMatrix, iters: int = 3):
    """Geometric-mean equilibration (``El::GeomEquil``): scale by
    1/sqrt(max * min_nonzero) per row/column -- centers the magnitude
    RANGE rather than the top, the upstream alternative for matrices with
    wide but structured dynamic range."""
    m, n = A.gshape
    dt = jnp.real(jnp.zeros((), A.dtype)).dtype
    d_r = jnp.ones((m,), dt)
    d_c = jnp.ones((n,), dt)
    As = A
    for _ in range(iters):
        rmax, cmax = row_col_maxabs(As)
        rmin, cmin = row_col_minabs(As)
        sr = jnp.where((rmax > 0) & jnp.isfinite(rmin),
                       1.0 / jnp.sqrt(jnp.maximum(rmax * rmin, 1e-30)), 1.0)
        sc = jnp.where((cmax > 0) & jnp.isfinite(cmin),
                       1.0 / jnp.sqrt(jnp.maximum(cmax * cmin, 1e-30)), 1.0)
        As = diagonal_scale("L", _wrap(sr, A.grid), As)
        As = diagonal_scale("R", _wrap(sc, A.grid), As)
        d_r = d_r * sr
        d_c = d_c * sc
    return As, d_r, d_c


def symmetric_ruiz_equil(Q: DistMatrix, iters: int = 6):
    """Symmetric variant (``El::SymmetricRuizEquil``): one scale vector d
    with Q~ = D Q D (preserves symmetry/definiteness)."""
    n = Q.gshape[0]
    dt = jnp.real(jnp.zeros((), Q.dtype)).dtype
    d = jnp.ones((n,), dt)
    Qs = Q
    for _ in range(iters):
        rowm, _ = row_col_maxabs(Qs)
        s = jnp.where(rowm > 0,
                      1.0 / jnp.sqrt(jnp.maximum(rowm, 1e-30)), 1.0)
        Qs = diagonal_scale("L", _wrap(s, Q.grid), Qs)
        Qs = diagonal_scale("R", _wrap(s, Q.grid), Qs)
        d = d * s
    return Qs, d
