"""Optimization tier: Mehrotra IPMs (LP/QP), positive-orthant utilities,
proximal operators, and models (SURVEY.md §3.5).

Reference: Elemental ``src/optimization/{solvers,util,prox,models}/**``.
"""
from .util import MehrotraCtrl, max_step, num_outside, safe_div
from .lp import lp
from .qp import qp
from .prox import (soft_threshold, svt, clip, frobenius_prox,
                   hinge_loss_prox, logistic_prox)
from .models import bp, lav, nnls, lasso, svm, rpca
