"""Optimization tier: Mehrotra IPMs (LP/QP), positive-orthant utilities,
proximal operators, and models (SURVEY.md §3.5).

Reference: Elemental ``src/optimization/{solvers,util,prox,models}/**``.
"""
from .util import MehrotraCtrl, max_step, num_outside, safe_div
from .lp import lp
from .qp import qp
from .soc import (socp, make_cone_layout, soc_dets, soc_apply, soc_inverse,
                  soc_sqrt, soc_identity, soc_max_step, soc_nesterov_todd)
from .prox import (soft_threshold, svt, clip, frobenius_prox,
                   hinge_loss_prox, logistic_prox)
from .models import (bp, lav, nnls, lasso, svm, rpca, cp, ds,
                     en, nmf, sparse_inv_cov,
                     long_only_portfolio, tv)
from .equilibrate import (ruiz_equil, geom_equil, symmetric_ruiz_equil,
                          row_col_maxabs)
from .affine import lp_affine, qp_affine, socp_affine, ruiz_equil_stacked
from .sparse_ipm import (lp_sparse, lav_sparse, bp_sparse,
                         sparse_ruiz_equil, sparse_to_coo)
