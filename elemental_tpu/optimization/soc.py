"""Second-order-cone utilities + the SOCP Mehrotra IPM.

Reference: Elemental ``src/optimization/util/SOC/**`` (``El::soc``:
``Dets``, ``Apply``, ``Inverse``, ``Sqrt``, ``NesterovTodd``, ``MaxStep``,
``Identity``) and ``src/optimization/solvers/SOCP/direct/IPM/Mehrotra.hpp``
(``El::socp::direct::Mehrotra``).

Cone layout (the reference's convention): a member vector stacks K cones;
``orders[i]`` is the length of the cone containing entry i and
``first_inds[i]`` the index of its head, so segment reductions express all
Jordan-algebra ops.  Vectors here are HOST/replicated numpy-backed
(they are O(n) against the O(n^2) distributed matrices of the KKT solves;
the reference's DistMultiVec plays the same subordinate role).

The SOCP solver runs the standard form

    min c^T x  s.t.  A x = b,  x in Q (product of second-order cones)

with Nesterov-Todd scaling and the same host-loop/device-KKT split as
:mod:`.lp`: one dense LDL of the augmented KKT per iteration.
"""
from __future__ import annotations

import numpy as np

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global, to_global
from ..redist.interior import interior_update, _blank
from ..blas.level3 import _check_mcmr
from ..lapack.ldl import ldl, ldl_solve_after
from .util import MehrotraCtrl
from .lp import _tp


# ---------------------------------------------------------------------
# Jordan-algebra segment ops (host numpy on replicated member vectors)
# ---------------------------------------------------------------------

def make_cone_layout(orders_list):
    """(orders, first_inds) index vectors from a list of cone sizes."""
    orders, firsts = [], []
    at = 0
    for k in orders_list:
        orders += [k] * k
        firsts += [at] * k
        at += k
    return np.asarray(orders), np.asarray(firsts)


def soc_dets(x, first_inds):
    """det(x) per cone, broadcast to members: x0^2 - ||x1||^2."""
    heads = first_inds == np.arange(x.shape[0])
    tail2 = np.where(heads, 0.0, np.abs(x) ** 2)
    sums = np.bincount(first_inds, weights=np.real(tail2),
                       minlength=x.shape[0])
    head_val = np.real(x[first_inds])
    return head_val ** 2 - sums[first_inds]


def soc_identity(first_inds, n):
    """The cone identity e = (1, 0, ...) per cone."""
    e = np.zeros(n)
    e[np.unique(first_inds)] = 1.0
    return e


def soc_apply(x, y, first_inds):
    """Jordan product (x o y): head = <x, y> per cone; tail =
    x0 y1 + y0 x1."""
    n = x.shape[0]
    heads = first_inds == np.arange(n)
    dots = np.bincount(first_inds, weights=np.real(x * y), minlength=n)
    x0 = x[first_inds]
    y0 = y[first_inds]
    out = x0 * y + y0 * x
    return np.where(heads, dots, out)


def soc_inverse(x, first_inds):
    """Jordan inverse: (x0, -x1)/det(x)."""
    d = soc_dets(x, first_inds)
    heads = first_inds == np.arange(x.shape[0])
    refl = np.where(heads, x, -x)
    return refl / d


def soc_sqrt(x, first_inds):
    """Jordan square root (for interior x)."""
    d = soc_dets(x, first_inds)
    x0 = x[first_inds]
    s = np.sqrt(np.maximum(0.5 * (x0 + np.sqrt(np.maximum(d, 0))), 1e-300))
    heads = first_inds == np.arange(x.shape[0])
    out = np.where(heads, s, x / (2.0 * s))
    return out


def soc_max_step(x, dx, first_inds, cap=1.0):
    """sup {a <= cap : x + a dx in cone} for interior x
    (``soc::MaxStep``): fully segment-vectorized per-cone boundary roots
    of det(x + a dx) = 0 plus the head-positivity crossing."""
    n = x.shape[0]
    heads = first_inds == np.arange(n)
    x0 = x[first_inds]
    dx0 = dx[first_inds]
    xt = np.where(heads, 0.0, x)
    dxt = np.where(heads, 0.0, dx)

    def seg(v):
        return np.bincount(first_inds, weights=v, minlength=n)[first_inds]

    a2 = dx0 ** 2 - seg(dxt * dxt)
    a1 = 2.0 * (x0 * dx0 - seg(xt * dxt))
    a0 = x0 ** 2 - seg(xt * xt)
    big = np.inf
    with np.errstate(all="ignore"):
        disc = a1 * a1 - 4.0 * a2 * a0
        sq = np.sqrt(np.maximum(disc, 0.0))
        r1 = (-a1 - sq) / (2.0 * a2)
        r2 = (-a1 + sq) / (2.0 * a2)
        rlin = -a0 / np.where(a1 == 0, 1.0, a1)
        rhead = -x0 / np.where(dx0 == 0, 1.0, dx0)
    quad = np.abs(a2) > 1e-300
    okq = quad & (disc >= 0)
    cand = np.where(okq & (r1 > 1e-14), r1, big)
    cand = np.minimum(cand, np.where(okq & (r2 > 1e-14), r2, big))
    cand = np.minimum(cand, np.where(~quad & (np.abs(a1) > 1e-300)
                                     & (rlin > 1e-14), rlin, big))
    cand = np.minimum(cand, np.where(dx0 < 0, rhead, big))
    alpha = float(cand[heads].min()) if heads.any() else cap
    return max(min(alpha, cap), 0.0)


def soc_nesterov_todd(x, z, first_inds):
    """The NT scaling point w with Q_w z = x (per cone, closed form)."""
    dx = np.sqrt(np.maximum(soc_dets(x, first_inds), 1e-300))
    dz = np.sqrt(np.maximum(soc_dets(z, first_inds), 1e-300))
    heads = first_inds == np.arange(x.shape[0])
    xb = x / dx
    zb = z / dz
    zb_refl = np.where(heads, zb, -zb)
    # det(xb + J zb) = 2 + 2 xb.zb (PLAIN dot), so this gamma normalizes wb
    gamma_n = np.bincount(first_inds, weights=xb * zb,
                          minlength=x.shape[0])[first_inds]
    gamma = np.sqrt(np.maximum((1.0 + gamma_n) / 2.0, 1e-300))
    wb = (xb + zb_refl) / (2.0 * gamma)
    return wb * np.sqrt(np.maximum(dx / dz, 1e-300))


def _arrow_matrix(w, orders, first_inds):
    """Dense quadratic-representation blocks Q_w (per cone, block diag).

    Q_w = 2 w w^T - det(w) R with R = diag(1, -1, ..., -1); assembled as a
    dense (n, n) block-diagonal host matrix (the KKT scaling block)."""
    n = w.shape[0]
    Q = np.zeros((n, n))
    for h in np.unique(first_inds):
        sel = np.where(first_inds == h)[0]
        wc = w[sel]
        k = len(sel)
        R = np.diag([1.0] + [-1.0] * (k - 1))
        det = wc[0] ** 2 - wc[1:] @ wc[1:]
        Q[np.ix_(sel, sel)] = 2.0 * np.outer(wc, wc) - det * R
    return Q


# ---------------------------------------------------------------------
# SOCP Mehrotra IPM
# ---------------------------------------------------------------------

def socp(A: DistMatrix, b: DistMatrix, c: DistMatrix, orders_list,
         ctrl: MehrotraCtrl | None = None, nb: int | None = None,
         precision=None):
    """Solve min c^T x s.t. A x = b, x in a product of second-order cones
    (``El::SOCP`` direct form).  ``orders_list`` gives the cone sizes
    (summing to n).  Returns (x, y, z, info)."""
    _check_mcmr(A, b, c)
    ctrl = ctrl or MehrotraCtrl()
    m, n = A.gshape
    orders, first_inds = make_cone_layout(orders_list)
    if orders.shape[0] != n:
        raise ValueError(f"cone sizes sum to {orders.shape[0]}, need {n}")
    g = A.grid

    if ctrl.equilibrate:
        # cone-aware Ruiz: the column scale is pooled UNIFORM within each
        # cone (x = Dc x~ then preserves membership); rows of A scale
        # freely.  y = Dr y~, z = Dc^{-1} z~.
        from .equilibrate import row_col_maxabs, _wrap
        from ..blas.level1 import diagonal_scale, diagonal_solve
        import dataclasses as _dc
        import jax.numpy as _jnp
        As = A
        d_r = np.ones(m)
        d_c = np.ones(n)
        starts = np.unique(first_inds)
        for _ in range(4):
            rmax, _cm = row_col_maxabs(As)
            sr = np.asarray(_jnp.where(
                rmax > 0, 1.0 / _jnp.sqrt(_jnp.maximum(rmax, 1e-30)), 1.0))
            As = diagonal_scale("L", _wrap(_jnp.asarray(sr, A.dtype), g), As)
            _rm, cmax = row_col_maxabs(As)
            cmax = np.asarray(cmax)
            pooled = np.maximum.reduceat(cmax, starts)[
                np.searchsorted(starts, first_inds)]
            sc = np.where(pooled > 0,
                          1.0 / np.sqrt(np.maximum(pooled, 1e-30)), 1.0)
            As = diagonal_scale("R", _wrap(_jnp.asarray(sc, A.dtype), g), As)
            d_r *= sr
            d_c *= sc
        wr = _wrap(_jnp.asarray(d_r, b.dtype), g)
        wc = _wrap(_jnp.asarray(d_c, c.dtype), g)
        bs = diagonal_scale("L", wr, b)
        cs = diagonal_scale("L", wc, c)
        xs, ys, zs, info = socp(As, bs, cs, orders_list,
                                _dc.replace(ctrl, equilibrate=False), nb,
                                precision)
        return (diagonal_scale("L", wc, xs), diagonal_scale("L", wr, ys),
                diagonal_solve("L", wc, zs), info)
    At = _tp(A)
    e = soc_identity(first_inds, n)
    K = len(orders_list)

    xv = e.copy()
    zv = e.copy()
    yv = np.zeros(m)
    An = np.asarray(to_global(A))
    bn = np.asarray(to_global(b)).ravel()
    cn = np.asarray(to_global(c)).ravel()
    nb_ = max(np.linalg.norm(bn), 1.0)
    nc_ = max(np.linalg.norm(cn), 1.0)
    info = {"iters": 0, "converged": False}

    def dmat(M):
        return from_global(M.astype(An.dtype), MC, MR, grid=g)

    best = (np.inf, xv, yv, zv)
    for it in range(ctrl.max_iters):
        rb = An @ xv - bn
        rc = cn - An.T @ yv - zv
        mu = float(xv @ zv) / K
        gap = float(xv @ zv)
        pobj = float(cn @ xv)
        rel_gap = gap / (1.0 + abs(pobj))
        pfeas = np.linalg.norm(rb) / nb_
        dfeas = np.linalg.norm(rc) / nc_
        info.update(iters=it, rel_gap=rel_gap, pfeas=pfeas, dfeas=dfeas,
                    mu=mu, pobj=pobj)
        if ctrl.print_progress:
            print(f"  socp it {it}: gap={rel_gap:.2e} pfeas={pfeas:.2e} "
                  f"dfeas={dfeas:.2e}")
        if rel_gap < ctrl.tol and pfeas < ctrl.tol and dfeas < ctrl.tol:
            info["converged"] = True
            break
        score = max(abs(rel_gap), pfeas, dfeas)
        if not np.isfinite(mu) or rel_gap < 0:
            # boundary breakdown: return the best iterate seen, with info
            # recomputed to describe THAT iterate (not the broken one)
            _, xv, yv, zv = best
            info["stalled"] = True
            gap = float(xv @ zv)
            pobj = float(cn @ xv)
            info.update(mu=gap / K, pobj=pobj,
                        rel_gap=gap / (1.0 + abs(pobj)),
                        pfeas=np.linalg.norm(An @ xv - bn) / nb_,
                        dfeas=np.linalg.norm(cn - An.T @ yv - zv) / nc_)
            break
        if score < best[0]:
            best = (score, xv.copy(), yv.copy(), zv.copy())

        # NT scaling: H = Q_w maps z to x; the Newton system linearizes
        # complementarity as dx + H dz = rcomb, giving the augmented KKT
        #   [ -H^{-1}  A^T ] [dx]   [ rc - H^{-1} rcomb ]
        #   [    A      0  ] [dy] = [       -rb         ]
        # with dz = H^{-1}(rcomb - dx); H^{-1} = Q_{w^-1} in closed form.
        w = soc_nesterov_todd(xv, zv, first_inds)
        winv = soc_inverse(w, first_inds)
        Hinv = _arrow_matrix(winv, orders, first_inds)   # Q_{w^{-1}} = H^{-1}
        Kd = _blank(n + m, n + m, A)
        Kd = interior_update(Kd, dmat(-Hinv), (0, 0))
        Kd = interior_update(Kd, At, (0, n))
        Kd = interior_update(Kd, A, (n, 0))
        Lp, dk, ek, perm = ldl(Kd, conjugate=False, nb=nb,
                               precision=precision)

        def direction(rcomb):
            rhs = np.concatenate([rc - Hinv @ rcomb, -rb])
            sol = ldl_solve_after(Lp, dk, ek, perm,
                                  dmat(rhs.reshape(-1, 1)),
                                  conjugate=False, nb=nb,
                                  precision=precision)
            sflat = np.asarray(to_global(sol)).ravel()
            dx_, dy_ = sflat[:n], sflat[n:]
            dz_ = Hinv @ (rcomb - dx_)
            return dx_, dy_, dz_

        # predictor (affine): drive x o z toward 0 -> rcomb = -x
        dx_a, dy_a, dz_a = direction(-xv)
        ap = soc_max_step(xv, dx_a, first_inds, cap=1.0)
        ad = soc_max_step(zv, dz_a, first_inds, cap=1.0)
        mu_aff = float((xv + ap * dx_a) @ (zv + ad * dz_a)) / K
        sigma = min(max(mu_aff / mu, 0.0) ** 3, 1.0) if mu > 0 else 0.1
        # corrector: rcomb = -x + sigma mu z^{-1} (Jordan inverse)
        rcomb = -xv + sigma * mu * soc_inverse(zv, first_inds)
        dx_c, dy_c, dz_c = direction(rcomb)
        ap = min(ctrl.eta * soc_max_step(xv, dx_c, first_inds,
                                         cap=1.0 / ctrl.eta), 1.0)
        ad = min(ctrl.eta * soc_max_step(zv, dz_c, first_inds,
                                         cap=1.0 / ctrl.eta), 1.0)
        a = min(ap, ad)
        xv = xv + a * dx_c
        yv = yv + a * dy_c
        zv = zv + a * dz_c
    x = dmat(xv.reshape(-1, 1))
    y = dmat(yv.reshape(-1, 1))
    z = dmat(zv.reshape(-1, 1))
    return x, y, z, info
