"""Dense Mehrotra predictor-corrector QP interior-point solver.

Reference: Elemental ``src/optimization/solvers/QP/direct/IPM/Mehrotra.hpp``
(``El::qp::direct::Mehrotra``, ``AUGMENTED_KKT``):

    min 1/2 x^T Q x + c^T x  s.t.  A x = b,  x >= 0

Each iteration solves the symmetric-indefinite augmented KKT system

    [ -(Q + X^{-1} Z)   A^T ] [ dx ]   [ rd - X^{-1} r_mu ]
    [       A            0  ] [ dy ] = [      -rp         ]

with the Bunch-Kaufman LDL from :mod:`..lapack.ldl` (the reference's dense
``LDL`` path), one factorization per iteration reused by predictor and
corrector.  With no equality constraints (``A is None``) the system
collapses to the HPD ``(Q + X^{-1} Z) dx = rhs`` and Cholesky is used --
this is the NNLS/Lasso/SVM-dual engine.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.distmatrix import DistMatrix
from ..redist.interior import interior_view, interior_update, vstack, _blank
from ..blas.level1 import _valid_mask, update_diagonal
from ..blas.level3 import _check_mcmr, gemm
from ..lapack.cholesky import cholesky, cholesky_solve_after
from ..lapack.ldl import ldl, ldl_solve_after
from .util import MehrotraCtrl, max_step, safe_div
from .lp import _tp, _dot, _norm, _wrap_diag


def qp(Q: DistMatrix, c: DistMatrix, A: DistMatrix | None = None,
       b: DistMatrix | None = None, ctrl: MehrotraCtrl | None = None,
       nb: int | None = None, precision=None):
    """Solve the standard-form convex QP; returns (x, y, z, info)."""
    _check_mcmr(Q, c)
    if (A is None) != (b is None):
        raise ValueError("A and b must be supplied together")
    ctrl = ctrl or MehrotraCtrl()
    n = Q.gshape[0]
    m = A.gshape[0] if A is not None else 0
    g = Q.grid

    if ctrl.equilibrate:
        # symmetric Ruiz on Q fixes the column scale Dc (Q~ = Dc Q Dc,
        # preserving symmetry/PSD); A gets the shared Dc plus its own row
        # scale.  x = Dc x~, y = Dr y~, z = Dc^{-1} z~.
        from .equilibrate import symmetric_ruiz_equil, row_col_maxabs, _wrap
        from ..blas.level1 import diagonal_scale, diagonal_solve
        import dataclasses as _dc
        import jax.numpy as _jnp
        Qs, d_c = symmetric_ruiz_equil(Q)
        wc = _wrap(d_c.astype(c.dtype), g)
        cs = diagonal_scale("L", wc, c)
        ctrl2 = _dc.replace(ctrl, equilibrate=False)
        if A is None:
            xs, ys, zs, info = qp(Qs, cs, None, None, ctrl2, nb, precision)
            return (diagonal_scale("L", wc, xs), ys,
                    diagonal_solve("L", wc, zs), info)
        As = diagonal_scale("R", wc, A)
        rmax, _ = row_col_maxabs(As)
        d_r = _jnp.where(rmax > 0,
                         1.0 / _jnp.sqrt(_jnp.maximum(rmax, 1e-30)), 1.0)
        wr = _wrap(d_r.astype(b.dtype), g)
        As = diagonal_scale("L", wr, As)
        bs = diagonal_scale("L", wr, b)
        xs, ys, zs, info = qp(Qs, cs, As, bs, ctrl2, nb, precision)
        return (diagonal_scale("L", wc, xs), diagonal_scale("L", wr, ys),
                diagonal_solve("L", wc, zs), info)

    At = _tp(A) if A is not None else None
    vm_x = _valid_mask(c)

    # simple interior start
    x = c.with_local(jnp.where(vm_x, jnp.ones_like(c.local), 0))
    z = c.with_local(jnp.where(vm_x, jnp.ones_like(c.local), 0))
    y = b.with_local(jnp.zeros_like(b.local)) if b is not None else None

    nb_ = max(_norm(b), 1.0) if b is not None else 1.0
    nc_ = max(_norm(c), 1.0)
    info = {"iters": 0, "converged": False, "rel_gap": np.inf}

    prev = (x, y, z)
    for it in range(ctrl.max_iters):
        Qx = gemm(Q, x, nb=nb, precision=precision)
        rd = c.with_local(Qx.local + c.local - z.local
                          - (gemm(At, y, nb=nb, precision=precision).local
                             if A is not None else 0))
        rp = (b.with_local(gemm(A, x, nb=nb, precision=precision).local
                           - b.local) if A is not None else None)
        mu = _dot(x, z) / n
        if not np.isfinite(mu):
            x, y, z = prev
            info["stalled"] = True
            break
        prev = (x, y, z)
        pobj = 0.5 * _dot(x, Qx) + _dot(c, x)
        gap_abs = _dot(x, z)
        rel_gap = gap_abs / (1.0 + abs(pobj))
        pfeas = (_norm(rp) / nb_) if rp is not None else 0.0
        dfeas = _norm(rd) / nc_
        info.update(iters=it, rel_gap=rel_gap, pfeas=pfeas, dfeas=dfeas,
                    mu=mu, pobj=pobj)
        if ctrl.print_progress:
            print(f"  qp it {it}: gap={rel_gap:.2e} pfeas={pfeas:.2e} "
                  f"dfeas={dfeas:.2e}")
        if rel_gap < ctrl.tol and pfeas < ctrl.tol and dfeas < ctrl.tol:
            info["converged"] = True
            break

        dinv2 = x.with_local(safe_div(z.local, x.local))    # X^{-1} Z
        H = update_diagonal(Q, _wrap_diag(dinv2))           # Q + X^{-1}Z
        # static regularization (dense reg_ldl analog; see lp.normal_solve)
        from ..blas.level1 import shift_diagonal
        H = shift_diagonal(H, 1e-12 * (1.0 + float(jnp.max(jnp.abs(H.local)))))

        if A is None:
            Lfac = cholesky(H, "L", nb=nb, precision=precision)

            def solve_dir(r_mu, _):
                xinv_rmu = safe_div(r_mu, x.local)
                rhs = c.with_local(-rd.local + xinv_rmu)
                dxv = cholesky_solve_after(Lfac, rhs, nb=nb,
                                           precision=precision)
                dzv = x.with_local(safe_div(r_mu - z.local * dxv.local,
                                            x.local))
                return dxv, None, dzv, Lfac
            fac = None
        else:
            K = _blank(n + m, n + m, Q)
            K = interior_update(K, H.with_local(-H.local), (0, 0))
            K = interior_update(K, At, (0, n))
            K = interior_update(K, A, (n, 0))
            Lp, dK, eK, permK = ldl(K, conjugate=False, nb=nb,
                                    precision=precision)
            fac = (Lp, dK, eK, permK)

            def solve_dir(r_mu, fac):
                Lp, dK, eK, permK = fac
                xinv_rmu = safe_div(r_mu, x.local)
                r1 = c.with_local(rd.local - xinv_rmu)
                r2 = rp.with_local(-rp.local)
                rhs = vstack(r1, r2)
                sol = ldl_solve_after(Lp, dK, eK, permK, rhs,
                                      conjugate=False, nb=nb,
                                      precision=precision)
                dxv = interior_view(sol, (0, n), (0, 1))
                dyv = interior_view(sol, (n, n + m), (0, 1))
                dzv = x.with_local(safe_div(r_mu - z.local * dxv.local,
                                            x.local))
                return dxv, dyv, dzv, fac

        r_aff = -(x.local * z.local)
        dx_a, dy_a, dz_a, fac = solve_dir(r_aff, fac)
        ap = float(max_step(x, dx_a))
        ad = float(max_step(z, dz_a))
        mu_aff = float(jnp.sum((x.local + ap * dx_a.local)
                               * (z.local + ad * dz_a.local))) / n
        sigma = min((mu_aff / mu) ** 3, 1.0) if mu > 0 else 0.1
        r_cor = sigma * mu * vm_x - x.local * z.local \
            - dx_a.local * dz_a.local
        dx_c, dy_c, dz_c, _ = solve_dir(r_cor, fac)
        ap = min(ctrl.eta * float(max_step(x, dx_c, cap=1.0 / ctrl.eta)), 1.0)
        ad = min(ctrl.eta * float(max_step(z, dz_c, cap=1.0 / ctrl.eta)), 1.0)
        a = min(ap, ad)      # QP couples x and (y,z) through Q: common step
        x = x.with_local(x.local + a * dx_c.local)
        if y is not None:
            y = y.with_local(y.local + a * dy_c.local)
        z = z.with_local(z.local + a * dz_c.local)
    return x, y, z, info
