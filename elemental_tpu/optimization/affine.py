"""Affine-form (general conic) Mehrotra IPMs for LP / QP / SOCP.

Reference: Elemental ``src/optimization/solvers/{LP,QP,SOCP}/affine/IPM/
Mehrotra.hpp`` (``El::lp::affine::Mehrotra`` et al.): the general form

    min c^T x + (1/2) x^T Q x          (Q = 0 for LP/SOCP)
    s.t.  A x = b,   G x + s = h,   s in K

with K the positive orthant (LP/QP) or a product of second-order cones
(SOCP).  The DIRECT standard forms are the special case G = -I, h = 0 --
this module is the general core the direct solvers conceptually reduce to.

Per iteration (SURVEY.md §4.6 shape -- host convergence loop, device KKT):
assemble the augmented KKT

    [ Q   A^T  G^T ] [dx]   [ -rc             ]
    [ A    0    0  ] [dy] = [ -rb             ]
    [ G    0   -H  ] [dz]   [ -rh + t(r_mu)   ]

where H linearizes the complementarity (pos orth: diag(s/z); SOC: the
Nesterov-Todd quadratic representation W^2 = Q_w), factor ONCE with the
dense distributed LDL, and reuse for the predictor and corrector solves;
recover ds = -rh - G dx from the slack equation.  Ruiz equilibration
(``El::RuizEquil`` on the stacked [A; G] with a shared column scale)
preprocesses badly scaled data -- upstream's mandatory first step --
cone-aware on the G rows for SOCP (uniform scale within each cone).

Cone member vectors are host/replicated (O(m+n+k) against the O(N^2)
distributed KKT, the same subordinate role as in :mod:`.soc`).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global, to_global
from ..redist.interior import interior_update, _blank
from ..blas.level1 import diagonal_scale
from ..blas.level3 import _check_mcmr
from ..lapack.ldl import ldl, ldl_solve_after
from .util import MehrotraCtrl
from .equilibrate import row_col_maxabs, _wrap
from .soc import (make_cone_layout, soc_identity, soc_apply, soc_inverse,
                  soc_max_step, soc_nesterov_todd, soc_dets, soc_sqrt,
                  _arrow_matrix)


# ---------------------------------------------------------------------
# stacked Ruiz equilibration (shared column scale)
# ---------------------------------------------------------------------

def _pool_rows(v, first_inds):
    """Max-pool a row-scale vector within each cone block: one scale per
    cone keeps scaled members inside the cone."""
    if first_inds is None:
        return v
    starts = np.unique(first_inds)
    cone_max = np.maximum.reduceat(v, starts)
    return cone_max[np.searchsorted(starts, first_inds)]


def ruiz_equil_stacked(A: DistMatrix, G: DistMatrix, iters: int = 6,
                       first_inds=None):
    """Ruiz on the stacked [A; G] with one shared column scale: returns
    (A~, G~, d_rA, d_rG, d_c) with A~ = D_rA A D_c, G~ = D_rG G D_c."""
    m, n = A.gshape
    k = G.gshape[0]
    d_rA = np.ones(m)
    d_rG = np.ones(k)
    d_c = np.ones(n)
    As, Gs = A, G
    for _ in range(iters):
        rA, _ = row_col_maxabs(As)
        rG, _ = row_col_maxabs(Gs)
        rA, rG = np.asarray(rA), np.asarray(rG)
        rG = _pool_rows(rG, first_inds)
        sA = np.where(rA > 0, 1.0 / np.sqrt(np.maximum(rA, 1e-30)), 1.0)
        sG = np.where(rG > 0, 1.0 / np.sqrt(np.maximum(rG, 1e-30)), 1.0)
        As = diagonal_scale("L", _wrap(jnp.asarray(sA, As.dtype), A.grid), As)
        Gs = diagonal_scale("L", _wrap(jnp.asarray(sG, Gs.dtype), A.grid), Gs)
        # column pass AFTER the row scaling (a true Ruiz sweep)
        _, cA = row_col_maxabs(As)
        _, cG = row_col_maxabs(Gs)
        cmax = np.maximum(np.asarray(cA), np.asarray(cG))
        sc = np.where(cmax > 0, 1.0 / np.sqrt(np.maximum(cmax, 1e-30)), 1.0)
        As = diagonal_scale("R", _wrap(jnp.asarray(sc, As.dtype), A.grid), As)
        Gs = diagonal_scale("R", _wrap(jnp.asarray(sc, Gs.dtype), A.grid), Gs)
        d_rA *= sA
        d_rG *= sG
        d_c *= sc
    return As, Gs, d_rA, d_rG, d_c


# ---------------------------------------------------------------------
# cone operation bundles
# ---------------------------------------------------------------------

class _PosOrth:
    """Positive-orthant cone ops on host vectors (K = R^k_+)."""

    first_inds = None

    def __init__(self, k):
        self.k = k
        self.num_cones = k

    def h_matrix(self, s, z):
        return np.diag(s / np.maximum(z, 1e-300))

    def compl(self, s, z):
        return s * z

    def corrector(self, s, z, ds_a, dz_a, sigma_mu):
        return s * z + ds_a * dz_a - sigma_mu

    def t_vector(self, s, z, r_mu):
        return r_mu / np.maximum(z, 1e-300)

    def max_step(self, v, dv, cap=1.0):
        neg = dv < 0
        ratio = np.where(neg, -v / np.where(neg, dv, -1.0), np.inf)
        return min(float(ratio.min()), cap)

    def mu(self, s, z):
        return float(s @ z) / self.num_cones

    def interior_shift(self, v):
        scale = max(1.0, float(np.abs(v).max()) if v.size else 1.0)
        v = v + max(0.0, -1.5 * float(v.min()))
        if float(v.min()) < 1e-6 * scale:
            v = v + 0.1 * scale
        return v


def _w_apply(u, x, first_inds):
    """Quadratic representation Q_u x = 2 u (u.x)_cone - det(u) R x with
    R = diag(1, -1, ..., -1) per cone.  With u = w^{1/2} this IS the NT
    scaling W x (Q_{w^{1/2}} = Q_w^{1/2} on the second-order cone)."""
    ux = np.bincount(first_inds, weights=u * x,
                     minlength=x.shape[0])[first_inds]
    dets = soc_dets(u, first_inds)
    heads = first_inds == np.arange(x.shape[0])
    Rx = np.where(heads, x, -x)
    return 2.0 * u * ux - dets * Rx


def _jordan_div(u, r, first_inds):
    """Solve u o y = r per cone: the Jordan product's arrow matrix
    L_u = [[u0, ub^T], [ub, u0 I]] inverted in closed form
    (y0 = (u0 r0 - ub.rb)/det(u), yb = (rb - y0 ub)/u0)."""
    n = u.shape[0]
    heads = first_inds == np.arange(n)
    u0 = u[first_inds]
    r0 = r[first_inds]
    dets = soc_dets(u, first_inds)
    dets = np.where(np.abs(dets) < 1e-300, 1e-300, dets)
    ubrb = np.bincount(first_inds, weights=np.where(heads, 0.0, u * r),
                       minlength=n)[first_inds]
    y0 = (u0 * r0 - ubrb) / dets
    u0s = np.where(np.abs(u0) < 1e-300, 1e-300, u0)
    yb = (r - u * y0) / u0s
    return np.where(heads, y0, yb)


class _Soc:
    """Product-of-second-order-cones ops (Nesterov-Todd scaling)."""

    def __init__(self, orders_list):
        self.orders, self.first_inds = make_cone_layout(orders_list)
        self.k = self.orders.shape[0]
        self.num_cones = len(orders_list)

    def h_matrix(self, s, z):
        # w: Q_w z = s; W = Q_{w^{1/2}} satisfies W z = W^{-1} s = lambda
        self._w = soc_nesterov_todd(s, z, self.first_inds)
        self._wh = soc_sqrt(self._w, self.first_inds)
        self._lam = _w_apply(self._wh, z, self.first_inds)
        return _arrow_matrix(self._w, self.orders, self.first_inds)  # W^2

    def compl(self, s, z):
        return soc_apply(self._lam, self._lam, self.first_inds)

    def corrector(self, s, z, ds_a, dz_a, sigma_mu):
        whinv = soc_inverse(self._wh, self.first_inds)
        dss = _w_apply(whinv, ds_a, self.first_inds)     # W^{-1} ds
        dzs = _w_apply(self._wh, dz_a, self.first_inds)  # W dz
        e = soc_identity(self.first_inds, self.k)
        return soc_apply(self._lam, self._lam, self.first_inds) \
            + soc_apply(dss, dzs, self.first_inds) - sigma_mu * e

    def t_vector(self, s, z, r_mu):
        # third-row correction t = W (lambda \ r_mu)
        return _w_apply(self._wh,
                        _jordan_div(self._lam, r_mu, self.first_inds),
                        self.first_inds)

    def max_step(self, v, dv, cap=1.0):
        return float(soc_max_step(v, dv, self.first_inds, cap=cap))

    def mu(self, s, z):
        return float(s @ z) / self.num_cones

    def interior_shift(self, v):
        heads = self.first_inds == np.arange(self.k)
        barb2 = np.bincount(self.first_inds,
                            weights=np.where(heads, 0.0, v * v),
                            minlength=self.k)[self.first_inds]
        margin = float(np.where(heads, v - np.sqrt(barb2), np.inf).min())
        scale = max(1.0, float(np.abs(v).max()) if v.size else 1.0)
        e = soc_identity(self.first_inds, self.k)
        v = v + max(0.0, -1.5 * margin) * e
        if margin < 1e-6 * scale:
            v = v + 0.1 * scale * e
        return v


# ---------------------------------------------------------------------
# the shared affine Mehrotra core
# ---------------------------------------------------------------------

def _conic_mehrotra(Q, A, G, b, c, h, cone, ctrl, nb, precision,
                    equilibrate=True):
    """Shared core; Q may be None (LP/SOCP) and (A, b) may be None (no
    equality constraints -- CP/TV-style models).  Operands are [MC,MR]
    DistMatrices; returns host vectors (x, y, z, s, info)."""
    if (A is None) != (b is None):
        raise ValueError("A and b must be supplied together (or both None)")
    _check_mcmr(*(X for X in (A, G, b, c, h) if X is not None))
    k, n = G.gshape
    m = A.gshape[0] if A is not None else 0
    g = G.grid

    d_rA = np.ones(m); d_rG = np.ones(k); d_c = np.ones(n)
    if equilibrate:
        if A is not None:
            A, G, d_rA, d_rG, d_c = ruiz_equil_stacked(
                A, G, first_inds=cone.first_inds)
        elif cone.first_inds is None:
            # A-free pos-orth path (CP/TV-style models): plain Ruiz on G
            from .equilibrate import ruiz_equil
            G, d_rG0, d_c0 = ruiz_equil(G)
            d_rG = np.asarray(d_rG0)
            d_c = np.asarray(d_c0)
        # (A-free SOC problems skip equilibration: pooling G's rows per
        # cone without the stacked column pass buys little)

    An = np.asarray(to_global(A)) if A is not None else np.zeros((0, n))
    Gn = np.asarray(to_global(G))
    bn = (np.asarray(to_global(b)).ravel() * d_rA) if b is not None \
        else np.zeros(0)
    cn = np.asarray(to_global(c)).ravel() * d_c
    hn = np.asarray(to_global(h)).ravel() * d_rG
    Qn = None
    if Q is not None:
        Qn = np.asarray(to_global(Q)) * d_c[:, None] * d_c[None, :]

    def dmat(M):
        return from_global(np.asarray(M, Gn.dtype), MC, MR, grid=g)

    N = n + m + k

    def kkt_factor(H):
        Kd = _blank(N, N, G)
        if Qn is not None:
            Kd = interior_update(Kd, dmat(Qn), (0, 0))
        if m > 0:
            Kd = interior_update(Kd, dmat(An.T), (0, n))
            Kd = interior_update(Kd, dmat(An), (n, 0))
        Kd = interior_update(Kd, dmat(Gn.T), (0, n + m))
        Kd = interior_update(Kd, dmat(Gn), (n + m, 0))
        Kd = interior_update(Kd, dmat(-H), (n + m, n + m))
        return ldl(Kd, conjugate=False, nb=nb, precision=precision)

    def kkt_solve(fac, r1, r2, r3):
        rhs = np.concatenate([r1, r2, r3]).reshape(-1, 1)
        sol = ldl_solve_after(*fac, dmat(rhs), conjugate=False, nb=nb,
                              precision=precision)
        sf = np.asarray(to_global(sol)).ravel()
        return sf[:n], sf[n:n + m], sf[n + m:]

    # ---- initialization: two least-norm solves with H = I -------------
    # primal: min ||s|| s.t. Ax=b, Gx+s=h; dual: min ||z|| s.t.
    # A'y + G'z ~= -c (both are this KKT with H=I and the right rhs)
    fac0 = kkt_factor(np.eye(k))
    x, _, _ = kkt_solve(fac0, np.zeros(n), bn, hn)
    s = cone.interior_shift(hn - Gn @ x)
    _, y, z0 = kkt_solve(fac0, -cn, np.zeros(m), np.zeros(k))
    z = cone.interior_shift(z0)

    nb_ = max(np.linalg.norm(bn), 1.0)
    nc_ = max(np.linalg.norm(cn), 1.0)
    nh_ = max(np.linalg.norm(hn), 1.0)
    info = {"iters": 0, "converged": False}
    best = (np.inf, x, y, z, s)

    for it in range(ctrl.max_iters):
        Qx = Qn @ x if Qn is not None else np.zeros(n)
        rb = An @ x - bn
        rh = Gn @ x + s - hn
        rc = Qx + An.T @ y + Gn.T @ z + cn
        mu = cone.mu(s, z)
        pobj = float(cn @ x) + 0.5 * float(x @ Qx)
        dobj = -float(bn @ y) - float(hn @ z) - 0.5 * float(x @ Qx)
        rel_gap = abs(pobj - dobj) / (1.0 + abs(pobj))
        pfeas = max(np.linalg.norm(rb) / nb_, np.linalg.norm(rh) / nh_)
        dfeas = np.linalg.norm(rc) / nc_
        info.update(iters=it, rel_gap=rel_gap, pfeas=pfeas, dfeas=dfeas,
                    mu=mu, pobj=pobj, dobj=dobj)
        if ctrl.print_progress:
            print(f"  affine it {it}: gap={rel_gap:.2e} pfeas={pfeas:.2e} "
                  f"dfeas={dfeas:.2e} mu={mu:.2e}")
        if rel_gap < ctrl.tol and pfeas < ctrl.tol and dfeas < ctrl.tol:
            info["converged"] = True
            break
        score = max(rel_gap, pfeas, dfeas)
        if not np.isfinite(mu) or mu < 0:
            _, x, y, z, s = best
            info["stalled"] = True
            break
        if score < best[0]:
            best = (score, x.copy(), y.copy(), z.copy(), s.copy())

        H = cone.h_matrix(s, z)
        fac = kkt_factor(H)

        def direction(r_mu):
            t = cone.t_vector(s, z, r_mu)
            dx, dy, dz = kkt_solve(fac, -rc, -rb, -rh + t)
            ds = -rh - Gn @ dx
            return dx, dy, dz, ds

        # predictor (affine scaling)
        dx_a, dy_a, dz_a, ds_a = direction(cone.compl(s, z))
        ap = min(cone.max_step(s, ds_a), cone.max_step(z, dz_a))
        mu_aff = cone.mu(s + ap * ds_a, z + ap * dz_a)
        sigma = min(max(mu_aff / mu, 0.0) ** 3, 1.0) if mu > 0 else 0.1

        # corrector (same factorization); eta-damped fraction to the
        # boundary, capped at a full unit step
        r_cor = cone.corrector(s, z, ds_a, dz_a, sigma * mu)
        dx, dy, dz, ds = direction(r_cor)
        ap = min(ctrl.eta * cone.max_step(s, ds, cap=2.0),
                 ctrl.eta * cone.max_step(z, dz, cap=2.0), 1.0)
        x = x + ap * dx
        y = y + ap * dy
        z = z + ap * dz
        s = s + ap * ds

    # undo equilibration: x = D_c x~, y = D_rA y~, z = D_rG z~, s = s~/d_rG
    return (x * d_c, y * d_rA, z * d_rG, s / d_rG, info)


# ---------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------

def lp_affine(A: DistMatrix, G: DistMatrix, b: DistMatrix, c: DistMatrix,
              h: DistMatrix, ctrl: MehrotraCtrl | None = None,
              nb: int | None = None, precision=None,
              equilibrate: bool = True):
    """Affine-form LP (``El::lp::affine::Mehrotra``):
    min c'x s.t. Ax=b, Gx+s=h, s >= 0.  Returns (x, y, z, s, info)."""
    ctrl = ctrl or MehrotraCtrl()
    cone = _PosOrth(G.gshape[0])
    return _conic_mehrotra(None, A, G, b, c, h, cone, ctrl, nb, precision,
                           equilibrate)


def qp_affine(Q: DistMatrix, A: DistMatrix, G: DistMatrix, b: DistMatrix,
              c: DistMatrix, h: DistMatrix,
              ctrl: MehrotraCtrl | None = None, nb: int | None = None,
              precision=None, equilibrate: bool = True):
    """Affine-form QP (``El::qp::affine::Mehrotra``):
    min (1/2)x'Qx + c'x s.t. Ax=b, Gx+s=h, s >= 0."""
    ctrl = ctrl or MehrotraCtrl()
    cone = _PosOrth(G.gshape[0])
    return _conic_mehrotra(Q, A, G, b, c, h, cone, ctrl, nb, precision,
                           equilibrate)


def socp_affine(A: DistMatrix, G: DistMatrix, b: DistMatrix, c: DistMatrix,
                h: DistMatrix, orders_list,
                ctrl: MehrotraCtrl | None = None, nb: int | None = None,
                precision=None, equilibrate: bool = True):
    """Affine-form SOCP (``El::socp::affine::Mehrotra``):
    min c'x s.t. Ax=b, Gx+s=h, s in a product of second-order cones."""
    ctrl = ctrl or MehrotraCtrl()
    if sum(orders_list) != G.gshape[0]:
        raise ValueError(f"cone sizes sum to {sum(orders_list)}, "
                         f"G has {G.gshape[0]} rows")
    cone = _Soc(orders_list)
    return _conic_mehrotra(None, A, G, b, c, h, cone, ctrl, nb, precision,
                           equilibrate)
