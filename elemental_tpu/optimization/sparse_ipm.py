"""Sparse-operand Mehrotra LP + sparse LAV/BP models.

Reference: the sparse instantiations of the upstream IPMs
(``src/optimization/solvers/LP/direct/IPM/Mehrotra.hpp`` over
``DistSparseMatrix``), whose KKT engine is the ~25k-LoC sparse-direct
multifrontal ``reg_ldl`` + FGMRES refinement
(``src/lapack_like/factor/LDL/sparse/**``, SURVEY.md §4.6).

TPU-native stand-in (VERDICT r4 item 3): the per-iteration normal system

    (A D^2 A^T + reg I) dy = rhs,   D^2 = diag(x / z)

is solved MATRIX-FREE by Jacobi-preconditioned CG on the SpMV operator
(two shard_map SpMVs per CG step) with outer iterative refinement --
the same regularized-solve + refinement shape as ``reg_ldl::
RegularizedSolveAfter``, with Krylov replacing the multifrontal factor.
The Jacobi diagonal diag(A D^2 A^T) costs ONE SpMV of the squared-value
matrix against d^2 per iteration.  Ruiz equilibration preprocesses the
triplets host-side (O(nnz), once per solve).

Why this maps well to TPU: the residual/step algebra is SpMV sweeps
(bandwidth-bound shard_map kernels that scale with devices) and the host
convergence loop stays tiny; no dense O(n^2) object is ever formed on
DEVICE ('cg' forms none anywhere; 'direct' holds the host sparse factor,
whose size is structure-dependent fill, not n^2) -- "sparse LP converges
at n >> dense" is the capability this buys.  Distributed multifrontal
LDL on supernodal dense fronts remains the upgrade path.

Each CG solve is ONE jitted ``lax.while_loop`` device call (the eager
host loop's ~6 dispatches + 3 blocking scalar reads per iteration
dominate wall-clock at scale); only the Mehrotra outer loop runs on the
host, matching the SURVEY.md §4.6 host/device split.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.multivec import (DistMultiVec, mv_axpy, mv_dot, mv_from_global,
                             mv_nrm2, mv_to_global)
from ..sparse.core import DistSparseMatrix, dist_sparse_from_coo
from .util import MehrotraCtrl


# ---------------------------------------------------------------------
# triplet helpers
# ---------------------------------------------------------------------

# re-exported for back-compat; the helper lives with its type now
from ..sparse.core import sparse_to_coo  # noqa: E402,F401


def sparse_ruiz_equil(rows, cols, vals, m, n, iters: int = 6):
    """Host-side Ruiz on COO triplets: returns (vals_scaled, d_r, d_c)."""
    d_r = np.ones(m)
    d_c = np.ones(n)
    v = np.asarray(vals, np.float64).copy()
    for _ in range(iters):
        rmax = np.zeros(m)
        np.maximum.at(rmax, rows, np.abs(v))
        sr = np.where(rmax > 0, 1.0 / np.sqrt(np.maximum(rmax, 1e-30)), 1.0)
        v *= sr[rows]
        cmax = np.zeros(n)
        np.maximum.at(cmax, cols, np.abs(v))
        sc = np.where(cmax > 0, 1.0 / np.sqrt(np.maximum(cmax, 1e-30)), 1.0)
        v *= sc[cols]
        d_r *= sr
        d_c *= sc
    return v, d_r, d_c


# ---------------------------------------------------------------------
# matrix-free preconditioned CG (the reg_ldl-solve stand-in)
# ---------------------------------------------------------------------

def _emul(X: DistMultiVec, Y: DistMultiVec) -> DistMultiVec:
    return X.with_local(X.local * Y.local)


import jax
from functools import partial


@partial(jax.jit, static_argnames=("maxiter",))
def _pcg_device(A: DistSparseMatrix, d2: DistMultiVec, reg,
                b: DistMultiVec, dinv: DistMultiVec, tol, maxiter: int):
    """Jacobi-preconditioned CG on the regularized normal operator
    w -> A D^2 A' w + reg w, as ONE device call (lax.while_loop): the
    eager host loop costs ~6 dispatches + 3 blocking scalar reads per
    iteration, which dominates wall-clock at scale (and is hopeless on
    high-latency tunneled backends)."""

    def op(w):
        t = A.spmv_adjoint(w)
        return mv_axpy(reg, w, A.spmv(_emul(d2, t)))

    x0 = b.with_local(jnp.zeros_like(b.local))
    z0 = _emul(dinv, b)
    rz0 = jnp.real(mv_dot(b, z0))
    bnorm = jnp.maximum(mv_nrm2(b), 1e-300)

    def cond(state):
        x, r, p, rz, it, ok = state
        return ok & (it < maxiter) & (mv_nrm2(r) / bnorm >= tol)

    def body(state):
        x, r, p, rz, it, ok = state
        Ap = op(p)
        denom = jnp.real(mv_dot(p, Ap))
        pd = denom > 0
        alpha = jnp.where(pd, rz / jnp.where(pd, denom, 1.0), 0.0)
        x = mv_axpy(alpha, p, x)
        r = mv_axpy(-alpha, Ap, r)
        zv = _emul(dinv, r)
        rz_new = jnp.real(mv_dot(r, zv))
        beta = rz_new / jnp.where(rz != 0, rz, 1.0)
        p = mv_axpy(beta, p, zv)
        return x, r, p, rz_new, it + 1, pd

    x, r, p, rz, it, ok = jax.lax.while_loop(
        cond, body, (x0, b, z0, rz0, jnp.asarray(0), jnp.asarray(True)))
    return x, it


# ---------------------------------------------------------------------
# sparse Mehrotra LP
# ---------------------------------------------------------------------

def lp_sparse(A: DistSparseMatrix, b: DistMultiVec, c: DistMultiVec,
              ctrl: MehrotraCtrl | None = None, cg_tol: float = 1e-10,
              cg_maxiter: int | None = None, refine: int = 1,
              kkt: str = "auto"):
    """Standard-form LP over a DistSparseMatrix: min c'x st Ax=b, x >= 0.

    Returns (x, y, z, info) as DistMultiVecs.  ``kkt`` picks the normal-
    equation engine (the ``reg_ldl`` role -- see module docstring):

      * 'direct' -- SEQUENTIAL sparse-direct factorization of
        A D^2 A' + reg (scipy splu on host triplets, refactored per
        iteration).  The analog of the reference's sequential sparse
        path (``El::SparseMatrix`` + ``ldl``); robust at high
        conditioning, where Krylov iteration counts blow up as
        ~1/sqrt(mu).  The distributed-multifrontal numeric factor is
        the upgrade path.
      * 'cg' -- matrix-free Jacobi-preconditioned CG (fully
        distributed, no host factorization; fine at moderate
        accuracy/conditioning).
      * 'auto' -- 'direct' when scipy is importable and m is moderate
        (robustness first, as upstream always factors); 'cg' otherwise.
        NOTE the trade: 'direct' gathers triplets to the host and its
        fill depends on structure (banded/separator graphs are cheap;
        random-expander patterns fill catastrophically -- for those,
        neither engine is good, which is the fundamental reason the
        reference bundles ParMETIS orderings).
    """
    ctrl = ctrl or MehrotraCtrl()
    m, n = A.gshape
    g = A.grid
    if b.gshape[0] != m or c.gshape[0] != n:
        raise ValueError(f"shape mismatch: A {A.gshape}, b {b.gshape}, "
                         f"c {c.gshape}")
    cg_maxiter = cg_maxiter or 4 * m
    if kkt not in ("auto", "direct", "cg"):
        raise ValueError(f"kkt must be 'auto', 'direct' or 'cg', got {kkt!r}")
    if kkt == "auto":
        try:
            import scipy.sparse  # noqa: F401
            kkt = "direct" if m <= 200_000 else "cg"
        except ImportError:
            kkt = "cg"

    d_r = np.ones(m)
    d_c = np.ones(n)
    if ctrl.equilibrate:
        ro, co, vo = sparse_to_coo(A)
        vs, d_r, d_c = sparse_ruiz_equil(ro, co, vo, m, n)
        A = dist_sparse_from_coo(ro, co, vs, m, n, grid=g,
                                 dtype=np.asarray(vo).dtype)
        b = b.with_local(b.local
                         * _pad_host(d_r, b.local.shape[0])[:, None]
                         .astype(b.dtype))
        c = c.with_local(c.local
                         * _pad_host(d_c, c.local.shape[0])[:, None]
                         .astype(c.dtype))

    A2 = A.with_values(A.vals * A.vals)          # |A|^2 for Jacobi diagonals
    vm_x = _valid(n, c)                          # row-validity masks
    vm_y = _valid(m, b)
    if kkt == "direct":
        import scipy.sparse as _sp
        ro2, co2, vo2 = sparse_to_coo(A)
        _Acsr = _sp.csr_matrix((np.asarray(vo2, np.float64),
                                (ro2, co2)), shape=(m, n))

    def esafe(xl, zl):
        return jnp.where(zl != 0, xl / jnp.where(zl == 0, 1, zl), 0)

    def engine_data(d2: DistMultiVec):
        """Per-IPM-iteration solver data (normal_solve runs 4x on the
        same D^2: predictor + corrector, each with a refinement pass).

        'direct': assemble A E A' + reg on host triplets and splu-factor
        (the reg_ldl refactor step).  'cg': Jacobi diagonal + reg."""
        if kkt == "direct":
            import scipy.sparse.linalg as _spl
            e = np.asarray(mv_to_global(d2)).ravel()
            M = (_Acsr.multiply(e[None, :])) @ _Acsr.T
            reg = 1e-10 * (1.0 + float(abs(M.diagonal()).max()))
            M = (M + reg * _sp.eye(m, format="csr")).tocsc()
            return reg, _spl.splu(M)
        diag = A2.spmv(d2)
        reg = 1e-10 * (1.0 + float(jnp.max(diag.local)))
        diag = diag.with_local(diag.local + reg * vm_y[:, None])
        return reg, diag.with_local(esafe(vm_y[:, None], diag.local))

    def normal_solve(d2: DistMultiVec, rhs: DistMultiVec, tol, jd=None):
        """(A D2 A' + reg) w = rhs by the selected engine + iterative
        refinement against the true (device-side) operator."""
        reg, fac = jd if jd is not None else engine_data(d2)

        def op(w):
            t = A.spmv_adjoint(w)
            return mv_axpy(reg, w, A.spmv(_emul(d2, t)))

        if kkt == "direct":
            rh = np.asarray(mv_to_global(rhs)).ravel()
            w = mv_from_global(fac.solve(rh).reshape(-1, 1), grid=g)
            it = 1                      # factor-solve counts as one pass
            for _ in range(refine):
                r = mv_axpy(-1.0, op(w), rhs)
                if float(mv_nrm2(r)) / max(float(mv_nrm2(rhs)),
                                           1e-300) < tol:
                    break
                dr = np.asarray(mv_to_global(r)).ravel()
                w = mv_axpy(1.0, mv_from_global(
                    fac.solve(dr).reshape(-1, 1), grid=g), w)
                it += 1
            return w, it
        w, it = _pcg_device(A, d2, reg, rhs, fac, tol, cg_maxiter)
        it = int(it)
        for _ in range(refine):
            r = mv_axpy(-1.0, op(w), rhs)
            if float(mv_nrm2(r)) / max(float(mv_nrm2(rhs)), 1e-300) < tol:
                break
            dw, it2 = _pcg_device(A, d2, reg, r, fac, tol, cg_maxiter)
            w = mv_axpy(1.0, dw, w)
            it += int(it2)
        return w, it

    # ---- Mehrotra initialization (least-norm via A A') ----------------
    ones = c.with_local(vm_x[:, None].astype(c.dtype))
    jd0 = engine_data(ones)          # one factorization for both solves
    w0, _ = normal_solve(ones, b, cg_tol, jd=jd0)
    x = A.spmv_adjoint(w0)
    yrhs = A.spmv(c)
    y, _ = normal_solve(ones, yrhs, cg_tol, jd=jd0)
    z = c.with_local(c.local - A.spmv_adjoint(y).local)
    xl, zl = x.local, z.local
    dx = max(0.0, -1.5 * float(jnp.min(jnp.where(vm_x[:, None] > 0, xl,
                                                 jnp.inf))))
    dz = max(0.0, -1.5 * float(jnp.min(jnp.where(vm_x[:, None] > 0, zl,
                                                 jnp.inf))))
    xl = jnp.where(vm_x[:, None] > 0, xl + dx, 0)
    zl = jnp.where(vm_x[:, None] > 0, zl + dz, 0)
    xz = float(jnp.sum(xl * zl))
    ex = 0.5 * xz / max(float(jnp.sum(zl)), 1e-30)
    ez = 0.5 * xz / max(float(jnp.sum(xl)), 1e-30)
    x = x.with_local(jnp.where(vm_x[:, None] > 0, xl + ex, 0))
    z = z.with_local(jnp.where(vm_x[:, None] > 0, zl + ez, 0))

    nb_ = max(float(mv_nrm2(b)), 1.0)
    nc_ = max(float(mv_nrm2(c)), 1.0)
    info = {"iters": 0, "converged": False, "rel_gap": np.inf,
            "cg_iters": 0}
    prev = (x, y, z)
    best = (np.inf, x, y, z, {})
    stall = 0

    for it in range(ctrl.max_iters):
        rb = mv_axpy(-1.0, A.spmv(x), b)
        rc = c.with_local(c.local - A.spmv_adjoint(y).local - z.local)
        mu = float(jnp.real(mv_dot(x, z))) / n
        if not np.isfinite(mu):
            x, y, z = prev
            info["stalled"] = True
            break
        prev = (x, y, z)
        pobj = float(jnp.real(mv_dot(c, x)))
        dobj = float(jnp.real(mv_dot(b, y)))
        rel_gap = abs(pobj - dobj) / (1.0 + abs(pobj))
        pfeas = float(mv_nrm2(rb)) / nb_
        dfeas = float(mv_nrm2(rc)) / nc_
        info.update(iters=it, rel_gap=rel_gap, pfeas=pfeas, dfeas=dfeas,
                    mu=mu, pobj=pobj, dobj=dobj)
        if ctrl.print_progress:
            print(f"  lp_sparse it {it}: gap={rel_gap:.2e} "
                  f"pfeas={pfeas:.2e} dfeas={dfeas:.2e} mu={mu:.2e} "
                  f"cg={info['cg_iters']}")
        if rel_gap < ctrl.tol and pfeas < ctrl.tol and dfeas < ctrl.tol:
            info["converged"] = True
            break
        # once mu underflows, D^2 = x/z spans ~1/mu and the Krylov normal
        # solve degrades into oscillation: keep the best iterate and stop
        # when no progress is made for several rounds
        score = max(rel_gap, pfeas, dfeas)
        if score < best[0]:
            best = (score, x, y, z,
                    dict(iters=it, rel_gap=rel_gap, pfeas=pfeas,
                         dfeas=dfeas, mu=mu, pobj=pobj, dobj=dobj))
            stall = 0
        else:
            stall += 1
        if stall >= 6 or mu < 1e-16:
            _, x, y, z, snap = best
            info.update(snap)
            info["converged"] = best[0] < ctrl.tol
            info["stalled"] = not info["converged"]
            break

        d2 = x.with_local(esafe(x.local, z.local))
        jd_it = engine_data(d2)
        # inexact-Newton forcing: solve the normal system just accurately
        # enough for the current mu (tightens as the iterates converge)
        tol_it = max(cg_tol, min(1e-6, 1e-2 * mu))

        def solve_core(rc_l, rb_mv, rmu_l):
            """One elimination pass for the KKT system
            A'dy + dz = rc, A dx = rb, z dx + x dz = rmu
            (targets as passed -- the dense lp.py sign convention)."""
            zinv_rmu = x.with_local(esafe(rmu_l, z.local))
            t = x.with_local(d2.local * rc_l - zinv_rmu.local)
            rhs = mv_axpy(1.0, A.spmv(t), rb_mv)
            dy, cg_it = normal_solve(d2, rhs, tol_it, jd=jd_it)
            info["cg_iters"] += cg_it
            Atdy = A.spmv_adjoint(dy)
            dxv = x.with_local(d2.local * (Atdy.local - rc_l)
                               + zinv_rmu.local)
            dzv = x.with_local(esafe(rmu_l - z.local * dxv.local, x.local))
            return dxv, dy, dzv

        def solve_dir(r_mu):
            # solve_core targets: A dx = rb, A'dy + dz = rc, z dx + x dz
            # = r_mu (the dense lp.py convention)
            dxv, dy, dzv = solve_core(rc.local, rb, r_mu)
            # KKT-level iterative refinement (the reg_ldl::
            # RegularizedSolveAfter role): the dx recovery amplifies the
            # inner normal-solve error by ||D^2||, so one correction pass
            # on the TRUE KKT residuals recovers full direction accuracy.
            e1 = rc.local - (A.spmv_adjoint(dy).local + dzv.local)
            e2 = mv_axpy(-1.0, A.spmv(dxv), rb)          # rb - A dx
            e3 = r_mu - (z.local * dxv.local + x.local * dzv.local)
            ex, ey, ez = solve_core(e1, e2, e3)
            return (x.with_local(dxv.local + ex.local),
                    mv_axpy(1.0, ey, dy),
                    x.with_local(dzv.local + ez.local))

        r_aff = -(x.local * z.local)
        dx_a, dy_a, dz_a = solve_dir(r_aff)
        ap = _max_step(x, dx_a)
        ad = _max_step(z, dz_a)
        mu_aff = float(jnp.sum((x.local + ap * dx_a.local)
                               * (z.local + ad * dz_a.local))) / n
        sigma = min(max(mu_aff / mu, 0.0) ** 3, 1.0) if mu > 0 else 0.1

        r_cor = sigma * mu * vm_x[:, None] - x.local * z.local \
            - dx_a.local * dz_a.local
        dx_c, dy_c, dz_c = solve_dir(r_cor)
        ap = min(ctrl.eta * _max_step(x, dx_c, cap=2.0), 1.0)
        ad = min(ctrl.eta * _max_step(z, dz_c, cap=2.0), 1.0)
        x = mv_axpy(ap, dx_c, x)
        y = mv_axpy(ad, dy_c, y)
        z = mv_axpy(ad, dz_c, z)

    if ctrl.equilibrate:
        x = x.with_local(x.local * _pad_host(d_c, x.local.shape[0])[:, None]
                         .astype(x.dtype))
        y = y.with_local(y.local * _pad_host(d_r, y.local.shape[0])[:, None]
                         .astype(y.dtype))
        dcp = _pad_host(d_c, z.local.shape[0])
        dcp = np.where(dcp == 0, 1.0, dcp)
        z = z.with_local(z.local / dcp[:, None].astype(z.dtype))
    return x, y, z, info


def _pad_host(v, rows):
    out = np.zeros(rows, v.dtype)
    out[: v.shape[0]] = v
    return out


def _valid(k, template: DistMultiVec):
    rows = template.local.shape[0]
    return (jnp.arange(rows) < k).astype(template.dtype)


def _max_step(v: DistMultiVec, dv: DistMultiVec, cap: float = 1.0):
    neg = dv.local < 0
    ratio = jnp.where(neg, -v.local / jnp.where(neg, dv.local, -1.0),
                      jnp.inf)
    return min(float(jnp.min(ratio)), cap)


# ---------------------------------------------------------------------
# sparse models: LAV and BP (the upstream LP-reduction models over
# DistSparseMatrix operands -- src/optimization/models/{LAV,BP}.cpp)
# ---------------------------------------------------------------------

def lav_sparse(A: DistSparseMatrix, b: DistMultiVec,
               ctrl: MehrotraCtrl | None = None, **kw):
    """Least absolute value regression min ||Ax - b||_1 (``El::LAV``
    sparse): LP on [x+; x-; u; v] >= 0 with [A, -A, I, -I] equality
    rows.  Returns (x, info)."""
    m, n = A.gshape
    g = A.grid
    ro, co, vo = sparse_to_coo(A)
    rows = np.concatenate([ro, ro, np.arange(m), np.arange(m)])
    cols = np.concatenate([co, co + n,
                           2 * n + np.arange(m), 2 * n + m + np.arange(m)])
    vals = np.concatenate([vo, -vo, np.ones(m), -np.ones(m)])
    N = 2 * n + 2 * m
    Ah = dist_sparse_from_coo(rows, cols, vals, m, N, grid=g,
                              dtype=np.asarray(vo).dtype)
    ch = mv_from_global(np.concatenate([np.zeros(2 * n), np.ones(2 * m)])
                        .reshape(-1, 1).astype(np.asarray(vo).dtype), grid=g)
    xh, yh, zh, info = lp_sparse(Ah, b, ch, ctrl, **kw)
    xg = np.asarray(mv_to_global(xh)).ravel()
    x = mv_from_global((xg[:n] - xg[n:2 * n]).reshape(-1, 1)
                       .astype(np.asarray(vo).dtype), grid=g)
    return x, info


def bp_sparse(A: DistSparseMatrix, b: DistMultiVec,
              ctrl: MehrotraCtrl | None = None, **kw):
    """Basis pursuit min ||x||_1 s.t. Ax = b (``El::BP`` sparse): LP on
    [x+; x-] >= 0 with [A, -A] equality rows.  Returns (x, info)."""
    m, n = A.gshape
    g = A.grid
    ro, co, vo = sparse_to_coo(A)
    rows = np.concatenate([ro, ro])
    cols = np.concatenate([co, co + n])
    vals = np.concatenate([vo, -vo])
    Ah = dist_sparse_from_coo(rows, cols, vals, m, 2 * n, grid=g,
                              dtype=np.asarray(vo).dtype)
    ch = mv_from_global(np.ones((2 * n, 1), np.asarray(vo).dtype), grid=g)
    xh, yh, zh, info = lp_sparse(Ah, b, ch, ctrl, **kw)
    xg = np.asarray(mv_to_global(xh)).ravel()
    x = mv_from_global((xg[:n] - xg[n:]).reshape(-1, 1)
                       .astype(np.asarray(vo).dtype), grid=g)
    return x, info
