"""Dense Mehrotra predictor-corrector LP interior-point solver.

Reference: Elemental ``src/optimization/solvers/LP/direct/IPM/Mehrotra.hpp``
(``El::lp::direct::Mehrotra``, ``KKTSystem = NORMAL_KKT`` dense path):

    min c^T x  s.t.  A x = b,  x >= 0        (primal, standard form)
    max b^T y  s.t.  A^T y + z = c, z >= 0   (dual)

TPU-native shape (SURVEY.md §4.6): the convergence loop runs on the HOST;
each iteration is distributed device work -- one Cholesky factorization of
the normal matrix M = A D^2 A^T (D^2 = diag(x/z)) reused by the predictor
and corrector solves, plus matmul-shaped residual/step algebra on [MC,MR]
storage.  The classic Mehrotra initialization (least-norm primal/dual via
A A^T, shifted to the interior) reuses the same Cholesky machinery.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute, transpose_dist
from ..blas.level1 import (_valid_mask, shift_diagonal, diagonal_scale,
                           diagonal_solve)
from ..blas.level3 import _check_mcmr, gemm
from ..lapack.cholesky import cholesky, cholesky_solve_after
from .util import MehrotraCtrl, max_step, safe_div


def _tp(A):
    return redistribute(transpose_dist(A), MC, MR)


def _dot(a: DistMatrix, b: DistMatrix) -> float:
    return float(jnp.sum(a.local * b.local))


def _norm(a: DistMatrix) -> float:
    return float(jnp.linalg.norm(a.local))


def _wrap_diag(v: DistMatrix) -> DistMatrix:
    """(n,1) [MC,MR] vector -> replicated (n,1) diagonal for diagonal_scale."""
    from ..core.dist import STAR
    from ..core.distmatrix import to_global
    # to_global is storage index math (no comm beyond what GSPMD inserts)
    g = to_global(v)
    return DistMatrix(g, v.gshape, STAR, STAR, 0, 0, v.grid)


def lp(A: DistMatrix, b: DistMatrix, c: DistMatrix,
       ctrl: MehrotraCtrl | None = None, nb: int | None = None,
       precision=None):
    """Solve the standard-form LP; returns (x, y, z, info dict)."""
    _check_mcmr(A, b, c)
    ctrl = ctrl or MehrotraCtrl()
    m, n = A.gshape
    g = A.grid

    if ctrl.equilibrate:
        # Ruiz first (El::RuizEquil): A~ = Dr A Dc, b~ = Dr b, c~ = Dc c;
        # solve scaled, then x = Dc x~, y = Dr y~, z = Dc^{-1} z~.
        from .equilibrate import ruiz_equil, _wrap
        import dataclasses as _dc
        As, d_r, d_c = ruiz_equil(A)
        wr = _wrap(d_r.astype(b.dtype), g)
        wc = _wrap(d_c.astype(c.dtype), g)
        bs = diagonal_scale("L", wr, b)
        cs = diagonal_scale("L", wc, c)
        xs, ys, zs, info = lp(As, bs, cs,
                              _dc.replace(ctrl, equilibrate=False), nb,
                              precision)
        x = diagonal_scale("L", wc, xs)
        y = diagonal_scale("L", wr, ys)
        z = diagonal_solve("L", wc, zs)
        return x, y, z, info

    At = _tp(A)
    vm_x = _valid_mask(c)
    vm_y = _valid_mask(b)

    def normal_solve(d2, rhs, Lfac=None):
        """Solve (A D2 A^T + reg I) w = rhs; returns (w, L) reusing Lfac.

        The static diagonal regularization is the dense analog of the
        reference's ``reg_ldl`` (``El::reg_ldl::RegularizedSolveAfter``):
        it keeps the normal matrix factorable as the iterates approach a
        degenerate face (D^2 dynamic range blows up near convergence)."""
        if Lfac is None:
            Ad = diagonal_scale("R", _wrap_diag(d2), A)
            M = gemm(Ad, At, nb=nb, precision=precision)
            M = M.with_local(0.5 * (M.local + redistribute(
                transpose_dist(M), MC, MR).local))
            reg = 1e-12 * (1.0 + float(jnp.max(jnp.abs(M.local))))
            M = shift_diagonal(M, reg)
            Lfac = cholesky(M, "L", nb=nb, precision=precision)
        w = cholesky_solve_after(Lfac, rhs, nb=nb, precision=precision)
        return w, Lfac

    # ---- Mehrotra initialization -------------------------------------
    ones = c.with_local(jnp.where(vm_x, jnp.ones_like(c.local), 0))
    w0, L0 = normal_solve(ones, b)                       # (A A^T) w = b
    x = gemm(At, w0, nb=nb, precision=precision)         # least-norm primal
    yrhs = gemm(A, c, nb=nb, precision=precision)
    y, _ = normal_solve(ones, yrhs, L0)                  # (A A^T) y = A c
    z = c.with_local(c.local - gemm(At, y, nb=nb, precision=precision).local)
    dx = max(0.0, -1.5 * float(jnp.min(jnp.where(vm_x, x.local, jnp.inf))))
    dz = max(0.0, -1.5 * float(jnp.min(jnp.where(vm_x, z.local, jnp.inf))))
    xs = x.with_local(jnp.where(vm_x, x.local + dx, 0))
    zs = z.with_local(jnp.where(vm_x, z.local + dz, 0))
    xz = _dot(xs, zs)
    ex = 0.5 * xz / max(float(jnp.sum(zs.local)), 1e-30)
    ez = 0.5 * xz / max(float(jnp.sum(xs.local)), 1e-30)
    x = xs.with_local(jnp.where(vm_x, xs.local + ex, 0))
    z = zs.with_local(jnp.where(vm_x, zs.local + ez, 0))

    nb_ = max(_norm(b), 1.0)
    nc_ = max(_norm(c), 1.0)
    info = {"iters": 0, "converged": False, "rel_gap": np.inf}

    prev = (x, y, z)
    for it in range(ctrl.max_iters):
        rb = b.with_local(b.local - gemm(A, x, nb=nb, precision=precision).local)
        rc = c.with_local(c.local
                          - gemm(At, y, nb=nb, precision=precision).local
                          - z.local)
        mu = _dot(x, z) / n
        if not np.isfinite(mu):
            # numerically singular normal system at a degenerate face:
            # keep the last good iterate (already near-optimal in practice)
            x, y, z = prev
            info["stalled"] = True
            break
        prev = (x, y, z)
        pobj = _dot(c, x)
        dobj = _dot(b, y)
        rel_gap = abs(pobj - dobj) / (1.0 + abs(pobj))
        pfeas = _norm(rb) / nb_
        dfeas = _norm(rc) / nc_
        info.update(iters=it, rel_gap=rel_gap, pfeas=pfeas, dfeas=dfeas,
                    mu=mu, pobj=pobj, dobj=dobj)
        if ctrl.print_progress:
            print(f"  lp it {it}: gap={rel_gap:.2e} pfeas={pfeas:.2e} "
                  f"dfeas={dfeas:.2e} mu={mu:.2e}")
        if rel_gap < ctrl.tol and pfeas < ctrl.tol and dfeas < ctrl.tol:
            info["converged"] = True
            break

        d2 = x.with_local(safe_div(x.local, z.local))

        def solve_dir(r_mu, Lfac):
            # A D2 A^T dy = rb + A (D2 rc - Z^{-1} r_mu)
            zinv_rmu = x.with_local(safe_div(r_mu, z.local))
            t = x.with_local(d2.local * rc.local - zinv_rmu.local)
            rhs = b.with_local(rb.local
                               + gemm(A, t, nb=nb, precision=precision).local)
            dy, Lfac = normal_solve(d2, rhs, Lfac)
            Atdy = gemm(At, dy, nb=nb, precision=precision)
            dxv = x.with_local(d2.local * (Atdy.local - rc.local)
                               + zinv_rmu.local)
            dzv = x.with_local(safe_div(r_mu - z.local * dxv.local, x.local))
            return dxv, dy, dzv, Lfac

        # predictor (affine scaling)
        r_aff = -(x.local * z.local)
        dx_a, dy_a, dz_a, Lfac = solve_dir(r_aff, None)
        ap = float(max_step(x, dx_a))
        ad = float(max_step(z, dz_a))
        mu_aff = float(jnp.sum((x.local + ap * dx_a.local)
                               * (z.local + ad * dz_a.local))) / n
        sigma = min((mu_aff / mu) ** 3, 1.0) if mu > 0 else 0.1

        # corrector (centering + second order), same factorization
        r_cor = sigma * mu * vm_x - x.local * z.local \
            - dx_a.local * dz_a.local
        dx_c, dy_c, dz_c, _ = solve_dir(r_cor, Lfac)
        ap = ctrl.eta * float(max_step(x, dx_c, cap=1.0 / ctrl.eta))
        ad = ctrl.eta * float(max_step(z, dz_c, cap=1.0 / ctrl.eta))
        ap, ad = min(ap, 1.0), min(ad, 1.0)
        x = x.with_local(x.local + ap * dx_c.local)
        y = y.with_local(y.local + ad * dy_c.local)
        z = z.with_local(z.local + ad * dz_c.local)
    return x, y, z, info
