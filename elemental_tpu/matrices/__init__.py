"""Matrix gallery (reference: Elemental ``src/matrices/``)."""
from .basic import (
    zeros, ones, identity, hilbert, lehmer, minij,
    uniform, gaussian, hermitian_uniform_spectrum,
)
from .gallery import (
    fourier, toeplitz, hankel, circulant, cauchy, walsh, wilkinson,
    laplacian_1d, laplacian_2d, jordan, kahan, grcar, parter, pei,
    redheffer, triw, gear, gepp_growth,
    gaussian_device, uniform_device, bernoulli, rademacher, wigner, haar,
    normal_uniform_spectrum,
    demmel, druinsky_toledo, egorov, extended_kahan, fiedler, fox_li,
    gks, hanowa, helmholtz_1d, helmholtz_2d, helmholtz_3d, laplacian_3d,
    jordan_cholesky, lauchli, legendre, lotkin, one_two_one, riffle,
    ris, whale, hatano_nelson, three_valued, kms,
)
