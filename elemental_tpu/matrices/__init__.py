"""Matrix gallery (reference: Elemental ``src/matrices/``)."""
from .basic import (
    zeros, ones, identity, hilbert, lehmer, minij,
    uniform, gaussian, hermitian_uniform_spectrum,
)
