"""Matrix gallery (initial slice).

Reference: Elemental ``src/matrices/**`` (~60 deterministic + random
generators, the test/benchmark input factory).  Deterministic generators are
built on the level-1 index-dependent fill (device-side, layout-independent);
random generators draw on the host for cross-layout determinism and enter
through ``from_global`` (the gallery widens in the breadth pass).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global, zeros as dm_zeros
from ..core.grid import Grid, default_grid
from ..blas.level1 import index_dependent_fill, shift_diagonal


def _empty(m, n, grid, dtype, cdist=MC, rdist=MR):
    return dm_zeros(m, n, cdist, rdist, grid, dtype=dtype)


def zeros(m: int, n: int | None = None, grid: Grid | None = None, dtype=jnp.float32):
    return _empty(m, n or m, grid or default_grid(), dtype)


def ones(m: int, n: int | None = None, grid: Grid | None = None, dtype=jnp.float32):
    from ..blas.level1 import fill
    return fill(_empty(m, n or m, grid or default_grid(), dtype), 1)


def identity(m: int, n: int | None = None, grid: Grid | None = None, dtype=jnp.float32):
    A = _empty(m, n or m, grid or default_grid(), dtype)
    return shift_diagonal(A, 1)


def hilbert(n: int, grid: Grid | None = None, dtype=jnp.float64):
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(A, lambda i, j: (1.0 / (i + j + 1)).astype(dtype))


def lehmer(n: int, grid: Grid | None = None, dtype=jnp.float64):
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: (jnp.minimum(i, j) + 1).astype(dtype)
        / (jnp.maximum(i, j) + 1))


def minij(n: int, grid: Grid | None = None, dtype=jnp.float64):
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(A, lambda i, j: (jnp.minimum(i, j) + 1).astype(dtype))


# ---- random ----------------------------------------------------------

def uniform(m: int, n: int | None = None, grid: Grid | None = None,
            dtype=jnp.float32, seed: int = 0, lo=0.0, hi=1.0) -> DistMatrix:
    n = n or m
    rng = np.random.default_rng(seed)
    F = rng.uniform(lo, hi, size=(m, n)).astype(np.dtype(dtype))
    return from_global(F, MC, MR, grid or default_grid())


def gaussian(m: int, n: int | None = None, grid: Grid | None = None,
             dtype=jnp.float32, seed: int = 0) -> DistMatrix:
    n = n or m
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if np.issubdtype(dt, np.complexfloating):
        F = (rng.normal(size=(m, n)) + 1j * rng.normal(size=(m, n))).astype(dt)
    else:
        F = rng.normal(size=(m, n)).astype(dt)
    return from_global(F, MC, MR, grid or default_grid())


def hermitian_uniform_spectrum(n: int, lo=1.0, hi=2.0, grid: Grid | None = None,
                               dtype=jnp.float64, seed: int = 0) -> DistMatrix:
    """HPD test matrix with known-conditioned uniform spectrum
    (``El::HermitianUniformSpectrum``): Q diag(u) Q^H, Q Haar via QR."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if np.issubdtype(dt, np.complexfloating):
        G = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    else:
        G = rng.normal(size=(n, n))
    Q, _ = np.linalg.qr(G)
    d = rng.uniform(lo, hi, size=n)
    A = (Q * d) @ Q.conj().T
    A = (A + A.conj().T) / 2
    return from_global(A.astype(dt), MC, MR, grid or default_grid())
