"""Matrix gallery breadth: deterministic generators + device-side RNG.

Reference: Elemental ``src/matrices/**`` (~60 deterministic + random
generators, the test/benchmark input factory): ``Fourier``, ``Toeplitz``,
``Hankel``, ``Circulant``, ``Cauchy``, ``Walsh``, ``Wilkinson``,
``Laplacian``, ``Jordan``, ``Kahan``, ``Grcar``, ``Parter``, ``Pei``,
``Redheffer``, ``TriW``, ``Gear``, ``GEPPGrowth``, ``Wigner``, ``Haar``,
``Bernoulli``, ``Rademacher``, ``NormalUniformSpectrum``...

Deterministic generators ride :func:`..blas.level1.index_dependent_fill`
(device-side, layout-independent).  Random generators come in two flavors:
the host path (:mod:`.basic`) is layout-INdependent; the device-side path
here seeds a per-device stream by mesh rank inside ``shard_map`` -- exactly
the reference's "per-process PRNGs with rank-dependent seeding" (SURVEY.md
§3.5 Random), so results depend on the grid shape but never leave device
memory (the at-scale requirement).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global
from ..core.grid import Grid, default_grid
from ..blas.level1 import index_dependent_fill, shift_diagonal
from .basic import _empty, gaussian as _host_gaussian


# ---------------------------------------------------------------------
# deterministic generators (device-side, layout-independent)
# ---------------------------------------------------------------------

def fourier(n: int, grid: Grid | None = None, dtype=jnp.complex128):
    """Unitary DFT matrix (``El::Fourier``): F[i,j] = e^{-2 pi i ij/n}/sqrt(n)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    w = -2.0 * math.pi / n
    return index_dependent_fill(
        A, lambda i, j: (jnp.exp(1j * w * (i * j)) / math.sqrt(n)).astype(dtype))


def toeplitz(col, row, grid: Grid | None = None, dtype=None):
    """Toeplitz from first column ``col`` and first row ``row``
    (``El::Toeplitz``; row[0] ignored in favor of col[0])."""
    col = jnp.asarray(col)
    row = jnp.asarray(row)
    m, n = col.shape[0], row.shape[0]
    dtype = dtype or jnp.result_type(col.dtype, row.dtype)
    A = _empty(m, n, grid or default_grid(), dtype)
    ext = jnp.concatenate([row[1:][::-1], col]).astype(dtype)

    def f(i, j):
        return jnp.take(ext, jnp.clip(i - j + n - 1, 0, m + n - 2))

    return index_dependent_fill(A, f)


def hankel(col, row, grid: Grid | None = None, dtype=None):
    """Hankel from first column and last row (``El::Hankel``)."""
    col = jnp.asarray(col)
    row = jnp.asarray(row)
    m, n = col.shape[0], row.shape[0]
    dtype = dtype or jnp.result_type(col.dtype, row.dtype)
    A = _empty(m, n, grid or default_grid(), dtype)
    ext = jnp.concatenate([col, row[1:]]).astype(dtype)

    def f(i, j):
        return jnp.take(ext, jnp.clip(i + j, 0, m + n - 2))

    return index_dependent_fill(A, f)


def circulant(c, grid: Grid | None = None, dtype=None):
    """Circulant with first column ``c`` (``El::Circulant``)."""
    c = jnp.asarray(c)
    n = c.shape[0]
    dtype = dtype or c.dtype
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.take(c.astype(dtype), (i - j) % n))


def cauchy(x, y, grid: Grid | None = None, dtype=jnp.float64):
    """C[i,j] = 1/(x_i - y_j) (``El::Cauchy``)."""
    x = jnp.asarray(x, dtype)
    y = jnp.asarray(y, dtype)
    A = _empty(x.shape[0], y.shape[0], grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: 1.0 / (jnp.take(x, i) - jnp.take(y, j)))


def walsh(k: int, binary: bool = False, grid: Grid | None = None,
          dtype=jnp.float64):
    """2^k x 2^k Walsh matrix (``El::Walsh``): (-1)^{popcount(i & j)}
    (or 0/1 when ``binary``)."""
    n = 1 << k
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        bits = i & j
        pc = jnp.zeros_like(bits)
        for b in range(k):
            pc = pc + ((bits >> b) & 1)
        val = 1.0 - 2.0 * (pc % 2)
        return ((val + 1) / 2 if binary else val).astype(dtype)

    return index_dependent_fill(A, f)


def wilkinson(k: int, grid: Grid | None = None, dtype=jnp.float64):
    """(2k+1) x (2k+1) Wilkinson eigenvalue test matrix (``El::Wilkinson``)."""
    n = 2 * k + 1
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        diag = jnp.abs(i - k).astype(dtype)
        off = ((j == i + 1) | (i == j + 1)).astype(dtype)
        return jnp.where(i == j, diag, off)

    return index_dependent_fill(A, f)


def laplacian_1d(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Tridiagonal [-1, 2, -1] (``El::Laplacian`` 1-D)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j, jnp.asarray(2, dtype),
                         jnp.where(jnp.abs(i - j) == 1,
                                   jnp.asarray(-1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def laplacian_2d(nx: int, ny: int, grid: Grid | None = None,
                 dtype=jnp.float64):
    """5-point 2-D grid Laplacian of size (nx*ny)^2 (``El::Laplacian``)."""
    n = nx * ny
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        xi, yi = i % nx, i // nx
        xj, yj = j % nx, j // nx
        same = (i == j)
        horiz = (yi == yj) & (jnp.abs(xi - xj) == 1)
        vert = (xi == xj) & (jnp.abs(yi - yj) == 1)
        return jnp.where(same, jnp.asarray(4, dtype),
                         jnp.where(horiz | vert, jnp.asarray(-1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def jordan(n: int, lam, grid: Grid | None = None, dtype=jnp.float64):
    """Jordan block with eigenvalue ``lam`` (``El::Jordan``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    A = shift_diagonal(A, lam, 0)
    return shift_diagonal(A, 1, 1)


def kahan(n: int, phi: float = 0.5, grid: Grid | None = None,
          dtype=jnp.float64):
    """Kahan's ill-conditioned triangular matrix (``El::Kahan``)."""
    zeta = math.sqrt(1.0 - phi * phi)
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        zi = jnp.asarray(zeta, dtype) ** i.astype(dtype)
        return jnp.where(i == j, zi,
                         jnp.where(j > i, -phi * zi, jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def grcar(n: int, k: int = 3, grid: Grid | None = None, dtype=jnp.float64):
    """Grcar nonnormal test matrix (``El::Grcar``)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j + 1, jnp.asarray(-1, dtype),
                         jnp.where((j >= i) & (j <= i + k),
                                   jnp.asarray(1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def parter(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """P[i,j] = 1/(i - j + 1/2) (``El::Parter``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: (1.0 / (i.astype(dtype) - j.astype(dtype) + 0.5)))


def pei(n: int, alpha: float = 1.0, grid: Grid | None = None,
        dtype=jnp.float64):
    """alpha I + ones (``El::Pei``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.where(i == j, jnp.asarray(alpha + 1, dtype),
                                  jnp.asarray(1, dtype)))


def redheffer(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """R[i,j] = 1 if j == 0 or (j+1) % (i+1) == 0 (``El::Redheffer``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: ((j == 0) | ((j + 1) % (i + 1) == 0)).astype(dtype))


def triw(n: int, alpha, k: int | None = None, grid: Grid | None = None,
         dtype=jnp.float64):
    """Upper triangular with 1s on the diagonal and ``alpha`` on the next
    ``k`` superdiagonals (``El::TriW``)."""
    k = n - 1 if k is None else k
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.where(i == j, jnp.asarray(1, dtype),
                                  jnp.where((j > i) & (j <= i + k),
                                            jnp.asarray(alpha, dtype),
                                            jnp.asarray(0, dtype))))


def gear(n: int, i_off: int = 1, j_off: int = -1, grid: Grid | None = None,
         dtype=jnp.float64):
    """Gear matrix (``El::Gear``): sub/super-diagonal of ones plus two
    corner entries."""
    i_idx = abs(i_off) - 1 if i_off != 0 else 0
    j_idx = n - abs(j_off) if j_off != 0 else n - 1
    si = float(np.sign(i_off))
    sj = float(np.sign(j_off))
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        off = ((i == j + 1) | (j == i + 1)).astype(dtype)
        c1 = (i == 0) & (j == i_idx)
        c2 = (i == n - 1) & (j == j_idx)
        # corners OVERWRITE (El::Gear uses Set, not Update)
        return jnp.where(c1, jnp.asarray(si, dtype),
                         jnp.where(c2, jnp.asarray(sj, dtype), off))

    return index_dependent_fill(A, f)


def gepp_growth(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Wilkinson's GEPP growth-factor matrix (``El::GEPPGrowth``):
    identity lower triangle of -1s with a ones last column."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j, jnp.asarray(1, dtype),
                         jnp.where(j == n - 1, jnp.asarray(1, dtype),
                                   jnp.where(i > j, jnp.asarray(-1, dtype),
                                             jnp.asarray(0, dtype))))

    return index_dependent_fill(A, f)


# ---------------------------------------------------------------------
# random generators (device-side, rank-seeded per shard)
# ---------------------------------------------------------------------

def _device_random(m: int, n: int, grid: Grid, dtype, seed: int, sampler):
    """Per-shard device-side sampling: each device draws its local block
    from a stream folded by its mesh rank (the reference's rank-seeded
    per-process PRNGs).  Layout-dependent by design; never materializes
    the global matrix anywhere."""
    meta = DistMatrix(None, (m, n), MC, MR, 0, 0, grid)
    lshape = (meta.local_rows, meta.local_cols)   # per-device block

    def f():
        r = jax.lax.axis_index("mc")
        c = jax.lax.axis_index("mr")
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 r * grid.width + c)
        return sampler(key, lshape)

    stor = jax.shard_map(f, mesh=grid.mesh, in_specs=(),
                         out_specs=P("mc", "mr"), check_vma=False)()
    out = meta.with_local(stor)
    # re-zero padding to keep the library invariant
    from ..blas.level1 import _valid_mask
    return out.with_local(jnp.where(_valid_mask(out), out.local, 0))


def gaussian_device(m: int, n: int | None = None, grid: Grid | None = None,
                    dtype=jnp.float32, seed: int = 0) -> DistMatrix:
    """Device-side standard normal (``El::Gaussian`` with rank seeding)."""
    n = n or m
    grid = grid or default_grid()
    if jnp.issubdtype(dtype, jnp.complexfloating):
        def sampler(key, shape):
            kr, ki = jax.random.split(key)
            rd = jnp.zeros((), dtype).real.dtype
            return (jax.random.normal(kr, shape, rd)
                    + 1j * jax.random.normal(ki, shape, rd)).astype(dtype)
    else:
        def sampler(key, shape):
            return jax.random.normal(key, shape, dtype)
    return _device_random(m, n, grid, dtype, seed, sampler)


def uniform_device(m: int, n: int | None = None, grid: Grid | None = None,
                   dtype=jnp.float32, seed: int = 0, lo=0.0,
                   hi=1.0) -> DistMatrix:
    """Device-side uniform (``El::Uniform`` with rank seeding)."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return jax.random.uniform(key, shape, dtype, lo, hi)

    return _device_random(m, n, grid, dtype, seed, sampler)


def bernoulli(m: int, n: int | None = None, p: float = 0.5,
              grid: Grid | None = None, dtype=jnp.float32,
              seed: int = 0) -> DistMatrix:
    """0/1 Bernoulli(p) entries (``El::Bernoulli``), device-side."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return jax.random.bernoulli(key, p, shape).astype(dtype)

    return _device_random(m, n, grid, dtype, seed, sampler)


def rademacher(m: int, n: int | None = None, grid: Grid | None = None,
               dtype=jnp.float32, seed: int = 0) -> DistMatrix:
    """+-1 entries (``El::Rademacher``), device-side."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return (2 * jax.random.bernoulli(key, 0.5, shape).astype(dtype) - 1)

    return _device_random(m, n, grid, dtype, seed, sampler)


def wigner(n: int, grid: Grid | None = None, dtype=jnp.float64,
           seed: int = 0) -> DistMatrix:
    """Symmetric Gaussian (Wigner) matrix (``El::Wigner``)."""
    from ..blas.level1 import make_symmetric
    G = _host_gaussian(n, n, grid=grid, dtype=dtype, seed=seed)
    return make_symmetric(
        G.with_local(G.local / math.sqrt(2.0 * n)), "L",
        conj=jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating))


def haar(n: int, grid: Grid | None = None, dtype=jnp.float64,
         seed: int = 0) -> DistMatrix:
    """Haar-distributed orthogonal/unitary matrix via QR of a Gaussian
    (``El::Haar``)."""
    from ..lapack.qr import qr, explicit_q
    G = _host_gaussian(n, n, grid=grid, dtype=dtype, seed=seed)
    Ap, tau = qr(G)
    return explicit_q(Ap, tau)


def normal_uniform_spectrum(n: int, center=0.0, radius: float = 1.0,
                            grid: Grid | None = None, dtype=jnp.complex128,
                            seed: int = 0) -> DistMatrix:
    """Normal matrix with eigenvalues uniform in a disk
    (``El::NormalUniformSpectrum``): Q diag(lam) Q^H, Q Haar."""
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        raise ValueError("normal_uniform_spectrum's spectrum is genuinely "
                         "complex; pass a complex dtype")
    rng = np.random.default_rng(seed)
    rad = radius * np.sqrt(rng.uniform(size=n))
    ang = rng.uniform(0, 2 * np.pi, size=n)
    lam = center + rad * np.exp(1j * ang)
    Gq = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    Q, _ = np.linalg.qr(Gq)
    A = (Q * lam) @ Q.conj().T
    return from_global(A.astype(np.dtype(dtype)), MC, MR,
                       grid=grid or default_grid())
