"""Matrix gallery breadth: deterministic generators + device-side RNG.

Reference: Elemental ``src/matrices/**`` (~60 deterministic + random
generators, the test/benchmark input factory): ``Fourier``, ``Toeplitz``,
``Hankel``, ``Circulant``, ``Cauchy``, ``Walsh``, ``Wilkinson``,
``Laplacian``, ``Jordan``, ``Kahan``, ``Grcar``, ``Parter``, ``Pei``,
``Redheffer``, ``TriW``, ``Gear``, ``GEPPGrowth``, ``Wigner``, ``Haar``,
``Bernoulli``, ``Rademacher``, ``NormalUniformSpectrum``...

Deterministic generators ride :func:`..blas.level1.index_dependent_fill`
(device-side, layout-independent).  Random generators come in two flavors:
the host path (:mod:`.basic`) is layout-INdependent; the device-side path
here seeds a per-device stream by mesh rank inside ``shard_map`` -- exactly
the reference's "per-process PRNGs with rank-dependent seeding" (SURVEY.md
§3.5 Random), so results depend on the grid shape but never leave device
memory (the at-scale requirement).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map
from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global
from ..core.grid import Grid, default_grid
from ..blas.level1 import index_dependent_fill, shift_diagonal
from .basic import _empty, gaussian as _host_gaussian


# ---------------------------------------------------------------------
# deterministic generators (device-side, layout-independent)
# ---------------------------------------------------------------------

def fourier(n: int, grid: Grid | None = None, dtype=jnp.complex128):
    """Unitary DFT matrix (``El::Fourier``): F[i,j] = e^{-2 pi i ij/n}/sqrt(n)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    w = -2.0 * math.pi / n
    return index_dependent_fill(
        A, lambda i, j: (jnp.exp(1j * w * (i * j)) / math.sqrt(n)).astype(dtype))


def toeplitz(col, row, grid: Grid | None = None, dtype=None):
    """Toeplitz from first column ``col`` and first row ``row``
    (``El::Toeplitz``; row[0] ignored in favor of col[0])."""
    col = jnp.asarray(col)
    row = jnp.asarray(row)
    m, n = col.shape[0], row.shape[0]
    dtype = dtype or jnp.result_type(col.dtype, row.dtype)
    A = _empty(m, n, grid or default_grid(), dtype)
    ext = jnp.concatenate([row[1:][::-1], col]).astype(dtype)

    def f(i, j):
        return jnp.take(ext, jnp.clip(i - j + n - 1, 0, m + n - 2))

    return index_dependent_fill(A, f)


def hankel(col, row, grid: Grid | None = None, dtype=None):
    """Hankel from first column and last row (``El::Hankel``)."""
    col = jnp.asarray(col)
    row = jnp.asarray(row)
    m, n = col.shape[0], row.shape[0]
    dtype = dtype or jnp.result_type(col.dtype, row.dtype)
    A = _empty(m, n, grid or default_grid(), dtype)
    ext = jnp.concatenate([col, row[1:]]).astype(dtype)

    def f(i, j):
        return jnp.take(ext, jnp.clip(i + j, 0, m + n - 2))

    return index_dependent_fill(A, f)


def circulant(c, grid: Grid | None = None, dtype=None):
    """Circulant with first column ``c`` (``El::Circulant``)."""
    c = jnp.asarray(c)
    n = c.shape[0]
    dtype = dtype or c.dtype
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.take(c.astype(dtype), (i - j) % n))


def cauchy(x, y, grid: Grid | None = None, dtype=jnp.float64):
    """C[i,j] = 1/(x_i - y_j) (``El::Cauchy``)."""
    x = jnp.asarray(x, dtype)
    y = jnp.asarray(y, dtype)
    A = _empty(x.shape[0], y.shape[0], grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: 1.0 / (jnp.take(x, i) - jnp.take(y, j)))


def walsh(k: int, binary: bool = False, grid: Grid | None = None,
          dtype=jnp.float64):
    """2^k x 2^k Walsh matrix (``El::Walsh``): (-1)^{popcount(i & j)}
    (or 0/1 when ``binary``)."""
    n = 1 << k
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        bits = i & j
        pc = jnp.zeros_like(bits)
        for b in range(k):
            pc = pc + ((bits >> b) & 1)
        val = 1.0 - 2.0 * (pc % 2)
        return ((val + 1) / 2 if binary else val).astype(dtype)

    return index_dependent_fill(A, f)


def wilkinson(k: int, grid: Grid | None = None, dtype=jnp.float64):
    """(2k+1) x (2k+1) Wilkinson eigenvalue test matrix (``El::Wilkinson``)."""
    n = 2 * k + 1
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        diag = jnp.abs(i - k).astype(dtype)
        off = ((j == i + 1) | (i == j + 1)).astype(dtype)
        return jnp.where(i == j, diag, off)

    return index_dependent_fill(A, f)


def laplacian_1d(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Tridiagonal [-1, 2, -1] (``El::Laplacian`` 1-D)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j, jnp.asarray(2, dtype),
                         jnp.where(jnp.abs(i - j) == 1,
                                   jnp.asarray(-1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def laplacian_2d(nx: int, ny: int, grid: Grid | None = None,
                 dtype=jnp.float64):
    """5-point 2-D grid Laplacian of size (nx*ny)^2 (``El::Laplacian``)."""
    n = nx * ny
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        xi, yi = i % nx, i // nx
        xj, yj = j % nx, j // nx
        same = (i == j)
        horiz = (yi == yj) & (jnp.abs(xi - xj) == 1)
        vert = (xi == xj) & (jnp.abs(yi - yj) == 1)
        return jnp.where(same, jnp.asarray(4, dtype),
                         jnp.where(horiz | vert, jnp.asarray(-1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def jordan(n: int, lam, grid: Grid | None = None, dtype=jnp.float64):
    """Jordan block with eigenvalue ``lam`` (``El::Jordan``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    A = shift_diagonal(A, lam, 0)
    return shift_diagonal(A, 1, 1)


def kahan(n: int, phi: float = 0.5, grid: Grid | None = None,
          dtype=jnp.float64):
    """Kahan's ill-conditioned triangular matrix (``El::Kahan``)."""
    zeta = math.sqrt(1.0 - phi * phi)
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        zi = jnp.asarray(zeta, dtype) ** i.astype(dtype)
        return jnp.where(i == j, zi,
                         jnp.where(j > i, -phi * zi, jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def grcar(n: int, k: int = 3, grid: Grid | None = None, dtype=jnp.float64):
    """Grcar nonnormal test matrix (``El::Grcar``)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j + 1, jnp.asarray(-1, dtype),
                         jnp.where((j >= i) & (j <= i + k),
                                   jnp.asarray(1, dtype),
                                   jnp.asarray(0, dtype)))

    return index_dependent_fill(A, f)


def parter(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """P[i,j] = 1/(i - j + 1/2) (``El::Parter``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: (1.0 / (i.astype(dtype) - j.astype(dtype) + 0.5)))


def pei(n: int, alpha: float = 1.0, grid: Grid | None = None,
        dtype=jnp.float64):
    """alpha I + ones (``El::Pei``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.where(i == j, jnp.asarray(alpha + 1, dtype),
                                  jnp.asarray(1, dtype)))


def redheffer(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """R[i,j] = 1 if j == 0 or (j+1) % (i+1) == 0 (``El::Redheffer``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: ((j == 0) | ((j + 1) % (i + 1) == 0)).astype(dtype))


def triw(n: int, alpha, k: int | None = None, grid: Grid | None = None,
         dtype=jnp.float64):
    """Upper triangular with 1s on the diagonal and ``alpha`` on the next
    ``k`` superdiagonals (``El::TriW``)."""
    k = n - 1 if k is None else k
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.where(i == j, jnp.asarray(1, dtype),
                                  jnp.where((j > i) & (j <= i + k),
                                            jnp.asarray(alpha, dtype),
                                            jnp.asarray(0, dtype))))


def gear(n: int, i_off: int = 1, j_off: int = -1, grid: Grid | None = None,
         dtype=jnp.float64):
    """Gear matrix (``El::Gear``): sub/super-diagonal of ones plus two
    corner entries."""
    i_idx = abs(i_off) - 1 if i_off != 0 else 0
    j_idx = n - abs(j_off) if j_off != 0 else n - 1
    si = float(np.sign(i_off))
    sj = float(np.sign(j_off))
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        off = ((i == j + 1) | (j == i + 1)).astype(dtype)
        c1 = (i == 0) & (j == i_idx)
        c2 = (i == n - 1) & (j == j_idx)
        # corners OVERWRITE (El::Gear uses Set, not Update)
        return jnp.where(c1, jnp.asarray(si, dtype),
                         jnp.where(c2, jnp.asarray(sj, dtype), off))

    return index_dependent_fill(A, f)


def gepp_growth(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Wilkinson's GEPP growth-factor matrix (``El::GEPPGrowth``):
    identity lower triangle of -1s with a ones last column."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j, jnp.asarray(1, dtype),
                         jnp.where(j == n - 1, jnp.asarray(1, dtype),
                                   jnp.where(i > j, jnp.asarray(-1, dtype),
                                             jnp.asarray(0, dtype))))

    return index_dependent_fill(A, f)


# ---------------------------------------------------------------------
# random generators (device-side, rank-seeded per shard)
# ---------------------------------------------------------------------

def _device_random(m: int, n: int, grid: Grid, dtype, seed: int, sampler):
    """Per-shard device-side sampling: each device draws its local block
    from a stream folded by its mesh rank (the reference's rank-seeded
    per-process PRNGs).  Layout-dependent by design; never materializes
    the global matrix anywhere."""
    meta = DistMatrix(None, (m, n), MC, MR, 0, 0, grid)
    lshape = (meta.local_rows, meta.local_cols)   # per-device block

    def f():
        r = jax.lax.axis_index("mc")
        c = jax.lax.axis_index("mr")
        key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                 r * grid.width + c)
        return sampler(key, lshape)

    stor = shard_map(f, mesh=grid.mesh, in_specs=(),
                         out_specs=P("mc", "mr"), check_vma=False)()
    out = meta.with_local(stor)
    # re-zero padding to keep the library invariant
    from ..blas.level1 import _valid_mask
    return out.with_local(jnp.where(_valid_mask(out), out.local, 0))


def gaussian_device(m: int, n: int | None = None, grid: Grid | None = None,
                    dtype=jnp.float32, seed: int = 0) -> DistMatrix:
    """Device-side standard normal (``El::Gaussian`` with rank seeding)."""
    n = n or m
    grid = grid or default_grid()
    if jnp.issubdtype(dtype, jnp.complexfloating):
        def sampler(key, shape):
            kr, ki = jax.random.split(key)
            rd = jnp.zeros((), dtype).real.dtype
            return (jax.random.normal(kr, shape, rd)
                    + 1j * jax.random.normal(ki, shape, rd)).astype(dtype)
    else:
        def sampler(key, shape):
            return jax.random.normal(key, shape, dtype)
    return _device_random(m, n, grid, dtype, seed, sampler)


def uniform_device(m: int, n: int | None = None, grid: Grid | None = None,
                   dtype=jnp.float32, seed: int = 0, lo=0.0,
                   hi=1.0) -> DistMatrix:
    """Device-side uniform (``El::Uniform`` with rank seeding)."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return jax.random.uniform(key, shape, dtype, lo, hi)

    return _device_random(m, n, grid, dtype, seed, sampler)


def bernoulli(m: int, n: int | None = None, p: float = 0.5,
              grid: Grid | None = None, dtype=jnp.float32,
              seed: int = 0) -> DistMatrix:
    """0/1 Bernoulli(p) entries (``El::Bernoulli``), device-side."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return jax.random.bernoulli(key, p, shape).astype(dtype)

    return _device_random(m, n, grid, dtype, seed, sampler)


def rademacher(m: int, n: int | None = None, grid: Grid | None = None,
               dtype=jnp.float32, seed: int = 0) -> DistMatrix:
    """+-1 entries (``El::Rademacher``), device-side."""
    n = n or m
    grid = grid or default_grid()

    def sampler(key, shape):
        return (2 * jax.random.bernoulli(key, 0.5, shape).astype(dtype) - 1)

    return _device_random(m, n, grid, dtype, seed, sampler)


def wigner(n: int, grid: Grid | None = None, dtype=jnp.float64,
           seed: int = 0) -> DistMatrix:
    """Symmetric Gaussian (Wigner) matrix (``El::Wigner``)."""
    from ..blas.level1 import make_symmetric
    G = _host_gaussian(n, n, grid=grid, dtype=dtype, seed=seed)
    return make_symmetric(
        G.with_local(G.local / math.sqrt(2.0 * n)), "L",
        conj=jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating))


def haar(n: int, grid: Grid | None = None, dtype=jnp.float64,
         seed: int = 0) -> DistMatrix:
    """Haar-distributed orthogonal/unitary matrix via QR of a Gaussian
    (``El::Haar``)."""
    from ..lapack.qr import qr, explicit_q
    G = _host_gaussian(n, n, grid=grid, dtype=dtype, seed=seed)
    Ap, tau = qr(G)
    return explicit_q(Ap, tau)


def normal_uniform_spectrum(n: int, center=0.0, radius: float = 1.0,
                            grid: Grid | None = None, dtype=jnp.complex128,
                            seed: int = 0) -> DistMatrix:
    """Normal matrix with eigenvalues uniform in a disk
    (``El::NormalUniformSpectrum``): Q diag(lam) Q^H, Q Haar."""
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        raise ValueError("normal_uniform_spectrum's spectrum is genuinely "
                         "complex; pass a complex dtype")
    rng = np.random.default_rng(seed)
    rad = radius * np.sqrt(rng.uniform(size=n))
    ang = rng.uniform(0, 2 * np.pi, size=n)
    lam = center + rad * np.exp(1j * ang)
    Gq = rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    Q, _ = np.linalg.qr(Gq)
    A = (Q * lam) @ Q.conj().T
    return from_global(A.astype(np.dtype(dtype)), MC, MR,
                       grid=grid or default_grid())


# ---------------------------------------------------------------------
# gallery breadth round 5 (SURVEY.md §3.5 remaining generators)
# ---------------------------------------------------------------------

def demmel(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """D[i,j] = beta^{i-j+1}-ish highly nonnormal example (``El::Demmel``):
    B[i,j] = beta^{j-i} above the diagonal with beta = 10^{4/(n-1)}."""
    A = _empty(n, n, grid or default_grid(), dtype)
    beta = 10.0 ** (4.0 / max(n - 1, 1))

    def f(i, j):
        d = (j - i).astype(dtype)
        return jnp.where(j >= i, beta ** d, 0.0)

    return index_dependent_fill(A, f)


def druinsky_toledo(k: int, grid: Grid | None = None, dtype=jnp.float64):
    """The 2k x 2k Bunch-Kaufman growth example of Druinsky-Toledo
    (``El::DruinskyToledo``): G = [A I; I 0]-style with A the k x k
    lower-triangular accumulation of alpha powers."""
    n = 2 * k
    A = _empty(n, n, grid or default_grid(), dtype)
    phi = (1.0 + math.sqrt(17.0)) / 8.0
    alpha = jnp.asarray(phi, dtype)

    def f(i, j):
        in_tl = (i < k) & (j < k)
        tl = jnp.where(i == j, 1.0,
                       jnp.where(i > j, -(alpha ** (i - j).astype(dtype)),
                                 0.0))
        eye_tr = ((j >= k) & (i == j - k)).astype(dtype)
        eye_bl = ((i >= k) & (j == i - k)).astype(dtype)
        return jnp.where(in_tl, tl, eye_tr + eye_bl)

    return index_dependent_fill(A, f)


def egorov(fn, n: int, grid: Grid | None = None, dtype=jnp.complex128):
    """Egorov Fourier-integral-operator matrix (``El::Egorov``):
    A[i,j] = e^{i phi(i, j)} / sqrt(n) for a caller phase function."""
    A = _empty(n, n, grid or default_grid(), dtype)
    s = 1.0 / math.sqrt(n)
    return index_dependent_fill(
        A, lambda i, j: (s * jnp.exp(1j * fn(i, j))).astype(dtype))


def extended_kahan(k: int, phi: float = 0.6, mu: float = 1e-5,
                   grid: Grid | None = None, dtype=jnp.float64):
    """The 3k x 3k extended Kahan RRQR counterexample
    (``El::ExtendedKahan``): R = diag(zeta^i) * [[I, zeta_m H, 0],
    [0, phi I, mu H], [0, 0, mu I]]-type block structure with H a
    Hadamard-like reflection; built densely from the closed form."""
    n = 3 * k
    if k & (k - 1):
        raise ValueError("extended_kahan needs k a power of two")
    zeta = math.sqrt(1.0 - phi * phi)
    # Walsh-Hadamard H_k (unnormalized +-1), closed form via bit parity
    def had(i, j):
        x = jnp.bitwise_and(i.astype(jnp.int32), j.astype(jnp.int32))
        # popcount via repeated shifts (k <= 2^15 is plenty)
        cnt = jnp.zeros_like(x)
        for sbit in range(15):
            cnt = cnt + jnp.bitwise_and(x >> sbit, 1)
        return jnp.where(cnt % 2 == 0, 1.0, -1.0)

    A = _empty(n, n, grid or default_grid(), dtype)
    sk = 1.0 / math.sqrt(k)

    def f(i, j):
        bi, bj = i // k, j // k
        ii, jj = i % k, j % k
        blk00 = ((bi == 0) & (bj == 0) & (ii == jj)).astype(dtype)
        blk01 = jnp.where((bi == 0) & (bj == 1),
                          zeta * sk * had(ii, jj), 0.0)
        blk11 = jnp.where((bi == 1) & (bj == 1) & (ii == jj), phi, 0.0)
        blk12 = jnp.where((bi == 1) & (bj == 2),
                          mu * sk * had(ii, jj), 0.0)
        blk22 = jnp.where((bi == 2) & (bj == 2) & (ii == jj), mu, 0.0)
        pre = blk00 + blk01 + blk11 + blk12 + blk22
        return (zeta ** i.astype(dtype)) * pre

    return index_dependent_fill(A, f)


def fiedler(c, grid: Grid | None = None, dtype=None):
    """F[i,j] = |c_i - c_j| (``El::Fiedler``)."""
    c = jnp.asarray(c)
    n = c.shape[0]
    dtype = dtype or c.dtype
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: jnp.abs(jnp.take(c, jnp.clip(i, 0, n - 1))
                                - jnp.take(c, jnp.clip(j, 0, n - 1))
                                ).astype(dtype))


def fox_li(n: int, omega: float = 16 * math.pi,
           grid: Grid | None = None, dtype=jnp.complex128):
    """Fox-Li laser cavity integral operator (``El::FoxLi``), midpoint
    discretization on [-1, 1]: A[i,j] = sqrt(i w/pi) e^{-i w (x_i-x_j)^2} h."""
    A = _empty(n, n, grid or default_grid(), dtype)
    h = 2.0 / n
    pref = jnp.sqrt(jnp.asarray(1j * omega / math.pi))

    def f(i, j):
        xi = -1.0 + (i.astype(jnp.float64) + 0.5) * h
        xj = -1.0 + (j.astype(jnp.float64) + 0.5) * h
        return (pref * jnp.exp(-1j * omega * (xi - xj) ** 2) * h
                ).astype(dtype)

    return index_dependent_fill(A, f)


def gks(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Upper triangular with G[i,i]=1/sqrt(i+1), G[i,j]=-1/sqrt(j+1) for
    j > i (``El::GKS``, a condition-estimator counterexample)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        rsj = 1.0 / jnp.sqrt(j.astype(dtype) + 1.0)
        return jnp.where(i == j, rsj, jnp.where(j > i, -rsj, 0.0))

    return index_dependent_fill(A, f)


def hanowa(n: int, mu: float = -1.0, grid: Grid | None = None,
           dtype=jnp.float64):
    """[[mu I, -D]; [D, mu I]] with D = diag(1..n/2) (``El::Hanowa``);
    eigenvalues mu +- i k."""
    if n % 2:
        raise ValueError("hanowa needs even n")
    k = n // 2
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        d = (i % k).astype(dtype) + 1.0
        diag = jnp.where(i == j, mu, 0.0)
        tr = jnp.where((i < k) & (j == i + k), -d, 0.0)
        bl = jnp.where((i >= k) & (j == i - k), d, 0.0)
        return diag + tr + bl

    return index_dependent_fill(A, f)


def helmholtz_1d(n: int, shift: float, grid: Grid | None = None,
                 dtype=jnp.float64):
    """1-D Laplacian minus a shift (``El::Helmholtz``)."""
    return shift_diagonal(laplacian_1d(n, grid=grid, dtype=dtype), -shift)


def helmholtz_2d(nx: int, ny: int, shift: float, grid: Grid | None = None,
                 dtype=jnp.float64):
    """2-D Helmholtz (``El::Helmholtz``)."""
    return shift_diagonal(laplacian_2d(nx, ny, grid=grid, dtype=dtype),
                          -shift)


def helmholtz_3d(nx: int, ny: int, nz: int, shift: float,
                 grid: Grid | None = None, dtype=jnp.float64):
    """3-D Helmholtz on the nx*ny*nz grid (7-point stencil)."""
    return shift_diagonal(laplacian_3d(nx, ny, nz, grid=grid, dtype=dtype),
                          -shift)


def laplacian_3d(nx: int, ny: int, nz: int, grid: Grid | None = None,
                 dtype=jnp.float64):
    """Negative 3-D Dirichlet Laplacian, 7-point stencil (diag 6, off -1),
    lexicographic (x fastest) ordering (``El::Laplacian`` 3-D overload).

    Family convention: like :func:`laplacian_1d`/:func:`laplacian_2d`,
    the stencil is UNSCALED (upstream multiplies each dimension by
    hInv^2 = (n+1)^2; scale the shift accordingly when porting)."""
    n = nx * ny * nz
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        xi, yi, zi = i % nx, (i // nx) % ny, i // (nx * ny)
        xj, yj, zj = j % nx, (j // nx) % ny, j // (nx * ny)
        diag = jnp.where(i == j, 6.0, 0.0)
        ex = jnp.where((zi == zj) & (yi == yj)
                       & (jnp.abs(xi - xj) == 1), -1.0, 0.0)
        ey = jnp.where((zi == zj) & (xi == xj)
                       & (jnp.abs(yi - yj) == 1), -1.0, 0.0)
        ez = jnp.where((yi == yj) & (xi == xj)
                       & (jnp.abs(zi - zj) == 1), -1.0, 0.0)
        return (diag + ex + ey + ez).astype(dtype)

    return index_dependent_fill(A, f)


def jordan_cholesky(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """J^T J for the Jordan block J with eigenvalue 2 and unit
    superdiagonal (``El::JordanCholesky``): tridiagonal with diagonal
    (4, 5, 5, ..., 5) and off-diagonals 2 -- an SPD matrix whose
    Cholesky factor is exactly that Jordan block transposed."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        diag = jnp.where(i == j, jnp.where(i == 0, 4.0, 5.0), 0.0)
        off = jnp.where(jnp.abs(i - j) == 1, 2.0, 0.0)
        return (diag + off).astype(dtype)

    return index_dependent_fill(A, f)


def lauchli(n: int, mu: float | None = None, grid: Grid | None = None,
            dtype=jnp.float64):
    """(n+1) x n [ones_row; mu I] (``El::Lauchli``), the classic
    normal-equations ill-conditioning example."""
    mu = mu if mu is not None else math.sqrt(np.finfo(np.float64).eps)
    A = _empty(n + 1, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == 0, 1.0,
                         jnp.where(i == j + 1, mu, 0.0)).astype(dtype)

    return index_dependent_fill(A, f)


def legendre(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Jacobi (tridiagonal) matrix of the Legendre recurrence
    (``El::Legendre``): beta_k = 1/(2 sqrt(1 - (2k)^{-2})) off-diagonal."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        k = jnp.maximum(i, j).astype(dtype)       # = min+1 on the off-diag
        beta = 0.5 / jnp.sqrt(1.0 - 1.0 / (4.0 * k * k))
        return jnp.where(jnp.abs(i - j) == 1, beta, 0.0).astype(dtype)

    return index_dependent_fill(A, f)


def lotkin(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Hilbert matrix with the first row set to ones (``El::Lotkin``)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        h = 1.0 / (i.astype(dtype) + j.astype(dtype) + 1.0)
        return jnp.where(i == 0, 1.0, h)

    return index_dependent_fill(A, f)


def one_two_one(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Tridiagonal (1, 2, 1) (``El::OneTwoOne``)."""
    A = _empty(n, n, grid or default_grid(), dtype)

    def f(i, j):
        return jnp.where(i == j, 2.0,
                         jnp.where(jnp.abs(i - j) == 1, 1.0, 0.0)
                         ).astype(dtype)

    return index_dependent_fill(A, f)


def _log_eulerian(n: int) -> np.ndarray:
    """log A(n, k) for k = 0..n-1 (Eulerian numbers: permutations of n with
    k descents), via the standard recurrence
    ``A(m,k) = (k+1) A(m-1,k) + (m-k) A(m-1,k-1)`` run in log space
    (A(n, n/2) ~ n!, far beyond float range for the n this gallery targets).
    O(n^2) host-side, vectorized per row."""
    la = np.zeros(1)                              # A(1, 0) = 1
    for m in range(2, n + 1):
        prev_k = np.concatenate([la, [-np.inf]])       # A(m-1, k)
        prev_k1 = np.concatenate([[-np.inf], la])      # A(m-1, k-1)
        k = np.arange(m, dtype=np.float64)
        la = np.logaddexp(np.log(k + 1) + prev_k,
                          np.log(m - k) + prev_k1)
    return la


def riffle(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """Gilbert-Shannon-Reeds riffle-shuffle transition matrix on descent
    classes (``El::Riffle``):

        P[i,j] = 2^{-n} C(n+1, 2i-j+1) A(n,j) / A(n,i)

    with A(n,k) the Eulerian numbers.  The Eulerian normalization makes P
    row-stochastic (rows sum to 1: ``sum_i C(n+1, 2i-j+1) = 2^n`` weighted
    by the Eulerian ratio) with stationary distribution ``A(n,i)/n!`` --
    the descent law of a uniform permutation."""
    A = _empty(n, n, grid or default_grid(), dtype)
    # log-binomials + log-Eulerian numbers, precomputed host-side
    lg = np.concatenate([[0.0], np.cumsum(np.log(np.arange(1, n + 2)))])
    lgj = jnp.asarray(lg)
    lA = jnp.asarray(_log_eulerian(n)) if n > 0 else jnp.zeros(1)

    def f(i, j):
        k = 2 * (i + 1) - (j + 1)
        valid = (k >= 0) & (k <= n + 1)
        kc = jnp.clip(k, 0, n + 1)
        logbin = lgj[n + 1] - lgj[kc] - lgj[n + 1 - kc]
        return jnp.where(valid,
                         jnp.exp(logbin - n * math.log(2.0)
                                 + lA[j] - lA[i]),
                         0.0).astype(dtype)

    return index_dependent_fill(A, f)


def ris(n: int, grid: Grid | None = None, dtype=jnp.float64):
    """R[i,j] = 0.5/(n - i - j - 0.5) (``El::Ris``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: (0.5 / (n - i.astype(dtype) - j.astype(dtype)
                                - 0.5)))


def whale(n: int, grid: Grid | None = None, dtype=jnp.complex128):
    """The "whale" pseudospectrum example (Trefethen-Embree): banded
    Toeplitz with symbol z^{-4} + (3+2i) z^{-3} - (1+2i) z^{-2} + z^{-1}
    + 10 z + (3+i) z^2 + 4 z^3 + i z^4 (``El::Whale``)."""
    coef = {-4: 1.0, -3: 3.0 + 2.0j, -2: -(1.0 + 2.0j), -1: 1.0,
            1: 10.0, 2: 3.0 + 1.0j, 3: 4.0, 4: 1.0j}
    # A[i,j] = a_{i-j}: positive symbol powers sit BELOW the diagonal
    col = np.zeros(n, np.complex128)
    row = np.zeros(n, np.complex128)
    for off, v in coef.items():
        if off >= 0 and off < n:
            col[off] = v
        elif off < 0 and -off < n:
            row[-off] = v
    return toeplitz(jnp.asarray(col), jnp.asarray(row), grid=grid,
                    dtype=dtype)


def hatano_nelson(n: int, shift: float = 0.0, g: float = 0.5,
                  periodic: bool = True, grid: Grid | None = None,
                  dtype=jnp.float64, seed: int = 0):
    """Hatano-Nelson non-Hermitian localization model
    (``El::HatanoNelson``): random diagonal + e^{+-g} hopping."""
    rng = np.random.default_rng(seed)
    d = jnp.asarray(rng.uniform(-1, 1, n) - shift, dtype)
    A = _empty(n, n, grid or default_grid(), dtype)
    eg, emg = math.exp(g), math.exp(-g)

    def f(i, j):
        diag = jnp.where(i == j, jnp.take(d, jnp.clip(i, 0, n - 1)), 0.0)
        up = jnp.where(j == i + 1, eg, 0.0)
        dn = jnp.where(j == i - 1, emg, 0.0)
        wrap = 0.0
        if periodic and n > 2:
            wrap = jnp.where((i == n - 1) & (j == 0), eg, 0.0) \
                + jnp.where((i == 0) & (j == n - 1), emg, 0.0)
        return (diag + up + dn + wrap).astype(dtype)

    return index_dependent_fill(A, f)


def three_valued(m: int, n: int | None = None, p: float = 2.0 / 3.0,
                 grid: Grid | None = None, dtype=jnp.float64,
                 seed: int = 0):
    """Random {-1, 0, +1} entries: 0 w.p. 1-p, +-1 w.p. p/2 each
    (``El::ThreeValued``)."""
    n = n if n is not None else m
    rng = np.random.default_rng(seed)
    u = rng.uniform(size=(m, n))
    vals = np.where(u < p / 2, -1.0, np.where(u < p, 1.0, 0.0))
    return from_global(jnp.asarray(vals, dtype), MC, MR,
                       grid=grid or default_grid())


def kms(n: int, rho: float = 0.5, grid: Grid | None = None,
        dtype=jnp.float64):
    """Kac-Murdock-Szego Toeplitz K[i,j] = rho^{|i-j|} (``El::KMS``)."""
    A = _empty(n, n, grid or default_grid(), dtype)
    return index_dependent_fill(
        A, lambda i, j: (rho ** jnp.abs(i - j).astype(dtype)))
