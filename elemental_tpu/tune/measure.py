"""Measurement engine: time candidate configs on the real backend and
record winners in the persistent cache.

The methodology is ``perf/ab_harness.py``'s, packaged as a library: every
candidate runs IN ONE PROCESS on the same devices, timings are
min-of-reps with the host round-trip latency subtracted and each variant
is bracketed by a matmul roofline measurement so chip weather is factored
out of the comparison.  Inputs are regenerated (untimed) per rep because
the jitted steps donate their operand.

``search()`` is the CLI entry (``python -m perf.tune search``): it
pre-ranks the candidate space with the analytic cost model (cheap), times
the top slice, and atomically persists the winner as a
``tuning_cache/v1`` entry that every later ``'auto'`` resolution on the
same (op, shape-bucket, dtype, grid, backend) key picks up first.
"""
from __future__ import annotations

import dataclasses
import time

from . import cache as _cache
from .cost_model import op_flops
from .policy import explain


@dataclasses.dataclass
class Measured:
    """One timed candidate."""
    config: dict
    seconds: float
    tflops: float
    roofline_tflops: float

    def to_doc(self) -> dict:
        return {"config": dict(self.config), "seconds": self.seconds,
                "tflops": self.tflops,
                "roofline_tflops": self.roofline_tflops}


def _latency():
    import jax
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros(())
    float(tiny(t))
    return min(_rep(lambda: float(tiny(t))) for _ in range(3))


def _rep(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _roofline(lat: float, n: int = 2048):
    import jax
    import jax.numpy as jnp
    R = jax.random.normal(jax.random.PRNGKey(9), (n, n), jnp.float32)
    mm = jax.jit(lambda x: jnp.matmul(x, x,
                                      precision=jax.lax.Precision.HIGHEST))
    float(mm(R)[0, 0])
    dt = max(min(_rep(lambda: float(mm(R)[0, 0])) for _ in range(3)) - lat,
             1e-9)
    return 2 * n ** 3 / dt / 1e12


def _builders(op: str, dims, grid, dtype):
    """(make_input, step_factory) for one op; step_factory(config) returns
    a donated jitted step whose output fences the whole computation."""
    import jax
    import jax.numpy as jnp
    import elemental_tpu as el

    HI = jax.lax.Precision.HIGHEST

    def dm(a, m, n):
        return el.DistMatrix(a, (m, n), el.MC, el.MR, 0, 0, grid)

    if op == "cholesky":
        n = dims[0]

        @jax.jit
        def gen():
            G = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype)
            return jnp.matmul(G, G.T) / n + n * jnp.eye(n, dtype=dtype)

        def make():
            return dm(gen(), n, n)

        def factory(cfg):
            return jax.jit(lambda a: el.cholesky(
                a, nb=cfg.get("nb"), lookahead=cfg.get("lookahead", True),
                crossover=cfg.get("crossover"),
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"),
                precision=HI).local,
                donate_argnums=0)
        return make, factory
    if op == "lu":
        m, n = dims[0], dims[-1]
        gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(1),
                                                (m, n), dtype))

        def make():
            return dm(gen(), m, n)

        def factory(cfg):
            return jax.jit(lambda a: tuple(el.lu(
                a, nb=cfg.get("nb"), lookahead=cfg.get("lookahead", True),
                crossover=cfg.get("crossover"),
                panel=cfg.get("panel") or "classic",
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"), precision=HI)),
                donate_argnums=0)
        return make, factory
    if op == "qr":
        m, n = dims[0], dims[-1]
        gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(2),
                                                (m, n), dtype))

        def make():
            return dm(gen(), m, n)

        def factory(cfg):
            return jax.jit(lambda a: tuple(el.qr(
                a, nb=cfg.get("nb"), panel=cfg.get("panel") or "classic",
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"), precision=HI)),
                donate_argnums=0)
        return make, factory
    if op == "trsm":
        m, n = dims[0], dims[-1]

        @jax.jit
        def gen():
            a = jax.random.normal(jax.random.PRNGKey(3), (m, m), dtype)
            a = jnp.tril(a) + m * jnp.eye(m, dtype=dtype)   # well-conditioned
            b = jax.random.normal(jax.random.PRNGKey(4), (m, n), dtype)
            return a, b

        def make():
            a, b = gen()
            return (dm(a, m, m), dm(b, m, n))

        def factory(cfg):
            return jax.jit(lambda ab: el.trsm(
                "L", "L", "N", ab[0], ab[1], nb=cfg.get("nb"),
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"),
                precision=HI).local,
                donate_argnums=0)
        return make, factory
    if op == "herk":
        m, k = dims[0], dims[-1]
        gen = jax.jit(lambda: jax.random.normal(jax.random.PRNGKey(5),
                                                (m, k), dtype))

        def make():
            return dm(gen(), m, k)

        def factory(cfg):
            return jax.jit(lambda a: el.herk(
                "L", a, nb=cfg.get("nb"),
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"),
                precision=HI).local,
                donate_argnums=0)
        return make, factory
    if op == "gemm":
        m, k, n = dims

        @jax.jit
        def gen():
            a = jax.random.normal(jax.random.PRNGKey(6), (m, k), dtype)
            b = jax.random.normal(jax.random.PRNGKey(7), (k, n), dtype)
            return a, b

        def make():
            a, b = gen()
            return (dm(a, m, k), dm(b, k, n))

        def factory(cfg):
            return jax.jit(lambda ab: el.gemm(
                ab[0], ab[1], alg=cfg.get("alg", "auto"),
                nb=cfg.get("nb"),
                comm_precision=cfg.get("comm_precision"),
                redist_path=cfg.get("redist_path"),
                precision=HI).local,
                donate_argnums=0)
        return make, factory
    raise KeyError(f"no measurement builder for op {op!r}")


def measure_candidates(op: str, dims, grid, dtype, candidates,
                       reps: int = 3, verbose: bool = False) -> list:
    """Time each candidate config (roofline-bracketed); best-first list."""
    import jax
    flops = op_flops(op, dims)
    make, factory = _builders(op, dims, grid, dtype)
    lat = _latency()
    out = []
    for cfg in candidates:
        step = factory(cfg)
        first = step(make())                       # compile + warm
        jax.block_until_ready(first)
        del first
        r0 = _roofline(lat)
        times = []
        for _ in range(reps):
            A = make()
            jax.block_until_ready(A)
            t0 = time.perf_counter()
            o = step(A)
            jax.block_until_ready(o)
            times.append(time.perf_counter() - t0)
        del o
        r1 = _roofline(lat)
        dt = max(min(times) - lat, 1e-9)
        m = Measured(config=dict(cfg), seconds=dt, tflops=flops / dt / 1e12,
                     roofline_tflops=0.5 * (r0 + r1))
        out.append(m)
        if verbose:
            print(f"  {str(cfg):60s} {dt * 1e3:9.2f} ms "
                  f"{m.tflops:7.3f} TFLOP/s (roof {m.roofline_tflops:.2f})",
                  flush=True)
        del step
    out.sort(key=lambda m: m.seconds)
    return out


def search(op: str, dims, grid, dtype, requested: dict | None = None,
           top: int = 8, reps: int = 3, write_cache: bool = True,
           verbose: bool = False):
    """Cost-model-pre-ranked measurement sweep; persists the winner.

    Returns ``(winner: Measured, all_measured: list, key)``.  The cache
    entry records the measured config with ``source='measured'`` so
    subsequent ``'auto'`` resolutions on this key skip the cost model.
    """
    ctx, scored = explain(op, gshape=dims, dtype=dtype, grid=grid,
                          requested=requested)
    cands = [b.config for b in scored[:max(1, top)]]
    if verbose:
        print(f"{op} {tuple(dims)} on {ctx.grid_shape[0]}x"
              f"{ctx.grid_shape[1]} {ctx.backend}: measuring "
              f"{len(cands)}/{len(scored)} cost-ranked candidates",
              flush=True)
    measured = measure_candidates(op, dims, grid, dtype, cands, reps=reps,
                                  verbose=verbose)
    winner = measured[0]
    key = _cache.make_key(op, ctx.dims, ctx.dtype, ctx.grid_shape,
                          ctx.backend)
    if write_cache:
        _cache.save(key, winner.config, source="measured",
                    metric={"seconds": winner.seconds,
                            "tflops": winner.tflops,
                            "roofline_tflops": winner.roofline_tflops})
        from .policy import clear_memo
        clear_memo()                       # new winner visible immediately
    return winner, measured, key


__all__ = ["Measured", "measure_candidates", "search"]
