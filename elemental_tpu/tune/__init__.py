"""Autotuning subsystem (ISSUE 4): pick driver knobs per problem instead
of per call site.

Four layers, consulted in order by a driver that receives ``'auto'``:

  :mod:`.knobs`       what is tunable and which configs are legal
  :mod:`.cache`       persistent ``tuning_cache/v1`` measured winners
                      (``$ELEMENTAL_TPU_TUNE_CACHE`` overrides the dir)
  :mod:`.cost_model`  analytic scoring -- abstract driver traces (ring-model
                      collective bytes) + an MXU-roofline flop term; works
                      cold on CPU with no device execution
  :mod:`.policy`      resolution: explicit wins > cache > cost model; also
                      the canonical :func:`blocksize_policy`

:mod:`.measure` (imported lazily; it compiles and runs on the real
backend) times candidates ab_harness-style and records winners.  CLI:
``python -m perf.tune {search,show,clear,explain}``.
"""
from .knobs import (DEFAULT_CROSSOVER, GEMM_ALGS, NB_LADDER, OPS,
                    TuneContext, candidate_configs, nb_candidates, op_names)
from .cache import (SCHEMA as CACHE_SCHEMA, ENV_DIR as CACHE_ENV_DIR,
                    CacheKey, cache_dir, clear as clear_cache,
                    entries as cache_entries, load as cache_load,
                    make_key, save as cache_save, scan as cache_scan,
                    shape_bucket)
from .policy import (Resolution, blocksize_policy, clear_memo, explain,
                     is_auto, resolve, resolve_knobs, wants_auto)

__all__ = [
    "DEFAULT_CROSSOVER", "GEMM_ALGS", "NB_LADDER", "OPS", "TuneContext",
    "candidate_configs", "nb_candidates", "op_names",
    "CACHE_SCHEMA", "CACHE_ENV_DIR", "CacheKey", "cache_dir", "clear_cache",
    "cache_entries", "cache_load", "make_key", "cache_save", "cache_scan",
    "shape_bucket",
    "Resolution", "blocksize_policy", "clear_memo", "explain", "is_auto",
    "resolve", "resolve_knobs", "wants_auto",
]
