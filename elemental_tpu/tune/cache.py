"""Persistent tuning cache: versioned ``tuning_cache/v1`` JSON entries.

One JSON file per key under the cache directory; the key is
``(op, shape-bucket, dtype, grid, backend)`` -- shape dims are bucketed to
the next power of two so near-identical problems share an entry.  Layout:

    ~/.cache/elemental_tpu/tuning/              (default; override with
    $ELEMENTAL_TPU_TUNE_CACHE)
      cholesky__b32768x32768__float32__g2x2__tpu.json

    {"schema": "tuning_cache/v1",
     "op": "cholesky", "bucket": [32768, 32768], "dtype": "float32",
     "grid": [2, 2], "backend": "tpu",
     "config": {"nb": 2048, "lookahead": true, "crossover": 4096},
     "source": "measured",            # who wrote it (measured | manual)
     "metric": {"seconds": ..., "tflops": ...},       # optional
     "created": 1754300000.0}

Writes are ATOMIC (same-directory temp file + ``os.replace``) so a crashed
or concurrent ``perf.tune search`` never leaves a torn entry.  Reads are
defensive: a missing file, unparsable JSON, a schema-version mismatch, or
key fields that do not match the request all return ``None`` (the resolver
then falls back to the cost model) -- a stale v0 cache can never steer a
v1 library.

Observability (ISSUE 5): every :func:`load` outcome is counted on the
current metrics registry as ``tune_cache_events{op, event}`` with event
one of ``hit`` / ``miss`` / ``unparsable`` / ``stale_schema`` /
``key_mismatch`` (writes count as ``write``), and :func:`scan` reports
per-file validity -- ``python -m perf.tune show`` surfaces both, so a
silently rejected stale cache is no longer invisible.

Unwritable directories (ISSUE 7): a read-only filesystem or a bad
``$ELEMENTAL_TPU_TUNE_CACHE`` must never fail a solve -- ``'auto'``
resolution can trigger a measured-winner write MID-DRIVER.  :func:`save`
therefore degrades gracefully: on any ``OSError`` it warns ONCE per
directory (``RuntimeWarning``) and falls back to an in-process memory
cache, which :func:`load` consults after a file miss; the outcomes are
counted as ``write_fallback`` / ``mem_hit`` events.
"""
from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import warnings

from ..obs import metrics as _metrics

SCHEMA = "tuning_cache/v1"

#: schema tag of measured redistribution machine constants (ISSUE 13):
#: per-(grid, backend) alpha (seconds/round) and bandwidth (bytes/s)
#: fitted by ``python -m perf.redist_bench --record`` and consulted by the
#: engine's ``path='auto'`` arbitration before the static ring model
REDIST_SCHEMA = "redist_constants/v1"

#: environment override for the cache directory
ENV_DIR = "ELEMENTAL_TPU_TUNE_CACHE"

_DEFAULT_DIR = os.path.join("~", ".cache", "elemental_tpu", "tuning")


def cache_dir() -> str:
    """The active cache directory (env override first; not created here)."""
    return os.path.expanduser(os.environ.get(ENV_DIR, _DEFAULT_DIR))


def shape_bucket(dims) -> tuple:
    """Per-dimension next-power-of-two bucket (>= 1)."""
    return tuple(1 << max(0, int(d) - 1).bit_length() if d > 1 else 1
                 for d in dims)


@dataclasses.dataclass(frozen=True)
class CacheKey:
    op: str
    bucket: tuple
    dtype: str
    grid_shape: tuple
    backend: str
    #: optional namespace (ISSUE 19): a fleet member's tuner constants
    #: live under its own prefix so two same-shaped grids in one pool
    #: can hold DIFFERENT measured winners (e.g. one grid re-swept after
    #: a breaker trip).  Filename-only -- the document body is unchanged
    #: and an un-namespaced reader never sees namespaced entries.
    ns: str = ""

    def filename(self) -> str:
        b = "x".join(str(d) for d in self.bucket)
        r, c = self.grid_shape
        base = f"{self.op}__b{b}__{self.dtype}__g{r}x{c}__{self.backend}.json"
        return f"{self.ns}__{base}" if self.ns else base

    def path(self) -> str:
        return os.path.join(cache_dir(), self.filename())


def make_key(op: str, dims, dtype: str, grid_shape, backend: str,
             ns: str = "") -> CacheKey:
    return CacheKey(op=op, bucket=shape_bucket(dims), dtype=str(dtype),
                    grid_shape=tuple(grid_shape), backend=str(backend),
                    ns=str(ns))


#: in-process fallback entries (keyed by filename) for sessions whose
#: cache directory is unwritable; loads consult it after a file miss
_MEM_FALLBACK: dict = {}

#: monotone in-process write generation: bumped by every :func:`save` /
#: :func:`clear` so consumers that MEMOIZE derived state (the serve
#: executor's tuner-provenance executable keys, ISSUE 14) can detect a
#: tuner re-sweep cheaply without re-reading cache files on every call
_EPOCH: int = 0


def epoch() -> int:
    """The in-process tuning-cache write generation (see ``_EPOCH``)."""
    return _EPOCH


def _bump_epoch() -> None:
    global _EPOCH
    _EPOCH += 1

#: directories already warned about (warn ONCE per dir per process)
_WARNED_DIRS: set = set()


def _warn_unwritable(d: str, exc: OSError) -> None:
    if d in _WARNED_DIRS:
        return
    _WARNED_DIRS.add(d)
    warnings.warn(
        f"elemental_tpu tuning cache directory {d!r} is not writable "
        f"({exc!s}); falling back to an in-process memory cache for this "
        f"session (set ${ENV_DIR} to a writable path to persist winners)",
        RuntimeWarning, stacklevel=3)


def save(key: CacheKey, config: dict, source: str = "measured",
         metric: dict | None = None) -> str:
    """Atomically persist a winner config for ``key``; returns the path.

    NEVER raises on an unwritable directory: the entry falls back to the
    in-process memory cache (warn-once + ``write_fallback`` event) so a
    mid-solve measured-winner write cannot take the solve down."""
    _bump_epoch()
    doc = {"schema": SCHEMA, "op": key.op, "bucket": list(key.bucket),
           "dtype": key.dtype, "grid": list(key.grid_shape),
           "backend": key.backend, "config": dict(config), "source": source,
           "created": time.time()}
    if metric:
        doc["metric"] = dict(metric)
    d = cache_dir()
    path = key.path()
    try:
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tune_", suffix=".tmp")
    except OSError as exc:
        _warn_unwritable(d, exc)
        _MEM_FALLBACK[key.filename()] = doc
        _metrics.inc("tune_cache_events", op=key.op, event="write_fallback")
        return path
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)            # atomic on POSIX
    except OSError as exc:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        _warn_unwritable(d, exc)
        _MEM_FALLBACK[key.filename()] = doc
        _metrics.inc("tune_cache_events", op=key.op, event="write_fallback")
        return path
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _metrics.inc("tune_cache_events", op=key.op, event="write")
    return path


def load(key: CacheKey) -> dict | None:
    """The cached document for ``key``, or None when absent/invalid.

    Rejected (returning None, never raising): unreadable or unparsable
    files, a ``schema`` other than ``tuning_cache/v1``, and documents whose
    op/bucket/dtype/grid/backend fields disagree with the key (e.g. a file
    copied between machines or renamed by hand).  Each outcome is counted
    as ``tune_cache_events{op, event}`` on the current metrics registry."""
    path = key.path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        mem = _MEM_FALLBACK.get(key.filename())
        if mem is not None:
            _metrics.inc("tune_cache_events", op=key.op, event="mem_hit")
            return mem
        _metrics.inc("tune_cache_events", op=key.op, event="miss")
        return None
    except ValueError:
        _metrics.inc("tune_cache_events", op=key.op, event="unparsable")
        return None
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        _metrics.inc("tune_cache_events", op=key.op, event="stale_schema")
        return None
    if (doc.get("op") != key.op
            or tuple(doc.get("bucket", ())) != key.bucket
            or doc.get("dtype") != key.dtype
            or tuple(doc.get("grid", ())) != key.grid_shape
            or doc.get("backend") != key.backend
            or not isinstance(doc.get("config"), dict)):
        _metrics.inc("tune_cache_events", op=key.op, event="key_mismatch")
        return None
    _metrics.inc("tune_cache_events", op=key.op, event="hit")
    return doc


# ---------------------------------------------------------------------
# measured redistribution constants (redist_constants/v1, ISSUE 13)
# ---------------------------------------------------------------------

#: per-process memo of loaded constants docs, keyed (dir, filename);
#: invalidated by save_redist_constants so a freshly recorded fit takes
#: effect immediately (the engine consults these on EVERY 'auto' call)
_REDIST_MEMO: dict = {}


def redist_constants_filename(grid_shape, backend: str) -> str:
    r, c = grid_shape
    return f"redist_constants__g{r}x{c}__{backend}.json"


def save_redist_constants(grid_shape, backend: str, alpha_s: float,
                          bw_bytes_per_s: float, nsamples: int = 0,
                          metric: dict | None = None) -> str:
    """Atomically persist measured alpha/beta machine constants for one
    (grid, backend); returns the path.  Same unwritable-directory
    degradation as :func:`save` (warn once, in-process fallback)."""
    grid_shape = tuple(int(v) for v in grid_shape)
    doc = {"schema": REDIST_SCHEMA, "grid": list(grid_shape),
           "backend": str(backend), "alpha_s": float(alpha_s),
           "bw_bytes_per_s": float(bw_bytes_per_s),
           "nsamples": int(nsamples), "created": time.time()}
    if metric:
        doc["metric"] = dict(metric)
    d = cache_dir()
    name = redist_constants_filename(grid_shape, backend)
    path = os.path.join(d, name)
    _REDIST_MEMO.pop((d, name), None)
    try:
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".redist_", suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)            # atomic on POSIX
    except OSError as exc:
        _warn_unwritable(d, exc)
        _MEM_FALLBACK[name] = doc
        _metrics.inc("tune_cache_events", op="redist_constants",
                     event="write_fallback")
        return path
    _metrics.inc("tune_cache_events", op="redist_constants", event="write")
    return path


def load_redist_constants(grid_shape, backend: str) -> dict | None:
    """The measured constants doc for (grid, backend), or None.

    Defensive like :func:`load`: unreadable/unparsable files, a schema
    other than ``redist_constants/v1``, mismatched grid/backend fields,
    or non-finite/non-positive constants all return None (the engine then
    falls back to the static ring model).  Results are memoized per
    (directory, file) -- 'auto' arbitration consults this on every call."""
    grid_shape = tuple(int(v) for v in grid_shape)
    d = cache_dir()
    name = redist_constants_filename(grid_shape, backend)
    memo_key = (d, name)
    if memo_key in _REDIST_MEMO:
        return _REDIST_MEMO[memo_key]
    doc = None
    try:
        with open(os.path.join(d, name)) as f:
            doc = json.load(f)
    except OSError:
        doc = _MEM_FALLBACK.get(name)
    except ValueError:
        _metrics.inc("tune_cache_events", op="redist_constants",
                     event="unparsable")
        doc = None
    if doc is not None:
        if (not isinstance(doc, dict)
                or doc.get("schema") != REDIST_SCHEMA
                or tuple(doc.get("grid", ())) != grid_shape
                or doc.get("backend") != backend):
            _metrics.inc("tune_cache_events", op="redist_constants",
                         event="stale_schema")
            doc = None
        else:
            try:
                a, bw = float(doc["alpha_s"]), float(doc["bw_bytes_per_s"])
                ok = a >= 0 and bw > 0 and a == a and bw == bw \
                    and a != float("inf") and bw != float("inf")
            except (KeyError, TypeError, ValueError):
                ok = False
            if not ok:
                _metrics.inc("tune_cache_events", op="redist_constants",
                             event="key_mismatch")
                doc = None
    _REDIST_MEMO[memo_key] = doc
    return doc


def clear_redist_constants_memo() -> None:
    """Drop the in-process constants memo (tests that swap cache dirs or
    rewrite files out-of-band call this between phases)."""
    _REDIST_MEMO.clear()


def scan() -> tuple:
    """(valid docs, rejects) across the whole cache directory.

    Valid docs carry a ``_file`` key; rejects are ``{"file", "reason"}``
    with reason ``unparsable`` / ``stale_schema`` (per-file validity for
    ``perf.tune show`` -- the key-field check needs a request key, so a
    renamed-but-well-formed file only surfaces as ``key_mismatch`` at
    :func:`load` time).  Rejects are also counted on the metrics
    registry."""
    d = cache_dir()
    out, rejects = [], []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out, rejects
    for name in names:
        if not name.endswith(".json"):
            continue
        if name.startswith("redist_constants__"):
            continue                     # machine constants, not winners
        op = name.split("__", 1)[0]
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            rejects.append({"file": name, "reason": "unparsable"})
            _metrics.inc("tune_cache_events", op=op, event="unparsable")
            continue
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            rejects.append({"file": name, "reason": "stale_schema"})
            _metrics.inc("tune_cache_events", op=op, event="stale_schema")
            continue
        doc["_file"] = name
        out.append(doc)
    return out, rejects


def entries() -> list:
    """All valid cache documents currently on disk (sorted by filename)."""
    return scan()[0]


def clear(op: str | None = None) -> int:
    """Delete cache entries (all, or only those of ``op``); returns count.
    In-process fallback entries (unwritable-dir sessions) clear too."""
    _bump_epoch()
    for name in [n for n in _MEM_FALLBACK
                 if op is None or n.startswith(f"{op}__")]:
        del _MEM_FALLBACK[name]
    d = cache_dir()
    removed = 0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".json"):
            continue
        if op is not None and not name.startswith(f"{op}__"):
            continue
        try:
            os.unlink(os.path.join(d, name))
            removed += 1
        except OSError:
            pass
    return removed
