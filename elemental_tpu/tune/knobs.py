"""Knob-space registry: which knobs each distributed driver exposes and
which configurations of them are legal.

One :class:`OpSpace` per tunable driver (``cholesky``, ``lu``, ``qr``,
``gemm``, ``trsm``, ``herk``) describes

  * the knob names the driver accepts as ``'auto'`` (``nb``, and for the
    factorizations ``lookahead``/``crossover``, for gemm ``alg``),
  * a candidate enumerator producing the LEGAL configurations for a
    concrete problem context (shape, dtype, grid) -- grain-aligned ``nb``
    ladders clamped to the extent, the replicated-C memory guard on
    ``gemm(alg='dot')``, and so on.

The registry is pure metadata: no jax import, no tracing, no device
execution.  The cost model (:mod:`.cost_model`) scores these candidates;
the resolver (:mod:`.policy`) picks one; explicit (non-``'auto'``) knob
values pin their dimension of the product space and always win.
"""
from __future__ import annotations

import dataclasses
import itertools
import math

from ..core.view import round_up

#: the nb ladder every blocked driver sweeps; mirrors the A/B-measured
#: ladder of ``perf/ab_harness.py`` (nb=2048 is the measured v5e winner at
#: N=32k; small entries matter on CPU-sized problems and small grids)
NB_LADDER = (64, 128, 256, 512, 1024, 2048, 4096)

#: default tail crossover-to-local threshold of the look-ahead schedules
#: (``lapack.cholesky._CROSSOVER`` == ``lapack.lu._CROSSOVER`` == 4096;
#: kept literal here so the registry stays import-light -- re-pinned by
#: ``tests/tune`` against the driver constants)
DEFAULT_CROSSOVER = 4096

#: replicated-C element cap for ``gemm(alg='dot')`` on p > 1 (the SUMMA-Dot
#: schedule replicates the full C on every device; same guard the old
#: in-driver heuristic used)
DOT_ELEMENT_CAP = 1 << 22


@dataclasses.dataclass(frozen=True)
class TuneContext:
    """The concrete problem a resolution runs against."""
    op: str
    dims: tuple            # driver dims: (n, n) / (m, n) / gemm (m, k, n)
    dtype: str             # canonical dtype name ("float32", ...)
    grid_shape: tuple      # (r, c)
    backend: str           # "cpu" / "tpu" / "gpu"

    @property
    def grid_size(self) -> int:
        r, c = self.grid_shape
        return r * c

    @property
    def grain(self) -> int:
        r, c = self.grid_shape
        return math.lcm(r, c)

    @property
    def extent(self) -> int:
        """The panel-sweep extent the nb ladder is clamped against."""
        if self.op == "gemm":
            return max(self.dims)
        if self.op == "herk":
            return self.dims[1]           # k-panel sweep
        if self.op in ("cholesky", "trsm"):
            return self.dims[0]           # row sweep
        return min(self.dims)             # lu/qr: min(m, n) diagonal sweep


def nb_candidates(ctx: TuneContext) -> tuple:
    """Grain-aligned nb ladder clamped to the problem extent (plus the
    extent/2 and extent/4 rungs so small problems still have a sweep)."""
    grain = ctx.grain
    cap = round_up(max(ctx.extent, 1), grain)
    raw = list(NB_LADDER) + [cap, cap // 2, cap // 4]
    vals = {min(round_up(max(v, grain), grain), cap) for v in raw if v >= 1}
    return tuple(sorted(vals))


def _factorization_space(ctx: TuneContext, pinned: dict) -> list:
    nbs = (pinned["nb"],) if "nb" in pinned else nb_candidates(ctx)
    las = (pinned["lookahead"],) if "lookahead" in pinned else (True, False)
    xos = (pinned["crossover"],) if "crossover" in pinned \
        else (DEFAULT_CROSSOVER, 0)
    out = []
    for nb, la, xo in itertools.product(nbs, las, xos):
        if not la and xo not in (0, None):
            continue                # classic never crosses over (driver default)
        out.append({"nb": nb, "lookahead": la, "crossover": xo})
    return out


def _nb_only_space(ctx: TuneContext, pinned: dict) -> list:
    nbs = (pinned["nb"],) if "nb" in pinned else nb_candidates(ctx)
    return [{"nb": nb} for nb in nbs]


#: wire-precision modes of the quantized-collective path (ISSUE 8, the
#: EQuARX direction): ``None`` = full precision (bit-identical, the
#: candidate-order tie-break leader), 'bf16' = cast wire (2x fewer
#: bytes), 'int8' = block-scaled wire (4x on the gather family).  Kept in
#: sync with ``redist.quantize.COMM_PRECISIONS`` (pinned by tests/tune).
COMM_PRECISIONS = (None, "bf16", "int8")


def _with_comm_precision(space: list, ctx: TuneContext, pinned: dict) -> list:
    """Cross every candidate with the legal comm_precision values.

    An explicitly pinned value (INCLUDING ``None``, the bit-identical
    default every driver passes when the user did not opt in) freezes the
    dimension; otherwise single-device grids enumerate only ``None`` (no
    collectives execute, so quantization would cost accuracy for zero
    byte savings) and multi-device grids sweep the full mode set."""
    if "comm_precision" in pinned:
        chosen = (pinned["comm_precision"],)
    elif ctx.grid_size <= 1:
        chosen = (None,)
    else:
        chosen = COMM_PRECISIONS
    return [{**cfg, "comm_precision": cp} for cfg in space for cp in chosen]


#: redistribution routes of the one-shot plan compiler (ISSUE 12, the
#: COSTA direction): ``None`` = the factored multi-hop chain (bit-identical
#: baseline, the candidate-order tie-break leader), ``'direct'`` = the
#: compiled single-collective plan (``redist.plan``).  Kept in sync with
#: ``redist.engine.REDIST_PATHS`` (pinned by tests/tune).
REDIST_PATHS = (None, "direct")


def _with_redist_path(space: list, ctx: TuneContext, pinned: dict) -> list:
    """Cross every candidate with the legal redist_path values.

    An explicitly pinned value (INCLUDING ``None``) freezes the
    dimension; otherwise single-device grids enumerate only ``None``
    (every plan is 'local' there -- no collective to save) and
    multi-device grids sweep chain vs direct."""
    if "redist_path" in pinned:
        chosen = (pinned["redist_path"],)
    elif ctx.grid_size <= 1:
        chosen = (None,)
    else:
        chosen = REDIST_PATHS
    return [{**cfg, "redist_path": rp} for cfg in space for rp in chosen]


#: panel-kernel implementations of the factorization critical path
#: (ISSUE 17): ``None``/'xla' = the status-quo op-ladder panels (the
#: candidate-order tie-break leader), 'pallas' = the fused VMEM-resident
#: kernels of :mod:`..kernels`.  Kept in sync with
#: ``kernels.PANEL_IMPLS`` (pinned by tests/tune) but mirrored here as a
#: literal so the registry stays import-light.
PANEL_IMPLS = ("xla", "pallas")


def _with_panel_impl(space: list, ctx: TuneContext, pinned: dict) -> list:
    """Cross every candidate with the legal panel_impl values.

    An explicitly pinned value (INCLUDING ``None``, the status-quo XLA
    ladder every driver passes when the user did not opt in) freezes
    the dimension; otherwise complex dtypes enumerate only 'xla' (the
    fused kernels are real-only and the dispatch would gate them back
    anyway) and real dtypes sweep both implementations -- the cost
    model's launch-count term decides per backend (fused wins on TPU;
    interpret-mode pallas never wins off-TPU)."""
    if "panel_impl" in pinned:
        chosen = (pinned["panel_impl"],)
    elif "complex" in str(ctx.dtype):
        chosen = ("xla",)
    else:
        chosen = PANEL_IMPLS
    return [{**cfg, "panel_impl": pi} for cfg in space for pi in chosen]


#: panel strategies of the pivoted/reflector factorizations (ISSUE 6):
#: 'classic' = replicated column-at-a-time panel (the stability baseline),
#: the alternative = communication-avoiding tree panel (CALU tournament
#: pivoting for lu, TSQR R-reduction for qr).  'classic' leads so the
#: deterministic tie-break keeps it on grids where the tree panel
#: degenerates (single grid row: the slab IS the panel).
LU_PANELS = ("classic", "calu")
QR_PANELS = ("classic", "tsqr")


def _with_panels(space: list, ctx: TuneContext, pinned: dict,
                 panels: tuple) -> list:
    chosen = (pinned["panel"],) if "panel" in pinned else panels
    out = []
    for cfg in space:
        for pan in chosen:
            if pan not in (panels[0],) and ctx.grid_shape[0] <= 1 \
                    and "panel" not in pinned:
                continue        # tree panel == classic on single-row grids
            out.append({**cfg, "panel": pan})
    return out


def _cholesky_space(ctx: TuneContext, pinned: dict) -> list:
    return _with_panel_impl(
        _with_redist_path(
            _with_comm_precision(_factorization_space(ctx, pinned), ctx,
                                 pinned), ctx, pinned), ctx, pinned)


def _lu_space(ctx: TuneContext, pinned: dict) -> list:
    base = {k: v for k, v in pinned.items()
            if k not in ("panel", "panel_impl")}
    return _with_panel_impl(
        _with_redist_path(
            _with_comm_precision(
                _with_panels(_factorization_space(ctx, base), ctx, pinned,
                             LU_PANELS), ctx, pinned), ctx, pinned),
        ctx, pinned)


def _qr_space(ctx: TuneContext, pinned: dict) -> list:
    base = {k: v for k, v in pinned.items()
            if k not in ("panel", "panel_impl")}
    return _with_panel_impl(
        _with_redist_path(
            _with_comm_precision(
                _with_panels(_nb_only_space(ctx, base), ctx, pinned,
                             QR_PANELS), ctx, pinned), ctx, pinned),
        ctx, pinned)


def _nb_comm_space(ctx: TuneContext, pinned: dict) -> list:
    return _with_redist_path(
        _with_comm_precision(_nb_only_space(ctx, pinned), ctx, pinned),
        ctx, pinned)


#: gemm candidate order doubles as the deterministic tie-break: on a 1x1
#: grid every alg has zero comm cost and 'dot' early-outs to ONE local
#: matmul (the pinned ``_summa_dot`` p==1 fast path), so it leads;
#: 'slice' (ISSUE 16) appends LAST so every pre-existing exact tie keeps
#: its historical winner and 'slice' only takes geometries it strictly
#: wins (tall-skinny / non-square grids).
GEMM_ALGS = ("dot", "C", "A", "B", "gspmd", "slice")


def _gemm_space(ctx: TuneContext, pinned: dict) -> list:
    m, k, n = ctx.dims
    algs = (pinned["alg"],) if "alg" in pinned else GEMM_ALGS
    nbs = (pinned["nb"],) if "nb" in pinned else nb_candidates(ctx)
    out = []
    for alg in algs:
        if alg == "dot" and ctx.grid_size > 1 and m * n > DOT_ELEMENT_CAP \
                and "alg" not in pinned:
            continue                      # replicated-C memory guard
        if alg == "slice" and ctx.grid_size > 1 and "alg" not in pinned:
            # replicated-operand memory guard: the mode rule broadcasts
            # the small operand ([STAR,STAR]); skip when even that is
            # too large to replicate per device.
            from ..redist.plan import slice_row_mode
            repl = k * n if slice_row_mode(m, n, ctx.grid_shape) else m * k
            if repl > DOT_ELEMENT_CAP:
                continue
        for nb in nbs:
            out.append({"alg": alg, "nb": nb})
            if alg in ("dot", "gspmd", "slice"):
                break                     # nb is dead for the one-shot algs
    return _with_redist_path(_with_comm_precision(out, ctx, pinned), ctx,
                             pinned)


@dataclasses.dataclass(frozen=True)
class OpSpace:
    """Registry entry: the knobs of one driver + its candidate enumerator."""
    op: str
    knobs: tuple                   # knob names accepted as 'auto'
    space: callable                # (ctx, pinned) -> list[config dict]


OPS = {
    "cholesky": OpSpace("cholesky",
                        ("nb", "lookahead", "crossover", "comm_precision",
                         "redist_path", "panel_impl"),
                        _cholesky_space),
    "lu": OpSpace("lu", ("nb", "lookahead", "crossover", "panel",
                         "comm_precision", "redist_path", "panel_impl"),
                  _lu_space),
    "qr": OpSpace("qr", ("nb", "panel", "comm_precision", "redist_path",
                         "panel_impl"), _qr_space),
    "gemm": OpSpace("gemm", ("alg", "nb", "comm_precision", "redist_path"),
                    _gemm_space),
    "trsm": OpSpace("trsm", ("nb", "comm_precision", "redist_path"),
                    _nb_comm_space),
    "herk": OpSpace("herk", ("nb", "comm_precision", "redist_path"),
                    _nb_comm_space),
}


def op_names() -> list:
    return sorted(OPS)


def candidate_configs(ctx: TuneContext, pinned: dict | None = None) -> list:
    """All legal configurations of ``ctx.op`` with the ``pinned`` knobs
    (explicit, non-'auto' values) frozen at their requested value."""
    spec = OPS.get(ctx.op)
    if spec is None:
        raise KeyError(f"unknown tunable op {ctx.op!r}; known: {op_names()}")
    pinned = dict(pinned or {})
    unknown = set(pinned) - set(spec.knobs)
    if unknown:
        raise KeyError(f"{ctx.op} has no knob(s) {sorted(unknown)}; "
                       f"knobs: {spec.knobs}")
    return spec.space(ctx, pinned)
