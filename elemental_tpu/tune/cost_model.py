"""Analytic cost model: score a knob configuration WITHOUT executing it.

Two ingredients, matching how the library's cost actually splits:

* **Communication** -- for the blocked factorizations and solves
  (``cholesky``/``lu``/``qr``/``trsm``/``herk``) the schedule is what the
  knobs change, so the model does not guess it: the candidate is traced
  ABSTRACTLY through the real driver (``jax.make_jaxpr`` on storage-form
  ``ShapeDtypeStruct`` inputs, exactly like :mod:`..analysis.drivers`) and
  the collective rounds/ring-model bytes are read off the resulting
  :class:`~elemental_tpu.analysis.plan.CommPlan`.  Problems larger than
  :data:`TRACE_REAL_LIMIT` are traced at a ratio-preserving scaled geometry
  (same schedule shape, capped step count) and extrapolated: latency
  scales with the real step count, bytes with the real matrix area.  For
  ``gemm`` the per-alg comm plans are closed-form ring-model site sums
  (the SUMMA panel schedules are simple enough to write down; the
  closed forms are cross-checked against the abstract traces in
  ``tests/tune``) so alg selection on the default ``alg='auto'`` hot path
  costs microseconds, never a trace.

* **Compute** -- an MXU-roofline flop term: ``flops / (p * peak)`` scaled
  by a blocksize-efficiency factor ``1 + HALF_NB/nb + IMB * nb/extent``
  (small panels starve the MXU; huge panels serialize the panel/diagonal
  work and unbalance the tail), which is what gives the nb sweep an
  interior optimum -- the same shape the A/B harness measures on real
  chips (nb=2048 at N=32k on v5e).

Everything runs cold on CPU (``'auto'`` with an empty cache never touches
a device), is deterministic, and is memoized per scaled trace geometry.
The model is a RANKING device: constants are first-order per-backend
defaults (override with ``machine=``), validated by the golden comm-plan
agreement tests rather than by absolute wall-clock accuracy.
"""
from __future__ import annotations

import dataclasses
import math

from .knobs import DEFAULT_CROSSOVER, TuneContext
from .policy import blocksize_policy

#: problems with sweep extent at or below this trace at their REAL
#: geometry (exact golden-comparable collective counts); larger ones trace
#: at a scaled geometry with at most _MAX_TRACE_STEPS blocked steps
TRACE_REAL_LIMIT = 96
_MAX_TRACE_STEPS = 6

#: blocksize-efficiency constants (see module docstring): HALF_NB is the
#: panel width at which MXU efficiency halves, IMB weights the serialized
#: panel/tail fraction nb/extent.  With the TPU machine model these place
#: the optimum at nb=2048 for N=32k -- the ab_harness-measured winner.
HALF_NB = 512.0
IMB = 3.0

#: the quantized-collective term (ISSUE 8): wire-byte scaling per
#: ``comm_precision`` mode.  bf16 is exactly half; int8 blends the ~4x
#: block-scaled gather family with the bf16-degraded pairs and the packed
#: scale rows, so 0.3 is the modeled blend (the traced *_commq golden
#: plans pin the exact per-driver ratios).
WIRE_FACTORS = {"bf16": 0.5, "int8": 0.3}

#: encode+decode vector passes over the LOGICAL payload per mode: bf16 is
#: one cast on each side; int8 adds the tile-amax reduction and the
#: scale multiply.  Priced against ``MachineModel.decode_bw_bytes_per_s``
#: so tiny latency-bound problems keep ``None`` (the candidate-order
#: tie-break) while bandwidth-bound geometries buy the narrower wire.
DECODE_PASSES = {"bf16": 2.0, "int8": 4.0}


@dataclasses.dataclass(frozen=True)
class MachineModel:
    """First-order per-backend constants for the scoring terms."""
    name: str
    latency_s: float           # per collective round (dispatch + hop)
    bw_bytes_per_s: float      # per-device collective bandwidth
    peak_flops: float          # per-device fp32-class matmul peak
    #: vector-unit (HBM-stream) bandwidth pricing the quantize/dequantize
    #: passes of the comm_precision path -- roughly 10x the wire
    decode_bw_bytes_per_s: float = 4.0e11
    #: per-device HBM capacity (ISSUE 18): candidates whose statically
    #: derived peak live bytes exceed it are PRUNED by the resolver, not
    #: merely penalized -- an OOM is not a slow configuration
    hbm_bytes: float = 16 * 2**30


MACHINES = {
    "tpu": MachineModel("tpu", latency_s=2e-6, bw_bytes_per_s=4.5e10,
                        peak_flops=3.0e13, hbm_bytes=16 * 2**30),
    "gpu": MachineModel("gpu", latency_s=3e-6, bw_bytes_per_s=3.0e10,
                        peak_flops=2.0e13, hbm_bytes=80 * 2**30),
    "cpu": MachineModel("cpu", latency_s=5e-6, bw_bytes_per_s=1.0e10,
                        peak_flops=2.0e11, hbm_bytes=64 * 2**30),
}


def machine_for(backend: str) -> MachineModel:
    return MACHINES.get(str(backend).lower(), MACHINES["cpu"])


@dataclasses.dataclass
class CostBreakdown:
    """One scored candidate, with the terms the ``explain`` CLI prints."""
    config: dict
    compute_s: float
    latency_s: float
    bandwidth_s: float
    rounds: float              # extrapolated collective rounds
    comm_bytes: float          # extrapolated ring-model WIRE bytes/device
    prim_counts: dict          # per-collective counts AT TRACE GEOMETRY
    detail: dict               # trace geometry / closed-form site notes
    pivot_s: float = 0.0       # pivot/reflector serial-chain latency
    decode_s: float = 0.0      # comm_precision encode/decode passes
    panel_impl_s: float = 0.0  # panel kernel-launch overhead (ISSUE 17)
    peak_bytes: float = 0.0    # statically derived per-device peak live
    pruned: bool = False       # peak_bytes > machine.hbm_bytes (OOM risk)

    @property
    def total_s(self) -> float:
        return self.compute_s + self.latency_s + self.bandwidth_s \
            + self.pivot_s + self.decode_s + self.panel_impl_s

    def to_doc(self) -> dict:
        return {"config": dict(self.config),
                "total_s": self.total_s, "compute_s": self.compute_s,
                "latency_s": self.latency_s, "bandwidth_s": self.bandwidth_s,
                "pivot_s": self.pivot_s, "decode_s": self.decode_s,
                "panel_impl_s": self.panel_impl_s,
                "rounds": self.rounds, "comm_bytes": self.comm_bytes,
                "peak_bytes": self.peak_bytes, "pruned": self.pruned,
                "prim_counts": dict(self.prim_counts),
                "detail": dict(self.detail)}


# ---------------------------------------------------------------------
# flop counts (LAPACK working notes; square getrf = 2n^3/3 etc.)
# ---------------------------------------------------------------------

def op_flops(op: str, dims) -> float:
    if op == "cholesky":
        n = dims[0]
        return n ** 3 / 3
    if op == "lu":
        m, n = dims[0], dims[-1]
        k = min(m, n)
        return 2 * (m * n * k - (m + n) * k * k / 2 + k ** 3 / 3)
    if op == "qr":
        m, n = dims[0], dims[-1]
        k = min(m, n)
        return 2 * k * k * (max(m, n) - k / 3)
    if op == "trsm":
        m, n = dims[0], dims[-1]
        return float(m) * m * n
    if op == "herk":
        m, k = dims[0], dims[-1]
        return float(m) * m * k
    if op == "gemm":
        m, k, n = dims
        return 2.0 * m * k * n
    raise KeyError(f"no flop formula for op {op!r}")


def _compute_seconds(op: str, ctx: TuneContext, nb, machine: MachineModel,
                     nb_sensitive: bool = True) -> float:
    p = ctx.grid_size
    base = op_flops(op, ctx.dims) / (p * machine.peak_flops)
    if not nb_sensitive:
        return base
    ext = max(ctx.extent, 1)
    nb_r = blocksize_policy(nb, ctx.grain, ext)
    return base * (1.0 + HALF_NB / nb_r + IMB * nb_r / ext)


def _pivot_seconds(op: str, ctx: TuneContext, config: dict,
                   machine: MachineModel) -> float:
    """Pivot/reflector serial-chain latency: the term that differentiates
    the panel strategies (ISSUE 6).

    The classic panels of lu/qr run one data-dependent step PER COLUMN
    over the full panel height -- an ``extent``-deep serial chain the MXU
    roofline term cannot see.  The tree panels (CALU tournament / TSQR)
    split that chain across the ``r`` grid rows (depth ``extent / r``)
    and add ``ceil(log2 r)`` pairwise playoff/reduction rounds per panel.
    Each unit of chain depth is priced at one ``machine.latency_s`` -- a
    RANKING device like the rest of the model: on single-row grids both
    strategies price identically (the slab IS the panel) and the
    candidate order's classic-first tie-break keeps the baseline."""
    if op not in ("lu", "qr"):
        return 0.0
    ext = max(ctx.extent, 1)
    unit = machine.latency_s
    panel = config.get("panel") or "classic"
    r = ctx.grid_shape[0]
    if panel == "classic" or r <= 1:
        return ext * unit
    nb_r = blocksize_policy(config.get("nb"), ctx.grain, ext)
    steps = max(1, math.ceil(ext / nb_r))
    return (ext / r) * unit + steps * math.ceil(math.log2(r)) * unit


#: interpret-mode slowdown of a pallas_call off-TPU: the fused panel
#: kernels run through the Pallas interpreter there (an eval_jaxpr walk,
#: orders of magnitude off compiled XLA), so 'auto' must never pick
#: 'pallas' on cpu/gpu.  50x is a deliberately blunt ranking constant --
#: any value >> 1 yields the same winner (pinned by tests/tune).
INTERPRET_PENALTY = 50.0


def _panel_impl_seconds(op: str, ctx: TuneContext, config: dict,
                        machine: MachineModel) -> float:
    """Panel kernel-LAUNCH overhead: the term that differentiates the
    panel implementations (ISSUE 17).

    The XLA panel ladder lowers to one data-dependent op chain PER
    COLUMN of the sweep (``extent`` launches of pivot/scale/update for
    lu, larfg steps for qr, per-block potrf/trinv pairs for cholesky)
    -- launch-latency work the flop roofline cannot see.  The fused
    Pallas kernel pays ONE launch per nb-panel (``steps`` total) and
    runs the column chain VMEM-resident, so on TPU
    ``panel_impl='auto'`` resolves to 'pallas'.  Off-TPU the kernels
    only exist in interpret mode, priced at :data:`INTERPRET_PENALTY`
    times the ladder -- 'auto' stays on 'xla' there.  Like
    ``_pivot_seconds`` this is a ranking device, not a wall-clock
    prediction; per-column units are one ``machine.latency_s``."""
    if op not in ("lu", "cholesky", "qr"):
        return 0.0
    ext = max(ctx.extent, 1)
    unit = machine.latency_s
    impl = config.get("panel_impl") or "xla"
    if impl != "pallas":
        return ext * unit
    if ctx.backend != "tpu":
        return ext * unit * INTERPRET_PENALTY
    nb_r = blocksize_policy(config.get("nb"), ctx.grain, ext)
    return max(1, math.ceil(ext / nb_r)) * unit


# ---------------------------------------------------------------------
# traced comm term (cholesky / lu / qr / trsm / herk)
# ---------------------------------------------------------------------

_TRACE_MEMO: dict = {}


def clear_trace_memo() -> None:
    _TRACE_MEMO.clear()


def _quant(v: float, grain: int, lo: int) -> int:
    from ..core.view import round_up
    return max(round_up(max(int(round(v)), 1), grain), lo)


def _geometry(ctx: TuneContext, nb, crossover, lookahead):
    """(trace dims, nb_t, xover_t, lat_scale, byte_scale) for the candidate.

    Small problems trace at their REAL geometry (exact counts, directly
    comparable to the golden comm plans).  Large ones keep the schedule
    shape but cap the step count: nb_t ~ 16 (grain-aligned), the crossover
    threshold maps to the same FRACTION of the sweep, latency extrapolates
    with the real step count and bytes with the real area (one full
    panel sweep moves O(area) words regardless of nb).
    """
    grain = ctx.grain
    ext = max(ctx.extent, 1)
    nb_r = blocksize_policy(nb, grain, ext)
    steps_real = max(1, math.ceil(ext / nb_r))
    xo = crossover
    if xo is None:
        xo = DEFAULT_CROSSOVER if lookahead else 0
    if ext <= TRACE_REAL_LIMIT:
        dims_t = tuple(ctx.dims)
        return dims_t, nb_r, int(xo), 1.0, 1.0
    steps_t = min(steps_real, _MAX_TRACE_STEPS)
    nb_t = _quant(16, grain, grain)
    ext_t = nb_t * steps_t
    scale = ext_t / ext
    dims_t = tuple(ext_t if d == ext else _quant(d * scale, grain, nb_t)
                   for d in ctx.dims)
    frac = min(float(xo) / ext, 1.0) if xo else 0.0
    xo_t = nb_t * int(round(frac * steps_t))
    lat_scale = steps_real / steps_t
    area = 1.0
    for d_r, d_t in zip(ctx.dims, dims_t):
        area *= d_r / d_t
    return dims_t, nb_t, xo_t, lat_scale, area


def _trace_stats(op: str, dims_t, nb_t: int, la, xo_t, grid, dtype,
                 panel: str = "classic", redist_path=None):
    """Abstract-trace ``op`` at the scaled geometry; totals memoized."""
    key = (op, dims_t, nb_t, bool(la), int(xo_t),
           (grid.height, grid.width), str(dtype), panel, redist_path)
    hit = _TRACE_MEMO.get(key)
    if hit is not None:
        return hit
    import jax
    import jax.numpy as jnp
    from ..core.dist import Dist
    from ..core.distmatrix import DistMatrix
    from ..analysis.drivers import storage_shape, trace_callable

    MC, MR = Dist.MC, Dist.MR

    def inp(m, n):
        return jax.ShapeDtypeStruct(storage_shape(m, n, MC, MR, grid), dtype)

    def dm(a, m, n):
        return DistMatrix(a, (m, n), MC, MR, 0, 0, grid)

    if op == "cholesky":
        n = dims_t[0]

        def fn(a):
            from ..lapack.cholesky import cholesky
            return cholesky(dm(a, n, n), nb=nb_t, lookahead=la, crossover=xo_t,
                            redist_path=redist_path)
        args = (inp(n, n),)
    elif op == "lu":
        m, n = dims_t[0], dims_t[-1]

        def fn(a):
            from ..lapack.lu import lu
            return lu(dm(a, m, n), nb=nb_t, lookahead=la, crossover=xo_t,
                      panel=panel, redist_path=redist_path)
        args = (inp(m, n),)
    elif op == "qr":
        m, n = dims_t[0], dims_t[-1]

        def fn(a):
            from ..lapack.qr import qr
            return qr(dm(a, m, n), nb=nb_t, panel=panel,
                      redist_path=redist_path)
        args = (inp(m, n),)
    elif op == "trsm":
        m, n = dims_t[0], dims_t[-1]

        def fn(a, b):
            from ..blas.level3 import trsm
            return trsm("L", "L", "N", dm(a, m, m), dm(b, m, n), nb=nb_t,
                        redist_path=redist_path)
        args = (inp(m, m), inp(m, n))
    elif op == "herk":
        m, k = dims_t[0], dims_t[-1]

        def fn(a):
            from ..blas.level3 import herk
            return herk("L", dm(a, m, k), nb=nb_t, redist_path=redist_path)
        args = (inp(m, k),)
    else:
        raise KeyError(f"no trace builder for op {op!r}")

    plan, closed, log = trace_callable(fn, args, name=f"tune:{op}",
                                       grid=grid)
    totals = plan.totals()
    # the memory term (ISSUE 18) rides the SAME abstract trace: the
    # liveness walk + replicated census of analysis.memory, at the trace
    # geometry (extrapolated with byte_scale by the caller, like bytes)
    from ..analysis.memory import analyze_jaxpr, replication_census
    p = max(grid.height * grid.width, 1)
    walk = analyze_jaxpr(closed, grid_size=p)
    census = replication_census(log, (grid.height, grid.width))
    # latency rounds count only REAL collectives: a collective over a
    # size-1 axis (1x1 grids, degenerate sub-axes) is elided by XLA.
    # prim_counts keep the raw per-primitive totals -- those are what the
    # golden comm-plan snapshots pin.
    stats = {"totals": totals,
             "rounds": sum(ev.count for ev in plan.events
                           if ev.axis_size > 1),
             "bytes": sum(t["bytes"] for t in totals.values()),
             "peak": walk.peak_bytes + walk.nonstatic_peak_bytes
             + census["max_extra_bytes"]}
    _TRACE_MEMO[key] = stats
    return stats


def _wire_terms(cbytes: float, comm_precision, machine: MachineModel):
    """(wire bytes, decode seconds) of the comm_precision term: the
    bytes-on-wire shrink by the mode's factor while an encode/decode
    vector pass over the LOGICAL payload is added on each side."""
    if not comm_precision:
        return cbytes, 0.0
    wire = cbytes * WIRE_FACTORS.get(comm_precision, 1.0)
    decode = DECODE_PASSES.get(comm_precision, 0.0) * cbytes \
        / machine.decode_bw_bytes_per_s
    return wire, decode


def _traced_cost(op: str, config: dict, ctx: TuneContext, grid, dtype,
                 machine: MachineModel) -> CostBreakdown:
    la = config.get("lookahead", True)
    xo = config.get("crossover")
    nb = config.get("nb")
    panel = config.get("panel") or "classic"
    cpm = config.get("comm_precision")
    # redist_path (ISSUE 12/13) reaches the traced driver, so the direct
    # route's collective counts/bytes are read off its REAL schedule --
    # the "one a2a round vs k gather rounds" term is the trace itself.
    rp = config.get("redist_path") \
        if op in ("lu", "cholesky", "qr", "trsm", "herk") else None
    # panel_impl deliberately does NOT reach _trace_stats: panels are
    # replicated-local compute, so the traced comm schedule is identical
    # under either implementation (the comm-invariance gate of
    # tools/check.sh kernels pins exactly this) -- keeping it out of the
    # memo key shares one trace across the panel_impl sweep.
    dims_t, nb_t, xo_t, lat_scale, byte_scale = _geometry(ctx, nb, xo, la)
    stats = _trace_stats(op, dims_t, nb_t, la, xo_t, grid, dtype, panel, rp)
    rounds = stats["rounds"] * lat_scale
    cbytes = stats["bytes"] * byte_scale
    wire_bytes, decode_s = _wire_terms(cbytes, cpm, machine)
    # resident bytes extrapolate with the matrix AREA like wire bytes
    # (the peak is operand-slab dominated, not schedule dominated)
    peak = stats["peak"] * byte_scale
    return CostBreakdown(
        config=dict(config),
        compute_s=_compute_seconds(op, ctx, nb, machine),
        latency_s=machine.latency_s * rounds,
        bandwidth_s=wire_bytes / machine.bw_bytes_per_s,
        pivot_s=_pivot_seconds(op, ctx, config, machine),
        decode_s=decode_s,
        panel_impl_s=_panel_impl_seconds(op, ctx, config, machine),
        rounds=rounds, comm_bytes=wire_bytes,
        peak_bytes=peak, pruned=peak > machine.hbm_bytes,
        prim_counts={k: t["count"] for k, t in stats["totals"].items()},
        detail={"trace_dims": list(dims_t), "trace_nb": nb_t,
                "trace_crossover": xo_t, "lat_scale": round(lat_scale, 3),
                "byte_scale": round(byte_scale, 3), "panel": panel,
                "comm_precision": cpm, "redist_path": rp})


# ---------------------------------------------------------------------
# closed-form gemm comm plans (ring model per SUMMA schedule)
# ---------------------------------------------------------------------

def _gemm_sites(alg: str, m: int, k: int, n: int, r: int, c: int,
                nb, itemsize: int, grain_lcm: int, redist_path=None):
    """(site list, rounds, bytes) for one SUMMA schedule.

    Per-device ring-model received bytes (cf. ``analysis.jaxpr_walk
    .estimate_bytes``): all_gather of a local block of B bytes over S
    ranks costs B*(S-1); a psum costs 2*B*(S-1)/S.  Panel loops use the
    same ``blocksize_policy`` grains as the drivers, so panel counts match
    the traced schedules.

    With ``redist_path='direct'`` the operand moves the drivers route
    through the one-shot plan compiler (ISSUE 12) are priced off the
    compiled :class:`~..redist.plan.RedistPlan` instead -- exactly one
    collective (or zero, when the plan is local) at the plan's honest
    padded wire bytes.  ``redist_path=None`` keeps this closed form
    byte-identical (pinned against the abstract trace by tests/tune).
    """
    p = r * c
    z = itemsize
    sites = []

    def ag(tag, local_elems, s):
        if s > 1:
            sites.append((tag, "all_gather", local_elems * z * (s - 1)))

    def ps(tag, local_elems, s):
        if s > 1:
            sites.append((tag, "psum", 2 * local_elems * z * (s - 1) // s))

    def direct(tag, src_pair, dst_pair, gshape):
        from ..redist.plan import compile_plan
        plan = compile_plan(src_pair, dst_pair, gshape, (r, c))
        if plan is None or plan.kind == "local":
            return                          # zero collective rounds
        prim = "all_to_all" if plan.kind == "a2a" else "ppermute"
        sites.append((tag, prim, plan.wire_bytes(z)))

    use_direct = redist_path == "direct" and p > 1
    if use_direct:
        from ..core.dist import MC, MR, VC, STAR  # jax-free taxonomy

    if alg == "C":
        kb = blocksize_policy(nb, grain_lcm, k)
        panels = max(1, math.ceil(k / kb))
        for _ in range(panels):
            if use_direct:
                direct("A1->[MC,*]", (MC, MR), (MC, STAR), (m, kb))
                direct("B1->[*,MR]", (MC, MR), (STAR, MR), (kb, n))
            else:
                ag("A1->[MC,*]", (m / r) * (kb / c), c)
                ag("B1->[*,MR]", (kb / r) * (n / c), r)
    elif alg == "A":
        jb = blocksize_policy(nb, c, n)
        panels = max(1, math.ceil(n / jb))
        for _ in range(panels):
            if use_direct:
                direct("B1->[MR,*]", (MC, MR), (MR, STAR), (k, jb))
            else:
                ag("B1->[MR,*]", (k / c) * (jb / r), r)  # gather over mc
            ps("D1 psum(mr)", (m / r) * jb, c)
            ag("D1->[MC,MR]", (m / r) * (jb / c), 1 if c == 1 else 2)
    elif alg == "B":
        ib = blocksize_policy(nb, r, m)
        panels = max(1, math.ceil(m / ib))
        for _ in range(panels):
            if use_direct:
                direct("A1^T->[MC,*]", (MR, MC), (MC, STAR), (k, ib))
            else:
                ag("A1^T->[MC,*]", (k / r) * (ib / c), c)
            ps("D1 psum(mc)", (ib / c) * n, r)
            ag("D1->[MC,MR]", (ib / r) * (n / c), 1 if r == 1 else 2)
    elif alg == "dot":
        if p > 1:
            if use_direct:
                direct("A->[*,VC]", (MC, MR), (STAR, VC), (m, k))
                direct("B->[VC,*]", (MC, MR), (VC, STAR), (k, n))
            else:
                ag("A->[*,VC]", m * (k / p), 2)          # cyclic re-land
                ag("B->[VC,*]", (k / p) * n, 2)
            ps("D psum(all)", m * n, p)
            ag("D filter", (m / r) * (n / c), 1)
    elif alg == "gspmd":
        ag("B->[MR,*]", (k / c) * (n / r), r)
        ps("D psum(mr)", (m / r) * n, c)
        ag("D->[MC,MR]", (m / r) * (n / c), 1 if c == 1 else 2)
    elif alg == "slice":
        # Slicing gemm (ISSUE 16): three one-shot plans, priced off the
        # SAME compiled RedistPlan byte math the executor runs --
        # regardless of redist_path (the slice gathers ARE direct plans,
        # so the knob crossing prices identically and the tie-break
        # keeps the default).  No hidden psum: k is unsharded on both
        # sides of the local contraction.
        if p > 1:
            from ..redist.plan import gemm_slice_plans
            for tag, plan in gemm_slice_plans(m, k, n, (r, c))[1]:
                if plan is None or plan.kind == "local":
                    continue                # degenerate relabeling leg
                prim = "all_to_all" if plan.kind == "a2a" else "ppermute"
                sites.append((tag, prim, plan.wire_bytes(z)))
    else:
        raise KeyError(f"unknown gemm alg {alg!r}")
    rounds = len(sites)
    total = int(sum(s[2] for s in sites))
    return sites, rounds, total


def _gemm_cost(config: dict, ctx: TuneContext, itemsize: int,
               machine: MachineModel) -> CostBreakdown:
    m, k, n = ctx.dims
    r, c = ctx.grid_shape
    alg = config["alg"]
    nb = config.get("nb")
    cpm = config.get("comm_precision")
    rp = config.get("redist_path")
    sites, rounds, cbytes = _gemm_sites(alg, m, k, n, r, c, nb, itemsize,
                                        ctx.grain, redist_path=rp)
    counts: dict = {}
    for _, prim, b in sites:
        if b > 0:
            counts[prim] = counts.get(prim, 0) + 1
    # the engine quantizes the redistribution collectives (gathers on the
    # chain, the one-shot a2a/ppermute payloads on the direct route);
    # GSPMD-inserted contraction psums stay full precision (gemm's non-SS
    # pairs all degrade int8 -> bf16, so both modes price at bf16)
    ag_bytes = sum(b for _, p, b in sites
                   if p in ("all_gather", "all_to_all", "ppermute"))
    wire_ag, decode_s = _wire_terms(ag_bytes,
                                    "bf16" if cpm else None, machine)
    wire_bytes = (cbytes - ag_bytes) + wire_ag
    # closed-form peak (ISSUE 18): the three operands sharded over p,
    # plus the largest single gathered/reduced buffer a site stages (a
    # collective's received bytes land in one live replicated form) --
    # the same ranking-device spirit as the rest of the model, pinned
    # within 2x of the abstract-trace walk by tests/tune
    p_dev = max(r * c, 1)
    base = (m * k + k * n + m * n) * itemsize / p_dev
    peak = base + max((b for _, _, b in sites), default=0)
    return CostBreakdown(
        config=dict(config),
        compute_s=_compute_seconds("gemm", ctx, nb, machine,
                                   nb_sensitive=alg in ("A", "B", "C")),
        latency_s=machine.latency_s * rounds,
        bandwidth_s=wire_bytes / machine.bw_bytes_per_s,
        decode_s=decode_s,
        rounds=rounds, comm_bytes=wire_bytes, prim_counts=counts,
        peak_bytes=peak, pruned=peak > machine.hbm_bytes,
        detail={"sites": [{"site": t, "prim": p, "bytes": b}
                          for t, p, b in sites],
                "comm_precision": cpm, "redist_path": rp})


# ---------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------

def score_config(op: str, config: dict, *, ctx: TuneContext, grid=None,
                 dtype=None, machine: MachineModel | None = None
                 ) -> CostBreakdown:
    """Score one candidate configuration of ``op`` at ``ctx``.

    ``grid``/``dtype`` (a live Grid and a jnp dtype) are required for the
    traced ops; gemm scores purely from ``ctx`` and the dtype itemsize.
    """
    machine = machine or machine_for(ctx.backend)
    if op == "gemm":
        import numpy as np
        itemsize = np.dtype(dtype if dtype is not None else "float32").itemsize
        return _gemm_cost(config, ctx, itemsize, machine)
    if grid is None or dtype is None:
        raise ValueError(f"scoring {op!r} needs a live grid and dtype "
                         "(the comm term traces the real driver)")
    return _traced_cost(op, config, ctx, grid, dtype, machine)
