"""Knob resolution policy: cache first, cost model second, explicit wins.

This is the driver-facing face of the subsystem.  A driver that receives
``'auto'`` for a knob calls :func:`resolve_knobs`; the resolver

  1. pins every knob the caller passed EXPLICITLY (an explicit value --
     including ``None``, the "driver default" sentinel -- always wins and
     simply constrains the candidate space),
  2. consults the persistent :mod:`.cache` for a measured winner under the
     ``(op, shape-bucket, dtype, grid, backend)`` key,
  3. otherwise scores the legal candidates with the analytic
     :mod:`.cost_model` (abstract traces + roofline; no device execution,
     so ``'auto'`` works cold on any machine) and picks the cheapest.

Resolutions are memoized in-process per (key, pinned-knobs, cache-dir), so
the hot path after the first call is one dict lookup.  The canonical
:func:`blocksize_policy` also lives here -- the single grain-rounding /
extent-clamping rule every blocked driver shares (re-exported as
``elemental_tpu.blas.level3._blocksize`` for its historical importers).
"""
from __future__ import annotations

import dataclasses

from . import cache as _cache
from .knobs import OPS, TuneContext, candidate_configs


# ---------------------------------------------------------------------
# the canonical blocksize policy (one rule, every driver)
# ---------------------------------------------------------------------

def blocksize_policy(nb, grain: int, extent: int) -> int:
    """Resolve an ``nb`` request to a legal block size: ``None`` reads the
    global :func:`~elemental_tpu.core.environment.blocksize` stack, the
    result is rounded up to the distribution ``grain`` (views must start
    and end on stride boundaries) and clamped to the grain-rounded
    ``extent``.  ``'auto'`` must already have been resolved by
    :func:`resolve_knobs` -- reaching here with a string is a driver bug.
    """
    if isinstance(nb, str):
        raise TypeError(f"nb={nb!r} reached blocksize_policy unresolved; "
                        "drivers must route 'auto' through tune.resolve_knobs")
    from ..core.view import round_up
    if nb is None:
        from ..core.environment import blocksize
        nb = blocksize()
    nb = round_up(max(nb, 1), grain)
    return min(nb, round_up(max(extent, 1), grain))


# ---------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------

@dataclasses.dataclass
class Resolution:
    """The outcome of one knob resolution."""
    op: str
    key: _cache.CacheKey
    source: str                  # "cache" | "cost_model"
    config: dict                 # values for the knobs that were 'auto'
    requested: dict              # the original knob request
    scores: list | None = None   # CostBreakdowns (cost-model path only)

    def to_doc(self) -> dict:
        return {"op": self.op, "key": self.key.filename(),
                "source": self.source, "config": dict(self.config),
                "requested": {k: str(v) if isinstance(v, str) else v
                              for k, v in self.requested.items()}}


_RESOLVE_MEMO: dict = {}


def clear_memo() -> None:
    """Drop the in-process resolution memo (tests swap cache dirs)."""
    _RESOLVE_MEMO.clear()
    from . import cost_model
    cost_model.clear_trace_memo()


def is_auto(value) -> bool:
    return isinstance(value, str) and value == "auto"


def wants_auto(*values) -> bool:
    return any(is_auto(v) for v in values)


def _context(op: str, dims, dtype, grid) -> TuneContext:
    import jax.numpy as jnp
    backend = "cpu"
    try:
        devs = grid.mesh.devices
        backend = devs.flat[0].platform
    except (AttributeError, IndexError):
        pass
    return TuneContext(op=op, dims=tuple(int(d) for d in dims),
                       dtype=jnp.dtype(dtype).name,
                       grid_shape=(grid.height, grid.width), backend=backend)


def resolve(op: str, *, gshape, dtype, grid, requested: dict,
            machine=None) -> Resolution:
    """Resolve the ``'auto'`` knobs of one driver call.

    ``gshape`` is the op's dim tuple ((n, n), (m, n), or gemm's
    (m, k, n)); ``requested`` maps every tunable knob to its requested
    value -- ``'auto'`` entries get resolved, anything else is pinned.
    """
    spec = OPS.get(op)
    if spec is None:
        raise KeyError(f"unknown tunable op {op!r}; known: {sorted(OPS)}")
    ctx = _context(op, gshape, dtype, grid)
    auto_keys = tuple(k for k, v in requested.items() if is_auto(v))
    # non-'auto' values pin their knob -- INCLUDING None, the "driver
    # default" sentinel (blocksize stack / schedule defaults), so a user
    # asking only alg='auto' never gets an nb-assuming alg choice
    pinned = {k: v for k, v in requested.items() if not is_auto(v)}
    key = _cache.make_key(op, ctx.dims, ctx.dtype, ctx.grid_shape,
                          ctx.backend)
    memo_key = (key, tuple(sorted(pinned.items(), key=repr)), auto_keys,
                _cache.cache_dir(), None if machine is None else machine.name)
    hit = _RESOLVE_MEMO.get(memo_key)
    if hit is not None:
        return hit

    res = None
    entry = _cache.load(key)
    if entry is not None:
        cfg = entry["config"]
        if all(k in cfg for k in auto_keys):
            res = Resolution(op=op, key=key, source="cache",
                             config={k: cfg[k] for k in auto_keys},
                             requested=dict(requested))
    if res is None:
        import jax.numpy as jnp
        from . import cost_model
        cands = candidate_configs(ctx, pinned)
        if not cands:
            raise ValueError(f"no legal {op} configuration for {requested} "
                             f"at dims {ctx.dims} on grid {ctx.grid_shape}")
        scored = [cost_model.score_config(op, cfg, ctx=ctx, grid=grid,
                                          dtype=jnp.dtype(dtype),
                                          machine=machine)
                  for cfg in cands]
        # memory-pruned candidates (statically derived peak over the
        # backend HBM, ISSUE 18) sort behind every fitting one: an OOM
        # is not a slow configuration.  All-pruned still resolves (the
        # least-bad candidate) so tiny dev grids never hard-fail.
        order = sorted(range(len(scored)),
                       key=lambda i: (scored[i].pruned,
                                      scored[i].total_s, i))
        best = scored[order[0]]
        res = Resolution(op=op, key=key, source="cost_model",
                         config={k: best.config[k] for k in auto_keys
                                 if k in best.config},
                         requested=dict(requested),
                         scores=[scored[i] for i in order])
    _RESOLVE_MEMO[memo_key] = res
    return res


def resolve_knobs(op: str, *, gshape, dtype, grid, knobs: dict,
                  machine=None) -> dict:
    """Driver-facing wrapper: return ``knobs`` with every ``'auto'`` entry
    replaced by the resolved concrete value (other entries pass through
    unchanged -- explicit always wins)."""
    if not wants_auto(*knobs.values()):
        return dict(knobs)
    res = resolve(op, gshape=gshape, dtype=dtype, grid=grid, requested=knobs,
                  machine=machine)
    out = dict(knobs)
    for k in knobs:
        if is_auto(knobs[k]):
            out[k] = res.config.get(k)
    return out


def explain(op: str, *, gshape, dtype, grid, requested: dict | None = None,
            machine=None):
    """(Resolution-like choice, scored candidates sorted best-first) for
    the ``perf.tune explain`` CLI: always runs the cost model (never the
    cache) so the breakdown reflects what a cold resolution would do."""
    import jax.numpy as jnp
    from . import cost_model
    spec = OPS.get(op)
    if spec is None:
        raise KeyError(f"unknown tunable op {op!r}; known: {sorted(OPS)}")
    requested = requested or {k: "auto" for k in spec.knobs}
    ctx = _context(op, gshape, dtype, grid)
    pinned = {k: v for k, v in requested.items() if not is_auto(v)}
    cands = candidate_configs(ctx, pinned)
    scored = sorted((cost_model.score_config(op, cfg, ctx=ctx, grid=grid,
                                             dtype=jnp.dtype(dtype),
                                             machine=machine)
                     for cfg in cands),
                    key=lambda b: (b.pruned, b.total_s))
    return ctx, scored
