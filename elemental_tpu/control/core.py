"""Control-theory solvers: Sylvester, Lyapunov, Riccati.

Reference: Elemental ``src/control/`` (``El::Sylvester``, ``El::Lyapunov``,
``El::Ricatti``) -- all built on the matrix sign function of structured
block matrices (Roberts' method), exactly as here: the sign iteration is
:func:`elemental_tpu.lapack.funcs.sign` (scaled Newton, LU solves on the
MXU), blocks are assembled/extracted with the interior embed/extract
primitives.
"""
from __future__ import annotations

from ..core.distmatrix import DistMatrix
from ..redist.interior import interior_view, interior_update, _blank
from ..blas.level3 import _check_mcmr
from ..lapack.funcs import sign as _sign
from ..lapack.qr import least_squares


def sylvester(A: DistMatrix, B: DistMatrix, C: DistMatrix,
              nb: int | None = None, precision=None) -> DistMatrix:
    """Solve ``A X + X B = C`` (``El::Sylvester``) via
    ``sign([[A, -C], [0, -B]]) = [[-I, 2X], [0, I]]``.

    Requires the spectra of A and -B to be separated by the imaginary axis
    (the classical stability assumption: A and B stable)."""
    _check_mcmr(A, B, C)
    m = A.gshape[0]
    n = B.gshape[0]
    if A.gshape != (m, m) or B.gshape != (n, n) or C.gshape != (m, n):
        raise ValueError(f"incompatible shapes {A.gshape},{B.gshape},{C.gshape}")
    W = _blank(m + n, m + n, A)
    W = interior_update(W, A, (0, 0))
    W = interior_update(W, C.with_local(-C.local), (0, m))
    W = interior_update(W, B.with_local(-B.local), (m, m))
    S = _sign(W, nb=nb, precision=precision)
    S12 = interior_view(S, (0, m), (m, m + n))
    return S12.with_local(0.5 * S12.local)


def lyapunov(A: DistMatrix, C: DistMatrix, nb: int | None = None,
             precision=None) -> DistMatrix:
    """Solve ``A X + X A^H = C`` (``El::Lyapunov``); A stable."""
    from ..redist.engine import redistribute, transpose_dist
    from ..core.dist import MC, MR
    Ah = redistribute(transpose_dist(A, conj=True), MC, MR)
    return sylvester(A, Ah, C, nb=nb, precision=precision)


def riccati(A: DistMatrix, G: DistMatrix, Q: DistMatrix,
            nb: int | None = None, precision=None) -> DistMatrix:
    """Stabilizing solution of the continuous algebraic Riccati equation
    ``A^H X + X A + Q - X G X = 0`` (``El::Ricatti``): the stable invariant
    subspace of the Hamiltonian ``H = [[A, -G], [-Q, -A^H]]`` satisfies
    ``(sign(H) + I) [I; X] = 0``; X is recovered from the (consistent)
    overdetermined system ``[S12; S22 + I] X = -[S11 + I; S21]``."""
    from ..redist.engine import redistribute, transpose_dist
    from ..redist.interior import vstack
    from ..core.dist import MC, MR
    from ..blas.level1 import shift_diagonal
    _check_mcmr(A, G, Q)
    n = A.gshape[0]
    Ah = redistribute(transpose_dist(A, conj=True), MC, MR)
    H = _blank(2 * n, 2 * n, A)
    H = interior_update(H, A, (0, 0))
    H = interior_update(H, G.with_local(-G.local), (0, n))
    H = interior_update(H, Q.with_local(-Q.local), (n, 0))
    H = interior_update(H, Ah.with_local(-Ah.local), (n, n))
    S = _sign(H, nb=nb, precision=precision)
    S11 = interior_view(S, (0, n), (0, n))
    S12 = interior_view(S, (0, n), (n, 2 * n))
    S21 = interior_view(S, (n, 2 * n), (0, n))
    S22 = interior_view(S, (n, 2 * n), (n, 2 * n))
    M = vstack(S12, shift_diagonal(S22, 1))
    R = vstack(shift_diagonal(S11, 1), S21)
    return least_squares(M, R.with_local(-R.local), nb=nb,
                         precision=precision)
