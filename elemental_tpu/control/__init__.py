"""Control theory layer (SURVEY.md §3.5): sign-function solvers.

Reference: Elemental ``src/control/``.
"""
from .core import sylvester, lyapunov, riccati
