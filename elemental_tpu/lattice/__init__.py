"""Lattice tier (reference: Elemental ``src/lattice/**`` ※)."""
from .core import lll, is_lll_reduced, shortest_vector
