"""Lattice reduction: LLL (+ deep insertions) and a small-dim SVP helper.

Reference: Elemental ``src/lattice/**`` (``El::LLL``, ``El::BKZ``,
``El::ShortestVector`` -- the late-master number-theory tier, SURVEY.md
§3.5 ※).  Columns of B are the basis vectors, matching upstream.

TPU stance: lattice reduction is an inherently sequential, precision-
sensitive scalar recurrence (upstream runs it on one rank in extended
precision) -- there is nothing for the MXU here, so the sweep runs
host-side in float64 on the gathered basis and the reduced basis +
unimodular transform scatter back to [MC,MR].  This mirrors upstream,
whose lattice tier is also sequential (``※`` in the survey).
"""
from __future__ import annotations

import numpy as np

from ..core.dist import MC, MR
from ..core.distmatrix import DistMatrix, from_global, to_global


def _gso(B):
    """Gram-Schmidt mu + squared norms of B* (columns)."""
    m, n = B.shape
    mu = np.eye(n)
    Bs = B.astype(np.float64).copy()
    nrm2 = np.zeros(n)
    for k in range(n):
        v = B[:, k].astype(np.float64)
        for j in range(k):
            mu[k, j] = (B[:, k] @ Bs[:, j]) / max(nrm2[j], 1e-300)
            v = v - mu[k, j] * Bs[:, j]
        Bs[:, k] = v
        nrm2[k] = v @ v
    return mu, nrm2


def _lll_host(B, delta: float, eta: float = 0.51, deep: bool = False,
              max_sweeps: int = 10_000):
    """Floating LLL (Schnorr-Euchner loop) on a host array; returns
    (B_reduced, U, n_swaps, converged) with B_reduced = B @ U, U unimodular.
    ``converged`` is False iff the ``max_sweeps * n`` iteration cap fired
    before the sweep index reached n (the basis may then be unreduced)."""
    B = B.astype(np.float64).copy()
    m, n = B.shape
    U = np.eye(n)
    swaps = 0
    k = 1
    it = 0
    while k < n and it < max_sweeps * n:
        it += 1
        # one GSO per k-visit; size reduction updates row k of mu IN
        # PLACE (B* is invariant under column-k subtractions, and the
        # descending-j sweep leaves every |mu[k, j]| <= 1/2 exactly) --
        # the standard bookkeeping, O(n) per j instead of a fresh
        # O(m n^2) Gram-Schmidt per subtraction
        mu, nrm2 = _gso(B)
        for j in range(k - 1, -1, -1):
            q = np.round(mu[k, j])
            if abs(mu[k, j]) > eta and q != 0:
                B[:, k] -= q * B[:, j]
                U[:, k] -= q * U[:, j]
                mu[k, : j + 1] -= q * mu[j, : j + 1]
        if deep:
            # Schnorr-Euchner deep insertion: walk c = ||pi_i(b_k)||^2
            # down the positions; insert at the first i where
            # c < delta * ||b_i*||^2 (the plain swap is the i = k-1 case)
            c = float(B[:, k] @ B[:, k])
            ins = k
            for i in range(k):
                if c >= delta * nrm2[i]:
                    c -= mu[k, i] ** 2 * nrm2[i]
                else:
                    ins = i
                    break
            if ins < k:
                col = B[:, k].copy()
                ucol = U[:, k].copy()
                B[:, ins + 1:k + 1] = B[:, ins:k]
                U[:, ins + 1:k + 1] = U[:, ins:k]
                B[:, ins] = col
                U[:, ins] = ucol
                swaps += 1
                k = max(ins, 1)
                continue
            k += 1
            continue
        if nrm2[k] >= (delta - mu[k, k - 1] ** 2) * nrm2[k - 1]:
            k += 1
        else:
            B[:, [k - 1, k]] = B[:, [k, k - 1]]
            U[:, [k - 1, k]] = U[:, [k, k - 1]]
            swaps += 1
            k = max(k - 1, 1)
    return B, U, swaps, k >= n


def lll(B: DistMatrix, delta: float = 0.99, eta: float = 0.51,
        deep: bool = False, max_sweeps: int = 10_000):
    """LLL-reduce the columns of B (``El::LLL``).  Returns
    (B_reduced [MC,MR], U [MC,MR] unimodular, info) with
    ``B_reduced = B U`` and the reduced basis satisfying the
    size-reduction (|mu_kj| <= eta) and Lovasz (delta) conditions.

    ``info["converged"]`` reports whether the returned basis actually IS
    LLL-reduced: True on normal termination; when the ``max_sweeps * n``
    iteration cap fires mid-sweep, :func:`is_lll_reduced` is run on the
    result (the cap can land exactly at completion) instead of silently
    handing back a possibly-unreduced basis."""
    Bn = np.asarray(to_global(B), np.float64)
    R, U, swaps, converged = _lll_host(Bn, delta, eta, deep, max_sweeps)
    if not converged:
        converged = is_lll_reduced(R, delta, eta)
    g = B.grid
    info = {"swaps": swaps,
            "first_norm": float(np.linalg.norm(R[:, 0])),
            "converged": bool(converged)}
    return (from_global(R.astype(np.asarray(Bn).dtype), MC, MR, grid=g),
            from_global(U, MC, MR, grid=g), info)


def is_lll_reduced(B, delta: float = 0.99, eta: float = 0.51) -> bool:
    """Check the size-reduction + Lovasz conditions (host-side)."""
    Bn = np.asarray(to_global(B), np.float64) if isinstance(B, DistMatrix) \
        else np.asarray(B, np.float64)
    mu, nrm2 = _gso(Bn)
    n = Bn.shape[1]
    for k in range(1, n):
        for j in range(k):
            if abs(mu[k, j]) > eta + 1e-9:
                return False
        if nrm2[k] < (delta - mu[k, k - 1] ** 2) * nrm2[k - 1] - 1e-9:
            return False
    return True


def shortest_vector(B: DistMatrix, delta: float = 0.99,
                    enum_radius: int = 2):
    """Short lattice vector (``El::ShortestVector`` approximation): LLL
    first, then exhaustive enumeration of small integer combinations of
    the first few reduced vectors (exact SVP enumeration is exponential;
    upstream's is too).  Returns (v host vector, norm)."""
    R, U, info = lll(B, delta)
    Rn = np.asarray(to_global(R))
    n = Rn.shape[1]
    best = Rn[:, 0]
    bestn = np.linalg.norm(best)
    kdim = min(n, 5)
    from itertools import product
    for coef in product(range(-enum_radius, enum_radius + 1), repeat=kdim):
        if not any(coef):
            continue
        v = Rn[:, :kdim] @ np.asarray(coef, np.float64)
        nv = np.linalg.norm(v)
        if 1e-9 < nv < bestn:
            best, bestn = v, nv
    return best, float(bestn)
