"""Level-1 zoo.

Reference: Elemental ``src/blas_like/level1/*.cpp`` (~70 files: Axpy, Scale,
Dot, Nrm2, Zero, Fill, EntrywiseMap, Hadamard, MakeTrapezoidal,
MakeSymmetric/Hermitian, DiagonalScale, GetDiagonal/SetDiagonal, ...).

TPU-native design point: because the stacked-storage array contains every
global entry EXACTLY ONCE (replication lives at the device level, not in the
storage array) and padding is zero, elementwise ops between same-distribution
operands and all entrywise reductions run directly on storage arrays OUTSIDE
shard_map -- XLA/GSPMD handles the sharded arithmetic.  Only index-dependent
ops (trapezoidal masks, diagonals) need the cyclic index maps.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute, transpose_dist


def _check_same_layout(A: DistMatrix, B: DistMatrix):
    if A.dist != B.dist or (A.calign, A.ralign) != (B.calign, B.ralign) \
            or A.gshape != B.gshape or A.grid != B.grid:
        raise ValueError(f"layout mismatch: {A} vs {B}")


# ---- elementwise ----------------------------------------------------

def axpy(alpha, X: DistMatrix, Y: DistMatrix) -> DistMatrix:
    _check_same_layout(X, Y)
    return Y.with_local(alpha * X.local + Y.local)


def scale(alpha, A: DistMatrix) -> DistMatrix:
    return A.with_local(alpha * A.local)


def zero(A: DistMatrix) -> DistMatrix:
    return A.with_local(jnp.zeros_like(A.local))


def fill(A: DistMatrix, value) -> DistMatrix:
    """Fill with a constant (padding kept zero via the global-index mask)."""
    mask = _valid_mask(A)
    return A.with_local(jnp.where(mask, jnp.asarray(value, A.dtype), 0))


def entrywise_map(A: DistMatrix, fn) -> DistMatrix:
    """EntrywiseMap; fn must map 0 -> 0 or the padding is re-zeroed."""
    out = fn(A.local)
    return A.with_local(jnp.where(_valid_mask(A), out, 0))


def hadamard(A: DistMatrix, B: DistMatrix) -> DistMatrix:
    _check_same_layout(A, B)
    return A.with_local(A.local * B.local)


def conjugate(A: DistMatrix) -> DistMatrix:
    return A.with_local(jnp.conj(A.local))


# ---- index-dependent maps -------------------------------------------

def _global_indices(A: DistMatrix):
    """(I, J) global index arrays matching the storage array layout."""
    m, n = A.gshape
    Sc, Sr = A.col_stride, A.row_stride
    lr, lc = A.local_rows, A.local_cols
    q = jnp.arange(Sc)[:, None]
    il = jnp.arange(lr)[None, :]
    I = (il * Sc + (q - A.calign) % Sc).reshape(-1)      # storage row -> global row
    q2 = jnp.arange(Sr)[:, None]
    jl = jnp.arange(lc)[None, :]
    J = (jl * Sr + (q2 - A.ralign) % Sr).reshape(-1)
    return I, J


def _valid_mask(A: DistMatrix):
    I, J = _global_indices(A)
    m, n = A.gshape
    return (I[:, None] < m) & (J[None, :] < n)


def index_dependent_map(A: DistMatrix, fn) -> DistMatrix:
    """IndexDependentMap: B[i,j] = fn(i, j, A[i,j]) (fn broadcast over index
    arrays); padding re-zeroed."""
    I, J = _global_indices(A)
    out = fn(I[:, None], J[None, :], A.local)
    return A.with_local(jnp.where(_valid_mask(A), out, 0))


def index_dependent_fill(A: DistMatrix, fn) -> DistMatrix:
    """IndexDependentFill: B[i,j] = fn(i, j)."""
    return index_dependent_map(A, lambda i, j, a: fn(i, j) + jnp.zeros_like(a))


def make_trapezoidal(A: DistMatrix, uplo: str, offset: int = 0) -> DistMatrix:
    """Zero outside the lower/upper trapezoid (MakeTrapezoidal)."""
    I, J = _global_indices(A)
    if uplo.upper().startswith("L"):
        keep = J[None, :] <= I[:, None] + offset
    else:
        keep = J[None, :] >= I[:, None] + offset
    return A.with_local(jnp.where(keep, A.local, 0))


def shift_diagonal(A: DistMatrix, alpha, offset: int = 0) -> DistMatrix:
    """A += alpha*I on the given diagonal (ShiftDiagonal / UpdateDiagonal)."""
    I, J = _global_indices(A)
    m, n = A.gshape
    on = (J[None, :] == I[:, None] + offset) & (I[:, None] < m) & (J[None, :] < n)
    return A.with_local(A.local + jnp.where(on, jnp.asarray(alpha, A.dtype), 0))


def make_symmetric(A: DistMatrix, uplo: str = "L", conj: bool = False) -> DistMatrix:
    """Reflect the given triangle onto the other (MakeSymmetric/Hermitian).

    Implemented as trapezoid(A) + trapezoid(A)^T - diag, using the free
    transpose-dist + a redistribution back.
    """
    tri = make_trapezoidal(A, uplo, 0)
    triT = redistribute(transpose_dist(tri, conj=conj), *A.dist,
                        calign=A.calign, ralign=A.ralign)
    I, J = _global_indices(A)
    on_diag = J[None, :] == I[:, None]
    dvals = jnp.where(on_diag, tri.local, 0)
    if conj:
        dvals = jnp.real(dvals).astype(A.dtype)
    out = tri.local + triT.local - dvals
    return A.with_local(out)


def get_diagonal(A: DistMatrix, offset: int = 0, dist: str = "star"):
    """Diagonal of A as a (k, 1) DistMatrix.

    ``dist='star'`` (default): replicated [STAR,STAR] -- the convenient
    form every elementwise consumer here takes.  ``dist='md'``: TRUE
    [MD,STAR] output (the reference's return type): diagonal entry k of
    an [MC,MR] matrix lives on device (k%r, k%c), which IS its MD owner,
    so the extraction is device-co-located and the per-device allocation
    is O(k/lcm) -- no replicated k-vector exists."""
    if dist == "md":
        return _get_diagonal_md(A, offset)
    if dist != "star":
        raise ValueError(f"get_diagonal dist must be 'star' or 'md', "
                         f"got {dist!r}")
    m, n = A.gshape
    k = min(m, n - offset) if offset >= 0 else min(m + offset, n)
    I, J = _global_indices(A)
    on = J[None, :] == I[:, None] + offset
    # scatter local diag entries into a dense k-vector, then sum-replicate
    didx = jnp.where(on, I[:, None] - (0 if offset >= 0 else -offset), 0)
    contrib = jnp.zeros((max(k, 1),), A.dtype).at[
        jnp.where(on, didx, k if k > 0 else 0).reshape(-1)
    ].add(jnp.where(on, A.local, 0).reshape(-1), mode="drop")
    # storage arrays hold each entry once; sum over devices happens via GSPMD
    vec = contrib.reshape(k, 1) if k > 0 else jnp.zeros((0, 1), A.dtype)
    from ..core.dist import STAR as _S
    out = DistMatrix(vec, (k, 1), _S, _S, 0, 0, A.grid)
    return out


def _get_diagonal_md(A: DistMatrix, offset: int):
    """[MD,STAR] diagonal extraction (offset 0; co-located, O(k/lcm))."""
    from ..core.dist import MC as _MC, MR as _MR, MD as _MD, STAR as _S
    from ..core.dist import md_slot_of_global, stride as _stride
    from ..core import indexing as _ix
    if offset != 0:
        raise NotImplementedError("MD output supports the main diagonal")
    if (A.cdist, A.rdist) != (_MC, _MR) or A.calign or A.ralign:
        raise ValueError("MD extraction needs a zero-aligned [MC,MR] source")
    m, n = A.gshape
    k = min(m, n)
    r, c = A.grid.height, A.grid.width
    L = _stride(_MD, r, c)
    l = _ix.max_local_length(k, L)
    lr, lc = A.local_rows, A.local_cols
    # storage coordinates of global (kk, kk) and the MD slot it feeds;
    # both live on device (kk%r, kk%c), so XLA lowers this to local moves
    kk = jnp.arange(k)
    ri = (kk % r) * lr + kk // r
    cj = (kk % c) * lc + kk // c
    vals = A.local[ri, cj]
    slots = jnp.asarray(md_slot_of_global(r, c, k))
    stor = jnp.zeros((r * c * l, 1), A.dtype).at[slots, 0].set(vals)
    out = DistMatrix(stor, (k, 1), _MD, _S, 0, 0, A.grid)
    import jax as _jax
    return out.with_local(_jax.device_put(stor, A.grid.sharding(out.spec)))


def _diag_vals(A: DistMatrix, d: DistMatrix, offset: int):
    """(on-diagonal mask, broadcast diagonal values) shared by the
    set/update diagonal ops."""
    m, n = A.gshape
    I, J = _global_indices(A)
    on = (J[None, :] == I[:, None] + offset) \
        & (I[:, None] < m) & (J[None, :] < n)
    di = I[:, None] - (0 if offset >= 0 else -offset)
    dv = d.local.reshape(-1)
    vals = dv[jnp.clip(di, 0, max(dv.shape[0] - 1, 0))]
    return on, vals


def set_diagonal(A: DistMatrix, d: DistMatrix, offset: int = 0) -> DistMatrix:
    """Write a replicated (k,1) diagonal into A."""
    on, vals = _diag_vals(A, d, offset)
    return A.with_local(jnp.where(on, vals, A.local))


def update_diagonal(A: DistMatrix, d: DistMatrix, offset: int = 0) -> DistMatrix:
    """A += diag(d) on the given diagonal; d replicated (k,1)
    (``El::UpdateDiagonal`` with a vector)."""
    on, vals = _diag_vals(A, d, offset)
    return A.with_local(jnp.where(on, A.local + vals, A.local))


def diagonal_scale(side: str, d: DistMatrix, A: DistMatrix) -> DistMatrix:
    """A := diag(d) A (side=L) or A diag(d) (side=R); d replicated (k,1)."""
    I, J = _global_indices(A)
    dv = d.local.reshape(-1)
    if side.upper().startswith("L"):
        vals = dv[jnp.clip(I, 0, dv.shape[0] - 1)]
        return A.with_local(A.local * vals[:, None])
    vals = dv[jnp.clip(J, 0, dv.shape[0] - 1)]
    return A.with_local(A.local * vals[None, :])


def diagonal_solve(side: str, d: DistMatrix, A: DistMatrix) -> DistMatrix:
    dv = d.local.reshape(-1)
    dinv = jnp.where(dv != 0, 1 / jnp.where(dv == 0, 1, dv), 0)
    return diagonal_scale(side, d.with_local(dinv.reshape(-1, 1)), A)


# ---- reductions (storage-based: each entry once, padding zero) -------

def frobenius_norm(A: DistMatrix):
    return jnp.linalg.norm(A.local)


def max_norm(A: DistMatrix):
    return jnp.max(jnp.abs(A.local)) if A.local.size else jnp.asarray(0.0)


def one_norm(A: DistMatrix):
    """max column sum -- column permutation of storage is irrelevant."""
    return jnp.max(jnp.sum(jnp.abs(A.local), axis=0))


def infinity_norm(A: DistMatrix):
    return jnp.max(jnp.sum(jnp.abs(A.local), axis=1))


def entrywise_norm(A: DistMatrix, p):
    return jnp.sum(jnp.abs(A.local) ** p) ** (1.0 / p)


def zero_norm(A: DistMatrix, tol=0.0):
    return jnp.sum(jnp.abs(A.local) > tol)


def dot(A: DistMatrix, B: DistMatrix):
    """Hilbert-Schmidt inner product <A,B> = sum conj(A) * B."""
    _check_same_layout(A, B)
    return jnp.sum(jnp.conj(A.local) * B.local)


def nrm2(A: DistMatrix):
    return frobenius_norm(A)


def trace(A: DistMatrix):
    d = get_diagonal(A)
    return jnp.sum(d.local)


# ---- orientation / parts (Transpose.cpp, RealPart.cpp, Conjugate.cpp) ----

def transpose(A: DistMatrix, conj: bool = False) -> DistMatrix:
    """B = A^T (``El::Transpose``): free dist-transpose + engine hops back to
    A's distribution pair."""
    return redistribute(transpose_dist(A, conj=conj), *A.dist,
                        calign=A.calign, ralign=A.ralign)


def adjoint(A: DistMatrix) -> DistMatrix:
    """B = A^H (``El::Adjoint``)."""
    return transpose(A, conj=True)


def real_part(A: DistMatrix) -> DistMatrix:
    """``El::RealPart`` (result is the real base dtype)."""
    return A.with_local(jnp.real(A.local))


def imag_part(A: DistMatrix) -> DistMatrix:
    """``El::ImagPart``."""
    return A.with_local(jnp.imag(A.local))


def round_entries(A: DistMatrix) -> DistMatrix:
    """``El::Round``: nearest integer, entrywise (complex: each part)."""
    if jnp.iscomplexobj(A.local):
        return A.with_local(jnp.round(jnp.real(A.local))
                            + 1j * jnp.round(jnp.imag(A.local)))
    return A.with_local(jnp.round(A.local))


def swap(A: DistMatrix, B: DistMatrix):
    """``El::Swap``: functionally, just the exchanged pair."""
    _check_same_layout(A, B)
    return B, A


def dotu(A: DistMatrix, B: DistMatrix):
    """Non-conjugated inner product (``El::Dotu``)."""
    _check_same_layout(A, B)
    return jnp.sum(A.local * B.local)


# ---- extremal entries with location (MaxAbsLoc / MaxLoc family) ------

def _loc_reduce(A: DistMatrix, vals, reducer):
    """Shared (value, (i,j)) reduction over the storage array: pack the
    global index into the comparison payload -- the ``mpi::MAXLOC`` analog
    (value,index) pairing, done as one argmax over each-entry-once storage."""
    I, J = _global_indices(A)
    m, n = A.gshape
    valid = (I[:, None] < m) & (J[None, :] < n)
    flat = jnp.where(valid, vals, reducer.pad).reshape(-1)
    idx = reducer.arg(flat)
    li, lj = idx // vals.shape[1], idx % vals.shape[1]
    return flat[idx], (I[li], J[lj])


class _MaxRed:
    pad = -jnp.inf
    arg = staticmethod(jnp.argmax)


class _MinRed:
    pad = jnp.inf
    arg = staticmethod(jnp.argmin)


def max_abs_loc(A: DistMatrix):
    """(|a_ij|max, (i,j)) -- ``El::MaxAbsLoc``; the LU pivot-search kernel."""
    return _loc_reduce(A, jnp.abs(A.local), _MaxRed)


def min_abs_loc(A: DistMatrix):
    """``El::MinAbsLoc``."""
    return _loc_reduce(A, jnp.abs(A.local), _MinRed)


def max_loc(A: DistMatrix):
    """``El::MaxLoc`` (real dtypes)."""
    return _loc_reduce(A, jnp.real(A.local), _MaxRed)


def min_loc(A: DistMatrix):
    """``El::MinLoc`` (real dtypes)."""
    return _loc_reduce(A, jnp.real(A.local), _MinRed)


# ---- trapezoid updates (ScaleTrapezoid.cpp, AxpyTrapezoid.cpp) -------

def _trapezoid_mask(A: DistMatrix, uplo: str, offset: int):
    I, J = _global_indices(A)
    if uplo.upper().startswith("L"):
        return J[None, :] <= I[:, None] + offset
    return J[None, :] >= I[:, None] + offset


def scale_trapezoid(alpha, A: DistMatrix, uplo: str, offset: int = 0
                    ) -> DistMatrix:
    """Scale the lower/upper trapezoid by alpha, rest untouched
    (``El::ScaleTrapezoid``)."""
    keep = _trapezoid_mask(A, uplo, offset)
    return A.with_local(jnp.where(keep, alpha * A.local, A.local))


def axpy_trapezoid(alpha, X: DistMatrix, Y: DistMatrix, uplo: str,
                   offset: int = 0) -> DistMatrix:
    """Y += alpha * trapezoid(X) (``El::AxpyTrapezoid``)."""
    _check_same_layout(X, Y)
    keep = _trapezoid_mask(X, uplo, offset)
    return Y.with_local(Y.local + jnp.where(keep, alpha * X.local, 0))


def safe_scale(numerator, denominator, A: DistMatrix):
    """A := (numerator/denominator) A staged to avoid overflow/underflow
    (``El::SafeScale``; the LAPACK ``dlascl`` multiplier-staging loop)."""
    import numpy as _np
    base = A.local.real.dtype if jnp.iscomplexobj(A.local) else A.local.dtype
    fin = _np.finfo(base)
    small, big = float(fin.tiny), 1.0 / float(fin.tiny)
    cfrom, cto = float(denominator), float(numerator)
    if cfrom == 0.0:
        raise ValueError("safe_scale: denominator must be nonzero")
    out = A
    while True:
        cfrom1 = cfrom * small
        cto1 = cto / big
        if abs(cfrom1) > abs(cto) and cto != 0.0:
            mul, cfrom = small, cfrom1
        elif abs(cto1) > abs(cfrom):
            mul, cto = big, cto1
        else:
            return out.with_local(out.local * (cto / cfrom))
        out = out.with_local(out.local * mul)


# ---- submatrix access (GetSubmatrix.cpp / SetSubmatrix.cpp) ----------

def get_submatrix(A: DistMatrix, i0: int, j0: int, m: int, n: int
                  ) -> DistMatrix:
    """Copy out A[i0:i0+m, j0:j0+n] as a zero-aligned matrix of the same
    distribution (``El::GetSubmatrix`` with contiguous ranges)."""
    from ..redist.interior import interior_view
    return interior_view(A, (i0, i0 + m), (j0, j0 + n))


def set_submatrix(A: DistMatrix, i0: int, j0: int, B: DistMatrix
                  ) -> DistMatrix:
    """Write B into A[i0:.., j0:..] (``El::SetSubmatrix``)."""
    from ..redist.interior import interior_update
    return interior_update(A, B, at=(i0, j0))
