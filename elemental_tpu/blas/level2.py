"""Level-2 BLAS: distributed matrix-vector operations.

Reference: Elemental ``src/blas_like/level2/`` -- ``Gemv`` (panel
redistributions + SumScatter), ``Ger``, ``Symv``/``Hemv`` (the
tridiagonalization workhorse, accumulating [MC,STAR] and [MR,STAR]
partials), ``Trsv``/``Trmv``.

TPU-native design: a vector is an (m, 1) zero-aligned [MC,MR] DistMatrix.
Because the stacked-storage array of a DistMatrix is an index PERMUTATION of
the global matrix (P_mc A P_mr^T), a matvec is a single storage-level matmul
between compatibly-permuted operands, and GSPMD lowers the sharded
contraction to a local MXU product plus the one collective the reference
hand-codes (psum over 'mr' for N, over 'mc' for T/C -- the AllGather +
local-gemv + ReduceScatter of ``El::Gemv``):

  N:  y_stor[MC,STAR] = A_stor @ x_stor[MR,STAR]     (contraction mr-sharded)
  T:  y_stor[MR,STAR] = A_stor^T @ x_stor[MC,STAR]   (contraction mc-sharded)

``hemv``/``symv`` read only the stored triangle: the strictly-off-triangle
product rides the transposed path, so exactly one triangle of A is touched
(matching the reference's one-triangle access guarantee).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute
from .level3 import _check_mcmr, _mask_triangle, _safe_astype, _nonzero, trsm


def _check_vector(x: DistMatrix, extent: int, what: str):
    if x.gshape != (extent, 1):
        raise ValueError(f"{what} must be ({extent}, 1), got {x.gshape}")


def _axpby(alpha, prod_mcmr: DistMatrix, beta, y: DistMatrix | None,
           like: DistMatrix):
    if y is None:
        return prod_mcmr.with_local(_safe_astype(alpha * prod_mcmr.local, like.dtype))
    newloc = alpha * prod_mcmr.local + (beta * y.local if _nonzero(beta) else 0)
    return y.with_local(_safe_astype(newloc, y.dtype))


def _matvec_n(A_local, x: DistMatrix, m: int, grid, precision):
    """op = N storage matvec: returns the [MC,STAR] (m,1) partial-free result."""
    x_mr = redistribute(x, MR, STAR)
    y = jnp.matmul(A_local, x_mr.local, precision=precision)
    return DistMatrix(y, (m, 1), MC, STAR, 0, 0, grid)


def _matvec_t(A_local, x: DistMatrix, n: int, grid, conj: bool, precision):
    """op = T/C storage matvec: returns the [MR,STAR] (n,1) result."""
    x_mc = redistribute(x, MC, STAR)
    a = jnp.conj(A_local) if conj else A_local
    y = jnp.matmul(a.T, x_mc.local, precision=precision)
    return DistMatrix(y, (n, 1), MR, STAR, 0, 0, grid)


def gemv(A: DistMatrix, x: DistMatrix, alpha=1.0, beta=0.0,
         y: DistMatrix | None = None, orient: str = "N",
         precision=None) -> DistMatrix:
    """y := alpha op(A) x + beta y (``El::Gemv``)."""
    _check_mcmr(A)
    m, n = A.gshape
    if orient == "N":
        _check_vector(x, n, "x")
        prod = redistribute(_matvec_n(A.local, x, m, A.grid, precision), MC, MR)
    else:
        _check_vector(x, m, "x")
        prod = redistribute(
            _matvec_t(A.local, x, n, A.grid, orient == "C", precision), MC, MR)
    return _axpby(alpha, prod, beta, y, A)


def ger(alpha, x: DistMatrix, y: DistMatrix, A: DistMatrix,
        conj: bool = True, precision=None) -> DistMatrix:
    """A := A + alpha x y^H (``El::Ger``; ``conj=False`` gives ``Geru``).

    Outer product of the [MC,STAR] column and [STAR,MR] row storage forms --
    a pure-local rank-1 update, zero communication beyond the two panel
    moves (exactly the reference's Ger data motion)."""
    _check_mcmr(A)
    m, n = A.gshape
    _check_vector(x, m, "x")
    _check_vector(y, n, "y")
    x_mc = redistribute(x, MC, STAR)
    y_mr = redistribute(y, MR, STAR)
    row = jnp.conj(y_mr.local).T if conj else y_mr.local.T
    upd = jnp.matmul(x_mc.local, row, precision=precision)
    return A.with_local(_safe_astype(A.local + alpha * upd, A.dtype))


def hemv(uplo: str, A: DistMatrix, x: DistMatrix, alpha=1.0, beta=0.0,
         y: DistMatrix | None = None, conj: bool = True,
         precision=None) -> DistMatrix:
    """y := alpha A x + beta y for Hermitian A stored in the ``uplo``
    triangle (``El::Hemv``; ``conj=False`` = ``Symv``).

    Split A = T + S^H where T is the stored (full) triangle and S the
    strict triangle's transpose image: T x rides the N path, S^H x = the
    transposed path on the strict triangle -- both touch ONLY stored
    entries.  The two partial results land [MC,STAR] and [MR,STAR] (the
    reference's two accumulators) and meet on [MC,MR]."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"hemv needs square A, got {A.gshape}")
    _check_vector(x, n, "x")
    tri = _mask_triangle(A, uplo)
    strict = _mask_triangle(A, uplo, strict=True)
    T = jnp.where(tri, A.local, 0)
    S = jnp.where(strict, A.local, 0)
    p1 = redistribute(_matvec_n(T, x, n, A.grid, precision), MC, MR)
    p2 = redistribute(_matvec_t(S, x, n, A.grid, conj, precision), MC, MR)
    prod = p1.with_local(p1.local + p2.local)
    return _axpby(alpha, prod, beta, y, A)


def symv(uplo: str, A: DistMatrix, x: DistMatrix, alpha=1.0, beta=0.0,
         y: DistMatrix | None = None, precision=None) -> DistMatrix:
    return hemv(uplo, A, x, alpha, beta, y, conj=False, precision=precision)


def her2(uplo: str, alpha, x: DistMatrix, y: DistMatrix, A: DistMatrix,
         conj: bool = True, precision=None) -> DistMatrix:
    """A(tri) += alpha x y^H + conj(alpha) y x^H (``El::Her2``/``Syr2``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    _check_vector(x, n, "x")
    _check_vector(y, n, "y")
    x_mc = redistribute(x, MC, STAR)
    y_mc = redistribute(y, MC, STAR)
    x_mr = redistribute(x, MR, STAR)
    y_mr = redistribute(y, MR, STAR)

    def _t(v):
        return (jnp.conj(v.local) if conj else v.local).T

    a2 = jnp.conj(alpha) if conj else alpha
    upd = alpha * jnp.matmul(x_mc.local, _t(y_mr), precision=precision) \
        + a2 * jnp.matmul(y_mc.local, _t(x_mr), precision=precision)
    mask = _mask_triangle(A, uplo)
    return A.with_local(jnp.where(mask, _safe_astype(A.local + upd, A.dtype), A.local))


def trmv(uplo: str, orient: str, A: DistMatrix, x: DistMatrix,
         unit: bool = False, precision=None) -> DistMatrix:
    """x := op(tri(A)) x (``El::Trmv``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    _check_vector(x, n, "x")
    T = jnp.where(_mask_triangle(A, uplo, strict=unit), A.local, 0)
    Adm = A.with_local(T)
    if unit:
        out = gemv(Adm, x, orient=orient, precision=precision)
        return out.with_local(out.local + x.local)
    return gemv(Adm, x, orient=orient, precision=precision)


def trsv(uplo: str, orient: str, A: DistMatrix, b: DistMatrix,
         unit: bool = False, nb: int | None = None,
         precision=None) -> DistMatrix:
    """Solve op(tri(A)) x = b (``El::Trsv``) -- the blocked Trsm with one RHS."""
    _check_vector(b, A.gshape[0], "b")
    return trsm("L", uplo, orient, A, b, unit=unit, nb=nb, precision=precision)
