"""BLAS-like layer (reference: Elemental ``src/blas_like/``)."""
from . import level1
from .level3 import gemm, herk, syrk, trrk, trsm
