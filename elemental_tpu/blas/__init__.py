"""BLAS-like layer (reference: Elemental ``src/blas_like/``)."""
from . import level1
from .level1 import (axpy, scale, zero, fill, entrywise_map, hadamard,
                     conjugate, index_dependent_map, index_dependent_fill,
                     make_trapezoidal, shift_diagonal, make_symmetric,
                     get_diagonal, set_diagonal, update_diagonal,
                     diagonal_scale, diagonal_solve, frobenius_norm,
                     max_norm, one_norm, infinity_norm, entrywise_norm,
                     zero_norm, dot, dotu, nrm2, trace, transpose, adjoint,
                     real_part, imag_part, round_entries, swap, max_abs_loc,
                     min_abs_loc, max_loc, min_loc, scale_trapezoid,
                     axpy_trapezoid, safe_scale, get_submatrix,
                     set_submatrix)
from .level2 import gemv, ger, hemv, symv, her2, trmv, trsv
from .level3 import (gemm, herk, syrk, trrk, trsm, trr2k, her2k, syr2k,
                     hemm, symm, trmm, two_sided_trsm, two_sided_trmm,
                     multishift_trsm, quasi_trsm)
