"""BLAS-like layer (reference: Elemental ``src/blas_like/``)."""
from . import level1
from .level2 import gemv, ger, hemv, symv, her2, trmv, trsv
from .level3 import (gemm, herk, syrk, trrk, trsm, trr2k, her2k, syr2k,
                     hemm, symm, trmm, two_sided_trsm, two_sided_trmm,
                     multishift_trsm)
