"""Level-3 BLAS: SUMMA Gemm, Herk/Syrk, Trrk, blocked Trsm.

Reference: Elemental ``src/blas_like/level3/`` -- ``Gemm.cpp`` +
``Gemm/{NN,NT,TN,TT}.hpp`` (SUMMA stationary-A/B/C variant selection),
``Herk``/``Syrk`` over ``Trrk``, ``Trsm.cpp`` + ``Trsm/*.hpp`` (blocked
panel solves).

TPU-native design: the stacked-storage array of a DistMatrix is a
row/column PERMUTATION of the global matrix (P_S A Q_S' for the cyclic
permutations of the dim strides).  Therefore, whenever two operands agree
on the contraction dimension's stride, their storage arrays multiply
directly -- ``P A Q^T  @  Q B R^T = P (A B) R^T`` -- and GSPMD lowers the
sharded matmul to local MXU calls plus the right ICI collective
(replicated-k: pure local; k sharded on a mesh axis: local + psum over
that axis).  So SUMMA here is: redistribute panels with the engine, then a
plain ``jnp.matmul`` on storage, letting XLA insert the collectives --
the scaling-book recipe, which is exactly what the reference hand-codes
with MPI AllGather + local BLAS + ReduceScatter.

Panel loops are Python-unrolled (static shapes per iteration; jit traces
once per (shape, grid)).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, VC, VR, STAR
from ..core.distmatrix import DistMatrix, zeros as dm_zeros
from ..core.view import view, update_view
from ..obs.tracer import NULL_HOOK as _NULL_HOOK, phase_hook as _phase_hook
from ..redist.engine import redistribute, transpose_dist, panel_spread
from .level1 import _global_indices


def _check_mcmr(*Ms: DistMatrix):
    g = Ms[0].grid
    for A in Ms:
        if A.dist != (MC, MR) or (A.calign, A.ralign) != (0, 0):
            raise ValueError(f"expected zero-aligned [MC,MR] operand, got {A}")
        if A.grid != g:
            raise ValueError("operands on different grids")


# The canonical blocksize rule lives in the tune subsystem (ISSUE 4);
# re-exported here under its historical name for the lapack drivers that
# import it from this module.
from ..tune.policy import blocksize_policy as _blocksize  # noqa: E402


def _resolve_auto(op: str, gshape, dtype, grid, **knobs) -> dict:
    """Route any ``'auto'`` knob through the tuner (cache > cost model);
    explicit values pass through untouched."""
    from ..tune.policy import resolve_knobs
    return resolve_knobs(op, gshape=gshape, dtype=dtype, grid=grid,
                         knobs=knobs)


def _orient(A: DistMatrix, orient: str) -> DistMatrix:
    """Materialize op(A) as a zero-aligned [MC,MR] matrix.

    The engine's transpose-exchange chain ([MR,MC] -> [MC,MR]) makes this a
    handful of fast hops (the reference's ``Transpose`` op does the same via
    ``copy::TransposeDist`` + ``Copy``).
    """
    if orient == "N":
        return A
    return redistribute(transpose_dist(A, conj=(orient == "C")), MC, MR)


def _mask_triangle(C: DistMatrix, uplo: str, strict: bool = False):
    """Boolean mask over C's storage selecting the given global triangle."""
    I, J = _global_indices(C)
    if uplo.upper().startswith("L"):
        return (J[None, :] < I[:, None]) if strict else (J[None, :] <= I[:, None])
    return (J[None, :] > I[:, None]) if strict else (J[None, :] >= I[:, None])


# ---------------------------------------------------------------------
# Gemm (SUMMA)
# ---------------------------------------------------------------------

def gemm(A: DistMatrix, B: DistMatrix, alpha=1.0, beta=0.0, C: DistMatrix | None = None,
         orient_a: str = "N", orient_b: str = "N", alg: str = "auto",
         nb: int | str | None = None, precision=None,
         comm_precision: str | None = None,
         redist_path: str | None = None) -> DistMatrix:
    """C := alpha op(A) op(B) + beta C on [MC,MR] (SUMMA).

    ``alg``: 'auto' routes through the tuning subsystem (measured-cache
    winner first, else the closed-form ring-model cost comparison of the
    SUMMA schedules -- the principled version of the reference's
    largest-operand-stationary heuristic in ``Gemm.cpp``), or one of
    'A' / 'B' / 'C' / 'dot' / 'gspmd' / 'slice' explicitly ('gspmd' =
    single storage matmul, XLA chooses the schedule; 'slice' = the
    one-sided slicing schedule of :func:`_summa_slice` -- three one-shot
    compiled plans, no ring, the tall-skinny/rectangular winner).
    ``nb='auto'`` likewise asks the tuner for the panel width; an
    explicit value always wins ('dot', 'gspmd' and 'slice' ignore it).

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'`` | ``'auto'``)
    selects the wire precision of the SUMMA panel moves (the per-panel
    operand redistributions; GSPMD-inserted contraction psums stay full
    precision): narrow encode -> collective -> decode, 2x fewer bytes on
    the wire.  Opt-in; ``None`` (default) is bit-identical.

    ``redist_path`` (``None`` | ``'chain'`` | ``'direct'`` | ``'auto'``,
    ISSUE 12) selects the route of the per-panel operand redistributions:
    ``'direct'`` replaces the factored multi-hop chains with the one-shot
    compiled plan (``redist.plan``), ``'auto'`` asks the tuner (knob) and
    falls back to the per-call ring-model arbitration.  ``None`` (default)
    keeps the bit-identical chained engine.

    Tiled ``BlockMatrix`` operands are accepted via read-proxy conversion
    (``DistMatrixReadProxy``): they re-lay out to [MC,MR] on entry; the
    result converts back to tiled when every input was tiled.
    """
    from ..core.block import BlockMatrix, as_elemental, block_from_cyclic
    tiled_in = [isinstance(x, BlockMatrix) for x in (A, B, C)
                if x is not None]
    ret_tiled = bool(tiled_in) and all(tiled_in)
    A, B = as_elemental(A), as_elemental(B)
    if C is not None:
        C = as_elemental(C)
    if ret_tiled:
        out = gemm(A, B, alpha, beta, C, orient_a, orient_b, alg, nb,
                   precision, comm_precision, redist_path)
        return block_from_cyclic(out)
    A = _orient(A, orient_a)
    B = _orient(B, orient_b)
    _check_mcmr(A, B)
    m, k = A.gshape
    k2, n = B.gshape
    if k != k2:
        raise ValueError(f"inner dims mismatch: {A.gshape} x {B.gshape}")
    if C is None:
        dts = [A.dtype, B.dtype]
        if isinstance(alpha, complex) or isinstance(beta, complex):
            dts.append(jnp.complex64)
        C = dm_zeros(m, n, MC, MR, A.grid, dtype=jnp.result_type(*dts))
        beta = 0.0
    else:
        _check_mcmr(A, B, C)
        if C.gshape != (m, n):
            raise ValueError(f"C shape {C.gshape} != ({m},{n})")

    if alg == "auto" or isinstance(nb, str) or comm_precision == "auto" \
            or redist_path == "auto":
        kn = _resolve_auto("gemm", (m, k, n), C.dtype, A.grid,
                           alg=alg, nb=nb, comm_precision=comm_precision,
                           redist_path=redist_path)
        alg, nb, comm_precision = kn["alg"], kn["nb"], kn["comm_precision"]
        redist_path = kn.get("redist_path")
    from ..redist.quantize import check_comm_precision
    check_comm_precision(comm_precision)
    cp, rp = comm_precision, redist_path
    tm = _phase_hook("gemm", alg=alg)
    tm.start()
    if alg == "C":
        return _summa_c(alpha, A, B, beta, C, nb, precision, tm, cp, rp)
    if alg == "A":
        return _summa_a(alpha, A, B, beta, C, nb, precision, tm, cp, rp)
    if alg == "B":
        return _summa_b(alpha, A, B, beta, C, nb, precision, tm, cp, rp)
    if alg == "dot":
        return _summa_dot(alpha, A, B, beta, C, precision, tm, cp, rp)
    if alg == "slice":
        return _summa_slice(alpha, A, B, beta, C, precision, tm, cp)
    if alg == "gspmd":
        # one-shot: re-land B's k-rows on A's k-col cyclic order ([MR,STAR]),
        # then a single storage matmul -- GSPMD inserts the psum over mr.
        Bk = redistribute(B, MR, STAR, comm_precision=cp)
        d = jnp.matmul(A.local, Bk.local, precision=precision)
        D = DistMatrix(d, (m, n), MC, STAR, 0, 0, A.grid)
        out = redistribute(D, MC, MR)
        res = C.with_local(_safe_astype(
            alpha * out.local + (beta * C.local if _nonzero(beta) else 0),
            C.dtype))
        tm.tick("panel", 0, res.local)
        return res
    raise ValueError(f"unknown gemm alg {alg!r}")


def _summa_c(alpha, A, B, beta, C, nb, precision, tm=_NULL_HOOK, cp=None,
             rp=None):
    """Stationary-C (``gemm::SUMMA_NNC``): per k-panel, A1 -> [MC,STAR]
    (AllGather over mr), B1 -> [STAR,MR] (AllGather over mc), local MXU
    product accumulates into C's storage."""
    m, k = A.gshape
    n = B.gshape[1]
    r, c = A.grid.height, A.grid.width
    kb = _blocksize(nb, math.lcm(r, c), k)
    acc = beta * C.local if _nonzero(beta) else jnp.zeros_like(C.local)
    for i, s in enumerate(range(0, k, kb)):
        e = min(s + kb, k)
        A1 = redistribute(view(A, cols=(s, e)), MC, STAR,
                          comm_precision=cp, path=rp)
        B1 = redistribute(view(B, rows=(s, e)), STAR, MR,
                          comm_precision=cp, path=rp)
        acc = acc + alpha * jnp.matmul(A1.local, B1.local, precision=precision)
        tm.tick("panel", i, acc)
    return C.with_local(_safe_astype(acc, C.dtype))


def _summa_a(alpha, A, B, beta, C, nb, precision, tm=_NULL_HOOK, cp=None,
             rp=None):
    """Stationary-A (``gemm::SUMMA_NNA``): per C column panel, B1 ->
    [MR,STAR]; the k-contraction is sharded over mr on both operands, so the
    storage matmul lowers to local product + psum over mr -> [MC,STAR]
    partial panel, filtered onto [MC,MR]."""
    m, k = A.gshape
    n = B.gshape[1]
    r, c = A.grid.height, A.grid.width
    jb = _blocksize(nb, c, n)
    out = C.with_local(_safe_astype(beta * C.local, C.dtype)
                       if _nonzero(beta) else jnp.zeros_like(C.local))
    for i, s in enumerate(range(0, n, jb)):
        e = min(s + jb, n)
        B1 = redistribute(view(B, cols=(s, e)), MR, STAR,
                          comm_precision=cp, path=rp)
        d = jnp.matmul(A.local, B1.local, precision=precision)   # [MC,STAR] storage
        D1 = DistMatrix(d, (m, e - s), MC, STAR, 0, 0, A.grid)
        panel = redistribute(D1, MC, MR)
        cur = view(out, cols=(s, e))
        out = update_view(out, cur.with_local(cur.local + _safe_astype(alpha * panel.local, C.dtype)),
                          cols=(s, e))
        tm.tick("panel", i, out.local)
    return out


def _summa_b(alpha, A, B, beta, C, nb, precision, tm=_NULL_HOOK, cp=None,
             rp=None):
    """Stationary-B: per C row panel, A1^T -> [MC,STAR] (so the k-contraction
    is sharded over mc on both operands); local product + psum over mc ->
    [STAR,MR] partial panel, filtered onto [MC,MR]."""
    m, k = A.gshape
    n = B.gshape[1]
    r, c = A.grid.height, A.grid.width
    ib = _blocksize(nb, r, m)
    out = C.with_local(_safe_astype(beta * C.local, C.dtype)
                       if _nonzero(beta) else jnp.zeros_like(C.local))
    for i, s in enumerate(range(0, m, ib)):
        e = min(s + ib, m)
        A1T = redistribute(transpose_dist(view(A, rows=(s, e))), MC, STAR,
                           comm_precision=cp, path=rp)
        d = jnp.matmul(A1T.local.T, B.local, precision=precision)  # [STAR,MR] storage
        D1 = DistMatrix(d, (e - s, n), STAR, MR, 0, 0, A.grid)
        panel = redistribute(D1, MC, MR)
        cur = view(out, rows=(s, e))
        out = update_view(out, cur.with_local(cur.local + _safe_astype(alpha * panel.local, C.dtype)),
                          rows=(s, e))
        tm.tick("panel", i, out.local)
    return out


def _summa_dot(alpha, A, B, beta, C, precision, tm=_NULL_HOOK, cp=None,
               rp=None):
    """SUMMA-Dot (``gemm::SUMMA_NNDot``, the small-C case): shard the
    inner dimension 1-D cyclic on BOTH operands ([STAR,VC] x [VC,STAR] --
    the same cyclic permutation on each side, so the storage matmul
    contracts correctly), local (m, k/p) x (k/p, n) products, one psum
    over all devices into the replicated C, filter onto [MC,MR].

    On a 1x1 grid the storage arrays ARE the global operands, so the
    [STAR,VC] round-trip is pure dispatch overhead: early-out to one local
    matmul.  ``beta`` may be any scalar (incl. complex); a complex result
    landing in a real C still raises through :func:`_safe_astype`."""
    m, n = C.gshape
    if A.grid.size == 1:
        d = jnp.matmul(A.local, B.local, precision=precision)
    else:
        Avc = redistribute(A, STAR, VC, comm_precision=cp, path=rp)
        Bvc = redistribute(B, VC, STAR, comm_precision=cp, path=rp)
        dl = jnp.matmul(Avc.local, Bvc.local, precision=precision)
        D = DistMatrix(dl, (m, n), STAR, STAR, 0, 0, A.grid)
        d = redistribute(D, MC, MR).local
    res = C.with_local(_safe_astype(
        alpha * d + (beta * C.local if _nonzero(beta) else 0),
        C.dtype))
    tm.tick("panel", 0, res.local)
    return res


def _summa_slice(alpha, A, B, beta, C, precision, tm=_NULL_HOOK, cp=None):
    """Slicing-based one-sided gemm (``alg='slice'``, the arXiv 2510.08874
    direction): every device owns one contiguous-cyclic SLICE of C's rows
    (or columns) and gathers, in ONE compiled one-shot plan per operand,
    exactly the A rows (B columns) that slice needs plus the shared small
    operand -- no k-panel ring, no per-panel barrier.

    Row mode (``m >= n`` or an Nx1 grid): A -> [VC,STAR] (each device
    takes its 1-D cyclic row slice -- a single ragged FFD-packed a2a over
    mr), B -> [STAR,STAR] (the small operand, one exchange), then a fully
    LOCAL contraction (k is unsharded on both sides, so no hidden psum)
    lands D = A_slice @ B as [VC,STAR] storage, filtered back onto
    [MC,MR] by a third one-shot plan.  Column mode mirrors with
    [STAR,STAR] x [STAR,VR].  Degeneracies: 1x1 grids early-out to one
    local matmul with ZERO redistributes (pinned); on Nx1 (row mode) and
    1xN (column mode) grids two of the three plans are pure local
    filters, leaving a single collective.

    The slice gathers ride the plan compiler natively
    (``path='direct'``), so ``comm_precision`` composes PER SLOT -- bf16
    cast or int8 block-scale-pack on every packed a2a slot -- and the
    ``redist_path`` knob is moot: the gather IS a one-shot plan.  The
    tuner prices the three plans with the same ``compile_plan`` byte
    math (``tune.cost_model``), which is what makes ``alg='auto'`` pick
    'slice' on tall-skinny / non-square-grid geometry and keep the SUMMA
    twins elsewhere."""
    m, n = C.gshape
    g = A.grid
    if g.size == 1:
        d = jnp.matmul(A.local, B.local, precision=precision)
    else:
        from ..redist.plan import slice_row_mode
        if slice_row_mode(m, n, (g.height, g.width)):
            As = redistribute(A, VC, STAR, comm_precision=cp, path="direct")
            Bs = redistribute(B, STAR, STAR, comm_precision=cp,
                              path="direct")
            dl = jnp.matmul(As.local, Bs.local, precision=precision)
            D = DistMatrix(dl, (m, n), VC, STAR, 0, 0, g)
        else:
            As = redistribute(A, STAR, STAR, comm_precision=cp,
                              path="direct")
            Bs = redistribute(B, STAR, VR, comm_precision=cp, path="direct")
            dl = jnp.matmul(As.local, Bs.local, precision=precision)
            D = DistMatrix(dl, (m, n), STAR, VR, 0, 0, g)
        d = redistribute(D, MC, MR, path="direct").local
    res = C.with_local(_safe_astype(
        alpha * d + (beta * C.local if _nonzero(beta) else 0),
        C.dtype))
    tm.tick("panel", 0, res.local)
    return res


def _nonzero(x) -> bool:
    # complex(0) counts as zero: a 0j beta must not force a complex
    # accumulator (and a TypeError out of _safe_astype) onto a real C
    return not (isinstance(x, (int, float, complex)) and x == 0)


def _safe_astype(x, dtype):
    """astype that refuses to silently drop an imaginary part."""
    if jnp.iscomplexobj(x) and not jnp.issubdtype(dtype, jnp.complexfloating):
        raise TypeError(f"complex result cannot be stored in {dtype} output; "
                        "pass a complex C (or complex operands)")
    return x.astype(dtype)


# ---------------------------------------------------------------------
# Trrk / Herk / Syrk
# ---------------------------------------------------------------------

def trrk(uplo: str, alpha, A_mc: DistMatrix, B_mr: DistMatrix, beta, C: DistMatrix,
         precision=None) -> DistMatrix:
    """Triangular rank-k: C(tri) := alpha A B + beta C(tri), other triangle
    untouched.  A is [MC,STAR], B is [STAR,MR] (the reference's
    ``LocalTrrk``, the factorization trailing-update workhorse).

    TPU note: we compute the full local product and mask -- the MXU doesn't
    exploit triangles, and the masked half is fused away as dead only at the
    boundary tiles; this matches what the reference's recursive Trrk saves
    asymptotically but costs nothing extra in wall-clock on TPU at nb<<n.
    """
    if A_mc.dist != (MC, STAR) or B_mr.dist != (STAR, MR):
        raise ValueError("trrk expects A [MC,STAR], B [STAR,MR]")
    _check_mcmr(C)
    mask = _mask_triangle(C, uplo)
    full = jnp.matmul(A_mc.local, B_mr.local, precision=precision)
    tri_new = alpha * full + beta * C.local
    return C.with_local(jnp.where(mask, _safe_astype(tri_new, C.dtype), C.local))


def herk(uplo: str, A: DistMatrix, alpha=1.0, beta=0.0, C: DistMatrix | None = None,
         orient: str = "N", nb: int | str | None = None, precision=None,
         conj: bool = True, comm_precision: str | None = None,
         redist_path: str | None = None) -> DistMatrix:
    """C(tri) := alpha op(A) op(A)^H + beta C(tri)  (orient 'N' or 'C'/'T').

    Per k-panel: A1 -> [VC,STAR], then the fused engine ``panel_spread``
    produces the [MC,STAR] panel and its [STAR,MR] adjoint in ONE
    collective round (the Cholesky trailing-update chain, cf.
    ``cholesky::LVar3``); masked local update.  ``nb='auto'`` asks the
    tuning subsystem for the k-panel width.  ``comm_precision`` selects
    the wire precision of the panel move + spread (see :func:`gemm`).
    ``redist_path='direct'`` replaces the [VC,STAR] hop + spread (two
    rounds per panel) with one one-shot [MC,MR] -> [STAR,STAR] exchange
    followed by zero-round local filters.
    """
    if orient != "N":
        A = _orient(A, "C" if conj else "T")
    _check_mcmr(A)
    m, k = A.gshape
    if isinstance(nb, str) or comm_precision == "auto" or redist_path == "auto":
        kn = _resolve_auto("herk", (m, k), A.dtype, A.grid, nb=nb,
                           comm_precision=comm_precision,
                           redist_path=redist_path)
        nb, comm_precision = kn["nb"], kn["comm_precision"]
        redist_path = kn.get("redist_path")
    from ..redist.quantize import check_comm_precision
    check_comm_precision(comm_precision)
    r, c = A.grid.height, A.grid.width
    if C is None:
        C = dm_zeros(m, m, MC, MR, A.grid, dtype=A.dtype)
        beta = 0.0
    else:
        _check_mcmr(A, C)
        if C.gshape != (m, m):
            raise ValueError(f"C shape {C.gshape} != ({m},{m})")
    tm = _phase_hook("herk")
    tm.start()
    kb = _blocksize(nb, c, k)
    mask = _mask_triangle(C, uplo)
    acc = beta * C.local if _nonzero(beta) else jnp.zeros_like(C.local)
    for i, s in enumerate(range(0, k, kb)):
        e = min(s + kb, k)
        if redist_path == "direct":
            # One one-shot exchange per panel; the [MC,STAR] panel and its
            # [STAR,MR] adjoint are then zero-round local filters.
            A1_ss = redistribute(view(A, cols=(s, e)), STAR, STAR,
                                 comm_precision=comm_precision, path="direct")
            A1_mc = redistribute(A1_ss, MC, STAR)
            A1H_mr = redistribute(transpose_dist(A1_ss, conj=conj), STAR, MR)
        else:
            A1_vc = redistribute(view(A, cols=(s, e)), VC, STAR,
                                 comm_precision=comm_precision,
                                 path=redist_path)
            A1_mc, A1H_mr = panel_spread(A1_vc, conj=conj,
                                         comm_precision=comm_precision)
        tm.tick("spread", i, A1_mc.local, A1H_mr.local)
        acc = acc + alpha * jnp.matmul(A1_mc.local, A1H_mr.local, precision=precision)
        tm.tick("update", i, acc)
    return C.with_local(jnp.where(mask, _safe_astype(acc, C.dtype), C.local))


def syrk(uplo: str, A: DistMatrix, alpha=1.0, beta=0.0, C: DistMatrix | None = None,
         orient: str = "N", nb: int | None = None, precision=None) -> DistMatrix:
    return herk(uplo, A, alpha, beta, C, orient=orient, nb=nb,
                precision=precision, conj=False)


# ---------------------------------------------------------------------
# Trsm (blocked panel solves)
# ---------------------------------------------------------------------

def trsm(side: str, uplo: str, orient: str, A: DistMatrix, B: DistMatrix,
         alpha=1.0, unit: bool = False, nb: int | str | None = None,
         precision=None, comm_precision: str | None = None,
         redist_path: str | None = None) -> DistMatrix:
    """Solve op(A) X = alpha B (side 'L') or X op(A) = alpha B (side 'R');
    A triangular [MC,MR].  Reference: ``El::Trsm`` 8 side/uplo/orientation
    cases (``src/blas_like/level3/Trsm/*.hpp``).

    ``nb='auto'`` asks the tuning subsystem for the panel width (explicit
    values always win).  Right-side solves reduce to left solves of the
    transposed system (X op(A) = B  <=>  op(A)^T X^T = B^T).
    ``comm_precision`` selects the wire precision of the panel moves
    (diagonal-block gathers, RHS panel transport, off-diagonal operand
    moves; see :func:`gemm`).  ``redist_path`` routes those moves through
    the one-shot plan compiler ('direct'), the hop chain ('chain'/None),
    or measured-constant arbitration ('auto'); right-side solves benefit
    most (the entry/exit transposes collapse from 3-hop chains to one
    exchange each)."""
    if isinstance(nb, str) or comm_precision == "auto" or redist_path == "auto":
        kn = _resolve_auto("trsm", B.gshape, B.dtype, B.grid, nb=nb,
                           comm_precision=comm_precision,
                           redist_path=redist_path)
        nb, comm_precision = kn["nb"], kn["comm_precision"]
        redist_path = kn.get("redist_path")
    from ..redist.quantize import check_comm_precision
    check_comm_precision(comm_precision)
    tm = _phase_hook("trsm")
    tm.start()
    trans = orient in ("T", "C")
    conj = orient == "C"
    if side.upper().startswith("R"):
        BT = redistribute(transpose_dist(B), MC, MR, path=redist_path)
        # op(A)^T: N -> T; T -> N; C -> conj-only (trans=False, conj=True)
        XT = _trsm_left(uplo, not trans, conj, A, BT, alpha, unit, nb,
                        precision, tm, comm_precision, redist_path)
        return redistribute(transpose_dist(XT), MC, MR, path=redist_path)
    return _trsm_left(uplo, trans, conj, A, B, alpha, unit, nb, precision,
                      tm, comm_precision, redist_path)


def _trsm_left(uplo: str, trans: bool, conj: bool, A: DistMatrix, B: DistMatrix,
               alpha, unit: bool, nb: int | None, precision,
               tm=_NULL_HOOK, cp=None, rp=None) -> DistMatrix:
    """All eight left cases.  Effective triangle: uplo XOR trans decides the
    sweep direction; per panel the diagonal block is replicated
    ([STAR,STAR]), the RHS panel goes 1-D cyclic ([STAR,VR]) for the local
    triangular solve, and the off-diagonal product rides
    [MC,STAR] x [STAR,MR] storage (pure local)."""
    _check_mcmr(A, B)
    m, n = B.gshape
    if A.gshape != (m, m):
        raise ValueError(f"A {A.gshape} incompatible with B {B.gshape}")
    lower = uplo.upper().startswith("L")
    r, c = A.grid.height, A.grid.width
    ib = _blocksize(nb, math.lcm(r, c), m)
    X = B.with_local(alpha * B.local if _nonzero(alpha - 1) else B.local)
    starts = list(range(0, m, ib))
    forward = lower != trans        # effective-lower => forward sweep
    if not forward:
        starts = starts[::-1]
    for k, s in enumerate(starts):
        e = min(s + ib, m)
        A11 = redistribute(view(A, rows=(s, e), cols=(s, e)), STAR, STAR,
                           comm_precision=cp, path=rp)
        # mask to the stored triangle so opposite-triangle garbage (e.g. the
        # packed L\U format of lu()) can never leak into the solve
        a11 = jnp.tril(A11.local) if lower else jnp.triu(A11.local)
        B1 = redistribute(view(X, rows=(s, e)), STAR, VR, comm_precision=cp,
                          path=rp)
        x1 = lax.linalg.triangular_solve(
            a11, B1.local, left_side=True, lower=lower,
            transpose_a=trans, conjugate_a=conj, unit_diagonal=unit)
        X1 = DistMatrix(x1, B1.gshape, STAR, VR, 0, 0, A.grid)
        X1_mr = redistribute(X1, STAR, MR, comm_precision=cp, path=rp)
        X = update_view(X, redistribute(X1_mr, MC, MR), rows=(s, e))  # local filter
        tm.tick("solve", k, X.local)
        # trailing update of the not-yet-solved rows
        lo, hi = (e, m) if forward else (0, s)
        if lo >= hi:
            continue
        if trans:
            # T21 = op(A)[hi-part, s:e] = op(A[s:e, hi-part])
            A1p = redistribute(view(A, rows=(s, e), cols=(lo, hi)), STAR, MC,
                               comm_precision=cp, path=rp)
            a_loc = A1p.local.T            # [MC,STAR]-storage of A1p^T
        else:
            A1p = redistribute(view(A, rows=(lo, hi), cols=(s, e)), MC, STAR,
                               comm_precision=cp, path=rp)
            a_loc = A1p.local
        if conj:
            a_loc = jnp.conj(a_loc)
        X = local_rank_update(X, a_loc, X1_mr.local, rows=(lo, hi),
                              precision=precision)
        tm.tick("update", k, X.local)
    return X


def local_rank_update(C: DistMatrix, A_loc, B_loc, rows=None, cols=None,
                      alpha=-1.0, precision=None) -> DistMatrix:
    """C[rows, cols] += alpha * A_loc @ B_loc on storage, pure-local.

    ``A_loc`` / ``B_loc`` are the STORAGE arrays of conforming [MC,STAR]
    and [STAR,MR] operands (rows/cols of the product land exactly on the
    view's cyclic layout), so the whole rank-k update is one local MXU
    matmul + writeback -- the reference's ``LocalGemm`` trailing-update
    idiom shared by trsm, quasi_trsm and the LU/look-ahead drivers."""
    sub = view(C, rows=rows, cols=cols)
    upd = jnp.matmul(A_loc, B_loc, precision=precision)
    new = sub.local + (alpha * upd).astype(C.dtype)
    return update_view(C, sub.with_local(new), rows=rows, cols=cols)


def quasi_trsm(side: str, orient: str, A: DistMatrix, B: DistMatrix,
               alpha=1.0, nb: int | None = None, precision=None
               ) -> DistMatrix:
    """Solve op(T) X = alpha B (side 'L') or X op(T) = alpha B (side 'R')
    with T UPPER QUASI-TRIANGULAR (real Schur form: 1x1/2x2 diagonal
    blocks, i.e. an upper triangle plus isolated subdiagonal entries).
    Reference: ``El::QuasiTrsm`` (``src/blas_like/level3/QuasiTrsm/``).

    TPU shape: ONE host read of T's subdiagonal places the panel splits
    so no 2x2 block is cut; each replicated diagonal block then solves
    with a small general ``jnp.linalg.solve`` (quasi-triangular blocks
    are not XLA-triangular-solvable), and the off-panel updates are the
    standard trsm SUMMA products -- the strictly-lower region outside the
    bumps is zero, so the update blocks are genuinely triangular."""
    trans = orient in ("T", "C")
    conj = orient == "C"
    if side.upper().startswith("R"):
        BT = redistribute(transpose_dist(B), MC, MR)
        XT = _quasi_trsm_left(not trans, conj, A, BT, alpha, nb, precision)
        return redistribute(transpose_dist(XT), MC, MR)
    return _quasi_trsm_left(trans, conj, A, B, alpha, nb, precision)


def _quasi_trsm_left(trans: bool, conj: bool, A: DistMatrix, B: DistMatrix,
                     alpha, nb: int | None, precision) -> DistMatrix:
    from ..blas.level1 import get_diagonal
    _check_mcmr(A, B)
    m, n = B.gshape
    if A.gshape != (m, m):
        raise ValueError(f"A {A.gshape} incompatible with B {B.gshape}")
    r, c = A.grid.height, A.grid.width
    grain = math.lcm(r, c)
    ib = _blocksize(nb, grain, m)
    # bump map (one O(m) host sync): a split at e is legal iff sub[e-1]==0.
    # Splits must stay on the distribution grain (view offsets are
    # stride-multiples), so an illegal split extends by a WHOLE grain.
    sub = np.asarray(get_diagonal(A, offset=-1).local).ravel() if m > 1 \
        else np.zeros(0)
    starts = []
    s = 0
    while s < m:
        e = min(s + ib, m)
        while e < m and sub[e - 1] != 0:
            e = min(e + grain, m)         # never cut a 2x2 block
        starts.append((s, e))
        s = e
    X = B.with_local(alpha * B.local if _nonzero(alpha - 1) else B.local)
    forward = trans                       # effective-upper sweep direction
    if not forward:
        starts = starts[::-1]
    for s, e in starts:
        A11 = redistribute(view(A, rows=(s, e), cols=(s, e)), STAR, STAR)
        a11 = jnp.triu(A11.local, -1)     # upper triangle + the bumps
        B1 = redistribute(view(X, rows=(s, e)), STAR, VR)
        op = a11.T if trans else a11
        if conj:
            op = jnp.conj(op)
        x1 = jnp.linalg.solve(op, B1.local)
        X1 = DistMatrix(x1.astype(X.dtype), B1.gshape, STAR, VR, 0, 0, A.grid)
        X1_mr = redistribute(X1, STAR, MR)
        X = update_view(X, redistribute(X1_mr, MC, MR), rows=(s, e))
        lo, hi = (e, m) if forward else (0, s)
        if lo >= hi:
            continue
        if trans:
            A1p = redistribute(view(A, rows=(s, e), cols=(lo, hi)), STAR, MC)
            a_loc = A1p.local.T
        else:
            A1p = redistribute(view(A, rows=(lo, hi), cols=(s, e)), MC, STAR)
            a_loc = A1p.local
        if conj:
            a_loc = jnp.conj(a_loc)
        X = local_rank_update(X, a_loc, X1_mr.local, rows=(lo, hi),
                              precision=precision)
    return X


# ---------------------------------------------------------------------
# Trr2k / Her2k / Syr2k
# ---------------------------------------------------------------------

def trr2k(uplo: str, alpha, A_mc: DistMatrix, B_mr: DistMatrix,
          beta, C_mc: DistMatrix, D_mr: DistMatrix, gamma, E: DistMatrix,
          precision=None) -> DistMatrix:
    """Triangular rank-2k: E(tri) := alpha A B + beta C D + gamma E(tri),
    other triangle untouched (``El::Trr2k`` with [MC,STAR] x [STAR,MR]
    operand pairs -- the reference's ``LocalTrr2k``)."""
    for X, d in ((A_mc, (MC, STAR)), (C_mc, (MC, STAR)),
                 (B_mr, (STAR, MR)), (D_mr, (STAR, MR))):
        if X.dist != d:
            raise ValueError(f"trr2k operand expected {d}, got {X.dist}")
    _check_mcmr(E)
    mask = _mask_triangle(E, uplo)
    full = alpha * jnp.matmul(A_mc.local, B_mr.local, precision=precision) \
        + beta * jnp.matmul(C_mc.local, D_mr.local, precision=precision)
    return E.with_local(jnp.where(mask, _safe_astype(full + gamma * E.local, E.dtype),
                                  E.local))


def her2k(uplo: str, A: DistMatrix, B: DistMatrix, alpha=1.0, beta=0.0,
          C: DistMatrix | None = None, orient: str = "N", conj: bool = True,
          nb: int | None = None, precision=None) -> DistMatrix:
    """C(tri) := alpha op(A) op(B)^H + conj(alpha) op(B) op(A)^H + beta C(tri)
    (``El::Her2k``; ``conj=False`` gives ``Syr2k`` with ^T and coefficient
    alpha on both products).

    Same panel schedule as :func:`herk` (the ``cholesky::LVar3`` chain via
    the fused ``panel_spread``), two masked storage products per k-panel."""
    if orient != "N":
        A = _orient(A, "C" if conj else "T")
        B = _orient(B, "C" if conj else "T")
    _check_mcmr(A, B)
    m, k = A.gshape
    if B.gshape != (m, k):
        raise ValueError(f"her2k needs conformal A,B; got {A.gshape} vs {B.gshape}")
    r, c = A.grid.height, A.grid.width
    if C is None:
        dts = [A.dtype, B.dtype]
        if isinstance(alpha, complex):
            dts.append(jnp.complex64)
        C = dm_zeros(m, m, MC, MR, A.grid, dtype=jnp.result_type(*dts))
        beta = 0.0
    else:
        _check_mcmr(C)
        if C.gshape != (m, m):
            raise ValueError(f"C shape {C.gshape} != ({m},{m})")
    kb = _blocksize(nb, c, k)
    mask = _mask_triangle(C, uplo)
    alpha2 = jnp.conj(alpha) if conj else alpha
    acc = beta * C.local if _nonzero(beta) else jnp.zeros_like(C.local)
    for s in range(0, k, kb):
        e = min(s + kb, k)
        A1_vc = redistribute(view(A, cols=(s, e)), VC, STAR)
        B1_vc = redistribute(view(B, cols=(s, e)), VC, STAR)
        A1_mc, A1H_mr = panel_spread(A1_vc, conj=conj)
        B1_mc, B1H_mr = panel_spread(B1_vc, conj=conj)
        acc = acc + alpha * jnp.matmul(A1_mc.local, B1H_mr.local, precision=precision) \
            + alpha2 * jnp.matmul(B1_mc.local, A1H_mr.local, precision=precision)
    return C.with_local(jnp.where(mask, _safe_astype(acc, C.dtype), C.local))


def syr2k(uplo: str, A: DistMatrix, B: DistMatrix, alpha=1.0, beta=0.0,
          C: DistMatrix | None = None, orient: str = "N",
          nb: int | None = None, precision=None) -> DistMatrix:
    return her2k(uplo, A, B, alpha, beta, C, orient=orient, conj=False,
                 nb=nb, precision=precision)


# ---------------------------------------------------------------------
# Symm / Hemm / Trmm
# ---------------------------------------------------------------------

def hemm(side: str, uplo: str, A: DistMatrix, B: DistMatrix, alpha=1.0,
         beta=0.0, C: DistMatrix | None = None, conj: bool = True,
         nb: int | None = None, precision=None) -> DistMatrix:
    """C := alpha A B + beta C (side 'L') or alpha B A + beta C ('R') with
    Hermitian A stored in the ``uplo`` triangle (``El::Hemm``;
    ``conj=False`` = ``Symm``).

    TPU-first: materialize the full Hermitian operand once (one
    transpose-exchange redistribution, ``MakeSymmetric``) and run plain
    SUMMA -- the MXU prefers one large dense product over the reference's
    two half-panel accumulations; the one-triangle ACCESS guarantee is kept
    (make_symmetric reads only the stored triangle)."""
    from .level1 import make_symmetric
    _check_mcmr(A, B)
    full = make_symmetric(A, uplo, conj=conj)
    if side.upper().startswith("L"):
        return gemm(full, B, alpha=alpha, beta=beta, C=C, nb=nb, precision=precision)
    return gemm(B, full, alpha=alpha, beta=beta, C=C, nb=nb, precision=precision)


def symm(side: str, uplo: str, A: DistMatrix, B: DistMatrix, alpha=1.0,
         beta=0.0, C: DistMatrix | None = None, nb: int | None = None,
         precision=None) -> DistMatrix:
    return hemm(side, uplo, A, B, alpha, beta, C, conj=False, nb=nb,
                precision=precision)


def trmm(side: str, uplo: str, orient: str, A: DistMatrix, B: DistMatrix,
         alpha=1.0, unit: bool = False, nb: int | None = None,
         precision=None) -> DistMatrix:
    """B := alpha op(tri(A)) B ('L') or alpha B op(tri(A)) ('R')
    (``El::Trmm``).  The triangle (with optional implicit unit diagonal) is
    masked on storage; the product is plain SUMMA."""
    from .level1 import _global_indices
    _check_mcmr(A, B)
    T = jnp.where(_mask_triangle(A, uplo, strict=unit), A.local, 0)
    if unit:
        I, J = _global_indices(A)
        on = (J[None, :] == I[:, None]) & (I[:, None] < A.gshape[0])
        T = jnp.where(on, jnp.asarray(1, A.dtype), T)
    Tm = A.with_local(T)
    if side.upper().startswith("L"):
        return gemm(Tm, B, alpha=alpha, orient_a=orient, nb=nb, precision=precision)
    return gemm(B, Tm, alpha=alpha, orient_b=orient, nb=nb, precision=precision)


# ---------------------------------------------------------------------
# Two-sided transforms (generalized eigenproblem reductions)
# ---------------------------------------------------------------------

def two_sided_trsm(uplo: str, A: DistMatrix, L: DistMatrix,
                   nb: int | None = None, precision=None) -> DistMatrix:
    """Congruence solve: lower -> inv(L) A inv(L)^H, upper -> inv(U)^H A inv(U)
    (``El::TwoSidedTrsm`` -- reduces A x = lambda B x with B = L L^H /
    U^H U to a standard Hermitian problem).  A is read from the ``uplo``
    triangle; the result is returned full (Hermitian)."""
    from .level1 import make_symmetric
    full = make_symmetric(A, uplo, conj=True)
    if uplo.upper().startswith("L"):
        Y = trsm("L", "L", "N", L, full, nb=nb, precision=precision)
        return trsm("R", "L", "C", L, Y, nb=nb, precision=precision)
    Y = trsm("L", "U", "C", L, full, nb=nb, precision=precision)
    return trsm("R", "U", "N", L, Y, nb=nb, precision=precision)


def two_sided_trmm(uplo: str, A: DistMatrix, L: DistMatrix,
                   nb: int | None = None, precision=None) -> DistMatrix:
    """Congruence product: lower -> L^H A L, upper -> U A U^H
    (``El::TwoSidedTrmm`` -- the inverse transform of two_sided_trsm)."""
    from .level1 import make_symmetric
    full = make_symmetric(A, uplo, conj=True)
    if uplo.upper().startswith("L"):
        Y = trmm("L", "L", "C", L, full, nb=nb, precision=precision)
        return trmm("R", "L", "N", L, Y, nb=nb, precision=precision)
    Y = trmm("L", "U", "N", L, full, nb=nb, precision=precision)
    return trmm("R", "U", "C", L, Y, nb=nb, precision=precision)


# ---------------------------------------------------------------------
# MultiShiftTrsm (the Pseudospectra / TriangEig engine)
# ---------------------------------------------------------------------

def _star_vr_colmap(n: int, p: int):
    """Static [STAR,VR] storage-column -> global-column map (zero align):
    (clipped global index per storage column, in-range mask)."""
    lc = -(-n // p)
    q = np.arange(p)[:, None]
    jl = np.arange(lc)[None, :]
    perm = (jl * p + q).reshape(-1)
    return jnp.asarray(np.clip(perm, 0, n - 1)), jnp.asarray(perm < n)


def multishift_trsm(uplo: str, orient: str, A: DistMatrix, shifts,
                    B: DistMatrix, alpha=1.0, nb: int | None = None,
                    precision=None, diag_hook=None) -> DistMatrix:
    """Solve (op(tri(A)) - shifts[j] I) X[:, j] = alpha B[:, j] for all j at
    once (``El::MultiShiftTrsm``, ``src/blas_like/level3/MultiShiftTrsm/``).

    Same blocked sweep as :func:`trsm`; the diagonal-block solve becomes a
    column-batched shifted triangular solve on the [STAR,VR] panel (each
    storage column's shift selected by the static cyclic column permutation
    -- pure local, zero extra communication), and the trailing update is
    shift-free (shifts only touch diagonal blocks).

    ``diag_hook(M, sigma, global_col, global_rows)``, if given, may rewrite
    the shifted diagonal block per column before the solve (TriangEig's
    identity-row replacement rides this)."""
    trans = orient in ("T", "C")
    conj = orient == "C"
    _check_mcmr(A, B)
    m, n = B.gshape
    if A.gshape != (m, m):
        raise ValueError(f"A {A.gshape} incompatible with B {B.gshape}")
    shifts = jnp.asarray(shifts)
    if shifts.shape != (n,):
        raise ValueError(f"shifts must be ({n},), got {shifts.shape}")
    lower = uplo.upper().startswith("L")
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    ib = _blocksize(nb, math.lcm(r, c), m)
    gcol, in_range = _star_vr_colmap(n, p)
    sig_stor = jnp.where(in_range, jnp.take(shifts, gcol), 0)
    # (op(M) - sigma I) = op(M - sigma' I): diagonal untouched by T, conj by C
    sig_eff = jnp.conj(sig_stor) if conj else sig_stor

    X = B.with_local(alpha * B.local if _nonzero(alpha - 1) else B.local)
    starts = list(range(0, m, ib))
    forward = lower != trans
    if not forward:
        starts = starts[::-1]
    for s in starts:
        e = min(s + ib, m)
        A11 = redistribute(view(A, rows=(s, e), cols=(s, e)), STAR, STAR)
        a11 = jnp.tril(A11.local) if lower else jnp.triu(A11.local)
        B1 = redistribute(view(X, rows=(s, e)), STAR, VR)
        d = a11.shape[0]
        eye = jnp.eye(d, dtype=a11.dtype)
        rowg = s + jnp.arange(d)

        def _one(sg, jg, b):
            M = a11 - sg * eye
            if diag_hook is not None:
                M = diag_hook(M, sg, jg, rowg)
            return lax.linalg.triangular_solve(
                M, b[:, None], left_side=True, lower=lower,
                transpose_a=trans, conjugate_a=conj)[:, 0]

        x1 = jax.vmap(_one, in_axes=(0, 0, 1), out_axes=1)(
            sig_eff.astype(a11.dtype), gcol, B1.local)
        X1 = DistMatrix(x1, B1.gshape, STAR, VR, 0, 0, g)
        X1_mr = redistribute(X1, STAR, MR)
        X = update_view(X, redistribute(X1_mr, MC, MR), rows=(s, e))
        lo, hi = (e, m) if forward else (0, s)
        if lo >= hi:
            continue
        if trans:
            A1p = redistribute(view(A, rows=(s, e), cols=(lo, hi)), STAR, MC)
            a_loc = A1p.local.T
        else:
            A1p = redistribute(view(A, rows=(lo, hi), cols=(s, e)), MC, STAR)
            a_loc = A1p.local
        if conj:
            a_loc = jnp.conj(a_loc)
        upd = jnp.matmul(a_loc, X1_mr.local, precision=precision)
        rest = view(X, rows=(lo, hi))
        X = update_view(X, rest.with_local(rest.local - upd.astype(X.dtype)),
                        rows=(lo, hi))
    return X
