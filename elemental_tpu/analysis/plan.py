"""Structured comm plans (``comm_plan/v1``): totals, sites, JSON, diff.

A :class:`CommPlan` bundles the collective events extracted by
:mod:`.jaxpr_walk` with the Python-level redistribution log recorded by
:func:`elemental_tpu.redist.engine.redist_trace` for one traced driver
call.  The JSON document (``comm_plan/v1``) is what ``perf/comm_audit.py``
emits and what the golden snapshots under ``tests/golden/comm_plans/``
pin:

    {"schema": "comm_plan/v1",
     "driver": "cholesky_lookahead", "grid": [2, 2],
     "n": 64, "nb": 16, "dtype": "float32",
     "static": true,                  # no while-loop collectives
     "totals": {"all_gather": {"count": 3, "bytes": 12288}, ...},
     "sites":  [{"prim", "axes", "axis_size", "shape", "dtype",
                 "count", "bytes"}, ...],          # aggregated, sorted
     "redistributes": {"[MC,MR]->[STAR,STAR]": 2, "panel_spread": 1, ...},
     "events": [...]}                 # full per-event detail (audit only)

Golden snapshots store the document WITHOUT the ``events`` list (sites +
totals pin the schedule; the event list is for human audits).  ``diff``
reports per-key mismatches so a CI failure names the collective that
regressed instead of dumping two JSON blobs.
"""
from __future__ import annotations

import dataclasses
import json

SCHEMA = "comm_plan/v1"


@dataclasses.dataclass
class CommPlan:
    """The extracted comm schedule of one traced driver call."""
    driver: str
    grid: tuple                      # (r, c)
    meta: dict                       # n, nb, dtype, extra driver knobs
    events: list                     # list[CollectiveEvent]
    redistributes: dict              # "{src}->{dst}" -> python call count

    # ---- aggregation -------------------------------------------------
    def totals(self) -> dict:
        """Per-collective ``{"count": N, "bytes": B}`` over all events."""
        out: dict = {}
        for ev in self.events:
            t = out.setdefault(ev.prim, {"count": 0, "bytes": 0})
            t["count"] += ev.count
            t["bytes"] += ev.total_bytes
        return dict(sorted(out.items()))

    def sites(self) -> list:
        """Events aggregated by (prim, axes, axis_size, shape, dtype)."""
        agg: dict = {}
        for ev in self.events:
            key = (ev.prim, ev.axes, ev.axis_size, ev.shape, ev.dtype)
            s = agg.setdefault(key, {"count": 0, "bytes": 0})
            s["count"] += ev.count
            s["bytes"] += ev.total_bytes
        rows = []
        for (prim, axes, size, shape, dtype), s in sorted(
                agg.items(), key=lambda kv: repr(kv[0])):
            rows.append({"prim": prim, "axes": list(axes), "axis_size": size,
                         "shape": list(shape), "dtype": dtype,
                         "count": s["count"], "bytes": s["bytes"]})
        return rows

    @property
    def static(self) -> bool:
        """True when every collective has a statically known trip count."""
        return all(ev.static for ev in self.events)

    def count(self, prim: str) -> int:
        return self.totals().get(prim, {}).get("count", 0)

    # ---- serialization ----------------------------------------------
    def to_doc(self, events: bool = True) -> dict:
        doc = {"schema": SCHEMA, "driver": self.driver,
               "grid": list(self.grid)}
        doc.update(self.meta)
        doc["static"] = self.static
        doc["totals"] = self.totals()
        doc["sites"] = self.sites()
        doc["redistributes"] = dict(sorted(self.redistributes.items()))
        if events:
            doc["events"] = [ev.to_doc() for ev in self.events]
        return doc

    def to_json(self, events: bool = True, indent: int = 1) -> str:
        return json.dumps(self.to_doc(events=events), indent=indent,
                          sort_keys=False)


def plan_from_parts(driver: str, grid, meta: dict, events, redist_log) -> CommPlan:
    """Assemble a CommPlan from walker events + an engine redist log."""
    redist: dict = {}
    for rec in redist_log:
        redist[rec.label] = redist.get(rec.label, 0) + 1
    return CommPlan(driver=driver, grid=tuple(grid), meta=dict(meta),
                    events=list(events), redistributes=redist)


def golden_doc(plan: CommPlan) -> dict:
    """The snapshot form: the plan document without per-event detail."""
    return plan.to_doc(events=False)


def diff_docs(golden: dict, current: dict) -> list:
    """Human-readable mismatch lines between two comm_plan/v1 documents.

    Compares schema/grid/meta scalars, per-collective totals, the
    aggregated sites table, and redistribute call counts.  Returns [] when
    the plans agree (the golden gate passes)."""
    lines: list = []
    for key in ("schema", "driver", "grid", "n", "nb", "dtype", "static"):
        if golden.get(key) != current.get(key):
            lines.append(f"{key}: golden={golden.get(key)!r} "
                         f"current={current.get(key)!r}")
    gt, ct = golden.get("totals", {}), current.get("totals", {})
    for prim in sorted(set(gt) | set(ct)):
        g, c = gt.get(prim), ct.get(prim)
        if g != c:
            lines.append(f"totals[{prim}]: golden={g} current={c}")
    gr, cr = golden.get("redistributes", {}), current.get("redistributes", {})
    for key in sorted(set(gr) | set(cr)):
        g, c = gr.get(key, 0), cr.get(key, 0)
        if g != c:
            lines.append(f"redistributes[{key}]: golden={g} current={c}")
    gs = set(_hashable_sites(golden))
    cs = set(_hashable_sites(current))

    def _row(t):
        return json.dumps(dict(t), sort_keys=True, default=str)

    for row in gs:
        if row not in cs:
            lines.append(f"site missing vs golden: {_row(row)}")
    for row in cs:
        if row not in gs:
            lines.append(f"site not in golden: {_row(row)}")
    return lines


def _hashable_sites(doc: dict):
    return [tuple(sorted(((k, tuple(v) if isinstance(v, list) else v)
                          for k, v in s.items())))
            for s in doc.get("sites", [])]
