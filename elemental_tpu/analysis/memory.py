"""Static memory-plan analyzer (ISSUE 18): jaxpr liveness -> peak bytes.

The memory twin of the comm-plan subsystem.  Where :mod:`.jaxpr_walk`
extracts every collective a traced driver issues, this module walks the
SAME closed jaxpr and computes what the program keeps *resident*:

* **per-device peak live bytes** -- a last-use liveness walk over every
  equation, recursing into ``pjit`` calls, ``shard_map`` bodies and
  ``scan``/``while``/``cond`` sub-jaxprs exactly like the collective
  walker.  Inside ``shard_map`` the avals are already per-device and are
  counted verbatim; outside, stacked-storage arrays are sharded over the
  mesh (``DistMatrix.spec`` tiles the storage array), so top-level avals
  count at ``ceil(bytes / p)``.  The known blind spot of that model --
  replicated residents whose storage aval LOOKS sharded -- is closed by
  the census below, not hand-waved;
* **a timeline of high-water marks** -- every time the live total sets a
  new peak, the (nesting path, primitive, live bytes) triple is recorded,
  so a regression names the scope that grew instead of a bare number;
* **a census of replicated materializations** -- every engine
  redistribution whose destination form keeps more than one copy of the
  operand per ``p`` devices ( ``[STAR,STAR]`` gathers, the ``[MC,STAR]``
  / ``[STAR,MR]`` panel forms, root-only ``[CIRC,CIRC]``), with the
  per-device bytes it costs OVER the evenly-sharded model.  The headline
  ``peak_bytes`` = walk peak + the largest single replicated extra (at
  least one replicated form is live at its own high-water mark; summing
  all of them would double-count sequential panel gathers that free
  between steps).

``while`` bodies have no static trip count, so allocations inside them
are EXCLUDED from the pinned ``peak_bytes`` and accumulated separately as
``nonstatic_peak_bytes`` -- surfaced by lint (EL006 folds it into the
budget check), never silently folded into a golden number.

The ``memory_plan/v1`` JSON document is pinned per registered driver
variant under ``tests/golden/memory_plans/`` by the same CLI pattern as
the comm plans: ``python -m perf.comm_audit mem|mem-diff
--update-golden``.

This module also owns the static VMEM cross-check behind lint EL007:
:func:`check_panel_vmem` recomputes, per fused-kernel dispatch site in
``kernels/``, BOTH the bytes the :meth:`PanelPlan.use_pallas` gate prices
(``copies`` tile-padded residents) and the bytes the kernel actually
allocates (its real ``pallas_call`` out_shapes + in-kernel carries,
including the square LANE-padding the Cholesky/larft kernels apply that
the gate's (8, 128) tile padding understates) -- proving the 16 MiB gate
conservative instead of trusting it.
"""
from __future__ import annotations

import dataclasses
import json

import numpy as np

try:
    from jax.extend import core as jcore
except ImportError:                                    # pragma: no cover
    from jax import core as jcore

from ..core.dist import stride as dist_stride
from ..kernels.common import LANE, PANEL_VMEM_BUDGET, SUBLANE, round_up
from .jaxpr_walk import _scope_label, _sub_jaxprs

MEM_SCHEMA = "memory_plan/v1"

#: high-water marks kept in the timeline (peaks are monotone, so these
#: are the LAST -- i.e. highest -- marks of the walk)
TIMELINE_CAP = 8


# ---------------------------------------------------------------------
# liveness walk
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HighWater:
    """One new-peak event of the liveness walk."""
    live_bytes: int
    path: tuple                  # nesting scopes from the root jaxpr
    prim: str                    # primitive whose output set the peak

    def to_doc(self) -> dict:
        return {"live_bytes": self.live_bytes, "path": "/".join(self.path),
                "prim": self.prim}


@dataclasses.dataclass
class WalkStats:
    """The liveness walk's result for one closed jaxpr."""
    peak_bytes: int              # per-device peak live (static scopes only)
    peak_path: tuple
    peak_prim: str
    args_bytes: int              # per-device input + trace-const residency
    outs_bytes: int              # per-device output residency
    timeline: list               # list[HighWater], last TIMELINE_CAP peaks
    nonstatic_peak_bytes: int    # high water of while-body allocations

    @property
    def static(self) -> bool:
        return self.nonstatic_peak_bytes == 0


class _State:
    __slots__ = ("live", "peak", "peak_path", "peak_prim", "timeline",
                 "ns_live", "ns_peak")

    def __init__(self):
        self.live = 0
        self.peak = 0
        self.peak_path = ()
        self.peak_prim = ""
        self.timeline = []
        self.ns_live = 0
        self.ns_peak = 0

    def alloc(self, nbytes: int, path, prim: str, static: bool) -> None:
        if nbytes <= 0:
            return
        if not static:
            self.ns_live += nbytes
            if self.ns_live > self.ns_peak:
                self.ns_peak = self.ns_live
            return
        self.live += nbytes
        if self.live > self.peak:
            self.peak = self.live
            self.peak_path = path
            self.peak_prim = prim
            self.timeline.append(HighWater(self.live, path, prim))
            if len(self.timeline) > TIMELINE_CAP:
                self.timeline.pop(0)

    def free(self, nbytes: int, static: bool) -> None:
        if nbytes <= 0:
            return
        if static:
            self.live -= nbytes
        else:
            self.ns_live -= nbytes


def _aval_bytes(aval, div: int) -> int:
    """Per-device bytes of one aval: total bytes / ``div``, ceil'd.

    ``div`` is the device count for top-level (storage-sharded) scopes
    and 1 inside ``shard_map`` bodies, where avals are already local."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        n = 1
        for s in shape:
            n *= int(s)
        nbytes = n * np.dtype(dtype).itemsize
    except (TypeError, ValueError):      # symbolic dims / exotic dtypes
        return 0
    return -(-nbytes // max(int(div), 1))


def _walk_scope(jaxpr, div: int, path: tuple, static: bool,
                state: _State) -> None:
    """Liveness walk of one scope.

    Protocol: the scope's invars/constvars are the CALLER's residents
    (aliased, never double counted here); everything allocated inside --
    including the scope's outvars -- is freed on exit, and the caller
    allocates its own eqn outvars afterward.  The transient "freed then
    re-allocated" boundary never lowers the recorded peak because the
    peak was taken while the scope's outputs were live inside it."""
    last: dict = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last[v] = idx
    end = len(jaxpr.eqns)
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last[v] = end
    inner: dict = {}                     # var -> (bytes, static)
    for idx, eqn in enumerate(jaxpr.eqns):
        prim = eqn.primitive.name
        sub_div = 1 if prim == "shard_map" else div
        sub_static = static and prim != "while"
        label = _scope_label(eqn)
        if prim == "cond":
            # branches walked from the same entry residency; free-on-exit
            # makes the recorded peak the max over branches
            for i, branch in enumerate(eqn.params.get("branches", ())):
                for sub in _sub_jaxprs(branch):
                    _walk_scope(sub, sub_div, path + (f"cond[{i}]",),
                                sub_static, state)
        else:
            for val in eqn.params.values():
                for sub in _sub_jaxprs(val):
                    _walk_scope(sub, sub_div, path + (label,),
                                sub_static, state)
        for v in eqn.outvars:
            b = _aval_bytes(getattr(v, "aval", None), div)
            state.alloc(b, path, prim, static)
            if isinstance(v, jcore.Var) and last.get(v, -1) > idx:
                inner[v] = (b, static)
            else:                        # DropVar / immediately dead
                state.free(b, static)
        for v in set(x for x in eqn.invars if isinstance(x, jcore.Var)):
            if last.get(v) == idx and v in inner:
                b, st = inner.pop(v)
                state.free(b, st)
    for b, st in inner.values():
        state.free(b, st)


def analyze_jaxpr(closed_jaxpr, grid_size: int = 1) -> WalkStats:
    """Liveness-walk a closed jaxpr; return per-device :class:`WalkStats`.

    ``grid_size`` is the device count ``p`` of the traced mesh: top-level
    storage avals count at ``ceil(bytes / p)`` (see module docstring for
    the replicated-form caveat and its census-based correction)."""
    jaxpr = closed_jaxpr.jaxpr \
        if isinstance(closed_jaxpr, jcore.ClosedJaxpr) else closed_jaxpr
    consts = getattr(closed_jaxpr, "consts", ())
    div = max(int(grid_size), 1)
    state = _State()
    args = 0
    for v in jaxpr.invars:
        args += _aval_bytes(getattr(v, "aval", None), div)
    for c in consts:
        nb = getattr(c, "nbytes", None)
        if nb is None:
            try:
                nb = np.asarray(c).nbytes
            except (TypeError, ValueError):
                nb = 0
        args += -(-int(nb) // div)
    outs = sum(_aval_bytes(getattr(v, "aval", None), div)
               for v in jaxpr.outvars if isinstance(v, jcore.Var))
    # inputs + trace constants are resident for the whole program
    state.alloc(args, ("<args>",), "input", True)
    _walk_scope(jaxpr, div, (), True, state)
    return WalkStats(peak_bytes=state.peak, peak_path=state.peak_path,
                     peak_prim=state.peak_prim, args_bytes=args,
                     outs_bytes=outs, timeline=list(state.timeline),
                     nonstatic_peak_bytes=state.ns_peak)


# ---------------------------------------------------------------------
# replicated-materialization census (redist-log level)
# ---------------------------------------------------------------------

def _replication(dst, grid_shape) -> int:
    """Copies of the operand per ``p`` devices in the ``dst`` form.

    1 for evenly sharded pairs ([MC,MR], [VC,STAR], ...); ``c`` for
    [MC,STAR]; ``p`` for [STAR,STAR].  [CIRC,CIRC] prices like
    [STAR,STAR]: the root holds the FULL operand, and peak accounting
    cares about the worst device."""
    r, c = int(grid_shape[0]), int(grid_shape[1])
    p = max(r * c, 1)
    cover = min(dist_stride(dst[0], r, c) * dist_stride(dst[1], r, c), p)
    return max(1, p // max(cover, 1))


def replication_census(redist_log, grid_shape) -> dict:
    """Aggregate the engine's redistribution log into the replicated
    section of a ``memory_plan/v1`` document.

    ``extra_bytes`` of one materialization = the per-device bytes its
    destination form keeps ABOVE the evenly-sharded model the liveness
    walk prices (``total * (repl - 1) / p``)."""
    r, c = int(grid_shape[0]), int(grid_shape[1])
    p = max(r * c, 1)
    agg: dict = {}
    star_star = 0
    max_extra = 0
    sum_extra = 0
    for rec in redist_log:
        gs = tuple(rec.grid_shape or (r, c))
        # "panel_spread" produces BOTH panel forms ([MC,*] and [*,MR])
        # from one entry; a plain "redistribute" targets one pair
        dst_pairs = rec.dst if rec.kind == "panel_spread" else (rec.dst,)
        try:
            z = np.dtype(rec.dtype).itemsize
        except TypeError:
            z = 4
        total = int(rec.gshape[0]) * int(rec.gshape[1]) * z
        rec_extra = 0
        for dst in dst_pairs:
            repl = _replication(dst, gs)
            if repl <= 1:
                continue
            names = tuple(d.value for d in dst)
            extra = total * (repl - 1) // max(gs[0] * gs[1], 1)
            if names == ("STAR", "STAR"):
                star_star += 1
            rec_extra += extra
            sum_extra += extra
            key = (f"[{names[0]},{names[1]}]",
                   tuple(int(x) for x in rec.gshape), str(rec.dtype))
            site = agg.setdefault(key, {"count": 0, "extra_bytes": 0})
            site["count"] += 1
            site["extra_bytes"] += extra
        # one entry's forms coexist, so its extras sum for the headline
        max_extra = max(max_extra, rec_extra)
    sites = [{"dst": dst, "gshape": list(gshape), "dtype": dt,
              "count": s["count"], "extra_bytes": s["extra_bytes"]}
             for (dst, gshape, dt), s in sorted(agg.items(),
                                                key=lambda kv: repr(kv[0]))]
    return {"count": sum(s["count"] for s in sites),
            "star_star": star_star, "max_extra_bytes": max_extra,
            "sum_extra_bytes": sum_extra, "sites": sites}


# ---------------------------------------------------------------------
# the memory plan document
# ---------------------------------------------------------------------

@dataclasses.dataclass
class MemoryPlan:
    """The extracted memory profile of one traced driver call."""
    driver: str
    grid: tuple                  # (r, c)
    meta: dict                   # n, nb, dtype, driver knobs (comm-plan meta)
    stats: WalkStats
    replicated: dict             # replication_census() output

    @property
    def peak_bytes(self) -> int:
        """The budgetable headline: walk peak + the largest replicated
        extra (see module docstring for why max, not sum)."""
        return self.stats.peak_bytes + int(
            self.replicated.get("max_extra_bytes", 0))

    @property
    def static(self) -> bool:
        return self.stats.static

    def to_doc(self) -> dict:
        doc = {"schema": MEM_SCHEMA, "driver": self.driver,
               "grid": list(self.grid)}
        doc.update(self.meta)
        doc["static"] = self.static
        doc["peak_bytes"] = self.peak_bytes
        doc["walk_peak_bytes"] = self.stats.peak_bytes
        doc["peak_path"] = "/".join(self.stats.peak_path)
        doc["peak_prim"] = self.stats.peak_prim
        doc["args_bytes"] = self.stats.args_bytes
        doc["outs_bytes"] = self.stats.outs_bytes
        doc["nonstatic_peak_bytes"] = self.stats.nonstatic_peak_bytes
        doc["replicated"] = dict(self.replicated)
        doc["timeline"] = [hw.to_doc() for hw in self.stats.timeline]
        return doc

    def to_json(self, indent: int = 1) -> str:
        return json.dumps(self.to_doc(), indent=indent, sort_keys=False)


def memory_plan(driver: str, grid, meta: dict, closed_jaxpr,
                redist_log=()) -> MemoryPlan:
    """Assemble a :class:`MemoryPlan` from one abstract driver trace."""
    grid = tuple(int(g) for g in grid)
    p = max(grid[0] * grid[1], 1)
    stats = analyze_jaxpr(closed_jaxpr, grid_size=p)
    census = replication_census(redist_log, grid)
    return MemoryPlan(driver=driver, grid=grid, meta=dict(meta),
                      stats=stats, replicated=census)


def trace_memory(name: str, grid, n=None, nb=None, dtype=None):
    """Trace a registered driver and return ``(MemoryPlan, closed_jaxpr,
    redist_log)`` -- the memory twin of :func:`..drivers.trace_driver`."""
    import jax.numpy as jnp
    from .drivers import DEFAULT_N, DEFAULT_NB, trace_driver
    kwargs = {"n": DEFAULT_N if n is None else n,
              "nb": DEFAULT_NB if nb is None else nb}
    if dtype is not None:
        kwargs["dtype"] = dtype
    else:
        kwargs["dtype"] = jnp.float32
    plan, closed, log = trace_driver(name, grid, **kwargs)
    mplan = memory_plan(name, (grid.height, grid.width), plan.meta,
                        closed, log)
    return mplan, closed, log


def golden_mem_doc(mplan: MemoryPlan) -> dict:
    """The snapshot form (currently the full document -- memory plans
    carry no per-event audit detail the way comm plans do)."""
    return mplan.to_doc()


def diff_mem_docs(golden: dict, current: dict) -> list:
    """Human-readable mismatch lines between two memory_plan/v1 docs."""
    lines: list = []
    scalar_keys = ("schema", "driver", "grid", "n", "nb", "dtype", "static",
                   "peak_bytes", "walk_peak_bytes", "peak_path", "peak_prim",
                   "args_bytes", "outs_bytes", "nonstatic_peak_bytes")
    for key in scalar_keys:
        if golden.get(key) != current.get(key):
            lines.append(f"{key}: golden={golden.get(key)!r} "
                         f"current={current.get(key)!r}")
    gr = golden.get("replicated", {})
    cr = current.get("replicated", {})
    for key in ("count", "star_star", "max_extra_bytes", "sum_extra_bytes"):
        if gr.get(key) != cr.get(key):
            lines.append(f"replicated[{key}]: golden={gr.get(key)} "
                         f"current={cr.get(key)}")

    def _rows(doc_rep):
        return set(json.dumps(s, sort_keys=True, default=str)
                   for s in doc_rep.get("sites", []))

    gs, cs = _rows(gr), _rows(cr)
    for row in sorted(gs - cs):
        lines.append(f"replicated site missing vs golden: {row}")
    for row in sorted(cs - gs):
        lines.append(f"replicated site not in golden: {row}")
    gt = golden.get("timeline", [])
    ct = current.get("timeline", [])
    if gt != ct:
        lines.append(f"timeline: golden={len(gt)} mark(s) "
                     f"{json.dumps(gt[-1] if gt else None, default=str)} "
                     f"current={len(ct)} mark(s) "
                     f"{json.dumps(ct[-1] if ct else None, default=str)}")
    return lines


# ---------------------------------------------------------------------
# static VMEM cross-check (lint EL007 support)
# ---------------------------------------------------------------------

#: resident-copy count each driver dispatch site passes to
#: :meth:`PanelPlan.use_pallas` -- pinned against the actual call sites
#: (lapack/lu.py, lapack/cholesky.py, lapack/qr.py) by tests/analysis.
PANEL_GATE_COPIES = {"lu": 3, "cholesky": 4, "qr": 4}


@dataclasses.dataclass(frozen=True)
class PanelVmemCheck:
    """One gate-vs-kernel cross-check of a fused panel dispatch."""
    op: str
    shape: tuple
    dtype: str
    gate_bytes: int              # what use_pallas prices (copies x tiles)
    kernel_bytes: int            # what the pallas_call actually allocates
    budget: int
    admitted: bool               # gate_bytes <= budget (use_pallas yes)
    fits: bool                   # kernel_bytes <= budget

    @property
    def overflow(self) -> bool:
        """True when the gate would admit a kernel that overflows."""
        return self.admitted and not self.fits

    def to_doc(self) -> dict:
        return {"op": self.op, "shape": list(self.shape),
                "dtype": self.dtype, "gate_bytes": self.gate_bytes,
                "kernel_bytes": self.kernel_bytes, "budget": self.budget,
                "admitted": self.admitted, "fits": self.fits}


def kernel_vmem_bytes(op: str, shape, dtype) -> int:
    """The fused kernel's ACTUAL VMEM residents for one panel.

    Read off the real ``pallas_call`` out_shapes + in-kernel functional
    carries:

    * ``lu_panel``: tile-padded input + packed output + the carried
      working panel (3 x (mp, wp)) + the (wp, 1) int32 pivot vector;
    * ``potrf_inv``: the input block is SQUARE-padded to a LANE multiple
      on BOTH axes (``pad_square``) and carried as D/L/Li/T -- 4 square
      residents at ``round_up(w, LANE)``, NOT the gate's (8, 128) tile
      padding;
    * ``qr_panel``: padded input + packed output + carried B (3 x
      (mp, wp)) + the (tp, tp) larft T accumulator + the (wp, 1) tau.
    """
    z = np.dtype(dtype).itemsize
    m, w = int(shape[0]), int(shape[1])
    if op == "cholesky":
        wp = round_up(w, LANE)
        return 4 * wp * wp * z
    mp, wp = round_up(m, SUBLANE), round_up(w, LANE)
    if op == "lu":
        return 3 * mp * wp * z + wp * np.dtype(np.int32).itemsize
    if op == "qr":
        tp = round_up(wp, LANE)
        return 3 * mp * wp * z + tp * tp * z + wp * z
    raise KeyError(f"no fused panel kernel for op {op!r}")


def check_panel_vmem(op: str, shape, dtype="float32", *,
                     budget: int = PANEL_VMEM_BUDGET) -> PanelVmemCheck:
    """Cross-check ONE panel shape: gate pricing vs kernel allocation.

    ``admitted`` reproduces :meth:`PanelPlan.use_pallas` exactly at the
    default budget (asserted by tests/analysis); ``fits`` is the truth
    the gate is supposed to imply."""
    copies = PANEL_GATE_COPIES[op]
    z = np.dtype(dtype).itemsize
    mp = round_up(int(shape[0]), SUBLANE)
    np_ = round_up(int(shape[1]), LANE)
    gate = copies * mp * np_ * z
    kern = kernel_vmem_bytes(op, shape, dtype)
    return PanelVmemCheck(op=op, shape=tuple(int(s) for s in shape),
                          dtype=np.dtype(dtype).name, gate_bytes=gate,
                          kernel_bytes=kern, budget=int(budget),
                          admitted=gate <= budget, fits=kern <= budget)


def panel_shapes(op: str, n: int, nb: int):
    """The panel shapes a blocked sweep of ``op`` at (n, nb) dispatches:
    tall (remaining-rows x block) panels for lu/qr, the (w, w) diagonal
    blocks for cholesky."""
    shapes = []
    for k in range(0, max(int(n), 1), max(int(nb), 1)):
        w = min(int(nb), int(n) - k)
        if w <= 0:
            break
        shapes.append((w, w) if op == "cholesky" else (int(n) - k, w))
    return shapes


def panel_vmem_checks(op: str, n: int, nb: int, dtype="float32", *,
                      budget: int = PANEL_VMEM_BUDGET):
    """Every dispatch-site cross-check of one blocked sweep."""
    return [check_panel_vmem(op, s, dtype, budget=budget)
            for s in panel_shapes(op, n, nb)]
