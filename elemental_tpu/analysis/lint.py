"""Rule-based lints over a comm plan + redistribution trace.

Each rule inspects the statically extracted comm schedule (the jaxpr-level
:class:`~elemental_tpu.analysis.jaxpr_walk.CollectiveEvent` list and/or
the engine's :class:`~elemental_tpu.redist.engine.RedistRecord` log) and
reports :class:`LintFinding` objects.  Rules:

  EL001 fuse-adjacent-gathers   two back-to-back redistributions of the
        SAME [VC,STAR]/[STAR,VC] panel onto the [MC,STAR]+[STAR,MR]
        operand pair -- the exact shape :func:`panel_spread` fuses into
        one collective round (cholesky/herk's trailing chain pre-PR2).
  EL002 redundant-round-trip    a redistribution whose output is fed
        UNTOUCHED (same object -- provably no intervening compute) into a
        redistribution straight back to the source distribution: the pair
        is a no-op costing two collective rounds.  The finding also
        carries the one-shot rewrite (ISSUE 12): its ``fix_hint`` quotes
        the equivalent compiled direct plan -- src->dst, plan kind,
        round count, ring-model byte estimate vs the chain's -- and
        ``perf/comm_audit lint --fix-hint`` prints it.
  EL003 loop-invariant-collective   a collective inside a scan/while body
        whose operands derive only from loop constants -- hoistable.
  EL004 f64-promotion           a collective moving float64/complex128
        bytes in a program traced from <=32-bit inputs: an unintended
        promotion doubling wire bytes (x64 mode makes these easy to leak).
  EL005 bf16-leak               a collective moving bfloat16 outside the
        opt-in ``update_precision`` paths (``allow_bf16`` in the driver
        spec): bf16 on the wire silently halves mantissa everywhere.

``lint_plan`` returns findings sorted by rule id; an empty list means the
plan is clean (the ``perf/comm_audit.py lint`` CLI exits non-zero on any
finding).
"""
from __future__ import annotations

import dataclasses

from .jaxpr_walk import find_loop_invariant_collectives

_NARROW = ("float16", "bfloat16", "float32", "complex64", "int32", "int16",
           "int8", "uint32", "uint16", "uint8", "bool")
_WIDE = ("float64", "complex128")


@dataclasses.dataclass(frozen=True)
class LintFinding:
    rule: str          # "EL00x"
    name: str          # short rule slug
    message: str       # human-readable, names the offending site
    severity: str = "warning"
    fix_hint: str = "" # concrete rewrite suggestion (lint --fix-hint)

    def __str__(self):
        return f"{self.rule} [{self.name}] {self.message}"


# ---------------------------------------------------------------------
# individual rules
# ---------------------------------------------------------------------

def _is_v_panel(dist) -> bool:
    names = tuple(d.value for d in dist)
    return names in (("VC", "STAR"), ("STAR", "VC"),
                     ("VR", "STAR"), ("STAR", "VR"))


def _spread_target(dist) -> bool:
    names = tuple(d.value for d in dist)
    return names in (("MC", "STAR"), ("STAR", "MR"),
                     ("MR", "STAR"), ("STAR", "MC"))


def rule_fuse_adjacent_gathers(plan, redist_log) -> list:
    """EL001: the panel + its adjoint spread issued as separate calls."""
    out = []
    recs = [r for r in redist_log if r.kind == "redistribute"]
    for a, b in zip(recs, recs[1:]):
        if not (_is_v_panel(a.src) and _spread_target(a.dst)):
            continue
        if not (_is_v_panel(b.src) and _spread_target(b.dst)):
            continue
        if a.dst == b.dst:
            continue
        # same panel extents (the adjoint chain transposes the gshape)
        if a.gshape not in (b.gshape, b.gshape[::-1]):
            continue
        out.append(LintFinding(
            "EL001", "fuse-adjacent-gathers",
            f"adjacent panel spreads {a.label} then {b.label} on a "
            f"{a.gshape} panel: fuse into one panel_spread() round "
            f"(one all_gather instead of separate gather chains)"))
    return out


def _direct_rewrite_hint(rec) -> str:
    """The one-shot rewrite of one chained leg (ISSUE 12): compile the
    src->dst direct plan and quote rounds/bytes next to the chain's."""
    gs = tuple(rec.grid_shape or ())
    if len(gs) != 2:
        return ""
    import numpy as np
    from ..redist.plan import compile_plan
    from ..redist.engine import chain_cost
    plan = compile_plan(rec.src, rec.dst, rec.gshape, gs)
    if plan is None:
        return ""
    z = np.dtype(rec.dtype).itemsize
    rounds_c, bytes_c = chain_cost(rec.src, rec.dst, rec.gshape, gs, z)
    return (f"if the {rec.dst[0].value}/{rec.dst[1].value} form is "
            f"actually consumed, route it as redistribute(..., "
            f"path='direct'): one-shot '{plan.kind}' plan for "
            f"{rec.label} at {rec.gshape} on {gs[0]}x{gs[1]} = "
            f"{plan.rounds} round(s) / ~{plan.wire_bytes(z)} B vs the "
            f"chain's {rounds_c} round(s) / ~{bytes_c} B; otherwise "
            f"delete both legs")


def rule_redundant_round_trip(plan, redist_log) -> list:
    """EL002: A->X then X->A on the untouched intermediate."""
    out = []
    recs = [r for r in redist_log if r.kind == "redistribute"]
    by_out = {}
    for r in recs:
        for oid in r.out_ids:
            by_out[oid] = r
    for r in recs:
        prev = by_out.get(r.in_id)
        if prev is None or prev is r:
            continue
        if prev.src == r.dst and prev.dst == r.src \
                and prev.gshape == r.gshape:
            out.append(LintFinding(
                "EL002", "redundant-round-trip",
                f"{prev.label} then {r.label} on the SAME untouched "
                f"{r.gshape} operand: the round trip is a no-op costing "
                f"two redistribution rounds",
                fix_hint=_direct_rewrite_hint(prev)))
    return out


def rule_loop_invariant(plan, closed_jaxpr=None) -> list:
    """EL003: hoistable collectives inside scan/while bodies."""
    if closed_jaxpr is None:
        return []
    out = []
    for prim, path in find_loop_invariant_collectives(closed_jaxpr):
        where = "/".join(path) or "<top>"
        out.append(LintFinding(
            "EL003", "loop-invariant-collective",
            f"{prim} inside {where} has loop-invariant operands: "
            f"hoist it out of the loop body"))
    return out


def rule_f64_promotion(plan) -> list:
    """EL004: wide dtypes on the wire from narrow inputs."""
    in_dtypes = plan.meta.get("input_dtypes") or [plan.meta.get("dtype")]
    if any(str(d) in _WIDE for d in in_dtypes if d):
        return []          # wide inputs: wide collectives are intended
    out = []
    seen = set()
    for ev in plan.events:
        if ev.dtype in _WIDE and (ev.prim, ev.dtype, ev.shape) not in seen:
            seen.add((ev.prim, ev.dtype, ev.shape))
            out.append(LintFinding(
                "EL004", "f64-promotion",
                f"{ev.prim} moves {ev.dtype} {ev.shape} at "
                f"{'/'.join(ev.path)} but the traced inputs are "
                f"{[str(d) for d in in_dtypes]}: unintended promotion "
                f"doubles wire bytes"))
    return out


def rule_bf16_leak(plan) -> list:
    """EL005: bf16 collectives without the update_precision opt-in."""
    if plan.meta.get("allow_bf16"):
        return []
    out = []
    seen = set()
    for ev in plan.events:
        if ev.dtype == "bfloat16" and (ev.prim, ev.shape) not in seen:
            seen.add((ev.prim, ev.shape))
            out.append(LintFinding(
                "EL005", "bf16-leak",
                f"{ev.prim} moves bfloat16 {ev.shape} at "
                f"{'/'.join(ev.path)} without the update_precision "
                f"opt-in: bf16 on the wire halves mantissa silently"))
    return out


def lint_plan(plan, redist_log=(), closed_jaxpr=None) -> list:
    """Run every rule; findings sorted by rule id (empty == clean)."""
    findings = []
    findings += rule_fuse_adjacent_gathers(plan, redist_log)
    findings += rule_redundant_round_trip(plan, redist_log)
    findings += rule_loop_invariant(plan, closed_jaxpr)
    findings += rule_f64_promotion(plan)
    findings += rule_bf16_leak(plan)
    return sorted(findings, key=lambda f: (f.rule, f.message))
