"""Rule-based lints over a comm plan + redistribution trace.

Each rule inspects the statically extracted comm schedule (the jaxpr-level
:class:`~elemental_tpu.analysis.jaxpr_walk.CollectiveEvent` list and/or
the engine's :class:`~elemental_tpu.redist.engine.RedistRecord` log) and
reports :class:`LintFinding` objects.  Rules:

  EL001 fuse-adjacent-gathers   two back-to-back redistributions of the
        SAME [VC,STAR]/[STAR,VC] panel onto the [MC,STAR]+[STAR,MR]
        operand pair -- the exact shape :func:`panel_spread` fuses into
        one collective round (cholesky/herk's trailing chain pre-PR2).
  EL002 redundant-round-trip    a redistribution whose output is fed
        UNTOUCHED (same object -- provably no intervening compute) into a
        redistribution straight back to the source distribution: the pair
        is a no-op costing two collective rounds.  The finding also
        carries the one-shot rewrite (ISSUE 12): its ``fix_hint`` quotes
        the equivalent compiled direct plan -- src->dst, plan kind,
        round count, ring-model byte estimate vs the chain's -- and
        ``perf/comm_audit lint --fix-hint`` prints it.
  EL003 loop-invariant-collective   a collective inside a scan/while body
        whose operands derive only from loop constants -- hoistable.
  EL004 f64-promotion           a collective moving float64/complex128
        bytes in a program traced from <=32-bit inputs: an unintended
        promotion doubling wire bytes (x64 mode makes these easy to leak).
  EL005 bf16-leak               a collective moving bfloat16 outside the
        opt-in ``update_precision`` paths (``allow_bf16`` in the driver
        spec): bf16 on the wire silently halves mantissa everywhere.

Memory rules (ISSUE 18) run over a ``memory_plan/v1``
:class:`~elemental_tpu.analysis.memory.MemoryPlan` via :func:`lint_memory`:

  EL006 peak-over-budget        statically derived per-device peak live
        bytes exceed the driver's declared budget
        (``DriverSpec.mem_budget_factor`` x input+output residency) --
        catches crossover/slice gathers that silently materialize the
        full matrix.  ``while``-body allocations have no static trip
        count; they are excluded from the pinned peak but FOLDED INTO
        this check, so non-static growth still surfaces in lint.
  EL007 vmem-overflow           a PanelPlan pallas dispatch whose gate
        pricing (``use_pallas``: copies x tile-padded bytes) admits a
        panel whose ACTUAL kernel allocation (real pallas_call
        out_shapes + carries, incl. square LANE padding) overflows the
        VMEM budget -- the 16 MiB fallback gate proven, not trusted.
  EL008 missing-donation        a jitted entry whose output aval matches
        an UNDONATED input aval: the buffer could be donated
        (``donate_argnums``) to halve residency.  Only checked when the
        plan's meta declares its donation set (``meta["donated"]``) --
        the bench.py donate-input and serve ``__donated`` exec-cache
        paths become lintable instead of conventions.
  EL009 double-materialization  two or more full-matrix ([STAR,STAR])
        gathers of the SAME source operand: ``p`` live replicas paid
        repeatedly for one global operand.

``lint_plan`` returns findings sorted by rule id; an empty list means the
plan is clean (the ``perf/comm_audit.py lint`` CLI exits non-zero on any
finding).
"""
from __future__ import annotations

import dataclasses

from .jaxpr_walk import find_loop_invariant_collectives

_NARROW = ("float16", "bfloat16", "float32", "complex64", "int32", "int16",
           "int8", "uint32", "uint16", "uint8", "bool")
_WIDE = ("float64", "complex128")


@dataclasses.dataclass(frozen=True)
class LintFinding:
    rule: str          # "EL00x"
    name: str          # short rule slug
    message: str       # human-readable, names the offending site
    severity: str = "warning"
    fix_hint: str = "" # concrete rewrite suggestion (lint --fix-hint)

    def __str__(self):
        return f"{self.rule} [{self.name}] {self.message}"


# ---------------------------------------------------------------------
# individual rules
# ---------------------------------------------------------------------

def _is_v_panel(dist) -> bool:
    names = tuple(d.value for d in dist)
    return names in (("VC", "STAR"), ("STAR", "VC"),
                     ("VR", "STAR"), ("STAR", "VR"))


def _spread_target(dist) -> bool:
    names = tuple(d.value for d in dist)
    return names in (("MC", "STAR"), ("STAR", "MR"),
                     ("MR", "STAR"), ("STAR", "MC"))


def rule_fuse_adjacent_gathers(plan, redist_log) -> list:
    """EL001: the panel + its adjoint spread issued as separate calls."""
    out = []
    recs = [r for r in redist_log if r.kind == "redistribute"]
    for a, b in zip(recs, recs[1:]):
        if not (_is_v_panel(a.src) and _spread_target(a.dst)):
            continue
        if not (_is_v_panel(b.src) and _spread_target(b.dst)):
            continue
        if a.dst == b.dst:
            continue
        # same panel extents (the adjoint chain transposes the gshape)
        if a.gshape not in (b.gshape, b.gshape[::-1]):
            continue
        out.append(LintFinding(
            "EL001", "fuse-adjacent-gathers",
            f"adjacent panel spreads {a.label} then {b.label} on a "
            f"{a.gshape} panel: fuse into one panel_spread() round "
            f"(one all_gather instead of separate gather chains)"))
    return out


def _slice_rewrite_hint(rec, z: int) -> str:
    """The sub-range refinement of the EL002 rewrite (ISSUE 18): when the
    src->dst pair is slice-legal, quote the ``compile_slice_plan`` of a
    representative half-row-range so blocked consumers see that gathering
    ONLY the block they touch is a compilable one-shot, not a
    full-matrix-endpoint detour."""
    from ..redist.plan import compile_slice_plan
    gs = tuple(rec.grid_shape)
    m, n = rec.gshape
    rows = (0, max(int(m) // 2, 1))
    try:
        splan = compile_slice_plan(rec.src, rec.dst, rec.gshape, gs,
                                   rows=rows)
    except (ValueError, KeyError):
        return ""
    if splan is None:
        return ""
    return (f"; consuming a sub-range only? compile_slice_plan(src, dst, "
            f"{tuple(rec.gshape)}, {gs}, rows={rows}) one-shots the "
            f"A[{rows[0]}:{rows[1]}, :] slice as a '{splan.kind}' plan = "
            f"{splan.rounds} round(s) / ~{splan.wire_bytes(z)} B -- "
            f"pay for the block you touch, not the matrix")


def _direct_rewrite_hint(rec) -> str:
    """The one-shot rewrite of one chained leg (ISSUE 12): compile the
    src->dst direct plan and quote rounds/bytes next to the chain's;
    slice-legal pairs additionally quote the sub-range rewrite
    (ISSUE 18)."""
    gs = tuple(rec.grid_shape or ())
    if len(gs) != 2:
        return ""
    import numpy as np
    from ..redist.plan import compile_plan
    from ..redist.engine import chain_cost
    plan = compile_plan(rec.src, rec.dst, rec.gshape, gs)
    if plan is None:
        return ""
    z = np.dtype(rec.dtype).itemsize
    rounds_c, bytes_c = chain_cost(rec.src, rec.dst, rec.gshape, gs, z)
    return (f"if the {rec.dst[0].value}/{rec.dst[1].value} form is "
            f"actually consumed, route it as redistribute(..., "
            f"path='direct'): one-shot '{plan.kind}' plan for "
            f"{rec.label} at {rec.gshape} on {gs[0]}x{gs[1]} = "
            f"{plan.rounds} round(s) / ~{plan.wire_bytes(z)} B vs the "
            f"chain's {rounds_c} round(s) / ~{bytes_c} B; otherwise "
            f"delete both legs" + _slice_rewrite_hint(rec, z))


def rule_redundant_round_trip(plan, redist_log) -> list:
    """EL002: A->X then X->A on the untouched intermediate."""
    out = []
    recs = [r for r in redist_log if r.kind == "redistribute"]
    by_out = {}
    for r in recs:
        for oid in r.out_ids:
            by_out[oid] = r
    for r in recs:
        prev = by_out.get(r.in_id)
        if prev is None or prev is r:
            continue
        if prev.src == r.dst and prev.dst == r.src \
                and prev.gshape == r.gshape:
            out.append(LintFinding(
                "EL002", "redundant-round-trip",
                f"{prev.label} then {r.label} on the SAME untouched "
                f"{r.gshape} operand: the round trip is a no-op costing "
                f"two redistribution rounds",
                fix_hint=_direct_rewrite_hint(prev)))
    return out


def rule_loop_invariant(plan, closed_jaxpr=None) -> list:
    """EL003: hoistable collectives inside scan/while bodies."""
    if closed_jaxpr is None:
        return []
    out = []
    for prim, path in find_loop_invariant_collectives(closed_jaxpr):
        where = "/".join(path) or "<top>"
        out.append(LintFinding(
            "EL003", "loop-invariant-collective",
            f"{prim} inside {where} has loop-invariant operands: "
            f"hoist it out of the loop body"))
    return out


def rule_f64_promotion(plan) -> list:
    """EL004: wide dtypes on the wire from narrow inputs."""
    in_dtypes = plan.meta.get("input_dtypes") or [plan.meta.get("dtype")]
    if any(str(d) in _WIDE for d in in_dtypes if d):
        return []          # wide inputs: wide collectives are intended
    out = []
    seen = set()
    for ev in plan.events:
        if ev.dtype in _WIDE and (ev.prim, ev.dtype, ev.shape) not in seen:
            seen.add((ev.prim, ev.dtype, ev.shape))
            out.append(LintFinding(
                "EL004", "f64-promotion",
                f"{ev.prim} moves {ev.dtype} {ev.shape} at "
                f"{'/'.join(ev.path)} but the traced inputs are "
                f"{[str(d) for d in in_dtypes]}: unintended promotion "
                f"doubles wire bytes"))
    return out


def rule_bf16_leak(plan) -> list:
    """EL005: bf16 collectives without the update_precision opt-in."""
    if plan.meta.get("allow_bf16"):
        return []
    out = []
    seen = set()
    for ev in plan.events:
        if ev.dtype == "bfloat16" and (ev.prim, ev.shape) not in seen:
            seen.add((ev.prim, ev.shape))
            out.append(LintFinding(
                "EL005", "bf16-leak",
                f"{ev.prim} moves bfloat16 {ev.shape} at "
                f"{'/'.join(ev.path)} without the update_precision "
                f"opt-in: bf16 on the wire halves mantissa silently"))
    return out


def lint_plan(plan, redist_log=(), closed_jaxpr=None) -> list:
    """Run every rule; findings sorted by rule id (empty == clean)."""
    findings = []
    findings += rule_fuse_adjacent_gathers(plan, redist_log)
    findings += rule_redundant_round_trip(plan, redist_log)
    findings += rule_loop_invariant(plan, closed_jaxpr)
    findings += rule_f64_promotion(plan)
    findings += rule_bf16_leak(plan)
    return sorted(findings, key=lambda f: (f.rule, f.message))


# ---------------------------------------------------------------------
# memory rules (ISSUE 18) -- over a memory_plan/v1 MemoryPlan
# ---------------------------------------------------------------------

def rule_mem_budget(mplan, budget_factor: float) -> list:
    """EL006: peak live bytes over the declared per-driver budget."""
    base = mplan.stats.args_bytes + mplan.stats.outs_bytes
    budget = int(budget_factor * max(base, 1))
    ns = mplan.stats.nonstatic_peak_bytes
    total = mplan.peak_bytes + ns
    if total <= budget:
        return []
    at = "/".join(mplan.stats.peak_path) or "<top>"
    msg = (f"{mplan.driver} on {mplan.grid[0]}x{mplan.grid[1]}: peak live "
           f"{total} B exceeds the declared budget {budget} B "
           f"({budget_factor:g}x the {base} B input+output residency); "
           f"high-water at {at} ({mplan.stats.peak_prim})")
    if ns:
        msg += (f"; {ns} B of that sits inside while bodies with NO "
                f"static trip count (excluded from the golden peak, "
                f"folded into this check)")
    return [LintFinding(
        "EL006", "peak-over-budget", msg,
        fix_hint=(f"either the driver legitimately stages this much "
                  f"(raise MEM_BUDGET_FACTORS[{mplan.driver!r}] in "
                  f"analysis/drivers.py and say why) or a gather is "
                  f"materializing more than its consumer touches -- "
                  f"check the replicated census "
                  f"({mplan.replicated.get('count', 0)} site(s), max "
                  f"extra {mplan.replicated.get('max_extra_bytes', 0)} B)"))]


def rule_vmem_overflow(panel_checks) -> list:
    """EL007: gate-admitted panels whose real kernel allocation
    overflows the VMEM budget."""
    out = []
    seen = set()
    for chk in panel_checks:
        if not chk.overflow or (chk.op, chk.shape) in seen:
            continue
        seen.add((chk.op, chk.shape))
        out.append(LintFinding(
            "EL007", "vmem-overflow",
            f"{chk.op} panel {chk.shape} {chk.dtype}: use_pallas prices "
            f"{chk.gate_bytes} B (admitted, budget {chk.budget} B) but "
            f"the fused kernel actually allocates {chk.kernel_bytes} B "
            f"-- the gate would dispatch a kernel that overflows VMEM",
            severity="error",
            fix_hint=(f"raise the copies= the dispatch site passes to "
                      f"use_pallas so the gate prices >= "
                      f"{chk.kernel_bytes} B, or shrink the kernel's "
                      f"scratch residents")))
    return out


def rule_missing_donation(mplan, closed_jaxpr) -> list:
    """EL008: an output aval matching an undonated input aval.

    Opt-in: only runs when the plan's meta DECLARES its donation set
    (``meta["donated"]`` = iterable of donated arg positions; absent
    meta means the entry never claimed jit-with-donation semantics)."""
    donated = mplan.meta.get("donated")
    if donated is None or closed_jaxpr is None:
        return []
    donated = set(int(i) for i in donated)
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    def _sig(v):
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            return None
        return (tuple(shape), str(dtype))

    out_sigs = [s for s in (_sig(v) for v in jaxpr.outvars) if s]
    findings = []
    for i, v in enumerate(jaxpr.invars):
        if i in donated:
            continue
        sig = _sig(v)
        if sig and sig in out_sigs:
            findings.append(LintFinding(
                "EL008", "missing-donation",
                f"{mplan.driver}: input {i} {sig[0]} {sig[1]} matches an "
                f"output aval but is not in the donated set "
                f"{sorted(donated)}: the buffer is held live across the "
                f"whole call for nothing",
                fix_hint=f"add {i} to donate_argnums (XLA reuses the "
                         f"input buffer for the matching output, halving "
                         f"this operand's residency)"))
    return findings


def rule_double_materialization(mplan, redist_log) -> list:
    """EL009: >= 2 full-matrix gathers of the SAME source operand."""
    by_src = {}
    for rec in redist_log:
        if rec.kind != "redistribute":
            continue
        names = tuple(d.value for d in rec.dst)
        if names != ("STAR", "STAR"):
            continue
        by_src.setdefault((rec.in_id, rec.gshape, rec.dtype),
                          []).append(rec)
    out = []
    for (in_id, gshape, dtype), recs in sorted(
            by_src.items(), key=lambda kv: repr(kv[0][1:])):
        if len(recs) < 2:
            continue
        p = 1
        gs = tuple(recs[0].grid_shape or ())
        if len(gs) == 2:
            p = max(gs[0] * gs[1], 1)
        out.append(LintFinding(
            "EL009", "double-materialization",
            f"{len(recs)} separate [*,*] gathers of the SAME {gshape} "
            f"{dtype} operand: each keeps {p} live replicas per grid -- "
            f"gather once and reuse the replicated form",
            fix_hint="hoist the redistribute(.., STAR, STAR) above the "
                     "consumers (or thread the gathered operand through) "
                     "so the full-matrix materialization is paid once"))
    return out


def lint_memory(mplan, redist_log=(), closed_jaxpr=None,
                budget_factor: float = None, panel_checks=None) -> list:
    """Run the memory rules over one :class:`MemoryPlan`.

    ``budget_factor`` defaults to the registry's declared factor for the
    driver (4.0 when the driver is unregistered); ``panel_checks``
    defaults to the EL007 sweep of the driver's own panel schedule when
    its op has a fused kernel (driver name prefix lu/cholesky/qr + n/nb
    from the plan meta)."""
    if budget_factor is None:
        from .drivers import DRIVERS
        spec = DRIVERS.get(mplan.driver)
        budget_factor = spec.mem_budget_factor if spec is not None else 4.0
    if panel_checks is None:
        from .memory import PANEL_GATE_COPIES, panel_vmem_checks
        panel_checks = []
        op = mplan.driver.split("_")[0]
        n, nb = mplan.meta.get("n"), mplan.meta.get("nb")
        if op in PANEL_GATE_COPIES and n and nb:
            panel_checks = panel_vmem_checks(
                op, int(n), int(nb), mplan.meta.get("dtype", "float32"))
    findings = []
    findings += rule_mem_budget(mplan, budget_factor)
    findings += rule_vmem_overflow(panel_checks)
    findings += rule_missing_donation(mplan, closed_jaxpr)
    findings += rule_double_materialization(mplan, redist_log)
    return sorted(findings, key=lambda f: (f.rule, f.message))
