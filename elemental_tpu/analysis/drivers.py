"""Traceable driver registry + the abstract trace entry point.

``trace_driver(name, grid, ...)`` builds storage-form abstract inputs for
a registered distributed driver, traces it with ``jax.make_jaxpr`` (no
device execution -- works under ``JAX_PLATFORMS=cpu``), and returns the
extracted :class:`~elemental_tpu.analysis.plan.CommPlan` together with
the closed jaxpr and the engine's redistribution log.

Registered drivers (ISSUE 3's golden set): ``gemm`` under every explicit
algorithm, ``trsm``, ``herk``, ``cholesky`` classic / look-ahead /
explicit-crossover, ``lu`` classic / look-ahead / explicit-crossover, and
``qr``.  Inputs default to float32 (n=64, nb=16) so the f64-promotion
lint (EL004) has teeth on the goldens.

Input construction note: inputs are built directly in stacked-storage
form (``DistMatrix(storage, ...)``) from ``ShapeDtypeStruct`` specs --
the ``from_global`` bridge would ``device_put`` eagerly and break the
pure-abstract trace.
"""
from __future__ import annotations

import contextlib
import dataclasses

import jax
import jax.numpy as jnp

from ..core import indexing as ix
from ..core.dist import Dist, storage_slots, stride as dist_stride
from ..core.distmatrix import DistMatrix
from ..core.grid import Grid
from ..redist.engine import redist_trace, redist_counts
from .jaxpr_walk import collect_events
from .plan import plan_from_parts

MC, MR = Dist.MC, Dist.MR

#: default trace geometry (4 blocked steps at 64/16; small enough that a
#: full registry sweep traces in seconds, large enough that look-ahead,
#: crossover, and the SUMMA panel loops all take their real schedules)
DEFAULT_N = 64
DEFAULT_NB = 16
#: explicit mid-range crossover for the *_crossover variants: at n=64 the
#: tail triggers after two distributed steps (64-32 <= 32), so the plan
#: shows pipelined steps AND the tail collapse in one snapshot
DEFAULT_XOVER = 32


def storage_shape(m: int, n: int, cdist: Dist, rdist: Dist, grid: Grid):
    """Stacked-storage array shape of a DistMatrix (outside shard_map)."""
    r, c = grid.height, grid.width
    lr = ix.max_local_length(m, dist_stride(cdist, r, c))
    lc = ix.max_local_length(n, dist_stride(rdist, r, c))
    return (storage_slots(cdist, r, c) * lr, storage_slots(rdist, r, c) * lc)


def _mcmr_input(grid, m, n, dtype):
    return jax.ShapeDtypeStruct(storage_shape(m, n, MC, MR, grid), dtype)


def _as_dm(a, grid, m, n):
    return DistMatrix(a, (m, n), MC, MR, 0, 0, grid)


@dataclasses.dataclass(frozen=True)
class DriverSpec:
    """One registry entry: builds the traced callable + abstract inputs."""
    name: str
    build: callable          # (grid, n, nb, dtype) -> (fn, args, meta)
    allow_bf16: bool = False
    #: lint EL006 budget: peak live bytes may not exceed this multiple of
    #: the driver's per-device input+output residency (see
    #: ``MEM_BUDGET_FACTORS`` for the declared exceptions)
    mem_budget_factor: float = 4.0


def _gemm_spec(alg, variant="", redist_path=None):
    def build(grid, n, nb, dtype):
        from ..blas.level3 import gemm

        def fn(a, b):
            A = _as_dm(a, grid, n, n)
            B = _as_dm(b, grid, n, n)
            return gemm(A, B, alg=alg, nb=nb, redist_path=redist_path)
        args = (_mcmr_input(grid, n, n, dtype), _mcmr_input(grid, n, n, dtype))
        meta = {"alg": alg}
        if redist_path is not None:
            meta["redist_path"] = redist_path
        return fn, args, meta
    name = f"gemm_{alg.lower()}"
    return DriverSpec(f"{name}_{variant}" if variant else name, build)


#: the slicing-gemm driver's rectangular trace geometry, as multiples of
#: the ``n`` trace parameter: (m, k, n) = (32n, n, n/4) -- the tall-skinny
#: class (m >> n, k = 4*cols) where ISSUE 16 pins the slice schedule at
#: strictly fewer collective rounds and >= 1.5x fewer wire bytes than the
#: stationary-C twin on BOTH golden grids (the twin ratio grows with m/n).
GEMM_SLICE_DIMS = (32, 1, 0.25)


def gemm_slice_extents(n: int) -> tuple:
    """(m, k, n') of the gemm_slice trace at trace parameter ``n``."""
    sm, sk, sn = GEMM_SLICE_DIMS
    return int(sm * n), int(sk * n), max(int(sn * n), 1)


def _gemm_slice_spec():
    """The slicing gemm (ISSUE 16) traces TALL-SKINNY, not square: its
    whole reason to exist is the rectangular regime, so the golden pins
    live where 'auto' would actually dispatch it."""
    def build(grid, n, nb, dtype):
        from ..blas.level3 import gemm
        m, k, n2 = gemm_slice_extents(n)

        def fn(a, b):
            A = _as_dm(a, grid, m, k)
            B = _as_dm(b, grid, k, n2)
            return gemm(A, B, alg="slice", nb=nb)
        args = (_mcmr_input(grid, m, k, dtype),
                _mcmr_input(grid, k, n2, dtype))
        meta = {"alg": "slice", "extents": [m, k, n2]}
        return fn, args, meta
    return DriverSpec("gemm_slice", build)


def _trsm_spec(variant="", side="L", redist_path=None):
    def build(grid, n, nb, dtype):
        from ..blas.level3 import trsm

        def fn(a, b):
            A = _as_dm(a, grid, n, n)
            B = _as_dm(b, grid, n, n)
            return trsm(side, "L", "N", A, B, nb=nb,
                        redist_path=redist_path)
        args = (_mcmr_input(grid, n, n, dtype), _mcmr_input(grid, n, n, dtype))
        meta = {}
        if side != "L":
            meta["side"] = side
        if redist_path is not None:
            meta["redist_path"] = redist_path
        return fn, args, meta
    return DriverSpec(f"trsm_{variant}" if variant else "trsm", build)


def _herk_spec(variant="", redist_path=None):
    def build(grid, n, nb, dtype):
        from ..blas.level3 import herk

        def fn(a):
            return herk("L", _as_dm(a, grid, n, n), nb=nb,
                        redist_path=redist_path)
        meta = {}
        if redist_path is not None:
            meta["redist_path"] = redist_path
        return fn, (_mcmr_input(grid, n, n, dtype),), meta
    return DriverSpec(f"herk_{variant}" if variant else "herk", build)


def _lq_spec(variant="", redist_path=None):
    def build(grid, n, nb, dtype):
        from ..lapack.qr import lq

        def fn(a):
            return lq(_as_dm(a, grid, n, n), nb=nb, redist_path=redist_path)
        meta = {}
        if redist_path is not None:
            meta["redist_path"] = redist_path
        return fn, (_mcmr_input(grid, n, n, dtype),), meta
    return DriverSpec(f"qr_lq_{variant}" if variant else "qr_lq", build)


def _redist_md_spec(variant="", redist_path=None):
    """[MC,MR] -> [MD,STAR] -> [STAR,MD] round-trip at RAGGED extents
    ((n-1, n-3): the diagonal locals straddle slot boundaries), the
    incompatible-residue pair whose one-shot plan exercises both ragged
    slot trimming and subgroup packing (ISSUE 13)."""
    def build(grid, n, nb, dtype):
        from ..core.dist import Dist
        from ..redist.engine import redistribute
        MD, STAR = Dist.MD, Dist.STAR
        m_, n_ = n - 1, n - 3

        def fn(a):
            A = _as_dm(a, grid, m_, n_)
            B = redistribute(A, MD, STAR, path=redist_path)
            return redistribute(B, STAR, MD, path=redist_path)
        meta = {"extents": [m_, n_]}
        if redist_path is not None:
            meta["redist_path"] = redist_path
        return fn, (_mcmr_input(grid, m_, n_, dtype),), meta
    return DriverSpec(f"redist_md_{variant}" if variant else "redist_md",
                      build)


def _redist_circ_spec(variant=""):
    """[MC,MR] -> [CIRC,CIRC] -> [VC,STAR]: both root-only endpoint
    legs (gather to root, scatter from root), landing on a THIRD pair
    so the lint does not read it as a redundant round trip.  Since
    ISSUE 14 both legs ride the jitted shard_map path (ONE fused gather
    chain to [STAR,STAR] + a root ``device_put`` out; a broadcast
    ``device_put`` + zero-collective local filter back), so the whole
    chain must trace WITHOUT an eager host sync -- this driver existing
    at all pins that (the former eager bridge could not be abstractly
    traced)."""
    def build(grid, n, nb, dtype):
        from ..core.dist import Dist
        from ..redist.engine import redistribute
        CIRC, VC, STAR = Dist.CIRC, Dist.VC, Dist.STAR

        def fn(a):
            A = _as_dm(a, grid, n, n)
            B = redistribute(A, CIRC, CIRC)
            return redistribute(B, VC, STAR)
        return fn, (_mcmr_input(grid, n, n, dtype),), {}
    return DriverSpec(f"redist_circ_{variant}" if variant
                      else "redist_circ", build)


#: trace-time panel-implementation override (ISSUE 17): the comm-plan
#: invariance gate re-traces every factorization variant with the fused
#: Pallas panels selected and byte-compares against the goldens.  A
#: module global (read INSIDE the traced fn, at trace time) rather than
#: a spec parameter, so the registry -- and therefore every golden doc's
#: meta -- is unchanged: the override is an assertion harness, not a
#: new driver variant.
_PANEL_IMPL_OVERRIDE = None


def _panel_impl():
    return _PANEL_IMPL_OVERRIDE


@contextlib.contextmanager
def panel_impl_override(impl):
    """Trace the factorization drivers with ``panel_impl=impl`` (e.g.
    'pallas') without touching their registered meta.  Used by the
    ``tools/check.sh kernels`` gate and tests/kernels to pin that panel
    kernels are replicated-local: every comm plan must stay
    byte-identical under the override."""
    global _PANEL_IMPL_OVERRIDE
    prev = _PANEL_IMPL_OVERRIDE
    _PANEL_IMPL_OVERRIDE = impl
    try:
        yield
    finally:
        _PANEL_IMPL_OVERRIDE = prev


def _cholesky_spec(variant, lookahead, crossover, comm_precision=None,
                   abft=False):
    def build(grid, n, nb, dtype):
        from ..lapack.cholesky import cholesky

        def fn(a):
            return cholesky(_as_dm(a, grid, n, n), nb=nb,
                            lookahead=lookahead, crossover=crossover,
                            comm_precision=comm_precision,
                            abft=abft or None, panel_impl=_panel_impl())
        meta = {"lookahead": lookahead, "crossover": crossover,
                "comm_precision": comm_precision, "abft": abft}
        return fn, (_mcmr_input(grid, n, n, dtype),), meta
    # commq variants intentionally move bf16 on the wire (EL005 opt-in)
    return DriverSpec(f"cholesky_{variant}", build,
                      allow_bf16=comm_precision is not None)


def _lu_spec(variant, lookahead, crossover, panel="classic",
             comm_precision=None, abft=False):
    def build(grid, n, nb, dtype):
        from ..lapack.lu import lu

        def fn(a):
            return lu(_as_dm(a, grid, n, n), nb=nb,
                      lookahead=lookahead, crossover=crossover, panel=panel,
                      comm_precision=comm_precision, abft=abft or None,
                      panel_impl=_panel_impl())
        meta = {"lookahead": lookahead, "crossover": crossover,
                "panel": panel, "comm_precision": comm_precision,
                "abft": abft}
        return fn, (_mcmr_input(grid, n, n, dtype),), meta
    return DriverSpec(f"lu_{variant}", build,
                      allow_bf16=comm_precision is not None)


def _qr_spec(variant="", panel="classic", abft=False):
    def build(grid, n, nb, dtype):
        from ..lapack.qr import qr

        def fn(a):
            return qr(_as_dm(a, grid, n, n), nb=nb, panel=panel,
                      abft=abft or None, panel_impl=_panel_impl())
        # the abft key is CONDITIONAL so the pre-ISSUE-15 qr / qr_tsqr
        # golden docs stay byte-identical (to_doc merges meta verbatim)
        meta = {"panel": panel, **({"abft": True} if abft else {})}
        return fn, (_mcmr_input(grid, n, n, dtype),), meta
    return DriverSpec(f"qr_{variant}" if variant else "qr", build)


def _registry() -> dict:
    specs = [
        _gemm_spec("A"), _gemm_spec("B"), _gemm_spec("C"),
        _gemm_spec("dot"), _gemm_spec("gspmd"), _gemm_slice_spec(),
        _trsm_spec(),
        _herk_spec(),
        # classic = right-looking baseline; lookahead = pure pipeline
        # (crossover disabled); crossover = pipeline + tail collapse
        _cholesky_spec("classic", lookahead=False, crossover=0),
        _cholesky_spec("lookahead", lookahead=True, crossover=0),
        _cholesky_spec("crossover", lookahead=True, crossover=DEFAULT_XOVER),
        _lu_spec("classic", lookahead=False, crossover=0),
        _lu_spec("lookahead", lookahead=True, crossover=0),
        _lu_spec("crossover", lookahead=True, crossover=DEFAULT_XOVER),
        # calu = ISSUE 6's tournament-pivoted panel on the default
        # pipelined (lookahead + crossover-tail) schedule; the one-psum
        # row-block solve replaces the classic all_to_all + all_gather
        # pair, so its plan must stay strictly smaller than both
        # lu_classic AND lu_crossover (pinned via CALU_PAIRS)
        _lu_spec("calu", lookahead=True, crossover=DEFAULT_XOVER,
                 panel="calu"),
        _qr_spec(),
        _qr_spec("tsqr", panel="tsqr"),
        # commq = ISSUE 8's quantized-wire twins: the SAME schedule knobs
        # as the baseline variant plus comm_precision='bf16', so the
        # golden pair pins the EQuARX win exactly -- identical collective
        # round counts, ~half the estimated wire bytes (COMMQ_PAIRS)
        _lu_spec("calu_commq", lookahead=True, crossover=DEFAULT_XOVER,
                 panel="calu", comm_precision="bf16"),
        _cholesky_spec("lookahead_commq", lookahead=True, crossover=0,
                       comm_precision="bf16"),
        # abft = ISSUE 11's checksum-guarded drivers: the classic
        # right-looking schedule (abft= forces it) plus the per-panel
        # checksum maintenance, traced with the guard's host checks
        # inert -- the golden pins the ABFT-enabled collective structure
        # so checksum overhead changes are a reviewed diff
        _lu_spec("abft", lookahead=False, crossover=0, abft=True),
        _cholesky_spec("abft", lookahead=False, crossover=0, abft=True),
        # qr_abft = ISSUE 15's guarded QR: the same blocked Householder
        # schedule plus the checksum reductions (panel gathers unchanged,
        # one extra [MC,MR] panel write already shared with the plain
        # sweep) -- pins the guarded collective structure like lu_abft
        _qr_spec("abft", abft=True),
        # direct = ISSUE 12's one-shot redistribution twins: the SAME
        # schedule knobs as the baseline variant plus redist_path=
        # 'direct', so the golden pair pins the plan-compiler win exactly
        # -- the chained operand moves (3 hops for the A/B operand
        # relands, 2 for dot's cyclic ones) collapse into a single
        # all_to_all on multi-chip grids (DIRECT_PAIRS)
        _gemm_spec("A", variant="direct", redist_path="direct"),
        _gemm_spec("B", variant="direct", redist_path="direct"),
        _gemm_spec("dot", variant="direct", redist_path="direct"),
        # ISSUE 13: every remaining driver family gets a one-shot twin.
        # qr's own panel gathers are already single-round, so the lq
        # entry transpose (a 3-hop chain) carries the qr-family pin;
        # trsm's win is the side='R' entry/exit transposes; herk's is the
        # per-panel [VC,STAR]+spread pair collapsing into ONE exchange.
        _lq_spec(),
        _lq_spec(variant="direct", redist_path="direct"),
        _trsm_spec(variant="r", side="R"),
        _trsm_spec(variant="r_direct", side="R", redist_path="direct"),
        _herk_spec(variant="direct", redist_path="direct"),
        # ragged [MD,*] round-trip: equal round counts chain vs direct,
        # so NOT in DIRECT_PAIRS -- its golden pins the ragged-slot BYTE
        # drop instead (trimmed slots + subgroup packing vs the padded
        # full-mesh exchange; see tests/analysis/test_direct_plan.py)
        _redist_md_spec(),
        _redist_md_spec(variant="direct", redist_path="direct"),
        # ISSUE 14: the CIRC endpoints folded into the jitted shard_map
        # path -- the round-trip traces abstractly (impossible with the
        # old eager bridge) and its golden pins the fused gather rounds
        _redist_circ_spec(),
    ]
    out = {}
    for s in specs:
        factor = MEM_BUDGET_FACTORS.get(s.name)
        if factor is not None:
            s = dataclasses.replace(s, mem_budget_factor=factor)
        out[s.name] = s
    return out


#: per-driver EL006 overrides above the 4.0x default, each a DECLARED
#: memory cost the variant is known to pay (measured on 1x1+2x2, pinned
#: by the memory_plan goldens + tests/analysis/test_mem_lint.py):
#: the slice gather one-shots whole operand slabs, `[CIRC,CIRC]` and
#: `[MD,*]` forms concentrate the operand on few devices, and the
#: direct one-shot plans stage full send+recv buffers at once.
MEM_BUDGET_FACTORS = {
    "gemm_slice": 6.5,        # one-shot row/col slab gathers (by design)
    "gemm_dot_direct": 5.0,   # replicated-form staging, direct plans
    "herk_direct": 6.0,
    "qr_lq_direct": 5.0,
    "redist_circ": 6.5,       # root holds the FULL gathered operand
    "redist_md": 7.5,         # lcm-stride staging buffers
    "redist_md_direct": 7.5,
}

DRIVERS = _registry()

#: look-ahead/classic pairs at EQUAL n/nb whose all_gather rounds the
#: golden tests compare: the default look-ahead configuration (crossover
#: tail enabled) must issue STRICTLY FEWER rounds than classic -- the
#: jaxpr-level pin of the PR 1-2 fusions.
LOOKAHEAD_PAIRS = (
    ("cholesky_crossover", "cholesky_classic"),
    ("lu_crossover", "lu_classic"),
)

#: CALU pins (ISSUE 6): at equal n/nb (equal panel count) the tournament-
#: pivoted schedule must issue strictly fewer collective rounds than the
#: classic partial-pivot baseline AND than the pipelined classic-panel
#: default -- i.e. strictly fewer rounds PER PANEL.  (calu variant,
#: classic-panel comparison variants.)
CALU_PAIRS = (
    ("lu_calu", ("lu_classic", "lu_crossover")),
)

#: quantized-wire pairs (ISSUE 8): (commq variant, full-precision twin) at
#: IDENTICAL schedule knobs.  The golden tests pin, per pair on the 2x2
#: grid: equal per-collective round counts and >= COMMQ_MIN_BYTE_RATIO x
#: lower total estimated wire bytes -- the jaxpr-level proof that the
#: comm_precision knob halves bytes without adding rounds.
COMMQ_PAIRS = (
    ("lu_calu_commq", "lu_calu"),
    ("cholesky_lookahead_commq", "cholesky_lookahead"),
)
COMMQ_MIN_BYTE_RATIO = 1.9

#: one-shot redistribution pairs (ISSUE 12): (direct variant, chained
#: twin) at IDENTICAL schedule knobs.  The golden tests pin, per pair on
#: the 2x2 grid: STRICTLY FEWER total collective rounds for the direct
#: variant (the multi-hop operand relands collapse into one all_to_all);
#: on 1x1 every plan is 'local', so the direct variant issues no
#: collectives at all (<= the chain's degenerate 1-participant rounds).
DIRECT_PAIRS = (
    ("gemm_a_direct", "gemm_a"),
    ("gemm_b_direct", "gemm_b"),
    ("gemm_dot_direct", "gemm_dot"),
    # ISSUE 13: the qr/trsm/herk one-shot twins (redist_md is pinned on
    # bytes, not rounds -- its chain and direct round counts tie)
    ("qr_lq_direct", "qr_lq"),
    ("trsm_r_direct", "trsm_r"),
    ("herk_direct", "herk"),
)


def driver_names() -> list:
    return sorted(DRIVERS)


def trace_driver(name: str, grid: Grid, n: int = DEFAULT_N,
                 nb: int = DEFAULT_NB, dtype=jnp.float32):
    """Abstractly trace a registered driver; return
    ``(CommPlan, closed_jaxpr, redist_log)``.

    Pure trace: no device buffers are created and nothing executes, so
    this runs identically under ``JAX_PLATFORMS=cpu`` on any host.  The
    grid's devices only parameterize the mesh metadata.
    """
    spec = DRIVERS.get(name)
    if spec is None:
        raise KeyError(f"unknown driver {name!r}; known: {driver_names()}")
    fn, args, meta = spec.build(grid, n, nb, dtype)
    with redist_counts():                      # isolate the global counter
        with redist_trace() as log:
            closed = jax.make_jaxpr(fn)(*args)
    events = collect_events(closed)
    full_meta = {"n": n, "nb": nb, "dtype": jnp.dtype(dtype).name,
                 "input_dtypes": [jnp.dtype(a.dtype).name for a in args],
                 "allow_bf16": spec.allow_bf16}
    full_meta.update(meta)
    plan = plan_from_parts(name, (grid.height, grid.width), full_meta,
                           events, log)
    return plan, closed, log


def trace_callable(fn, args, name: str = "custom", grid=None, meta=None):
    """Trace an arbitrary driver callable (used by tests and the linter's
    seeded-regression harness).  ``args`` are ShapeDtypeStructs (or
    arrays); returns ``(CommPlan, closed_jaxpr, redist_log)``."""
    with redist_counts():
        with redist_trace() as log:
            closed = jax.make_jaxpr(fn)(*args)
    events = collect_events(closed)
    gshape = (grid.height, grid.width) if grid is not None else (0, 0)
    full_meta = {"input_dtypes": [jnp.dtype(a.dtype).name for a in args]}
    full_meta.update(meta or {})
    plan = plan_from_parts(name, gshape, full_meta, events, log)
    return plan, closed, log
