"""Recursive jaxpr walker: extract every collective primitive with context.

The core of the static comm-plan analyzer (ISSUE 3): given a closed jaxpr
(from ``jax.make_jaxpr`` over a distributed driver -- tracing only, no
device execution), walk every equation recursively -- into ``pjit`` calls,
``shard_map`` bodies, ``scan``/``while``/``cond`` sub-jaxprs, custom-deriv
call jaxprs -- and emit one :class:`CollectiveEvent` per collective
equation encountered, annotated with

  * the mesh axes it communicates over and their total size,
  * the operand shape/dtype and an estimated per-device byte volume
    (ring-algorithm cost model, see :func:`estimate_bytes`),
  * the nesting path (``pjit:_redistribute_jit/shard_map``),
  * a static trip-count multiplier (``scan`` lengths compose; ``while``
    bodies are marked non-static since XLA cannot bound them),
  * whether the event sits on a conditional branch.

Scope note: this sees the EXPLICIT collectives of the redistribution
engine (everything issued inside ``shard_map``).  Communication inserted
later by GSPMD for storage-level ops on sharded arrays (e.g. the row-swap
scatters of the LU driver or stationary-A/B storage matmul psums) is a
compile-time decision and is out of scope here -- the plan pins the
schedule the library *chose*, which is what the `[MC,MR]`/`[VC,STAR]`
redistribution algebra controls.
"""
from __future__ import annotations

import dataclasses

try:
    # the blessed public location (jax >= 0.4.35; survives the removal of
    # jax.core internals in newer releases -- cf. core/compat.py)
    from jax.extend import core as jcore
except ImportError:                                    # pragma: no cover
    from jax import core as jcore

#: jaxpr primitive names treated as collectives.
COLLECTIVE_PRIMS = (
    "all_gather",
    "psum",
    "reduce_scatter",
    "ppermute",
    "all_to_all",
)

#: primitives whose sub-jaxpr runs once per loop iteration
_LOOP_PRIMS = ("while", "scan")


@dataclasses.dataclass(frozen=True)
class CollectiveEvent:
    """One collective equation found in the traced program."""
    prim: str                   # one of COLLECTIVE_PRIMS
    axes: tuple                 # mesh axis names communicated over
    axis_size: int              # product of the participating axis sizes
    shape: tuple                # operand (per-device) shape
    dtype: str                  # operand dtype name
    bytes_per_call: int         # estimated per-device bytes moved, one call
    path: tuple                 # nesting scopes from the root jaxpr
    count: int                  # static multiplier (composed scan lengths)
    static: bool                # False under a while loop (unbounded trips)
    conditional: bool           # True on a cond/branch path

    @property
    def total_bytes(self) -> int:
        return self.bytes_per_call * self.count

    def to_doc(self) -> dict:
        d = dataclasses.asdict(self)
        d["axes"] = list(self.axes)
        d["shape"] = list(self.shape)
        d["path"] = list(self.path)
        return d


def estimate_bytes(prim: str, nbytes: int, axis_size: int) -> int:
    """Ring-algorithm per-device traffic estimate for one collective call.

    ``nbytes`` is the operand's local byte size, ``axis_size`` the number
    of participants S.  Formulas (received bytes per device):

      all_gather      nbytes * (S - 1)        (S-1 remote shards land here)
      reduce_scatter  nbytes * (S - 1) / S    (ring reduce-scatter)
      psum            2 * nbytes * (S-1) / S  (reduce-scatter + all-gather)
      all_to_all      nbytes * (S - 1) / S    (keep own shard, swap rest)
      ppermute        nbytes                  (wholesale block move)
    """
    if axis_size <= 1:
        return 0
    if prim == "all_gather":
        return nbytes * (axis_size - 1)
    if prim == "reduce_scatter":
        return nbytes * (axis_size - 1) // axis_size
    if prim == "psum":
        return 2 * nbytes * (axis_size - 1) // axis_size
    if prim == "all_to_all":
        return nbytes * (axis_size - 1) // axis_size
    if prim == "ppermute":
        return nbytes
    return nbytes


def _axis_names(params: dict):
    names = params.get("axis_name", params.get("axes", ()))
    if names is None:
        return ()
    if isinstance(names, (tuple, list)):
        return tuple(str(a) for a in names)
    return (str(names),)


def _axis_size(axes, axis_env: dict, params: dict) -> int:
    groups = params.get("axis_index_groups")
    if groups:
        # grouped collective (all_gather/all_to_all/psum over device
        # subsets): participants = one group's length, not the full axis
        # product -- byte estimates must price the subgroup ring
        return max(1, len(tuple(groups)[0]))
    if "axis_size" in params and params["axis_size"] is not None:
        return int(params["axis_size"])
    size = 1
    for a in axes:
        size *= int(axis_env.get(a, 1))
    return size


def _mesh_axis_sizes(mesh) -> dict:
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except (AttributeError, TypeError):
        return {}


def _payload_avals(eqn):
    """Every array operand of a collective equation.

    Byte estimates must price the ACTUAL wire payload: each operand with
    its own dtype (a tuple ``psum`` can mix dtypes, and the engine's
    ``comm_precision`` path converts payloads to bfloat16/int8 right
    before the collective -- assuming the driver's input dtype here would
    over-report those by 2-4x)."""
    out = []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "shape", None) is not None \
                and getattr(aval, "dtype", None) is not None:
            out.append(aval)
    return out


def _payload_nbytes(avals) -> int:
    total = 0
    for aval in avals:
        n = 1
        for s in aval.shape:
            n *= int(s)
        total += n * aval.dtype.itemsize
    return total


def _sub_jaxprs(val):
    """Yield every (closed or open) jaxpr reachable from a param value."""
    vals = val if isinstance(val, (tuple, list)) else (val,)
    for x in vals:
        if isinstance(x, jcore.ClosedJaxpr):
            yield x.jaxpr
        elif isinstance(x, jcore.Jaxpr):
            yield x


def _scope_label(eqn) -> str:
    name = eqn.params.get("name")
    if eqn.primitive.name == "pjit" and name:
        return f"pjit:{name}"
    if eqn.primitive.name == "scan":
        return f"scan[{eqn.params.get('length', '?')}]"
    return eqn.primitive.name


def collect_events(closed_jaxpr, axis_env: dict | None = None):
    """Walk ``closed_jaxpr`` recursively; return a list of CollectiveEvent.

    ``axis_env`` optionally seeds mesh axis sizes (normally discovered from
    enclosing ``shard_map`` equations).
    """
    out: list[CollectiveEvent] = []
    jaxpr = closed_jaxpr.jaxpr if isinstance(closed_jaxpr, jcore.ClosedJaxpr) \
        else closed_jaxpr
    _walk(jaxpr, dict(axis_env or {}), (), 1, True, False, out)
    return out


def _walk(jaxpr, axis_env, path, mult, static, conditional, out):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMS:
            axes = _axis_names(eqn.params)
            size = _axis_size(axes, axis_env, eqn.params)
            avals = _payload_avals(eqn)
            shape = tuple(int(s) for s in avals[0].shape) if avals else ()
            dtype = str(avals[0].dtype) if avals else "?"
            nbytes = _payload_nbytes(avals)
            out.append(CollectiveEvent(
                prim=prim, axes=axes, axis_size=size, shape=shape,
                dtype=dtype,
                bytes_per_call=estimate_bytes(prim, nbytes, size),
                path=path, count=mult, static=static,
                conditional=conditional))
            continue
        env = axis_env
        if prim == "shard_map":
            env = dict(axis_env)
            env.update(_mesh_axis_sizes(eqn.params.get("mesh")))
        sub_mult, sub_static = mult, static
        if prim == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        elif prim == "while":
            sub_static = False
        sub_cond = conditional or prim == "cond"
        label = _scope_label(eqn)
        if prim == "cond":
            for i, branch in enumerate(eqn.params.get("branches", ())):
                for sub in _sub_jaxprs(branch):
                    _walk(sub, env, path + (f"cond[{i}]",), sub_mult,
                          sub_static, True, out)
            continue
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                _walk(sub, env, path + (label,), sub_mult, sub_static,
                      sub_cond, out)


def count_pjit_calls(closed_jaxpr, name: str) -> int:
    """Number of ``pjit`` equations named ``name`` anywhere in the traced
    program -- e.g. ``_redistribute_jit`` / ``_panel_spread_jit`` call
    sites, cross-checkable against the engine's Python-level counters."""
    jaxpr = closed_jaxpr.jaxpr if isinstance(closed_jaxpr, jcore.ClosedJaxpr) \
        else closed_jaxpr
    return _count_pjit(jaxpr, name)


def _count_pjit(jaxpr, name: str) -> int:
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit" and eqn.params.get("name") == name:
            total += 1
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                total += _count_pjit(sub, name)
    return total


# ---------------------------------------------------------------------
# loop-invariant collective detection (lint EL003 support)
# ---------------------------------------------------------------------

def find_loop_invariant_collectives(closed_jaxpr):
    """Collectives inside ``scan``/``while`` bodies whose operands are all
    loop-invariant (derived only from loop constants) -- hoistable out of
    the loop.  Returns a list of ``(prim, path)`` tuples."""
    found: list[tuple] = []
    jaxpr = closed_jaxpr.jaxpr if isinstance(closed_jaxpr, jcore.ClosedJaxpr) \
        else closed_jaxpr
    _scan_loops(jaxpr, (), found)
    return found


def _scan_loops(jaxpr, path, found):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        label = _scope_label(eqn)
        if prim == "scan":
            body = eqn.params["jaxpr"].jaxpr
            nconsts = int(eqn.params.get("num_consts", 0))
            _check_body(body, nconsts, path + (label,), found)
        elif prim == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            nconsts = int(eqn.params.get("body_nconsts", 0))
            _check_body(body, nconsts, path + (label,), found)
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                _scan_loops(sub, path + (label,), found)


def _check_body(body, nconsts, path, found):
    invariant = set(body.constvars) | set(body.invars[:nconsts])
    for eqn in body.eqns:
        ins_invariant = all(
            not isinstance(v, jcore.Var) or v in invariant
            for v in eqn.invars)
        if eqn.primitive.name in COLLECTIVE_PRIMS and ins_invariant:
            found.append((eqn.primitive.name, path))
        if ins_invariant and str(eqn.primitive.name) not in _LOOP_PRIMS:
            invariant.update(eqn.outvars)
