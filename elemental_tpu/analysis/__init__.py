"""Static comm-plan analysis (ISSUE 3).

Trace-time extraction of every driver's collective schedule straight from
the closed jaxpr -- no device execution -- plus a rule-based linter and
the ``comm_plan/v1`` golden-snapshot machinery.  CLI:
``python -m perf.comm_audit {audit,diff,lint} ...``; generalizes the
Python-call-level ``REDIST_COUNTS`` to "what does the traced program
actually do".
"""
from .jaxpr_walk import (CollectiveEvent, COLLECTIVE_PRIMS, collect_events,
                         count_pjit_calls, estimate_bytes,
                         find_loop_invariant_collectives)
from .plan import SCHEMA, CommPlan, plan_from_parts, golden_doc, diff_docs
from .lint import LintFinding, lint_plan
from .drivers import (DRIVERS, LOOKAHEAD_PAIRS, CALU_PAIRS, COMMQ_PAIRS,
                      COMMQ_MIN_BYTE_RATIO, DIRECT_PAIRS, DEFAULT_N,
                      DEFAULT_NB, DEFAULT_XOVER, driver_names, trace_driver,
                      trace_callable, storage_shape)

__all__ = [
    "CollectiveEvent", "COLLECTIVE_PRIMS", "collect_events",
    "count_pjit_calls", "estimate_bytes", "find_loop_invariant_collectives",
    "SCHEMA", "CommPlan", "plan_from_parts", "golden_doc", "diff_docs",
    "LintFinding", "lint_plan",
    "DRIVERS", "LOOKAHEAD_PAIRS", "CALU_PAIRS", "COMMQ_PAIRS",
    "COMMQ_MIN_BYTE_RATIO", "DIRECT_PAIRS", "DEFAULT_N", "DEFAULT_NB",
    "DEFAULT_XOVER", "driver_names", "trace_driver", "trace_callable",
    "storage_shape",
]
