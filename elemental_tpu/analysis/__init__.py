"""Static comm-plan + memory-plan analysis (ISSUES 3, 18).

Trace-time extraction of every driver's collective schedule straight from
the closed jaxpr -- no device execution -- plus a rule-based linter and
the ``comm_plan/v1`` golden-snapshot machinery.  CLI:
``python -m perf.comm_audit {audit,diff,lint} ...``; generalizes the
Python-call-level ``REDIST_COUNTS`` to "what does the traced program
actually do".

The memory twin (ISSUE 18) walks the SAME jaxprs for liveness instead of
collectives: per-device peak live bytes, high-water timelines, a census
of replicated materializations, ``memory_plan/v1`` goldens
(``python -m perf.comm_audit {mem,mem-diff,mem-lint}``) and lint rules
EL006-EL009 (budget / VMEM / donation / double-materialization).
"""
from .jaxpr_walk import (CollectiveEvent, COLLECTIVE_PRIMS, collect_events,
                         count_pjit_calls, estimate_bytes,
                         find_loop_invariant_collectives)
from .plan import SCHEMA, CommPlan, plan_from_parts, golden_doc, diff_docs
from .lint import LintFinding, lint_plan, lint_memory
from .memory import (MEM_SCHEMA, MemoryPlan, WalkStats, HighWater,
                     PanelVmemCheck, PANEL_GATE_COPIES, analyze_jaxpr,
                     memory_plan, trace_memory, replication_census,
                     golden_mem_doc, diff_mem_docs, kernel_vmem_bytes,
                     check_panel_vmem, panel_vmem_checks, panel_shapes)
from .drivers import (DRIVERS, MEM_BUDGET_FACTORS, LOOKAHEAD_PAIRS,
                      CALU_PAIRS, COMMQ_PAIRS, COMMQ_MIN_BYTE_RATIO,
                      DIRECT_PAIRS, DEFAULT_N, DEFAULT_NB, DEFAULT_XOVER,
                      driver_names, trace_driver, trace_callable,
                      storage_shape)

__all__ = [
    "CollectiveEvent", "COLLECTIVE_PRIMS", "collect_events",
    "count_pjit_calls", "estimate_bytes", "find_loop_invariant_collectives",
    "SCHEMA", "CommPlan", "plan_from_parts", "golden_doc", "diff_docs",
    "LintFinding", "lint_plan", "lint_memory",
    "MEM_SCHEMA", "MemoryPlan", "WalkStats", "HighWater", "PanelVmemCheck",
    "PANEL_GATE_COPIES", "analyze_jaxpr", "memory_plan", "trace_memory",
    "replication_census", "golden_mem_doc", "diff_mem_docs",
    "kernel_vmem_bytes", "check_panel_vmem", "panel_vmem_checks",
    "panel_shapes",
    "DRIVERS", "MEM_BUDGET_FACTORS", "LOOKAHEAD_PAIRS", "CALU_PAIRS",
    "COMMQ_PAIRS", "COMMQ_MIN_BYTE_RATIO", "DIRECT_PAIRS", "DEFAULT_N",
    "DEFAULT_NB", "DEFAULT_XOVER", "driver_names", "trace_driver",
    "trace_callable", "storage_shape",
]
