"""Sparse core + iterative solvers.

Reference: Elemental's sparse layer (``include/El/core/{Graph, DistGraph,
SparseMatrix, DistSparseMatrix, DistMap}/``) and the iterative pieces of
``reg_ldl``/``LeastSquares``.  The reference's sparse-DIRECT multifrontal
factorization (METIS nested dissection) is consciously out of scope
(SURVEY.md §3.7 item 4, §8.3 item 6); the TPU-native sparse story is
static-shape COO kernels under ``shard_map`` + matmul-free Krylov solvers.
"""
from .core import (sparse_to_coo, Graph, DistGraph, SparseMatrix, DistSparseMatrix,
                   DistMap, sparse_from_coo, dist_sparse_from_coo)
from .solvers import cg, cgls, gmres, sparse_direct_solve
