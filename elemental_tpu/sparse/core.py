"""Sparse matrices: queue-built COO, row-block distributed, shard_map SpMV.

Reference: ``El::Graph``/``DistGraph`` (1-D row-distributed adjacency),
``El::SparseMatrix<T>``/``DistSparseMatrix<T>`` (CSR built via
``Reserve``+``QueueUpdate``+``ProcessQueues``), ``El::DistMap``
(distributed permutation) -- ``include/El/core/*``.

TPU-native design decisions:

* **Build = host-side queues, freeze = device arrays.** The reference's
  QueueUpdate/ProcessQueues idiom maps to a Python builder phase followed
  by ``freeze()``; the frozen matrix is a pytree whose leaves are the
  (p, k) per-device triplet arrays, so the nonzero COUNT is static but the
  structure and values are device data -- one jitted SpMV serves every
  matrix of the same (shape, k), and ``with_values`` re-uses a frozen
  structure with new numbers (the IPM re-factorization pattern).
* **COO, not CSR.** TPU has no CSR advantage; a row-block-partitioned COO
  triplet list feeds one scatter-add -- the whole SpMV is two VPU gathers
  and one scatter on each device.
* **Row-block ownership over the flat mesh**, matching ``DistMultiVec``:
  device d owns rows [d*blk, (d+1)*blk); its triplets are padded to the
  max per-device count k with val=0 no-ops, giving the uniform (p, k)
  stacked arrays ``shard_map`` needs.
* SpMV: x is gathered replicated (``all_gather`` over both axes -- the
  reference's ``DistSparseMatrix::Multiply`` likewise exchanges the
  needed x entries); y comes back row-block.  Adjoint SpMV scatter-adds
  into a replicated accumulator and ``psum``s.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.compat import shard_map
from ..core.grid import Grid, default_grid
from ..core.multivec import DistMultiVec, _blk
from ..core.dist import MC, MR


# ---------------------------------------------------------------------
# Graph / DistGraph (structure-only; El::Graph, El::DistGraph)
# ---------------------------------------------------------------------

class Graph:
    """Adjacency structure built by queued edge insertion.

    ``El::Graph``: ``QueueConnection(s, t)`` + ``ProcessQueues``; here the
    frozen form is the sorted, deduplicated (sources, targets) pair."""

    def __init__(self, num_sources: int, num_targets: int | None = None):
        self.num_sources = num_sources
        self.num_targets = num_sources if num_targets is None else num_targets
        self._q: list[tuple[int, int]] = []
        self._frozen = None

    def queue_connection(self, s: int, t: int) -> None:
        if not (0 <= s < self.num_sources and 0 <= t < self.num_targets):
            raise ValueError(f"edge ({s},{t}) out of bounds")
        self._q.append((s, t))
        self._frozen = None

    def process_queues(self):
        """Sort + dedup; returns (sources, targets) int arrays."""
        if self._frozen is None:
            if self._q:
                st = np.unique(np.asarray(self._q, np.int64), axis=0)
            else:
                st = np.zeros((0, 2), np.int64)
            self._frozen = (st[:, 0].copy(), st[:, 1].copy())
        return self._frozen

    @property
    def num_edges(self) -> int:
        return len(self.process_queues()[0])


class DistGraph(Graph):
    """Row-block distributed adjacency (``El::DistGraph``): same build API;
    the partition is implied by the owning ``DistSparseMatrix``."""

    def __init__(self, num_sources: int, num_targets: int | None = None,
                 grid: Grid | None = None):
        super().__init__(num_sources, num_targets)
        self.grid = grid or default_grid()


# ---------------------------------------------------------------------
# DistMap (El::DistMap): distributed permutation for reorderings
# ---------------------------------------------------------------------

class DistMap:
    """A permutation of [0, n) applied to DistMultiVec rows.

    ``El::DistMap`` stores the image distributed; here the image vector is
    replicated metadata (n host ints) and application is one row-gather on
    the padded leaf -- XLA shards the take."""

    def __init__(self, image, grid: Grid | None = None):
        self.image = np.asarray(image, np.int64)
        n = self.image.shape[0]
        if sorted(self.image.tolist()) != list(range(n)):
            raise ValueError("DistMap image is not a permutation")
        self.grid = grid or default_grid()

    @property
    def size(self) -> int:
        return self.image.shape[0]

    def inverse(self) -> "DistMap":
        inv = np.empty_like(self.image)
        inv[self.image] = np.arange(self.size)
        return DistMap(inv, self.grid)

    def translate(self, v: DistMultiVec) -> DistMultiVec:
        """w[image[i]] = v[i] (``DistMap::Translate``)."""
        m = v.gshape[0]
        if m != self.size:
            raise ValueError(f"DistMap size {self.size} vs vector rows {m}")
        inv = self.inverse().image
        pad = v.local.shape[0] - m
        idx = jnp.concatenate([jnp.asarray(inv),
                               jnp.arange(m, m + pad)])
        return v.with_local(jnp.take(v.local, idx, axis=0))


# ---------------------------------------------------------------------
# DistSparseMatrix: frozen (p, k) row-block COO
# ---------------------------------------------------------------------

_ROWSPEC = P(("mc", "mr"), None)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["vals", "rows_loc", "cols"],
    meta_fields=["gshape", "nnz", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DistSparseMatrix:
    """Frozen row-block COO matrix (leaves = per-device triplet arrays).

    ``rows_loc``: (p, k) int32 LOCAL row offsets (global row = d*blk +
    rows_loc[d]); ``cols``: (p, k) int32 global column ids; ``vals``:
    (p, k) values.  All three sharded row-block over the flat mesh;
    padding entries are (0, 0, 0) no-ops.  ``nnz``/``gshape``/``grid`` are
    static, so one jit specialization covers every matrix with the same
    shape and per-device budget k.
    """
    vals: Any
    rows_loc: Any
    cols: Any
    gshape: tuple
    nnz: int
    grid: Grid

    @property
    def dtype(self):
        return self.vals.dtype

    def with_values(self, vals) -> "DistSparseMatrix":
        """New numbers on the same frozen structure (IPM refactor path)."""
        return dataclasses.replace(self, vals=vals)

    def __repr__(self):
        return (f"DistSparseMatrix(gshape={self.gshape}, nnz={self.nnz}, "
                f"grid={self.grid})")

    # ---- SpMV --------------------------------------------------------

    def spmv(self, x: DistMultiVec, alpha=1.0) -> DistMultiVec:
        """y = alpha * A x (``El::Multiply(NORMAL, ...)``)."""
        if x.gshape[0] != self.gshape[1]:
            raise ValueError(f"A is {self.gshape}, x has {x.gshape[0]} rows")
        return _spmv(self, x, jnp.asarray(alpha, self.vals.dtype))

    def spmv_adjoint(self, x: DistMultiVec, alpha=1.0) -> DistMultiVec:
        """y = alpha * A^H x (``El::Multiply(ADJOINT, ...)``)."""
        if x.gshape[0] != self.gshape[0]:
            raise ValueError(f"A^H needs {self.gshape[0]} rows, "
                             f"x has {x.gshape[0]}")
        return _spmv_adjoint(self, x, jnp.asarray(alpha, self.vals.dtype))

    # ---- bridges -----------------------------------------------------

    def to_dense(self):
        """Materialize as a [MC,MR] DistMatrix (small problems / tests)."""
        from ..core.distmatrix import from_global
        m, n = self.gshape
        blk = _blk(m, self.grid.size)
        rl = np.asarray(self.rows_loc)
        p, k = rl.shape
        rg = rl + blk * np.arange(p)[:, None]
        dense = np.zeros((m, n), np.asarray(self.vals).dtype)
        np.add.at(dense, (np.minimum(rg, m - 1).reshape(-1),
                          np.asarray(self.cols).reshape(-1)),
                  np.asarray(self.vals).reshape(-1))
        return from_global(dense, MC, MR, grid=self.grid)


@jax.jit
def _spmv(A: DistSparseMatrix, x: DistMultiVec, alpha) -> DistMultiVec:
    """Footprint note: x is all-gathered fully replicated before the local
    gather-multiply -- O(p * n * w) aggregate traffic and O(n * w) memory
    per device.  The reference instead exchanges only the column support
    per rank (``DistSparseMatrix::Multiply`` metadata); the all-gather is
    the right TPU trade while n*w stays << HBM (w is 1..O(10) here), and
    the per-support exchange (a ragged all_to_all) is the upgrade path if
    a workload ever needs n beyond replicated-vector scale."""
    m, n = A.gshape
    g = A.grid
    w = x.width
    blk_m = _blk(m, g.size)
    out_meta = DistMultiVec(None, (m, w), g)

    def f(vals, rows_l, cols_g, xloc):
        xf = lax.all_gather(xloc, ("mc", "mr"), tiled=True)    # (n_pad, w)
        contrib = vals.reshape(-1, 1) * jnp.take(xf, cols_g.reshape(-1),
                                                 axis=0)       # (k, w)
        y = jnp.zeros((blk_m, w), contrib.dtype).at[
            rows_l.reshape(-1)].add(contrib)
        return alpha * y

    y = shard_map(
        f, mesh=g.mesh,
        in_specs=(_ROWSPEC, _ROWSPEC, _ROWSPEC, x.spec),
        out_specs=out_meta.spec, check_vma=False,
    )(A.vals, A.rows_loc, A.cols, x.local)
    return out_meta.with_local(y)


@jax.jit
def _spmv_adjoint(A: DistSparseMatrix, x: DistMultiVec, alpha) -> DistMultiVec:
    m, n = A.gshape
    g = A.grid
    w = x.width
    blk_n = _blk(n, g.size)
    out_meta = DistMultiVec(None, (n, w), g)

    def f(vals, rows_l, cols_g, xloc):
        # this device's triplets hit ITS OWN x rows (row-block match)
        contrib = jnp.conj(vals.reshape(-1, 1)) * jnp.take(
            xloc, rows_l.reshape(-1), axis=0)                  # (k, w)
        yfull = jnp.zeros((g.size * blk_n, w), contrib.dtype).at[
            cols_g.reshape(-1)].add(contrib)
        yfull = lax.psum(yfull, ("mc", "mr"))
        me = lax.axis_index("mc") * g.width + lax.axis_index("mr")
        return alpha * lax.dynamic_slice_in_dim(yfull, me * blk_n, blk_n)

    y = shard_map(
        f, mesh=g.mesh,
        in_specs=(_ROWSPEC, _ROWSPEC, _ROWSPEC, x.spec),
        out_specs=out_meta.spec, check_vma=False,
    )(A.vals, A.rows_loc, A.cols, x.local)
    return out_meta.with_local(y)


# ---------------------------------------------------------------------
# Builders
# ---------------------------------------------------------------------

class SparseMatrix:
    """Sequential/queue-building front end (``El::SparseMatrix``).

    ``queue_update(i, j, v)`` batches entries (duplicates sum);
    ``freeze(grid)`` coalesces and returns the immutable
    ``DistSparseMatrix`` (1x1 grid => sequential semantics)."""

    def __init__(self, m: int, n: int | None = None):
        self.m = m
        self.n = m if n is None else n
        self._i: list[int] = []
        self._j: list[int] = []
        self._v: list[float] = []

    def queue_update(self, i: int, j: int, v) -> None:
        if not (0 <= i < self.m and 0 <= j < self.n):
            raise ValueError(f"entry ({i},{j}) out of bounds "
                             f"for {self.m}x{self.n}")
        self._i.append(i); self._j.append(j); self._v.append(v)

    def freeze(self, grid: Grid | None = None, dtype=None) -> DistSparseMatrix:
        return dist_sparse_from_coo(self._i, self._j, self._v,
                                    self.m, self.n, grid=grid, dtype=dtype)


def sparse_from_coo(rows, cols, vals, m: int, n: int,
                    dtype=None) -> DistSparseMatrix:
    """COO -> frozen sparse matrix on the default grid."""
    return dist_sparse_from_coo(rows, cols, vals, m, n, dtype=dtype)


def dist_sparse_from_coo(rows, cols, vals, m: int, n: int,
                         grid: Grid | None = None, dtype=None,
                         pad_to: int | None = None) -> DistSparseMatrix:
    """Coalesce (sum duplicates), partition by row-block owner, pad each
    device's triplet list to the max count (or ``pad_to``, to share one
    jit specialization across matrices), freeze to device arrays."""
    grid = grid or default_grid()
    p = grid.size
    rows = np.asarray(rows, np.int64).reshape(-1)
    cols = np.asarray(cols, np.int64).reshape(-1)
    vals = np.asarray(vals).reshape(-1)
    if dtype is not None:
        vals = vals.astype(dtype)
    if rows.size:
        if rows.min() < 0 or rows.max() >= m or cols.min() < 0 \
                or cols.max() >= n:
            raise ValueError("COO indices out of bounds")
        key = rows * n + cols
        order = np.argsort(key, kind="stable")
        key, vals = key[order], vals[order]
        uniq, start = np.unique(key, return_index=True)
        vals = np.add.reduceat(vals, start)
        rows, cols = uniq // n, uniq % n
    nnz = rows.size
    blk = _blk(m, p)
    owner = rows // blk
    k = max(int(np.bincount(owner, minlength=p).max()) if nnz else 0, 1)
    if pad_to is not None:
        if pad_to < k:
            raise ValueError(f"pad_to={pad_to} < required per-device {k}")
        k = pad_to
    R = np.zeros((p, k), np.int32)
    C = np.zeros((p, k), np.int32)
    V = np.zeros((p, k), vals.dtype)
    for d in range(p):
        sel = owner == d
        cnt = int(sel.sum())
        R[d, :cnt] = rows[sel] - d * blk
        C[d, :cnt] = cols[sel]
        V[d, :cnt] = vals[sel]
    sh = grid.sharding(_ROWSPEC)
    return DistSparseMatrix(
        jax.device_put(jnp.asarray(V), sh),
        jax.device_put(jnp.asarray(R), sh),
        jax.device_put(jnp.asarray(C), sh),
        (m, n), nnz, grid)


def sparse_to_coo(A: DistSparseMatrix):
    """Host (rows, cols, vals) triplets of a DistSparseMatrix (padding
    no-ops dropped) -- the inverse of :func:`dist_sparse_from_coo`."""
    from ..core.multivec import _blk
    m, n = A.gshape
    blk = _blk(m, A.grid.size)
    rl = np.asarray(A.rows_loc)
    p, k = rl.shape
    rg = (rl + blk * np.arange(p)[:, None]).reshape(-1)
    cg = np.asarray(A.cols).reshape(-1)
    vg = np.asarray(A.vals).reshape(-1)
    keep = vg != 0
    return rg[keep], cg[keep], vg[keep]
