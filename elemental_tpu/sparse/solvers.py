"""Matmul-free Krylov solvers on (DistSparseMatrix, DistMultiVec).

Reference analogs: the iterative layer the reference wraps around its
sparse factorizations -- ``reg_ldl::RegularizedSolveAfter``'s FGMRES/IR
refinement loops and the sparse branch of ``El::LeastSquares``
(``src/lapack_like/euclidean_min/LeastSquares.cpp``).  The reference
refines a multifrontal LDL preconditioner; with sparse-direct out of scope
(SURVEY.md §8.3 item 6) the solvers stand alone (optionally Jacobi-
preconditioned) -- same host-side convergence loop, device-side iteration
split (SURVEY.md §4.6).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.multivec import (DistMultiVec, mv_axpy, mv_dot, mv_nrm2,
                             mv_scale, mv_zeros)
from .core import DistSparseMatrix


def _check(A: DistSparseMatrix, b: DistMultiVec, square: bool):
    m, n = A.gshape
    if square and m != n:
        raise ValueError(f"cg needs square A, got {A.gshape}")
    if b.gshape[0] != m:
        raise ValueError(f"b has {b.gshape[0]} rows, A is {A.gshape}")


def cg(A: DistSparseMatrix, b: DistMultiVec, x0: DistMultiVec | None = None,
       tol: float = 1e-10, maxiter: int | None = None):
    """Conjugate gradients for SPD A x = b.

    Returns (x, info) with info = {converged, iters, relres}."""
    _check(A, b, square=True)
    n = A.gshape[1]
    maxiter = 2 * n if maxiter is None else maxiter
    x = mv_zeros(n, b.width, grid=b.grid, dtype=b.dtype) if x0 is None else x0
    r = mv_axpy(-1.0, A.spmv(x), b)               # r = b - A x
    p = r
    rs = float(jnp.real(mv_dot(r, r)))
    bnorm = max(float(mv_nrm2(b)), 1e-300)
    iters = 0
    while iters < maxiter and np.sqrt(rs) / bnorm >= tol:
        Ap = A.spmv(p)
        alpha = rs / float(jnp.real(mv_dot(p, Ap)))
        x = mv_axpy(alpha, p, x)
        r = mv_axpy(-alpha, Ap, r)
        rs_new = float(jnp.real(mv_dot(r, r)))
        p = mv_axpy(rs_new / rs, p, r)            # p = r + beta p
        rs = rs_new
        iters += 1
    relres = np.sqrt(rs) / bnorm
    return x, {"converged": relres < tol, "iters": iters, "relres": relres}


def cgls(A: DistSparseMatrix, b: DistMultiVec,
         tol: float = 1e-10, maxiter: int | None = None,
         damp: float = 0.0):
    """CGLS: min ||A x - b||^2 + damp^2 ||x||^2 via CG on the normal
    equations, without forming A^H A (the sparse LeastSquares/Ridge path).

    Returns (x, info)."""
    _check(A, b, square=False)
    m, n = A.gshape
    maxiter = 2 * n if maxiter is None else maxiter
    x = mv_zeros(n, b.width, grid=b.grid, dtype=b.dtype)
    r = b                                          # residual in range space
    s = A.spmv_adjoint(r)                          # normal-eq residual
    if damp:
        s = mv_axpy(-damp * damp, x, s)
    p = s
    gamma = float(jnp.real(mv_dot(s, s)))
    s0 = max(np.sqrt(gamma), 1e-300)
    iters = 0
    while iters < maxiter and np.sqrt(gamma) / s0 >= tol:
        q = A.spmv(p)
        denom = float(jnp.real(mv_dot(q, q))) + damp * damp * float(
            jnp.real(mv_dot(p, p)))
        alpha = gamma / max(denom, 1e-300)
        x = mv_axpy(alpha, p, x)
        r = mv_axpy(-alpha, q, r)
        s = A.spmv_adjoint(r)
        if damp:
            s = mv_axpy(-damp * damp, x, s)
        gamma_new = float(jnp.real(mv_dot(s, s)))
        p = mv_axpy(gamma_new / gamma, p, s)
        gamma = gamma_new
        iters += 1
    relres = np.sqrt(gamma) / s0
    return x, {"converged": relres < tol, "iters": iters, "relres": relres}


def gmres(A: DistSparseMatrix, b: DistMultiVec,
          tol: float = 1e-10, maxiter: int | None = None,
          restart: int = 50):
    """Restarted GMRES(restart) for general square A x = b.

    Arnoldi basis vectors are DistMultiVecs; the (restart+1, restart)
    Hessenberg least-squares is solved on host (it is tiny) -- the
    FGMRES-shaped loop of ``reg_ldl::RegularizedSolveAfter``."""
    _check(A, b, square=True)
    n = A.gshape[1]
    maxiter = 2 * n if maxiter is None else maxiter
    if b.width != 1:
        raise ValueError("gmres expects a single right-hand side")
    x = mv_zeros(n, 1, grid=b.grid, dtype=b.dtype)
    bnorm = max(float(mv_nrm2(b)), 1e-300)
    total_it = 0
    relres = np.inf
    while total_it < maxiter:
        r = mv_axpy(-1.0, A.spmv(x), b)
        beta = float(mv_nrm2(r))
        if beta / bnorm < tol:
            return x, {"converged": True, "iters": total_it,
                       "relres": beta / bnorm}
        V = [mv_scale(1.0 / beta, r)]
        k = min(restart, maxiter - total_it)
        cplx = np.issubdtype(np.dtype(b.dtype), np.complexfloating)
        H = np.zeros((k + 1, k), np.complex128 if cplx else np.float64)
        j_done = 0
        for j in range(k):
            w = A.spmv(V[j])
            for i in range(j + 1):                 # modified Gram-Schmidt
                hij = complex(mv_dot(V[i], w)) if cplx else float(
                    jnp.real(mv_dot(V[i], w)))
                H[i, j] = hij
                w = mv_axpy(-hij, V[i], w)
            hnorm = float(mv_nrm2(w))              # real even for complex A
            H[j + 1, j] = hnorm
            j_done = j + 1
            total_it += 1
            # in-loop convergence: the Arnoldi relation gives the TRUE
            # residual norm as the tiny (j+2, j+1) host least-squares
            # residual -- O(j^3) host flops, nothing vs one distributed spmv
            e1 = np.zeros(j + 2, H.dtype); e1[0] = beta
            _, res, *_ = np.linalg.lstsq(H[: j + 2, : j + 1], e1, rcond=None)
            relres = float(np.sqrt(res[0])) / bnorm if res.size \
                else float(np.linalg.norm(
                    e1 - H[: j + 2, : j + 1] @ np.linalg.lstsq(
                        H[: j + 2, : j + 1], e1, rcond=None)[0])) / bnorm
            # lucky breakdown: the Krylov space is invariant (exact solve)
            if relres < tol or hnorm < 1e-14 * max(abs(H[j, j]), 1.0):
                break
            V.append(mv_scale(1.0 / hnorm, w))
        e1 = np.zeros(j_done + 1, H.dtype); e1[0] = beta
        y, *_ = np.linalg.lstsq(H[: j_done + 1, : j_done], e1, rcond=None)
        for i in range(j_done):
            coef = complex(y[i]) if cplx else float(np.real(y[i]))
            x = mv_axpy(coef, V[i], x)
        if relres < tol:
            break
    r = mv_axpy(-1.0, A.spmv(x), b)
    relres = float(mv_nrm2(r)) / bnorm
    return x, {"converged": relres < tol, "iters": total_it,
               "relres": relres}


def sparse_direct_solve(A: DistSparseMatrix, b: DistMultiVec,
                        refine: int = 2, tol: float = 1e-12):
    """Sequential sparse-direct solve A x = b (square A) -- the
    ``El::SparseMatrix`` + ``ldl``/``LinearSolve`` sequential sparse path:
    one host splu factorization (SuperLU: the role the reference's
    bundled sequential multifrontal plays) + device-side SpMV iterative
    refinement, mirroring ``reg_ldl::RegularizedSolveAfter``'s
    factor-then-refine shape.  Returns (x, info).

    For the fully-distributed-solver path use :func:`cg`/:func:`gmres`;
    the distributed multifrontal numeric factorization is the upgrade
    path (SURVEY.md §3.4 sparse-direct row)."""
    import numpy as np
    import scipy.sparse as sp
    import scipy.sparse.linalg as spl
    from ..core.multivec import mv_from_global, mv_to_global
    from .core import sparse_to_coo
    _check(A, b, square=True)
    m, n = A.gshape
    ro, co, vo = sparse_to_coo(A)
    vo = np.asarray(vo)
    dt = np.complex128 if np.iscomplexobj(vo) else np.float64
    M = sp.csc_matrix((vo.astype(dt), (ro, co)), shape=(m, n))
    lu = spl.splu(M)
    bh = np.asarray(mv_to_global(b))
    x = mv_from_global(lu.solve(bh), grid=b.grid)
    bnorm = max(float(mv_nrm2(b)), 1e-300)
    relres = np.inf
    for _ in range(refine):
        r = mv_axpy(-1.0, A.spmv(x), b)        # device-side true residual
        relres = float(mv_nrm2(r)) / bnorm
        if relres < tol:
            break
        rh = np.asarray(mv_to_global(r))
        x = mv_axpy(1.0, mv_from_global(lu.solve(rh), grid=b.grid), x)
    r = mv_axpy(-1.0, A.spmv(x), b)
    relres = float(mv_nrm2(r)) / bnorm
    return x, {"relres": relres, "converged": relres < max(tol, 1e-10)}
