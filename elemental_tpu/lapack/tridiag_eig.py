"""Cuppen divide-and-conquer symmetric tridiagonal eigensolver.

The TPU-native replacement for the reference's bundled PMRRR
(``external/pmrrr``, driven from ``src/lapack_like/spectral/HermitianEig.cpp``
via ``herm_tridiag_eig::``): the reference farms the tridiagonal EVP out to
a 15k-LoC MPI+pthreads MRRR code; on TPU the right shape is Cuppen's
divide-and-conquer (LAPACK ``dstedc``'s algorithm), whose O(n^3) work is
eigenvector *matmuls* (MXU) and whose O(n^2) secular-equation work
vectorizes over roots on the VPU.

Design (SURVEY.md §8.1 item 4, VERDICT r3 item 3):

  * **Static shapes, no dynamic deflation.**  LAPACK's ``dlaed2`` deflates
    tiny rank-one weights and rotates away near-equal poles, producing
    data-dependent problem sizes -- hostile to XLA.  Here both cases are
    handled by a bounded PERTURBATION instead: pole gaps are enforced to
    ``>= 8 eps * scale`` (parallel cummax trick) and rank-one weights are
    floored at ``sqrt(eps)``, then the full-size secular problem is solved.
    The computed eigenpairs are EXACT for a tridiagonal within
    ``O(eps * ||T||)`` of the input -- the same backward-error contract as
    deflation, with none of the shape dynamism (the flop saving deflation
    buys on CPUs is irrelevant on the MXU).
  * **mu-anchored bisection.**  Root i of the secular equation
    ``1 + rho sum z_j^2/(d_j - lam) = 0`` is found as ``lam_i = d_i + mu_i``
    by bisecting in ``mu`` over (0, d_{i+1}-d_i): the tiny difference
    ``lam_i - d_i`` is the iterate itself, so eigenvector denominators
    ``(d_j - d_i) - mu_i`` never cancel (the dlaed4 trick).  All roots in
    parallel, memory chunked O(n * chunk).
  * **Gu-Eisenstat reconstruction.**  zhat is recomputed from the computed
    roots via the characteristic-polynomial product formula (log1p-paired
    so partial sums stay bounded), making the eigenvector matrix orthogonal
    to working precision without Gram-Schmidt.
  * **Two-phase batching.**  Subproblems of size <= ``repl_max`` are merged
    REPLICATED and vmap-batched over the subproblem axis ((B, nm, nm)
    arrays, O(n * repl_max) memory); larger merges keep the accumulated
    eigenvector matrix as a block-diagonal [MC,MR] ``DistMatrix`` and do
    the two half-height updates as distributed SUMMA gemms with the secular
    eigenvector matrix V filled TILE-LOCALLY from O(n) replicated vectors
    -- no replicated n x n array ever exists above ``repl_max``.

The secular stage runs in float64 when x64 is enabled (CPU mesh tests) and
float32 otherwise (TPU), independent of the storage dtype.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute
from ..redist.interior import interior_view, interior_update
from ..blas.level1 import index_dependent_fill
from ..blas.level3 import gemm
from .lu import _hi


def _sec_dtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# ---------------------------------------------------------------------
# secular equation: one merge, all roots in parallel
# ---------------------------------------------------------------------

def _enforce_gaps(ds, eta):
    """Monotone perturbation: ds_i <- max over j<=i of (ds_j + (i-j)*eta),
    guaranteeing ds_{i+1} - ds_i >= eta while moving each entry by at most
    (#violations)*eta.  Parallel via the cummax-of-shifted trick."""
    n = ds.shape[0]
    i = jnp.arange(n, dtype=ds.dtype)
    u = ds - i * eta
    u = lax.associative_scan(jnp.maximum, u)
    return u + i * eta


def _secular(D, z, beta, scale, n_iters: int, chunk: int):
    """Solve eig(D + beta z z^T) with static shapes.

    Returns (lam, perm, ds, tau, aidx, zhat, cninv, flip):
      lam   -- eigenvalues ascending, shape (n,)
      perm  -- argsort of the (possibly negated) pole vector: core row k
               corresponds to original position perm[k]
      ds    -- gap-enforced sorted poles (core domain)
      tau   -- lam_core[i] - ds[aidx[i]]: signed offset from the CLOSER
               interval endpoint (the dlaed4 anchoring -- root i lies in
               (ds[i], ds[i+1]); anchoring at the nearer pole keeps every
               eigenvector denominator ds[k] - lam_i cancellation-free)
      aidx  -- anchor index per root (i or i+1)
      zhat  -- Gu-Eisenstat weights in core row order
      cninv -- 1/||column i||
      flip  -- True where beta < 0: final column c = core column n-1-c,
               final lam = -reverse(core lam)
    All in the secular dtype; the caller maps V entries through
    (perm, flip) when materializing eigenvectors.
    """
    sdt = _sec_dtype()
    eps = jnp.finfo(sdt).eps
    tfloor = 4 * jnp.sqrt(jnp.finfo(sdt).tiny) * jnp.maximum(scale, 1.0)
    D = D.astype(sdt)
    z = z.astype(sdt)
    beta = jnp.asarray(beta, sdt)
    n = D.shape[0]

    flip = beta < 0
    rho = jnp.maximum(jnp.abs(beta), 16 * eps * scale)
    Dw = jnp.where(flip, -D, D)
    perm = jnp.argsort(Dw)
    ds = _enforce_gaps(Dw[perm], 8 * eps * scale)
    zp = z[perm]
    sgn = jnp.where(zp >= 0, 1.0, -1.0).astype(sdt)
    # floor |z| at 2 eps: just enough to keep every secular pole present
    # (no 0/0 in the eigenvector fill); the off-diagonal backward error
    # rho*|dz|*|z_k| stays at eps * ||T||.  A sqrt(eps) floor here costs
    # sqrt(eps)-level residuals -- eigenvector rows of tridiagonals decay
    # exponentially, so tiny z entries are COMMON, not an edge case.
    zs = sgn * jnp.maximum(jnp.abs(zp), 2 * eps)
    z2 = zs * zs
    zn2 = jnp.sum(z2)

    # interval upper widths: gap to next pole; last root in
    # (ds[n-1], ds[n-1] + rho*||z||^2)
    gaps = jnp.concatenate([ds[1:] - ds[:-1],
                            (rho * zn2 * (1 + 4 * eps) + eps * scale)[None]])

    def solve_chunk(s, width):
        idx = s + jnp.arange(width)
        g0 = gaps[idx]
        half = 0.5 * g0
        # anchor choice (dlaed4): f at the interval midpoint; f < 0 means
        # the root is in the upper half -- anchor at the UPPER pole and
        # solve for tau in (-gap/2, 0).  Last root always anchors low.
        diff_lo = ds[None, :] - ds[idx][:, None]       # (C, n): d_j - d_i
        fmid = 1.0 + rho * jnp.sum(
            z2[None, :] / (diff_lo - half[:, None]), axis=1)
        upper = (fmid < 0) & (idx < n - 1)
        aidx = idx + upper
        diff = ds[None, :] - ds[aidx][:, None]         # d_j - d_anchor
        lo = jnp.where(upper, -half, 0.0)
        hi = jnp.where(upper, 0.0, half)

        def body(_, lh):
            lo, hi = lh
            mid = 0.5 * (lo + hi)
            f = 1.0 + rho * jnp.sum(z2[None, :] / (diff - mid[:, None]),
                                    axis=1)
            neg = f < 0
            return jnp.where(neg, mid, lo), jnp.where(neg, hi, mid)

        lo, hi = lax.fori_loop(0, n_iters, body, (lo, hi))
        tau = 0.5 * (lo + hi)
        # Newton polish (clamped to the bisection bracket): restores
        # RELATIVE accuracy for roots tiny compared to their interval,
        # which pure absolute bisection cannot deliver.
        for _ in range(2):
            den = diff - tau[:, None]
            f = 1.0 + rho * jnp.sum(z2[None, :] / den, axis=1)
            fp = rho * jnp.sum(z2[None, :] / (den * den), axis=1)
            t_new = tau - f / fp
            tau = jnp.where((t_new > lo) & (t_new < hi), t_new, tau)
        # keep tau strictly off the anchor pole (else 0/0 downstream)
        tau = jnp.where(upper, jnp.minimum(tau, -tfloor),
                        jnp.maximum(tau, tfloor))
        return tau, aidx

    taus, aidxs = [], []
    c = min(chunk, n)
    for s in range(0, n, c):
        w = min(c, n - s)
        t, a = solve_chunk(s, w)
        taus.append(t)
        aidxs.append(a)
    tau = jnp.concatenate(taus) if len(taus) > 1 else taus[0]
    aidx = jnp.concatenate(aidxs) if len(aidxs) > 1 else aidxs[0]
    off = (ds[aidx] - ds) + tau            # lam_i - ds[i]  (in (0, gap_i))

    # Gu-Eisenstat: zhat_k^2 = prod_i (lam_i - d_k) / (rho prod_{i!=k}
    # (d_i - d_k)), paired per i as log1p(off_i/(d_i - d_k)) so partial
    # sums stay O(1).  Exact special cases: i == k contributes
    # log(off_k); k == aidx_i (upper-anchored neighbor) contributes
    # log(-tau_i) - log(gap_i) since lam_i - d_k = tau_i exactly.
    k_idx = jnp.arange(n)
    acc = jnp.zeros((n,), sdt)
    nrm = jnp.zeros((n,), sdt)                 # column norms^2, core order
    gap_anchor = ds[aidx] - ds                 # gap_i for upper roots, 0 else
    for s in range(0, n, c):
        w = min(c, n - s)
        i_idx = s + jnp.arange(w)
        diff_ki = ds[i_idx][None, :] - ds[:, None]     # (n, C): d_i - d_k
        offi = off[i_idx][None, :]
        is_diag = k_idx[:, None] == i_idx[None, :]
        is_anchor = (k_idx[:, None] == aidx[i_idx][None, :]) & ~is_diag
        safe = jnp.where(is_diag | is_anchor, 1.0, diff_ki)
        generic = jnp.log1p(offi / safe)
        anchor_term = (jnp.log(-tau[i_idx]) -
                       jnp.log(gap_anchor[i_idx]))[None, :] \
            * jnp.ones((n, 1), sdt)
        diag_term = jnp.log(off[i_idx])[None, :] * jnp.ones((n, 1), sdt)
        pair = jnp.where(is_diag, diag_term,
                         jnp.where(is_anchor, anchor_term, generic))
        acc = acc + jnp.sum(pair, axis=1)
    zhat = sgn * jnp.exp(0.5 * (acc - jnp.log(rho)))
    zh2 = zhat * zhat
    for s in range(0, n, c):
        w = min(c, n - s)
        i_idx = s + jnp.arange(w)
        denom = (ds[:, None] - ds[aidx[i_idx]][None, :]) \
            - tau[i_idx][None, :]
        contrib = jnp.sum(zh2[:, None] / (denom * denom), axis=0)
        nrm = nrm.at[i_idx].set(contrib)
    cninv = 1.0 / jnp.sqrt(nrm)

    lam_core = ds + off
    lam = jnp.where(flip, -lam_core[::-1], lam_core)
    return lam, perm, ds, tau, aidx, zhat, cninv, flip


def _v_entries(row_pos, col_pos, perm, ds, tau, aidx, zhat, cninv, flip,
               out_dtype):
    """V[row_pos, col_pos] of the secular eigenvector matrix in ORIGINAL
    row basis and FINAL (ascending-lam) column order, given the core
    quantities from :func:`_secular`.  Shapes broadcast: row_pos (..., 1),
    col_pos (1, ...) or any broadcastable pair of int arrays."""
    n = perm.shape[0]
    invperm = jnp.argsort(perm)
    k = invperm[jnp.clip(row_pos, 0, n - 1)]           # core row of orig row
    col = jnp.where(flip, n - 1 - jnp.clip(col_pos, 0, n - 1),
                    jnp.clip(col_pos, 0, n - 1))
    denom = (ds[k] - ds[aidx[col]]) - tau[col]         # d_k - lam_col, exact
    return (zhat[k] / denom * cninv[col]).astype(out_dtype)


# ---------------------------------------------------------------------
# replicated batched phase
# ---------------------------------------------------------------------

def _merge_replicated(lam1, lam2, Q1, Q2, beta, scale, n_iters, chunk,
                      precision):
    """One merge on replicated data: returns (lam_new, Q_new) with
    Q_new = blockdiag(Q1, Q2) @ V.  All matmul work on the MXU."""
    nm = lam1.shape[0]
    n2 = 2 * nm
    D = jnp.concatenate([lam1, lam2])
    z = jnp.concatenate([Q1[-1, :], Q2[0, :]])
    lam, perm, ds, tau, aidx, zhat, cninv, flip = _secular(
        D, z, beta, scale, n_iters, chunk)
    rows = jnp.arange(n2)[:, None]
    cols = jnp.arange(n2)[None, :]
    V = _v_entries(rows, cols, perm, ds, tau, aidx, zhat, cninv, flip,
                   Q1.dtype)
    # eigenvector accumulation is factor-forming: full f32 accumulation
    # (default bf16-input matmul costs ~1e-3 residuals on TPU)
    top = jnp.matmul(Q1, V[:nm, :], precision=_hi(precision))
    bot = jnp.matmul(Q2, V[nm:, :], precision=_hi(precision))
    return lam.astype(lam1.dtype), jnp.concatenate([top, bot], axis=0)


def _merge_rows_only(lam1, lam2, fr1, lr1, fr2, lr2, beta, scale, n_iters,
                     chunk, precision):
    """Eigenvalue-only merge: carries just the FIRST and LAST rows of the
    eigenvector matrix (enough to form the next level's z), O(nm^2) work,
    O(nm) state."""
    nm = lam1.shape[0]
    n2 = 2 * nm
    D = jnp.concatenate([lam1, lam2])
    z = jnp.concatenate([lr1, fr2])
    lam, perm, ds, tau, aidx, zhat, cninv, flip = _secular(
        D, z, beta, scale, n_iters, chunk)
    rows = jnp.arange(n2)[:, None]
    cols = jnp.arange(n2)[None, :]
    V = _v_entries(rows, cols, perm, ds, tau, aidx, zhat, cninv, flip,
                   fr1.dtype)
    fr = jnp.concatenate([fr1, jnp.zeros_like(fr2)]) @ V
    lr = jnp.concatenate([jnp.zeros_like(lr1), lr2]) @ V
    return lam.astype(lam1.dtype), fr, lr


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------

def _plan(n: int, leaf_max: int):
    """(base, levels): npad = base * 2^levels >= n with base in
    (leaf_max/2, leaf_max] so padding never exceeds 2^levels entries."""
    if n <= leaf_max:
        return n, 0
    L = max(0, math.ceil(math.log2(n / leaf_max)))
    base = math.ceil(n / (1 << L))
    return base, L


def _leaf_eigh(d_adj, e_leaf, base: int, B: int):
    """Batched dense EVP of the (B, base, base) leaf blocks; ``e_leaf`` is
    (B, base) with per-leaf interior couplings in columns [0, base-1)."""
    dmat = jax.vmap(jnp.diag)(d_adj.reshape(B, base))
    if base > 1:
        eb = e_leaf[:, :-1]
        idx = jnp.arange(base - 1)
        dmat = dmat.at[:, idx + 1, idx].add(eb)
        dmat = dmat.at[:, idx, idx + 1].add(eb)
    return jnp.linalg.eigh(dmat)


def tridiag_eig(d, e, grid=None, vectors: bool = True,
                leaf_max: int = 96, repl_max: int = 512,
                chunk: int = 1024, precision=None):
    """Eigendecomposition of the symmetric tridiagonal T = tridiag(e, d, e).

    Returns ascending ``w`` (replicated, secular dtype cast to d.dtype) and,
    when ``vectors``, the eigenvector matrix as an [MC,MR] ``DistMatrix``
    over ``grid`` (replicated ndarray if ``grid`` is None).

    The scalable replacement for the reference's PMRRR tridiagonal kernel
    (``src/core/imports/pmrrr.cpp``): above ``repl_max`` no replicated
    n x n array is ever materialized.

    The whole driver runs under ONE jit (static plan metadata): eager
    per-op dispatch of its hundreds of small secular-stage ops is fine on
    CPU but pathological on remote/tunneled TPU backends.
    """
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    return _tridiag_eig_jit(d, e, grid, vectors, leaf_max, repl_max,
                            chunk, precision)


@partial(jax.jit, static_argnames=("grid", "vectors", "leaf_max",
                                   "repl_max", "chunk", "precision"))
def _tridiag_eig_jit(d, e, grid, vectors, leaf_max, repl_max, chunk,
                     precision):
    sdt = _sec_dtype()
    n = d.shape[0]
    odt = jnp.result_type(jnp.asarray(d).dtype, jnp.float32)
    d = jnp.asarray(d, sdt)
    e = jnp.asarray(e, sdt)
    n_iters = 62 if sdt == jnp.float64 else 30
    scale = jnp.max(jnp.abs(d)) + 2 * jnp.max(jnp.abs(e)) if n > 1 \
        else jnp.abs(d[0]) + 1.0
    scale = scale + 1e-30

    base, L = _plan(n, leaf_max)
    npad = base << L
    # pad with decoupled sentinel diagonals ABOVE the spectrum so they sort
    # to the tail and slice off exactly
    sent = scale * (3.0 + jnp.arange(npad - n, dtype=sdt))
    dp = jnp.concatenate([d, sent])
    ep = jnp.concatenate([e, jnp.zeros((npad - 1 - (n - 1),), sdt)])

    # pre-apply every split's rank-one diagonal correction: at each interior
    # leaf boundary k (multiple of base), d[k-1] -= e[k-1], d[k] -= e[k-1]
    nblk = npad // base
    bidx = base * jnp.arange(1, nblk)
    beta_all = ep[bidx - 1]
    d_adj = dp.at[bidx - 1].add(-beta_all).at[bidx].add(-beta_all)
    # leaf-interior e, laid out (B, base): column base-1 unused
    e_leaf = jnp.concatenate([ep, jnp.zeros((1,), sdt)]).reshape(nblk, base)

    lam, Q = _leaf_eigh(d_adj, e_leaf, base, nblk)
    if vectors:
        Q = Q.astype(odt)        # O(n^3) matmul work runs in storage dtype

    # ---- replicated batched phase ------------------------------------
    B, nm = nblk, base
    merge_v = jax.vmap(_merge_replicated,
                       in_axes=(0, 0, 0, 0, 0, None, None, None, None))
    rows_v = jax.vmap(_merge_rows_only,
                      in_axes=(0, 0, 0, 0, 0, 0, 0, None, None, None, None))
    if not vectors:
        fr, lr = Q[:, 0, :], Q[:, -1, :]
    while B > 1 and 2 * nm <= max(repl_max, 2 * base):
        betas = ep[jnp.arange(B // 2) * 2 * nm + nm - 1]
        if vectors:
            lam, Q = merge_v(lam[0::2], lam[1::2], Q[0::2], Q[1::2], betas,
                             scale, n_iters, chunk, precision)
        else:
            lam, fr, lr = rows_v(lam[0::2], lam[1::2], fr[0::2], lr[0::2],
                                 fr[1::2], lr[1::2], betas, scale, n_iters,
                                 chunk, precision)
        B //= 2
        nm *= 2

    if not vectors:
        while B > 1:
            betas = ep[jnp.arange(B // 2) * 2 * nm + nm - 1]
            lam, fr, lr = rows_v(lam[0::2], lam[1::2], fr[0::2], lr[0::2],
                                 fr[1::2], lr[1::2], betas, scale, n_iters,
                                 chunk, precision)
            B //= 2
            nm *= 2
        return lam[0][:n].astype(odt)

    if B == 1:
        w, Z = lam[0], Q[0]
        w, Z = w[:n].astype(odt), Z[:n, :n]
        if grid is None:
            return w, Z
        Zd = redistribute(DistMatrix(Z, (n, n), STAR, STAR, 0, 0, grid),
                          MC, MR)
        return w, Zd

    # ---- distributed phase -------------------------------------------
    if grid is None:
        raise ValueError("tridiag_eig: n exceeds repl_max and no grid given")
    # assemble block-diagonal DistMatrix from the (B, nm, nm) batch
    Qb = Q

    def qfill(i, j):
        bi, ri = i // nm, i % nm
        bj, cj = j // nm, j % nm
        val = Qb[jnp.clip(bi, 0, B - 1), ri, cj]
        return jnp.where(bi == bj, val, 0.0).astype(odt)

    from ..core.distmatrix import zeros as dm_zeros
    Qd = index_dependent_fill(
        dm_zeros(npad, npad, MC, MR, grid, dtype=odt), qfill)
    lam_full = lam.reshape(-1)

    while B > 1:
        for p in range(B // 2):
            o = p * 2 * nm
            beta = ep[o + nm - 1]
            lam1 = lam_full[o:o + nm]
            lam2 = lam_full[o + nm:o + 2 * nm]
            Q1 = interior_view(Qd, (o, o + nm), (o, o + nm))
            Q2 = interior_view(Qd, (o + nm, o + 2 * nm), (o + nm, o + 2 * nm))
            z1 = redistribute(interior_view(Q1, (nm - 1, nm), (0, nm)),
                              STAR, STAR).local[0]
            z2 = redistribute(interior_view(Q2, (0, 1), (0, nm)),
                              STAR, STAR).local[0]
            D = jnp.concatenate([lam1, lam2])
            z = jnp.concatenate([z1, z2]).astype(sdt)
            lamn, perm, ds, tau, aidx, zhat, cninv, flip = _secular(
                D, z, beta, scale, n_iters, chunk)

            def vfill(i, j, _p=perm, _ds=ds, _tau=tau, _ai=aidx, _zh=zhat,
                      _cn=cninv, _fl=flip):
                return _v_entries(i, j, _p, _ds, _tau, _ai, _zh, _cn, _fl,
                                  odt)

            V = index_dependent_fill(
                dm_zeros(2 * nm, 2 * nm, MC, MR, grid, dtype=odt), vfill)
            Vtop = interior_view(V, (0, nm), (0, 2 * nm))
            Vbot = interior_view(V, (nm, 2 * nm), (0, 2 * nm))
            Ztop = gemm(Q1, Vtop, precision=_hi(precision))
            Zbot = gemm(Q2, Vbot, precision=_hi(precision))
            Qd = interior_update(Qd, Ztop, (o, o))
            Qd = interior_update(Qd, Zbot, (o + nm, o))
            lam_full = lax.dynamic_update_slice(lam_full, lamn, (o,))
        B //= 2
        nm *= 2

    w = lam_full[:n].astype(odt)
    Zd = interior_view(Qd, (0, n), (0, n))
    return w, Zd
