"""Blocked distributed Cholesky + SPD solve, look-ahead pipelined.

Reference: Elemental ``src/lapack_like/factor/Cholesky.cpp`` +
``Cholesky/LVar3.hpp`` (blocked right-looking lower variant) and
``src/lapack_like/solve/HPDSolve.cpp`` (Cholesky + two triangular sweeps)
-- BASELINE.json's headline "SPD Ax=b" config.

Per panel (the LVar3 loop, SURVEY.md §4.2):
  A11 -> [STAR,STAR]            replicated diagonal block, local potrf
  A21 -> [VC,STAR]              1-D cyclic panel, local right-Trsm by L11^H
  (L21, L21^H) spread           fused engine ``panel_spread``: [MC,STAR]
                                and the [STAR,MR] adjoint in ONE collective
  A22 -= L21 L21^H (lower tri)  one storage matmul on the MXU, masked

Look-ahead schedule (default on; the Cholesky twin of lu.py's pipeline)
-----------------------------------------------------------------------
The classic right-looking driver serializes diag -> panel -> spread ->
update every step, leaving the latency-bound replicated ``_potrf_inv`` on
the critical path ``n/nb`` times.  The pipelined driver splits step k's
trailing update at the next panel boundary:

    write back L11_k                          (from the carried factor)
    (L21, L21^H) := panel_spread(L21_vc)      (one fused collective)
    strip := A22[:, :nb] - L21 L21^H[:, :nb]  (narrow column-strip update)
    factor diag block k+1 from ``strip``      (off the critical path)
    solve panel k+1 from ``strip``            (off the critical path)
    rest := A22[:, nb:] - L21 L21^H[:, nb:]   (wide MXU update)

The strip/rest/diag operands are all captured BEFORE any writeback, so the
replicated ``_potrf_inv`` of step k+1 and the wide remainder matmul share
no data dependence and XLA is free to overlap them.  ``lookahead=False``
keeps the classic order -- bit-identical factors, the A/B baseline
(``perf/ab_harness.py cholesky``).

Tail crossover-to-local (``crossover``)
---------------------------------------
The shrinking tail pays full per-step redistribution latency on ever
smaller trailing matmuls.  Once the trailing matrix drops to ``crossover``
(default :data:`_CROSSOVER` when look-ahead is on; 0 disables), it is
gathered ONCE to [STAR,STAR] and finished with the replicated sequential
schedule (:func:`_local_chol_array`) -- O(t^3) redundant flops on every
device, but zero further collectives.  ``crossover=None`` picks the
default; pass an int to override (``perf/ab_harness.py cholesky`` sweeps
it).

Phase timing (``timer``)
------------------------
Pass a ``perf.phase_timer.PhaseTimer`` and call ``cholesky`` EAGERLY: the
driver ticks at every diag / panel / spread / update (/tail) boundary and
the timer attributes per-step wall-clock (same ``phase_timings/v1`` schema
as LU; ``python perf/ab_harness.py phases cholesky`` is the CLI).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, VC, STAR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..redist.engine import (apply_fault, redistribute, transpose_dist,
                             panel_spread)
from ..redist.quantize import check_comm_precision
from ..blas.level1 import make_trapezoidal, _global_indices
from ..blas.level3 import _blocksize, _check_mcmr, _mask_triangle, trsm
from .lu import _hi, _NULL_TIMER, _phase_hook

#: Trailing-matrix size at which the distributed loop gathers the tail and
#: finishes locally (look-ahead schedule only, unless overridden).  The
#: per-step cost floor of the distributed loop is ~3 collective rounds; at
#: t <= ~4k the whole remaining O(t^3/3) factors locally in less time than
#: the remaining t/nb rounds cost.  Re-pin via ``perf/ab_harness.py
#: cholesky`` (crossover sweep) on the target chip/grid.
_CROSSOVER = 4096


def _potrf_inv(D, precision, bs: int = 512, plan=None):
    """:func:`_potrf_inv_impl` routed through the engine's ``'compute'``
    fault seam (identity unless a FaultPlan is installed -- ISSUE 9):
    the diagonal-block factor/inverse pair IS cholesky's local panel
    math, so corrupting it here models a soft error in local compute.

    ``plan`` (a ``kernels.PanelPlan``) selects the implementation: the
    fused Pallas kernel (``kernels.potrf_inv`` -- blocked potrf +
    triangular inverse in ONE launch) when the resolved ``panel_impl``
    says so and the block passes the static VMEM/dtype gate; else the
    XLA path.  Both land on the same fault seam."""
    if plan is not None and plan.use_pallas(D.shape, D.dtype, copies=4):
        from ..kernels import potrf_inv as _pallas_potrf_inv
        return apply_fault("compute", _pallas_potrf_inv(D, precision, bs=bs))
    return apply_fault("compute", _potrf_inv_impl(D, precision, bs))


def _potrf_inv_impl(D, precision, bs: int = 512):
    """Blocked lower Cholesky of a (w, w) Hermitian block (lower triangle
    valid) returning ``(L, L^{-1})`` with all O(w^3) work as MXU matmuls.

    XLA's native ``cholesky``/``triangular_solve`` at w ~ 2048 are
    latency-bound inner loops (~20 ms / ~12 ms in-graph on v5e); restricting
    them to ``bs``-sized diagonal blocks (~0.9 ms each) and doing the panel
    solve, trailing update, and inverse assembly as matmuls keeps the whole
    diagonal-block factorization near matmul speed.  The explicit inverse is
    what turns every downstream Trsm into a matmul; for blocked factorization
    panels this is the standard GPU/TPU trade (diag-block inverse + GEMM),
    numerically benign at panel sizes since cond(L11) ~ sqrt(cond(A11))."""
    w = D.shape[0]
    dt = D.dtype
    # factor-forming matmuls run at full accumulation (see lu._hi)
    precision = _hi(precision)
    d = jnp.tril(D)
    d = d + jnp.conj(jnp.tril(d, -1)).T
    if w <= bs:
        L = jnp.linalg.cholesky(d)
        Li = lax.linalg.triangular_solve(L, jnp.eye(w, dtype=dt),
                                         left_side=True, lower=True)
        return L, Li
    L = jnp.zeros((w, w), dt)
    Li = jnp.zeros((w, w), dt)
    T = d
    for s in range(0, w, bs):
        e = min(s + bs, w)
        wb = e - s
        dkk = jnp.tril(T[:wb, :wb])
        dkk = dkk + jnp.conj(jnp.tril(dkk, -1)).T
        Lkk = jnp.linalg.cholesky(dkk)
        Likk = lax.linalg.triangular_solve(Lkk, jnp.eye(wb, dtype=dt),
                                           left_side=True, lower=True)
        L = L.at[s:e, s:e].set(Lkk)
        # inverse assembly: Li[s:e, :s] = -Likk @ L[s:e, :s] @ Li[:s, :s]
        if s > 0:
            corr = jnp.matmul(
                Likk, jnp.matmul(L[s:e, :s], Li[:s, :s], precision=precision),
                precision=precision)
            Li = Li.at[s:e, :s].set(-corr.astype(dt))
        Li = Li.at[s:e, s:e].set(Likk)
        if e < w:
            B21 = jnp.matmul(T[wb:, :wb], jnp.conj(Likk).T,
                             precision=precision).astype(dt)
            L = L.at[e:, s:e].set(B21)
            T = T[wb:, wb:] - jnp.matmul(B21, jnp.conj(B21).T,
                                         precision=precision).astype(dt)
    return L, Li


def _local_chol_array(a, n: int, ib: int, precision, lookahead: bool = True,
                      timer=None, plan=None):
    """Blocked lower Cholesky of an (n, n) array (lower triangle valid),
    returning the full lower-triangular factor array.  Shared by the p == 1
    driver and the distributed tail crossover (where it runs REPLICATED on
    the gathered trailing block -- deterministic, so every device agrees).

    Schedule (tuned on v5e at N=32768):
      * diagonal blocks factored by :func:`_potrf_inv` (small-base potrf +
        matmul inverse assembly) and the panel solve L21 = A21 L11^{-H}
        done as ONE matmul -- XLA's potrf/trsm at nb=2048 are latency-bound
        and were ~55%% of total runtime;
      * the trailing matrix SHRINKS each panel (finished columns are
        assembled once at the end) -- no aliasing/copy questions;
      * the rank-nb update touches only the LOWER triangle, via row-stripe
        blocks ``T[i:i+q, :i+q] -= L21[i:i+q] L21[:i+q]^H`` (contiguous
        row-major writes; half the FLOPs of the full product -- the MXU
        answer to the reference's recursive ``Trrk``);
      * ``lookahead=True`` additionally computes the next panel's column
        strip first and factors diag block k+1 + its panel solve from it,
        so the latency-bound ``_potrf_inv`` inner loop is data-independent
        of the wide remainder stripes and XLA may overlap them (the same
        pipeline as ``lu._local_lu``)."""
    tm = timer if timer is not None else _NULL_TIMER
    dt = a.dtype
    q = 2 * ib
    panels = []
    T = a
    nxt = None
    if lookahead:
        w0 = min(ib, n)
        L11, Li11 = _potrf_inv(T[:w0, :w0], precision, plan=plan)
        tm.tick("diag", 0, L11)
        L21 = None
        if w0 < n:
            L21 = jnp.matmul(T[w0:, :w0], jnp.conj(Li11).T,
                             precision=_hi(precision)).astype(dt)
            tm.tick("panel", 0, L21)
        nxt = (L11, Li11, L21)
    for k, s in enumerate(range(0, n, ib)):
        w = min(ib, n - s)
        if lookahead:
            L11, Li11, L21 = nxt
        else:
            L11, Li11 = _potrf_inv(T[:w, :w], precision, plan=plan)
            tm.tick("diag", k, L11)
            L21 = None
            if s + w < n:
                L21 = jnp.matmul(T[w:, :w], jnp.conj(Li11).T,
                                 precision=_hi(precision)).astype(dt)
                tm.tick("panel", k, L21)
        if s + w == n:
            panels.append(L11)
            break
        panels.append(jnp.concatenate([L11, L21], axis=0))
        T2 = T[w:, w:]
        mt = T2.shape[0]
        if not lookahead:
            for i in range(0, mt, q):
                iq = min(i + q, mt)
                upd = jnp.matmul(L21[i:iq, :], jnp.conj(L21[:iq, :]).T,
                                 precision=precision)
                T2 = T2.at[i:iq, :iq].set(T2[i:iq, :iq] - upd.astype(dt))
            T = T2
            tm.tick("update", k, T)
            continue
        # look-ahead: the next panel's column strip updates first (one tall
        # narrow matmul), diag block k+1 factors + panel k+1 solves from it;
        # the wide remainder stripes read only the pre-update T2, so the
        # replicated _potrf_inv and the MXU stripes can overlap.
        w2 = min(ib, mt)
        strip = T2[:, :w2] - jnp.matmul(L21, jnp.conj(L21[:w2, :]).T,
                                        precision=precision).astype(dt)
        L11n, Li11n = _potrf_inv(strip[:w2, :w2], precision, plan=plan)
        tm.tick("diag", k + 1, L11n)
        L21n = None
        if w2 < mt:
            L21n = jnp.matmul(strip[w2:, :], jnp.conj(Li11n).T,
                              precision=_hi(precision)).astype(dt)
            tm.tick("panel", k + 1, L21n)
        nxt = (L11n, Li11n, L21n)
        T2 = T2.at[:, :w2].set(strip)
        for i in range(w2, mt, q):
            iq = min(i + q, mt)
            upd = jnp.matmul(L21[i:iq, :], jnp.conj(L21[w2:iq, :]).T,
                             precision=precision)
            T2 = T2.at[i:iq, w2:iq].set(T2[i:iq, w2:iq] - upd.astype(dt))
        T = T2
        tm.tick("update", k, T)
    out = jnp.zeros((n, n), dt)
    s = 0
    for P in panels:
        out = lax.dynamic_update_slice(out, P, (s, s))
        s += P.shape[1]
    return out


def _local_cholesky(A: DistMatrix, nb: int | None, precision,
                    lookahead: bool = True, timer=None,
                    plan=None) -> DistMatrix:
    """Sequential (p == 1) lower path: the analog of the reference's local
    ``Matrix<T>`` dispatch onto sequential BLAS.  On a 1x1 grid the storage
    array IS the global matrix, so the whole blocked loop is one fused XLA
    program with no shard_map/redistribute sub-computation boundaries."""
    ib = max(nb or 2048, 1)
    out = _local_chol_array(A.local, A.gshape[0], ib, precision,
                            lookahead=lookahead, timer=timer, plan=plan)
    return make_trapezoidal(A.with_local(out), "L")


def cholesky(A: DistMatrix, uplo: str = "L", nb: int | str | None = None,
             precision=None, lookahead: bool | str = True,
             crossover: int | str | None = None,
             panel_impl: str | None = None,
             comm_precision: str | None = None,
             redist_path: str | None = None, timer=None,
             health=None, abft=None) -> DistMatrix:
    """Cholesky factor of an HPD [MC,MR] matrix; reads only the ``uplo``
    triangle.  Returns L (A = L L^H) for 'L', U (A = U^H U) for 'U'.

    ``lookahead`` selects the pipelined schedule (module docstring; ``False``
    restores the classic right-looking order, bit-identical factors);
    ``crossover`` is the trailing-matrix size at which the distributed loop
    gathers the tail once and finishes locally (``None`` = :data:`_CROSSOVER`
    with look-ahead, disabled classic; 0 never crosses over); ``timer``
    enables eager per-phase wall-clock attribution (``perf/phase_timer.py``).

    ``panel_impl`` (``None`` | ``'xla'`` | ``'pallas'`` | ``'auto'``)
    selects the diagonal-block factor/inverse IMPLEMENTATION: ``'pallas'``
    runs :func:`_potrf_inv` as ONE fused VMEM-resident kernel
    (``kernels.potrf_inv``: blocked potrf + triangular inverse in a
    single launch; ``interpret=True`` off-TPU), ``None``/``'xla'`` keep
    the blocked XLA path.  Residual-bounded twin (same math, different
    scalar-recurrence rounding -- pinned by ``tests/kernels``); complex
    dtypes and oversize blocks fall back to XLA silently.  The schedule
    and every collective are IDENTICAL under either value (comm-plan
    goldens byte-pinned by ``tools/check.sh kernels``).

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'``) selects the
    WIRE precision of the schedule's redistributions -- the diagonal-block
    gathers, the [VC,STAR] panel moves, the fused ``panel_spread`` and
    the crossover tail gather all encode narrow, move 2-4x fewer bytes
    at identical round counts, and decode back before any local math
    (see ``redist.quantize``).  Opt-in: ``None`` (default) is
    bit-identical; quantized wire raises the factor residual to the
    ~1e-2..1e-3 relative level -- pair with
    ``resilience.certified_solve('hpd', ...)`` for certified answers.

    ``redist_path`` (``None`` | ``'chain'`` | ``'direct'`` | ``'auto'``)
    selects the redistribution ROUTE of the same sites: ``'direct'``
    compiles each dist change into a one-shot collective plan
    (``redist.plan``), ``'auto'`` arbitrates per move via the engine's
    chain-vs-plan cost mirror, and ``None``/``'chain'`` keep the factored
    multi-hop chain (bit-identical baseline).  Both routes move the same
    values, so the factor is unchanged up to collective reduction order.

    Any of ``nb`` / ``lookahead`` / ``crossover`` / ``comm_precision`` /
    ``redist_path`` may be ``'auto'``: the tuning subsystem resolves them
    per (shape, dtype, grid, backend) -- measured-cache winner first,
    analytic cost model cold (explicit values always win; see
    ``elemental_tpu/tune``).

    ``health`` opts into the resilience guards (NaN/Inf scans, growth
    estimate, non-positive/near-zero diagonal detection on the ``diag``
    ticks): a ``HealthMonitor`` or ``True``, same semantics as
    ``lu(..., health=...)``; ``None`` (default) attaches nothing.

    ``abft`` opts into checksum-guarded execution with panel-granular
    recovery (same semantics as ``lu(..., abft=...)``; ISSUE 11): the
    guarded path verifies column-sum invariants per panel and on
    violation re-executes only that panel step.  It forces the classic
    right-looking schedule (``lookahead`` / ``crossover`` ignored);
    ``abft=None`` (default) is the unguarded path, bit-identical to
    before.
    """
    _check_mcmr(A)
    if any(isinstance(v, str) for v in (nb, lookahead, crossover)) \
            or comm_precision == "auto" or redist_path == "auto" \
            or panel_impl == "auto":
        from ..tune.policy import resolve_knobs
        kn = resolve_knobs("cholesky", gshape=A.gshape, dtype=A.dtype,
                           grid=A.grid, knobs={"nb": nb, "lookahead": lookahead,
                                               "crossover": crossover,
                                               "panel_impl": panel_impl,
                                               "comm_precision": comm_precision,
                                               "redist_path": redist_path})
        nb, lookahead, crossover = kn["nb"], kn["lookahead"], kn["crossover"]
        comm_precision = kn["comm_precision"]
        redist_path = kn["redist_path"]
        panel_impl = kn["panel_impl"]
    check_comm_precision(comm_precision)
    rp = redist_path
    from ..kernels import resolve_panel
    plan = resolve_panel(panel_impl, dtype=A.dtype)
    if uplo.upper().startswith("U"):
        # U = (lower factor of A^H-as-lower)^H; A hermitian so the data of
        # the upper triangle, conj-transposed, is the lower triangle.
        Alow = redistribute(transpose_dist(A, conj=True), MC, MR)
        L = cholesky(Alow, "L", nb=nb, precision=precision,
                     lookahead=lookahead, crossover=crossover,
                     panel_impl=panel_impl,
                     comm_precision=comm_precision, redist_path=redist_path,
                     timer=timer, health=health, abft=abft)
        return redistribute(transpose_dist(L, conj=True), MC, MR)
    if abft:
        from ..resilience.abft import abft_cholesky
        return abft_cholesky(A, nb=nb, precision=precision,
                             comm_precision=comm_precision, timer=timer,
                             health=health, abft=abft, plan=plan)

    m = A.gshape[0]
    if A.gshape != (m, m):
        raise ValueError(f"cholesky needs square, got {A.gshape}")
    g = A.grid
    tm = _phase_hook("cholesky", timer)
    hm = None
    if health:
        from ..resilience.health import attach_health
        tm, hm = attach_health("cholesky", health, tm, scale_from=A)
    tm.start()
    if g.size == 1:
        out = _local_cholesky(A, nb, precision, lookahead, tm, plan)
        if hm is not None:
            hm.report()
        return out
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), m)
    xover = (_CROSSOVER if lookahead else 0) if crossover is None \
        else max(int(crossover), 0)
    L = A
    if lookahead:
        # prologue: factor diag block 0 + solve panel 0 from the input
        e0 = min(ib, m)
        A11 = redistribute(view(L, rows=(0, e0), cols=(0, e0)), STAR, STAR,
                           comm_precision=comm_precision, path=rp)
        L11, Li11 = _potrf_inv(A11.local, precision, plan=plan)
        tm.tick("diag", 0, L11)
        L21_vc = None
        if e0 < m:
            A21_vc = redistribute(view(L, rows=(e0, m), cols=(0, e0)),
                                  VC, STAR, comm_precision=comm_precision,
                                  path=rp)
            x21 = jnp.matmul(A21_vc.local, jnp.conj(Li11).T,
                             precision=_hi(precision)).astype(L.dtype)
            L21_vc = DistMatrix(x21, (m - e0, e0), VC, STAR, 0, 0, g)
            tm.tick("panel", 0, L21_vc)
        nxt = (L11, Li11, L21_vc)
    for k, s in enumerate(range(0, m, ib)):
        e = min(s + ib, m)
        if lookahead:
            L11, Li11, L21_vc = nxt
        else:
            A11 = redistribute(view(L, rows=(s, e), cols=(s, e)),
                               STAR, STAR, comm_precision=comm_precision,
                               path=rp)
            # replicated diagonal-block factor + inverse: every device runs
            # the same deterministic _potrf_inv, so the panel Trsm below is
            # a matmul
            L11, Li11 = _potrf_inv(A11.local, precision, plan=plan)
            tm.tick("diag", k, L11)
        L11_ss = DistMatrix(L11, (e - s, e - s), STAR, STAR, 0, 0, g)
        L = update_view(L, redistribute(L11_ss, MC, MR), rows=(s, e), cols=(s, e))
        if e == m:
            break
        if not lookahead:
            A21_vc = redistribute(view(L, rows=(e, m), cols=(s, e)),
                                  VC, STAR, comm_precision=comm_precision,
                                  path=rp)
            x21 = jnp.matmul(A21_vc.local, jnp.conj(Li11).T,
                             precision=_hi(precision)).astype(L.dtype)  # A21 L11^{-H}
            L21_vc = DistMatrix(x21, (m - e, e - s), VC, STAR, 0, 0, g)
            tm.tick("panel", k, L21_vc)
        L21_mc, L21H_mr = panel_spread(L21_vc, conj=True,
                                       comm_precision=comm_precision)
        tm.tick("spread", k, L21_mc, L21H_mr)
        tail = bool(xover) and m - e <= xover
        if not lookahead:
            A22 = view(L, rows=(e, m), cols=(e, m))
            upd = jnp.matmul(L21_mc.local, L21H_mr.local, precision=precision)
            mask = _mask_triangle(A22, "L")
            A22new = jnp.where(mask, A22.local - upd.astype(L.dtype), A22.local)
            L = update_view(L, A22.with_local(A22new), rows=(e, m), cols=(e, m))
            L = update_view(L, redistribute(L21_mc, MC, MR), rows=(e, m), cols=(s, e))
            tm.tick("update", k, L)
        else:
            # (a) narrow strip update: the next panel's columns of A22
            e2 = min(e + ib, m)
            A22a = view(L, rows=(e, m), cols=(e, e2))
            L21H_a = view(L21H_mr, cols=(0, e2 - e))
            maskA = _mask_triangle(A22a, "L")
            stripD = A22a.with_local(jnp.where(
                maskA,
                A22a.local - jnp.matmul(L21_mc.local, L21H_a.local,
                                        precision=precision).astype(L.dtype),
                A22a.local))
            if not tail:
                # factor diag block k+1 + solve panel k+1 from the strip,
                # off the critical path of the wide remainder update
                A11n = redistribute(view(stripD, rows=(0, e2 - e),
                                         cols=(0, e2 - e)), STAR, STAR,
                                    comm_precision=comm_precision, path=rp)
                L11n, Li11n = _potrf_inv(A11n.local, precision, plan=plan)
                tm.tick("diag", k + 1, L11n)
                L21n_vc = None
                if e2 < m:
                    A21n = redistribute(view(stripD, rows=(e2 - e, m - e),
                                             cols=(0, e2 - e)), VC, STAR,
                                        comm_precision=comm_precision,
                                        path=rp)
                    x21n = jnp.matmul(A21n.local, jnp.conj(Li11n).T,
                                      precision=_hi(precision)).astype(L.dtype)
                    L21n_vc = DistMatrix(x21n, (m - e2, e2 - e), VC, STAR,
                                         0, 0, g)
                    tm.tick("panel", k + 1, L21n_vc)
                nxt = (L11n, Li11n, L21n_vc)
            # (b) wide remainder update; operands captured pre-writeback so
            # it is data-independent of the step-k+1 factorization above
            restD = None
            if e2 < m:
                A22b = view(L, rows=(e, m), cols=(e2, m))
                L21H_b = view(L21H_mr, cols=(e2 - e, m - e))
                I, J = _global_indices(A22b)
                maskB = (J[None, :] + (e2 - e)) <= I[:, None]
                restD = A22b.with_local(jnp.where(
                    maskB,
                    A22b.local - jnp.matmul(L21_mc.local, L21H_b.local,
                                            precision=precision).astype(L.dtype),
                    A22b.local))
            L = update_view(L, redistribute(L21_mc, MC, MR), rows=(e, m), cols=(s, e))
            L = update_view(L, stripD, rows=(e, m), cols=(e, e2))
            if restD is not None:
                L = update_view(L, restD, rows=(e, m), cols=(e2, m))
            tm.tick("update", k, L)
        if tail:
            # crossover-to-local: one gather of the (fully updated) trailing
            # block, replicated sequential finish, one scatter back -- the
            # remaining t/nb steps of per-step collective latency collapse
            # into a single round trip
            Atail = redistribute(view(L, rows=(e, m), cols=(e, m)),
                                 STAR, STAR,
                                 comm_precision=comm_precision, path=rp)
            lt = _local_chol_array(Atail.local, m - e, ib, precision,
                                   lookahead=lookahead, plan=plan)
            Lt_ss = DistMatrix(lt, (m - e, m - e), STAR, STAR, 0, 0, g)
            L = update_view(L, redistribute(Lt_ss, MC, MR),
                            rows=(e, m), cols=(e, m))
            tm.tick("tail", k, L)
            break
    if hm is not None:
        hm.report()
    return make_trapezoidal(L, "L")


def hpd_solve(A: DistMatrix, B: DistMatrix, uplo: str = "L",
              nb: int | None = None, precision=None, info: bool = False,
              health=None):
    """Solve A X = B for HPD A: Cholesky + forward/backward sweeps
    (``El::HPDSolve``, ``src/lapack_like/solve/HPDSolve.cpp``).

    ``info=True`` returns ``(X, info)`` with the structured singularity
    signal ``{"singular", "diag_index", "finite"}`` from the factor's
    diagonal (a singular / non-PD A surfaces as a non-finite or
    non-positive diagonal entry instead of a silently NaN X; eager-mode
    only); ``health`` forwards to :func:`cholesky`.  For the
    residual-certified path use
    ``elemental_tpu.resilience.certified_solve('hpd', A, B)``."""
    uplo = "U" if uplo.upper().startswith("U") else "L"
    F = cholesky(A, uplo, nb=nb, precision=precision, health=health)
    X = cholesky_solve_after(F, B, uplo, nb=nb, precision=precision)
    if not info:
        return X
    from ..resilience.health import factor_diag_info
    return X, factor_diag_info("hpd", F)


def cholesky_solve_after(L: DistMatrix, B: DistMatrix, uplo: str = "L",
                         nb: int | None = None, precision=None) -> DistMatrix:
    """Re-use an existing factor (``cholesky::SolveAfter``)."""
    if uplo.upper().startswith("U"):
        Y = trsm("L", "U", "C", L, B, nb=nb, precision=precision)
        return trsm("L", "U", "N", L, Y, nb=nb, precision=precision)
    Y = trsm("L", "L", "N", L, B, nb=nb, precision=precision)
    return trsm("L", "L", "C", L, Y, nb=nb, precision=precision)


def cholesky_pivoted(A: DistMatrix, tol: float = 0.0, precision=None):
    """Full (diagonal) pivoted Cholesky of a PSD matrix:
    ``P A P^T = L L^H`` with the pivot chosen as the largest remaining
    diagonal each step (LAPACK ``pstrf`` / ``cholesky::PivotedLVar3``,
    Elemental ``src/lapack_like/factor/Cholesky/PivotedLVar3.hpp``).

    Returns ``(L, perm, rank)``: L lower-triangular [MC,MR], ``perm`` the
    traced permutation (``(P A P^T)[i, j] = A[perm[i], perm[j]]``), and
    the detected numerical rank (columns whose pivot fell below
    ``tol * max_diag`` are zeroed).

    The factorization runs REPLICATED on the gathered matrix (one jitted
    fori_loop; the reference's pivoted variant is likewise its slow
    path -- per-column pivot search serializes everything) and scatters
    the factor back; use the unpivoted :func:`cholesky` for speed on
    definite matrices.
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"cholesky_pivoted needs square, got {A.gshape}")
    g = A.grid
    Ag = redistribute(A, STAR, STAR).local
    a = jnp.tril(Ag)
    a = a + jnp.conj(jnp.tril(a, -1)).T
    rdt = jnp.real(a).dtype
    # rank threshold anchored on A's ORIGINAL diagonal scale (pstrf
    # semantics); the working diagonal mixes in L's sqrt-scaled entries
    thresh = jnp.asarray(tol, rdt) * jnp.maximum(
        jnp.max(jnp.real(jnp.diagonal(a))), jnp.asarray(1e-30, rdt))

    def body(j, state):
        a, perm, rank = state
        d = jnp.real(jnp.diagonal(a))
        idx = jnp.arange(n)
        cand = jnp.where(idx >= j, d, -jnp.inf)
        p = jnp.argmax(cand)
        # symmetric swap rows/cols j <-> p
        rj, rp = a[j], a[p]
        a = a.at[j].set(rp).at[p].set(rj)
        cj, cp = a[:, j], a[:, p]
        a = a.at[:, j].set(cp).at[:, p].set(cj)
        perm = perm.at[j].set(perm[p]).at[p].set(perm[j])
        piv = jnp.real(a[j, j])
        ok = piv > thresh
        sq = jnp.sqrt(jnp.where(ok, piv, 1.0))
        col = jnp.where(idx > j, a[:, j] / sq, 0).at[j].set(sq)
        col = jnp.where(ok, col, 0)
        # trailing update: a[j+1:, j+1:] -= col col^H (lower part suffices)
        mask = (idx[:, None] > j) & (idx[None, :] > j)
        a = jnp.where(mask, a - jnp.outer(col, jnp.conj(col)), a)
        a = a.at[:, j].set(col)
        rank = rank + jnp.where(ok, 1, 0)
        return a, perm, rank

    a, perm, rank = lax.fori_loop(0, n, body, (a, jnp.arange(n), 0))
    L = jnp.tril(a)
    Ld = redistribute(DistMatrix(L.astype(A.dtype), (n, n), STAR, STAR,
                                 0, 0, g), MC, MR)
    return Ld, perm, rank


def cholesky_mod(L: DistMatrix, V: DistMatrix, alpha: float = 1.0,
                 precision=None):
    """Rank-k Cholesky modification (``El::CholeskyMod``,
    ``Cholesky/{LMod,UMod}.hpp``): given lower L with A = L L^H, return
    the factor of ``A + alpha V V^H`` in O(n^2 k) via the classic
    column-recurrence (one hyperbolic/Givens sweep per update vector).

    ``alpha < 0`` is a DOWNDATE and requires the result to stay positive
    definite (the sweep's r^2 staying positive); like the pivoted
    variants, the sweep runs replicated on the gathered factor (it is a
    latency-bound sequential recurrence -- the reference's is too) and
    scatters back."""
    _check_mcmr(L, V)
    if jnp.issubdtype(L.dtype, jnp.complexfloating):
        raise NotImplementedError("cholesky_mod supports real factors")
    n = L.gshape[0]
    if V.gshape[0] != n:
        raise ValueError(f"V rows {V.gshape[0]} != n {n}")
    k = V.gshape[1]
    g = L.grid
    a = jnp.tril(redistribute(L, STAR, STAR).local)
    W = redistribute(V, STAR, STAR).local.astype(a.dtype)
    sign = 1.0 if alpha >= 0 else -1.0
    scal = math.sqrt(abs(alpha))
    idx = jnp.arange(n)

    def one_vector(a, w):
        def body(j, state):
            a, w = state
            ljj = a[j, j]
            wj = w[j]
            # an indefinite downdate makes r2 negative: sqrt -> NaN, which
            # poisons the factor and is caught by the host check below
            r = jnp.sqrt(ljj * ljj + sign * wj * wj)
            c = r / ljj
            s = wj / ljj
            col = a[:, j]
            newcol = (col + sign * s * w) / c
            newcol = jnp.where(idx > j, newcol, col).at[j].set(r)
            wnew = jnp.where(idx > j, c * w - s * newcol, w)
            return a.at[:, j].set(newcol), wnew

        a, _ = lax.fori_loop(0, n, body, (a, w))
        return a

    for t in range(k):
        a = one_vector(a, scal * W[:, t])
    import numpy as _np
    if not bool(_np.isfinite(_np.asarray(jnp.diagonal(a))).all()):
        raise ValueError("cholesky_mod: downdate leaves the matrix "
                         "indefinite (El::CholeskyMod throws here too)")
    out = redistribute(DistMatrix(jnp.tril(a), (n, n), STAR, STAR, 0, 0, g),
                       MC, MR)
    return out
