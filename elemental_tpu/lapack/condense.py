"""Condense layer: reduction to tridiagonal (and Hessenberg) form.

Reference: Elemental ``src/lapack_like/condense/HermitianTridiag/**``
(``El::HermitianTridiag``; blocked panels building a distributed-Hemv W
panel, then a Her2k-style two-sided trailing update -- SURVEY.md §4.5) and
``condense/Hessenberg/**`` (``El::Hessenberg``).

TPU-first design: the reduction panel loop is ONE jitted ``lax.fori_loop``
per panel (LAPACK ``latrd`` semantics).  Per column the only distributed
work is a single :func:`~elemental_tpu.blas.level2.hemv` against the fixed
trailing view (the reference's distributed Hemv with [MC,STAR]/[MR,STAR]
accumulators); the V/W panels live replicated (n x nb -- small).  The
trailing update ``A22 -= V W^H + W V^H`` is one masked storage matmul on
the MXU (exactly the reference's rank-2k update), so all O(n^3/MXU-friendly)
FLOPs are large matmuls and all latency-bound work is batched into one
compiled loop.

Packing (lower): reflector j has an implicit 1 at row j+1; its tail lives in
``Ap[j+2:, j]``; ``d``/``e`` (real) are returned separately, and also
written to the diagonal/subdiagonal of ``Ap``.  ``uplo`` selects which
triangle of the Hermitian input is READ; the packing is always lower (a
documented deviation from LAPACK's dual packing -- A is Hermitian, so both
read paths factor the same matrix).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view, round_up
from ..redist.engine import redistribute, transpose_dist
from ..blas.level2 import gemv, hemv
from ..blas.level1 import _global_indices
from ..blas.level3 import _blocksize, _check_mcmr, _mask_triangle
from .lu import _update_cols_lt, _hi
from .qr import _larft


def _real_dtype(dtype):
    return jnp.zeros((), dtype).real.dtype


def _wrap_vec(v, grid) -> DistMatrix:
    """Replicated (nt,) vector -> zero-aligned (nt, 1) [MC,MR] DistMatrix."""
    ss = DistMatrix(v[:, None], (v.shape[0], 1), STAR, STAR, 0, 0, grid)
    return redistribute(ss, MC, MR)


def _unwrap_vec(x: DistMatrix):
    return redistribute(x, STAR, STAR).local[:, 0]


def _larfg_at(col, piv, ridx, dtype):
    """Householder reflector pivoting at row ``piv`` (zeroes rows > piv):
    real beta, H = I - tau v v^H, implicit v[piv] = 1."""
    alpha = col[piv]
    tail2 = jnp.where(ridx > piv, col, 0)
    sigma = jnp.sum(jnp.abs(tail2) ** 2)
    anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
    re_a = jnp.real(alpha)
    beta = -jnp.sign(jnp.where(re_a == 0, 1.0, re_a)) * anorm
    degenerate = anorm == 0
    safe_beta = jnp.where(degenerate, 1.0, beta)
    tau = jnp.where(degenerate, 0.0, (safe_beta - alpha) / safe_beta)
    denom = alpha - safe_beta
    safe_denom = jnp.where(denom == 0, 1.0, denom)
    v = jnp.where(ridx > piv, col / safe_denom, 0)
    v = jnp.where(ridx == piv, jnp.ones((), dtype), v)
    return v.astype(dtype), jnp.asarray(tau, dtype), beta


def _larfg_tail(col, jj, ridx, dtype):
    """Householder reflector zeroing rows > jj+1 of ``col`` (LAPACK larfg:
    real beta, H = I - tau v v^H with implicit v[jj+1] = 1)."""
    return _larfg_at(col, jj + 1, ridx, dtype)


@partial(jax.jit, static_argnums=(2, 3, 4))
def _tridiag_panel(Atrail: DistMatrix, P, nbw: int, extract_last: bool,
                   precision):
    """latrd: reduce ``nbw`` columns of the trailing matrix.

    ``Atrail`` is the fixed (nt, nt) [MC,MR] trailing view; ``P`` the
    replicated panel columns.  Returns (V, W, d, e, tau) with V/W the
    (nt, nbw) replicated reflector/update panels.
    """
    nt = Atrail.gshape[0]
    g = Atrail.grid
    dtype = P.dtype
    rdtype = _real_dtype(dtype)
    ridx = jnp.arange(nt)
    nd = nbw + 1 if extract_last else nbw

    def corrected_col(P, V, W, jj):
        return P[:, jj] - V @ jnp.conj(W[jj, :]) - W @ jnp.conj(V[jj, :])

    def body(jj, carry):
        V, W, d, e, tau = carry
        col = corrected_col(P, V, W, jj)
        d = d.at[jj].set(jnp.real(col[jj]).astype(rdtype))
        v, tau_j, beta = _larfg_tail(col, jj, ridx, dtype)
        e = e.at[jj].set(beta.astype(rdtype))
        # the one distributed op per column: u = A_trail v (Hemv; v's leading
        # zeros make this the reference's A22*v on the true subproblem)
        u = _unwrap_vec(hemv("L", Atrail, _wrap_vec(v, g), precision=_hi(precision)))
        u = u - V @ (jnp.conj(W).T @ v) - W @ (jnp.conj(V).T @ v)
        w = tau_j * u
        w = jnp.where(ridx > jj, w, 0)
        w = w - (0.5 * tau_j * (jnp.conj(w) @ v)) * v
        V = V.at[:, jj].set(v)
        W = W.at[:, jj].set(w.astype(dtype))
        tau = tau.at[jj].set(tau_j)
        return V, W, d, e, tau

    init = (jnp.zeros((nt, nbw), dtype), jnp.zeros((nt, nbw), dtype),
            jnp.zeros((nd,), rdtype), jnp.zeros((nbw,), rdtype),
            jnp.zeros((nbw,), dtype))
    V, W, d, e, tau = lax.fori_loop(0, nbw, body, init)
    if extract_last:
        col = corrected_col(P, V, W, nbw)
        d = d.at[nbw].set(jnp.real(col[nbw]).astype(rdtype))
    return V, W, d, e, tau


def _packed_panel(V, d, e, nbw: int, dtype):
    """Assemble the packed panel: diag d, subdiag e, reflector tails below."""
    nt = V.shape[0]
    ridx = jnp.arange(nt)[:, None]
    cidx = jnp.arange(nbw)[None, :]
    packed = jnp.where(ridx >= cidx + 2, V[:, :nbw], 0)
    packed = jnp.where(ridx == cidx, d[:nbw].astype(dtype), packed)
    packed = jnp.where(ridx == cidx + 1, e[:nbw].astype(dtype), packed)
    return packed


def hermitian_tridiag(A: DistMatrix, uplo: str = "L", nb: int | None = None,
                      precision=None):
    """Reduce a Hermitian [MC,MR] matrix to real tridiagonal form.

    Returns ``(Ap, d, e, tau)``: ``A = Q T Q^H`` with ``T = tridiag(e, d, e)``
    and ``Q = H_0 H_1 ... H_{n-2}`` packed in ``Ap``'s lower triangle
    (``El::HermitianTridiag``).
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"hermitian_tridiag needs square, got {A.gshape}")
    if uplo.upper().startswith("U"):
        A = redistribute(transpose_dist(A, conj=True), MC, MR)
    g = A.grid
    r, c = g.height, g.width
    dtype = A.dtype
    rdtype = _real_dtype(dtype)
    if n == 0:
        z = jnp.zeros((0,), rdtype)
        return A, z, z, jnp.zeros((0,), dtype)
    if n == 1:
        dd = jnp.real(redistribute(A, STAR, STAR).local[0, 0])[None]
        return A, dd.astype(rdtype), jnp.zeros((0,), rdtype), jnp.zeros((0,), dtype)

    ib = _blocksize(nb, math.lcm(r, c), n)
    kend = n - 1                          # reflector columns 0 .. n-2
    Ap = A
    d_parts, e_parts, tau_parts = [], [], []
    s = 0
    while s < kend:
        e_col = min(s + ib, kend)
        nbw = e_col - s
        final = e_col == kend
        wp_end = n if final else min(round_up(e_col, c), n)
        Atrail = view(Ap, rows=(s, n), cols=(s, n))
        P = redistribute(view(Ap, rows=(s, n), cols=(s, wp_end)), STAR, STAR).local
        V, W, dpan, epan, taupan = _tridiag_panel(Atrail, P, nbw, final, precision)
        d_parts.append(dpan)
        e_parts.append(epan)
        tau_parts.append(taupan)
        packed = _packed_panel(V, dpan, epan, nbw, dtype)
        if final:
            # last column: its diagonal entry
            nt = n - s
            last = jnp.zeros((nt, 1), dtype).at[nt - 1, 0].set(
                dpan[nbw].astype(dtype))
            packed = jnp.concatenate([packed, last], axis=1)
            blk = DistMatrix(packed, (nt, nt), STAR, STAR, 0, 0, g)
            Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, n), (s, n), n)
            break
        wpad = wp_end - s - nbw
        if wpad:
            packed = jnp.pad(packed, ((0, 0), (0, wpad)))
        blk = DistMatrix(packed, (n - s, wp_end - s), STAR, STAR, 0, 0, g)
        Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, n), (s, wp_end), e_col)
        # trailing two-sided update: A22 -= V2 W2^H + W2 V2^H (lower triangle)
        nt2 = n - e_col
        V2 = V[e_col - s:, :]
        W2 = W[e_col - s:, :]
        V2mc = redistribute(DistMatrix(V2, (nt2, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        W2mc = redistribute(DistMatrix(W2, (nt2, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        V2Hmr = redistribute(
            DistMatrix(jnp.conj(V2).T, (nbw, nt2), STAR, STAR, 0, 0, g), STAR, MR)
        W2Hmr = redistribute(
            DistMatrix(jnp.conj(W2).T, (nbw, nt2), STAR, STAR, 0, 0, g), STAR, MR)
        A22 = view(Ap, rows=(e_col, n), cols=(e_col, n))
        upd = (jnp.matmul(V2mc.local, W2Hmr.local, precision=_hi(precision))
               + jnp.matmul(W2mc.local, V2Hmr.local, precision=_hi(precision)))
        mask = _mask_triangle(A22, "L")
        newloc = jnp.where(mask, A22.local - upd.astype(dtype), A22.local)
        Ap = update_view(Ap, A22.with_local(newloc), rows=(e_col, n), cols=(e_col, n))
        s = e_col
    d = jnp.concatenate(d_parts)
    e_ = jnp.concatenate(e_parts)
    tau = jnp.concatenate(tau_parts)
    return Ap, d, e_, tau


def _tridiag_v_panel(P, nbw: int):
    """Unit-structured reflector panel from tridiag packing: V[jj+1,jj]=1,
    tails from rows >= jj+2."""
    nt = P.shape[0]
    ridx = jnp.arange(nt)[:, None]
    cidx = jnp.arange(nbw)[None, :]
    V = jnp.where(ridx >= cidx + 2, P[:, :nbw], 0)
    return V + jnp.eye(nt, nbw, k=-1, dtype=P.dtype)


def apply_q_herm_tridiag(Ap: DistMatrix, tau, B: DistMatrix,
                         orient: str = "N", nb: int | None = None,
                         precision=None) -> DistMatrix:
    """B := Q B ('N') or Q^H B ('C') with Q from :func:`hermitian_tridiag`
    (the back-transform of ``El::HermitianEig``, ``herm_eig::`` +
    ``ApplyPackedReflectors``).  ``nb`` must match the factorization's."""
    _check_mcmr(Ap, B)
    n = Ap.gshape[0]
    if B.gshape[0] != n:
        raise ValueError(f"B height {B.gshape[0]} != {n}")
    g = Ap.grid
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), n)
    kend = n - 1
    starts = list(range(0, kend, ib))
    if orient == "N":
        starts = starts[::-1]
    for s in starts:
        e_col = min(s + ib, kend)
        nbw = e_col - s
        wp_end = n if e_col == kend else min(round_up(e_col, c), n)
        P = redistribute(view(Ap, rows=(s, n), cols=(s, wp_end)), STAR, STAR).local
        V = _tridiag_v_panel(P, nbw)
        T = _larft(V, tau[s:e_col])
        Tm = jnp.conj(T).T if orient == "C" else T
        V_mc = redistribute(
            DistMatrix(V, (n - s, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        B2 = view(B, rows=(s, n))
        Wl = jnp.matmul(jnp.conj(V_mc.local).T, B2.local, precision=_hi(precision))
        Wl = jnp.matmul(Tm, Wl, precision=_hi(precision))
        upd = jnp.matmul(V_mc.local, Wl, precision=_hi(precision))
        B = update_view(B, B2.with_local(B2.local - upd.astype(B.dtype)),
                        rows=(s, n))
    return B


# ---------------------------------------------------------------------
# Bidiagonal reduction (the SVD condense step)
# ---------------------------------------------------------------------

@partial(jax.jit, static_argnums=(3, 4))
def _bidiag_panel(Atrail: DistMatrix, Pc, Pr, nbw: int, precision):
    """labrd: reduce ``nbw`` columns AND rows of the (mt, nt) trailing view.

    ``Pc``/``Pr``: replicated panel columns (mt, nbw) / rows (nbw, nt) at
    panel start.  The running matrix is ``A0 - U Y^H - X V^H``; per column
    the two distributed ops are one ``gemv^H`` (building Y) and one ``gemv``
    (building X) against the FIXED trailing view -- the reference's
    ``bidiag::PanelBidiag`` distributed products."""
    mt, nt = Atrail.gshape
    g = Atrail.grid
    dtype = Pc.dtype
    rdtype = _real_dtype(dtype)
    ridx = jnp.arange(mt)
    cidx = jnp.arange(nt)

    def body(j, carry):
        U, Y, V, X, d, e, tauq, taup = carry
        # current column j
        col = Pc[:, j] - U @ jnp.conj(Y[j, :]) - X @ jnp.conj(V[j, :])
        u, tq, beta = _larfg_at(col, j, ridx, dtype)
        d = d.at[j].set(beta.astype(rdtype))
        # zlarfg: H^H x = beta e, so the left update A <- H^H A is
        # A - u y^H with y = tq * A_cur^H u
        base = _unwrap_vec(gemv(Atrail, _wrap_vec(u, g), orient="C",
                                precision=_hi(precision)))
        y = base - Y @ (jnp.conj(U).T @ u) - V @ (jnp.conj(X).T @ u)
        y = (tq * y).astype(dtype)
        U = U.at[:, j].set(u)
        Y = Y.at[:, j].set(y)
        tauq = tauq.at[j].set(tq)
        # current row j (after the left update): right reflector at col j+1
        row = Pr[j, :] - U[j, :] @ jnp.conj(Y).T - X[j, :] @ jnp.conj(V).T
        do_right = j + 1 < nt
        rbar = jnp.conj(row)
        v, tp, betar = _larfg_at(rbar, jnp.minimum(j + 1, nt - 1), cidx, dtype)
        v = jnp.where(do_right, v, jnp.zeros_like(v))
        tp = jnp.where(do_right, tp, 0)
        e = e.at[j].set(jnp.where(do_right, betar, 0).astype(rdtype))
        # right update A <- A G with G = I - tp v v^H: x = tp * A_cur v
        basex = _unwrap_vec(gemv(Atrail, _wrap_vec(v, g), orient="N",
                                 precision=_hi(precision)))
        x = basex - U @ (jnp.conj(Y).T @ v) - X @ (jnp.conj(V).T @ v)
        x = (tp * x).astype(dtype)
        V = V.at[:, j].set(v)
        X = X.at[:, j].set(x)
        taup = taup.at[j].set(tp)
        return U, Y, V, X, d, e, tauq, taup

    init = (jnp.zeros((mt, nbw), dtype), jnp.zeros((nt, nbw), dtype),
            jnp.zeros((nt, nbw), dtype), jnp.zeros((mt, nbw), dtype),
            jnp.zeros((nbw,), rdtype), jnp.zeros((nbw,), rdtype),
            jnp.zeros((nbw,), dtype), jnp.zeros((nbw,), dtype))
    return lax.fori_loop(0, nbw, body, init)


def bidiag(A: DistMatrix, nb: int | None = None, precision=None):
    """Reduce a tall/square [MC,MR] matrix (m >= n) to upper bidiagonal
    form ``A = Q B P^H`` (``El::Bidiag``, ``src/lapack_like/condense/
    Bidiag/**``).

    Returns ``(Ap, d, e, tauq, taup)``: ``d`` the diagonal, ``e`` the
    superdiagonal (length n-1); left reflectors packed below the diagonal
    of ``Ap`` (unit at row j -- geqrf layout, so :func:`.qr.apply_q`
    applies Q); right reflector j's tail stored in ROW j at columns
    >= j+2 (unit at column j+1), applied by :func:`apply_p_bidiag`."""
    _check_mcmr(A)
    m, n = A.gshape
    if m < n:
        raise ValueError("bidiag requires m >= n (transpose the input)")
    g = A.grid
    r, c = g.height, g.width
    dtype = A.dtype
    rdtype = _real_dtype(dtype)
    if n == 0:
        z = jnp.zeros((0,), rdtype)
        return A, z, z, jnp.zeros((0,), dtype), jnp.zeros((0,), dtype)
    grain = math.lcm(r, c)
    ib = _blocksize(nb, grain, n)
    Ap = A
    d_parts, e_parts, tq_parts, tp_parts = [], [], [], []
    for s in range(0, n, ib):
        e_col = min(s + ib, n)
        nbw = e_col - s
        Atrail = view(Ap, rows=(s, m), cols=(s, n))
        ce_up = min(round_up(e_col, c), n)
        re_up = min(round_up(e_col, r), m)
        Pc = redistribute(view(Ap, rows=(s, m), cols=(s, ce_up)),
                          STAR, STAR).local[:, :nbw]
        Pr = redistribute(view(Ap, rows=(s, re_up), cols=(s, n)),
                          STAR, STAR).local[:nbw, :]
        U, Y, V, X, dpan, epan, tq, tp = _bidiag_panel(Atrail, Pc, Pr, nbw,
                                                       precision)
        d_parts.append(dpan)
        e_parts.append(epan)
        tq_parts.append(tq)
        tp_parts.append(tp)
        # packed panel columns: u tails below diag, d on diag, e on superdiag
        mt, nt = m - s, n - s
        rl = jnp.arange(mt)[:, None]
        cl = jnp.arange(nbw)[None, :]
        packedc = jnp.where(rl > cl, U[:, :nbw], 0)
        packedc = jnp.where(rl == cl, dpan[None, :nbw].astype(dtype)
                            * jnp.ones((mt, 1), dtype), packedc)
        esup = jnp.concatenate([jnp.zeros((1,), rdtype), epan[:nbw]])
        packedc = jnp.where(rl == cl - 1,
                            esup[None, jnp.arange(nbw)].astype(dtype)
                            * jnp.ones((mt, 1), dtype), packedc)
        # in-panel right-reflector tails: entry (i, jc) with i <= jc-2 holds
        # v_i[jc] (row-stored packing restricted to the panel's columns)
        VT = jnp.pad(V.T[:nbw, :nbw], ((0, max(mt - nbw, 0)), (0, 0)))[:mt, :]
        packedc = jnp.where(rl + 2 <= cl, VT, packedc)
        if ce_up > e_col:
            packedc = jnp.pad(packedc, ((0, 0), (0, ce_up - e_col)))
        blk = DistMatrix(packedc, (mt, ce_up - s), STAR, STAR, 0, 0, g)
        Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, m),
                             (s, ce_up), e_col)
        # packed panel rows: v tails right of superdiag, e on superdiag
        rl2 = jnp.arange(nbw)[:, None]
        cl2 = jnp.arange(nt)[None, :]
        packedr = jnp.where(cl2 > rl2 + 1, V.T[:nbw, :], 0)
        packedr = jnp.where(cl2 == rl2 + 1,
                            epan[:nbw, None].astype(dtype)
                            * jnp.ones((1, nt), dtype), packedr)
        if re_up > e_col:
            packedr = jnp.pad(packedr, ((0, re_up - e_col), (0, 0)))
        blkr = DistMatrix(packedr, (re_up - s, nt), STAR, STAR, 0, 0, g)
        cur = view(Ap, rows=(s, re_up), cols=(s, n))
        I2, J2 = _global_indices(cur)
        # rows < nbw, columns >= e_col only: the diag/superdiag and in-panel
        # tails are owned by the column write above
        keep = (I2 < nbw)[:, None] & (J2 >= (e_col - s))[None, :]
        merged = jnp.where(keep, redistribute(blkr, MC, MR).local, cur.local)
        Ap = update_view(Ap, cur.with_local(merged), rows=(s, re_up),
                         cols=(s, n))
        if e_col == n:
            break
        # trailing update: A22 -= U2 Y2^H + X2 V2^H
        U2 = U[nbw:, :]
        X2 = X[nbw:, :]
        Y2 = Y[nbw:, :]
        V2 = V[nbw:, :]
        mt2, nt2 = m - e_col, n - e_col
        U2mc = redistribute(DistMatrix(U2, (mt2, nbw), STAR, STAR, 0, 0, g),
                            MC, STAR)
        X2mc = redistribute(DistMatrix(X2, (mt2, nbw), STAR, STAR, 0, 0, g),
                            MC, STAR)
        Y2Hmr = redistribute(DistMatrix(jnp.conj(Y2).T, (nbw, nt2), STAR,
                                        STAR, 0, 0, g), STAR, MR)
        V2Hmr = redistribute(DistMatrix(jnp.conj(V2).T, (nbw, nt2), STAR,
                                        STAR, 0, 0, g), STAR, MR)
        A22 = view(Ap, rows=(e_col, m), cols=(e_col, n))
        upd = (jnp.matmul(U2mc.local, Y2Hmr.local, precision=_hi(precision))
               + jnp.matmul(X2mc.local, V2Hmr.local, precision=_hi(precision)))
        Ap = update_view(Ap, A22.with_local(A22.local - upd.astype(dtype)),
                         rows=(e_col, m), cols=(e_col, n))
    d = jnp.concatenate(d_parts)[:n]
    e_ = jnp.concatenate(e_parts)[:n - 1] if n > 1 else jnp.zeros((0,), rdtype)
    tauq = jnp.concatenate(tq_parts)[:n]
    taup = jnp.concatenate(tp_parts)[:max(n - 1, 0)]
    return Ap, d, e_, tauq, taup


def apply_p_bidiag(Ap: DistMatrix, taup, B: DistMatrix, orient: str = "N",
                   nb: int | None = None, precision=None) -> DistMatrix:
    """B := P B ('N') or P^H B ('C') with P = G_0 G_1 ... G_{n-2} the
    right-reflector product from :func:`bidiag` (G_j = I - taup_j
    v_j v_j^H, v_j unit at position j+1)."""
    _check_mcmr(Ap, B)
    n = Ap.gshape[1]
    if B.gshape[0] != n:
        raise ValueError(f"B height {B.gshape[0]} != {n}")
    g = Ap.grid
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), n)
    kend = max(n - 1, 0)
    starts = list(range(0, kend, ib))
    if orient == "N":
        starts = starts[::-1]
    for s in starts:
        e_col = min(s + ib, kend)
        nbw = e_col - s
        re_up = min(round_up(e_col, r), Ap.gshape[0])
        Prow = redistribute(view(Ap, rows=(s, re_up), cols=(s, n)),
                            STAR, STAR).local[:nbw, :]
        # V panel: v_j tails from row j at cols >= j+2 (unit at j+1)
        nt = n - s
        rl = jnp.arange(nt)[:, None]
        cl = jnp.arange(nbw)[None, :]
        V = jnp.where(rl >= cl + 2, Prow.T[:nt, :nbw], 0)
        V = V + jnp.eye(nt, nbw, k=-1, dtype=Prow.dtype)
        T = _larft(V, taup[s:e_col])
        Tm = jnp.conj(T).T if orient == "C" else T
        V_mc = redistribute(
            DistMatrix(V, (nt, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        B2 = view(B, rows=(s, n))
        Wl = jnp.matmul(jnp.conj(V_mc.local).T, B2.local, precision=_hi(precision))
        Wl = jnp.matmul(Tm, Wl, precision=_hi(precision))
        upd = jnp.matmul(V_mc.local, Wl, precision=_hi(precision))
        B = update_view(B, B2.with_local(B2.local - upd.astype(B.dtype)),
                        rows=(s, n))
    return B


# ---------------------------------------------------------------------
# Hessenberg reduction (for Schur / pseudospectra)
# ---------------------------------------------------------------------

def hessenberg(A: DistMatrix, nb: int | None = None, precision=None):
    """Reduce A to upper Hessenberg form: A = Q H Q^H
    (``El::Hessenberg``, lower/'L' reflector convention).

    Returns ``(H, Q_packed, tau)`` where ``H`` is the [MC,MR] Hessenberg
    matrix and ``Q_packed``/``tau`` hold the reflectors (same packing as
    :func:`hermitian_tridiag`).

    v1 is unblocked at panel granularity (per-column distributed gemv +
    per-panel rank-2k trailing updates come with the blocked Schur work);
    correctness-first -- the spectral layer's Schur path is the consumer.
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"hessenberg needs square, got {A.gshape}")
    g = A.grid
    dtype = A.dtype
    if n <= 2:
        return A, A, jnp.zeros((max(n - 1, 0),), dtype)
    # v1: replicated reduction (correctness path; the distributed blocked
    # version follows the tridiag pattern once Schur lands)
    Ag = redistribute(A, STAR, STAR).local
    ridx = jnp.arange(n)

    def body(jj, carry):
        Ag, Vp, tau = carry
        col = Ag[:, jj]
        v, tau_j, _ = _larfg_tail(col, jj, ridx, dtype)
        # A := H^H A H, H = I - tau v v^H
        w = jnp.conj(tau_j) * (jnp.conj(v) @ Ag)
        Ag = Ag - jnp.outer(v, w)
        u = Ag @ (tau_j * v)
        Ag = Ag - jnp.outer(u, jnp.conj(v))
        Vp = Vp.at[:, jj].set(v)
        tau = tau.at[jj].set(tau_j)
        return Ag, Vp, tau

    Ag, Vp, tau = lax.fori_loop(
        0, n - 1, body,
        (Ag, jnp.zeros((n, n - 1), dtype), jnp.zeros((n - 1,), dtype)))
    # zero below the first subdiagonal (numerical dust from the loop)
    Hloc = jnp.where(jnp.arange(n)[:, None] > jnp.arange(n)[None, :] + 1, 0, Ag)
    H = redistribute(DistMatrix(Hloc, (n, n), STAR, STAR, 0, 0, g), MC, MR)
    packed = jnp.where(jnp.arange(n)[:, None] >= jnp.arange(n - 1)[None, :] + 2,
                       Vp, 0)
    ridx2 = jnp.arange(n)[:, None]
    cidx2 = jnp.arange(n - 1)[None, :]
    packed = jnp.where(ridx2 == cidx2 + 1, Hloc[:, :n - 1], packed)
    packed = jnp.where(ridx2 == cidx2, Hloc[:, :n - 1], packed)
    Qp = redistribute(DistMatrix(packed, (n, n - 1), STAR, STAR, 0, 0, g), MC, MR)
    return H, Qp, tau


def apply_q_hessenberg(Qp: DistMatrix, tau, B: DistMatrix, orient: str = "N",
                       precision=None) -> DistMatrix:
    """B := Q B / Q^H B with Q from :func:`hessenberg` (packing as tridiag)."""
    n = B.gshape[0]
    g = B.grid
    P = redistribute(Qp, STAR, STAR).local
    nref = tau.shape[0]
    V = _tridiag_v_panel(jnp.pad(P, ((0, 0), (0, max(0, n - P.shape[1])))), nref)
    T = _larft(V, tau)
    Tm = jnp.conj(T).T if orient == "C" else T
    V_mc = redistribute(DistMatrix(V, (n, nref), STAR, STAR, 0, 0, g), MC, STAR)
    Wl = jnp.matmul(jnp.conj(V_mc.local).T, B.local, precision=_hi(precision))
    Wl = jnp.matmul(Tm, Wl, precision=_hi(precision))
    upd = jnp.matmul(V_mc.local, Wl, precision=_hi(precision))
    return B.with_local(B.local - upd.astype(B.dtype))
