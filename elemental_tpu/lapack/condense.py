"""Condense layer: reduction to tridiagonal (and Hessenberg) form.

Reference: Elemental ``src/lapack_like/condense/HermitianTridiag/**``
(``El::HermitianTridiag``; blocked panels building a distributed-Hemv W
panel, then a Her2k-style two-sided trailing update -- SURVEY.md §4.5) and
``condense/Hessenberg/**`` (``El::Hessenberg``).

TPU-first design: the reduction panel loop is ONE jitted ``lax.fori_loop``
per panel (LAPACK ``latrd`` semantics).  Per column the only distributed
work is a single :func:`~elemental_tpu.blas.level2.hemv` against the fixed
trailing view (the reference's distributed Hemv with [MC,STAR]/[MR,STAR]
accumulators); the V/W panels live replicated (n x nb -- small).  The
trailing update ``A22 -= V W^H + W V^H`` is one masked storage matmul on
the MXU (exactly the reference's rank-2k update), so all O(n^3/MXU-friendly)
FLOPs are large matmuls and all latency-bound work is batched into one
compiled loop.

Packing (lower): reflector j has an implicit 1 at row j+1; its tail lives in
``Ap[j+2:, j]``; ``d``/``e`` (real) are returned separately, and also
written to the diagonal/subdiagonal of ``Ap``.  ``uplo`` selects which
triangle of the Hermitian input is READ; the packing is always lower (a
documented deviation from LAPACK's dual packing -- A is Hermitian, so both
read paths factor the same matrix).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view, round_up
from ..redist.engine import redistribute, transpose_dist
from ..blas.level2 import hemv
from ..blas.level3 import _blocksize, _check_mcmr, _mask_triangle
from .lu import _update_cols_lt
from .qr import _larft


def _real_dtype(dtype):
    return jnp.zeros((), dtype).real.dtype


def _wrap_vec(v, grid) -> DistMatrix:
    """Replicated (nt,) vector -> zero-aligned (nt, 1) [MC,MR] DistMatrix."""
    ss = DistMatrix(v[:, None], (v.shape[0], 1), STAR, STAR, 0, 0, grid)
    return redistribute(ss, MC, MR)


def _unwrap_vec(x: DistMatrix):
    return redistribute(x, STAR, STAR).local[:, 0]


def _larfg_tail(col, jj, ridx, dtype):
    """Householder reflector zeroing rows > jj+1 of ``col`` (LAPACK larfg:
    real beta, H = I - tau v v^H with implicit v[jj+1] = 1)."""
    alpha = col[jj + 1]
    tail2 = jnp.where(ridx > jj + 1, col, 0)
    sigma = jnp.sum(jnp.abs(tail2) ** 2)
    anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
    re_a = jnp.real(alpha)
    beta = -jnp.sign(jnp.where(re_a == 0, 1.0, re_a)) * anorm      # real
    degenerate = anorm == 0
    safe_beta = jnp.where(degenerate, 1.0, beta)
    tau = jnp.where(degenerate, 0.0, (safe_beta - alpha) / safe_beta)
    denom = alpha - safe_beta
    safe_denom = jnp.where(denom == 0, 1.0, denom)
    v = jnp.where(ridx > jj + 1, col / safe_denom, 0)
    v = jnp.where(ridx == jj + 1, jnp.ones((), dtype), v)
    return v.astype(dtype), jnp.asarray(tau, dtype), beta


@partial(jax.jit, static_argnums=(2, 3, 4))
def _tridiag_panel(Atrail: DistMatrix, P, nbw: int, extract_last: bool,
                   precision):
    """latrd: reduce ``nbw`` columns of the trailing matrix.

    ``Atrail`` is the fixed (nt, nt) [MC,MR] trailing view; ``P`` the
    replicated panel columns.  Returns (V, W, d, e, tau) with V/W the
    (nt, nbw) replicated reflector/update panels.
    """
    nt = Atrail.gshape[0]
    g = Atrail.grid
    dtype = P.dtype
    rdtype = _real_dtype(dtype)
    ridx = jnp.arange(nt)
    nd = nbw + 1 if extract_last else nbw

    def corrected_col(P, V, W, jj):
        return P[:, jj] - V @ jnp.conj(W[jj, :]) - W @ jnp.conj(V[jj, :])

    def body(jj, carry):
        V, W, d, e, tau = carry
        col = corrected_col(P, V, W, jj)
        d = d.at[jj].set(jnp.real(col[jj]).astype(rdtype))
        v, tau_j, beta = _larfg_tail(col, jj, ridx, dtype)
        e = e.at[jj].set(beta.astype(rdtype))
        # the one distributed op per column: u = A_trail v (Hemv; v's leading
        # zeros make this the reference's A22*v on the true subproblem)
        u = _unwrap_vec(hemv("L", Atrail, _wrap_vec(v, g), precision=precision))
        u = u - V @ (jnp.conj(W).T @ v) - W @ (jnp.conj(V).T @ v)
        w = tau_j * u
        w = jnp.where(ridx > jj, w, 0)
        w = w - (0.5 * tau_j * (jnp.conj(w) @ v)) * v
        V = V.at[:, jj].set(v)
        W = W.at[:, jj].set(w.astype(dtype))
        tau = tau.at[jj].set(tau_j)
        return V, W, d, e, tau

    init = (jnp.zeros((nt, nbw), dtype), jnp.zeros((nt, nbw), dtype),
            jnp.zeros((nd,), rdtype), jnp.zeros((nbw,), rdtype),
            jnp.zeros((nbw,), dtype))
    V, W, d, e, tau = lax.fori_loop(0, nbw, body, init)
    if extract_last:
        col = corrected_col(P, V, W, nbw)
        d = d.at[nbw].set(jnp.real(col[nbw]).astype(rdtype))
    return V, W, d, e, tau


def _packed_panel(V, d, e, nbw: int, dtype):
    """Assemble the packed panel: diag d, subdiag e, reflector tails below."""
    nt = V.shape[0]
    ridx = jnp.arange(nt)[:, None]
    cidx = jnp.arange(nbw)[None, :]
    packed = jnp.where(ridx >= cidx + 2, V[:, :nbw], 0)
    packed = jnp.where(ridx == cidx, d[:nbw].astype(dtype), packed)
    packed = jnp.where(ridx == cidx + 1, e[:nbw].astype(dtype), packed)
    return packed


def hermitian_tridiag(A: DistMatrix, uplo: str = "L", nb: int | None = None,
                      precision=None):
    """Reduce a Hermitian [MC,MR] matrix to real tridiagonal form.

    Returns ``(Ap, d, e, tau)``: ``A = Q T Q^H`` with ``T = tridiag(e, d, e)``
    and ``Q = H_0 H_1 ... H_{n-2}`` packed in ``Ap``'s lower triangle
    (``El::HermitianTridiag``).
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"hermitian_tridiag needs square, got {A.gshape}")
    if uplo.upper().startswith("U"):
        A = redistribute(transpose_dist(A, conj=True), MC, MR)
    g = A.grid
    r, c = g.height, g.width
    dtype = A.dtype
    rdtype = _real_dtype(dtype)
    if n == 0:
        z = jnp.zeros((0,), rdtype)
        return A, z, z, jnp.zeros((0,), dtype)
    if n == 1:
        dd = jnp.real(redistribute(A, STAR, STAR).local[0, 0])[None]
        return A, dd.astype(rdtype), jnp.zeros((0,), rdtype), jnp.zeros((0,), dtype)

    ib = _blocksize(nb, math.lcm(r, c), n)
    kend = n - 1                          # reflector columns 0 .. n-2
    Ap = A
    d_parts, e_parts, tau_parts = [], [], []
    s = 0
    while s < kend:
        e_col = min(s + ib, kend)
        nbw = e_col - s
        final = e_col == kend
        wp_end = n if final else min(round_up(e_col, c), n)
        Atrail = view(Ap, rows=(s, n), cols=(s, n))
        P = redistribute(view(Ap, rows=(s, n), cols=(s, wp_end)), STAR, STAR).local
        V, W, dpan, epan, taupan = _tridiag_panel(Atrail, P, nbw, final, precision)
        d_parts.append(dpan)
        e_parts.append(epan)
        tau_parts.append(taupan)
        packed = _packed_panel(V, dpan, epan, nbw, dtype)
        if final:
            # last column: its diagonal entry
            nt = n - s
            last = jnp.zeros((nt, 1), dtype).at[nt - 1, 0].set(
                dpan[nbw].astype(dtype))
            packed = jnp.concatenate([packed, last], axis=1)
            blk = DistMatrix(packed, (nt, nt), STAR, STAR, 0, 0, g)
            Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, n), (s, n), n)
            break
        wpad = wp_end - s - nbw
        if wpad:
            packed = jnp.pad(packed, ((0, 0), (0, wpad)))
        blk = DistMatrix(packed, (n - s, wp_end - s), STAR, STAR, 0, 0, g)
        Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, n), (s, wp_end), e_col)
        # trailing two-sided update: A22 -= V2 W2^H + W2 V2^H (lower triangle)
        nt2 = n - e_col
        V2 = V[e_col - s:, :]
        W2 = W[e_col - s:, :]
        V2mc = redistribute(DistMatrix(V2, (nt2, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        W2mc = redistribute(DistMatrix(W2, (nt2, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        V2Hmr = redistribute(
            DistMatrix(jnp.conj(V2).T, (nbw, nt2), STAR, STAR, 0, 0, g), STAR, MR)
        W2Hmr = redistribute(
            DistMatrix(jnp.conj(W2).T, (nbw, nt2), STAR, STAR, 0, 0, g), STAR, MR)
        A22 = view(Ap, rows=(e_col, n), cols=(e_col, n))
        upd = (jnp.matmul(V2mc.local, W2Hmr.local, precision=precision)
               + jnp.matmul(W2mc.local, V2Hmr.local, precision=precision))
        mask = _mask_triangle(A22, "L")
        newloc = jnp.where(mask, A22.local - upd.astype(dtype), A22.local)
        Ap = update_view(Ap, A22.with_local(newloc), rows=(e_col, n), cols=(e_col, n))
        s = e_col
    d = jnp.concatenate(d_parts)
    e_ = jnp.concatenate(e_parts)
    tau = jnp.concatenate(tau_parts)
    return Ap, d, e_, tau


def _tridiag_v_panel(P, nbw: int):
    """Unit-structured reflector panel from tridiag packing: V[jj+1,jj]=1,
    tails from rows >= jj+2."""
    nt = P.shape[0]
    ridx = jnp.arange(nt)[:, None]
    cidx = jnp.arange(nbw)[None, :]
    V = jnp.where(ridx >= cidx + 2, P[:, :nbw], 0)
    return V + jnp.eye(nt, nbw, k=-1, dtype=P.dtype)


def apply_q_herm_tridiag(Ap: DistMatrix, tau, B: DistMatrix,
                         orient: str = "N", nb: int | None = None,
                         precision=None) -> DistMatrix:
    """B := Q B ('N') or Q^H B ('C') with Q from :func:`hermitian_tridiag`
    (the back-transform of ``El::HermitianEig``, ``herm_eig::`` +
    ``ApplyPackedReflectors``).  ``nb`` must match the factorization's."""
    _check_mcmr(Ap, B)
    n = Ap.gshape[0]
    if B.gshape[0] != n:
        raise ValueError(f"B height {B.gshape[0]} != {n}")
    g = Ap.grid
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), n)
    kend = n - 1
    starts = list(range(0, kend, ib))
    if orient == "N":
        starts = starts[::-1]
    for s in starts:
        e_col = min(s + ib, kend)
        nbw = e_col - s
        wp_end = n if e_col == kend else min(round_up(e_col, c), n)
        P = redistribute(view(Ap, rows=(s, n), cols=(s, wp_end)), STAR, STAR).local
        V = _tridiag_v_panel(P, nbw)
        T = _larft(V, tau[s:e_col])
        Tm = jnp.conj(T).T if orient == "C" else T
        V_mc = redistribute(
            DistMatrix(V, (n - s, nbw), STAR, STAR, 0, 0, g), MC, STAR)
        B2 = view(B, rows=(s, n))
        Wl = jnp.matmul(jnp.conj(V_mc.local).T, B2.local, precision=precision)
        Wl = jnp.matmul(Tm, Wl, precision=precision)
        upd = jnp.matmul(V_mc.local, Wl, precision=precision)
        B = update_view(B, B2.with_local(B2.local - upd.astype(B.dtype)),
                        rows=(s, n))
    return B


# ---------------------------------------------------------------------
# Hessenberg reduction (for Schur / pseudospectra)
# ---------------------------------------------------------------------

def hessenberg(A: DistMatrix, nb: int | None = None, precision=None):
    """Reduce A to upper Hessenberg form: A = Q H Q^H
    (``El::Hessenberg``, lower/'L' reflector convention).

    Returns ``(H, Q_packed, tau)`` where ``H`` is the [MC,MR] Hessenberg
    matrix and ``Q_packed``/``tau`` hold the reflectors (same packing as
    :func:`hermitian_tridiag`).

    v1 is unblocked at panel granularity (per-column distributed gemv +
    per-panel rank-2k trailing updates come with the blocked Schur work);
    correctness-first -- the spectral layer's Schur path is the consumer.
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"hessenberg needs square, got {A.gshape}")
    g = A.grid
    dtype = A.dtype
    if n <= 2:
        return A, A, jnp.zeros((max(n - 1, 0),), dtype)
    # v1: replicated reduction (correctness path; the distributed blocked
    # version follows the tridiag pattern once Schur lands)
    Ag = redistribute(A, STAR, STAR).local
    ridx = jnp.arange(n)

    def body(jj, carry):
        Ag, Vp, tau = carry
        col = Ag[:, jj]
        v, tau_j, _ = _larfg_tail(col, jj, ridx, dtype)
        # A := H^H A H, H = I - tau v v^H
        w = jnp.conj(tau_j) * (jnp.conj(v) @ Ag)
        Ag = Ag - jnp.outer(v, w)
        u = Ag @ (tau_j * v)
        Ag = Ag - jnp.outer(u, jnp.conj(v))
        Vp = Vp.at[:, jj].set(v)
        tau = tau.at[jj].set(tau_j)
        return Ag, Vp, tau

    Ag, Vp, tau = lax.fori_loop(
        0, n - 1, body,
        (Ag, jnp.zeros((n, n - 1), dtype), jnp.zeros((n - 1,), dtype)))
    # zero below the first subdiagonal (numerical dust from the loop)
    Hloc = jnp.where(jnp.arange(n)[:, None] > jnp.arange(n)[None, :] + 1, 0, Ag)
    H = redistribute(DistMatrix(Hloc, (n, n), STAR, STAR, 0, 0, g), MC, MR)
    packed = jnp.where(jnp.arange(n)[:, None] >= jnp.arange(n - 1)[None, :] + 2,
                       Vp, 0)
    ridx2 = jnp.arange(n)[:, None]
    cidx2 = jnp.arange(n - 1)[None, :]
    packed = jnp.where(ridx2 == cidx2 + 1, Hloc[:, :n - 1], packed)
    packed = jnp.where(ridx2 == cidx2, Hloc[:, :n - 1], packed)
    Qp = redistribute(DistMatrix(packed, (n, n - 1), STAR, STAR, 0, 0, g), MC, MR)
    return H, Qp, tau


def apply_q_hessenberg(Qp: DistMatrix, tau, B: DistMatrix, orient: str = "N",
                       precision=None) -> DistMatrix:
    """B := Q B / Q^H B with Q from :func:`hessenberg` (packing as tridiag)."""
    n = B.gshape[0]
    g = B.grid
    P = redistribute(Qp, STAR, STAR).local
    nref = tau.shape[0]
    V = _tridiag_v_panel(jnp.pad(P, ((0, 0), (0, max(0, n - P.shape[1])))), nref)
    T = _larft(V, tau)
    Tm = jnp.conj(T).T if orient == "C" else T
    V_mc = redistribute(DistMatrix(V, (n, nref), STAR, STAR, 0, 0, g), MC, STAR)
    Wl = jnp.matmul(jnp.conj(V_mc.local).T, B.local, precision=precision)
    Wl = jnp.matmul(Tm, Wl, precision=precision)
    upd = jnp.matmul(V_mc.local, Wl, precision=precision)
    return B.with_local(B.local - upd.astype(B.dtype))
