"""LAPACK-like layer: factorizations, solves, spectral (growing per
SURVEY.md §3.4 / §8.2)."""
from .cholesky import (cholesky, hpd_solve, cholesky_solve_after,
                       cholesky_pivoted, cholesky_mod)
from .lu import (lu, lu_solve, lu_solve_after, permute_rows, permute_cols,
                 lu_full_pivot)
from .qr import (qr, apply_q, explicit_q, least_squares, tsqr, lq,
                 apply_q_lq, explicit_l, qr_col_piv, rq)
from .euclidean_min import ridge, tikhonov, lse, glm
from .condense import (hermitian_tridiag, apply_q_herm_tridiag, hessenberg,
                       apply_q_hessenberg, bidiag, apply_p_bidiag)
from .ldl import (ldl, ldl_solve_after, symmetric_solve, hermitian_solve,
                  inertia)
from .funcs import (polar, sign, inverse, triangular_inverse, hpd_inverse,
                    pseudoinverse, square_root, hpd_square_root)
from .spectral import (herm_eig, skew_herm_eig, herm_gen_def_eig,
                       hermitian_svd, svd)
from .tridiag_eig import tridiag_eig
from .schur import schur, triang_eig, eig, pseudospectra
from .props import (determinant, safe_determinant, hpd_determinant,
                    two_norm_estimate, condition, inertia as matrix_inertia,
                    nuclear_norm, schatten_norm, two_norm)
