"""Spectral layer: Hermitian eigensolvers and the SVD.

Reference: Elemental ``src/lapack_like/spectral/HermitianEig.cpp``
(``El::HermitianEig``: tridiagonalize -> tridiagonal EVP -> back-transform;
upstream solves the tridiagonal problem with bundled PMRRR), ``SVD.cpp``
(``El::SVD``, ``svd::Chan`` tall path), ``HermitianGenDefEig``,
``SkewHermitianEig``, ``HermitianSVD``.

TPU-native redesign (SURVEY.md §8.1 item 4): PMRRR (MPI+pthreads C) has no
TPU analog, so the tridiagonal EVP is solved REDUNDANTLY on every device on
the replicated (d, e) -- the same shape as the reference's older
gather-and-run-LAPACK-redundantly path for bidiagonal SVD -- while all
O(n^3) work (the reduction and the eigenvector back-transform) stays
distributed and matmul-shaped.  The matmul-rich polar-based spectral D&C
(QDWH-eig, PAPERS.md arXiv 2112.09017) lives in :mod:`.funcs` /
:func:`herm_eig` ``approach='qdwh'``.

Subset eigenpairs (``HermitianEigSubset``) select tridiagonal eigenvector
columns BEFORE the back-transform, so a k-subset costs an (n, k) apply-Q.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute, transpose_dist
from ..blas.level3 import _check_mcmr, gemm, trsm, two_sided_trsm
from ..core.view import pad_matrix
from ..redist.interior import interior_view
from ..blas.level1 import diagonal_scale, make_trapezoidal
from .cholesky import cholesky
from .condense import hermitian_tridiag, apply_q_herm_tridiag, _real_dtype
from .lu import permute_cols, _hi
from .qr import qr, apply_q
from .tridiag_eig import tridiag_eig

# Above this order the tridiagonal EVP switches from the replicated
# jnp.linalg.eigh fallback to the scalable Cuppen D&C (:mod:`.tridiag_eig`,
# the PMRRR analog) -- no replicated n x n array is materialized past its
# ``repl_max``.  The switchover is tied to repl_max: below it the D&C would
# still run fully replicated (no memory win) at slightly lower accuracy
# than the direct eigh, so there is nothing to gain.
_DC_MIN = 512
_REPL_MAX = 512


def _sym_from_triangle(Ag, uplo: str):
    """Rebuild the full Hermitian matrix from one stored triangle."""
    if uplo.upper().startswith("L"):
        t = jnp.tril(Ag)
        return t + jnp.conj(jnp.tril(t, -1)).T
    t = jnp.triu(Ag)
    return t + jnp.conj(jnp.triu(t, 1)).T


def _subset_slice(w, subset):
    """Resolve a HermitianEigSubset analog to a column slice (host-side).

    ``subset``: None (all), ``('index', il, iu)`` inclusive indices into the
    ascending spectrum, or ``('value', lo, hi)`` selecting the half-open
    interval (lo, hi] -- LAPACK range='V' / ``HermitianEigSubset``
    semantics.  An optional 4th element overrides the searchsorted sides
    (internal; used by the skew translation).
    """
    n = w.shape[0]
    if subset is None:
        return 0, n
    kind = subset[0]
    if kind == "index":
        il, iu = subset[1], subset[2]
        return il, iu + 1
    if kind == "value":
        lo, hi = subset[1], subset[2]
        sides = subset[3] if len(subset) > 3 else ("right", "right")
        wn = np.asarray(w)
        il = int(np.searchsorted(wn, lo, side=sides[0]))
        iu = int(np.searchsorted(wn, hi, side=sides[1]))
        return il, iu
    raise ValueError(f"bad subset {subset!r}")


def herm_eig(A: DistMatrix, uplo: str = "L", vectors: bool = True,
             subset=None, nb: int | None = None, approach: str = "tridiag",
             precision=None, dc_min: int | None = None,
             repl_max: int | None = None):
    """Eigendecomposition of a Hermitian [MC,MR] matrix: ``A = Z diag(w) Z^H``
    (``El::HermitianEig``).  Returns ascending real ``w`` (replicated) and,
    when ``vectors``, the distributed eigenvector matrix ``Z``.
    """
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"herm_eig needs square, got {A.gshape}")
    g = A.grid
    rdtype = _real_dtype(A.dtype)
    if n <= 2:
        Ag = _sym_from_triangle(redistribute(A, STAR, STAR).local, uplo)
        w, Z = jnp.linalg.eigh(Ag)
        s, e = _subset_slice(w, subset)
        w = w[s:e].astype(rdtype)
        if not vectors:
            return w
        Zd = redistribute(
            DistMatrix(Z[:, s:e], (n, e - s), STAR, STAR, 0, 0, g), MC, MR)
        return w, Zd
    if approach == "qdwh":
        from .funcs import _qdwh_eig
        return _qdwh_eig(A, uplo, vectors, subset, nb, precision)
    Ap, d, e_, tau = hermitian_tridiag(A, uplo, nb=nb, precision=_hi(precision))
    if dc_min is None:
        dc_min = _DC_MIN
    if repl_max is None:
        repl_max = _REPL_MAX
    if n > dc_min:
        # scalable Cuppen D&C tridiagonal stage (the PMRRR replacement):
        # above repl_max the eigenvector matrix only ever exists [MC,MR]
        if not vectors:
            w = tridiag_eig(d, e_, grid=None, vectors=False,
                            repl_max=repl_max, precision=_hi(precision))
            s, e = _subset_slice(w, subset)
            return w[s:e].astype(rdtype)
        w, ZTd = tridiag_eig(d, e_, grid=g, vectors=True, repl_max=repl_max,
                             precision=_hi(precision))
        s, e = _subset_slice(w, subset)
        w = w[s:e].astype(rdtype)
        if (s, e) != (0, n):
            ZTd = interior_view(ZTd, (0, n), (s, e))
        if ZTd.dtype != A.dtype:
            ZTd = ZTd.astype(A.dtype)
        Z = apply_q_herm_tridiag(Ap, tau, ZTd, orient="N", nb=nb,
                                 precision=_hi(precision))
        return w, Z
    T = (jnp.diag(d) + jnp.diag(e_, -1) + jnp.diag(e_, 1)).astype(rdtype)
    w, ZT = jnp.linalg.eigh(T)            # redundant replicated tridiag solve
    s, e = _subset_slice(w, subset)
    w = w[s:e]
    if not vectors:
        return w
    k = e - s
    ZTd = redistribute(
        DistMatrix(ZT[:, s:e].astype(A.dtype), (n, k), STAR, STAR, 0, 0, g),
        MC, MR)
    Z = apply_q_herm_tridiag(Ap, tau, ZTd, orient="N", nb=nb,
                             precision=_hi(precision))
    return w, Z


def _translate_skew_subset(subset, n: int):
    """Map a subset request on the FINAL ascending imaginary parts
    ``m_j = -w_{n-1-j}`` to one on ``w = eig(iA)`` (ascending)."""
    if subset is None:
        return None
    kind = subset[0]
    if kind == "index":
        il, iu = subset[1], subset[2]
        return ("index", n - 1 - iu, n - 1 - il)
    if kind == "value":
        lo, hi = subset[1], subset[2]
        # m in (lo, hi]  <=>  w = -m in [-hi, -lo)
        return ("value", -hi, -lo, ("left", "left"))
    raise ValueError(f"bad subset {subset!r}")


def skew_herm_eig(A: DistMatrix, uplo: str = "L", vectors: bool = True,
                  subset=None, nb: int | None = None, precision=None,
                  approach: str = "tridiag"):
    """Eigenvalues (purely imaginary, returned as their imaginary parts,
    ascending) of a skew-Hermitian matrix: eig(iA) with a sign flip
    (``El::SkewHermitianEig``)."""
    cdtype = jnp.result_type(A.dtype, jnp.complex64)
    iA = A.with_local((1j * A.local.astype(cdtype)))
    n = A.gshape[0]
    out = herm_eig(iA, uplo, vectors, _translate_skew_subset(subset, n), nb,
                   approach=approach, precision=_hi(precision))
    # eig(A) = -i * eig(iA): imaginary parts are -w; re-sort ascending.
    if not vectors:
        return -out[::-1]
    w, Z = out
    k = Z.gshape[1]
    Zr = permute_cols(Z, jnp.arange(k)[::-1]) if k > 1 else Z
    return (-w)[::-1], Zr


def herm_gen_def_eig(A: DistMatrix, B: DistMatrix, uplo: str = "L",
                     vectors: bool = True, subset=None, nb: int | None = None,
                     precision=None, approach: str = "tridiag"):
    """Generalized definite pencil ``A x = w B x`` with HPD ``B``
    (``El::HermitianGenDefEig``, AXBX form): Cholesky B = L L^H, reduce via
    ``TwoSidedTrsm`` to ``L^-1 A L^-H``, solve, back-substitute
    ``x = L^-H y``."""
    L = cholesky(B, "L", nb=nb, precision=_hi(precision))
    C = two_sided_trsm(uplo, A, L, nb=nb, precision=_hi(precision))
    out = herm_eig(C, uplo, vectors, subset, nb=nb, approach=approach,
                   precision=_hi(precision))
    if not vectors:
        return out
    w, Y = out
    X = trsm("L", "L", "C", L, Y, nb=nb, precision=_hi(precision))
    return w, X


# ---------------------------------------------------------------------
# SVD
# ---------------------------------------------------------------------

def hermitian_svd(A: DistMatrix, uplo: str = "L", vectors: bool = True,
                  nb: int | None = None, precision=None,
                  approach: str = "tridiag"):
    """SVD of a Hermitian matrix via its eigendecomposition
    (``El::HermitianSVD``): s = |w| descending, U = Z*sign(w), V = Z."""
    out = herm_eig(A, uplo, vectors, nb=nb, approach=approach,
                   precision=_hi(precision))
    if not vectors:
        w = out
        return jnp.sort(jnp.abs(w))[::-1]
    w, Z = out
    order = jnp.argsort(-jnp.abs(w))
    s = jnp.abs(w)[order]
    signs = jnp.where(w[order] < 0, -1.0, 1.0).astype(A.dtype)
    V = permute_cols(Z, order)          # distributed column permutation
    d = DistMatrix(signs[:, None], (signs.shape[0], 1), STAR, STAR, 0, 0,
                   A.grid)
    U = diagonal_scale("R", d, V)
    return U, s, V


def svd(A: DistMatrix, vectors: bool = True, approach: str = "auto",
        nb: int | None = None, precision=None, eig_approach: str = "tridiag"):
    """Singular value decomposition ``A = U diag(s) V^H`` (``El::SVD``).

    ``approach``:
      * 'chan'  -- tall path (``svd::Chan``): QR first, SVD of the small R,
        U = Q U_R (the reference's default for m >= 1.5 n).
      * 'polar' -- QDWH polar + Hermitian eigensolve of the factor H
        (matmul-rich, fully distributed; the TPU-paper recipe).
      * 'golub' -- Bidiag + tridiagonal EVP of B^H B + back-transform
        (``svd::GolubReinsch`` analog; see :func:`_svd_golub_kahan`).
      * 'auto'  -- 'chan' when m >= 1.5 n (or the mirrored transpose when
        n >= 1.5 m), else 'polar'.
    ``eig_approach`` is forwarded to the inner :func:`herm_eig` ('qdwh'
    selects the fully-scalable spectral D&C).
    Returns (U, s, V) with s descending (replicated real vector).
    """
    _check_mcmr(A)
    m, n = A.gshape
    g = A.grid
    if n > m:
        out = svd(redistribute(transpose_dist(A, conj=True), MC, MR),
                  vectors, approach, nb, precision, eig_approach)
        if not vectors:
            return out
        U, s, V = out
        return V, s, U
    if approach == "auto":
        approach = "chan" if m >= max(int(1.5 * n), n + 1) else "polar"

    if approach == "chan" and m > n:
        Ap, tau = qr(A, nb=nb, precision=_hi(precision))
        Rd = make_trapezoidal(interior_view(Ap, (0, n), (0, n)), "U")
        out = svd(Rd, vectors, "polar" if n > 128 else "local", nb, precision,
                  eig_approach)
        if not vectors:
            return out
        UR, s, V = out
        # U = Q [UR; 0] -- the row pad is a pure-local storage extension
        U0 = pad_matrix(UR, m, n)
        U = apply_q(Ap, tau, U0, orient="N", nb=nb, precision=_hi(precision))
        return U, s, V

    if approach == "golub":
        return _svd_golub_kahan(A, vectors, nb, precision, eig_approach)

    if approach == "local" or (approach in ("chan",) and m == n):
        approach = "local"
    if approach == "local":
        # replicated fallback for small blocks (the redundant-LAPACK analog)
        Ag = redistribute(A, STAR, STAR).local
        U, s, Vh = jnp.linalg.svd(Ag, full_matrices=False)
        if not vectors:
            return s.astype(_real_dtype(A.dtype))
        Ud = redistribute(DistMatrix(U, (m, n), STAR, STAR, 0, 0, g), MC, MR)
        Vd = redistribute(DistMatrix(jnp.conj(Vh).T, (n, n), STAR, STAR, 0, 0, g),
                          MC, MR)
        return Ud, s.astype(_real_dtype(A.dtype)), Vd

    if approach == "polar":
        return _svd_polar(A, vectors, nb, precision, eig_approach)
    raise ValueError(f"unknown svd approach {approach!r}")


def _svd_golub_kahan(A: DistMatrix, vectors: bool, nb, precision,
                     eig_approach: str):
    """Golub-Kahan path (``svd::GolubReinsch`` analog): Bidiag, then the
    symmetric tridiagonal EVP of B^H B (with eig_approach='qdwh' this is the
    fully-scalable spectral D&C -- no replicated O(n^2) construct), then
    back-transform U = Q [B V_B S^{-1}; 0], V = P V_B.

    Numerical note: forming B^H B squares the condition number; singular
    values below ~sqrt(eps)*s_max lose relative accuracy (the price of the
    bidiagonal-free tridiagonal solve; use 'polar' when they matter).
    """
    from ..core.view import pad_matrix
    from ..redist.interior import interior_view
    from ..blas.level1 import index_dependent_fill
    from ..core.distmatrix import zeros as dm_zeros
    from .condense import bidiag, apply_p_bidiag
    from .lu import permute_cols
    m, n = A.gshape
    g = A.grid
    rdtype = _real_dtype(A.dtype)
    Ap, d, e, tauq, taup = bidiag(A, nb=nb, precision=_hi(precision))
    epad = jnp.concatenate([jnp.zeros((1,), rdtype), e])      # e_{j-1} at j
    enext = jnp.concatenate([e, jnp.zeros((1,), rdtype)])     # e_j at j
    T0 = dm_zeros(n, n, MC, MR, g, dtype=rdtype)

    def tfill(i, j):
        ic = jnp.clip(i, 0, n - 1)
        jc = jnp.clip(j, 0, n - 1)
        diag = d[ic] ** 2 + epad[ic] ** 2
        # (B^H B)[i, i+1] = d_i e_i ; [i+1, i] its conjugate (real here)
        sup = d[ic] * jnp.take(e, jnp.clip(i, 0, max(n - 2, 0)))
        sub = d[jc] * jnp.take(e, jnp.clip(j, 0, max(n - 2, 0)))
        return jnp.where(i == j, diag,
                         jnp.where(j == i + 1, sup,
                                   jnp.where(i == j + 1, sub, 0.0)))

    T = index_dependent_fill(T0, tfill)
    out = herm_eig(T, "L", vectors, nb=nb, approach=eig_approach,
                   precision=_hi(precision))
    if not vectors:
        w = out
        return jnp.sqrt(jnp.clip(jnp.sort(w)[::-1], 0, None))
    w, Z = out
    order = jnp.argsort(-w)
    s = jnp.sqrt(jnp.clip(w[order], 0, None))
    # cast to A's dtype BEFORE the complex back-transforms (a real-typed VB
    # would silently truncate the reflectors' imaginary parts)
    VB = permute_cols(Z, order).astype(A.dtype)
    # U_B = B V_B S^{-1}: row i of B V_B = d_i VB[i,:] + e_i VB[i+1,:]
    dd = DistMatrix(d[:, None].astype(A.dtype), (n, 1), STAR, STAR, 0, 0, g)
    ee = DistMatrix(enext[:, None].astype(A.dtype), (n, 1), STAR, STAR, 0, 0, g)
    VBshift = pad_matrix(interior_view(VB, (1, n), (0, n)), n, n)
    BV = diagonal_scale("L", dd, VB)
    BV = BV.with_local(BV.local + diagonal_scale("L", ee, VBshift).local)
    sinv = jnp.where(s > 0, 1.0 / jnp.where(s == 0, 1.0, s), 0)
    ds = DistMatrix(sinv[:, None].astype(A.dtype), (n, 1), STAR, STAR, 0, 0, g)
    UB = diagonal_scale("R", ds, BV)
    V = apply_p_bidiag(Ap, taup, VB, orient="N", nb=nb, precision=_hi(precision))
    U = apply_q(Ap, tauq, pad_matrix(UB, m, n), orient="N", nb=nb,
                precision=_hi(precision))
    return U, s, V


def _svd_polar(A: DistMatrix, vectors: bool, nb, precision,
               eig_approach: str):
    # polar path: A = Up H; H = V diag(w) V^H; s = w desc; U = Up V
    from .funcs import polar
    Up, H = polar(A, nb=nb, precision=_hi(precision))
    if not vectors:
        w = herm_eig(H, "L", vectors=False, nb=nb, approach=eig_approach,
                     precision=_hi(precision))
        return jnp.clip(jnp.sort(w)[::-1], 0, None)
    w, V = herm_eig(H, "L", True, nb=nb, approach=eig_approach,
                    precision=_hi(precision))
    # H is PSD: w ascending >= 0 (up to rounding); descending order
    order = jnp.argsort(-w)
    s = jnp.clip(w[order], 0, None)
    Vd = permute_cols(V, order)
    U = gemm(Up, Vd, precision=_hi(precision))
    return U, s, Vd
