"""Householder QR, compact-WY application, TSQR, least squares.

Reference: Elemental ``src/lapack_like/factor/QR.cpp`` +
``QR/{Householder,PanelHouseholder,TS,ApplyQ,SolveAfter}.hpp`` and
``src/lapack_like/reflect/ApplyPacked`` -- BASELINE.json's
"Householder QR / least-squares (TSQR panel factor)" config.

TPU-first design (same pattern as lu.py): the panel is gathered to
[STAR,STAR] and reduced REDUNDANTLY on every device with a local larfg
fori_loop (the reference's ``qr::PanelHouseholder`` runs one Nrm2 AllReduce
per column).  The trailing update is the compact-WY form
``A2 -= V T^H (V^H A2)`` where ``V^H A2`` is a storage matmul whose
mc-sharded contraction GSPMD lowers to local MXU product + psum -- exactly
the reference's [MC,STAR]/[STAR,MR] Her2k-style update, with T computed
locally (larft) on the replicated panel.

Packing follows LAPACK geqrf: R on/above the diagonal, the Householder
vectors' tails below it (unit diagonal implicit), plus a tau vector.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, VC, STAR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..core.compat import shard_map
from ..redist.engine import apply_fault, redistribute
from ..blas.level3 import _blocksize, _check_mcmr, trsm
from .lu import (_update_cols_lt, _update_cols_ge, _hi, _phase_hook,
                 _nopiv_panel)


# ---------------------------------------------------------------------
# replicated panel reduction (larfg loop) + larft
# ---------------------------------------------------------------------

def _panel_qr(P):
    """Unblocked Householder QR of a replicated (M, k) panel.

    Returns (packed V\\R panel, tau).  LAPACK larfg conventions: real beta,
    H_j = I - tau_j v_j v_j^H, applied as H^H during the reduction, so the
    panel ends as Q^H A with Q = H_0 ... H_{k-1}."""
    M, k = P.shape
    ridx = jnp.arange(M)
    cidx = jnp.arange(k)

    def body(j, state):
        P, tau = state
        col = P[:, j]
        alpha = col[j]
        tail = jnp.where(ridx > j, col, 0)
        sigma = jnp.sum(jnp.abs(tail) ** 2)
        anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
        re_a = jnp.real(alpha)
        beta = -jnp.sign(jnp.where(re_a == 0, 1.0, re_a)) * anorm   # real
        degenerate = anorm == 0
        safe_beta = jnp.where(degenerate, 1.0, beta)
        tau_j = jnp.where(degenerate, 0.0, (safe_beta - alpha) / safe_beta)
        denom = alpha - safe_beta
        safe_denom = jnp.where(denom == 0, 1.0, denom)
        v = jnp.where(ridx > j, col / safe_denom, 0)
        v = v.at[j].set(jnp.where(degenerate, 0.0, 1.0).astype(P.dtype))
        # apply H_j^H = I - conj(tau) v v^H to the trailing columns.
        # HIGHEST precision: on TPU the default lowers dots to bf16, which
        # would corrupt the reflectors themselves (panel work is tiny).
        w = jnp.matmul(jnp.conj(v), P, precision=lax.Precision.HIGHEST)
        upd = jnp.outer(jnp.conj(tau_j) * v, w)
        P = P - jnp.where(cidx[None, :] > j, upd, 0)
        # store [beta; v-tail] in column j
        newcol = jnp.where(ridx > j, v, P[:, j]).at[j].set(
            jnp.asarray(beta, P.dtype))
        newcol = jnp.where(ridx >= j, newcol, P[:, j])
        P = P.at[:, j].set(newcol)
        tau = tau.at[j].set(jnp.asarray(tau_j, tau.dtype))
        return P, tau

    tau0 = jnp.zeros((k,), P.dtype)
    return lax.fori_loop(0, k, body, (P, tau0))


def _larft(V, tau):
    """Forward-columnwise block-reflector triangle: Q = I - V T V^H."""
    k = tau.shape[0]
    B = jnp.matmul(jnp.conj(V).T, V, precision=lax.Precision.HIGHEST)
    kidx = jnp.arange(k)

    def body(i, T):
        col = jnp.where(kidx < i, B[:, i], 0)
        newcol = -tau[i] * jnp.matmul(T, col, precision=lax.Precision.HIGHEST)
        newcol = newcol.at[i].set(tau[i])
        return T.at[:, i].set(newcol)

    return lax.fori_loop(0, k, body, jnp.zeros((k, k), V.dtype))


def _panel_v(Pf):
    """Unit-lower V from a packed panel (replicated)."""
    M, k = Pf.shape
    return jnp.tril(Pf, -1) + jnp.eye(M, k, dtype=Pf.dtype)


def _panel_qr_dispatch(P, plan=None):
    """Route one classic replicated panel through the resolved
    ``panel_impl`` plan: returns ``(packed, tau, T)`` with ``T`` the
    fused kernel's larft triangle when the Pallas path ran, else
    ``None`` (the caller builds T via :func:`_larft` exactly as
    before).  ``plan=None`` / complex / oversize panels keep the XLA
    larfg recurrence -- the status-quo path, bit-identical."""
    if plan is not None and plan.use_pallas(P.shape, P.dtype, copies=4):
        from ..kernels import qr_panel
        return qr_panel(P)
    Pf, tau = _panel_qr(P)
    return Pf, tau, None


# ---------------------------------------------------------------------
# TSQR/CAQR tree panel (the QR rider of the CALU PR): local Householder
# QR per grid-row slab, a log-depth pairwise reduction of the R factors,
# and the aggregated thin Q converted BACK to geqrf packing via the
# LU-based Householder reconstruction (Ballard/Demmel et al., "Recon-
# structing Householder vectors from TSQR"), so every downstream consumer
# -- compact-WY trailing updates, apply_q, least_squares -- is unchanged.
# ---------------------------------------------------------------------

def _tsqr_tree(P, r: int, precision=None):
    """Replicated TSQR reduction of an (M, b) panel over ``r`` cyclic
    grid-row slabs: returns ``(Q1, R)`` with Q1 the explicit thin
    orthonormal factor (rows back in original order) and R upper
    triangular.  The tree mirrors a message-passing CAQR: slab QRs are
    independent (zero communication), then ceil(log2(r)) pairwise
    stacked-QR playoffs combine the R factors, with each leaf's b x b
    aggregated transform accumulated so Q1 is assembled by one matmul
    per slab."""
    M, b = P.shape
    lslab = max(-(-M // r), b)
    sidx = jnp.arange(lslab)[None, :] * r + jnp.arange(r)[:, None]
    ok = sidx < M                                       # (r, lslab)
    vals = jnp.where(ok[:, :, None], P[jnp.clip(sidx, 0, M - 1)], 0)
    with jax.default_matmul_precision("highest"):
        Qs, Rs = jax.vmap(lambda v: jnp.linalg.qr(v, mode="reduced"))(vals)
    Rlist = [Rs[i] for i in range(r)]
    groups = [[i] for i in range(r)]
    Ts = [None] * r                                     # None == identity
    while len(Rlist) > 1:
        nR, nG = [], []
        for a in range(0, len(Rlist) - 1, 2):
            st = jnp.concatenate([Rlist[a], Rlist[a + 1]], axis=0)
            with jax.default_matmul_precision("highest"):
                q, rnew = jnp.linalg.qr(st, mode="reduced")
            for leaf, blk in ((groups[a], q[:b]), (groups[a + 1], q[b:])):
                for i in leaf:
                    Ts[i] = blk if Ts[i] is None else jnp.matmul(
                        Ts[i], blk, precision=_hi(precision))
            nR.append(rnew)
            nG.append(groups[a] + groups[a + 1])
        if len(Rlist) % 2:
            nR.append(Rlist[-1])
            nG.append(groups[-1])
        Rlist, groups = nR, nG
    T = jnp.stack([jnp.eye(b, dtype=P.dtype) if t is None else t
                   for t in Ts])
    Qfull = jnp.matmul(Qs, T, precision=_hi(precision))  # (r, lslab, b)
    targets = jnp.where(ok, sidx, M).reshape(-1)
    Q1 = jnp.zeros((M, b), P.dtype).at[targets].set(
        Qfull.reshape(r * lslab, b), mode="drop")
    return Q1, Rlist[0]


def _panel_qr_tsqr(P, r: int, precision=None):
    """TSQR tree panel in geqrf packing: ``(packed V\\R, tau)``, same
    contract as :func:`_panel_qr`.

    The tree (:func:`_tsqr_tree`) produces the explicit thin ``Q1`` and
    ``R``; the Householder form is reconstructed exactly from the
    identity ``Q1 - [I; 0] = Y U`` (Y the unit-lower-trapezoidal
    reflector panel, ``U = -T Y1^H`` upper triangular), i.e. ONE
    unpivoted LU of ``Q1 - I`` -- the lu module's :func:`_nopiv_panel` --
    with ``tau_j = -U[j,j]``.  Columns are sign-flipped first so the
    diagonal of ``Q1 - I`` is bounded away from zero (the stability
    device of the reconstruction paper).  Replaces the serial
    column-at-a-time larfg recurrence over the full panel height with
    slab-local QR kernels plus log-depth b x b reductions."""
    M, b = P.shape
    Q1, R = _tsqr_tree(P, max(int(r), 1), precision)
    d = jnp.diagonal(Q1[:b])
    absd = jnp.abs(d)
    s = jnp.where(absd == 0, -jnp.ones_like(d),
                  -(jnp.conj(d) / jnp.where(absd == 0, 1, absd)))
    s = s.astype(P.dtype)
    Q1p = Q1 * s[None, :]
    Rp = jnp.conj(s)[:, None] * R
    B = Q1p.at[:b].add(-jnp.eye(b, dtype=P.dtype))
    F = _nopiv_panel(B, b, precision)
    tau = -jnp.diagonal(F[:b])
    packed = jnp.concatenate(
        [jnp.triu(Rp) + jnp.tril(F[:b], -1), F[b:]], axis=0)
    return packed, tau


# ---------------------------------------------------------------------
# blocked Householder QR
# ---------------------------------------------------------------------

def qr(A: DistMatrix, nb: int | str | None = None, precision=None,
       panel: str = "classic", panel_impl: str | None = None,
       comm_precision: str | None = None,
       timer=None, health=None, redist_path: str | None = None,
       abft=None):
    """Blocked Householder QR; returns (packed, tau) in geqrf format.

    ``nb='auto'`` asks the tuning subsystem for the panel width.  The
    resolved block size is ATTACHED to the returned packed matrix (the
    ``_qr_nb`` attribute), so :func:`apply_q` called with ``nb=None``
    reuses exactly the factorization's blocking and a mismatching
    explicit ``nb`` raises instead of silently producing a wrong Q.  (The
    attribute is host-side metadata: it does not survive a ``jax.jit``
    boundary -- inside jit, pass the same ``nb`` to both ends as before.)
    ``timer`` enables eager per-phase (panel/update) wall-clock
    attribution, same protocol as ``lu``/``cholesky`` (ISSUE 5).

    ``panel`` selects the panel reduction: ``'classic'`` (default) is the
    replicated column-at-a-time larfg recurrence; ``'tsqr'`` the TSQR/CAQR
    tree panel (:func:`_panel_qr_tsqr`) -- slab-local QR kernels per grid
    row, a log-depth R reduction, and LU-based Householder reconstruction
    back into the SAME geqrf packing, so ``apply_q``/``least_squares``
    consume the result unchanged (R's diagonal signs may differ from
    classic; the (packed, tau) pair is self-consistent).  ``'auto'``
    resolves through the tuning subsystem like ``nb``.

    ``panel_impl`` (``None`` | ``'xla'`` | ``'pallas'`` | ``'auto'``)
    selects the classic panel's IMPLEMENTATION, orthogonal to ``panel``:
    ``'pallas'`` fuses the whole larfg reflector chain AND the larft
    T-triangle build into ONE VMEM-resident kernel
    (``kernels.qr_panel``; ``interpret=True`` off-TPU), so the driver
    skips the separate ``_larft`` launch per step.  Residual-bounded
    twin of the XLA recurrence (pinned by ``tests/kernels``); complex
    dtypes and oversize panels fall back to XLA silently; the TSQR tree
    panel keeps its slab kernels.  The schedule and every collective
    are identical under either value (comm-plan goldens byte-pinned).

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'`` | ``'auto'``)
    selects the wire precision of the per-step panel gathers (the
    sweep's only bulk collective): narrow encode -> gather -> decode, so
    the gathers move 2-4x fewer bytes at identical round counts.
    Opt-in; ``None`` (default) is bit-identical.  See the README's
    "Quantized collectives" section for the accuracy trade.

    ``redist_path`` (``None`` | ``'chain'`` | ``'direct'`` | ``'auto'``)
    routes the panel gathers through the one-shot plan compiler instead
    of the hop chain; ``'auto'`` arbitrates per move with the measured
    redist constants when recorded (see :mod:`perf.redist_bench`).

    ``health`` opts into the resilience subsystem's numerical-health
    guards, with the same contract as ``lu``/``cholesky`` (ISSUE 7 gap
    closed in ISSUE 9): pass a ``HealthMonitor`` (read
    ``monitor.report()`` afterwards) or ``True`` (report retrievable via
    ``resilience.last_health_report('qr')``).  Every panel/update tick is
    NaN/Inf-scanned and growth-tracked, and the packed panel's diagonal
    -- which carries R's diagonal (the larfg betas) -- is checked for
    near-zero entries, the QR image of rank deficiency.  ``health=None``
    (default) attaches nothing: the zero-overhead NULL_HOOK path, pinned
    by redist-count equality and the unchanged qr/qr_tsqr comm goldens.

    ``abft`` opts into Huang-Abraham checksum guarding with per-panel
    transactional recovery (ISSUE 15; same contract as
    ``lu``/``cholesky``): pass ``True`` (report retrievable via
    ``resilience.last_abft_report('qr')``) or a caller-owned
    ``AbftGuard``.  The guarded schedule keeps ``panel=`` ('classic' and
    'tsqr' are both guarded) but ignores ``redist_path`` -- per-panel
    transactions pin the default hop-chain gathers.  ``abft=None``
    (default) never imports the resilience module: the unguarded sweep
    is bit-identical and its comm goldens unchanged."""
    _check_mcmr(A)
    m, n = A.gshape
    g = A.grid
    if isinstance(nb, str) or panel == "auto" or comm_precision == "auto" \
            or redist_path == "auto" or panel_impl == "auto":
        from ..tune.policy import resolve_knobs
        kn = resolve_knobs("qr", gshape=A.gshape, dtype=A.dtype, grid=g,
                           knobs={"nb": nb, "panel": panel,
                                  "panel_impl": panel_impl,
                                  "comm_precision": comm_precision,
                                  "redist_path": redist_path})
        nb, panel, comm_precision = kn["nb"], kn["panel"], \
            kn["comm_precision"]
        redist_path = kn.get("redist_path")
        panel_impl = kn.get("panel_impl")
    from ..redist.quantize import check_comm_precision
    check_comm_precision(comm_precision)
    if panel is None:
        panel = "classic"
    if panel not in ("classic", "tsqr"):
        raise ValueError(f"qr: unknown panel strategy {panel!r}; "
                         "expected 'classic', 'tsqr', or 'auto'")
    from ..kernels import resolve_panel
    plan = resolve_panel(panel_impl, dtype=A.dtype)
    if abft:
        from ..resilience.abft import abft_qr
        return abft_qr(A, nb=nb, precision=precision, panel=panel,
                       comm_precision=comm_precision, timer=timer,
                       health=health, abft=abft, plan=plan)
    tm = _phase_hook("qr", timer)
    hm = None
    if health:
        from ..resilience.health import attach_health
        tm, hm = attach_health("qr", health, tm, scale_from=A)
    tm.start()
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    taus = []
    for k, s in enumerate(range(0, kend, ib)):
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        panel_ss = redistribute(view(A, rows=(s, m), cols=(s, e_up)),
                                STAR, STAR,
                                comm_precision=comm_precision,
                                path=redist_path)
        Tk = None
        if panel == "tsqr":
            Pf, tau = _panel_qr_tsqr(panel_ss.local[:, :nbw], r, precision)
        else:
            Pf, tau, Tk = _panel_qr_dispatch(panel_ss.local[:, :nbw], plan)
        Pf, = apply_fault("compute", (Pf,))
        taus.append(tau)
        tm.tick("panel", k, Pf, tau)
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        A = _update_cols_lt(A, redistribute(Pf_ss, MC, MR), (s, m), (s, e_up), e)
        if e < n:
            V = _panel_v(Pf)
            T = Tk if Tk is not None else _larft(V, tau)
            V_ss = DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0, g)
            V_mc = redistribute(V_ss, MC, STAR)
            A2 = view(A, rows=(s, m), cols=(s, n))
            W = jnp.matmul(jnp.conj(V_mc.local).T, A2.local,
                           precision=_hi(precision))          # [STAR,MR] storage
            W = jnp.matmul(jnp.conj(T).T, W, precision=_hi(precision))
            upd = jnp.matmul(V_mc.local, W, precision=_hi(precision))
            A = _update_cols_ge(A, A2.with_local(A2.local - upd.astype(A.dtype)),
                                (s, m), (s, n), e)
            tm.tick("update", k, A)
    _record_qr_nb(A, ib)
    if hm is not None:
        hm.report()
    return A, jnp.concatenate(taus) if taus else jnp.zeros((0,), A.dtype)


def _record_qr_nb(Ap: DistMatrix, ib: int) -> None:
    """Attach the block size a factorization actually used to the packed
    matrix (frozen dataclass => object.__setattr__).  Host-side metadata
    only: lost across jit/pytree boundaries, where callers must keep
    passing a consistent ``nb`` themselves."""
    object.__setattr__(Ap, "_qr_nb", int(ib))


def _applyq_blocksize(Ap: DistMatrix, nb, grain: int, kend: int) -> int:
    """The blocking :func:`apply_q` must sweep with: default to the block
    size recorded by :func:`qr`, and REFUSE a mismatching explicit ``nb``
    (different panel boundaries silently produce a wrong Q)."""
    rec = getattr(Ap, "_qr_nb", None)
    if nb is None:
        return rec if rec is not None else _blocksize(None, grain, kend)
    if isinstance(nb, str):
        from ..tune.policy import resolve_knobs
        nb = resolve_knobs("qr", gshape=Ap.gshape, dtype=Ap.dtype,
                           grid=Ap.grid, knobs={"nb": nb})["nb"]
    ib = _blocksize(nb, grain, kend)
    if rec is not None and ib != rec:
        raise ValueError(
            f"apply_q: nb={nb!r} derives block size {ib}, but this packed "
            f"factor was produced by qr() with block size {rec}; pass "
            "nb=None to reuse the factorization's blocking")
    return ib


def apply_q(Ap: DistMatrix, tau, B: DistMatrix, orient: str = "N",
            nb: int | str | None = None, precision=None) -> DistMatrix:
    """B := Q B ('N') or Q^H B ('C'), Q from (packed, tau)
    (``qr::ApplyQ`` / ``ApplyPackedReflectors``).

    ``nb`` MUST match the factorization's blocking.  The default
    (``None``) reuses the block size :func:`qr` recorded on ``Ap``; an
    explicit ``nb`` that derives different panel boundaries raises
    ``ValueError`` instead of silently applying a wrong Q."""
    _check_mcmr(Ap, B)
    m, n = Ap.gshape
    if B.gshape[0] != m:
        raise ValueError(f"B height {B.gshape[0]} != {m}")
    g = Ap.grid
    r, c = g.height, g.width
    kend = min(m, n)
    ib = _applyq_blocksize(Ap, nb, math.lcm(r, c), kend)
    starts = list(range(0, kend, ib))
    if orient == "N":
        starts = starts[::-1]
    for s in starts:
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        panel = redistribute(view(Ap, rows=(s, m), cols=(s, e_up)), STAR, STAR)
        V = _panel_v(panel.local[:, :nbw])
        T = _larft(V, tau[s:e])
        Tm = jnp.conj(T).T if orient == "C" else T
        V_ss = DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0, g)
        V_mc = redistribute(V_ss, MC, STAR)
        B2 = view(B, rows=(s, m))
        W = jnp.matmul(jnp.conj(V_mc.local).T, B2.local, precision=_hi(precision))
        W = jnp.matmul(Tm, W, precision=_hi(precision))
        upd = jnp.matmul(V_mc.local, W, precision=_hi(precision))
        B = update_view(B, B2.with_local(B2.local - upd.astype(B.dtype)),
                        rows=(s, m))
    return B


def explicit_q(Ap: DistMatrix, tau, nb: int | None = None,
               precision=None) -> DistMatrix:
    """The m x m unitary Q as a DistMatrix (``qr::ExplicitUnitary``)."""
    from ..matrices.basic import identity
    I = identity(Ap.gshape[0], grid=Ap.grid, dtype=Ap.dtype)
    return apply_q(Ap, tau, I, orient="N", nb=nb, precision=_hi(precision))


def least_squares(A: DistMatrix, B: DistMatrix, nb: int | None = None,
                  precision=None, abft=None) -> DistMatrix:
    """Minimize ||A X - B||_F for m >= n via QR (``El::LeastSquares``,
    dense path of ``src/lapack_like/euclidean_min/LeastSquares.cpp``).

    Fully distributed: Q^H B via packed reflectors, then a distributed
    triangular solve against the interior-extracted R (no replication).

    ``abft`` threads through to :func:`qr` (ISSUE 15): the factorization
    -- the solve's whole O(m n^2) fault surface -- runs checksum-guarded
    with panel-granular recovery, so the serve executor's ``grid_qr``
    escalation rung is corruption-attested end to end."""
    from ..redist.interior import interior_view      # qr <- interior is cycle-free
    from ..blas.level1 import make_trapezoidal
    _check_mcmr(A, B)
    m, n = A.gshape
    if m < n:
        raise ValueError("least_squares requires m >= n (tall)")
    Ap, tau = qr(A, nb=nb, precision=_hi(precision), abft=abft)
    Y = apply_q(Ap, tau, B, orient="C", nb=nb, precision=_hi(precision))
    R = make_trapezoidal(interior_view(Ap, (0, n), (0, n)), "U")
    Y1 = interior_view(Y, (0, n), (0, B.gshape[1]))
    return trsm("L", "U", "N", R, Y1, nb=nb, precision=_hi(precision))


# ---------------------------------------------------------------------
# Column-pivoted QR (Businger-Golub / geqp3)
# ---------------------------------------------------------------------

@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7))
def _panel_qp(stor, colnorms, s: int, m: int, n: int, nbw: int,
              Sc: int, Sr: int):
    """One left-looking pivoted panel (LAPACK ``laqps`` analog).

    Columns are identified by GLOBAL id throughout (the F accumulator is
    indexed by global column), so no physical swaps happen inside the
    panel; ``stor`` is the panel-start full storage snapshot.  Per column:
    one traced-index column fetch + one row fetch + corrections, one
    reflector, and the norm downdates.  Returns (V, F, packed R+v panel,
    tau, jpvt, updated colnorms)."""
    mt = m - s
    dtype = stor.dtype
    rdtype = jnp.zeros((), dtype).real.dtype
    ridx = jnp.arange(mt)
    lr = -(-m // Sc)
    lc = -(-n // Sr)

    def snap_col(gcol):
        scol = (gcol % Sr) * lc + gcol // Sr
        colf = lax.dynamic_index_in_dim(stor, scol, axis=1, keepdims=False)
        grow = s + jnp.arange(mt)
        srow = (grow % Sc) * lr + grow // Sc
        return jnp.take(colf, srow, axis=0)

    def snap_row(grow):
        srow = (grow % Sc) * lr + grow // Sc
        rowf = lax.dynamic_index_in_dim(stor, srow, axis=0, keepdims=False)
        gcol = jnp.arange(n)
        scol = (gcol % Sr) * lc + gcol // Sr
        return jnp.take(rowf, scol, axis=0)

    def body(k, carry):
        V, F, P, tau, jpvt, norms = carry
        gc = jnp.argmax(norms)
        jpvt = jpvt.at[k].set(gc.astype(jnp.int32))
        c = snap_col(gc) - V @ jnp.conj(F[gc, :])
        v, tq, beta = _panel_qp_larfg(c, k, ridx, dtype)
        # packed column: R entries above the pivot, beta on it, v tail below
        pc = jnp.where(ridx < k, c, 0).at[k].set(jnp.asarray(beta, dtype))
        pc = jnp.where(ridx > k, v, pc)
        P = P.at[:, k].set(pc)
        V = V.at[:, k].set(v)
        tau = tau.at[k].set(tq)
        # F[:, k] = tq * (A0^H v - F V^H v): base is precomputed outside?
        # A0^H v needs the distributed trailing view -- computed by caller
        # via a matmul on the snapshot strip (mt x n): here stor strip
        # already replicated? No: use the full-width strip gathered by the
        # caller.  (See _strip below -- closed over.)
        base = jnp.conj(_strip).T @ v
        f = tq * (base - F @ (jnp.conj(V).T @ v))
        F = F.at[:, k].set(f.astype(dtype))
        # R row k across all columns (V/F now include column k, whose
        # V[k, k] = 1 carries the new reflector's contribution)
        rowk = snap_row(s + k) - V[k, :] @ jnp.conj(F).T
        down = jnp.abs(rowk) ** 2
        # downdate only live columns; used ones carry the -1 sentinel
        norms = jnp.where(norms < 0, norms,
                          jnp.sqrt(jnp.maximum(norms ** 2 - down, 0.0)))
        norms = norms.at[gc].set(-1.0)
        return V, F, P, tau, jpvt, norms

    # full-width row strip of the snapshot (rows [s, m) in global order):
    grow = s + jnp.arange(mt)
    srow = (grow % Sc) * lr + grow // Sc
    gcol = jnp.arange(n)
    scol = (gcol % Sr) * lc + gcol // Sr
    _strip = jnp.take(jnp.take(stor, srow, axis=0), scol, axis=1)

    init = (jnp.zeros((mt, nbw), dtype), jnp.zeros((n, nbw), dtype),
            jnp.zeros((mt, nbw), dtype), jnp.zeros((nbw,), dtype),
            jnp.zeros((nbw,), jnp.int32), colnorms.astype(rdtype))
    return lax.fori_loop(0, nbw, body, init)


def _panel_qp_larfg(col, piv, ridx, dtype):
    from .condense import _larfg_at
    return _larfg_at(col, piv, ridx, dtype)


def qr_col_piv(A: DistMatrix, nb: int | None = None, precision=None):
    """Column-pivoted QR ``A[:, jpvt] = Q R`` (``El::qr::BusingerGolub`` /
    LAPACK geqp3).  Returns ``(packed, tau, jpvt)`` in geqrf packing with
    greedy max-norm pivot order (R's diagonal is non-increasing in
    magnitude).

    Norm downdates use the squared-recurrence with clamping but WITHOUT
    LAPACK's cancellation-triggered exact recomputation (documented
    deviation; pathological cancellation can perturb late pivot choices).
    """
    _check_mcmr(A)
    m, n = A.gshape
    g = A.grid
    r, c = g.height, g.width
    Sc, Sr = A.col_stride, A.row_stride
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    # initial exact column norms (storage cols are global cols)
    from ..blas.level1 import _global_indices
    ns = jnp.sqrt(jnp.sum(jnp.abs(A.local) ** 2, axis=0))
    _, J = _global_indices(A)
    colnorms = jnp.zeros((n,), ns.dtype).at[J].set(ns, mode="drop")
    Awork = A
    panels, taus, jps = [], [], []
    for s in range(0, kend, ib):
        e = min(s + ib, kend)
        nbw = e - s
        V, F, P, tau, jpvt, colnorms = _panel_qp(
            Awork.local, colnorms, s, m, n, nbw, Sc, Sr)
        panels.append(P)
        taus.append(tau)
        jps.append(jpvt)
        if e < kend or e < n:
            # trailing update of rows [s, m) across the full width
            strip = view(Awork, rows=(s, m))
            Vmc = redistribute(DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0,
                                          g), MC, STAR)
            FH = redistribute(DistMatrix(jnp.conj(F).T, (nbw, n), STAR, STAR,
                                         0, 0, g), STAR, MR)
            upd = jnp.matmul(Vmc.local, FH.local, precision=_hi(precision))
            Awork = update_view(Awork, strip.with_local(
                strip.local - upd.astype(A.dtype)), rows=(s, m))
    jpvt = jnp.concatenate(jps)
    tau = jnp.concatenate(taus)
    # assemble: permute columns into pivot order, then overwrite each
    # panel's rows with its packed block
    from .lu import permute_cols, _update_cols_lt
    full_perm = jnp.concatenate(
        [jpvt, _complement(jpvt, n)]) if n > kend else jpvt
    Ap = permute_cols(Awork, full_perm)
    for i, s in enumerate(range(0, kend, ib)):
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        P = panels[i]
        if e_up > e:
            P = jnp.pad(P, ((0, 0), (0, e_up - e)))
        blk = DistMatrix(P, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        Ap = _update_cols_lt(Ap, redistribute(blk, MC, MR), (s, m),
                             (s, e_up), e)
    _record_qr_nb(Ap, ib)
    return Ap, tau, jpvt


def _complement(jpvt, n: int):
    """Global columns not chosen as pivots, ascending (traced)."""
    mask = jnp.ones((n,), bool).at[jpvt].set(False)
    return jnp.nonzero(mask, size=n - jpvt.shape[0])[0]


# ---------------------------------------------------------------------
# LQ (via the QR of the adjoint)
# ---------------------------------------------------------------------

def lq(A: DistMatrix, nb: int | None = None, precision=None,
       redist_path: str | None = None):
    """LQ factorization ``A = L Q`` with L lower-trapezoidal and Q having
    orthonormal rows (``El::LQ``): computed as the QR of ``A^H``
    (``A^H = Q_r R  =>  A = R^H Q_r^H``).  Returns ``(packed, tau)`` where
    ``packed`` is the geqrf-packed QR of ``A^H`` ((n, m)-shaped); use
    :func:`apply_q_lq` / :func:`explicit_l` to consume it.
    ``redist_path='direct'`` collapses the entry transpose-exchange from a
    3-hop chain to one one-shot exchange and rides the QR panel gathers."""
    from ..redist.engine import transpose_dist
    Ah = redistribute(transpose_dist(A, conj=True), MC, MR, path=redist_path)
    return qr(Ah, nb=nb, precision=_hi(precision), redist_path=redist_path)


def apply_q_lq(Ap: DistMatrix, tau, B: DistMatrix, orient: str = "N",
               nb: int | None = None, precision=None) -> DistMatrix:
    """B := Q B ('N') or Q^H B ('C') with Q the LQ unitary (Q = Q_r^H of
    the underlying adjoint-QR)."""
    flip = "C" if orient == "N" else "N"
    return apply_q(Ap, tau, B, orient=flip, nb=nb, precision=_hi(precision))


def explicit_l(Ap: DistMatrix) -> DistMatrix:
    """The explicit (m, min(m,n)) lower-trapezoidal L from :func:`lq`'s
    packing (L = R^H of the adjoint QR; shape is read from ``Ap``)."""
    from ..redist.engine import transpose_dist
    from ..redist.interior import interior_view
    from ..blas.level1 import make_trapezoidal
    n_, m_ = Ap.gshape                      # Ap is the packed QR of A^H
    k = min(n_, m_)
    R = make_trapezoidal(interior_view(Ap, (0, k), (0, m_)), "U")
    return redistribute(transpose_dist(R, conj=True), MC, MR)


def rq(A: DistMatrix, nb: int | None = None, precision=None):
    """RQ factorization ``A = R Q`` (``El::RQ``) with R (m, k) upper
    triangular/trapezoidal against the BOTTOM-RIGHT corner and Q (k, n)
    having orthonormal rows (k = min(m, n)).

    Computed via the exchange identity: with J the anti-identity,
    J_m A J_n = L W (LQ)  =>  A = (J_m L J_k) (J_k W J_n), and the flip of
    a lower-trapezoidal L is upper-trapezoidal.  Returns explicit (R, Q)
    (the reference's packed-reflector form is reachable through
    :func:`lq` on the flipped matrix)."""
    from .lu import permute_rows, permute_cols
    m, n = A.gshape
    k = min(m, n)
    rev_m = jnp.arange(m)[::-1]
    rev_n = jnp.arange(n)[::-1]
    rev_k = jnp.arange(k)[::-1]
    Af = permute_cols(permute_rows(A, rev_m), rev_n)     # J_m A J_n
    packed, tau = lq(Af, nb=nb, precision=_hi(precision))
    L = explicit_l(packed)                               # (m, k)
    # W = first k rows of the (n, n) LQ unitary.  Rows cannot be sliced
    # before a left-apply, but W^H = Q^H [I_k; 0]: apply Q^H to the
    # (n, k) identity SLAB and adjoint -- O(n k) instead of O(n^2).
    from ..matrices.basic import identity
    from ..redist.interior import interior_view
    from ..redist.engine import transpose_dist
    Ik = interior_view(identity(n, grid=A.grid, dtype=A.dtype), (0, n),
                       (0, k)) if k < n \
        else identity(n, grid=A.grid, dtype=A.dtype)
    Wh = apply_q_lq(packed, tau, Ik, orient="C", nb=nb,
                    precision=_hi(precision))            # (n, k) = W^H
    W = redistribute(transpose_dist(Wh, conj=True), MC, MR)
    R = permute_cols(permute_rows(L, rev_m), rev_k)
    Q = permute_cols(permute_rows(W, rev_k), rev_n)
    return R, Q


# ---------------------------------------------------------------------
# TSQR (tall-skinny)
# ---------------------------------------------------------------------

def tsqr(A: DistMatrix):
    """Tall-skinny QR of a [VC,STAR] matrix (``qr::TS``): per-device local
    QR + one all-gather of the p small R factors + a redundant stacked QR.
    Returns (Q [VC,STAR] with orthonormal columns, R [STAR,STAR])."""
    if A.dist != (VC, STAR) or (A.calign, A.ralign) != (0, 0):
        raise ValueError(f"tsqr expects zero-aligned [VC,STAR], got {A}")
    m, k = A.gshape
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    if m < k:
        raise ValueError("tsqr needs m >= k")

    import jax
    from jax.sharding import PartitionSpec as P

    def f(a):
        q1, r1 = jnp.linalg.qr(a, mode="reduced")        # (lr,kk),(kk,k)
        rs = lax.all_gather(r1, ("mr", "mc"), axis=0)    # VC rank order
        kk = r1.shape[0]
        stacked = rs.reshape(p * kk, k)
        q2, R = jnp.linalg.qr(stacked, mode="reduced")   # (p*kk,k),(k,k)
        vc = lax.axis_index("mc") + r * lax.axis_index("mr")
        q2b = lax.dynamic_slice_in_dim(q2, vc * kk, kk, axis=0)
        return q1 @ q2b, R

    # float32-accurate dots: the TPU default would run the local QRs' and the
    # Q1*Q2 product's matmuls in bf16
    with jax.default_matmul_precision("highest"):
        Qs, Rs = shard_map(
            f, mesh=g.mesh, in_specs=(A.spec,),
            out_specs=(A.spec, P(None, None)), check_vma=False,
        )(A.local)
    Q = DistMatrix(Qs, (m, k), VC, STAR, 0, 0, g)
    R = DistMatrix(Rs, (k, k), STAR, STAR, 0, 0, g)
    return Q, R
